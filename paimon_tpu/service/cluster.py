"""Cluster service: coordinator/worker mesh execution with a cluster-wide
compaction drain.

The reference scales out by running many buckets across Flink/Spark task
managers while a SINGLE-parallelism committer serializes snapshots (SURVEY
§2.9). This module joins the two halves this repo already built separately:
the mesh engine (PR 7: many devices, ONE process) and the proc-soak
supervisor (PR 9: many processes, NO devices).

  coordinator (this process — the only committer)
  ├── bucket assignment: contiguous ranges, per-bucket epochs, reassignment
  │   on missed heartbeats (exactly once per orphaned bucket)
  ├── per-worker commit handles: workers ship CommitMessages, the
  │   coordinator commits through the snapshot-CAS path
  │   (parallel.distributed.is_commit_coordinator — the reference's
  │   CommitterOperator)
  ├── cluster compaction service: table.compactor.AdaptiveCompactorService
  │   observing + deciding here, with execute_group plugged so each decision
  │   dispatches to the worker OWNING that bucket; the worker rewrites
  │   through its local mesh engine and ships the result back; the
  │   debt-admission gate (read-amp ceiling) is enforced cluster-wide via
  │   the admit RPC, charges tagged per worker (a killed worker's charges
  │   release on reassignment)
  ├── worker-0 (OS process): jax runtime with N forced-host devices,
  │   merge.engine=mesh over its bucket shard, intent/ack journal (PR 9),
  │   serving plane (get_batch + subscribe + join_part) on its own port
  ├── worker-1 ...
  └── reader processes (reused from proc_soak) pinning + scanning snapshots

Correctness fences:
  * epoch fencing — every (re)grant of a bucket bumps its epoch; a shipped
    CommitMessage is rejected as STALE unless every touched bucket is still
    owned by the shipper at an epoch <= the one it shipped with. A worker
    killed, reassigned, and then heard from again cannot double-apply.
  * journal/oracle — the PR 9 protocol verbatim: intent fsynced before the
    ship, ack after the coordinator's sid comes back, landed-unacked rounds
    resolved from the snapshot chain on respawn (adopt-never-replay).
  * debt gate — admit() charges the coordinator's AdaptiveCompactorService
    projection per target bucket (owner-tagged); ship/abort settles, death
    releases. No bucket's projected sorted-run count passes the ceiling.

Run directly:  python -m paimon_tpu.service.cluster [base_dir] [flags]
Child roles:   python -m paimon_tpu.service.cluster worker|reader ...
"""

from __future__ import annotations

import base64
import json
import os
import socket
import socketserver
import subprocess
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field

import numpy as np

from . import _recv, _send
from .soak import KEYSPACE, SCHEMA, find_landed_append

__all__ = [
    "ClusterConfig",
    "ClusterCoordinator",
    "ClusterWorkerAgent",
    "ClusterClient",
    "ClusterSupervisor",
    "run_cluster_soak",
    "DEFAULT_CLUSTER_KILLS",
]

# one spec per worker spawn while they last: one ingest-flush death, one
# mid-compaction death (the rewrite ran, the CommitMessage never shipped —
# its debt charge and its bucket range must both be recovered), one death
# between prepare_commit and the ship RPC
DEFAULT_CLUSTER_KILLS = (
    "flush:files-written:2:kill",
    "cluster:compact-executing:1:kill",
    "cluster:before-ship:2:kill",
)


def _b64(arr: np.ndarray) -> dict:
    a = np.ascontiguousarray(arr)
    return {"d": base64.b64encode(a.tobytes()).decode(), "t": str(a.dtype), "s": list(a.shape)}


def _unb64(d: dict) -> np.ndarray:
    return np.frombuffer(base64.b64decode(d["d"]), dtype=np.dtype(d["t"])).reshape(d["s"])


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------
@dataclass
class ClusterConfig:
    workers: int = 2
    devices_per_worker: int = 2
    buckets: int = 4
    duration_s: float = 45.0
    seed: int = 0
    round_rows: int = 256  # per owned bucket per ingest round
    update_fraction: float = 0.3
    heartbeat_interval_s: float = 0.5
    heartbeat_timeout_s: float = 4.0
    admit_timeout_s: float = 30.0
    compaction: bool = True
    read_amp_ceiling: int = 10
    readers: int = 1
    scripted_kills: tuple = DEFAULT_CLUSTER_KILLS
    kill_period_s: float = 10.0  # mean seconds between random SIGKILLs (0 = scripted only)
    sweep_period_s: float = 15.0
    sweep_older_than_ms: int = 45_000
    serve: bool = True  # workers run the get/subscribe serving plane
    # scripted elastic events: ("rescale", frac, new_buckets) /
    # ("admit", frac) / ("retire", frac) — frac is the fraction of
    # duration_s at which the event fires (the elastic soak's churn plan)
    elastic: tuple = ()
    table_options: dict = field(default_factory=dict)

    @classmethod
    def from_table_options(cls, options) -> "ClusterConfig":
        from ..options import CoreOptions

        o = options.options
        return cls(
            workers=o.get(CoreOptions.CLUSTER_WORKERS),
            devices_per_worker=o.get(CoreOptions.CLUSTER_DEVICES_PER_WORKER),
            heartbeat_interval_s=o.get(CoreOptions.CLUSTER_HEARTBEAT_INTERVAL) / 1000.0,
            heartbeat_timeout_s=o.get(CoreOptions.CLUSTER_HEARTBEAT_TIMEOUT) / 1000.0,
            round_rows=o.get(CoreOptions.CLUSTER_ROUND_ROWS),
            admit_timeout_s=o.get(CoreOptions.CLUSTER_ADMIT_TIMEOUT) / 1000.0,
            compaction=o.get(CoreOptions.CLUSTER_COMPACTION_ENABLED),
        )


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------
class _WorkerSlot:
    def __init__(self, wid: int):
        self.wid = wid
        self.incarnation = -1
        self.buckets: set[int] = set()
        self.epoch = 0  # assignment epoch the worker was last told
        self.last_heartbeat = time.monotonic()
        self.alive = False
        self.serve_addr: tuple[str, int] | None = None
        self.tasks: list[dict] = []  # queued compaction tasks
        self.committed: dict[int, int] = {}  # ident -> sid (idempotent re-ship)
        self.done_stats: dict | None = None


class ClusterCoordinator:
    """Assignment + commit + compaction-scheduling brain, fronted by a
    threaded length-prefixed-JSON TCP server (the KvQueryServer protocol).
    All state transitions happen in handle() under one lock, so tests drive
    the failover edges directly without sockets."""

    USER_PREFIX = "cluster-w"

    def __init__(
        self,
        table_path: str,
        cfg: ClusterConfig,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        from ..table import load_table

        # the one committer: everything in this process commits, nothing in
        # any worker does (parallel.distributed.is_commit_coordinator)
        os.environ.setdefault("PAIMON_TPU_CLUSTER_ROLE", "coordinator")
        self.cfg = cfg
        self.table_path = table_path
        self.table = load_table(table_path, commit_user="cluster-coordinator")
        self.num_buckets = max(self.table.store.options.bucket, 1)
        self._lock = threading.RLock()
        self._slots: dict[int, _WorkerSlot] = {}
        self._owner: dict[int, int] = {}  # bucket -> wid
        self._bucket_epoch: dict[int, int] = {}  # bucket -> epoch of last grant
        self._epoch = 0
        self._pending: list[int] = []  # orphaned buckets with no live worker
        self._home: dict[int, list[int]] = self._split_ranges()
        self._commit_stores: dict[int, object] = {}
        self._admit_charges: dict[tuple, list[int]] = {}  # (wid, ident) -> buckets
        self._compact_inflight: dict[tuple, tuple] = {}  # (part, bucket) -> (task_id, wid)
        self._task_seq = 0
        self._task_groups: dict[int, list] = {}  # task_id -> [CompactionDecision]
        self._barriers: dict[str, set[int]] = {}
        # elastic topology (ISSUE 19): the route epoch bumps on ANY
        # reassignment / rescale / replica change and piggybacks on every
        # RPC reply (coordinator and worker serving planes alike), so
        # clients refresh the bucket->worker table immediately instead of
        # discovering staleness via a rejected shipment or a timeout window
        self._route_epoch = 1
        self._rescale: dict | None = None  # active cross-worker rescale state
        self._rescale_committing = False
        self._retiring: set[int] = set()  # wids told to drain + hand off
        self._replicas: dict[int, list[int]] = {}  # bucket -> replica wids
        self._get_counts: dict[int, int] = {}  # bucket -> gets since last pass
        self._heat_ema: dict[int, float] = {}  # bucket -> serve-read EMA (1/s)
        self._heat_t: float | None = None
        self._next_replica_pass = 0.0
        from ..options import CoreOptions

        o = self.table.store.options.options
        self.replica_threshold = float(o.get(CoreOptions.CLUSTER_REPLICA_HEAT_THRESHOLD))
        self.replica_max = int(o.get(CoreOptions.CLUSTER_REPLICA_MAX_PER_BUCKET))
        self.replica_interval_s = o.get(CoreOptions.CLUSTER_REPLICA_INTERVAL) / 1000.0
        self.rescale_timeout_s = o.get(CoreOptions.CLUSTER_RESCALE_TIMEOUT) / 1000.0
        self.go_event = threading.Event()
        self.stop_event = threading.Event()
        self.compaction = None
        if cfg.compaction:
            from ..table.compactor import AdaptiveCompactorService

            self.compaction = AdaptiveCompactorService(
                self.table, execute_group=self._dispatch_group
            )
        # TCP front
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    req = _recv(self.request)
                    if req is None:
                        return
                    rid = req.pop("id", None)
                    method = req.pop("method", "")
                    try:
                        out = outer.handle(method, req)
                        out["id"] = rid
                        out.setdefault("ok", True)
                    except Exception as e:  # noqa: BLE001 — surface to the worker
                        out = {"id": rid, "ok": False, "error": f"{type(e).__name__}: {e}"}
                    _send(self.request, out)

        self._server = socketserver.ThreadingTCPServer((host, port), Handler, bind_and_activate=True)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[0], self._server.server_address[1]
        self._threads: list[threading.Thread] = []

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> "ClusterCoordinator":
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        reaper = threading.Thread(
            target=self._reap_loop, name="paimon-clu-reaper", daemon=True
        )
        reaper.start()
        self._threads.append(reaper)
        if self.compaction is not None:
            self.compaction.start()
        return self

    def close(self) -> None:
        self.stop_event.set()
        if self.compaction is not None:
            self.compaction.close()
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self) -> "ClusterCoordinator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- assignment ----------------------------------------------------
    def _split_ranges(self) -> dict[int, list[int]]:
        """Home ranges: contiguous, disjoint, covering [0, num_buckets)."""
        n, w = self.num_buckets, max(self.cfg.workers, 1)
        out: dict[int, list[int]] = {}
        for i in range(w):
            out[i] = list(range(i * n // w, (i + 1) * n // w))
        return out

    def _metrics(self):
        from ..metrics import cluster_metrics

        return cluster_metrics()

    def _grant(self, slot: _WorkerSlot, buckets: list[int]) -> None:
        """Move `buckets` to `slot` under the lock, bumping the fence."""
        self._epoch += 1
        for b in buckets:
            prev = self._owner.get(b)
            if prev is not None and prev != slot.wid:
                self._slots[prev].buckets.discard(b)
            self._owner[b] = slot.wid
            self._bucket_epoch[b] = self._epoch
            slot.buckets.add(b)
            if b in self._pending:
                self._pending.remove(b)
        slot.epoch = self._epoch
        self._route_epoch += 1
        # a grant DURING a rescale re-queues the rewrite for any moved
        # bucket not yet done — the new owner's task carries the post-grant
        # epoch, so the dead previous owner's late rescale shipment for the
        # same bucket is fenced off exactly like a late append
        if self._rescale is not None:
            todo = [b for b in buckets if b not in self._rescale["done"]]
            if todo:
                slot.tasks.append(self._rescale_task(todo))

    def _rescale_task(self, buckets: list[int]) -> dict:
        rs = self._rescale
        return {
            "kind": "rescale",
            "buckets": sorted(buckets),
            "new_buckets": rs["new"],
            "snapshot": rs["snapshot"],
            "epoch": self._epoch,
        }

    def _reassign_dead(self, slot: _WorkerSlot) -> None:
        """Missed-heartbeat death: every bucket the dead worker owned moves
        EXACTLY ONCE to a live worker (least-loaded first), or parks in the
        pending list until one registers; the worker's queued compaction
        tasks, in-flight compaction marks, and debt-gate charges all
        release (nothing it never shipped can ever land)."""
        g = self._metrics()
        slot.alive = False
        orphans = sorted(slot.buckets)
        slot.buckets.clear()
        slot.tasks.clear()
        for key, (task_id, wid) in list(self._compact_inflight.items()):
            if wid == slot.wid:
                del self._compact_inflight[key]
                self._task_groups.pop(task_id, None)
        # release the dead worker's debt-gate charges (ingest admits it
        # never shipped + compaction decisions it never completed)
        released = 0
        for (wid, ident), buckets in list(self._admit_charges.items()):
            if wid == slot.wid:
                del self._admit_charges[(wid, ident)]
        if self.compaction is not None:
            released = self.compaction.release_owner(slot.wid)
        if released:
            g.counter("charges_released").inc(released)
        # drop the dead worker from every replica set before choosing new
        # owners, so promotion below never picks the corpse
        pruned = False
        for b, wids in list(self._replicas.items()):
            if slot.wid in wids:
                wids = [w for w in wids if w != slot.wid]
                pruned = True
                if wids:
                    self._replicas[b] = wids
                else:
                    del self._replicas[b]
        live = [s for s in self._slots.values() if s.alive]
        if not live:
            self._pending.extend(orphans)
        else:
            for b in orphans:
                # warm promotion: a live replica already serves this bucket
                # off shared FS — make it the new primary and retire the
                # grant from the replica set (a worker is never its own
                # replica); otherwise least-loaded live worker
                target = None
                for w in self._replicas.get(b, ()):
                    s = self._slots.get(w)
                    if s is not None and s.alive:
                        target = s
                        break
                if target is not None:
                    rest = [w for w in self._replicas[b] if w != target.wid]
                    if rest:
                        self._replicas[b] = rest
                    else:
                        del self._replicas[b]
                else:
                    target = min(live, key=lambda s: len(s.buckets))
                self._grant(target, [b])
        if orphans or pruned:
            self._route_epoch += 1
            g.gauge("replicas_active").set(sum(len(v) for v in self._replicas.values()))
        if orphans:
            g.counter("reassignments").inc(len(orphans))
        g.gauge("workers_live").set(sum(1 for s in self._slots.values() if s.alive))

    def _reap_loop(self) -> None:
        while not self.stop_event.wait(min(self.cfg.heartbeat_timeout_s / 4, 0.5)):
            now = time.monotonic()
            with self._lock:
                for slot in self._slots.values():
                    if slot.alive and now - slot.last_heartbeat > self.cfg.heartbeat_timeout_s:
                        self._reassign_dead(slot)
                if self._rescale is not None and now > self._rescale["deadline"]:
                    self._abort_rescale_locked()
            if self.replica_threshold > 0 and now >= self._next_replica_pass:
                self._next_replica_pass = now + self.replica_interval_s
                try:
                    self._replica_pass()
                except Exception:  # noqa: BLE001 — placement is best-effort
                    pass

    def _abort_rescale_locked(self) -> None:
        """Rescale timed out (a straggler never shipped): drop the state and
        re-grant every live worker its current buckets — the fresh epochs
        resync the fleet and ingest resumes; the rewrite files already
        shipped are unreferenced and fall to the orphan sweep."""
        self._rescale = None
        for slot in self._slots.values():
            if slot.alive and slot.buckets:
                slot.tasks = [t for t in slot.tasks if t.get("kind") != "rescale"]
                self._grant(slot, sorted(slot.buckets))
        self._metrics().counter("rescale_aborts").inc()

    # ---- replica placement (hot-shard serving, ISSUE 19) ----------------
    def _replica_pass(self) -> None:
        """Grant read replicas for hot buckets; demote cooled ones.

        Heat per bucket = serve-side get EMA (reported by workers in
        heartbeats) + write-heat EMA from the adaptive compactor's
        observation loop. Crossing `cluster.replica.heat-threshold` grants a
        secondary owner (least-replica-loaded live worker that is not the
        primary) for get_batch/subscribe/scan_frag off shared FS; dropping
        under HALF the threshold demotes (hysteresis, no flapping). The
        primary keeps writes. Every change bumps the route epoch."""
        g = self._metrics()
        now = time.monotonic()
        with self._lock:
            dt = (now - self._heat_t) if self._heat_t is not None else None
            self._heat_t = now
            if dt and dt > 0:
                # drain only when there is an interval to rate the counts
                # over — the first pass must NOT discard gets that landed
                # before it (a warm client can burst its whole workload in
                # under one pass interval)
                counts, self._get_counts = self._get_counts, {}
                seen = set(counts) | set(self._heat_ema)
                for b in seen:
                    inst = counts.get(b, 0) / dt
                    prev = self._heat_ema.get(b, inst)
                    self._heat_ema[b] = 0.5 * prev + 0.5 * inst
            wheat = self.compaction.heat() if self.compaction is not None else {}
            live = [
                s
                for s in self._slots.values()
                if s.alive and s.serve_addr is not None and s.wid not in self._retiring
            ]
            if self._rescale is not None:
                return  # placement waits out the rescale window
            rload = {s.wid: 0 for s in live}
            for wids in self._replicas.values():
                for w in wids:
                    if w in rload:
                        rload[w] += 1
            changed = False
            for b in range(self.num_buckets):
                heat = self._heat_ema.get(b, 0.0) + float(wheat.get(b, 0.0))
                cur = [w for w in self._replicas.get(b, []) if any(s.wid == w for s in live)]
                if cur != self._replicas.get(b, []):
                    changed = True
                primary = self._owner.get(b)
                if heat >= self.replica_threshold and len(cur) < self.replica_max:
                    cands = [s for s in live if s.wid != primary and s.wid not in cur]
                    if cands:
                        pick = min(cands, key=lambda s: (rload.get(s.wid, 0), len(s.buckets), s.wid))
                        cur = cur + [pick.wid]
                        rload[pick.wid] = rload.get(pick.wid, 0) + 1
                        changed = True
                elif cur and heat < self.replica_threshold * 0.5:
                    cur = []
                    changed = True
                if cur:
                    self._replicas[b] = cur
                elif b in self._replicas:
                    del self._replicas[b]
            if changed:
                self._route_epoch += 1
            g.gauge("replicas_active").set(sum(len(v) for v in self._replicas.values()))

    # ---- compaction dispatch (the execute_group seam) ------------------
    def _dispatch_group(self, group: list, deep: bool) -> int:
        """AdaptiveCompactorService execution seam: queue each decision on
        the worker owning its bucket (skipping buckets already in flight);
        the commit happens later, when the worker ships the result."""
        g = self._metrics()
        dispatched = 0
        with self._lock:
            if self._rescale is not None or self._rescale_committing:
                return 0  # bucket ids are about to change meaning
            for d in group:
                key = (d.partition, d.bucket)
                if key in self._compact_inflight:
                    continue
                wid = self._owner.get(d.bucket)
                slot = self._slots.get(wid) if wid is not None else None
                if slot is None or not slot.alive:
                    continue
                self._task_seq += 1
                task_id = self._task_seq
                self._compact_inflight[key] = (task_id, wid)
                self._task_groups[task_id] = [d]
                slot.tasks.append(
                    {
                        "task_id": task_id,
                        "partition": list(d.partition),
                        "bucket": d.bucket,
                        "deep": bool(deep or d.deep),
                        "trigger": self.compaction.policy.trigger,
                    }
                )
                dispatched += 1
        if dispatched:
            g.counter("compact_tasks").inc(dispatched)
        return dispatched

    # ---- RPC handlers --------------------------------------------------
    def handle(self, method: str, req: dict) -> dict:
        fn = getattr(self, f"_m_{method}", None)
        if fn is None:
            raise ValueError(f"unknown method {method!r}")
        out = fn(req)
        # push-based route invalidation: every reply carries the route
        # epoch and bucket count, so any client touching the coordinator
        # for ANY reason learns about reassignments/rescales/replica
        # changes immediately — including workers whose rescale shipment
        # reply races the final commit
        out.setdefault("route_epoch", self._route_epoch)
        out.setdefault("num_buckets", self.num_buckets)
        return out

    def _flags(self) -> dict:
        return {"go": self.go_event.is_set(), "stop": self.stop_event.is_set()}

    def _m_ping(self, req: dict) -> dict:
        return {}

    def _m_register(self, req: dict) -> dict:
        wid = int(req["worker"])
        g = self._metrics()
        with self._lock:
            slot = self._slots.setdefault(wid, _WorkerSlot(wid))
            slot.incarnation = int(req.get("incarnation", 0))
            slot.alive = True
            slot.last_heartbeat = time.monotonic()
            if req.get("serve_port"):
                slot.serve_addr = (req.get("serve_host", "127.0.0.1"), int(req["serve_port"]))
            if wid in self._retiring:
                # a retiring worker (or its respawn after a mid-handoff
                # kill) gets nothing — the heartbeat retire flag drains it
                pass
            elif not slot.buckets:
                # first registration gets the home range; a respawn whose
                # range was already reassigned steals it back (bounded
                # churn, keeps every live worker productive) — the epoch
                # bump fences the previous owner's in-flight rounds
                want = [b for b in self._home.get(wid, []) if self._owner.get(b) != wid]
                want += [b for b in self._pending if b not in want]
                if not want and self._rescale is None:
                    # runtime scale-out: a joining worker outside the home
                    # split plans a range handoff — steal buckets from the
                    # most-loaded live peers toward an even share; each
                    # grant's epoch bump fences the donor's in-flight round
                    # (the one fencing round), nothing else is rejected
                    want = self._plan_join_steal(wid)
                    if want:
                        g.counter("handoffs").inc()
                self._grant(slot, want)
            else:
                # same buckets, fresh epoch: the PREVIOUS incarnation's
                # late messages must not be accepted as this one's
                self._grant(slot, sorted(slot.buckets))
            g.counter("workers_registered").inc()
            g.gauge("workers_live").set(sum(1 for s in self._slots.values() if s.alive))
            g.gauge("buckets_assigned").set(len(self._owner))
            return {
                "epoch": slot.epoch,
                "buckets": sorted(slot.buckets),
                "num_buckets": self.num_buckets,
                **self._flags(),
            }

    def _plan_join_steal(self, wid: int) -> list[int]:
        """Pick buckets for a joining worker: repeatedly take the highest
        bucket from the currently most-loaded live donor (never stripping a
        donor bare) until the joiner holds an even share. Caller holds the
        lock; the buckets move via the caller's _grant."""
        donors = [s for s in self._slots.values() if s.alive and s.wid != wid and s.buckets]
        total = sum(len(s.buckets) for s in donors)
        target = total // (len(donors) + 1) if donors else 0
        sizes = {s.wid: len(s.buckets) for s in donors}
        steal: list[int] = []
        taken: set[int] = set()
        while len(steal) < target:
            donor = max(donors, key=lambda s: (sizes[s.wid], s.wid))
            if sizes[donor.wid] <= 1:
                break
            pool = [b for b in donor.buckets if b not in taken]
            if not pool:
                break
            b = max(pool)
            steal.append(b)
            taken.add(b)
            sizes[donor.wid] -= 1
        return steal

    def _m_heartbeat(self, req: dict) -> dict:
        wid = int(req["worker"])
        gets = req.get("gets") or {}
        with self._lock:
            for b, n in gets.items():
                self._get_counts[int(b)] = self._get_counts.get(int(b), 0) + int(n)
            slot = self._slots.get(wid)
            if slot is None:
                return {"reregister": True, **self._flags()}
            slot.last_heartbeat = time.monotonic()
            if not slot.alive:
                # declared dead but actually alive (slow round): it must
                # re-register to get a fresh (possibly different) range
                return {"reregister": True, **self._flags()}
            out = {
                "epoch": slot.epoch,
                "buckets": sorted(slot.buckets),
                "num_buckets": self.num_buckets,
                **self._flags(),
            }
            if wid in self._retiring:
                out["retire"] = True
            return out

    def _m_admit(self, req: dict) -> dict:
        """Cluster-wide debt-admission gate: non-blocking here, the worker
        retries with backoff (an RPC thread parked in wait_for would pin
        the server thread pool)."""
        wid = int(req["worker"])
        ident = int(req["ident"])
        buckets = [int(b) for b in req.get("buckets", ())]
        with self._lock:
            if self._rescale is not None or self._rescale_committing:
                # the rescale window: no new rounds start, the already
                # admitted in-flight ones get fenced at ship — the worker
                # sees `rescaling` and goes execute its rewrite task
                self._metrics().counter("admit_denied").inc()
                return {"admitted": False, "retry_after_ms": 200, "rescaling": True}
        if self.compaction is None:
            return {"admitted": True}
        key = (wid, ident)
        with self._lock:
            if key in self._admit_charges:
                return {"admitted": True}  # idempotent retry of the RPC
        ok = self.compaction.admit(
            buckets=[((), b) for b in buckets], timeout_s=0.0, project=True, owner=wid
        )
        if ok:
            with self._lock:
                self._admit_charges[key] = buckets
            return {"admitted": True}
        self._metrics().counter("admit_denied").inc()
        return {"admitted": False, "retry_after_ms": 100}

    def _settle_charges(self, wid: int, ident: int, landed: bool) -> None:
        with self._lock:
            buckets = self._admit_charges.pop((wid, ident), None)
        if buckets and self.compaction is not None:
            self.compaction.settle([((), b) for b in buckets], landed=landed, owner=wid)

    def _check_fence(self, slot: _WorkerSlot, epoch: int, buckets: list[int]) -> bool:
        """True when every bucket is still owned by the shipper at an epoch
        it has seen — the reassignment fence."""
        for b in buckets:
            if self._owner.get(b) != slot.wid or self._bucket_epoch.get(b, 1 << 62) > epoch:
                return False
        return True

    def _commit_store(self, wid: int):
        from ..table import load_table

        store = self._commit_stores.get(wid)
        if store is None:
            store = load_table(self.table_path, commit_user=f"{self.USER_PREFIX}{wid}").store
            self._commit_stores[wid] = store
        return store

    def _m_ship_commit(self, req: dict) -> dict:
        from ..core.commit import CommitConflictError, CommitGiveUpError
        from ..core.manifest import CommitMessage, ManifestCommittable

        wid = int(req["worker"])
        epoch = int(req["epoch"])
        kind = req.get("kind", "append")
        msgs = [CommitMessage.from_dict(m) for m in req.get("messages", ())]
        # a rescale shipment's messages carry NEW bucket ids, which nobody
        # owns under the old routing — the fence checks the OLD buckets the
        # task covered instead
        if kind == "rescale":
            touched = sorted(int(b) for b in req.get("buckets", ()))
        else:
            touched = sorted({m.bucket for m in msgs})
        g = self._metrics()
        with self._lock:
            slot = self._slots.get(wid)
            stale = slot is None or not self._check_fence(slot, epoch, touched)
        if kind == "rescale":
            return self._commit_rescale_part(req, msgs, touched, stale)
        if kind == "compact":
            return self._commit_compact(req, msgs, stale)
        ident = int(req["ident"])
        if stale:
            # the whole round is one commit: one reassigned bucket rejects
            # the shipment (never a partial apply of a fenced-off round)
            g.counter("commits_rejected_stale").inc()
            self._settle_charges(wid, ident, landed=False)
            return {"stale": True, "sid": None}
        with self._lock:
            prior = slot.committed.get(ident)
        if prior is not None:
            return {"sid": prior, "stale": False}  # idempotent re-ship
        store = self._commit_store(wid)
        sid = None
        try:
            sids = store.new_commit().commit(ManifestCommittable(ident, messages=msgs))
            sid = sids[0] if sids else None
        except (CommitConflictError, CommitGiveUpError):
            # the APPEND half may have landed before the loss — the chain,
            # not the exception, is the truth (PR 8 protocol)
            sid = find_landed_append(store, f"{self.USER_PREFIX}{wid}", ident)
        if sid is not None:
            with self._lock:
                slot.committed[ident] = sid
            g.counter("rounds_committed").inc()
        self._settle_charges(wid, ident, landed=sid is not None)
        return {"sid": sid, "stale": False}

    def _commit_compact(self, req: dict, msgs: list, stale: bool) -> dict:
        from ..core.commit import BATCH_COMMIT_IDENTIFIER, CommitConflictError, CommitGiveUpError
        from ..core.manifest import ManifestCommittable

        g = self._metrics()
        task_id = int(req.get("task_id", 0))
        with self._lock:
            group = self._task_groups.pop(task_id, None)
            for key, (tid, _w) in list(self._compact_inflight.items()):
                if tid == task_id:
                    del self._compact_inflight[key]
        if stale:
            g.counter("commits_rejected_stale").inc()
            return {"stale": True, "sid": None}
        if not msgs:
            return {"sid": None, "stale": False}
        try:
            sids = self.table.store.new_commit().commit(
                ManifestCommittable(BATCH_COMMIT_IDENTIFIER, messages=msgs)
            )
        except (CommitConflictError, CommitGiveUpError):
            # lost to a rival commit: abandoned, fresh state next round
            g.counter("compact_conflicts").inc()
            return {"sid": None, "stale": False}
        if group and self.compaction is not None:
            self.compaction.note_compaction_landed(group)
        g.counter("compact_commits").inc()
        return {"sid": sids[0] if sids else None, "stale": False}

    # ---- cross-worker dynamic-bucket rescale (ISSUE 19 tentpole) --------
    def start_rescale(self, new_buckets: int) -> dict:
        """Begin a coordinator-driven rescale to `new_buckets`.

        One global epoch bump fences EVERY bucket at once — the one fencing
        round: in-flight appends/compacts admitted before this instant get
        rejected stale at ship, new admits are denied for the window, and
        compaction dispatch pauses. Each live owner is handed a rescale
        task (its owned old buckets + the pinned snapshot); the rewrites
        ship back as kind="rescale" CommitMessages and land atomically in
        `_finish_rescale` once every old bucket is covered. Readers pinned
        at or before the snapshot stay bit-identical throughout."""
        new_buckets = int(new_buckets)
        if new_buckets < 1:
            return {"started": False, "reason": "bad-bucket-count"}
        snap = self.table.store.snapshot_manager.latest_snapshot()
        with self._lock:
            if self._rescale is not None or self._rescale_committing:
                return {"started": False, "reason": "rescale-in-progress"}
            if new_buckets == self.num_buckets:
                return {"started": False, "reason": "already-at-count"}
            if snap is None:
                return {"started": False, "reason": "empty-table"}
            self._epoch += 1
            for b in range(self.num_buckets):
                self._bucket_epoch[b] = self._epoch
            self._rescale = {
                "new": new_buckets,
                "snapshot": snap.id,
                "epoch": self._epoch,
                "needed": set(range(self.num_buckets)),
                "done": set(),
                "msgs": [],
                "deadline": time.monotonic() + self.rescale_timeout_s,
            }
            self._route_epoch += 1
            for slot in self._slots.values():
                if slot.alive and slot.buckets:
                    slot.tasks.append(self._rescale_task(sorted(slot.buckets)))
        return {"started": True, "snapshot": snap.id, "new_buckets": new_buckets}

    def _m_rescale(self, req: dict) -> dict:
        return self.start_rescale(int(req["new_buckets"]))

    def _m_rescale_status(self, req: dict) -> dict:
        with self._lock:
            rs = self._rescale
            return {
                "active": rs is not None or self._rescale_committing,
                "num_buckets": self.num_buckets,
                "done": sorted(rs["done"]) if rs else [],
            }

    def _commit_rescale_part(self, req: dict, msgs: list, covered: list[int], stale: bool) -> dict:
        g = self._metrics()
        with self._lock:
            rs = self._rescale
            if rs is None or stale:
                g.counter("commits_rejected_stale").inc()
                return {"stale": True, "sid": None}
            fresh = [b for b in covered if b in rs["needed"] and b not in rs["done"]]
            if not fresh:
                return {"stale": False, "sid": None, "dup": True}
            rs["done"].update(fresh)
            rs["msgs"].extend(msgs)
            complete = rs["done"] >= rs["needed"]
            if complete:
                # flip to the committing phase under the lock: admits stay
                # denied and no rival _finish_rescale can start
                self._rescale = None
                self._rescale_committing = True
        if not complete:
            return {"stale": False, "sid": None}
        return self._finish_rescale(rs)

    def _finish_rescale(self, rs: dict) -> dict:
        """Every old bucket rewritten: commit schema-(N+1) (`bucket` option
        bump) + ONE OVERWRITE snapshot, then atomically republish routing at
        the new bucket count (fresh contiguous split over live workers).
        Old data files stay on disk until snapshot expiry, so readers
        pinned pre-rescale keep their bit-identical view."""
        from ..table import load_table
        from ..table.rescale import commit_rescale

        g = self._metrics()
        try:
            sid = commit_rescale(self.table, rs["new"], rs["msgs"])
        except Exception:
            with self._lock:
                self._rescale_committing = False
                self._abort_rescale_locked()
            raise
        with self._lock:
            self.table = load_table(self.table_path, commit_user="cluster-coordinator")
            self.num_buckets = rs["new"]
            self._owner.clear()
            self._bucket_epoch.clear()
            self._pending.clear()
            self._commit_stores.clear()  # per-wid stores hold old-layout tables
            self._replicas.clear()  # bucket ids changed meaning
            self._heat_ema.clear()
            self._get_counts.clear()
            self._home = self._split_ranges()
            live = sorted((s for s in self._slots.values() if s.alive), key=lambda s: s.wid)
            for s in self._slots.values():
                s.buckets.clear()
                s.tasks = [t for t in s.tasks if t.get("kind") != "rescale"]
            if live:
                n, w = self.num_buckets, len(live)
                for i, s in enumerate(live):
                    self._grant(s, list(range(i * n // w, (i + 1) * n // w)))
            else:
                self._pending.extend(range(self.num_buckets))
            self._rescale_committing = False
            self._route_epoch += 1
            g.gauge("replicas_active").set(0)
            g.gauge("buckets_assigned").set(len(self._owner))
        if self.compaction is not None:
            self.compaction.table = self.table
        g.counter("rescales").inc()
        return {"stale": False, "sid": sid, "rescaled": rs["new"]}

    # ---- planned worker retire (scale-in) -------------------------------
    def request_retire(self, wid: int) -> None:
        """Flag `wid` for planned drain: the next heartbeat reply carries
        `retire`, the worker finishes its in-flight round, settles its
        charges, and calls the retire RPC for the range handoff."""
        with self._lock:
            self._retiring.add(int(wid))

    def _m_request_retire(self, req: dict) -> dict:
        self.request_retire(int(req["worker"]))
        return {}

    def _m_retire(self, req: dict) -> dict:
        """The drained worker's handoff: a planned retire is a death without
        the timeout — the same reassignment machinery moves its range (one
        fencing round), releases its debt-gate charges, and prunes its
        replica grants; the worker then exits clean."""
        wid = int(req["worker"])
        g = self._metrics()
        with self._lock:
            self._retiring.discard(wid)
            slot = self._slots.get(wid)
            if slot is None or not slot.alive:
                return {"retired": True}
            had = bool(slot.buckets)
            self._reassign_dead(slot)
            if had:
                g.counter("handoffs").inc()
        return {"retired": True}

    def _m_poll_work(self, req: dict) -> dict:
        wid = int(req["worker"])
        epoch = int(req["epoch"])
        with self._lock:
            slot = self._slots.get(wid)
            if slot is None:
                return {"tasks": [], "resync": True, **self._flags()}
            if slot.epoch != epoch:
                # stale poller (its range moved, or a rescale republished
                # routing): hand back the current assignment so it resyncs
                # on this reply instead of waiting out a heartbeat
                if slot.alive:
                    return {
                        "tasks": [], "resync": True, "epoch": slot.epoch,
                        "buckets": sorted(slot.buckets), **self._flags(),
                    }
                return {"tasks": [], "resync": True, **self._flags()}
            tasks, slot.tasks = slot.tasks, []
            return {"tasks": tasks, **self._flags()}

    def _m_barrier(self, req: dict) -> dict:
        """Named phase barrier (bench mode: every worker finishes ingest
        before anyone's timed merge-read pins the final state)."""
        name = str(req["name"])
        wid = int(req["worker"])
        expected = int(req.get("expected", self.cfg.workers))
        with self._lock:
            members = self._barriers.setdefault(name, set())
            members.add(wid)
            return {"released": len(members) >= expected}

    def _m_worker_done(self, req: dict) -> dict:
        wid = int(req["worker"])
        with self._lock:
            slot = self._slots.get(wid)
            if slot is not None:
                slot.done_stats = dict(req.get("stats", {}))
        return {}

    def _m_route(self, req: dict) -> dict:
        with self._lock:
            workers = {
                str(wid): {
                    "host": slot.serve_addr[0] if slot.serve_addr else None,
                    "port": slot.serve_addr[1] if slot.serve_addr else None,
                    "buckets": sorted(slot.buckets),
                    "epoch": slot.epoch,
                }
                for wid, slot in self._slots.items()
                if slot.alive
            }
            replicas = {str(b): list(wids) for b, wids in self._replicas.items()}
        return {"workers": workers, "num_buckets": self.num_buckets, "replicas": replicas}

    def _m_status(self, req: dict) -> dict:
        with self._lock:
            return {
                "workers": {
                    str(w): {
                        "alive": s.alive,
                        "buckets": sorted(s.buckets),
                        "epoch": s.epoch,
                        "commits": len(s.committed),
                        "done": s.done_stats,
                    }
                    for w, s in self._slots.items()
                },
                "pending_buckets": list(self._pending),
                "compact_inflight": len(self._compact_inflight),
            }

    # supervisor-side helpers (same process)
    def assignment_of(self, wid: int) -> tuple[int, list[int]]:
        with self._lock:
            slot = self._slots.get(wid)
            return (slot.epoch, sorted(slot.buckets)) if slot else (0, [])

    def all_done(self) -> bool:
        with self._lock:
            return bool(self._slots) and all(
                s.done_stats is not None for s in self._slots.values()
            )


# ---------------------------------------------------------------------------
# RPC client plumbing (shared by workers and ClusterClient)
# ---------------------------------------------------------------------------
class _RpcConn:
    """One persistent length-prefixed-JSON connection, thread-safe."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._lock = threading.Lock()
        self._id = 0

    def call(self, method: str, **kw) -> dict:
        with self._lock:
            self._id += 1
            _send(self._sock, {"id": self._id, "method": method, **kw})
            resp = _recv(self._sock)
        if resp is None:
            raise ConnectionError(f"{method}: server closed the connection")
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", f"{method} failed"))
        return resp

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def cancel(self) -> None:
        """Abort an in-flight call from ANOTHER thread. shutdown() unblocks
        a peer stuck in recv (close() alone need not), so the blocked call
        raises ConnectionError — the gateway's hedge-loser teardown.

        Deliberately NOT close(): the blocked caller still owns this fd.
        Closing here frees the fd number for reuse while that caller may be
        an instruction away from recv()ing on it — it would then block
        forever stealing a brand-new connection's replies. The caller's
        error path discards (closes) the connection itself."""
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass


def _row_buckets(table, batch) -> np.ndarray:
    """(n,) int32 bucket id per row of a value batch (fixed-bucket route)."""
    from ..table.bucket import bucket_ids

    return bucket_ids(batch, table.schema.bucket_keys, max(table.store.options.bucket, 1))


def bucket_key_pools(num_buckets: int, base: int, count_per_bucket: int) -> dict[int, np.ndarray]:
    """Deterministic per-bucket key pools: scan candidate keys base+[0, M)
    in vector chunks, bucketize with the table's own hash, and keep the
    first `count_per_bucket` keys landing in each bucket. Identical in
    every process for identical args — the bench's worker-count-independent
    row generator and the soak's owned-bucket key source."""
    from ..data.batch import ColumnBatch
    from ..table.bucket import bucket_ids
    from ..types import BIGINT, RowType

    rt = RowType.of(("k", BIGINT()))
    pools: dict[int, list] = {b: [] for b in range(num_buckets)}
    start = base
    while any(len(p) < count_per_bucket for p in pools.values()):
        ks = np.arange(start, start + 4096, dtype=np.int64)
        start += 4096
        bs = bucket_ids(ColumnBatch.from_pydict(rt, {"k": ks}), ["k"], num_buckets)
        for b in range(num_buckets):
            need = count_per_bucket - len(pools[b])
            if need > 0:
                pools[b].extend(ks[bs == b][:need].tolist())
    return {b: np.asarray(p, dtype=np.int64) for b, p in pools.items()}


# ---------------------------------------------------------------------------
# worker serving plane: get_batch + subscribe + join_part on the worker
# ---------------------------------------------------------------------------
class _WorkerServer:
    """The worker's request plane (closes the PR 13/14 follow-ups: gets and
    subscriptions served FROM the mesh workers). LocalTableQuery rides the
    subscription-driven refresher (query.follow — one decode-once tailer
    keeps every touched bucket's probe index fresh); subscriptions filter
    each fanned batch to the requested buckets so a routed client folds
    exactly its shard's changelog."""

    def __init__(
        self,
        table,
        owned: "callable",
        host: str = "127.0.0.1",
        port: int = 0,
        delay_ms: "float | None" = None,
        route_epoch: "callable | None" = None,
    ):
        from ..options import CoreOptions
        from ..table.query import LocalTableQuery
        from .subscription import SubscriptionHub

        self.table = table
        self._owned = owned  # () -> set[int], the worker's live bucket set
        self._route_epoch = route_epoch  # () -> int, piggybacked on replies
        self._get_counts: dict[int, int] = {}  # bucket -> gets (heat report)
        self._lock = threading.Lock()
        # injected straggler latency on the read plane (get_batch/scan_frag):
        # the gateway bench/storm latency-shame one worker to measure hedging
        if delay_ms is None:
            delay_ms = float(os.environ.get("PAIMON_TPU_WORKER_SERVE_DELAY_MS", "0"))
        self._delay_ms = float(delay_ms)
        self._closed = False
        # scan_frag admission (ISSUE 16, the PR 13 semaphore + retry_after
        # pattern): a scan storm sheds typed-BUSY instead of starving the
        # get/subscribe serving this plane exists for
        self._scan_slots = threading.BoundedSemaphore(
            max(1, int(table.store.options.options.get(CoreOptions.SQL_CLUSTER_SCAN_MAX_INFLIGHT)))
        )
        # shuffle exchange plane (ISSUE 20). Admission is a SEPARATE
        # semaphore from _scan_slots: a scan_frag HOLDS its scan slot while
        # delivering parts to peer owners, so shared admission would
        # livelock a fleet of mutually-delivering workers into circular
        # BUSY retries. Buffers are TTL-GC'd; a coordinator that finishes
        # cleanly closes them explicitly (exchange_close).
        self._exch_slots = threading.BoundedSemaphore(
            max(2, 2 * int(table.store.options.options.get(CoreOptions.SQL_CLUSTER_SCAN_MAX_INFLIGHT)))
        )
        self._exch_lock = threading.Lock()
        # inbound: qid -> {"ts", "parts": {(range, src): wire partial}} —
        # delivery is keyed, so hedged/re-executed duplicates overwrite
        # with bit-identical content instead of double-counting
        self._exch_in: dict[str, dict] = {}
        # outbound (the reship buffer): (qid, src) -> {"ts", "parts":
        # {range: wire partial}} — survives the range owner, not the source
        self._exch_out: dict[tuple, dict] = {}
        self._peer_conns: dict[tuple, _RpcConn] = {}
        # one hub per worker process: the refresher AND every routed
        # subscription share its decode-once tailer; the server owns its
        # lifecycle (for_table hubs outlive their subscribers by design)
        self._hub = SubscriptionHub.for_table(table)
        self.query = LocalTableQuery(table)
        self.query.follow(hub=self._hub, lock=self._lock)
        self._subs: dict[str, object] = {}
        self._sub_seq = 0
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    req = _recv(self.request)
                    if req is None:
                        return
                    rid = req.pop("id", None)
                    method = req.pop("method", "")
                    try:
                        out = outer._dispatch(method, req)
                        out["id"] = rid
                        out.setdefault("ok", True)
                        if outer._route_epoch is not None:
                            # push invalidation rides the serving plane too:
                            # a client talking only to workers still learns
                            # of reassignments the moment they happen
                            out.setdefault("route_epoch", int(outer._route_epoch()))
                    except Exception as e:  # noqa: BLE001
                        out = {"id": rid, "ok": False, "error": f"{type(e).__name__}: {e}"}
                    _send(self.request, out)

        self._server = socketserver.ThreadingTCPServer((host, port), Handler, bind_and_activate=True)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def _metrics(self):
        from ..metrics import cluster_metrics

        return cluster_metrics()

    def _dispatch(self, method: str, req: dict) -> dict:
        if method == "ping":
            return {"buckets": sorted(self._owned())}
        if self._closed and method in (
            "get_batch",
            "subscribe_open",
            "scan_frag",
            "exchange_part",
            "exchange_combine",
            "exchange_reship",
        ):
            # shutdown race (ISSUE 17 bugfix hunt): a request landing while
            # close() tears down the hub must answer a TYPED shed, not leak
            # a fresh hub/tailer out of a re-created subscription
            from .shed import ShedInfo

            return ShedInfo(kind="request", state="shutting-down", retry_after_ms=100).to_payload()
        if method in ("get_batch", "scan_frag") and self._delay_ms > 0:
            time.sleep(self._delay_ms / 1000.0)
        if method == "get_batch":
            ks = [tuple(k) if isinstance(k, list) else (k,) for k in req["keys"]]
            with self._lock:
                res = self.query.get_batch(ks, tuple(req.get("partition", ())))
            self._metrics().counter("serve_gets").inc(len(ks))
            self._note_gets(ks)
            return {"rows": [None if r is None else list(r) for r in res.to_pylist()]}
        if method == "subscribe_open":
            # _sub_seq increments under the lock: two concurrent opens in
            # separate handler threads must never mint the same sub_id (the
            # shadowed Subscription would leak its consumer slot)
            with self._lock:
                self._sub_seq += 1
                sub_id = f"s{self._sub_seq}"
            self._subs[sub_id] = (
                self._hub.subscribe(
                    consumer_id=req.get("consumer_id"),
                    from_snapshot=req.get("from_snapshot"),
                ),
                [int(b) for b in req.get("buckets", [])] or None,
            )
            return {"sub_id": sub_id}
        if method == "subscribe_poll":
            return self._subscribe_poll(req)
        if method == "subscribe_close":
            sub, _ = self._subs.pop(req["sub_id"], (None, None))
            if sub is not None:
                sub.close(delete_consumer=bool(req.get("delete_consumer")))
            return {}
        if method == "join_part":
            return self._join_part(req)
        if method == "scan_frag":
            return self._scan_frag(req)
        if method == "exchange_part":
            return self._exchange_part(req)
        if method == "exchange_combine":
            return self._exchange_combine(req)
        if method == "exchange_reship":
            return self._exchange_reship(req)
        if method == "exchange_close":
            return self._exchange_close(req)
        raise ValueError(f"unknown method {method!r}")

    def _scan_frag(self, req: dict) -> dict:
        """One distributed-SQL scan fragment (ISSUE 16): rebuild the shipped
        splits, scan + reduce locally (table.query.execute_scan_fragment),
        ship the partial back. Admission is typed-BUSY under
        sql.cluster.scan.max-inflight; sheds count into soak{shed_requests}
        beside every other serving-plane BUSY."""
        if not self._scan_slots.acquire(blocking=False):
            from ..metrics import soak_metrics
            from .shed import ShedInfo

            soak_metrics().counter("shed_requests").inc()
            return ShedInfo(kind="sql", state="busy-scan", retry_after_ms=50).to_payload()
        try:
            from ..sql.cluster import decode_fragment, encode_partial
            from ..table.query import execute_scan_fragment

            frag = decode_fragment(req["frag"])
            part = execute_scan_fragment(self.table, frag)
            self._metrics().counter("scan_frags_served").inc()
            if frag.get("shuffle") and part["mode"] == "agg":
                return {"partial": self._shuffle_out(frag, part)}
            return {"partial": encode_partial(part, code_domain=bool(frag.get("code_domain", True)))}
        finally:
            self._scan_slots.release()

    # ---- shuffle exchange plane (ISSUE 20) ------------------------------
    _EXCHANGE_TTL_S = 600.0

    def _shuffle_out(self, frag: dict, part: dict) -> dict:
        """Shuffle-source tail of scan_frag: hash-partition the fragment
        partial by group-key VALUE into the plan's R ranges
        (table.query.partition_agg_partial), buffer every nonempty part for
        reship, deliver each to its range owner, and answer a summary whose
        `sent` map is the coordinator's per-range expectation source. A
        delivery that fails is swallowed — the part stays buffered and the
        coordinator reships/recovers at combine time; failing the scan here
        would throw away a perfectly good partial."""
        from ..sql.cluster import encode_partial, wire_partial_bytes
        from ..table.query import partition_agg_partial

        qid, src = frag["shuffle"]["qid"], frag["src"]
        ranges = frag["shuffle"]["ranges"]
        code_domain = bool(frag.get("code_domain", True))
        parts = partition_agg_partial(part, len(ranges))
        wire = {
            r: encode_partial(pt, code_domain=code_domain)
            for r, pt in enumerate(parts)
            if pt is not None
        }
        now = time.monotonic()
        with self._exch_lock:
            self._gc_exchange_locked()
            self._exch_out[(qid, src)] = {"ts": now, "parts": wire}
        sent: dict = {}
        nbytes = 0

        def _ship(r, wp):
            try:
                self._deliver_part(ranges[r][1], int(ranges[r][2]), qid, r, src, wp)
            except (ConnectionError, OSError, TimeoutError):
                pass  # dead/slow owner: coordinator heals it at combine time

        # concurrent deliveries: each remote part pays a full serialize +
        # round-trip, and a source owes R-1 of them — overlapping them keeps
        # the scatter's critical path at ~one part instead of R-1
        remote = []
        for r, wp in wire.items():
            nbytes += wire_partial_bytes(wp)
            sent[str(r)] = int(parts[r]["rows"])
            if (ranges[r][1], int(ranges[r][2])) == (self.host, self.port):
                _ship(r, wp)  # self-delivery is a buffer insert, no wire
            else:
                remote.append((r, wp))
        if len(remote) == 1:
            _ship(*remote[0])
        elif remote:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(remote)) as pool:
                for f in [pool.submit(_ship, r, wp) for r, wp in remote]:
                    f.result()
        self._metrics().counter("exchange_parts_sent").inc(len(wire))
        return {
            "mode": "shuffle",
            "src": src,
            "rows": int(part["rows"]),
            "rows_reduced_device": int(part.get("rows_reduced_device", 0)),
            "sent": sent,
            "bytes": int(nbytes),
        }

    def _deliver_part(
        self, host: str, port: int, qid: str, rng: int, src: str, wp: dict, busy_wait_s: float = 10.0
    ) -> None:
        """Ship one buffered part to a range owner. Self-delivery drops
        straight into the inbound buffer (no wire); remote delivery absorbs
        typed-BUSY with the advertised backoff and raises on a dead peer."""
        if (host, int(port)) == (self.host, self.port):
            with self._exch_lock:
                box = self._exch_in.setdefault(qid, {"ts": time.monotonic(), "parts": {}})
                box["parts"][(int(rng), src)] = wp
                box["ts"] = time.monotonic()
            return
        deadline = time.monotonic() + busy_wait_s
        while True:
            conn = self._peer_conn(host, int(port))
            try:
                r = conn.call("exchange_part", qid=qid, rng=int(rng), src=src, part=wp)
            except (ConnectionError, OSError):
                self._drop_peer(host, int(port))
                raise
            if not r.get("busy"):
                return
            if time.monotonic() >= deadline:
                raise TimeoutError(f"exchange peer {host}:{port} still BUSY after {busy_wait_s}s")
            time.sleep(float(r.get("retry_after_ms", 50)) / 1000.0)

    def _peer_conn(self, host: str, port: int) -> _RpcConn:
        with self._exch_lock:
            conn = self._peer_conns.get((host, port))
        if conn is not None:
            return conn
        fresh = _RpcConn(host, port, timeout=10.0)  # connect outside the lock
        with self._exch_lock:
            won = self._peer_conns.setdefault((host, port), fresh)
        if won is not fresh:
            fresh.close()
        return won

    def _drop_peer(self, host: str, port: int) -> None:
        with self._exch_lock:
            conn = self._peer_conns.pop((host, port), None)
        if conn is not None:
            conn.close()

    def _gc_exchange_locked(self) -> None:
        cutoff = time.monotonic() - self._EXCHANGE_TTL_S
        for q in [q for q, box in self._exch_in.items() if box["ts"] < cutoff]:
            del self._exch_in[q]
        for k in [k for k, box in self._exch_out.items() if box["ts"] < cutoff]:
            del self._exch_out[k]

    def _exchange_shed(self):
        from ..metrics import soak_metrics
        from .shed import ShedInfo

        soak_metrics().counter("shed_requests").inc()
        return ShedInfo(kind="sql", state="busy-exchange", retry_after_ms=50).to_payload()

    def _exchange_part(self, req: dict) -> dict:
        """Receive one shuffle part from a peer worker (keyed delivery:
        (qid, range, src) — redelivery overwrites idempotently)."""
        if not self._exch_slots.acquire(blocking=False):
            return self._exchange_shed()
        try:
            with self._exch_lock:
                self._gc_exchange_locked()
                box = self._exch_in.setdefault(req["qid"], {"ts": time.monotonic(), "parts": {}})
                box["parts"][(int(req["rng"]), req["src"])] = req["part"]
                box["ts"] = time.monotonic()
            self._metrics().counter("exchange_parts_received").inc()
            return {}
        finally:
            self._exch_slots.release()

    def _exchange_combine(self, req: dict) -> dict:
        """Fold this worker's shuffle range: decode every EXPECTED part
        from the inbound buffer and run the coordinator's own
        combine_partials over them — the range's final reduction, answered
        as one already-reduced partial. Parts still missing (delivery
        failed in flight, or this worker is a fresh replacement owner) are
        named so the coordinator can reship them."""
        if not self._exch_slots.acquire(blocking=False):
            return self._exchange_shed()
        try:
            from ..sql.cluster import combine_partials, decode_partial, encode_partial

            qid, rng = req["qid"], int(req["rng"])
            expect = list(req.get("expect") or [])
            with self._exch_lock:
                parts_map = dict(self._exch_in.get(qid, {}).get("parts", {}))
            have = {src: parts_map.get((rng, src)) for src in expect}
            missing = sorted(src for src, wp in have.items() if wp is None)
            if missing:
                return {"missing": missing}
            group_cols = list(req.get("group_cols") or [])
            kern = [tuple(k) for k in req.get("kern") or []]
            projection = req.get("projection")
            schema = (
                self.table.row_type.project(list(projection))
                if projection is not None
                else self.table.row_type
            )
            parts = [decode_partial(have[src], schema, group_cols) for src in expect]
            parts = [q for q in parts if q["rows"]]
            if not parts:  # unreachable: senders never ship empty parts
                raise ValueError(f"exchange_combine: no nonempty parts for range {rng}")
            pools, codes, outs, anyv, first_pos = combine_partials(
                parts, len(group_cols), kern, req.get("engine", "xla")
            )
            out_part = {
                "mode": "agg",
                "pools": pools,
                "group_codes": codes,
                "outs": outs,
                "anyv": anyv,
                "first_pos": first_pos,
                "rows": int(len(first_pos)),
                "rows_reduced_device": 0,  # the sources already accounted theirs
            }
            self._metrics().counter("exchange_combines_served").inc()
            return {"partial": encode_partial(out_part, code_domain=bool(req.get("code_domain", True)))}
        finally:
            self._exch_slots.release()

    def _exchange_reship(self, req: dict) -> dict:
        """Re-send one buffered outbound part to a (possibly re-homed)
        range owner. Delivery failure answers shipped=false instead of
        raising: the coordinator's next move (re-execute the fragment)
        is the same either way, and an error reply would surface as a
        spurious RuntimeError in its recovery loop."""
        if not self._exch_slots.acquire(blocking=False):
            return self._exchange_shed()
        try:
            qid, src, rng = req["qid"], req["src"], int(req["rng"])
            with self._exch_lock:
                wp = self._exch_out.get((qid, src), {}).get("parts", {}).get(rng)
            if wp is None:
                return {"shipped": False}
            try:
                self._deliver_part(req["host"], int(req["port"]), qid, rng, src, wp)
            except (ConnectionError, OSError, TimeoutError):
                return {"shipped": False}
            self._metrics().counter("exchange_parts_reshipped").inc()
            return {"shipped": True}
        finally:
            self._exch_slots.release()

    def _exchange_close(self, req: dict) -> dict:
        """Drop a finished query's exchange buffers (best-effort; the TTL
        GC catches whatever a dead coordinator leaves behind)."""
        qid = req["qid"]
        with self._exch_lock:
            self._exch_in.pop(qid, None)
            for k in [k for k in self._exch_out if k[0] == qid]:
                del self._exch_out[k]
        return {}

    def _subscribe_poll(self, req: dict) -> dict:
        from ..types import RowKind
        from .subscription import SubscriberShedError

        sub, buckets = self._subs.get(req["sub_id"], (None, None))
        if sub is None:
            raise ValueError(f"unknown subscription {req['sub_id']!r}")
        timeout = float(req.get("timeout_ms", 1000)) / 1000.0
        try:
            batch = sub.poll(timeout=timeout)
        except SubscriberShedError as e:
            self._subs.pop(req["sub_id"], None)
            return {"shed": True, **{k: v for k, v in e.payload.items() if k != "state"}}
        self._metrics().counter("serve_subscribe_polls").inc()
        if batch is None:
            return {"rows": [], "snapshot_id": None, "checkpoint": sub.checkpoint}
        rows = list(zip(batch.data.to_pylist(), batch.kinds.tolist()))
        if buckets is not None:
            mask = _row_buckets(self.table, batch.data)
            rows = [rv for rv, b in zip(rows, mask.tolist()) if b in buckets]
        return {
            "rows": [[RowKind(int(k)).short_string, *r] for r, k in rows],
            "snapshot_id": batch.snapshot_id,
            "checkpoint": sub.checkpoint,
        }

    def _join_part(self, req: dict) -> dict:
        """One JSPIM partition's kernel, executed on this worker (ISSUE 15
        satellite: the skew split spans worker processes)."""
        from ..ops.join import _join_part as run_part

        ll = _unb64(req["ll"])
        rl = _unb64(req["rl"])
        lt, rt = run_part(ll, rl, req.get("algorithm", "sort-merge"), req.get("engine", "numpy"))
        self._metrics().counter("join_parts_served").inc()
        return {"lt": _b64(np.asarray(lt, dtype=np.int64)), "rt": _b64(np.asarray(rt, dtype=np.int64))}

    def _note_gets(self, ks: list) -> None:
        """Fold served probe keys into per-bucket counts — the worker's
        heartbeat ships the deltas, the coordinator's replica planner turns
        them into the serve-read heat EMA."""
        try:
            from ..data.batch import ColumnBatch
            from ..table.bucket import bucket_ids
            from ..types import RowType

            keys = self.table.schema.bucket_keys
            if not ks or len(keys) != 1 or any(len(k) != 1 for k in ks):
                return
            fields = {f.name: f for f in self.table.schema.fields}
            rt = RowType.of((keys[0], fields[keys[0]].type))
            probe = ColumnBatch.from_pydict(rt, {keys[0]: [k[0] for k in ks]})
            bs = bucket_ids(probe, keys, max(self.table.store.options.bucket, 1))
            with self._lock:
                for b in bs.tolist():
                    self._get_counts[b] = self._get_counts.get(b, 0) + 1
        except Exception:  # noqa: BLE001 — heat is advisory, never fail a get
            pass

    def take_get_counts(self) -> dict[int, int]:
        with self._lock:
            out, self._get_counts = self._get_counts, {}
        return out

    def reload_table(self, table) -> None:
        """Swap the serving plane onto a reloaded table (bucket-count change
        after a rescale): a query constructed over the OLD schema would
        bucketize probes hash%old against the new layout — a silent miss.
        The new query refreshes off-lock, then swaps in atomically; the
        shared hub keeps tailing (decode is bucket-count independent)."""
        from ..table.query import LocalTableQuery

        fresh = LocalTableQuery(table)
        with self._lock:
            old_q, self.query = self.query, fresh
            self.table = table
        fresh.follow(hub=self._hub, lock=self._lock)
        try:
            old_q.unfollow()
        except Exception:  # noqa: BLE001
            pass

    def close(self) -> None:
        self._closed = True
        with self._exch_lock:
            peer_conns = list(self._peer_conns.values())
            self._peer_conns.clear()
            self._exch_in.clear()
            self._exch_out.clear()
        for c in peer_conns:
            c.close()
        for sub_id in list(self._subs):
            sub, _ = self._subs.pop(sub_id, (None, None))
            if sub is not None:
                try:
                    sub.close()
                except Exception:
                    pass
        self.query.unfollow()
        try:
            self._hub.close()
        except Exception:
            pass
        self._server.shutdown()
        self._server.server_close()


# ---------------------------------------------------------------------------
# worker agent
# ---------------------------------------------------------------------------
class _KeyGen:
    """Owned-bucket fresh-key source over this worker's private keyspace:
    scan candidates forward from a durable offset, bucketize with the
    table's own hash, keep what lands in owned buckets. The journal records
    (scan_start, scan_span) per intent so a respawned incarnation resumes
    PAST every scanned candidate — a key is never minted twice, landed or
    not, which keeps the fold unambiguous."""

    def __init__(self, num_buckets: int, base: int, offset: int = 0):
        self.num_buckets = num_buckets
        self.base = base
        self.offset = offset

    def take(self, owned: "set[int]", per_bucket: int) -> tuple[dict[int, list[int]], int, int]:
        from ..data.batch import ColumnBatch
        from ..table.bucket import bucket_ids
        from ..types import BIGINT, RowType

        rt = RowType.of(("k", BIGINT()))
        start = self.offset
        got: dict[int, list[int]] = {b: [] for b in owned}
        while any(len(v) < per_bucket for v in got.values()):
            ks = np.arange(self.base + self.offset, self.base + self.offset + 2048, dtype=np.int64)
            self.offset += 2048
            bs = bucket_ids(ColumnBatch.from_pydict(rt, {"k": ks}), ["k"], self.num_buckets)
            for b in owned:
                need = per_bucket - len(got[b])
                if need > 0:
                    got[b].extend(ks[bs == b][:need].tolist())
        return got, start, self.offset - start


class ClusterWorkerAgent:
    """One worker's protocol logic, independent of process boundaries so
    tests drive it in-process. The OS-process child (worker_main) wraps one
    around a freshly initialized jax runtime (parallel.distributed.
    init_worker_runtime — multi-host when configured, single-process
    fallback otherwise)."""

    def __init__(
        self,
        wid: int,
        table,
        coord_host: str,
        coord_port: int,
        journal_path: str | None = None,
        incarnation: int = 0,
        serve: bool = True,
        round_rows: int = 256,
        update_fraction: float = 0.3,
        admit_timeout_s: float = 30.0,
        heartbeat_interval_s: float = 0.5,
        seed: int = 0,
        serve_delay_ms: "float | None" = None,
    ):
        from .proc_soak import WriterJournal

        self.wid = wid
        self.table = table
        self.user = f"{ClusterCoordinator.USER_PREFIX}{wid}"
        self.num_buckets = max(table.store.options.bucket, 1)
        self.round_rows = round_rows
        self.update_fraction = update_fraction
        self.admit_timeout_s = admit_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.rng = np.random.default_rng(seed * 7919 + wid * 104729 + incarnation)
        self.incarnation = incarnation
        self.conn = _RpcConn(coord_host, coord_port)
        self.route_epoch = 0
        self.server: _WorkerServer | None = None
        if serve:
            self.server = _WorkerServer(
                table, self._owned_set, delay_ms=serve_delay_ms,
                route_epoch=lambda: self.route_epoch,
            )
        self._assign_lock = threading.Lock()
        self._reload_lock = threading.Lock()
        self._epoch = 0
        self._buckets: set[int] = set()
        self._go = False
        self._retire_flag = False
        self.retired = False
        self._stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self.journal = None
        self.next_ident = 1
        self.landed_by_bucket: dict[int, list[int]] = {}
        self.keygen = _KeyGen(self.num_buckets, wid * KEYSPACE)
        self.recovered = 0
        if journal_path is not None:
            self.journal = WriterJournal(journal_path)
            self._recover(journal_path)
            self.journal.open()

    # ---- journal recovery (PR 9 machinery, verbatim protocol) ----------
    def _recover(self, journal_path: str) -> None:
        from ..data.batch import ColumnBatch
        from ..table.bucket import bucket_ids
        from ..types import BIGINT, RowType
        from .proc_soak import WriterJournal

        events = WriterJournal.read(journal_path)
        intents = [e for e in events if e["t"] == "intent"]
        resolved = {e["ident"] for e in events if e["t"] in ("ack", "recovered", "abort")}
        acked = {e["ident"] for e in events if e["t"] in ("ack", "recovered")}
        self.next_ident = max((e["ident"] for e in intents), default=0) + 1
        self.keygen.offset = max((e["fresh"][0] + e["fresh"][1] for e in intents), default=0)
        self._pending_recovery = [e for e in intents if e["ident"] not in resolved]
        landed_keys = [int(k) for e in intents if e["ident"] in acked for k in e["rows"]]
        self._landed_pending = landed_keys
        if landed_keys:
            rt = RowType.of(("k", BIGINT()))
            ks = np.asarray(landed_keys, dtype=np.int64)
            bs = bucket_ids(ColumnBatch.from_pydict(rt, {"k": ks}), ["k"], self.num_buckets)
            for k, b in zip(landed_keys, bs.tolist()):
                self.landed_by_bucket.setdefault(int(b), []).append(k)

    def _resolve_unacked(self) -> None:
        """Respawn half of the recovery: every intent without an ack is
        resolved against the SNAPSHOT CHAIN (the coordinator may have
        committed the round after this worker died mid-ship) —
        adopt-never-replay, exactly the PR 9 writer protocol."""
        pending = getattr(self, "_pending_recovery", [])
        self._pending_recovery = []
        for e in pending:
            sid = find_landed_append(self.table.store, self.user, e["ident"])
            if sid is not None:
                self.journal.recovered(e["ident"], sid)
                self.recovered += 1
                from ..data.batch import ColumnBatch
                from ..table.bucket import bucket_ids
                from ..types import BIGINT, RowType

                ks = np.asarray([int(k) for k in e["rows"]], dtype=np.int64)
                if len(ks):
                    rt = RowType.of(("k", BIGINT()))
                    bs = bucket_ids(ColumnBatch.from_pydict(rt, {"k": ks}), ["k"], self.num_buckets)
                    for k, b in zip(ks.tolist(), bs.tolist()):
                        self.landed_by_bucket.setdefault(int(b), []).append(int(k))
            else:
                self.journal.abort(e["ident"])

    # ---- assignment sync -----------------------------------------------
    def _owned_set(self) -> set[int]:
        with self._assign_lock:
            return set(self._buckets)

    def _apply(self, resp: dict) -> None:
        # bucket-count change applies BEFORE the epoch/bucket assignment:
        # by the time a post-rescale epoch is visible to ingest_round, the
        # table, keygen, and serving query already speak the new layout
        # (the reverse order would let a round write old-layout files and
        # ship them under a new epoch — past the fence, wrong total_buckets)
        nb = resp.get("num_buckets")
        if nb is not None and int(nb) != self.num_buckets:
            self._on_bucket_count_change(int(nb))
        with self._assign_lock:
            re = resp.get("route_epoch")
            if re is not None and int(re) > self.route_epoch:
                self.route_epoch = int(re)
            if "epoch" in resp and resp.get("epoch") is not None:
                self._epoch = int(resp["epoch"])
                self._buckets = {int(b) for b in resp.get("buckets", ())}
            self._go = bool(resp.get("go", self._go))
            if resp.get("retire"):
                self._retire_flag = True
            if resp.get("stop"):
                self._stop.set()

    def _on_bucket_count_change(self, n: int) -> None:
        """The coordinator committed a rescale: reload the table at the new
        schema, re-key the fresh-key generator, rebucketize the landed-key
        update pool, and swap the serving plane's query — all before the
        new assignment epoch becomes visible (see _apply)."""
        from ..table import load_table

        with self._reload_lock:
            if n == self.num_buckets:
                return
            table = load_table(str(self.table.path), commit_user=self.user)
            new_map: dict[int, list[int]] = {}
            landed = [k for ks in self.landed_by_bucket.values() for k in ks]
            if landed:
                from ..data.batch import ColumnBatch
                from ..table.bucket import bucket_ids
                from ..types import BIGINT, RowType

                ks = np.asarray(landed, dtype=np.int64)
                bs = bucket_ids(
                    ColumnBatch.from_pydict(RowType.of(("k", BIGINT())), {"k": ks}), ["k"], n
                )
                for k, b in zip(landed, bs.tolist()):
                    new_map.setdefault(int(b), []).append(int(k))
            self.table = table
            self.num_buckets = n
            self.keygen.num_buckets = n
            self.landed_by_bucket = new_map
            if self.server is not None:
                self.server.reload_table(table)

    def assignment(self) -> tuple[int, list[int]]:
        with self._assign_lock:
            return self._epoch, sorted(self._buckets)

    def register(self) -> None:
        kw = {"worker": self.wid, "incarnation": self.incarnation}
        if self.server is not None:
            kw["serve_host"] = self.server.host
            kw["serve_port"] = self.server.port
        self._apply(self.conn.call("register", **kw))
        if self.journal is not None:
            self._resolve_unacked()

    def start_heartbeats(self) -> None:
        if self._hb_thread is not None:
            return

        def loop():
            while not self._stop.wait(self.heartbeat_interval_s):
                kw = {"worker": self.wid, "epoch": self._epoch}
                if self.server is not None:
                    gets = self.server.take_get_counts()
                    if gets:
                        # serve-read heat report: the replica planner's input
                        kw["gets"] = {str(b): n for b, n in gets.items()}
                try:
                    resp = self.conn.call("heartbeat", **kw)
                except Exception:
                    continue  # coordinator shutting down: main loop handles stop
                if resp.get("reregister"):
                    try:
                        self._apply(self.conn.call("register", worker=self.wid,
                                                   incarnation=self.incarnation,
                                                   **({"serve_host": self.server.host,
                                                       "serve_port": self.server.port}
                                                      if self.server else {})))
                    except Exception:
                        pass
                else:
                    self._apply(resp)

        self._hb_thread = threading.Thread(
            target=loop, name=f"paimon-clu-hb-{self.wid}", daemon=True
        )
        self._hb_thread.start()

    # ---- ingest --------------------------------------------------------
    def _admit(self, ident: int, buckets: list[int]) -> bool:
        deadline = time.monotonic() + self.admit_timeout_s
        while not self._stop.is_set():
            r = self.conn.call("admit", worker=self.wid, ident=ident, buckets=buckets)
            if r.get("admitted"):
                return True
            if r.get("rescaling"):
                # the rescale window: stop queueing at the gate and go poll —
                # the rewrite task for our owned buckets is waiting
                return False
            if time.monotonic() >= deadline:
                return False
            time.sleep(min(r.get("retry_after_ms", 100) / 1000.0, 0.25))
        return False

    def ingest_round(self) -> bool:
        """One journaled ingest round over the currently owned buckets:
        admit -> intent -> local mesh flush -> ship -> ack/abort. Returns
        True when the round landed."""
        from ..data.batch import ColumnBatch
        from ..resilience.faults import crash_point
        from ..table.write import TableWrite

        epoch, owned = self.assignment()
        if not owned:
            time.sleep(0.1)
            return False
        ident = self.next_ident
        if not self._admit(ident, owned):
            return False
        self.next_ident += 1
        per_bucket = max(self.round_rows, 1)
        n_upd = int(per_bucket * self.update_fraction)
        fresh, scan_start, scan_span = self.keygen.take(set(owned), per_bucket - n_upd)
        keys: list[int] = []
        for b in owned:
            keys.extend(fresh[b])
            landed = self.landed_by_bucket.get(b, [])
            if landed and n_upd:
                idx = self.rng.integers(0, len(landed), min(n_upd, len(landed)))
                keys.extend(landed[i] for i in idx)
        vals = (ident * 1000.0 + self.wid) + self.rng.random(len(keys))
        rows = dict(zip(keys, (float(v) for v in vals)))
        if self.journal is not None:
            self.journal.intent(ident, scan_start, scan_span, rows)
        tw = TableWrite(self.table)
        try:
            ks = list(rows)
            vs = [rows[k] for k in ks]
            for i in range(0, len(ks), 512):
                tw.write(ColumnBatch.from_pydict(SCHEMA, {"k": ks[i : i + 512], "v": vs[i : i + 512]}))
            msgs = tw.prepare_commit()
        finally:
            tw.close()
        crash_point("cluster:before-ship")
        r = self.conn.call(
            "ship_commit",
            worker=self.wid,
            epoch=epoch,
            ident=ident,
            kind="append",
            messages=[m.to_dict() for m in msgs],
        )
        if r.get("sid") is not None:
            if self.journal is not None:
                self.journal.ack(ident, r["sid"])
            for b in owned:
                self.landed_by_bucket.setdefault(b, []).extend(fresh[b])
            return True
        # stale fence or verifiably-not-landed: the round's files are
        # orphans for the sweep, the keys are never reused
        if self.journal is not None:
            self.journal.abort(ident)
        return False

    # ---- compaction execution ------------------------------------------
    def poll_and_compact(self) -> int:
        epoch, _ = self.assignment()
        r = self.conn.call("poll_work", worker=self.wid, epoch=epoch)
        self._apply(r)
        done = 0
        for task in r.get("tasks", ()):
            if task.get("kind") == "rescale":
                if self._execute_rescale(task):
                    done += 1
            elif self._execute_task(task, epoch):
                done += 1
        return done

    def _execute_rescale(self, task: dict) -> bool:
        """Worker half of the cross-worker rescale: rewrite the owned old
        buckets at the pinned snapshot (merged rows, clustered by new
        bucket id), ship the new-layout CommitMessages under the task's
        fence epoch. The coordinator commits once every old bucket is
        covered; a kill before the ship just re-queues these buckets on
        whoever inherits them."""
        from ..resilience.faults import crash_point
        from ..table.rescale import rescale_messages

        _, msgs, _ = rescale_messages(
            self.table,
            int(task["new_buckets"]),
            buckets=[int(b) for b in task["buckets"]],
            snapshot_id=task.get("snapshot"),
        )
        crash_point("rescale:before-ship")
        r = self.conn.call(
            "ship_commit",
            worker=self.wid,
            epoch=int(task["epoch"]),
            kind="rescale",
            buckets=[int(b) for b in task["buckets"]],
            messages=[m.to_dict() for m in msgs],
        )
        self._apply(r)
        return not r.get("stale")

    def _execute_task(self, task: dict, epoch: int) -> bool:
        """Worker half of the cluster compaction drain: rewrite through the
        local mesh engine, ship the CommitMessage — the coordinator commits
        (or abandons on conflict)."""
        from ..resilience.faults import crash_point
        from ..table.write import TableWrite

        t = self.table.copy(
            {
                "write-only": "false",
                "num-sorted-run.compaction-trigger": str(max(int(task.get("trigger", 3)) - 1, 1)),
            }
        )
        tw = TableWrite(t)
        try:
            tw._writer(tuple(task["partition"]), int(task["bucket"]))
            crash_point("cluster:compact-executing")
            tw.compact(full=bool(task["deep"]))
            msgs = [m for m in tw.prepare_commit() if not m.is_empty()]
        finally:
            tw.close()
        r = self.conn.call(
            "ship_commit",
            worker=self.wid,
            epoch=epoch,
            kind="compact",
            task_id=task["task_id"],
            messages=[m.to_dict() for m in msgs],
        )
        return r.get("sid") is not None

    # ---- loops ----------------------------------------------------------
    def run_serve(self) -> None:
        """Serve-only loop (distributed SQL workers): register, heartbeat,
        answer get_batch / subscribe / join_part / scan_frag until told to
        stop. No ingest — the table is whatever the store already holds."""
        self.register()
        self.start_heartbeats()
        while not self._stop.wait(0.2):
            if self._retire_flag:
                self.retire()
                break

    def run_soak(self) -> None:
        self.register()
        self.start_heartbeats()
        while not self._stop.is_set():
            try:
                if self._retire_flag:
                    self.retire()
                    break
                self.ingest_round()
                self.poll_and_compact()
            except ConnectionError:
                break  # coordinator gone: drain
            except Exception:
                # a lost CAS race surfaced as an error response, an injected
                # fault, etc. — survivable, re-observe and continue
                time.sleep(0.05)

    def retire(self) -> None:
        """Planned scale-in drain: called BETWEEN rounds, so every shipped
        round is settled and nothing is in flight — the retire RPC hands the
        range off through the reassignment machinery (a death without the
        timeout) and this process exits clean. A kill at the crash point
        degrades to exactly the missed-heartbeat path: same handoff, later."""
        from ..resilience.faults import crash_point

        crash_point("handoff:before-retire")
        try:
            self.conn.call("retire", worker=self.wid)
        except Exception:  # noqa: BLE001 — coordinator gone: drain anyway
            pass
        self.retired = True
        self._stop.set()

    def wait_go(self, timeout_s: float = 120.0) -> None:
        deadline = time.monotonic() + timeout_s
        while not self._go and time.monotonic() < deadline and not self._stop.is_set():
            time.sleep(0.05)

    def barrier(self, name: str, expected: int, timeout_s: float = 300.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            r = self.conn.call("barrier", worker=self.wid, name=name, expected=expected)
            if r.get("released"):
                return
            time.sleep(0.05)
        raise TimeoutError(f"barrier {name} not released")

    def close(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=10.0)
            self._hb_thread = None
        if self.server is not None:
            self.server.close()
            self.server = None
        if self.journal is not None:
            self.journal.close()
        self.conn.close()


# ---------------------------------------------------------------------------
# routed client: get_batch / subscribe / join partitions across workers
# ---------------------------------------------------------------------------
class ClusterClient:
    """Client-side routing over the coordinator's bucket->worker table.

    * get_batch: probe keys bucketize with the table's own hash, each
      owner-worker serves its group in one vectorized probe, results
      reassemble in probe order — the PR 13 serving path, now spanning
      worker processes.
    * subscribe: one filtered subscription per owning worker; each worker
      fans only the rows of the requested buckets (the PR 14 follow-up).
    * join partitions: `partition_executor()` returns the seam ops.join
      installs — JSPIM partition i routes to the worker owning bucket
      (i % num_buckets), so the skew split spans workers."""

    def __init__(self, table, coord_host: str, coord_port: int):
        self.table = table
        self.num_buckets = max(table.store.options.bucket, 1)
        self._coord = _RpcConn(coord_host, coord_port)
        self._conns: dict[int, _RpcConn] = {}
        self._route: dict[int, int] = {}
        self._addrs: dict[int, tuple[str, int]] = {}
        self._replicas: dict[int, list[int]] = {}
        self._route_lock = threading.Lock()
        self.route_epoch = 0
        self._route_dirty = False
        self._rr = 0
        self.refresh_route()

    def refresh_route(self) -> None:
        r = self._coord.call("route")
        route: dict[int, int] = {}
        addrs: dict[int, tuple[str, int]] = {}
        for wid_s, info in r["workers"].items():
            wid = int(wid_s)
            if info.get("port") is None:
                continue
            addrs[wid] = (info["host"], info["port"])
            for b in info["buckets"]:
                route[int(b)] = wid
        replicas = {
            int(b): [int(w) for w in wids if int(w) in addrs]
            for b, wids in (r.get("replicas") or {}).items()
        }
        self._route, self._addrs = route, addrs
        self._replicas = {b: ws for b, ws in replicas.items() if ws}
        self.num_buckets = int(r.get("num_buckets", self.num_buckets))
        with self._route_lock:
            e = int(r.get("route_epoch", 0))
            if e > self.route_epoch:
                self.route_epoch = e
            self._route_dirty = False
        for wid in list(self._conns):
            if wid not in addrs:
                self._conns.pop(wid).close()

    def note_route_epoch(self, epoch: int) -> None:
        """Push-based invalidation sink: every RPC reply (coordinator or
        worker serving plane) carries the route epoch; a bump marks the
        cached route dirty, and the next routing decision refreshes —
        clients learn about rescales/reassignments/replica changes without
        waiting for a rejected call."""
        with self._route_lock:
            if epoch > self.route_epoch:
                self.route_epoch = epoch
                self._route_dirty = True

    def _maybe_refresh(self) -> None:
        with self._route_lock:
            dirty = self._route_dirty
        if dirty:
            self.refresh_route()

    def _call(self, wid: int, method: str, **kw) -> dict:
        """Worker RPC with the route-epoch sniff on the reply."""
        r = self._conn(wid).call(method, **kw)
        e = r.get("route_epoch")
        if e is not None:
            self.note_route_epoch(int(e))
        return r

    def _conn(self, wid: int) -> _RpcConn:
        conn = self._conns.get(wid)
        if conn is None:
            conn = self._conns[wid] = _RpcConn(*self.addr_of(wid))
        return conn

    def replicas_of(self, bucket: int) -> list[int]:
        """Live replica owners of a bucket (primaries excluded) — the
        gateway's replica-first hedge pool."""
        self._maybe_refresh()
        return [w for w in self._replicas.get(int(bucket), ()) if w in self._addrs]

    def serving_owner_of(self, bucket: int) -> int:
        """Read routing: round-robin over the primary plus every live
        replica (a hot bucket's gets spread across its owner set); writes
        and compaction stay primary-only, so this is only ever used on the
        serving plane where any owner answers bit-identically off shared
        FS."""
        primary = self.owner_of(bucket)
        reps = [w for w in self._replicas.get(int(bucket), ()) if w != primary and w in self._addrs]
        if not reps:
            return primary
        ring = [primary, *reps]
        with self._route_lock:
            self._rr += 1
            pick = ring[self._rr % len(ring)]
        if pick != primary:
            from ..metrics import cluster_metrics

            cluster_metrics().counter("replica_reads").inc()
        return pick

    def owner_of(self, bucket: int) -> int:
        """The worker serving a bucket's reads. Every consumer (routed
        gets, scan fragments, subscribe fan-in, join partitions) reads the
        shared filesystem, so a bucket whose owner died and has not
        re-registered falls back to any live worker — bit-identical answer,
        no window where a respawn surfaces as a raw KeyError. With nothing
        live at all the escape is ConnectionError, which every dispatch
        failover loop already absorbs."""
        self._maybe_refresh()
        if bucket not in self._route:
            self.refresh_route()
        wid = self._route.get(bucket)
        if wid is not None:
            return wid
        live = sorted(self._addrs)
        if live:
            return live[bucket % len(live)]
        raise ConnectionError(f"no live worker serves bucket {bucket}")

    def drop_conn(self, wid: int) -> None:
        """Forget a worker's cached connection (the failover path: the next
        fragment for its buckets reconnects through a refreshed route)."""
        conn = self._conns.pop(wid, None)
        if conn is not None:
            conn.close()

    def live_workers(self) -> list[int]:
        """Worker ids with a serving address under the current route — the
        gateway's hedge-secondary candidate pool (any live worker serves
        get_batch/scan_frag from the shared filesystem, owner or not)."""
        return sorted(self._addrs)

    def addr_of(self, wid: int) -> "tuple[str, int]":
        """A worker's serving address. A wid the route advertised a moment
        ago can vanish under a concurrent refresh (the respawn window) —
        that is a dead route, ConnectionError, never a KeyError escaping
        through a dispatch path that only absorbs connection-grain faults."""
        try:
            return self._addrs[wid]
        except KeyError:
            raise ConnectionError(f"worker {wid} has no serving address") from None

    # ---- distributed SQL scan fragments (ISSUE 16) ----------------------
    def scan_frag(self, wid: int, frag: dict, busy_wait_s: float = 10.0) -> dict:
        """Execute one wire-encoded scan fragment on worker `wid`, absorbing
        typed-BUSY sheds with the server-advertised retry_after backoff.
        Raises ConnectionError/RuntimeError like every other worker call —
        the planner's failover loop owns re-dispatch."""
        deadline = time.monotonic() + busy_wait_s
        while True:
            r = self._call(wid, "scan_frag", frag=frag)
            if not r.get("busy"):
                return r["partial"]
            if time.monotonic() >= deadline:
                raise TimeoutError(f"worker {wid} still BUSY after {busy_wait_s}s")
            time.sleep(float(r.get("retry_after_ms", 50)) / 1000.0)

    # ---- shuffle exchange (ISSUE 20) ------------------------------------
    def exchange_combine(
        self,
        wid: int,
        qid: str,
        rng: int,
        expect: list,
        group_cols,
        kern,
        engine: str,
        code_domain: bool,
        projection,
        busy_wait_s: float = 10.0,
    ) -> "tuple[dict | None, list]":
        """Ask range owner `wid` to fold the expected parts of range `rng`
        into one reduced partial. Returns (wire partial, []) on success or
        (None, missing srcs) when the owner's inbound buffer has gaps —
        the coordinator reships those and retries. BUSY absorbs with the
        advertised backoff like scan_frag."""
        deadline = time.monotonic() + busy_wait_s
        while True:
            r = self._call(
                wid,
                "exchange_combine",
                qid=qid,
                rng=int(rng),
                expect=list(expect),
                group_cols=list(group_cols),
                kern=[list(k) for k in kern],
                engine=engine,
                code_domain=bool(code_domain),
                projection=None if projection is None else list(projection),
            )
            if r.get("busy"):
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"worker {wid} still BUSY after {busy_wait_s}s")
                time.sleep(float(r.get("retry_after_ms", 50)) / 1000.0)
                continue
            if r.get("missing") is not None:
                return None, list(r["missing"])
            return r["partial"], []

    def exchange_reship(self, wid: int, qid: str, rng: int, src: str, host: str, port: int) -> bool:
        """Ask source worker `wid` to re-send its buffered part for
        (qid, rng, src) to the range's current owner at host:port. False on
        any failure (source dead, buffer gone, delivery failed) — the
        caller's escalation (re-execute the fragment) is uniform."""
        try:
            r = self._call(
                wid, "exchange_reship", qid=qid, rng=int(rng), src=src, host=host, port=int(port)
            )
        except (ConnectionError, OSError, TimeoutError, RuntimeError):
            self.drop_conn(wid)
            return False
        if r.get("busy"):
            return False
        return bool(r.get("shipped"))

    def exchange_close(self, qid: str, wids) -> None:
        """Best-effort buffer release on every worker a shuffle touched;
        the worker-side TTL GC covers whatever this misses."""
        for wid in wids:
            try:
                self._call(wid, "exchange_close", qid=qid)
            except Exception:  # noqa: BLE001 — cleanup must never fail a query
                pass

    # ---- batched gets ---------------------------------------------------
    def get_batch(self, keys, partition: tuple = ()) -> list:
        """list[tuple | None] aligned with `keys`, each group served by the
        worker owning its bucket."""
        from ..data.batch import ColumnBatch
        from ..table.bucket import bucket_ids

        store = self.table.store
        ks = [k if isinstance(k, tuple) else (k,) for k in keys]
        key_schema = store.value_schema.project(store.key_names)
        probe = ColumnBatch.from_pydict(
            key_schema,
            {name: [k[i] for k in ks] for i, name in enumerate(store.key_names)},
        )
        buckets = bucket_ids(probe, self.table.schema.bucket_keys, self.num_buckets)
        out: list = [None] * len(ks)
        by_wid: dict[int, list[int]] = {}
        for i, b in enumerate(buckets.tolist()):
            by_wid.setdefault(self.serving_owner_of(int(b)), []).append(i)
        for wid, idxs in by_wid.items():
            try:
                rows = self._call(
                    wid,
                    "get_batch",
                    keys=[list(ks[i]) for i in idxs],
                    partition=list(partition),
                )["rows"]
            except ConnectionError:
                # the picked owner (typically a replica) died mid-read: one
                # failover pass through the refreshed primaries — a second
                # failure escapes like any other dead route
                self.drop_conn(wid)
                self.refresh_route()
                retry: dict[int, list[int]] = {}
                for i in idxs:
                    retry.setdefault(self.owner_of(int(buckets[i])), []).append(i)
                for w2, idxs2 in retry.items():
                    rows2 = self._call(
                        w2,
                        "get_batch",
                        keys=[list(ks[i]) for i in idxs2],
                        partition=list(partition),
                    )["rows"]
                    for i, row in zip(idxs2, rows2):
                        out[i] = None if row is None else tuple(row)
                continue
            for i, row in zip(idxs, rows):
                out[i] = None if row is None else tuple(row)
        return out

    # ---- routed subscriptions -------------------------------------------
    def subscribe(self, buckets: "list[int] | None" = None, from_snapshot: int | None = None):
        """[(wid, handle)] per owning worker; each handle's poll() returns
        {rows, snapshot_id, checkpoint} filtered to that worker's share of
        `buckets` (all buckets when None)."""
        self._maybe_refresh()
        want = list(range(self.num_buckets)) if buckets is None else [int(b) for b in buckets]
        by_wid: dict[int, list[int]] = {}
        for b in want:
            by_wid.setdefault(self.serving_owner_of(b), []).append(b)
        handles = []
        for wid, bs in by_wid.items():
            conn = self._conn(wid)
            sub_id = conn.call(
                "subscribe_open", buckets=bs, from_snapshot=from_snapshot
            )["sub_id"]
            handles.append((wid, _RoutedSubscription(conn, sub_id)))
        return handles

    # ---- distributed join partitions ------------------------------------
    def partition_executor(self):
        """The ops.join.partition_executor seam: partition i runs on the
        worker owning bucket (i % num_buckets)."""

        def run(parts):
            out = []
            for i, (ll, rl, algorithm, engine) in enumerate(parts):
                wid = self.owner_of(i % self.num_buckets)
                r = self._call(
                    wid,
                    "join_part",
                    ll=_b64(np.asarray(ll, dtype=np.uint32)),
                    rl=_b64(np.asarray(rl, dtype=np.uint32)),
                    algorithm=algorithm,
                    engine=engine,
                )
                out.append((_unb64(r["lt"]), _unb64(r["rt"])))
            return out

        return run

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._coord.close()


class _RoutedSubscription:
    def __init__(self, conn: _RpcConn, sub_id: str):
        self._conn = conn
        self.sub_id = sub_id

    def poll(self, timeout_ms: int = 1000) -> dict:
        return self._conn.call("subscribe_poll", sub_id=self.sub_id, timeout_ms=timeout_ms)

    def close(self, delete_consumer: bool = False) -> None:
        try:
            self._conn.call("subscribe_close", sub_id=self.sub_id, delete_consumer=delete_consumer)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# supervisor: spawn/kill/respawn workers, run the coordinator, verify
# ---------------------------------------------------------------------------
class ClusterSupervisor:
    """The PR 9 supervisor shape around a live coordinator: worker OS
    processes are spawned (crash-point armed through the environment),
    SIGKILLed on a seeded timer and at scripted points (including
    mid-compaction), respawned and journal-recovered; the coordinator
    reassigns orphaned bucket ranges on missed heartbeats. End-of-soak
    verification is the proc-soak oracle verbatim: fold of landed rounds ==
    final scan, total_record_count == unique keys, zero leaked files after
    the threshold-0 sweep — plus the cluster's own gate: sampled read-amp
    p99 never passed the adaptive ceiling."""

    def __init__(self, base_dir: str, cfg: ClusterConfig | None = None):
        self.cfg = cfg or ClusterConfig()
        self.base_dir = str(base_dir)
        self.table_root = os.path.join(self.base_dir, "cluster_table")
        self.run_dir = os.path.join(self.base_dir, "cluster_run")
        self.stop_file = os.path.join(self.run_dir, "stop")
        self.coordinator: ClusterCoordinator | None = None
        self.errors: list[str] = []
        self.inconsistencies: list[dict] = []
        self.read_amp_samples: list[float] = []
        self.counts = {
            "procs_spawned": 0,
            "procs_killed": 0,
            "procs_respawned": 0,
            "worker_errors": 0,
            "sweeps_during_soak": 0,
            "workers_admitted": 0,
            "workers_retired": 0,
            "rescales_requested": 0,
        }
        self._kill_cursor = 0
        self._incarnations: dict[tuple, int] = {}
        self._retiring_wids: set[int] = set()
        self._spawned_wids: set[int] = set()

    # ---- setup ---------------------------------------------------------
    def _table_options(self) -> dict:
        cfg = self.cfg
        opts = {
            "bucket": str(cfg.buckets),
            "write-only": "true",  # compaction belongs to the cluster service
            "merge.engine": "mesh",
            "write-buffer-rows": str(max(cfg.round_rows, 64)),
            "commit.max-retries": "30",
            "commit.retry-backoff": "2 ms",
            "cluster.workers": str(cfg.workers),
            "cluster.devices-per-worker": str(cfg.devices_per_worker),
            "compaction.adaptive.read-amp-ceiling": str(cfg.read_amp_ceiling),
            "compaction.adaptive.interval": "300 ms",
            "compaction.adaptive.max-buckets-per-round": "2",
        }
        opts.update(cfg.table_options)
        return opts

    def setup(self) -> None:
        from ..core.schema import SchemaManager
        from ..fs import get_file_io

        os.makedirs(self.run_dir, exist_ok=True)
        io = get_file_io(self.table_root)
        SchemaManager(io, self.table_root).create_table(
            SCHEMA, primary_keys=["k"], options=self._table_options()
        )

    def _fresh_table(self):
        from ..table import load_table

        return load_table(self.table_root, commit_user="cluster-supervisor")

    # ---- children ------------------------------------------------------
    def _child_env(self, crash_spec: str | None, devices: int) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        flags = " ".join(
            f for f in flags.split() if not f.startswith("--xla_force_host_platform_device_count")
        )
        env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={devices}").strip()
        env["PAIMON_TPU_CLUSTER_ROLE"] = "worker"
        env.pop("PAIMON_TPU_CRASH_POINT", None)
        if crash_spec:
            env["PAIMON_TPU_CRASH_POINT"] = crash_spec
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        return env

    def _spawn_worker(self, wid: int) -> subprocess.Popen:
        from ..metrics import soak_metrics

        cfg = self.cfg
        crash_spec = None
        if self._kill_cursor < len(cfg.scripted_kills):
            crash_spec = cfg.scripted_kills[self._kill_cursor]
            self._kill_cursor += 1
        inc = self._incarnations.get(("w", wid), 0)
        self._incarnations[("w", wid)] = inc + 1
        self._spawned_wids.add(wid)
        log = open(os.path.join(self.run_dir, f"worker-{wid}.{inc}.log"), "wb")
        cmd = [
            sys.executable, "-m", "paimon_tpu.service.cluster", "worker",
            "--table", self.table_root,
            "--wid", str(wid),
            "--coordinator", f"{self.coordinator.host}:{self.coordinator.port}",
            "--journal", os.path.join(self.run_dir, f"journal-{wid}.jsonl"),
            "--incarnation", str(inc),
            "--seed", str(cfg.seed),
            "--round-rows", str(cfg.round_rows),
            "--devices", str(cfg.devices_per_worker),
            "--admit-timeout", str(cfg.admit_timeout_s),
            "--heartbeat-interval", str(cfg.heartbeat_interval_s),
        ]
        if not cfg.serve:
            cmd.append("--no-serve")
        p = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT,
            env=self._child_env(crash_spec, cfg.devices_per_worker),
        )
        log.close()
        self.counts["procs_spawned"] += 1
        soak_metrics().counter("procs_spawned").inc()
        return p

    def _spawn_reader(self, rid: int) -> subprocess.Popen:
        inc = self._incarnations.get(("r", rid), 0)
        self._incarnations[("r", rid)] = inc + 1
        log = open(os.path.join(self.run_dir, f"reader-{rid}.{inc}.log"), "wb")
        cmd = [
            sys.executable, "-m", "paimon_tpu.service.cluster", "reader",
            "--table", self.table_root,
            "--rid", str(rid),
            "--log", os.path.join(self.run_dir, f"reads-{rid}.jsonl"),
            "--stop-file", self.stop_file,
        ]
        p = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=self._child_env(None, 1)
        )
        log.close()
        self.counts["procs_spawned"] += 1
        return p

    def _elastic_event(self, act: str, arg: "int | None", workers: dict) -> None:
        """One scripted elastic action against the live fleet: a rescale
        (coordinator-driven, under load), a worker admit (fresh wid beyond
        the home split — the register steal path plans its range handoff),
        or a retire (coordinator drain flag; the clean rc=0 exit is removed
        from the fleet instead of respawned)."""
        if act == "rescale":
            new_n = arg if arg else self.coordinator.num_buckets * 2
            r = self.coordinator.start_rescale(new_n)
            if r.get("started"):
                self.counts["rescales_requested"] += 1
        elif act == "admit":
            wid = (max(workers) + 1) if workers else self.cfg.workers
            workers[wid] = self._spawn_worker(wid)
            self.counts["workers_admitted"] += 1
        elif act == "retire":
            live = [
                w
                for w in sorted(workers)
                if workers[w].poll() is None and w not in self._retiring_wids
            ]
            if len(live) > 1:  # never retire the last worker
                wid = live[-1]  # highest wid: the admitted joiner when present
                self.coordinator.request_retire(wid)
                self._retiring_wids.add(wid)
        else:
            raise ValueError(f"unknown elastic action {act!r}")

    def _reap(self, role: str, idx: int, rc: int) -> None:
        from ..metrics import soak_metrics
        from ..resilience.faults import KILL_EXIT_CODE

        if rc == KILL_EXIT_CODE or rc < 0:
            self.counts["procs_killed"] += 1
            soak_metrics().counter("procs_killed").inc()
        elif rc != 0:
            self.counts["worker_errors"] += 1
            inc = self._incarnations.get((role[0], idx), 1) - 1
            log = os.path.join(self.run_dir, f"{role}-{idx}.{inc}.log")
            tail = ""
            if os.path.exists(log):
                with open(log, "rb") as f:
                    tail = f.read()[-2000:].decode(errors="replace")
            self.errors.append(f"{role} {idx} exited rc={rc}:\n{tail}")

    # ---- run -----------------------------------------------------------
    def run(self) -> dict:
        from ..metrics import compaction_metrics
        from ..resilience.orphan import remove_orphan_files

        cfg = self.cfg
        if not os.path.exists(self.table_root):
            self.setup()
        os.makedirs(self.run_dir, exist_ok=True)
        self.coordinator = ClusterCoordinator(self.table_root, cfg).start()
        rng = np.random.default_rng(cfg.seed * 31 + 17)
        t_start = time.monotonic()
        deadline = t_start + cfg.duration_s
        workers = {w: self._spawn_worker(w) for w in range(cfg.workers)}
        readers = {r: self._spawn_reader(r) for r in range(cfg.readers)}
        next_kill = (
            t_start + float(rng.uniform(0.5, 1.5)) * cfg.kill_period_s
            if cfg.kill_period_s > 0
            else float("inf")
        )
        next_sweep = t_start + cfg.sweep_period_s if cfg.sweep_period_s > 0 else float("inf")
        # scripted elastic plan: (absolute time, action, arg), time-ordered
        elastic = sorted(
            (
                t_start + float(ev[1]) * cfg.duration_s,
                str(ev[0]),
                int(ev[2]) if len(ev) > 2 and ev[2] is not None else None,
            )
            for ev in cfg.elastic
        )
        gauge = compaction_metrics().gauge("read_amplification_p99")
        while time.monotonic() < deadline:
            for wid, p in list(workers.items()):
                rc = p.poll()
                if rc is None:
                    continue
                if rc == 0 and wid in self._retiring_wids:
                    # planned retire completed its handoff: remove, never
                    # respawn — the range already moved to the survivors
                    del workers[wid]
                    self.counts["workers_retired"] += 1
                    continue
                self._reap("worker", wid, rc)
                workers[wid] = self._spawn_worker(wid)
                self.counts["procs_respawned"] += 1
            for rid, p in list(readers.items()):
                rc = p.poll()
                if rc is None:
                    continue
                self._reap("reader", rid, rc)
                readers[rid] = self._spawn_reader(rid)
                self.counts["procs_respawned"] += 1
            now = time.monotonic()
            while elastic and now >= elastic[0][0]:
                _, act, arg = elastic.pop(0)
                try:
                    self._elastic_event(act, arg, workers)
                except Exception:
                    self.errors.append(f"elastic {act} failed:\n{traceback.format_exc()}")
            if now >= next_kill and workers:
                wids = sorted(workers)
                victim = workers[wids[int(rng.integers(0, len(wids)))]]
                if victim.poll() is None:
                    victim.kill()
                next_kill = now + float(rng.uniform(0.5, 1.5)) * cfg.kill_period_s
            if now >= next_sweep:
                try:
                    remove_orphan_files(
                        self._fresh_table(), older_than_millis=cfg.sweep_older_than_ms
                    )
                    self.counts["sweeps_during_soak"] += 1
                except Exception:
                    self.errors.append(f"mid-soak sweep crashed:\n{traceback.format_exc()}")
                next_sweep = now + cfg.sweep_period_s
            v = getattr(gauge, "value", None)
            if v:
                self.read_amp_samples.append(float(v))
            time.sleep(0.15)
        # ---- drain -----------------------------------------------------
        self.coordinator.stop_event.set()  # workers see stop via heartbeat
        with open(self.stop_file, "w") as f:
            f.write("stop")  # readers poll the file
        drain_deadline = time.monotonic() + 90.0
        procs = [("worker", w, p) for w, p in workers.items()] + [
            ("reader", r, p) for r, p in readers.items()
        ]
        for role, idx, p in procs:
            timeout = max(1.0, drain_deadline - time.monotonic())
            try:
                rc = p.wait(timeout=timeout)
                if rc not in (0, None):
                    self._reap(role, idx, rc)
            except subprocess.TimeoutExpired:
                self.errors.append(f"{role} {idx} failed to drain; killed")
                p.kill()
                p.wait(timeout=30)
        wall_s = time.monotonic() - t_start
        self.coordinator.close()
        return self._verify(wall_s)

    # ---- verification --------------------------------------------------
    def _verify(self, wall_s: float) -> dict:
        from .oracle import fold_landed_rounds, read_client_logs, verify_table_state

        table = self._fresh_table()
        journal_wids = sorted(self._spawned_wids) or list(range(self.cfg.workers))
        landed, stats = fold_landed_rounds(
            table.store,
            {
                f"{ClusterCoordinator.USER_PREFIX}{wid}": os.path.join(
                    self.run_dir, f"journal-{wid}.jsonl"
                )
                for wid in journal_wids
            },
            user_prefix=ClusterCoordinator.USER_PREFIX,
            inconsistencies=self.inconsistencies,
        )
        expected: dict = {}
        for sid in sorted(landed):
            expected.update(landed[sid])
        state = verify_table_state(
            table,
            expected,
            self.table_root,
            self.errors,
            self.inconsistencies,
            force_writable=True,  # lift write-only=true for the final compact
        )
        reads = read_client_logs(
            [os.path.join(self.run_dir, f"reads-{rid}.jsonl") for rid in range(self.cfg.readers)]
        )
        if stats["double_applied"]:
            self.inconsistencies.append({"kind": "double-applied", "rounds": stats["double_applied"]})
        read_amp_max = max(self.read_amp_samples) if self.read_amp_samples else None
        consistent = (
            not self.errors
            and not self.inconsistencies
            and state["lost_rows"] == 0
            and state["duplicated_rows"] == 0
            and state["wrong_values"] == 0
            and reads["read_errors"] == 0
            and state["record_count_matches"]
            and len(state["leaked_files"]) == 0
            and (read_amp_max is None or read_amp_max <= self.cfg.read_amp_ceiling)
        )
        from ..metrics import cluster_metrics

        g = cluster_metrics()
        cluster_counts = {
            k: g.counter(k).count
            for k in (
                "workers_registered",
                "rounds_committed",
                "commits_rejected_stale",
                "reassignments",
                "compact_tasks",
                "compact_commits",
                "compact_conflicts",
                "admit_denied",
                "charges_released",
                "rescales",
                "rescale_aborts",
                "handoffs",
                "replica_reads",
            )
        }
        return {
            "wall_s": round(wall_s, 2),
            "consistent": consistent,
            "final_buckets": table.store.options.bucket,
            "accepted_commits": len(landed),
            "expected_unique_keys": len(expected),
            "final_rows": state["final_rows"],
            "total_record_count": state["total_record_count"],
            "lost_rows": state["lost_rows"],
            "duplicated_rows": state["duplicated_rows"],
            "wrong_values": state["wrong_values"],
            "commits_per_sec": round(len(landed) / wall_s, 2) if wall_s > 0 else None,
            "read_amp_p99_max": read_amp_max,
            "read_amp_ceiling": self.cfg.read_amp_ceiling,
            **stats,
            **self.counts,
            **reads,
            "cluster": cluster_counts,
            "orphans_removed": state["orphans_removed"],
            "leaked_file_count": len(state["leaked_files"]),
            "leaked_files": state["leaked_files"][:10],
            "inconsistencies": self.inconsistencies[:10],
            "errors": self.errors[:5],
        }


def run_cluster_soak(base_dir: str, cfg: ClusterConfig | None = None) -> dict:
    """Create a fresh cluster table under base_dir, run the supervisor
    (coordinator + worker/reader OS processes + kills), return the report."""
    return ClusterSupervisor(base_dir, cfg).run()


# ---------------------------------------------------------------------------
# worker child process
# ---------------------------------------------------------------------------
def worker_main(args) -> int:
    import jax

    from ..parallel import distributed
    from ..table import load_table

    if args.table.startswith(("fail:", "fail-s3", "latency:", "traceable:", "chaos:")):
        # test-harness schemes register on import (the chaos scheme also
        # applies PAIMON_TPU_CHAOS, so this worker inherits the store shape)
        from ..fs import testing as _testing  # noqa: F401
    if args.rtt_read_ms or args.rtt_write_ms:
        from ..fs.testing import LatencyFileIO

        LatencyFileIO.configure(read_ms=args.rtt_read_ms, write_ms=args.rtt_write_ms)
    # the worker startup path runs through the multi-host module —
    # single-process fallback here, the real jax.distributed join when a
    # pod topology is configured; the mesh it returns is the same one the
    # mesh executor will span (parallel.mesh.make_mesh over jax.devices())
    distributed.init_worker_runtime()
    assert not distributed.is_commit_coordinator(), "workers never commit"
    if args.devices:
        assert len(jax.devices()) == args.devices, (len(jax.devices()), args.devices)
    host, port = args.coordinator.rsplit(":", 1)
    table = load_table(args.table, commit_user=f"{ClusterCoordinator.USER_PREFIX}{args.wid}")
    agent = ClusterWorkerAgent(
        args.wid,
        table,
        host,
        int(port),
        journal_path=args.journal,
        incarnation=args.incarnation,
        serve=args.serve,
        round_rows=args.round_rows,
        admit_timeout_s=args.admit_timeout,
        heartbeat_interval_s=args.heartbeat_interval,
        seed=args.seed,
    )
    try:
        if args.mode == "soak":
            agent.run_soak()
        elif args.mode == "serve":
            agent.run_serve()
        else:
            _run_bench_worker(agent, args)
    finally:
        agent.close()
    return 0


def _run_bench_worker(agent: "ClusterWorkerAgent", args) -> None:
    """Bench mode: deterministic per-bucket rounds (independent of worker
    count — the single-process oracle writes the identical rows), a barrier
    so nobody's timed merge-read sees a moving table, then cold merge-read
    passes over the owned shard, each pass asserting a stable digest."""
    import hashlib

    from ..utils.cache import data_file_cache

    from ..data.batch import ColumnBatch
    from ..table.write import TableWrite

    agent.register()
    agent.start_heartbeats()
    agent.wait_go()
    pools = bucket_key_pools(agent.num_buckets, 0, args.round_rows)
    epoch, owned = agent.assignment()

    # ONE long-lived TableWrite across rounds (the reference's streaming
    # writers survive checkpoints): per-round writer re-creation would
    # re-restore sequence state from manifests over the store RTT
    tw = TableWrite(agent.table)

    def ingest_round(r: int) -> int:
        ks: list[int] = []
        for b in owned:
            ks.extend(pools[b].tolist())
        vs = [float(r * 1000 + (k % 997)) for k in ks]
        tw.write(ColumnBatch.from_pydict(SCHEMA, {"k": ks, "v": vs}))
        msgs = tw.prepare_commit()
        resp = agent.conn.call(
            "ship_commit", worker=agent.wid, epoch=epoch, ident=r + 1,
            kind="append", messages=[m.to_dict() for m in msgs],
        )
        assert resp.get("sid") is not None, f"bench round {r} did not land: {resp}"
        return len(ks)

    def plan_owned():
        rb = agent.table.new_read_builder()
        return rb, [s for s in rb.new_scan().plan() if s.bucket in owned]

    def read_pass(planned=None):
        # plan once per phase, read many: the serving layer's refresh()
        # diff keeps plans cached exactly like this — re-planning every
        # pass would measure metadata RTT, not merge-read scaling
        data_file_cache().clear()  # cold data bytes every pass
        rb, splits = planned if planned is not None else plan_owned()
        out = rb.new_read().read_all(splits)
        ks = np.asarray(out.column("k").values)
        vs = np.asarray(out.column("v").values)
        order = np.argsort(ks)
        return out.num_rows, hashlib.sha256(ks[order].tobytes() + vs[order].tobytes()).hexdigest()

    # warm round 0 + one warm read: jit compiles (flush + merge kernels) and
    # the plan's manifest RTT stay out of the timed window — every worker
    # count pays them identically, the bench measures steady-state scaling
    ingest_round(0)
    read_pass()
    agent.barrier("warm", expected=args.expected_workers)
    t0 = time.perf_counter()
    ingested = sum(ingest_round(r) for r in range(1, args.rounds + 1))
    t_ingest = time.perf_counter()
    agent.barrier("ingest", expected=args.expected_workers)
    t_barrier = time.perf_counter()
    rows_read = 0
    digest = None
    planned = plan_owned()
    for _ in range(args.read_iters):
        n, d = read_pass(planned)
        assert digest is None or digest == d, "merge-read digest changed between passes"
        digest = d
        rows_read += n
    wall = time.perf_counter() - t0
    tw.close()
    agent.conn.call(
        "worker_done",
        worker=agent.wid,
        stats={
            "ingested": ingested,
            "rows_read": rows_read,
            "digest": digest,
            "buckets": list(owned),
            "wall_s": wall,
            "ingest_s": round(t_ingest - t0, 3),
            "barrier_s": round(t_barrier - t_ingest, 3),
            "read_s": round(wall - (t_barrier - t0), 3),
        },
    )


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def _worker_args(argv):
    import argparse

    ap = argparse.ArgumentParser(prog="cluster worker")
    ap.add_argument("--table", required=True)
    ap.add_argument("--wid", type=int, required=True)
    ap.add_argument("--coordinator", required=True, help="host:port")
    ap.add_argument("--journal", default=None)
    ap.add_argument("--incarnation", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--round-rows", type=int, default=256, dest="round_rows")
    ap.add_argument("--devices", type=int, default=0)
    ap.add_argument("--admit-timeout", type=float, default=30.0, dest="admit_timeout")
    ap.add_argument("--heartbeat-interval", type=float, default=0.5, dest="heartbeat_interval")
    ap.add_argument("--no-serve", action="store_false", dest="serve")
    ap.add_argument("--mode", choices=("soak", "bench", "serve"), default="soak")
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--read-iters", type=int, default=4, dest="read_iters")
    ap.add_argument("--expected-workers", type=int, default=1, dest="expected_workers")
    ap.add_argument("--rtt-read-ms", type=float, default=0.0, dest="rtt_read_ms")
    ap.add_argument("--rtt-write-ms", type=float, default=0.0, dest="rtt_write_ms")
    return ap.parse_args(argv)


def main(argv: list[str] | None = None) -> int:
    import argparse
    import tempfile

    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "worker":
        return worker_main(_worker_args(argv[1:]))
    if argv and argv[0] == "reader":
        from .proc_soak import _reader_args, reader_main

        return reader_main(_reader_args(argv[1:]))

    ap = argparse.ArgumentParser(description="paimon-tpu cluster soak (coordinator + workers)")
    ap.add_argument("base_dir", nargs="?", default=None)
    ap.add_argument("--duration", type=float, default=45.0)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--devices-per-worker", type=int, default=2)
    ap.add_argument("--readers", type=int, default=1)
    ap.add_argument("--buckets", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--scripted-kills",
        default=",".join(DEFAULT_CLUSTER_KILLS),
        help="comma-separated PAIMON_TPU_CRASH_POINT specs, one per worker spawn",
    )
    ap.add_argument("--kill-period", type=float, default=10.0)
    ap.add_argument("--sweep-period", type=float, default=15.0)
    ap.add_argument("--round-rows", type=int, default=256)
    ap.add_argument("--read-amp-ceiling", type=int, default=10)
    ap.add_argument("--min-kills", type=int, default=0)
    ap.add_argument("--no-compaction", action="store_false", dest="compaction")
    ap.add_argument(
        "--elastic-script",
        default="",
        help=(
            "comma-separated elastic events action[:arg]@frac, e.g. "
            "'rescale:8@0.3,admit@0.5,retire@0.7' — rescale to 8 buckets at "
            "30%% of the duration, admit a worker at 50%%, retire one at 70%%"
        ),
    )
    args = ap.parse_args(argv)
    elastic = []
    for spec in (s.strip() for s in args.elastic_script.split(",")):
        if not spec:
            continue
        head, frac = spec.rsplit("@", 1)
        act, _, arg = head.partition(":")
        elastic.append((act, float(frac), int(arg)) if arg else (act, float(frac)))
    base = args.base_dir or tempfile.mkdtemp(prefix="paimon_cluster_")
    cfg = ClusterConfig(
        workers=args.workers,
        devices_per_worker=args.devices_per_worker,
        buckets=args.buckets,
        duration_s=args.duration,
        seed=args.seed,
        readers=args.readers,
        round_rows=args.round_rows,
        read_amp_ceiling=args.read_amp_ceiling,
        scripted_kills=tuple(s for s in args.scripted_kills.split(",") if s.strip()),
        kill_period_s=args.kill_period,
        sweep_period_s=args.sweep_period,
        compaction=args.compaction,
        elastic=tuple(elastic),
    )
    report = run_cluster_soak(base, cfg)
    print(json.dumps(report, indent=2, default=str))
    ok = report["consistent"] and report["procs_killed"] >= args.min_kills
    if report["procs_killed"] < args.min_kills:
        print(
            f"FAIL: only {report['procs_killed']} kills survived (expected >= {args.min_kills})",
            file=sys.stderr,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
