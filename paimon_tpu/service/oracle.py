"""The one cross-plane consistency oracle every soak shares.

Four oracles grew up independently — the thread soak's serialized OracleLog
fold, the process soaks' journal intent/ack chain walk, the subscriber
fold==pinned-scan check, and the reachable-closure disk audit — and the
proc-soak and cluster supervisors each carried a near-verbatim copy of the
end-of-run verification. This module is the single home for all of them:

  OracleLog            serialized landed-commit log (thread-grain soaks)
  find_landed_append   snapshot-chain probe: did (user, identifier) land?
  fold_landed_rounds   journal ∩ snapshot-chain fold → {append sid: rows}
  sweep_and_audit      orphan sweep + independent disk walk vs closure
  scan_rows            pinned scan at a snapshot → {key: value}, row count
  compare_final        expected-vs-scanned → (lost, duplicated, wrong)
  final_full_compact   quiesced 3-retry full compaction before the scan
  read_client_logs     torn-tolerant reader/getter JSONL log fold
  verify_table_state   the whole end-of-run gate the supervisors share

The verdict every caller derives from these pieces is the same sentence:
the fold of landed rounds in snapshot-id order EQUALS the final scan, the
physical row count equals the unique-key count (a double-applied replay
cannot hide), and after the threshold-0 sweep the on-disk file set is
EXACTLY the reachable closure plus table metadata.
"""

from __future__ import annotations

import os
import threading
import traceback

__all__ = [
    "OracleLog",
    "find_landed_append",
    "fold_landed_rounds",
    "sweep_and_audit",
    "scan_rows",
    "compare_final",
    "final_full_compact",
    "read_client_logs",
    "verify_table_state",
]


class OracleLog:
    """Serialized log of landed commits: (append snapshot id -> rows).
    The single source of truth every concurrent read is verified against."""

    def __init__(self):
        self._cond = threading.Condition()
        self._events: dict[int, dict] = {}  # snapshot id -> {key: value}

    def record(self, snapshot_id: int, rows: dict) -> None:
        with self._cond:
            self._events[snapshot_id] = dict(rows)
            self._cond.notify_all()

    def covers(self, needed: set[int]) -> bool:
        with self._cond:
            return needed <= self._events.keys()

    def wait_covers(self, needed: set[int], timeout_s: float) -> bool:
        with self._cond:
            return self._cond.wait_for(lambda: needed <= self._events.keys(), timeout_s)

    def expected_at(self, snapshot_id: int) -> dict:
        """Fold of all recorded events with id <= snapshot_id, in id order —
        the exact row set a consistent read of that snapshot must return."""
        with self._cond:
            items = sorted((sid, rows) for sid, rows in self._events.items() if sid <= snapshot_id)
        out: dict = {}
        for _, rows in items:
            out.update(rows)
        return out

    def expected_final(self) -> dict:
        return self.expected_at(1 << 62)

    @property
    def commits(self) -> int:
        with self._cond:
            return len(self._events)

    @property
    def accepted_rows(self) -> int:
        with self._cond:
            return sum(len(r) for r in self._events.values())


def find_landed_append(store, user: str, identifier: int) -> int | None:
    """Did this (user, identifier) round's APPEND phase land? A commit that
    raised (conflict on its COMPACT half, retry exhaustion, an injected
    fault mid-protocol) may still have published rows — the snapshot chain,
    not the exception, is the truth the oracle must record."""
    from ..core.snapshot import CommitKind

    try:
        for snap in store.snapshot_manager.snapshots_of_user_with_identifier(user, identifier):
            if snap.commit_kind == CommitKind.APPEND:
                return snap.id
    except Exception:
        return None
    return None


def fold_landed_rounds(
    store,
    journals: dict[str, str],
    user_prefix: str,
    inconsistencies: list,
    decode_key=int,
) -> tuple[dict[int, dict], dict]:
    """One walk of the snapshot chain (the authority on what LANDED) plus
    the writers' intent/ack journals (the authority on what each round
    CONTAINED) → the landed map {append sid: rows} and the protocol
    bookkeeping counters. `journals` maps commit_user -> journal path;
    `user_prefix` filters the chain to this soak's writers; journal keys are
    JSON strings and are decoded back with `decode_key`.

    Two invariants are checked in passing: a (user, identifier) pair landing
    more than once is double-applied (recorded in stats and escalated by the
    caller), and a chain commit with no journaled intent violates the
    intent-fsync-before-commit protocol (appended to `inconsistencies`)."""
    from ..core.snapshot import CommitKind

    from .proc_soak import WriterJournal

    sm = store.snapshot_manager
    chain: dict[tuple, list[int]] = {}
    latest, earliest = sm.latest_snapshot_id(), sm.earliest_snapshot_id()
    if latest is not None and earliest is not None:
        for sid in range(earliest, latest + 1):
            if not sm.snapshot_exists(sid):
                continue
            snap = sm.snapshot(sid)
            if snap.commit_kind == CommitKind.APPEND and snap.commit_user.startswith(user_prefix):
                chain.setdefault((snap.commit_user, snap.commit_identifier), []).append(sid)
    landed: dict[int, dict] = {}
    stats = {
        "rounds_intended": 0,
        "rounds_landed": 0,
        "rounds_failed": 0,  # aborted AND verifiably absent from the chain
        "rounds_ack_lost": 0,  # landed with no journal ack (probe/chain resolved)
        "crash_recoveries": 0,
        "double_applied": [],
    }
    seen_pairs = set()
    for user, path in journals.items():
        events = WriterJournal.read(path)
        acked = {e["ident"] for e in events if e["t"] == "ack"}
        stats["crash_recoveries"] += sum(1 for e in events if e["t"] == "recovered")
        for e in events:
            if e["t"] != "intent":
                continue
            stats["rounds_intended"] += 1
            sids = chain.get((user, e["ident"]), [])
            seen_pairs.add((user, e["ident"]))
            if len(sids) > 1:
                stats["double_applied"].append({"user": user, "ident": e["ident"], "sids": sids})
            if sids:
                stats["rounds_landed"] += 1
                if e["ident"] not in acked:
                    stats["rounds_ack_lost"] += 1
                landed[sids[0]] = {decode_key(k): v for k, v in e["rows"].items()}
            else:
                stats["rounds_failed"] += 1
    # every soak APPEND snapshot must trace back to a journaled intent
    # (the intent fsync precedes the commit — an unjournaled commit is
    # a protocol violation)
    for (user, ident), sids in chain.items():
        if (user, ident) not in seen_pairs:
            inconsistencies.append(
                {"kind": "unjournaled-commit", "user": user, "ident": ident, "sids": sids}
            )
    return landed, stats


def sweep_and_audit(
    table, local_root: str, older_than_millis: int = 0, sweep: bool = True
) -> dict:
    """Orphan sweep (optional, threshold `older_than_millis`), then an
    INDEPENDENT disk walk of `local_root`: the surviving file set must be
    EXACTLY the reachable closure plus table metadata (snapshots/schemas/
    hints/markers). `sweep=False` audits without reclaiming — the
    seed-contrast runs use it to show what a sweep-less build leaks."""
    from ..resilience.orphan import reachable_files, remove_orphan_files

    removed = remove_orphan_files(table, older_than_millis=older_than_millis) if sweep else None
    closure = reachable_files(table)
    meta_names = set().union(*closure["meta"].values()) if closure["meta"] else set()
    index_names = set().union(*closure["index"].values()) if closure["index"] else set()
    data_names = {name for (_, name) in closure["data"]}
    leaked = []
    for dirpath, _dirs, files in os.walk(local_root):
        rel = os.path.relpath(dirpath, local_root)
        parts = [] if rel == "." else rel.split(os.sep)
        top = parts[0] if parts else ""
        for f in files:
            if top == "manifest":
                ok = f in meta_names
            elif top == "index":
                ok = f in index_names
            elif top in (
                "snapshot",
                "schema",
                "branch",
                "tag",
                "consumer",
                "service",
                "statistics",
                "changelog",
            ):
                ok = True  # metadata planes: hints, schema history, markers
            elif any(p.startswith("bucket-") for p in parts):
                ok = f in data_names
            else:
                ok = False
            if not ok:
                leaked.append(os.path.join(rel, f))
    return {
        "orphans_removed": len(removed) if removed is not None else None,
        "leaked_files": leaked,
    }


def scan_rows(table, sid: int) -> tuple[dict, int]:
    """Pinned scan at `sid` → ({key: value}, physical row count). Key is the
    first schema column; value is the second column for two-column schemas
    (the k/v soaks) or the tuple of the remaining columns otherwise (the
    mega matrix's wider shapes). A physical count above len(keys) is a
    duplicate-key finding the caller turns into `duplicated_rows`."""
    t = table.copy({"scan.snapshot-id": str(sid)})
    rb = t.new_read_builder()
    batch = rb.new_read().read_all(rb.new_scan().plan())
    rows = batch.to_pylist()
    got: dict = {}
    for row in rows:
        got[row[0]] = row[1] if len(row) == 2 else tuple(row[1:])
    return got, len(rows)


def compare_final(expected: dict, got: dict, physical_rows: int) -> tuple[int, int, int]:
    """(lost, duplicated, wrong): keys the scan is missing, keys present
    beyond the expected set (plus physical duplicates the dict collapsed),
    and keys whose value differs from the fold."""
    dup = physical_rows - len(got)
    lost = sum(1 for k in expected if k not in got)
    wrong = sum(1 for k in expected if k in got and got[k] != expected[k])
    dup += sum(1 for k in got if k not in expected)
    return lost, dup, wrong


def final_full_compact(table, attempts: int = 3, force_writable: bool = False) -> None:
    """Quiesced full compaction before the final scan (nothing else runs;
    retries cover stragglers). `force_writable` lifts a cluster table's
    write-only=true — the supervisor compacts after the workers are gone."""
    from ..core.commit import BATCH_COMMIT_IDENTIFIER
    from ..core.manifest import ManifestCommittable
    from ..table.write import TableWrite

    t = table.copy({"write-only": "false"}) if force_writable else table
    for _ in range(attempts):
        tw = TableWrite(t)
        try:
            tw.compact(full=True)
            msgs = tw.prepare_commit()
            if not msgs:
                return
            t.store.new_commit().commit(
                ManifestCommittable(BATCH_COMMIT_IDENTIFIER, messages=msgs)
            )
            return
        except Exception:
            continue
        finally:
            tw.close()


def read_client_logs(paths: list[str]) -> dict:
    """Fold reader/getter client JSONL logs (torn-tail tolerant): sum the
    'done' summaries, collect err/dup-keys samples, and count every logged
    error for clients drained by force before they wrote a summary."""
    from .proc_soak import WriterJournal

    out = {"reads_ok": 0, "read_errors": 0, "read_error_samples": []}
    for path in paths:
        if not os.path.exists(path):
            continue
        done = False
        events = WriterJournal.read(path)  # same torn-tolerant JSONL parse
        for e in events:
            if e.get("t") == "done":
                out["reads_ok"] += e["reads_ok"]
                out["read_errors"] += e["read_errors"]
                done = True
            elif e.get("t") in ("err", "dup-keys"):
                out["read_error_samples"].append(e)
        if not done:
            # client was drained by force: count its logged errors
            out["read_errors"] += sum(1 for e in events if e.get("t") in ("err", "dup-keys"))
    return out


def verify_table_state(
    table,
    expected: dict,
    local_root: str,
    errors: list,
    inconsistencies: list,
    *,
    sweep: bool = True,
    force_writable: bool = False,
) -> dict:
    """The shared end-of-run gate: full-compact, scan the latest snapshot,
    compare against the fold (`expected`), assert total_record_count ==
    unique keys, sweep-and-audit at threshold 0, then re-scan and assert the
    sweep removed nothing a reader can still see. Crashes land in `errors`,
    findings in `inconsistencies`; the caller folds the returned counters
    into its consistent verdict."""
    lost = dup = wrong = 0
    final_rows = total_record_count = None
    store = table.store
    try:
        final_full_compact(table, force_writable=force_writable)
        latest = store.snapshot_manager.latest_snapshot()
        if latest is not None:
            got, physical = scan_rows(table, latest.id)
            final_rows = physical
            lost, dup, wrong = compare_final(expected, got, physical)
            total_record_count = store.snapshot_manager.latest_snapshot().total_record_count
        elif expected:
            lost = len(expected)
    except Exception:
        errors.append(f"final verification crashed:\n{traceback.format_exc()}")
    audit = {"orphans_removed": None, "leaked_files": ["<audit crashed>"]}
    try:
        audit = sweep_and_audit(table, local_root, older_than_millis=0, sweep=sweep)
        if sweep and final_rows is not None:
            # the sweep must not have removed anything a reader can see
            latest = store.snapshot_manager.latest_snapshot()
            _, after = scan_rows(table, latest.id)
            if after != final_rows:
                inconsistencies.append(
                    {"kind": "sweep-removed-live-rows", "before": final_rows, "after": after}
                )
    except Exception:
        errors.append(f"orphan audit crashed:\n{traceback.format_exc()}")
    return {
        "lost_rows": lost,
        "duplicated_rows": dup,
        "wrong_values": wrong,
        "final_rows": final_rows,
        "total_record_count": total_record_count,
        "record_count_matches": (
            total_record_count is None or total_record_count == len(expected)
        ),
        "orphans_removed": audit["orphans_removed"],
        "leaked_files": audit["leaked_files"],
    }
