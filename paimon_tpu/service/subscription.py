"""Streaming CDC subscription service: decode-once changelog fan-out.

The delta-propagation pattern of read-optimized stores ("Fast Updates on
Read-Optimized Databases", PAPERS.md) applied at serving scale: ONE tailer
follows the snapshot chain and decodes each delta/changelog split exactly
once; the same decoded batches fan out to every live subscriber. The pieces
this ties together already exist in isolation — changelog production
(core/changelog.py), streaming scans (table/stream.py), durable consumer
offsets (table/consumer.py), CDC wire formats (table/cdc_format.py), and the
Flight server (service/flight.py). This module is the serving path that
makes them one system:

* **SubscriptionHub** — one per table (process-wide registry). A single
  tailer thread (``paimon-subtail-*``) follows the snapshot chain via
  ``StreamTableScan`` with blocking poll + exponential backoff (no busy
  loop), reads each new snapshot's delta/changelog splits ONCE through the
  PR 1 data-file cache, and fans the decoded ``ChangelogBatch`` out to every
  subscriber's bounded queue. Decode cost is therefore flat in the number of
  subscribers (``sub{decode_reuse_hits}`` counts the deliveries that reused
  a previously decoded batch; ``benchmarks/subscribe_bench.py`` pins
  ``decode{pages_decoded}`` flat in N).

* **Durable consumer ids** — every subscriber registers a consumer-id with
  ``ConsumerManager`` BEFORE reading anything, so snapshot expiry keeps
  every snapshot >= its position pinned while it lags. Progress advances
  at-least-once (the handed-out snapshot is recorded, exactly like
  ``StreamTableScan``'s at-least-once mode), and a heartbeat thread
  re-records each position every ``subscription.heartbeat-interval`` so
  ``consumer.expiration-time`` only collects genuinely abandoned readers
  (re-recording refreshes the consumer file's mtime).

* **Flow control riding the PR 8 admission machinery** — queued batches are
  accounted against a shared ``WriteBufferController`` byte budget
  (``subscription.buffer.max-memory``) and each queue is bounded by
  ``subscription.queue-depth``. A consumer that stays full past
  ``subscription.shed-timeout`` is SHED with the typed-BUSY protocol
  (``SubscriberShedError`` carrying its durable restart offset) — it never
  stalls the tailer or its peers, and it resumes losslessly from its
  consumer-id (at-least-once replay from the recorded position).

* **Catch-up replay** — a subscriber whose start position is behind the
  hub's live frontier replays the missing snapshots through its OWN
  ``StreamTableScan``; those reads hit the data-file cache the tailer (or a
  peer's catch-up) already populated, so late joiners do not multiply
  decode work either.

Surfaces: ``SubscriptionHub.for_table(t).subscribe(...)`` (in-process
iterator), ``FileStoreTable.subscribe(...)`` (convenience), the Flight
server's ``do_action("subscribe_poll")`` / ``do_get`` subscribe ticket
(service/flight.py) emitting Arrow rows or ``table/cdc_format.py`` wire
messages, and a subscriber OS-process CLI
(``python -m paimon_tpu.service.subscription``) used by the soak harness to
prove kill -9 + resume.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass

import numpy as np

from .shed import ShedError, ShedInfo

__all__ = [
    "ChangelogBatch",
    "SubscriberShedError",
    "Subscription",
    "SubscriptionHub",
    "fold_changelog",
]


class SubscriberShedError(ShedError):
    """The hub shed this subscriber with a typed BUSY: its queue stayed full
    (or the shared buffer budget stayed exhausted) past
    ``subscription.shed-timeout``. A serialization of service.shed.ShedInfo
    (kind="subscribe", restart_offset = the consumer-id's recorded durable
    position) — so the caller can resume losslessly with
    ``subscribe(consumer_id=...)``. The streaming twin of
    WriterBackpressureError / KvBusyError / FlightBusyError."""

    default_kind = "subscribe"

    def __init__(self, payload: "dict | ShedInfo"):
        super().__init__(payload, message=f"subscriber shed: {payload}")
        self.consumer_id = self.payload.get("consumer_id")
        self.next_snapshot = self.payload.get("next_snapshot")


@dataclass(frozen=True)
class ChangelogBatch:
    """One snapshot's decoded change stream. `data`/`kinds` are SHARED across
    subscribers (decode-once) — consumers must never mutate them (the read
    path is copy-on-filter throughout, same contract as the data-file
    cache)."""

    snapshot_id: int
    data: object  # ColumnBatch
    kinds: np.ndarray  # uint8 RowKind per row
    is_catchup: bool = False

    @property
    def num_rows(self) -> int:
        return self.data.num_rows

    def byte_size(self) -> int:
        return int(self.data.byte_size()) + int(self.kinds.nbytes)

    def events(self) -> list[tuple]:
        """[(kind short string, *row), ...] — the debugging/test view."""
        from ..types import RowKind

        return [
            (RowKind(int(k)).short_string, *row)
            for row, k in zip(self.data.to_pylist(), self.kinds.tolist())
        ]


def fold_changelog(state: dict, batch: ChangelogBatch, key_fields: list[str]) -> dict:
    """Fold one batch into a {key tuple: value row tuple} dict: +I/+U upsert,
    -D delete, -U ignored (always followed by its +U). The soak oracle and
    the subscriber-process journal verification both use this fold — at its
    checkpoint it must equal the pinned-snapshot scan."""
    from ..types import RowKind

    names = batch.data.schema.field_names
    key_idx = [names.index(k) for k in key_fields]
    for row, kind in zip(batch.data.to_pylist(), batch.kinds.tolist()):
        key = tuple(row[i] for i in key_idx)
        k = RowKind(int(kind))
        if k in (RowKind.INSERT, RowKind.UPDATE_AFTER):
            state[key] = tuple(row)
        elif k == RowKind.DELETE:
            state.pop(key, None)
    return state


class _SubscriberState:
    """Hub-internal per-consumer state: the bounded queue, shed latch, and
    position bookkeeping. `expected_next` = the next snapshot id this
    subscriber has NOT yet been handed; `progress` = the last handed-out
    snapshot (the at-least-once durable record value; -1 before the first)."""

    def __init__(self, consumer_id: str, start: int, catch_up_until: int):
        self.consumer_id = consumer_id
        self.start = start
        self.catch_up_until = catch_up_until
        self.cond = threading.Condition()
        self.queue: deque[ChangelogBatch] = deque()
        self.reserved_bytes = 0
        self.shed_payload: dict | None = None
        self.closed = False
        self.expected_next = start
        self.progress = -1  # last handed-out snapshot id
        self.queue_high_water = 0
        # pressure window: set when the queue first fills, cleared only once
        # the consumer drains to half depth (hysteresis). The shed clock runs
        # over the WINDOW, not per batch — a consumer slower than production
        # can free one slot per offer forever, and resetting the clock on
        # each slot would let it pace the tailer (stalling every peer)
        # indefinitely instead of being shed.
        self.pressure_since: float | None = None

    @property
    def durable_position(self) -> int:
        """What the consumer file should hold: the snapshot a resume must
        replay from. Before anything was handed out, the start position."""
        return self.progress if self.progress >= 0 else self.start

    def restart_offset(self) -> int:
        """First snapshot a shed subscriber still needs: the head of its
        unconsumed queue, else the next it was expecting."""
        with self.cond:
            if self.queue:
                return self.queue[0].snapshot_id
            return self.expected_next


class Subscription:
    """One consumer's live handle: an iterator of ChangelogBatch.

    ``poll(timeout)`` returns the next batch or None on timeout; raises
    SubscriberShedError once the hub shed this consumer (typed, carries the
    restart offset) and StopIteration-style None forever after close().
    Batches arrive in strict snapshot order; ``checkpoint`` is the next
    snapshot id not yet handed out (fold of everything received ==
    pinned-snapshot scan at checkpoint-1)."""

    def __init__(self, hub: "SubscriptionHub", st: _SubscriberState, scan):
        self._hub = hub
        self._st = st
        self._scan = scan  # private StreamTableScan for catch-up replay
        self._pending: tuple[int, list] | None = None  # (sid, splits) to retry
        self._read = hub.table.new_read_builder().new_read()

    @property
    def consumer_id(self) -> str:
        return self._st.consumer_id

    @property
    def checkpoint(self) -> int:
        return self._st.expected_next

    @property
    def is_shed(self) -> bool:
        return self._st.shed_payload is not None

    # ---- consuming -----------------------------------------------------
    def poll(self, timeout: float | None = None) -> ChangelogBatch | None:
        st = self._st
        if st.shed_payload is not None:
            raise SubscriberShedError(st.shed_payload)
        if st.closed:
            return None
        # catch-up phase: replay [start, catch_up_until) through the cache
        while st.expected_next < st.catch_up_until:
            batch = self._catchup_next()
            if batch is not None:
                self._handed(batch)
                return batch
            if st.expected_next >= st.catch_up_until:
                break  # only empty snapshots remained
        # live phase: the tailer feeds the bounded queue
        deadline = None if timeout is None else time.monotonic() + timeout
        with st.cond:
            while not st.queue:
                if st.shed_payload is not None:
                    raise SubscriberShedError(st.shed_payload)
                if st.closed:
                    return None
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                st.cond.wait(remaining if remaining is not None else 0.5)
            batch = st.queue.popleft()
            nbytes = batch.byte_size()
            st.reserved_bytes = max(0, st.reserved_bytes - nbytes)
            if st.pressure_since is not None and len(st.queue) <= self._hub.queue_depth // 2:
                st.pressure_since = None  # real headroom drained: pressure over
            st.cond.notify_all()
        if self._hub.controller is not None:
            self._hub.controller.release(nbytes)
        if batch.snapshot_id < st.expected_next:
            # defensive dedup: a replayed enqueue can never regress the fold
            return self.poll(timeout)
        self._handed(batch)
        return batch

    def _handed(self, batch: ChangelogBatch) -> None:
        st = self._st
        st.progress = batch.snapshot_id
        st.expected_next = batch.snapshot_id + 1

    def _catchup_next(self) -> ChangelogBatch | None:
        """Advance the private scan by one snapshot; None when that snapshot
        was empty (frontier still advanced) or nothing is available. A read
        failure keeps (sid, splits) pending so the next poll retries without
        losing the snapshot (the scan position already advanced)."""
        from ..utils.cache import data_file_cache

        st = self._st
        if self._pending is None:
            cached = self._hub._replay_get(st.expected_next)
            if cached is not None:
                # whole-batch reuse: the tailer (or an earlier catch-up)
                # already decoded AND merged this snapshot — skip planning
                # and reading entirely
                self._scan.restore(cached.snapshot_id + 1)
                if cached.num_rows == 0:
                    st.expected_next = min(cached.snapshot_id + 1, st.catch_up_until)
                    return None
                g = self._hub._metrics()
                g.counter("batches_fanned").inc()
                g.counter("rows_fanned").inc(cached.num_rows)
                g.counter("decode_reuse_hits").inc()
                return ChangelogBatch(cached.snapshot_id, cached.data, cached.kinds, is_catchup=True)
            splits = self._scan.plan()
            if splits is None:
                # chain shorter than catch_up_until (rolled back): go live
                st.expected_next = st.catch_up_until
                return None
            if not splits:
                st.expected_next = min(self._scan._next, st.catch_up_until)
                return None
            self._pending = (splits[0].snapshot_id, splits)
        sid, splits = self._pending
        cache = data_file_cache()
        reused = all(
            cache.contains_file(f.file_name) for s in splits for f in s.files
        )
        parts = [self._read.read_with_kinds(s) for s in splits]
        self._pending = None
        batch = _concat_parts(sid, parts, is_catchup=True)
        self._hub._replay_put(batch)  # the next catch-up reuses the merge too
        g = self._hub._metrics()
        g.counter("batches_fanned").inc()
        g.counter("rows_fanned").inc(batch.num_rows)
        if reused:
            g.counter("decode_reuse_hits").inc()
        if batch.num_rows == 0:
            st.expected_next = min(sid + 1, st.catch_up_until)
            return None
        return batch

    def __iter__(self):
        return self

    def __next__(self) -> ChangelogBatch:
        while True:
            b = self.poll(timeout=None)
            if b is not None:
                return b
            if self._st.closed:
                raise StopIteration

    # ---- lifecycle -----------------------------------------------------
    def close(self, delete_consumer: bool = False) -> None:
        """Detach from the hub. The consumer file is KEPT by default (the
        durable resume token); delete_consumer=True releases the expiry pin
        explicitly."""
        self._hub._detach(self._st, delete_consumer=delete_consumer)


def _concat_parts(sid: int, parts: list[tuple], is_catchup: bool = False) -> ChangelogBatch:
    from ..data.batch import concat_batches

    datas = [p[0] for p in parts]
    kinds = [p[1] for p in parts]
    data = datas[0] if len(datas) == 1 else concat_batches(datas)
    kind = kinds[0] if len(kinds) == 1 else np.concatenate(kinds)
    return ChangelogBatch(sid, data, kind, is_catchup=is_catchup)


class SubscriptionHub:
    """Subscription hub for one table: single tailer, N subscribers.

    Use ``SubscriptionHub.for_table(table)`` for the process-wide registry
    (the Flight server and colocated jobs share one tailer per table) or
    construct directly for a private hub. ``close()`` stops the tailer and
    heartbeat threads and detaches every subscriber."""

    _hubs: dict[str, "SubscriptionHub"] = {}
    _hubs_lock = threading.Lock()

    @classmethod
    def for_table(cls, table) -> "SubscriptionHub":
        key = table.store.table_path
        with cls._hubs_lock:
            hub = cls._hubs.get(key)
            if hub is None or hub._stop.is_set():
                hub = cls._hubs[key] = SubscriptionHub(table)
            return hub

    @classmethod
    def shutdown_all(cls) -> None:
        with cls._hubs_lock:
            hubs = list(cls._hubs.values())
            cls._hubs.clear()
        for hub in hubs:
            hub.close()

    def __init__(self, table):
        from ..core.admission import WriteBufferController
        from ..options import CoreOptions
        from ..table.consumer import ConsumerManager

        self.table = table
        o = table.options.options
        self.queue_depth = int(o.get(CoreOptions.SUBSCRIPTION_QUEUE_DEPTH))
        self.poll_backoff_ms = int(o.get(CoreOptions.SUBSCRIPTION_POLL_BACKOFF))
        self.shed_timeout_ms = int(o.get(CoreOptions.SUBSCRIPTION_SHED_TIMEOUT))
        self.heartbeat_ms = int(o.get(CoreOptions.SUBSCRIPTION_HEARTBEAT_INTERVAL))
        self.max_subscribers = int(o.get(CoreOptions.SUBSCRIPTION_MAX_SUBSCRIBERS))
        self.backoff_cap_ms = int(o.get(CoreOptions.CONTINUOUS_DISCOVERY_INTERVAL) or 10_000)
        budget = int(o.get(CoreOptions.SUBSCRIPTION_BUFFER_MAX_MEMORY))
        # PR 8 admission machinery as the fan-out byte budget: reserve() on
        # enqueue blocks at most shed-timeout, then the typed reject sheds
        # the consumer that exhausted the budget
        self.controller = (
            WriteBufferController(
                budget,
                stop_trigger=1.0,
                block_timeout_ms=self.shed_timeout_ms,
                max_pending_flushes=0,
            )
            if budget > 0
            else None
        )
        # consumer files route through the store's RetryingFileIO so a
        # transient blip on record/read lands in the PR 3 retry policy
        # instead of surfacing per heartbeat
        self.consumers = ConsumerManager(table.store.file_io, table.path)
        # replay cache: recently decoded ChangelogBatches by snapshot id,
        # byte-budgeted LRU. The data-file cache already makes PAGE decode
        # once-per-process; this extends decode-once to the whole batch
        # (merge + concat included), so a late joiner's catch-up replay —
        # and a shed consumer's resume — reuse the tailer's work instead of
        # re-merging every snapshot per subscriber.
        self._replay: "dict[int, ChangelogBatch]" = {}
        self._replay_order: list[int] = []
        self._replay_bytes = 0
        self._replay_budget = int(o.get(CoreOptions.SUBSCRIPTION_REPLAY_CACHE_MAX_MEMORY))
        self._replay_lock = threading.Lock()
        self._cond = threading.Condition()
        self._subs: dict[str, _SubscriberState] = {}
        self._frontier: int | None = None
        self._inflight_sid: int | None = None  # fan-out in progress for this sid
        self._stop = threading.Event()
        self._tailer: threading.Thread | None = None
        self._heartbeat: threading.Thread | None = None
        self._read = table.new_read_builder().new_read()
        self._scan = None

    def _metrics(self):
        from ..metrics import sub_metrics

        return sub_metrics()

    # ---- replay cache ---------------------------------------------------
    def _replay_get(self, sid: int) -> "ChangelogBatch | None":
        with self._replay_lock:
            return self._replay.get(sid)

    def _replay_put(self, batch: "ChangelogBatch") -> None:
        if self._replay_budget <= 0:
            return
        nbytes = batch.byte_size()
        if nbytes > self._replay_budget:
            return
        with self._replay_lock:
            if batch.snapshot_id in self._replay:
                return
            self._replay[batch.snapshot_id] = batch
            self._replay_order.append(batch.snapshot_id)
            self._replay_bytes += nbytes
            while self._replay_bytes > self._replay_budget and self._replay_order:
                cold = self._replay_order.pop(0)
                old = self._replay.pop(cold, None)
                if old is not None:
                    self._replay_bytes -= old.byte_size()

    # ---- lifecycle -----------------------------------------------------
    def _ensure_started(self) -> None:
        """Called under self._cond."""
        if self._tailer is not None:
            return
        from ..table.stream import StreamTableScan

        sm = self.table.store.snapshot_manager
        latest = sm.latest_snapshot_id()
        self._frontier = (latest + 1) if latest is not None else 1
        self._scan = StreamTableScan(self.table.copy({"scan.mode": "latest"}))
        self._scan.restore(self._frontier)
        name = self.table.name or "table"
        self._tailer = threading.Thread(
            target=self._tail_loop, name=f"paimon-subtail-{name}", daemon=False
        )
        self._heartbeat = threading.Thread(
            target=self._heartbeat_loop, name=f"paimon-subhb-{name}", daemon=False
        )
        self._tailer.start()
        self._heartbeat.start()

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
            subs = list(self._subs.values())
        for st in subs:
            self._detach(st)
        for t in (self._tailer, self._heartbeat):
            if t is not None:
                t.join(timeout=30.0)
        with SubscriptionHub._hubs_lock:
            if SubscriptionHub._hubs.get(self.table.store.table_path) is self:
                del SubscriptionHub._hubs[self.table.store.table_path]

    def health_dict(self) -> dict:
        with self._cond:
            subs = list(self._subs.values())
            frontier = self._frontier
        lag = max((frontier - st.expected_next for st in subs), default=0) if frontier else 0
        out = {
            "state": "ok" if len(subs) < self.max_subscribers else "busy-subscribers",
            "subscribers": len(subs),
            "frontier": frontier,
            "lag_snapshots": int(lag),
            "retry_after_ms": 0 if len(subs) < self.max_subscribers else max(1, self.shed_timeout_ms // 2),
        }
        if self.controller is not None:
            out["buffered_bytes"] = self.controller.in_use
        return out

    # ---- subscribing ---------------------------------------------------
    def subscribe(self, consumer_id: str | None = None, from_snapshot: int | None = None) -> Subscription:
        """Register a subscriber. Resolution order for the start position:
        the consumer-id's durable saved progress (resume wins), else
        `from_snapshot`, else the live frontier (new changes only). The
        consumer file is recorded BEFORE anything is read, so expiry pins the
        whole replay range from the instant subscribe() returns."""
        from ..table.stream import StreamTableScan

        with self._cond:
            if self._stop.is_set():
                # racing close(): a typed shed, never a half-registered
                # subscriber on a hub whose tailer already exited
                raise SubscriberShedError(
                    ShedInfo(
                        kind="subscribe",
                        state="shutting-down",
                        retry_after_ms=max(1, self.shed_timeout_ms // 2),
                        extras={"consumer_id": consumer_id},
                    )
                )
            if len(self._subs) >= self.max_subscribers:
                self._metrics().counter("shed_subscribers").inc()
                raise SubscriberShedError(
                    {
                        "state": "busy-subscribers",
                        "consumer_id": consumer_id,
                        "next_snapshot": None,
                        "subscribers": len(self._subs),
                        "retry_after_ms": max(1, self.shed_timeout_ms // 2),
                    }
                )
            self._ensure_started()
            cid = consumer_id or f"sub-{uuid.uuid4().hex[:12]}"
            saved = self.consumers.consumer(cid) if consumer_id else None
            if saved is not None:
                start = saved
            elif from_snapshot is not None:
                start = from_snapshot
            else:
                start = self._frontier
            # durable pin first: expiry must never outrun a registered reader
            self.consumers.record(cid, start)
            catch_up_until = self._frontier
            if self._inflight_sid is not None:
                # a fan-out we were not part of is in flight: replay its
                # snapshot ourselves (one extra cache-hit read, never a gap)
                catch_up_until = max(catch_up_until, self._inflight_sid + 1)
            old = self._subs.get(cid)
            st = _SubscriberState(cid, start, catch_up_until)
            self._subs[cid] = st
            self._cond.notify_all()
            self._metrics().gauge("subscribers").set(len(self._subs))
        if old is not None:
            # consumer-id takeover: the superseded handle wakes and closes
            with old.cond:
                old.closed = True
                old.cond.notify_all()
            self._release_queue(old)
        scan = StreamTableScan(self.table.copy({"scan.mode": "latest"}))
        scan.restore(start)
        return Subscription(self, st, scan)

    def _detach(self, st: _SubscriberState, delete_consumer: bool = False) -> None:
        with self._cond:
            if self._subs.get(st.consumer_id) is st:
                del self._subs[st.consumer_id]
            self._metrics().gauge("subscribers").set(len(self._subs))
        with st.cond:
            st.closed = True
            st.cond.notify_all()
        self._release_queue(st)
        try:
            if delete_consumer:
                self.consumers.delete(st.consumer_id)
            else:
                self.consumers.record(st.consumer_id, st.durable_position)
        except Exception:
            pass  # best-effort: the heartbeat already recorded a position

    def _release_queue(self, st: _SubscriberState) -> None:
        with st.cond:
            st.queue.clear()
            reserved, st.reserved_bytes = st.reserved_bytes, 0
            st.cond.notify_all()
        if reserved and self.controller is not None:
            self.controller.release(reserved)

    # ---- shedding ------------------------------------------------------
    def _shed(self, st: _SubscriberState, reason: str) -> None:
        restart = st.restart_offset()
        payload = {
            "state": reason,
            "consumer_id": st.consumer_id,
            "next_snapshot": min(restart, st.durable_position if st.progress >= 0 else restart),
            "retry_after_ms": max(1, self.shed_timeout_ms // 2),
        }
        with self._cond:
            if self._subs.get(st.consumer_id) is st:
                del self._subs[st.consumer_id]
            self._metrics().counter("shed_subscribers").inc()
            self._metrics().gauge("subscribers").set(len(self._subs))
        # durable restart offset: resume replays from here (at-least-once)
        try:
            self.consumers.record(st.consumer_id, payload["next_snapshot"])
        except Exception:
            pass
        with st.cond:
            st.shed_payload = payload
            st.cond.notify_all()
        self._release_queue(st)

    # ---- the tailer ----------------------------------------------------
    def _tail_loop(self) -> None:
        backoff_ms = self.poll_backoff_ms
        while not self._stop.is_set():
            with self._cond:
                if not self._subs:
                    self._cond.wait(0.5)  # idle: no subscribers, no planning
                    continue
            try:
                splits = self._scan.plan()
            except Exception:
                # transient planning fault (the store IO already burned its
                # retry budget): back off and re-plan — plan() does not
                # advance past a snapshot it failed to plan
                if self._stop.wait(backoff_ms / 1000.0):
                    return
                backoff_ms = min(backoff_ms * 2, self.backoff_cap_ms)
                continue
            if splits is None:
                # nothing new: blocking poll with exponential backoff
                if self._stop.wait(backoff_ms / 1000.0):
                    return
                backoff_ms = min(backoff_ms * 2, self.backoff_cap_ms)
                continue
            backoff_ms = self.poll_backoff_ms
            if not splits:
                # a snapshot with no change stream (compaction, empty delta):
                # the frontier advances, nothing to fan out
                with self._cond:
                    self._frontier = self._scan._next
                continue
            sid = splits[0].snapshot_id
            batch = None
            while batch is None and not self._stop.is_set():
                try:
                    parts = [self._read.read_with_kinds(s) for s in splits]
                    batch = _concat_parts(sid, parts)
                except Exception:
                    # data files are immutable: a transient read fault cannot
                    # lose the snapshot, only delay it — retry until it lands
                    if self._stop.wait(min(backoff_ms, 100) / 1000.0):
                        return
            if batch is None:
                return
            self._replay_put(batch)
            with self._cond:
                self._inflight_sid = sid
                subs = list(self._subs.values())
            g = self._metrics()
            if batch.num_rows:
                fanned = 0
                for st in subs:
                    if self._offer(st, batch):
                        fanned += 1
                g.counter("batches_fanned").inc(fanned)
                g.counter("rows_fanned").inc(batch.num_rows * fanned)
                if fanned > 1:
                    g.counter("decode_reuse_hits").inc(fanned - 1)
            with self._cond:
                self._inflight_sid = None
                self._frontier = sid + 1
                lag = max(
                    (self._frontier - s.expected_next for s in self._subs.values()),
                    default=0,
                )
            g.gauge("lag_snapshots").set(int(lag))

    def _offer(self, st: _SubscriberState, batch: ChangelogBatch) -> bool:
        """Enqueue for one subscriber, bounded: wait at most shed-timeout for
        queue space (and the shared byte budget), then shed THAT subscriber —
        the tailer and its peers never stall on the slowest reader."""
        from ..core.admission import WriterBackpressureError

        if batch.snapshot_id < st.catch_up_until:
            return False  # the subscriber replays this one itself
        with st.cond:
            if len(st.queue) >= self.queue_depth and st.pressure_since is None:
                st.pressure_since = time.monotonic()
            while len(st.queue) >= self.queue_depth:
                if st.shed_payload is not None or st.closed:
                    return False
                # the shed clock runs over the whole pressure window: one
                # freed slot does NOT reset it (poll clears it at half
                # depth), so a persistently slow consumer is shed after
                # shed-timeout even though it keeps consuming
                remaining = st.pressure_since + self.shed_timeout_ms / 1000.0 - time.monotonic()
                if remaining <= 0:
                    break
                st.cond.wait(min(remaining, 0.1))
            if st.shed_payload is not None or st.closed:
                return False
            still_full = len(st.queue) >= self.queue_depth
        if still_full:
            # shed outside st.cond: _shed takes the hub lock first, and
            # holding st.cond here would invert _detach's ordering
            self._shed(st, "queue-full")
            return False
        nbytes = batch.byte_size()
        if self.controller is not None:
            try:
                self.controller.reserve(nbytes)
            except WriterBackpressureError:
                self._shed(st, "buffer-exhausted")
                return False
        with st.cond:
            if st.shed_payload is not None or st.closed:
                if self.controller is not None:
                    self.controller.release(nbytes)
                return False
            st.queue.append(batch)
            st.reserved_bytes += nbytes
            st.queue_high_water = max(st.queue_high_water, len(st.queue))
            st.cond.notify_all()
        g = self._metrics()
        hw = g.gauge("queue_high_water")
        if st.queue_high_water > hw.value:
            hw.set(st.queue_high_water)
        return True

    # ---- heartbeat -----------------------------------------------------
    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_ms / 1000.0):
            with self._cond:
                subs = list(self._subs.values())
            for st in subs:
                try:
                    # re-recording refreshes the consumer file's mtime, so
                    # consumer.expiration-time only collects readers that
                    # genuinely stopped heartbeating — AND advances the
                    # durable at-least-once position
                    self.consumers.record(st.consumer_id, st.durable_position)
                except Exception:
                    pass  # transient: the next beat retries


# ---------------------------------------------------------------------------
# subscriber OS process (soak harness: kill -9 + durable resume)
# ---------------------------------------------------------------------------


def _run_subscriber_process(argv: list[str] | None = None) -> int:
    """A subscriber in its own OS process, journaling every received batch
    (fsync per batch, torn-tail tolerant: one JSON object per line). The soak
    supervisor kill -9s this process mid-stream and respawns it with the same
    consumer-id; the respawned incarnation resumes from the durable consumer
    position and the journal fold must still equal the pinned-snapshot scan
    at its checkpoint (at-least-once replays overwrite by snapshot id)."""
    import argparse
    import json

    from ..table import load_table

    ap = argparse.ArgumentParser(description="paimon-tpu subscriber process")
    ap.add_argument("--table", required=True, help="table path (any registered scheme)")
    ap.add_argument("--consumer", required=True)
    ap.add_argument("--journal", required=True)
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--from-snapshot", type=int, default=1)
    ap.add_argument("--slow-ms", type=float, default=0.0, help="sleep per batch (slow-consumer modeling)")
    ap.add_argument("--idle-exit", type=float, default=2.0, help="exit after deadline once idle this long")
    ap.add_argument(
        "--format",
        default=None,
        dest="cdc_format",
        help="CDC wire format: each batch is encoded to this format and "
        "parsed back before journaling (parse∘format==identity on the wire)",
    )
    args = ap.parse_args(argv)

    if args.table.startswith(("fail:", "fail-s3", "latency:", "traceable:", "chaos:")):
        # test-harness schemes register on import; a child process spawned
        # onto a fault-injecting warehouse has no reason to know that
        from ..fs import testing as _testing  # noqa: F401

    table = load_table(args.table, commit_user=f"subscriber-{args.consumer}")
    hub = SubscriptionHub(table)
    sub = hub.subscribe(consumer_id=args.consumer, from_snapshot=args.from_snapshot)
    deadline = time.monotonic() + args.duration
    last_batch = time.monotonic()
    jf = open(args.journal, "a", encoding="utf-8")

    def journal(obj: dict) -> None:
        jf.write(json.dumps(obj) + "\n")
        jf.flush()
        os.fsync(jf.fileno())

    try:
        while True:
            now = time.monotonic()
            if now >= deadline and (now - last_batch) >= args.idle_exit:
                break
            try:
                batch = sub.poll(timeout=0.25)
            except SubscriberShedError as exc:
                journal({"shed": exc.payload})
                sub = hub.subscribe(consumer_id=args.consumer)
                continue
            if batch is None:
                continue
            rows, kinds = batch.data.to_pylist(), batch.kinds.tolist()
            if args.cdc_format:
                # ride the wire format both ways: the journal records what a
                # downstream consumer of THIS format would have decoded, so
                # the end-of-run fold==scan check covers the codec too
                from ..table.cdc_format import encode_changelog, get_cdc_parser
                from ..types import RowKind

                names = batch.data.schema.field_names
                msgs = encode_changelog(batch.data, batch.kinds, args.cdc_format)
                parse = get_cdc_parser(args.cdc_format)
                decoded = [rec for m in msgs for rec in parse(m)]
                short_to_kind = {k.short_string: int(k) for k in RowKind}
                rows = [[rec.get(n) for n in names] for rec in decoded]
                kinds = [short_to_kind[rec.kind] for rec in decoded]
            journal(
                {
                    "sid": batch.snapshot_id,
                    "rows": rows,
                    "kinds": kinds,
                }
            )
            from ..resilience.faults import crash_point

            # armed by the mega soak: die AFTER the fsync, BEFORE advancing —
            # the respawn must resume from the durable consumer position and
            # the journal fold (sid-deduped) must absorb the replay
            crash_point("subscriber:batch-journaled")
            last_batch = time.monotonic()
            if args.slow_ms > 0:
                time.sleep(args.slow_ms / 1000.0)
        journal({"done": True, "checkpoint": sub.checkpoint - 1})
        return 0
    finally:
        jf.close()
        sub.close()
        hub.close()


if __name__ == "__main__":
    raise SystemExit(_run_subscriber_process())
