"""Production traffic soak: concurrent writers, churn, verified reads.

Every resilience piece of this system exists in isolation — RetryingFileIO +
commit auto-retry + orphan sweep, streaming reads, offloaded flushes, the
mesh engine, and (this PR) writer admission control. The soak harness is
where they prove they compose: N committer threads on disjoint AND
overlapping buckets, M reader threads asserting snapshot-consistent scans
against a serialized oracle log, a dedicated full-compactor and a snapshot
expirer churning underneath, all over a fault-injecting filesystem at a
sustained op rate, with one shared `WriteBufferController` modelling the
host-memory budget ("Fast Updates on Read-Optimized Databases" assumes the
delta never outruns the merge; this is the machinery that makes it true).

Consistency protocol. Writers commit through the real snapshot-CAS path and
record every LANDED commit in the `OracleLog` under one lock:
(append-snapshot-id -> {key: value}). Keyspaces are disjoint per writer
(key = writer_id * KEYSPACE + n) so cross-writer merge order is irrelevant,
while updates WITHIN a writer are ordered by its monotone sequence numbers —
the expected row set at snapshot S is therefore exactly the fold of all
recorded events with id <= S, in id order. A reader pins snapshot S
(scan.snapshot-id), scans, waits for the oracle to cover every soak APPEND
snapshot <= S (the record happens microseconds after commit() returns), and
asserts the scanned row set EQUALS the fold. A commit that raises may still
have landed its APPEND phase (conflict on the COMPACT half, a lost rename
ack, a crash-replay) — `find_landed_append` resolves the truth from the
snapshot chain, so the oracle counts exactly what the table counts: no lost
rows, no duplicated rows.

End of soak: drain writers, disable faults, full-compact once, assert the
final scan equals the oracle fold and the physical row count matches, then
run the orphan sweep with threshold 0 and assert the on-disk file set is
exactly the reachable closure (zero leaked files) — and that the sweep
removed nothing a reader can still see.

Run directly:  python -m paimon_tpu.service.soak [base_dir]
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from dataclasses import dataclass, field

import numpy as np

from ..types import BIGINT, DOUBLE, RowType

# the oracle pieces live in service/oracle.py (shared with proc_soak,
# cluster and mega_soak); re-exported here for back-compat
from .oracle import OracleLog, find_landed_append, sweep_and_audit

__all__ = [
    "SoakConfig",
    "OracleLog",
    "SoakHarness",
    "run_soak",
    "find_landed_append",
    "sweep_and_audit",
]

SCHEMA = RowType.of(("k", BIGINT()), ("v", DOUBLE()))
KEYSPACE = 10_000_000  # per-writer key stride: keyspaces never collide


@dataclass
class SoakConfig:
    """Knobs for one soak run. `from_table_options` maps the soak.* table
    options onto the same fields so a run is reproducible from table config
    alone; the CLI/bench/tests override programmatically."""

    duration_s: float = 45.0
    writers: int = 3
    readers: int = 2
    buckets: int = 4
    fault_possibility: int = 0  # 1/N ops fail (20 = 5%); 0 = off
    seed: int = 0
    rows_per_commit: int = 400
    write_chunk_rows: int = 100  # rows per TableWrite.write call
    update_fraction: float = 0.3  # fraction of a round re-writing own keys
    compact_every: int = 4  # full-compact every Nth commit per writer
    compactor_pause_s: float = 0.4
    expire_every_s: float = 1.5
    # the churn compactor: False = periodic all-bucket full compaction;
    # True = the LUDA-style adaptive scheduler (table.compactor.
    # AdaptiveCompactorService) draining debt by heat/read-amp priority.
    # PAIMON_TPU_SOAK_ADAPTIVE=1 flips the default (the verify.sh soak
    # stage runs with it on).
    adaptive: bool = field(
        default_factory=lambda: os.environ.get("PAIMON_TPU_SOAK_ADAPTIVE", "") == "1"
    )
    mesh: bool = False
    # flow control (the shared WriteBufferController)
    backpressure: bool = True
    max_memory: int = 512 * 1024
    stop_trigger: float = 0.6
    block_timeout_ms: int = 30_000
    max_pending_flushes: int = 2
    # point-get storm (ISSUE 13): getter threads running batched gets with
    # a scalar-lookup()-loop oracle, a read-your-writes checker committing
    # through an attached TableWrite, and (get_server) a KvQueryServer the
    # getters deliberately overload to prove typed-BUSY shedding
    getters: int = 0
    get_batch_keys: int = 512
    get_oracle_keys: int = 16  # scalar lookups verified per round
    ryw: bool = True  # read-your-writes checker rides along with getters
    get_server: bool = True  # typed-BUSY overload bursts via KvQueryServer
    # CDC subscription storm (ISSUE 14): subscriber threads on one shared
    # decode-once hub, each folding its received changelog stream and
    # asserting fold == pinned-snapshot scan at its checkpoint; subscriber 0
    # is deliberately SLOW (must be shed with the typed protocol and resume
    # from its consumer-id), and an optional subscriber OS process rides
    # along, journaling batches, to be kill -9'd and respawned
    subscribers: int = 0
    slow_subscriber: bool = True
    # per-batch stall of the slow subscriber: decisively past the soak's
    # 1.5 s subscription.shed-timeout, so the shed fires whenever its queue
    # is full — independent of the host's commit rate
    sub_slow_sleep_s: float = 2.5
    sub_verify_every: int = 8  # fold==scan check cadence (batches)
    subscriber_procs: int = 0
    kill_subscriber: bool = True  # SIGKILL the subscriber process once
    # resilience (False = seed-like config: first fault aborts, no CAS retry)
    resilient: bool = True
    table_options: dict = field(default_factory=dict)

    @classmethod
    def from_table_options(cls, options) -> "SoakConfig":
        from ..options import CoreOptions

        o = options.options
        return cls(
            duration_s=o.get(CoreOptions.SOAK_DURATION) / 1000.0,
            writers=o.get(CoreOptions.SOAK_WRITERS),
            readers=o.get(CoreOptions.SOAK_READERS),
            fault_possibility=o.get(CoreOptions.SOAK_FAULT_POSSIBILITY),
            rows_per_commit=o.get(CoreOptions.SOAK_ROWS_PER_COMMIT),
            compact_every=o.get(CoreOptions.SOAK_COMPACT_EVERY),
        )


class SoakHarness:
    def __init__(self, base_dir: str, cfg: SoakConfig | None = None, domain: str | None = None):
        self.cfg = cfg or SoakConfig()
        self.base_dir = str(base_dir)
        self.domain = domain or f"soak{os.getpid()}_{self.cfg.seed}"
        self.local_root = os.path.join(self.base_dir, "soak_table")
        self.path = f"fail://{self.domain}{self.local_root}"
        self.stop = threading.Event()
        self.oracle = OracleLog()
        self.errors: list[str] = []  # unexpected thread crashes
        self.inconsistencies: list[dict] = []
        self.read_latencies_ms: list[float] = []
        self.get_latencies_us: list[float] = []  # per-key batched get latency
        self._lock = threading.Lock()
        self.counts = {
            "commits_ok": 0,
            "commits_failed": 0,
            "commits_conflict_survived": 0,  # raised, but APPEND landed
            "commits_conflict_aborted": 0,  # raised, nothing landed
            "writes_rejected_rounds": 0,
            "compactor_commits": 0,
            "compactor_conflicts": 0,
            "expire_runs": 0,
            "reads_ok": 0,
            "reads_expired_race": 0,
            "read_errors": 0,
            "gets_served": 0,  # probe keys answered by batched gets
            "get_rounds": 0,
            "get_oracle_checks": 0,
            "get_mismatches": 0,
            "gets_shed_typed": 0,  # KvBusyError responses under overload
            "gets_shed_untyped": 0,  # anything else (timeouts = failures)
            "ryw_rounds": 0,
            "ryw_misses": 0,
            "sub_batches": 0,  # ChangelogBatches received across subscribers
            "sub_rows": 0,
            "sub_verifies": 0,  # fold == pinned-scan checks performed
            "sub_mismatches": 0,
            "sub_shed_typed": 0,  # SubscriberShedError (slow consumer shed)
            "sub_shed_untyped": 0,  # anything else severing a subscriber
            "sub_resumes": 0,  # consumer-id resumes after a typed shed
            "subproc_kills": 0,  # SIGKILLs of the subscriber OS process
        }
        self._table = None
        self._controller = None
        self._sub_hub = None

    # ---- setup ---------------------------------------------------------
    def _table_options(self) -> dict:
        cfg = self.cfg
        opts = {
            "bucket": str(cfg.buckets),
            "merge.engine": "mesh" if cfg.mesh else "single",
            # small memtables force the offloaded-flush path under load
            "write-buffer-rows": str(max(cfg.write_chunk_rows * 2, 64)),
            # enough history that a pinned read never races expiry
            "snapshot.num-retained.min": "16",
            "snapshot.num-retained.max": "30",
            "commit.retry-backoff": "2 ms",
        }
        if cfg.subscribers or cfg.subscriber_procs:
            # subscription storm knobs: a shallow queue + short shed timeout
            # so the deliberately-slow subscriber actually gets shed, and a
            # fast heartbeat so durable progress (and the expiry pin) tracks
            # consumption closely
            opts.update(
                {
                    "subscription.queue-depth": "4",
                    "subscription.shed-timeout": "1500 ms",
                    "subscription.heartbeat-interval": "1 s",
                    "subscription.poll-backoff": "20 ms",
                }
            )
        if cfg.resilient:
            opts.update(
                {
                    "commit.max-retries": "30",
                    "fs.retry.max-attempts": "6",
                    "fs.retry.initial-backoff": "2 ms",
                    "fs.retry.max-backoff": "40 ms",
                }
            )
        else:
            # the seed contrast: first IO fault aborts, no CAS retry budget
            opts.update({"commit.max-retries": "0", "fs.retry.max-attempts": "1"})
        opts.update(cfg.table_options)
        return opts

    def setup(self):
        from ..core.schema import SchemaManager
        from ..fs import get_file_io
        from ..fs.testing import FailingFileIO
        from ..table import FileStoreTable

        FailingFileIO.reset(self.domain, 0, 0)
        io = get_file_io(self.path)
        ts = SchemaManager(io, self.path).create_table(
            SCHEMA, primary_keys=["k"], options=self._table_options()
        )
        self._table = FileStoreTable(io, self.path, ts, commit_user="soak-setup")
        if self.cfg.backpressure:
            from ..core.admission import WriteBufferController

            self._controller = WriteBufferController(
                self.cfg.max_memory,
                stop_trigger=self.cfg.stop_trigger,
                block_timeout_ms=self.cfg.block_timeout_ms,
                max_pending_flushes=self.cfg.max_pending_flushes,
            )
        if self.cfg.subscribers:
            # ONE hub: every subscriber thread rides the same decode-once
            # tailer (the subscriber process has its own, in its own process)
            from ..service.subscription import SubscriptionHub

            self._sub_hub = SubscriptionHub(self._table.with_user("soak-subhub"))
        return self._table

    def _handle(self, user: str):
        """A fresh table handle (own store, own commit user) — one per
        thread, exactly how independent jobs would mount the table."""
        return self._table.with_user(user)

    # ---- writer --------------------------------------------------------
    def _writer_loop(self, wid: int, deadline: float) -> None:
        from ..core.admission import WriterBackpressureError
        from ..core.commit import CommitConflictError, CommitGiveUpError
        from ..core.manifest import ManifestCommittable
        from ..fs.testing import ArtificialException
        from ..metrics import soak_metrics
        from ..table.write import TableWrite

        cfg = self.cfg
        rng = np.random.default_rng(cfg.seed * 7919 + wid)
        user = f"soak-w{wid}"
        table = self._handle(user)
        store = table.store
        g = soak_metrics()
        ident = 0
        next_key = 0
        written: list[int] = []
        while not self.stop.is_set() and time.monotonic() < deadline:
            ident += 1
            n_upd = int(cfg.rows_per_commit * cfg.update_fraction) if written else 0
            n_new = cfg.rows_per_commit - n_upd
            fresh = [wid * KEYSPACE + next_key + i for i in range(n_new)]
            upd = (
                [written[i] for i in rng.integers(0, len(written), n_upd)] if n_upd else []
            )
            keys = fresh + upd
            vals = (ident * 1_000.0 + wid) + rng.random(len(keys))
            rows = dict(zip(keys, [float(v) for v in vals]))  # unique keys per round
            try:
                tw = TableWrite(table, buffer_controller=self._controller)
                try:
                    data_keys = list(rows)
                    data_vals = [rows[k] for k in data_keys]
                    from ..data.batch import ColumnBatch

                    for i in range(0, len(data_keys), cfg.write_chunk_rows):
                        tw.write(
                            ColumnBatch.from_pydict(
                                SCHEMA,
                                {
                                    "k": data_keys[i : i + cfg.write_chunk_rows],
                                    "v": data_vals[i : i + cfg.write_chunk_rows],
                                },
                            )
                        )
                    if cfg.compact_every and ident % cfg.compact_every == 0:
                        tw.compact(full=True)
                    msgs = tw.prepare_commit()
                finally:
                    tw.close()  # releases any reservation this round still holds
                sids = store.new_commit().commit(ManifestCommittable(ident, messages=msgs))
                if sids:
                    self.oracle.record(sids[0], rows)
                    next_key += n_new
                    written.extend(fresh)
                    with self._lock:
                        self.counts["commits_ok"] += 1
                    g.counter("commits_ok").inc()
            except WriterBackpressureError:
                # load shed: the round was REJECTED before any byte buffered —
                # not lost, not accepted. Back off and continue.
                with self._lock:
                    self.counts["writes_rejected_rounds"] += 1
                time.sleep(0.02)
            except (CommitConflictError, CommitGiveUpError, ArtificialException):
                sid = find_landed_append(store, user, ident)
                if sid is not None:
                    # COMPACT half lost the race/faulted, APPEND landed: the
                    # rows ARE committed and the oracle must count them
                    self.oracle.record(sid, rows)
                    next_key += n_new
                    written.extend(fresh)
                    with self._lock:
                        self.counts["commits_conflict_survived"] += 1
                    g.counter("commits_conflict_replanned").inc()
                else:
                    with self._lock:
                        if self.cfg.resilient:
                            self.counts["commits_conflict_aborted"] += 1
                        else:
                            self.counts["commits_failed"] += 1

    # ---- reader --------------------------------------------------------
    def _append_sids_up_to(self, sm, sid: int) -> set[int]:
        """The soak-writer APPEND snapshots <= sid the oracle must cover
        before the read at sid can be judged. A snapshot that vanishes
        mid-walk was just expired — expiry only reaches OLD snapshots, whose
        commits were recorded long ago, so skipping it never weakens the
        coverage requirement (sm.snapshots() itself is list-then-read and
        would throw on exactly that race)."""
        from ..core.snapshot import CommitKind

        out: set[int] = set()
        earliest = sm.earliest_snapshot_id()
        if earliest is None:
            return out
        for i in range(earliest, sid + 1):
            try:
                if not sm.snapshot_exists(i):
                    continue
                snap = sm.snapshot(i)
            except FileNotFoundError:
                continue  # expired between the exists check and the read
            if snap.commit_kind == CommitKind.APPEND and snap.commit_user.startswith("soak-w"):
                out.add(snap.id)
        return out

    def _read_at(self, table, sid: int):
        t = table.copy({"scan.snapshot-id": str(sid)})
        rb = t.new_read_builder()
        splits = rb.new_scan().plan()
        return rb.new_read().read_all(splits)

    def _reader_loop(self, rid: int, deadline: float) -> None:
        user = f"soak-r{rid}"
        table = self._handle(user)
        sm = table.store.snapshot_manager
        while not self.stop.is_set() and time.monotonic() < deadline:
            t0 = time.perf_counter()
            try:
                sid = sm.latest_snapshot_id()
            except Exception:
                sid = None
            if sid is None:
                time.sleep(0.05)
                continue
            try:
                from ..fs.testing import ArtificialException

                try:
                    batch = self._read_at(table, sid)
                except ArtificialException:
                    # the IO layer already burned fs.retry.max-attempts; one
                    # fresh pass covers the (rare) full-budget exhaustion
                    batch = self._read_at(table, sid)
                needed = self._append_sids_up_to(sm, sid)
            except Exception as exc:
                earliest = None
                try:
                    earliest = sm.earliest_snapshot_id()
                except Exception:
                    pass
                with self._lock:
                    if earliest is not None and sid < earliest:
                        # pinned snapshot expired mid-read: a retriable race,
                        # not an inconsistency (retention bounds its rate)
                        self.counts["reads_expired_race"] += 1
                    else:
                        self.counts["read_errors"] += 1
                        self.errors.append(f"reader {rid} @ snapshot {sid}: {exc!r}")
                continue
            self.read_latencies_ms.append((time.perf_counter() - t0) * 1000)
            ks = batch.column("k").values.tolist()
            got = dict(zip(ks, batch.column("v").values.tolist()))
            if len(ks) != len(got):
                self.inconsistencies.append(
                    {"snapshot": sid, "kind": "duplicate-keys", "rows": len(ks), "unique": len(got)}
                )
                continue
            if not self.oracle.wait_covers(needed, timeout_s=10.0):
                self.inconsistencies.append(
                    {"snapshot": sid, "kind": "oracle-lag", "needed": sorted(needed)[-3:]}
                )
                continue
            expected = self.oracle.expected_at(sid)
            if got != expected:
                missing = [k for k in expected if k not in got]
                extra = [k for k in got if k not in expected]
                wrong = [k for k in expected if k in got and got[k] != expected[k]]
                self.inconsistencies.append(
                    {
                        "snapshot": sid,
                        "kind": "row-set-mismatch",
                        "missing": len(missing),
                        "extra": len(extra),
                        "wrong_value": len(wrong),
                        "sample": (missing[:3], extra[:3], wrong[:3]),
                    }
                )
            else:
                with self._lock:
                    self.counts["reads_ok"] += 1

    # ---- point-get storm (ISSUE 13) ------------------------------------
    RYW_WID = 97  # read-your-writes checker keyspace, disjoint from writers

    def _getter_loop(self, gid: int, deadline: float) -> None:
        """Batched point-gets against the live table: every round runs ONE
        vectorized get_batch over a random slice of a random writer's
        keyspace (present, absent and deleted keys all occur naturally),
        then verifies a random subset against the scalar lookup() walk —
        the independent oracle. Getter queries are private, so the levels
        they probe are frozen between their own refresh() calls."""
        from ..table.query import LocalTableQuery

        cfg = self.cfg
        rng = np.random.default_rng(cfg.seed * 104729 + gid)
        table = self._handle(f"soak-g{gid}")
        q = None
        while not self.stop.is_set() and time.monotonic() < deadline:
            try:
                if q is None:
                    q = LocalTableQuery(table)
                else:
                    q.refresh()
            except Exception:
                time.sleep(0.05)  # no snapshot yet / a refresh racing expiry
                continue
            wid = int(rng.integers(0, cfg.writers))
            keys = [
                int(wid * KEYSPACE + k)
                for k in rng.integers(0, 6000, size=cfg.get_batch_keys)
            ]
            t0 = time.perf_counter()
            try:
                got = q.get_batch(keys).to_pylist()
            except Exception as exc:
                with self._lock:
                    self.counts["read_errors"] += 1
                    self.errors.append(f"getter {gid}: {exc!r}")
                continue
            self.get_latencies_us.append(
                (time.perf_counter() - t0) / max(len(keys), 1) * 1e6
            )
            # scalar oracle on a random subset: the batched path and the
            # LookupLevels walk read the SAME frozen per-bucket state
            for i in rng.choice(len(keys), size=min(cfg.get_oracle_keys, len(keys)), replace=False):
                row = q.lookup((), keys[int(i)])
                expect = None if row is None else row.to_pylist()[0]
                with self._lock:
                    self.counts["get_oracle_checks"] += 1
                if got[int(i)] != expect:
                    with self._lock:
                        self.counts["get_mismatches"] += 1
                    self.inconsistencies.append(
                        {"kind": "get-mismatch", "key": keys[int(i)],
                         "batched": got[int(i)], "scalar": expect}
                    )
            with self._lock:
                self.counts["gets_served"] += len(keys)
                self.counts["get_rounds"] += 1

    def _get_overload_loop(self, deadline: float) -> None:
        """Deliberately overload a KvQueryServer (max_inflight_gets=1) with
        concurrent get_batch bursts: under saturation the server must answer
        a TYPED busy (KvBusyError with a retry hint) — a socket timeout or
        any other failure counts as untyped and fails the soak."""
        from ..service import KvBusyError, KvQueryClient, KvQueryServer

        try:
            server = KvQueryServer(self._table, max_inflight_gets=1)
            host, port = server.start()
        except Exception as exc:
            self.errors.append(f"get-overload server failed to start: {exc!r}")
            return
        try:
            clients = [KvQueryClient(host, port, timeout=30.0) for _ in range(4)]
            keys = [list(range(64))]

            def one(c):
                try:
                    c.get_batch(keys[0])
                    with self._lock:
                        self.counts["gets_served"] += len(keys[0])
                except KvBusyError:
                    with self._lock:
                        self.counts["gets_shed_typed"] += 1
                except Exception:
                    with self._lock:
                        self.counts["gets_shed_untyped"] += 1

            while not self.stop.is_set() and time.monotonic() < deadline:
                burst = [threading.Thread(target=one, args=(c,)) for c in clients]
                for t in burst:
                    t.start()
                for t in burst:
                    t.join(timeout=30.0)
                time.sleep(0.1)
            for c in clients:
                c.close()
        finally:
            server.shutdown()

    def _ryw_loop(self, deadline: float) -> None:
        """Read-your-writes checker: a committer on its own keyspace whose
        attached query must see every buffered row BEFORE the commit lands,
        and (after refresh) the committed rows after. Landed commits are
        recorded in the oracle exactly like writer commits, so the final
        verification covers this keyspace too."""
        from ..core.commit import CommitConflictError, CommitGiveUpError
        from ..core.manifest import ManifestCommittable
        from ..data.batch import ColumnBatch
        from ..fs.testing import ArtificialException
        from ..table.query import LocalTableQuery
        from ..table.write import TableWrite

        cfg = self.cfg
        rng = np.random.default_rng(cfg.seed * 7919 + 9999)
        user = "soak-ryw"
        table = self._handle(user)
        store = table.store
        tw = None
        q = None
        ident = 0
        next_key = 0
        while not self.stop.is_set() and time.monotonic() < deadline:
            ident += 1
            keys = [self.RYW_WID * KEYSPACE + next_key + i for i in range(32)]
            vals = [float(ident * 1000 + i) + float(rng.random()) for i in range(32)]
            rows = dict(zip(keys, vals))
            try:
                if tw is None:
                    tw = TableWrite(table, buffer_controller=self._controller)
                    q = None
                if q is None:
                    q = LocalTableQuery(table).attach_write(tw)
                else:
                    q.refresh()
                tw.write(ColumnBatch.from_pydict(SCHEMA, {"k": keys, "v": vals}))
                got = q.get_batch(keys).to_pylist()
                with self._lock:
                    self.counts["ryw_rounds"] += 1
                misses = [
                    k for k, g in zip(keys, got) if g is None or g[1] != rows[k]
                ]
                if misses:
                    with self._lock:
                        self.counts["ryw_misses"] += len(misses)
                    self.inconsistencies.append(
                        {"kind": "ryw-miss", "ident": ident, "missing": misses[:3]}
                    )
                msgs = tw.prepare_commit()
                sids = store.new_commit().commit(ManifestCommittable(ident, messages=msgs))
                if sids:
                    self.oracle.record(sids[0], rows)
                    next_key += 32
            except (CommitConflictError, CommitGiveUpError, ArtificialException):
                sid = find_landed_append(store, user, ident)
                if sid is not None:
                    self.oracle.record(sid, rows)
                    next_key += 32
                    with self._lock:
                        self.counts["commits_conflict_survived"] += 1
                else:
                    with self._lock:
                        self.counts["commits_conflict_aborted"] += 1
                # a failed round may leave writer state ambiguous: rebuild
                try:
                    tw.close()
                except Exception:
                    pass
                tw = None
            except Exception as exc:
                with self._lock:
                    self.errors.append(f"ryw checker: {exc!r}")
                try:
                    tw.close()
                except Exception:
                    pass
                tw = None
        if tw is not None:
            try:
                tw.close()
            except Exception:
                pass

    # ---- CDC subscribers (ISSUE 14) ------------------------------------
    def _sub_scan_at(self, table, sid: int):
        """Pinned scan at sid as {key: full row tuple} — the truth a
        subscriber's fold is checked against (one retry for the rare
        full-retry-budget fault exhaustion, like the reader loop)."""
        from ..fs.testing import ArtificialException

        try:
            batch = self._read_at(table, sid)
        except ArtificialException:
            batch = self._read_at(table, sid)
        ks = batch.column("k").values.tolist()
        vs = batch.column("v").values.tolist()
        return {(k,): (k, v) for k, v in zip(ks, vs)}

    def _subscriber_loop(self, sidx: int, deadline: float) -> None:
        """One subscriber on the shared decode-once hub: fold every received
        batch (sid-deduped, so at-least-once replays after a shed/resume are
        harmless) and periodically assert fold == pinned scan at the
        checkpoint. Subscriber 0 (slow_subscriber) stalls per batch until the
        hub sheds it with the typed protocol, then resumes from its
        consumer-id — losslessly."""
        from ..service.subscription import SubscriberShedError

        cfg = self.cfg
        slow = cfg.slow_subscriber and sidx == 0
        table = self._handle(f"soak-sub{sidx}")
        consumer = f"soak-sub-{sidx}"
        received: dict[int, object] = {}  # sid -> ChangelogBatch (last wins)

        def fold_up_to(sid: int) -> dict:
            from ..service.subscription import fold_changelog

            state: dict = {}
            for s in sorted(received):
                if s <= sid:
                    fold_changelog(state, received[s], ["k"])
            return state

        def verify(sid: int) -> None:
            with self._lock:
                self.counts["sub_verifies"] += 1
            expected = self._sub_scan_at(table, sid)
            got = fold_up_to(sid)
            if got != expected:
                with self._lock:
                    self.counts["sub_mismatches"] += 1
                missing = [k for k in expected if k not in got]
                extra = [k for k in got if k not in expected]
                self.inconsistencies.append(
                    {
                        "kind": "sub-fold-mismatch",
                        "subscriber": sidx,
                        "snapshot": sid,
                        "missing": len(missing),
                        "extra": len(extra),
                        "sample": (missing[:3], extra[:3]),
                    }
                )

        sub = None
        since_verify = 0
        try:
            while not self.stop.is_set():
                draining = time.monotonic() >= deadline
                try:
                    if sub is None:
                        sub = self._sub_hub.subscribe(consumer_id=consumer, from_snapshot=1)
                    batch = sub.poll(timeout=1.0)
                except SubscriberShedError:
                    with self._lock:
                        self.counts["sub_shed_typed"] += 1
                        self.counts["sub_resumes"] += 1
                    sub = None  # resume from the durable consumer position
                    continue
                except Exception as exc:
                    if draining:
                        break
                    with self._lock:
                        self.counts["sub_shed_untyped"] += 1
                        self.errors.append(f"subscriber {sidx}: {exc!r}")
                    time.sleep(0.2)
                    continue
                if batch is None:
                    if draining:
                        break  # queue drained after the writer deadline
                    continue
                received[batch.snapshot_id] = batch
                since_verify += 1
                with self._lock:
                    self.counts["sub_batches"] += 1
                    self.counts["sub_rows"] += batch.num_rows
                if slow and not draining:
                    time.sleep(cfg.sub_slow_sleep_s)
                if since_verify >= cfg.sub_verify_every and not draining:
                    since_verify = 0
                    try:
                        verify(batch.snapshot_id)
                    except Exception as exc:
                        with self._lock:
                            self.errors.append(f"subscriber {sidx} verify @ {batch.snapshot_id}: {exc!r}")
            # final oracle: the fold of EVERYTHING received must equal the
            # pinned scan at the final checkpoint, for every subscriber
            if received:
                try:
                    verify(max(received))
                except Exception as exc:
                    with self._lock:
                        self.errors.append(f"subscriber {sidx} final verify: {exc!r}")
        finally:
            if sub is not None:
                try:
                    sub.close()
                except Exception:
                    pass

    def _subscriber_proc_loop(self, deadline: float) -> None:
        """Subscriber as an OS process (the kill -9 half of the oracle): a
        child subscribes with a durable consumer-id and journals every batch
        (fsync per line). Mid-soak the supervisor SIGKILLs it and respawns
        it with the SAME consumer-id; the respawn resumes from the recorded
        position. _verify folds the journal (sid-deduped) and asserts it
        equals the pinned scan at the journal's checkpoint."""
        import signal
        import subprocess
        import sys

        cfg = self.cfg
        self._subproc_journal = os.path.join(self.base_dir, "subscriber_proc.journal")
        consumer = "soak-subproc"

        def spawn() -> subprocess.Popen:
            remaining = max(deadline - time.monotonic(), 1.0)
            env = dict(os.environ)
            env.setdefault("JAX_PLATFORMS", "cpu")
            return subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "paimon_tpu.service.subscription",
                    "--table",
                    self.path,
                    "--consumer",
                    consumer,
                    "--journal",
                    self._subproc_journal,
                    "--duration",
                    str(remaining + 5.0),
                    "--from-snapshot",
                    "1",
                ],
                env=env,
            )

        proc = spawn()
        kill_at = time.monotonic() + max((deadline - time.monotonic()) * 0.45, 2.0)
        killed = False
        try:
            while time.monotonic() < deadline and not self.stop.is_set():
                if cfg.kill_subscriber and not killed and time.monotonic() >= kill_at:
                    try:
                        os.kill(proc.pid, signal.SIGKILL)
                        proc.wait(timeout=30)
                    except Exception:
                        pass
                    killed = True
                    with self._lock:
                        self.counts["subproc_kills"] += 1
                    proc = spawn()  # same consumer-id: durable resume
                if proc.poll() is not None and time.monotonic() < deadline - 3.0:
                    # premature death is a failure unless we just killed it
                    with self._lock:
                        self.errors.append(
                            f"subscriber process exited early rc={proc.returncode}"
                        )
                    return
                time.sleep(0.2)
            try:
                proc.wait(timeout=60 + cfg.duration_s)
            except Exception:
                proc.kill()
                with self._lock:
                    self.errors.append("subscriber process failed to drain; killed")
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

    def _verify_subproc_journal(self) -> None:
        """Fold the subscriber process's journal and assert it equals the
        pinned-snapshot scan at its checkpoint — across the kill -9."""
        import json as _json

        path = getattr(self, "_subproc_journal", None)
        if path is None or not os.path.exists(path):
            self.errors.append("subscriber process journal missing")
            return
        from ..types import RowKind

        by_sid: dict[int, tuple[list, list]] = {}
        done = None
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = _json.loads(line)
                except ValueError:
                    continue  # torn tail from the SIGKILL
                if "sid" in rec:
                    by_sid[rec["sid"]] = (rec["rows"], rec["kinds"])
                elif rec.get("done"):
                    done = rec.get("checkpoint")
        if not by_sid:
            self.errors.append("subscriber process journal recorded no batches")
            return
        checkpoint = max(by_sid)
        state: dict = {}
        for sid in sorted(by_sid):
            rows, kinds = by_sid[sid]
            for row, kind in zip(rows, kinds):
                k = RowKind(int(kind))
                if k in (RowKind.INSERT, RowKind.UPDATE_AFTER):
                    state[(row[0],)] = tuple(row)
                elif k == RowKind.DELETE:
                    state.pop((row[0],), None)
        table = self._handle("soak-subproc-verify")
        expected = self._sub_scan_at(table, checkpoint)
        self.counts["sub_verifies"] += 1
        if state != expected:
            self.counts["sub_mismatches"] += 1
            missing = [k for k in expected if k not in state]
            extra = [k for k in state if k not in expected]
            self.inconsistencies.append(
                {
                    "kind": "subproc-journal-mismatch",
                    "checkpoint": checkpoint,
                    "done_marker": done,
                    "missing": len(missing),
                    "extra": len(extra),
                    "sample": (missing[:3], extra[:3]),
                }
            )

    # ---- churn ---------------------------------------------------------
    def _compactor_loop(self, deadline: float) -> None:
        from ..core.commit import BATCH_COMMIT_IDENTIFIER, CommitConflictError, CommitGiveUpError
        from ..core.manifest import ManifestCommittable
        from ..fs.testing import ArtificialException
        from ..table.write import TableWrite

        table = self._handle("soak-compactor")
        store = table.store
        if self.cfg.adaptive:
            # adaptive churn: the LUDA scheduler observes per-bucket LSM
            # shape each round and compacts by heat/read-amp priority —
            # run_round() is driven from this thread (no service thread),
            # so drain/join semantics stay identical to the legacy loop
            from ..table.compactor import AdaptiveCompactorService

            svc = AdaptiveCompactorService(table)
            while not self.stop.is_set() and time.monotonic() < deadline:
                time.sleep(self.cfg.compactor_pause_s)
                try:
                    done = svc.run_round()
                    if done:
                        with self._lock:
                            self.counts["compactor_commits"] += done
                except (CommitConflictError, CommitGiveUpError, ArtificialException):
                    # a fault mid-observation/compaction aborts the round;
                    # rows are untouched — writers own them
                    with self._lock:
                        self.counts["compactor_conflicts"] += 1
            return
        while not self.stop.is_set() and time.monotonic() < deadline:
            time.sleep(self.cfg.compactor_pause_s)
            try:
                tw = TableWrite(table)
                try:
                    tw.compact(full=True)
                    msgs = tw.prepare_commit()
                finally:
                    tw.close()
                if not msgs:
                    continue
                store.new_commit().commit(ManifestCommittable(BATCH_COMMIT_IDENTIFIER, messages=msgs))
                with self._lock:
                    self.counts["compactor_commits"] += 1
            except (CommitConflictError, CommitGiveUpError, ArtificialException):
                # losing a compaction race (or a fault aborting one) is the
                # expected storm; rows are untouched — writers own them
                with self._lock:
                    self.counts["compactor_conflicts"] += 1

    def _expirer_loop(self, deadline: float) -> None:
        table = self._handle("soak-expirer")
        while not self.stop.is_set() and time.monotonic() < deadline:
            time.sleep(self.cfg.expire_every_s)
            try:
                table.expire_snapshots()
                with self._lock:
                    self.counts["expire_runs"] += 1
            except Exception:
                pass  # expiry is maintenance: faults here must never matter

    # ---- orchestration -------------------------------------------------
    def _spawn(self, name: str, fn, *args) -> threading.Thread:
        def guarded():
            try:
                fn(*args)
            except BaseException:
                self.errors.append(f"{name} crashed:\n{traceback.format_exc()}")

        t = threading.Thread(target=guarded, name=name, daemon=False)
        t.start()
        return t

    def run(self) -> dict:
        from ..fs.testing import FailingFileIO
        from ..metrics import registry, soak_metrics

        cfg = self.cfg
        if self._table is None:
            self.setup()
        # drop ONLY the soak{...} group so back-to-back runs in one process
        # (the bench's full-vs-seed contrast) report their own counters;
        # other groups keep accumulating and are reported as deltas
        with registry._lock:
            registry.groups.pop(("soak", ()), None)
        commit_group = registry.group("commit")
        base_retries = commit_group.counter("retries").count
        base_abandoned = commit_group.counter("buckets_abandoned").count
        base_conflicts = commit_group.counter("conflicts").count
        if cfg.fault_possibility > 0:
            FailingFileIO.reset(
                self.domain, max_fails=10**9, possibility=cfg.fault_possibility, seed=cfg.seed
            )
        t_start = time.monotonic()
        deadline = t_start + cfg.duration_s
        threads = [
            self._spawn(f"soak-writer-{w}", self._writer_loop, w, deadline)
            for w in range(cfg.writers)
        ]
        threads += [
            self._spawn(f"soak-reader-{r}", self._reader_loop, r, deadline)
            for r in range(cfg.readers)
        ]
        threads += [
            self._spawn(f"soak-getter-{g}", self._getter_loop, g, deadline)
            for g in range(cfg.getters)
        ]
        if cfg.getters and cfg.ryw:
            threads.append(self._spawn("soak-ryw", self._ryw_loop, deadline))
        if cfg.getters and cfg.get_server:
            threads.append(self._spawn("soak-get-overload", self._get_overload_loop, deadline))
        threads += [
            self._spawn(f"soak-sub-{s}", self._subscriber_loop, s, deadline)
            for s in range(cfg.subscribers)
        ]
        if cfg.subscriber_procs:
            threads.append(self._spawn("soak-subproc-super", self._subscriber_proc_loop, deadline))
        threads.append(self._spawn("soak-compactor", self._compactor_loop, deadline))
        threads.append(self._spawn("soak-expirer", self._expirer_loop, deadline))
        for t in threads:
            t.join(timeout=cfg.duration_s + max(120.0, cfg.block_timeout_ms / 1000.0 * 3))
        alive = [t.name for t in threads if t.is_alive()]
        if alive:
            self.stop.set()
            for t in threads:
                t.join(timeout=60.0)
            self.errors.append(f"threads failed to drain in time: {alive}")
        if self._sub_hub is not None:
            self._sub_hub.close()
        wall_s = time.monotonic() - t_start
        FailingFileIO.reset(self.domain, 0, 0)  # faults off for verification
        report = self._verify(wall_s)
        g = soak_metrics()
        g.counter("commits_retried").inc(commit_group.counter("retries").count - base_retries)
        report["commit_cas_retries"] = commit_group.counter("retries").count - base_retries
        report["commit_conflicts_detected"] = commit_group.counter("conflicts").count - base_conflicts
        report["commit_buckets_replanned"] = (
            commit_group.counter("buckets_abandoned").count - base_abandoned
        )
        if self.read_latencies_ms:
            p50 = float(np.percentile(self.read_latencies_ms, 50))
            p99 = float(np.percentile(self.read_latencies_ms, 99))
            g.gauge("read_p50_ms").set(p50)
            g.gauge("read_p99_ms").set(p99)
            report["read_p50_ms"] = round(p50, 2)
            report["read_p99_ms"] = round(p99, 2)
        else:
            report["read_p50_ms"] = report["read_p99_ms"] = None
        report["gets_per_sec"] = (
            round(self.counts["gets_served"] / wall_s, 1) if wall_s > 0 else None
        )
        if self.get_latencies_us:
            from ..metrics import get_metrics

            p99_us = float(np.percentile(self.get_latencies_us, 99))
            get_metrics().gauge("p99_us").set(p99_us)
            report["get_p50_us"] = round(float(np.percentile(self.get_latencies_us, 50)), 2)
            report["get_p99_us"] = round(p99_us, 2)
        else:
            report["get_p50_us"] = report["get_p99_us"] = None
        return report

    # ---- post-soak verification ----------------------------------------
    def _verify(self, wall_s: float) -> dict:
        from .oracle import verify_table_state

        expected = self.oracle.expected_final()
        state = verify_table_state(
            self._handle("soak-verify"),
            expected,
            self.local_root,
            self.errors,
            self.inconsistencies,
        )
        from ..metrics import soak_metrics

        g = soak_metrics()
        if self.cfg.subscriber_procs:
            try:
                self._verify_subproc_journal()
            except Exception:
                self.errors.append(f"subproc journal verification crashed:\n{traceback.format_exc()}")
        consistent = (
            not self.inconsistencies
            and not self.errors
            and state["lost_rows"] == 0
            and state["duplicated_rows"] == 0
            and state["wrong_values"] == 0
            and self.counts["gets_shed_untyped"] == 0  # overload must shed TYPED
            and self.counts["sub_shed_untyped"] == 0  # slow consumers shed TYPED
            and self.counts["sub_mismatches"] == 0  # every fold == pinned scan
            and state["record_count_matches"]
        )
        report = {
            "wall_s": round(wall_s, 2),
            "consistent": consistent,
            "accepted_commits": self.oracle.commits,
            "accepted_rows": self.oracle.accepted_rows,
            "expected_unique_keys": len(expected),
            "final_rows": state["final_rows"],
            "total_record_count": state["total_record_count"],
            "lost_rows": state["lost_rows"],
            "duplicated_rows": state["duplicated_rows"],
            "wrong_values": state["wrong_values"],
            "commits_per_sec": round(self.oracle.commits / wall_s, 2) if wall_s > 0 else None,
            "writes_throttled": g.counter("writes_throttled").count,
            "writes_rejected": g.counter("writes_rejected").count,
            "backpressure_ms_mean": round(g.histogram("backpressure_ms").mean, 2),
            "inconsistencies": self.inconsistencies[:10],
            "errors": self.errors[:5],
            **self.counts,
            "orphans_removed": state["orphans_removed"],
            "leaked_files": state["leaked_files"][:10],
            "leaked_file_count": len(state["leaked_files"]),
        }
        return report


def run_soak(base_dir: str, cfg: SoakConfig | None = None, domain: str | None = None) -> dict:
    """Create a fresh soak table under base_dir, run the harness, return the
    report dict (see SoakHarness._verify for fields)."""
    return SoakHarness(base_dir, cfg, domain=domain).run()


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json
    import tempfile

    ap = argparse.ArgumentParser(description="paimon-tpu production traffic soak")
    ap.add_argument("base_dir", nargs="?", default=None)
    ap.add_argument("--duration", type=float, default=45.0)
    ap.add_argument("--writers", type=int, default=3)
    ap.add_argument("--readers", type=int, default=2)
    ap.add_argument("--getters", type=int, default=0, help="batched point-get storm threads")
    ap.add_argument("--subscribers", type=int, default=0, help="CDC subscription storm threads")
    ap.add_argument("--subscriber-procs", type=int, default=0, help="subscriber OS processes (kill -9 + resume)")
    ap.add_argument("--fault-possibility", type=int, default=20, help="1/N ops fail (20 = 5%%)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mesh", action="store_true")
    ap.add_argument("--adaptive", action="store_true", help="adaptive (LUDA) churn compactor")
    ap.add_argument("--no-backpressure", action="store_true")
    ap.add_argument("--seed-mode", action="store_true", help="seed-like resilience: no IO/CAS retries")
    args = ap.parse_args(argv)
    base = args.base_dir or tempfile.mkdtemp(prefix="paimon_soak_")
    cfg = SoakConfig(
        duration_s=args.duration,
        writers=args.writers,
        readers=args.readers,
        getters=args.getters,
        subscribers=args.subscribers,
        subscriber_procs=args.subscriber_procs,
        fault_possibility=args.fault_possibility,
        seed=args.seed,
        mesh=args.mesh,
        adaptive=args.adaptive or os.environ.get("PAIMON_TPU_SOAK_ADAPTIVE", "") == "1",
        backpressure=not args.no_backpressure,
        resilient=not args.seed_mode,
    )
    report = run_soak(base, cfg)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["consistent"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
