"""Process-grain crash soak: kill -9 fault injection across OS processes.

The thread soak (service/soak.py) proves the resilience stack composes under
concurrent load — but a thread "crash" is a raised exception with intact
process state. The failure grain production traffic actually sees is a whole
TASK PROCESS dying mid-protocol: a SIGKILLed Flink/Spark JVM vanishes holding
buffered memtables, an in-flight offloaded flush, and half-written manifests,
and runs no cleanup at all. This harness reproduces exactly that:

  supervisor (this process)
  ├── writer-0  (OS process)  ── intent/ack journal-0 ──┐
  ├── writer-1  (OS process)  ── intent/ack journal-1 ──┤  shared warehouse
  ├── reader-0  (OS process)  ── read log ──────────────┤  filesystem only
  └── periodic orphan sweep + kill/respawn scheduling ──┘

Journal/oracle protocol. A writer process appends an INTENT record (round
identifier + the exact row set) to its own append-only journal and fsyncs it
BEFORE committing; after the commit lands it appends an ACK with the snapshot
id. The journal is the only state that survives the writer's death, and is
torn-tail tolerant (a kill can sever the last line). The truth about whether
a round landed is the SNAPSHOT CHAIN, not the journal: a writer killed at
`commit:snapshot-committed` dies after the CAS but before the ACK, so on
respawn (and again at final verification) every intent without an ACK is
resolved against the chain (`find_landed_append` — the same landed-snapshot
probe the thread soak uses in-thread). The end-of-soak oracle fold is the
union of landed rounds in snapshot-id order, and the final scan must equal
it exactly: no lost rows, no duplicated rows, `total_record_count` == unique
keys (a double-applied replay cannot hide), and the post-sweep disk file set
must equal the reachable closure (independent walk).

Crash injection. The supervisor arms children through the environment
(`PAIMON_TPU_CRASH_POINT=<point>:<nth>:kill` — resilience/faults.py): the
child really dies with `os._exit` mid-commit or mid-flush, leaving torn
`.tmp` siblings, orphaned manifests, and unreferenced level-0 files behind.
On top of the scripted kills a seeded timer SIGKILLs random writers. Every
death is respawned until the deadline; the respawned incarnation resumes
from its journal (next identifier, next key, landed update keys) — the
cross-process recovery the commit protocol promises but PR 8 never proved.

Run directly:  python -m paimon_tpu.service.proc_soak [base_dir] [flags]
Child roles:   python -m paimon_tpu.service.proc_soak writer|reader ...
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback
from dataclasses import dataclass, field

import numpy as np

from .soak import KEYSPACE, SCHEMA, find_landed_append

__all__ = [
    "ProcSoakConfig",
    "WriterJournal",
    "ProcSoakSupervisor",
    "run_proc_soak",
    "DEFAULT_SCRIPTED_KILLS",
]

# one kill per writer spawn while specs last, covering every commit-protocol
# point plus both writer-side flush points (nth >= 2 so each incarnation
# lands at least one commit before dying mid-operation)
DEFAULT_SCRIPTED_KILLS = (
    "commit:manifests-written:2:kill",
    "commit:snapshot-committed:2:kill",
    "flush:files-written:3:kill",
    "commit:before-manifests:2:kill",
    "flush:before-dispatch:2:kill",
)


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------
class WriterJournal:
    """Append-only intent/ack log, fsynced per record, torn-tail tolerant.

    Record kinds:
      intent     {"t":"intent","ident":i,"fresh":[start,n],"rows":{k:v}}
                 written (and fsynced) BEFORE the commit attempt
      ack        {"t":"ack","ident":i,"sid":s}   the commit landed at s
      recovered  {"t":"recovered","ident":i,"sid":s}  a respawned process
                 resolved a landed-but-unacked round from the snapshot chain
      abort      {"t":"abort","ident":i}  the round verifiably did not land
                 (shed by backpressure, or probe-negative after a failure)
    """

    def __init__(self, path: str):
        self.path = path
        self._fd: int | None = None

    def open(self) -> "WriterJournal":
        self._fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        return self

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def _append(self, obj: dict) -> None:
        assert self._fd is not None, "journal not open"
        os.write(self._fd, (json.dumps(obj, separators=(",", ":")) + "\n").encode())
        # the fsync is the protocol: the intent must be durable before the
        # commit it describes can possibly land
        os.fsync(self._fd)

    def intent(self, ident: int, fresh_start: int, n_fresh: int, rows: dict) -> None:
        self._append(
            {
                "t": "intent",
                "ident": ident,
                "fresh": [fresh_start, n_fresh],
                "rows": {str(k): v for k, v in rows.items()},
            }
        )

    def ack(self, ident: int, sid: int) -> None:
        self._append({"t": "ack", "ident": ident, "sid": sid})

    def recovered(self, ident: int, sid: int) -> None:
        self._append({"t": "recovered", "ident": ident, "sid": sid})

    def abort(self, ident: int) -> None:
        self._append({"t": "abort", "ident": ident})

    @staticmethod
    def read(path: str) -> list[dict]:
        """Parse the journal; a torn final line (the writer died mid-append)
        is dropped — its round resolves through the snapshot-chain probe."""
        if not os.path.exists(path):
            return []
        out = []
        with open(path, "rb") as f:
            for line in f.read().split(b"\n"):
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    break  # torn tail: nothing after it can be trusted
        return out


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------
@dataclass
class ProcSoakConfig:
    duration_s: float = 60.0
    writers: int = 2
    readers: int = 1
    buckets: int = 4
    seed: int = 0
    rows_per_commit: int = 300
    write_chunk_rows: int = 150
    update_fraction: float = 0.3
    compact_every: int = 5  # full-compact every Nth commit per writer
    # crash injection: one scripted spec per writer spawn while they last,
    # then a seeded random SIGKILL timer
    scripted_kills: tuple = DEFAULT_SCRIPTED_KILLS
    kill_period_s: float = 8.0  # mean seconds between random kills (0 = scripted only)
    sweep_period_s: float = 12.0  # periodic orphan sweep cadence (0 = final only)
    sweep_older_than_ms: int = 45_000  # an in-flight round's files must survive
    # flow control inside each writer process
    max_memory: int = 256 * 1024
    block_timeout_ms: int = 20_000
    # False = seed contrast: no CAS retries, no recovery probe in writers,
    # no orphan sweep (audit only) — demonstrably loses commits / leaks files
    resilient: bool = True
    table_options: dict = field(default_factory=dict)

    @classmethod
    def from_table_options(cls, options) -> "ProcSoakConfig":
        from ..options import CoreOptions

        o = options.options
        return cls(
            duration_s=o.get(CoreOptions.SOAK_PROCESS_DURATION) / 1000.0,
            writers=o.get(CoreOptions.SOAK_PROCESS_WRITERS),
            readers=o.get(CoreOptions.SOAK_PROCESS_READERS),
            kill_period_s=o.get(CoreOptions.SOAK_PROCESS_KILL_PERIOD) / 1000.0,
            sweep_period_s=o.get(CoreOptions.SOAK_PROCESS_SWEEP_PERIOD) / 1000.0,
        )


# ---------------------------------------------------------------------------
# child process: writer
# ---------------------------------------------------------------------------
def writer_main(args) -> int:
    from ..core.admission import WriteBufferController, WriterBackpressureError
    from ..core.commit import CommitConflictError, CommitGiveUpError
    from ..core.manifest import ManifestCommittable
    from ..data.batch import ColumnBatch
    from ..table import load_table
    from ..table.write import TableWrite

    if args.table.startswith(("fail:", "fail-s3", "latency:", "traceable:", "chaos:")):
        # test-harness schemes register on import (the chaos scheme also
        # applies PAIMON_TPU_CHAOS, so this child inherits the store shape)
        from ..fs import testing as _testing  # noqa: F401

    wid = args.wid
    user = f"psoak-w{wid}"
    rng = np.random.default_rng(args.seed * 7919 + wid * 104729 + args.incarnation)
    events = WriterJournal.read(args.journal)
    intents = [e for e in events if e["t"] == "intent"]
    resolved = {e["ident"] for e in events if e["t"] in ("ack", "recovered", "abort")}
    acked = {e["ident"] for e in events if e["t"] in ("ack", "recovered")}
    next_ident = max((e["ident"] for e in intents), default=0) + 1
    # fresh keys advance past every intent, landed or not: a key is never
    # reused for a different round, so the fold is unambiguous
    next_key = max((e["fresh"][0] + e["fresh"][1] for e in intents), default=0)
    landed_keys = [int(k) for e in intents if e["ident"] in acked for k in e["rows"]]

    table = load_table(args.table, commit_user=user)
    store = table.store
    journal = WriterJournal(args.journal).open()

    # ---- cross-process crash recovery ----------------------------------
    # the previous incarnation died holding intents with no ack: the
    # snapshot chain (not the exception we never saw) says whether they
    # landed. Resolving BEFORE writing anything new keeps the journal a
    # prefix-complete account of this writer's rounds.
    recovered = 0
    for e in intents:
        if e["ident"] in resolved:
            continue
        sid = find_landed_append(store, user, e["ident"]) if args.resilient else None
        if sid is not None:
            journal.recovered(e["ident"], sid)
            landed_keys.extend(int(k) for k in e["rows"])
            recovered += 1
        else:
            journal.abort(e["ident"])
    if recovered:
        print(f"writer {wid} incarnation {args.incarnation}: recovered {recovered} landed-unacked round(s)", flush=True)

    ctrl = None
    if args.max_memory > 0:
        ctrl = WriteBufferController(
            args.max_memory,
            stop_trigger=0.6,
            block_timeout_ms=args.block_timeout_ms,
            max_pending_flushes=2,
        )

    rounds = 0
    while rounds < args.max_rounds and not os.path.exists(args.stop_file):
        ident = next_ident
        next_ident += 1
        rounds += 1
        n_upd = int(args.rows_per_commit * args.update_fraction) if landed_keys else 0
        n_new = args.rows_per_commit - n_upd
        fresh = [wid * KEYSPACE + next_key + i for i in range(n_new)]
        upd = (
            [landed_keys[i] for i in rng.integers(0, len(landed_keys), n_upd)] if n_upd else []
        )
        keys = fresh + upd
        vals = (ident * 1_000.0 + wid) + rng.random(len(keys))
        rows = dict(zip(keys, [float(v) for v in vals]))  # unique keys per round
        journal.intent(ident, next_key, n_new, rows)
        next_key += n_new
        try:
            tw = TableWrite(table, buffer_controller=ctrl)
            try:
                ks = list(rows)
                vs = [rows[k] for k in ks]
                for i in range(0, len(ks), args.chunk_rows):
                    tw.write(
                        ColumnBatch.from_pydict(
                            SCHEMA, {"k": ks[i : i + args.chunk_rows], "v": vs[i : i + args.chunk_rows]}
                        )
                    )
                if args.compact_every and ident % args.compact_every == 0:
                    tw.compact(full=True)
                msgs = tw.prepare_commit()
            finally:
                tw.close()
            sids = store.new_commit().commit(ManifestCommittable(ident, messages=msgs))
            if sids:
                journal.ack(ident, sids[0])
                landed_keys.extend(fresh)
            else:
                journal.abort(ident)
        except WriterBackpressureError:
            # shed: rejected before any byte buffered — verifiably not landed
            journal.abort(ident)
        except (CommitConflictError, CommitGiveUpError):
            # the COMPACT half lost a cross-process race (or, seed mode, the
            # first CAS loss aborted) — the APPEND half may still have landed
            sid = find_landed_append(store, user, ident) if args.resilient else None
            if sid is not None:
                journal.ack(ident, sid)
                landed_keys.extend(fresh)
            else:
                journal.abort(ident)
    journal.close()
    return 0


# ---------------------------------------------------------------------------
# child process: reader
# ---------------------------------------------------------------------------
def reader_main(args) -> int:
    from ..table import load_table

    if args.table.startswith(("fail:", "fail-s3", "latency:", "traceable:", "chaos:")):
        from ..fs import testing as _testing  # noqa: F401

    table = load_table(args.table, commit_user=f"psoak-r{args.rid}")
    sm = table.store.snapshot_manager
    ok = errors = 0
    with open(args.log, "a", buffering=1) as log:
        while not os.path.exists(args.stop_file):
            try:
                sid = sm.latest_snapshot_id()
            except Exception:
                sid = None
            if sid is None:
                time.sleep(0.05)
                continue
            try:
                t = table.copy({"scan.snapshot-id": str(sid)})
                rb = t.new_read_builder()
                batch = rb.new_read().read_all(rb.new_scan().plan())
                ks = batch.column("k").values.tolist()
                if len(ks) != len(set(ks)):
                    errors += 1
                    log.write(json.dumps({"t": "dup-keys", "sid": sid, "rows": len(ks)}) + "\n")
                else:
                    ok += 1
            except Exception as exc:  # noqa: BLE001 — every pinned-read error is a finding
                errors += 1
                log.write(json.dumps({"t": "err", "sid": sid, "exc": repr(exc)}) + "\n")
            time.sleep(0.02)
        log.write(json.dumps({"t": "done", "reads_ok": ok, "read_errors": errors}) + "\n")
    return 0


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------
class ProcSoakSupervisor:
    def __init__(self, base_dir: str, cfg: ProcSoakConfig | None = None):
        self.cfg = cfg or ProcSoakConfig()
        self.base_dir = str(base_dir)
        self.table_root = os.path.join(self.base_dir, "proc_soak_table")
        # journals/logs live OUTSIDE the table root: the end-of-soak disk
        # audit walks the table root and must only ever see table files
        self.run_dir = os.path.join(self.base_dir, "proc_soak_run")
        self.stop_file = os.path.join(self.run_dir, "stop")
        self.errors: list[str] = []
        self.inconsistencies: list[dict] = []
        self.counts = {
            "procs_spawned": 0,
            "procs_killed": 0,
            "procs_respawned": 0,
            "writer_errors": 0,
            "sweeps_during_soak": 0,
        }
        self._kill_cursor = 0
        self._incarnations: dict[tuple, int] = {}

    # ---- setup ---------------------------------------------------------
    def _table_options(self) -> dict:
        cfg = self.cfg
        opts = {
            "bucket": str(cfg.buckets),
            # small memtables force real flushes (and the offloaded flush
            # worker) inside every writer process
            "write-buffer-rows": str(max(cfg.write_chunk_rows * 2, 64)),
            "commit.retry-backoff": "2 ms",
        }
        if cfg.resilient:
            opts["commit.max-retries"] = "30"
        else:
            # the seed contrast: the first CAS loss aborts the round
            opts.update({"commit.max-retries": "0", "fs.retry.max-attempts": "1"})
        opts.update(cfg.table_options)
        return opts

    def setup(self):
        from ..core.schema import SchemaManager
        from ..fs import get_file_io

        os.makedirs(self.run_dir, exist_ok=True)
        io = get_file_io(self.table_root)
        SchemaManager(io, self.table_root).create_table(
            SCHEMA, primary_keys=["k"], options=self._table_options()
        )

    def _fresh_table(self):
        from ..table import load_table

        return load_table(self.table_root, commit_user="psoak-supervisor")

    # ---- child process plumbing ----------------------------------------
    def _child_env(self, crash_spec: str | None) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PAIMON_TPU_CRASH_POINT", None)
        if crash_spec:
            env["PAIMON_TPU_CRASH_POINT"] = crash_spec
        # the package must resolve in the child no matter where the
        # supervisor was launched from
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        return env

    def _spawn_writer(self, wid: int) -> subprocess.Popen:
        from ..metrics import soak_metrics

        cfg = self.cfg
        crash_spec = None
        if self._kill_cursor < len(cfg.scripted_kills):
            crash_spec = cfg.scripted_kills[self._kill_cursor]
            self._kill_cursor += 1
        inc = self._incarnations.get(("w", wid), 0)
        self._incarnations[("w", wid)] = inc + 1
        log = open(os.path.join(self.run_dir, f"writer-{wid}.{inc}.log"), "wb")
        cmd = [
            sys.executable,
            "-m",
            "paimon_tpu.service.proc_soak",
            "writer",
            "--table", self.table_root,
            "--wid", str(wid),
            "--journal", os.path.join(self.run_dir, f"journal-{wid}.jsonl"),
            "--stop-file", self.stop_file,
            "--seed", str(cfg.seed),
            "--incarnation", str(inc),
            "--rows-per-commit", str(cfg.rows_per_commit),
            "--chunk-rows", str(cfg.write_chunk_rows),
            "--update-fraction", str(cfg.update_fraction),
            "--compact-every", str(cfg.compact_every),
            "--max-memory", str(cfg.max_memory),
            "--block-timeout-ms", str(cfg.block_timeout_ms),
        ]
        if not cfg.resilient:
            cmd.append("--seed-mode")
        p = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, env=self._child_env(crash_spec)
        )
        log.close()  # the child holds the fd
        self.counts["procs_spawned"] += 1
        soak_metrics().counter("procs_spawned").inc()
        return p

    def _spawn_reader(self, rid: int) -> subprocess.Popen:
        from ..metrics import soak_metrics

        inc = self._incarnations.get(("r", rid), 0)
        self._incarnations[("r", rid)] = inc + 1
        log = open(os.path.join(self.run_dir, f"reader-{rid}.{inc}.log"), "wb")
        cmd = [
            sys.executable,
            "-m",
            "paimon_tpu.service.proc_soak",
            "reader",
            "--table", self.table_root,
            "--rid", str(rid),
            "--log", os.path.join(self.run_dir, f"reads-{rid}.jsonl"),
            "--stop-file", self.stop_file,
        ]
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT, env=self._child_env(None))
        log.close()
        self.counts["procs_spawned"] += 1
        soak_metrics().counter("procs_spawned").inc()
        return p

    def _reap(self, role: str, idx: int, rc: int) -> None:
        from ..metrics import soak_metrics
        from ..resilience.faults import KILL_EXIT_CODE

        if rc == KILL_EXIT_CODE or rc < 0:
            # armed crash-point death (os._exit 137) or supervisor SIGKILL
            self.counts["procs_killed"] += 1
            soak_metrics().counter("procs_killed").inc()
        elif rc != 0:
            self.counts["writer_errors"] += 1
            tail = ""
            inc = self._incarnations.get((role[0], idx), 1) - 1
            log = os.path.join(self.run_dir, f"{role}-{idx}.{inc}.log")
            if os.path.exists(log):
                with open(log, "rb") as f:
                    tail = f.read()[-2000:].decode(errors="replace")
            self.errors.append(f"{role} {idx} exited rc={rc}:\n{tail}")

    # ---- run -----------------------------------------------------------
    def run(self) -> dict:
        from ..metrics import soak_metrics
        from ..resilience.orphan import remove_orphan_files

        cfg = self.cfg
        g = soak_metrics()
        if not os.path.exists(self.table_root):
            self.setup()
        rng = np.random.default_rng(cfg.seed * 31 + 17)
        t_start = time.monotonic()
        deadline = t_start + cfg.duration_s
        writers = {w: self._spawn_writer(w) for w in range(cfg.writers)}
        readers = {r: self._spawn_reader(r) for r in range(cfg.readers)}
        next_kill = (
            t_start + float(rng.uniform(0.5, 1.5)) * cfg.kill_period_s
            if cfg.kill_period_s > 0
            else float("inf")
        )
        next_sweep = (
            t_start + cfg.sweep_period_s
            if (cfg.sweep_period_s > 0 and cfg.resilient)
            else float("inf")
        )
        while time.monotonic() < deadline:
            for wid, p in list(writers.items()):
                rc = p.poll()
                if rc is None:
                    continue
                self._reap("writer", wid, rc)
                writers[wid] = self._spawn_writer(wid)
                self.counts["procs_respawned"] += 1
                g.counter("procs_respawned").inc()
            for rid, p in list(readers.items()):
                rc = p.poll()
                if rc is None:
                    continue
                self._reap("reader", rid, rc)
                readers[rid] = self._spawn_reader(rid)
                self.counts["procs_respawned"] += 1
                g.counter("procs_respawned").inc()
            now = time.monotonic()
            if now >= next_kill and writers:
                victim = writers[int(rng.integers(0, cfg.writers))]
                if victim.poll() is None:
                    victim.kill()  # SIGKILL: reaped (and counted) next loop
                next_kill = now + float(rng.uniform(0.5, 1.5)) * cfg.kill_period_s
            if now >= next_sweep:
                # the mid-soak sweep: old enough that no in-flight round's
                # files qualify, young enough to reclaim early kills' orphans
                try:
                    remove_orphan_files(self._fresh_table(), older_than_millis=cfg.sweep_older_than_ms)
                    self.counts["sweeps_during_soak"] += 1
                except Exception:
                    self.errors.append(f"mid-soak sweep crashed:\n{traceback.format_exc()}")
                next_sweep = now + cfg.sweep_period_s
            time.sleep(0.15)
        # ---- drain -----------------------------------------------------
        with open(self.stop_file, "w") as f:
            f.write("stop")
        drain_deadline = time.monotonic() + max(60.0, cfg.block_timeout_ms / 1000.0 * 2)
        procs = list(writers.items()) + [(f"r{r}", p) for r, p in readers.items()]
        for name, p in procs:
            timeout = max(1.0, drain_deadline - time.monotonic())
            try:
                rc = p.wait(timeout=timeout)
                if rc not in (0, None):
                    self._reap("writer" if not str(name).startswith("r") else "reader",
                               int(str(name).lstrip("r")), rc)
            except subprocess.TimeoutExpired:
                self.errors.append(f"proc {name} failed to drain; killed")
                p.kill()
                p.wait(timeout=30)
        wall_s = time.monotonic() - t_start
        return self._verify(wall_s)

    # ---- verification --------------------------------------------------
    def _verify(self, wall_s: float) -> dict:
        from .oracle import fold_landed_rounds, read_client_logs, verify_table_state

        table = self._fresh_table()
        landed, stats = fold_landed_rounds(
            table.store,
            {
                f"psoak-w{wid}": os.path.join(self.run_dir, f"journal-{wid}.jsonl")
                for wid in range(self.cfg.writers)
            },
            user_prefix="psoak-w",
            inconsistencies=self.inconsistencies,
        )
        expected: dict = {}
        for sid in sorted(landed):
            expected.update(landed[sid])
        # resilient: sweep at threshold 0 then audit (file set must equal
        # the closure). Seed contrast: audit only — the leak list IS the
        # result being demonstrated.
        state = verify_table_state(
            table,
            expected,
            self.table_root,
            self.errors,
            self.inconsistencies,
            sweep=self.cfg.resilient,
        )
        reads = read_client_logs(
            [os.path.join(self.run_dir, f"reads-{rid}.jsonl") for rid in range(self.cfg.readers)]
        )
        if stats["double_applied"]:
            self.inconsistencies.append({"kind": "double-applied", "rounds": stats["double_applied"]})
        consistent = (
            not self.errors
            and not self.inconsistencies
            and state["lost_rows"] == 0
            and state["duplicated_rows"] == 0
            and state["wrong_values"] == 0
            and reads["read_errors"] == 0
            and state["record_count_matches"]
            and (not self.cfg.resilient or len(state["leaked_files"]) == 0)
        )
        return {
            "wall_s": round(wall_s, 2),
            "consistent": consistent,
            "resilient": self.cfg.resilient,
            "accepted_commits": len(landed),
            "expected_unique_keys": len(expected),
            "final_rows": state["final_rows"],
            "total_record_count": state["total_record_count"],
            "lost_rows": state["lost_rows"],
            "duplicated_rows": state["duplicated_rows"],
            "wrong_values": state["wrong_values"],
            "commits_per_sec": round(len(landed) / wall_s, 2) if wall_s > 0 else None,
            **stats,
            **self.counts,
            **reads,
            "orphans_removed": state["orphans_removed"],
            "leaked_file_count": len(state["leaked_files"]),
            "leaked_files": state["leaked_files"][:10],
            "inconsistencies": self.inconsistencies[:10],
            "errors": self.errors[:5],
        }


def run_proc_soak(base_dir: str, cfg: ProcSoakConfig | None = None) -> dict:
    """Create a fresh process-soak table under base_dir, run the supervisor,
    return the report dict (see ProcSoakSupervisor._verify for fields)."""
    return ProcSoakSupervisor(base_dir, cfg).run()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def _writer_args(argv):
    import argparse

    ap = argparse.ArgumentParser(prog="proc_soak writer")
    ap.add_argument("--table", required=True)
    ap.add_argument("--wid", type=int, required=True)
    ap.add_argument("--journal", required=True)
    ap.add_argument("--stop-file", required=True, dest="stop_file")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--incarnation", type=int, default=0)
    ap.add_argument("--rows-per-commit", type=int, default=300, dest="rows_per_commit")
    ap.add_argument("--chunk-rows", type=int, default=150, dest="chunk_rows")
    ap.add_argument("--update-fraction", type=float, default=0.3, dest="update_fraction")
    ap.add_argument("--compact-every", type=int, default=5, dest="compact_every")
    ap.add_argument("--max-rounds", type=int, default=10**9, dest="max_rounds")
    ap.add_argument("--max-memory", type=int, default=0, dest="max_memory")
    ap.add_argument("--block-timeout-ms", type=int, default=20_000, dest="block_timeout_ms")
    ap.add_argument("--seed-mode", action="store_true", dest="seed_mode")
    args = ap.parse_args(argv)
    args.resilient = not args.seed_mode
    return args


def _reader_args(argv):
    import argparse

    ap = argparse.ArgumentParser(prog="proc_soak reader")
    ap.add_argument("--table", required=True)
    ap.add_argument("--rid", type=int, required=True)
    ap.add_argument("--log", required=True)
    ap.add_argument("--stop-file", required=True, dest="stop_file")
    return ap.parse_args(argv)


def main(argv: list[str] | None = None) -> int:
    import argparse
    import tempfile

    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "writer":
        return writer_main(_writer_args(argv[1:]))
    if argv and argv[0] == "reader":
        return reader_main(_reader_args(argv[1:]))

    ap = argparse.ArgumentParser(description="paimon-tpu process-grain crash soak")
    ap.add_argument("base_dir", nargs="?", default=None)
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--writers", type=int, default=2)
    ap.add_argument("--readers", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--scripted-kills",
        default=",".join(DEFAULT_SCRIPTED_KILLS),
        help="comma-separated PAIMON_TPU_CRASH_POINT specs, one per writer spawn",
    )
    ap.add_argument("--kill-period", type=float, default=8.0, help="mean s between random SIGKILLs (0=off)")
    ap.add_argument("--sweep-period", type=float, default=12.0)
    ap.add_argument("--rows-per-commit", type=int, default=300)
    ap.add_argument("--min-kills", type=int, default=0, help="fail unless >= N kills were survived")
    ap.add_argument("--seed-mode", action="store_true", help="seed-like config: no retries, no sweep, no recovery")
    args = ap.parse_args(argv)
    base = args.base_dir or tempfile.mkdtemp(prefix="paimon_proc_soak_")
    cfg = ProcSoakConfig(
        duration_s=args.duration,
        writers=args.writers,
        readers=args.readers,
        seed=args.seed,
        scripted_kills=tuple(s for s in args.scripted_kills.split(",") if s.strip()),
        kill_period_s=args.kill_period,
        sweep_period_s=args.sweep_period,
        rows_per_commit=args.rows_per_commit,
        resilient=not args.seed_mode,
    )
    report = run_proc_soak(base, cfg)
    print(json.dumps(report, indent=2, default=str))
    ok = report["consistent"] and report["procs_killed"] >= args.min_kills
    if report["procs_killed"] < args.min_kills:
        print(
            f"FAIL: only {report['procs_killed']} kills survived (expected >= {args.min_kills})",
            file=sys.stderr,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
