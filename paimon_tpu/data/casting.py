"""Runtime casts: schema-evolution reads + the full explicit matrix.

Parity: /root/reference/paimon-core/.../casting/ (CastExecutors + 30 cast
rules: NumericPrimitiveCastRule, StringTo*/.*ToString, Boolean<->Numeric,
Decimal rules, Date/Time/Timestamp rules) and CastedRow. Two entry points:

  can_cast / cast_column          — the *evolution* gate: only widening casts,
                                    schema evolution must never silently wrap
                                    or truncate stored data (SchemaManager
                                    rejects narrowing updates the same way)
  can_cast_explicit / cast_explicit — the full CastExecutors matrix for
                                    explicit expressions (MERGE INTO/UPDATE
                                    assignments, CDC coercion): narrowing
                                    truncates like Java, strings parse, with
                                    nulls for unparseable values

Vectorized: one numpy conversion per column where possible; string parsing
falls back to a per-row loop (same as the reference's per-record executor).

Internal value representations: DATE = int32 days since epoch, TIMESTAMP =
int64 micros, DECIMAL = unscaled int64 (scale on the type).
"""

from __future__ import annotations

import datetime

import numpy as np

from ..types import DataType, TypeRoot
from .batch import Column

__all__ = ["cast_column", "can_cast", "cast_explicit", "can_cast_explicit"]

_NUMERIC_ORDER = [
    TypeRoot.TINYINT,
    TypeRoot.SMALLINT,
    TypeRoot.INT,
    TypeRoot.BIGINT,
    TypeRoot.FLOAT,
    TypeRoot.DOUBLE,
]
_STRINGS = (TypeRoot.CHAR, TypeRoot.VARCHAR)
_BINARIES = (TypeRoot.BINARY, TypeRoot.VARBINARY)
_TIMESTAMPS = (TypeRoot.TIMESTAMP, TypeRoot.TIMESTAMP_LTZ)
_US_PER_DAY = 86_400_000_000


def can_cast(src: DataType, dst: DataType) -> bool:
    """Only *widening* casts are allowed — schema evolution must never
    silently wrap or truncate stored data (reference SchemaManager rejects
    narrowing updates the same way)."""
    if src.root == dst.root:
        return True
    if src.root in _NUMERIC_ORDER and dst.root in _NUMERIC_ORDER:
        return _NUMERIC_ORDER.index(src.root) < _NUMERIC_ORDER.index(dst.root)
    if dst.root in _STRINGS:
        return True  # anything can render to string
    if src.root == TypeRoot.DATE and dst.root in _TIMESTAMPS:
        return True
    return False


def can_cast_explicit(src: DataType, dst: DataType) -> bool:
    """The full CastExecutors matrix."""
    s, d = src.root, dst.root
    if s == d:
        return True
    if can_cast(src, dst):
        return True
    numericish = set(_NUMERIC_ORDER) | {TypeRoot.DECIMAL}
    if s in numericish and d in numericish:
        return True
    if s == TypeRoot.BOOLEAN and (d in numericish or d in _STRINGS):
        return True
    if d == TypeRoot.BOOLEAN and (s in numericish or s in _STRINGS):
        return True
    if s in _STRINGS and (
        d in numericish or d in _BINARIES or d == TypeRoot.DATE or d in _TIMESTAMPS
    ):
        return True
    if s in _BINARIES and d in _STRINGS:
        return True
    if s in _TIMESTAMPS and (d == TypeRoot.DATE or d in _TIMESTAMPS or d in _STRINGS):
        return True
    if s == TypeRoot.DATE and (d in _TIMESTAMPS or d in _STRINGS):
        return True
    return False


def cast_column(col: Column, src: DataType, dst: DataType) -> Column:
    """Evolution cast (widening only)."""
    if src.root == dst.root:
        return col
    if not can_cast(src, dst):
        raise ValueError(f"cannot cast {src.root} -> {dst.root}")
    return _cast(col, src, dst)


def cast_explicit(col: Column, src: DataType, dst: DataType) -> Column:
    """Explicit cast with the full matrix (Java truncation semantics for
    narrowing; unparseable strings become null)."""
    if src.root == dst.root and src.root != TypeRoot.DECIMAL:
        if src.root in _STRINGS and _bounded_string(dst):
            return _string_to_string(col, dst)
        return col
    if not can_cast_explicit(src, dst):
        raise ValueError(f"cannot cast {src.root} -> {dst.root}")
    return _cast(col, src, dst)


def _cast(col: Column, src: DataType, dst: DataType) -> Column:
    s, d = src.root, dst.root
    v, validity = col.values, col.validity

    if d in _STRINGS:
        return _to_string(col, src, dst)
    if s in _STRINGS:
        return _from_string(col, src, dst)
    if s == TypeRoot.BOOLEAN and d in _NUMERIC_ORDER:
        return Column(v.astype(dst.numpy_dtype()), validity)
    if d == TypeRoot.BOOLEAN:
        return Column(v != 0, validity)
    if s == TypeRoot.DATE and d in _TIMESTAMPS:
        return Column(v.astype(np.int64) * _US_PER_DAY, validity)
    if s in _TIMESTAMPS and d == TypeRoot.DATE:
        return Column(np.floor_divide(v.astype(np.int64), _US_PER_DAY).astype(np.int32), validity)
    if s in _TIMESTAMPS and d in _TIMESTAMPS:
        return Column(v.astype(np.int64), validity)
    if s == TypeRoot.DECIMAL and d == TypeRoot.DECIMAL:
        return Column(_rescale(v.astype(np.int64), src.scale or 0, dst.scale or 0), validity)
    if s == TypeRoot.DECIMAL and d in _NUMERIC_ORDER:
        scale = src.scale or 0
        if dst.numpy_dtype().kind == "f":
            return Column((v.astype(np.float64) / 10**scale).astype(dst.numpy_dtype()), validity)
        u = v.astype(np.int64)
        # truncate toward zero like Java's BigDecimal narrowing (-1.5 -> -1)
        q = np.where(u < 0, -((-u) // 10**scale), u // 10**scale)
        return Column(q.astype(dst.numpy_dtype()), validity)
    if s in _NUMERIC_ORDER and d == TypeRoot.DECIMAL:
        scale = dst.scale or 0
        if v.dtype.kind == "f":
            scaled = v.astype(np.float64) * 10**scale
            # HALF_UP (away from zero), matching _rescale and the string path
            return Column((np.sign(scaled) * np.floor(np.abs(scaled) + 0.5)).astype(np.int64), validity)
        return Column(v.astype(np.int64) * 10**scale, validity)
    if s == TypeRoot.BOOLEAN and d == TypeRoot.DECIMAL:
        return Column(v.astype(np.int64) * 10 ** (dst.scale or 0), validity)
    if s in _BINARIES and d in _BINARIES:
        return col
    # numeric <-> numeric: any direction, Java truncation via astype
    return Column(v.astype(dst.numpy_dtype()), validity)


def _rescale(unscaled: np.ndarray, s_from: int, s_to: int) -> np.ndarray:
    if s_to == s_from:
        return unscaled
    if s_to > s_from:
        return unscaled * 10 ** (s_to - s_from)
    div = 10 ** (s_from - s_to)
    # round half away from zero like BigDecimal.setScale(HALF_UP)
    q, r = np.divmod(np.abs(unscaled), div)
    q = q + (2 * r >= div)
    return np.where(unscaled < 0, -q, q)


def _to_string(col: Column, src: DataType, dst: DataType) -> Column:
    v = col.values
    valid = col.valid_mask()
    out = np.empty(len(v), dtype=object)
    s = src.root
    for i in range(len(v)):
        if not valid[i]:
            out[i] = None
        elif s == TypeRoot.BOOLEAN:
            out[i] = "true" if v[i] else "false"
        elif s == TypeRoot.DATE:
            out[i] = (datetime.date(1970, 1, 1) + datetime.timedelta(days=int(v[i]))).isoformat()
        elif s in _TIMESTAMPS:
            dt = datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=int(v[i]))
            out[i] = dt.isoformat(sep=" ")
        elif s == TypeRoot.DECIMAL:
            scale = src.scale or 0
            x = int(v[i])
            if scale == 0:
                out[i] = str(x)
            else:
                sign = "-" if x < 0 else ""
                x = abs(x)
                out[i] = f"{sign}{x // 10**scale}.{x % 10**scale:0{scale}d}"
        elif s in _BINARIES:
            out[i] = bytes(v[i]).decode("utf-8", "replace")
        else:
            out[i] = str(v[i])
    c = Column(out, col.validity)
    if _bounded_string(dst):
        return _string_to_string(c, dst)
    return c


def _bounded_string(dst: DataType) -> bool:
    from ..types import _MAX_LEN

    return dst.root in _STRINGS and dst.length is not None and dst.length < _MAX_LEN


def _string_to_string(col: Column, dst: DataType) -> Column:
    """CHAR(n)/VARCHAR(n): truncate over-length values (reference
    StringToStringCastRule)."""
    n = dst.length
    v = col.values
    out = np.empty(len(v), dtype=object)
    for i in range(len(v)):
        x = v[i]
        out[i] = x[:n] if isinstance(x, str) and len(x) > n else x
    return Column(out, col.validity)


def _from_string(col: Column, src: DataType, dst: DataType) -> Column:
    v = col.values
    valid = col.valid_mask().copy()
    d = dst.root
    if d in _BINARIES:
        out = np.empty(len(v), dtype=object)
        for i in range(len(v)):
            out[i] = v[i].encode("utf-8") if valid[i] else None
        return Column(out, col.validity)
    if d == TypeRoot.BOOLEAN:
        out = np.zeros(len(v), dtype=np.bool_)
        truthy = {"true", "t", "yes", "y", "1"}
        falsy = {"false", "f", "no", "n", "0"}
        for i in range(len(v)):
            if valid[i]:
                t = str(v[i]).strip().lower()
                if t in truthy:
                    out[i] = True
                elif t in falsy:
                    out[i] = False
                else:
                    valid[i] = False
        return Column(out, valid if not valid.all() else None)
    if d == TypeRoot.DATE:
        out = np.zeros(len(v), dtype=np.int32)
        epoch = datetime.date(1970, 1, 1)
        for i in range(len(v)):
            if valid[i]:
                try:
                    out[i] = (datetime.date.fromisoformat(str(v[i]).strip()) - epoch).days
                except ValueError:
                    valid[i] = False
        return Column(out, valid if not valid.all() else None)
    if d in _TIMESTAMPS:
        out = np.zeros(len(v), dtype=np.int64)
        epoch = datetime.datetime(1970, 1, 1)
        for i in range(len(v)):
            if valid[i]:
                try:
                    t = str(v[i]).strip().replace("T", " ")
                    dt = datetime.datetime.fromisoformat(t)
                    out[i] = int((dt - epoch).total_seconds() * 1_000_000)
                except ValueError:
                    valid[i] = False
        return Column(out, valid if not valid.all() else None)
    if d == TypeRoot.DECIMAL:
        scale = dst.scale or 0
        out = np.zeros(len(v), dtype=np.int64)
        from decimal import ROUND_HALF_UP, Decimal, InvalidOperation

        for i in range(len(v)):
            if valid[i]:
                try:
                    out[i] = int(Decimal(str(v[i]).strip()).scaleb(scale).to_integral_value(rounding=ROUND_HALF_UP))
                except (InvalidOperation, ValueError, OverflowError):
                    valid[i] = False
        return Column(out, valid if not valid.all() else None)
    # string -> numeric
    tgt = dst.numpy_dtype()
    out = np.zeros(len(v), dtype=tgt)
    for i in range(len(v)):
        if valid[i]:
            try:
                if tgt.kind == "f":
                    out[i] = tgt.type(float(v[i]))
                else:
                    s = str(v[i]).strip()
                    # exact integer parse first: int-via-float corrupts
                    # values past 2^53
                    try:
                        out[i] = tgt.type(int(s))
                    except ValueError:
                        out[i] = tgt.type(int(float(s)))
            except (TypeError, ValueError, OverflowError):
                valid[i] = False
    return Column(out, valid if not valid.all() else None)
