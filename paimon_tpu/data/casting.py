"""Runtime casts for schema evolution reads.

Parity: /root/reference/paimon-common/.../casting/CastExecutors.java +
CastedRow — when a data file was written under an older schema, its columns
are cast to the current field types while reading. Vectorized: one numpy
conversion per column, no per-row dispatch.
"""

from __future__ import annotations

import numpy as np

from ..types import DataType, TypeRoot
from .batch import Column

__all__ = ["cast_column", "can_cast"]

_NUMERIC_ORDER = [
    TypeRoot.TINYINT,
    TypeRoot.SMALLINT,
    TypeRoot.INT,
    TypeRoot.BIGINT,
    TypeRoot.FLOAT,
    TypeRoot.DOUBLE,
]


def can_cast(src: DataType, dst: DataType) -> bool:
    """Only *widening* casts are allowed — schema evolution must never
    silently wrap or truncate stored data (reference SchemaManager rejects
    narrowing updates the same way)."""
    if src.root == dst.root:
        return True
    if src.root in _NUMERIC_ORDER and dst.root in _NUMERIC_ORDER:
        return _NUMERIC_ORDER.index(src.root) < _NUMERIC_ORDER.index(dst.root)
    if dst.root in (TypeRoot.VARCHAR, TypeRoot.CHAR):
        return True  # anything can render to string
    if src.root == TypeRoot.DATE and dst.root in (TypeRoot.TIMESTAMP, TypeRoot.TIMESTAMP_LTZ):
        return True
    return False


def cast_column(col: Column, src: DataType, dst: DataType) -> Column:
    if src.root == dst.root:
        return col
    if not can_cast(src, dst):
        raise ValueError(f"cannot cast {src.root} -> {dst.root}")
    v, validity = col.values, col.validity
    if dst.root in (TypeRoot.VARCHAR, TypeRoot.CHAR):
        out = np.empty(len(v), dtype=object)
        valid = col.valid_mask()
        for i in range(len(v)):
            out[i] = str(v[i]) if valid[i] else None
        return Column(out, validity)
    if src.root in (TypeRoot.VARCHAR, TypeRoot.CHAR) and dst.root in _NUMERIC_ORDER:
        tgt = dst.numpy_dtype()
        out = np.zeros(len(v), dtype=tgt)
        valid = col.valid_mask().copy()
        for i in range(len(v)):
            if valid[i]:
                try:
                    out[i] = tgt.type(float(v[i])) if tgt.kind == "f" else tgt.type(int(float(v[i])))
                except (TypeError, ValueError):
                    valid[i] = False
        return Column(out, valid if not valid.all() else None)
    if src.root == TypeRoot.DATE and dst.root in (TypeRoot.TIMESTAMP, TypeRoot.TIMESTAMP_LTZ):
        # days -> micros since epoch
        return Column((v.astype(np.int64) * 86_400_000_000), validity)
    # numeric widening/narrowing
    return Column(v.astype(dst.numpy_dtype()), validity)
