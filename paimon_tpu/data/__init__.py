"""L1: the data kernel — column batches, normalized keys, predicates, casts.

Where the reference's currency is the row (BinaryRow over MemorySegments,
/root/reference/paimon-common/.../data/BinaryRow.java:55), ours is the column
batch: dense numpy vectors host-side that transfer to TPU HBM as jax arrays.
Rows exist only at API edges (to_pylist / from_pylist).
"""

from .batch import Column, ColumnBatch, concat_batches
from .keys import NormalizedKeys, encode_key_lanes
from .predicate import (
    Predicate,
    PredicateBuilder,
    and_,
    equal,
    greater_or_equal,
    greater_than,
    in_,
    is_not_null,
    is_null,
    less_or_equal,
    less_than,
    not_equal,
    or_,
    starts_with,
)

__all__ = [
    "Column",
    "ColumnBatch",
    "concat_batches",
    "NormalizedKeys",
    "encode_key_lanes",
    "Predicate",
    "PredicateBuilder",
    "and_",
    "or_",
    "equal",
    "not_equal",
    "less_than",
    "less_or_equal",
    "greater_than",
    "greater_or_equal",
    "is_null",
    "is_not_null",
    "in_",
    "starts_with",
]
