"""Predicate AST with two vectorized evaluators.

Capability parity with the reference predicate kernel
(/root/reference/paimon-common/.../predicate/Predicate.java, LeafPredicate /
CompoundPredicate / PredicateBuilder, ~30 leaf functions): the same AST is
evaluated (a) against data as a dense boolean mask over a ColumnBatch — one
numpy/XLA expression per leaf, no per-row interpretation — and (b) against
per-file / per-field min/max/null-count stats to decide whether a file can be
skipped entirely (file pruning in the scan planner).

Leaves are serializable (to_dict/from_dict) so splits can carry them across
process boundaries, mirroring Paimon's serializable predicates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from .batch import ColumnBatch

__all__ = [
    "Predicate",
    "LeafPredicate",
    "CompoundPredicate",
    "PredicateBuilder",
    "FieldStats",
    "and_",
    "or_",
    "equal",
    "not_equal",
    "less_than",
    "less_or_equal",
    "greater_than",
    "greater_or_equal",
    "is_null",
    "is_not_null",
    "in_",
    "not_in",
    "starts_with",
    "ends_with",
    "contains",
    "between",
]


@dataclass(frozen=True)
class FieldStats:
    """Per-file, per-field statistics used for pruning (reference:
    stats/SimpleStats + predicate evaluation on stats).

    null_count None means *unknown* (the writer did not record it): null
    predicates then cannot prune, and the field is never treated as all-null.
    """

    min: Any
    max: Any
    null_count: int | None
    row_count: int

    @property
    def all_null(self) -> bool:
        return self.null_count is not None and self.null_count >= self.row_count


class Predicate:
    def eval(self, batch: ColumnBatch) -> np.ndarray:
        """Dense bool mask, SQL three-valued logic collapsed to False for NULL."""
        raise NotImplementedError

    def test_stats(self, stats: dict[str, FieldStats]) -> bool:
        """True if a file with these stats *might* contain a matching row.
        Missing stats for a referenced field => conservatively True."""
        raise NotImplementedError

    def referenced_fields(self) -> set[str]:
        raise NotImplementedError

    def negate(self) -> Optional["Predicate"]:
        return None

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Predicate":
        if d["kind"] == "leaf":
            return LeafPredicate(d["function"], d["field"], d.get("literals"))
        return CompoundPredicate(d["function"], [Predicate.from_dict(c) for c in d["children"]])

    def __and__(self, other: "Predicate") -> "Predicate":
        return and_(self, other)

    def __or__(self, other: "Predicate") -> "Predicate":
        return or_(self, other)


_NEGATIONS = {
    "equal": "notEqual",
    "notEqual": "equal",
    "lessThan": "greaterOrEqual",
    "greaterOrEqual": "lessThan",
    "greaterThan": "lessOrEqual",
    "lessOrEqual": "greaterThan",
    "isNull": "isNotNull",
    "isNotNull": "isNull",
    "in": "notIn",
    "notIn": "in",
    "startsWith": "notStartsWith",
    "notStartsWith": "startsWith",
    "endsWith": "notEndsWith",
    "notEndsWith": "endsWith",
    "contains": "notContains",
    "notContains": "contains",
}


# value-determined leaf functions: the verdict depends only on the (non-null)
# value, and NULL rows fail them all — exactly the set whose eval transfers
# from the dictionary domain to the rows (decode/pushdown.py gates on the
# same property)
_VALUE_FUNCS = frozenset(
    {
        "equal",
        "notEqual",
        "lessThan",
        "lessOrEqual",
        "greaterThan",
        "greaterOrEqual",
        "in",
        "notIn",
        "between",
        "startsWith",
        "endsWith",
        "contains",
        "notStartsWith",
        "notEndsWith",
        "notContains",
    }
)


@dataclass(frozen=True)
class LeafPredicate(Predicate):
    function: str
    field: str
    literals: Any = None  # scalar, or list for in/notIn/between

    def referenced_fields(self) -> set[str]:
        return {self.field}

    def negate(self) -> Optional[Predicate]:
        neg = _NEGATIONS.get(self.function)
        return LeafPredicate(neg, self.field, self.literals) if neg else None

    def to_dict(self) -> dict:
        return {"kind": "leaf", "function": self.function, "field": self.field, "literals": self.literals}

    # ---- data evaluation ----------------------------------------------
    def eval(self, batch: ColumnBatch) -> np.ndarray:
        col = batch.column(self.field)
        f, lit = self.function, self.literals
        if f == "isNull":
            return ~col.valid_mask()
        if f == "isNotNull":
            return col.valid_mask().copy()
        if col.is_code_backed and f in _VALUE_FUNCS:
            # compressed-domain eval (LSM-OPD): the remaining functions are
            # value-determined and NULL rows fail them all (the `& valid`
            # below), so one |pool|-sized eval + a uint32 verdict gather
            # replaces the |rows|-sized eval — the column never expands
            pool, codes = col.dict_cache
            verdict = self._eval_values(pool, np.ones(len(pool), dtype=np.bool_))
            if len(pool) == 0:
                return np.zeros(len(col), dtype=np.bool_)
            return verdict.take(np.minimum(codes, len(pool) - 1)) & col.valid_mask()
        return self._eval_values(col.values, col.valid_mask())

    def _eval_values(self, v: np.ndarray, valid: np.ndarray) -> np.ndarray:
        f, lit = self.function, self.literals
        if f == "equal":
            m = _masked_cmp(v, valid, "==", lit)
        elif f == "notEqual":
            m = _masked_cmp(v, valid, "!=", lit)
        elif f == "lessThan":
            m = _masked_cmp(v, valid, "<", lit)
        elif f == "lessOrEqual":
            m = _masked_cmp(v, valid, "<=", lit)
        elif f == "greaterThan":
            m = _masked_cmp(v, valid, ">", lit)
        elif f == "greaterOrEqual":
            m = _masked_cmp(v, valid, ">=", lit)
        elif f == "in":
            m = np.isin(v, np.asarray(list(lit), dtype=v.dtype)) if v.dtype != object else np.isin(v, list(lit))
        elif f == "notIn":
            m = (
                ~np.isin(v, np.asarray(list(lit), dtype=v.dtype))
                if v.dtype != object
                else ~np.isin(v, list(lit))
            )
        elif f == "between":
            lo, hi = lit
            m = _masked_cmp(v, valid, ">=", lo) & _masked_cmp(v, valid, "<=", hi)
        elif f in ("startsWith", "endsWith", "contains"):
            m = _string_match(v, f, lit)
        elif f in ("notStartsWith", "notEndsWith", "notContains"):
            # SQL three-valued logic: NULL rows match neither LIKE nor NOT LIKE
            m = ~_string_match(v, f[3].lower() + f[4:], lit)
        else:
            raise ValueError(f"unknown predicate function {f}")
        return np.asarray(m, dtype=np.bool_) & valid

    # ---- stats evaluation (file skipping) ------------------------------
    def test_stats(self, stats: dict[str, FieldStats]) -> bool:
        st = stats.get(self.field)
        if st is None:
            return True
        f, lit = self.function, self.literals
        if f == "isNull":
            return st.null_count is None or st.null_count > 0
        if f == "isNotNull":
            return not st.all_null
        if st.all_null:
            return False
        if st.min is None or st.max is None:
            return True  # stats not collected: cannot prune
        if f == "equal":
            return st.min <= lit <= st.max
        if f == "notEqual":
            return not (st.min == lit == st.max)
        if f == "lessThan":
            return st.min < lit
        if f == "lessOrEqual":
            return st.min <= lit
        if f == "greaterThan":
            return st.max > lit
        if f == "greaterOrEqual":
            return st.max >= lit
        if f == "in":
            return any(st.min <= x <= st.max for x in lit)
        if f == "notIn":
            return not all(st.min == x == st.max for x in lit)
        if f == "between":
            lo, hi = lit
            return st.max >= lo and st.min <= hi
        if f == "startsWith":
            p = lit
            lo = str(st.min)[: len(p)] if st.min is not None else ""
            hi = str(st.max)[: len(p)] if st.max is not None else ""
            return lo <= p <= hi
        return True  # endsWith/contains can't prune


_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _masked_cmp(v: np.ndarray, valid: np.ndarray, op: str, lit: Any) -> np.ndarray:
    """Comparison that never evaluates null slots (object arrays hold None,
    which would raise on ordering comparisons)."""
    fn = _OPS[op]
    if v.dtype == np.dtype(object) and not valid.all():
        out = np.zeros(len(v), dtype=np.bool_)
        out[valid] = np.asarray(fn(v[valid], lit), dtype=np.bool_)
        return out
    return np.asarray(fn(v, lit), dtype=np.bool_)


def _string_match(v: np.ndarray, f: str, lit: Any) -> np.ndarray:
    out = np.zeros(len(v), dtype=np.bool_)
    if f == "startsWith":
        for i, x in enumerate(v):
            out[i] = x is not None and str(x).startswith(lit)
    elif f == "endsWith":
        for i, x in enumerate(v):
            out[i] = x is not None and str(x).endswith(lit)
    else:
        for i, x in enumerate(v):
            out[i] = x is not None and lit in str(x)
    return out


@dataclass(frozen=True)
class CompoundPredicate(Predicate):
    function: str  # "and" | "or"
    children: tuple[Predicate, ...]

    def __init__(self, function: str, children: Sequence[Predicate]):
        object.__setattr__(self, "function", function)
        object.__setattr__(self, "children", tuple(children))

    def referenced_fields(self) -> set[str]:
        out: set[str] = set()
        for c in self.children:
            out |= c.referenced_fields()
        return out

    def negate(self) -> Optional[Predicate]:
        negs = [c.negate() for c in self.children]
        if any(n is None for n in negs):
            return None
        return CompoundPredicate("or" if self.function == "and" else "and", negs)  # type: ignore[arg-type]

    def to_dict(self) -> dict:
        return {"kind": "compound", "function": self.function, "children": [c.to_dict() for c in self.children]}

    def eval(self, batch: ColumnBatch) -> np.ndarray:
        masks = [c.eval(batch) for c in self.children]
        out = masks[0]
        for m in masks[1:]:
            out = (out & m) if self.function == "and" else (out | m)
        return out

    def test_stats(self, stats: dict[str, FieldStats]) -> bool:
        if self.function == "and":
            return all(c.test_stats(stats) for c in self.children)
        return any(c.test_stats(stats) for c in self.children)


# ---- builder functions --------------------------------------------------

def equal(field: str, value: Any) -> Predicate:
    return LeafPredicate("equal", field, value)


def not_equal(field: str, value: Any) -> Predicate:
    return LeafPredicate("notEqual", field, value)


def less_than(field: str, value: Any) -> Predicate:
    return LeafPredicate("lessThan", field, value)


def less_or_equal(field: str, value: Any) -> Predicate:
    return LeafPredicate("lessOrEqual", field, value)


def greater_than(field: str, value: Any) -> Predicate:
    return LeafPredicate("greaterThan", field, value)


def greater_or_equal(field: str, value: Any) -> Predicate:
    return LeafPredicate("greaterOrEqual", field, value)


def is_null(field: str) -> Predicate:
    return LeafPredicate("isNull", field)


def is_not_null(field: str) -> Predicate:
    return LeafPredicate("isNotNull", field)


def in_(field: str, values: Sequence[Any]) -> Predicate:
    return LeafPredicate("in", field, list(values))


def not_in(field: str, values: Sequence[Any]) -> Predicate:
    return LeafPredicate("notIn", field, list(values))


def starts_with(field: str, prefix: str) -> Predicate:
    return LeafPredicate("startsWith", field, prefix)


def ends_with(field: str, suffix: str) -> Predicate:
    return LeafPredicate("endsWith", field, suffix)


def contains(field: str, sub: str) -> Predicate:
    return LeafPredicate("contains", field, sub)


def between(field: str, lo: Any, hi: Any) -> Predicate:
    return LeafPredicate("between", field, [lo, hi])


def and_(*preds: Predicate) -> Predicate:
    flat: list[Predicate] = []
    for p in preds:
        if isinstance(p, CompoundPredicate) and p.function == "and":
            flat.extend(p.children)
        else:
            flat.append(p)
    return flat[0] if len(flat) == 1 else CompoundPredicate("and", flat)


def or_(*preds: Predicate) -> Predicate:
    flat: list[Predicate] = []
    for p in preds:
        if isinstance(p, CompoundPredicate) and p.function == "or":
            flat.extend(p.children)
        else:
            flat.append(p)
    return flat[0] if len(flat) == 1 else CompoundPredicate("or", flat)


class PredicateBuilder:
    """Schema-aware helper mirroring reference PredicateBuilder: validates the
    field exists and splits conjunctions for pushdown."""

    def __init__(self, row_type):
        self.row_type = row_type

    def _check(self, field: str) -> str:
        if field not in self.row_type:
            raise KeyError(f"no field {field!r} in {self.row_type.field_names}")
        return field

    def equal(self, field: str, value: Any) -> Predicate:
        return equal(self._check(field), value)

    def not_equal(self, field: str, value: Any) -> Predicate:
        return not_equal(self._check(field), value)

    def less_than(self, field: str, value: Any) -> Predicate:
        return less_than(self._check(field), value)

    def less_or_equal(self, field: str, value: Any) -> Predicate:
        return less_or_equal(self._check(field), value)

    def greater_than(self, field: str, value: Any) -> Predicate:
        return greater_than(self._check(field), value)

    def greater_or_equal(self, field: str, value: Any) -> Predicate:
        return greater_or_equal(self._check(field), value)

    def is_null(self, field: str) -> Predicate:
        return is_null(self._check(field))

    def is_not_null(self, field: str) -> Predicate:
        return is_not_null(self._check(field))

    def in_(self, field: str, values: Sequence[Any]) -> Predicate:
        return in_(self._check(field), values)

    def between(self, field: str, lo: Any, hi: Any) -> Predicate:
        return between(self._check(field), lo, hi)

    def starts_with(self, field: str, prefix: str) -> Predicate:
        return starts_with(self._check(field), prefix)

    @staticmethod
    def split_and(p: Predicate | None) -> list[Predicate]:
        if p is None:
            return []
        if isinstance(p, CompoundPredicate) and p.function == "and":
            return list(p.children)
        return [p]

    @staticmethod
    def pick_by_fields(preds: Sequence[Predicate], fields: set[str]) -> list[Predicate]:
        return [p for p in preds if p.referenced_fields() <= fields]
