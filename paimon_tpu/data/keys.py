"""Normalized binary sort keys as uint32 lanes.

The reference JIT-generates per-schema comparators over BinaryRow bytes
(paimon-codegen SortCodeGenerator / NormalizedKeyComputer; loaded via
/root/reference/paimon-common/.../codegen/CompileUtils.java). The TPU analog:
encode each key column into one or two uint32 "lanes" such that unsigned
lexicographic comparison of the lane tuple equals the typed comparison of the
key tuple. Sorting N rows by a K-column key then becomes one
`jax.lax.sort(lanes..., num_keys=L)` — no comparators, no codegen, and the
same encoding serves the merge kernel, min/max stats, and range partitioning.

uint32 (not uint64) because 32-bit is the TPU's native integer width.

Encodings (all order-preserving into unsigned space):
  * signed ints  : flip the sign bit (x ^ 0x80..0), widened to 32 bits
  * floats       : IEEE total order — if sign bit set, flip all bits, else
                   set the sign bit
  * bool/date/time/timestamp/decimal(unscaled) : via the int paths
  * string/bytes : dictionary rank against a sorted pool built over all
                   inputs participating in one merge (exact, collision-free;
                   see build_string_pool). Variable-length data itself never
                   reaches the device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

import numpy as np

from ..types import RowType, TypeRoot
from .batch import ColumnBatch

__all__ = [
    "NormalizedKeys",
    "encode_key_lanes",
    "lane_count",
    "build_string_pool",
    "exact_string_pool",
    "split_int64_lanes",
    "lexsort_rows",
]


def lane_count(row_type: RowType, key_names: Sequence[str]) -> int:
    n = 0
    for name in key_names:
        n += _lanes_for(row_type.field(name).type.root)
    return n


def _lanes_for(root: TypeRoot) -> int:
    if root in (
        TypeRoot.BOOLEAN,
        TypeRoot.TINYINT,
        TypeRoot.SMALLINT,
        TypeRoot.INT,
        TypeRoot.DATE,
        TypeRoot.TIME,
        TypeRoot.FLOAT,
        TypeRoot.CHAR,
        TypeRoot.VARCHAR,
        TypeRoot.BINARY,
        TypeRoot.VARBINARY,
    ):
        return 1
    if root in (
        TypeRoot.BIGINT,
        TypeRoot.TIMESTAMP,
        TypeRoot.TIMESTAMP_LTZ,
        TypeRoot.DOUBLE,
        TypeRoot.DECIMAL,
    ):
        return 2
    raise ValueError(f"type {root} not supported as a key column")


def split_int64_lanes(v: np.ndarray, signed: bool = True) -> tuple[np.ndarray, np.ndarray]:
    """int64 -> (hi, lo) uint32 lanes, order preserving."""
    u = v.astype(np.int64).view(np.uint64)
    if signed:
        u = u ^ np.uint64(1 << 63)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo


def _encode_column(values: np.ndarray, root: TypeRoot, pool: np.ndarray | None) -> list[np.ndarray]:
    if root == TypeRoot.BOOLEAN:
        return [values.astype(np.uint32)]
    if root in (TypeRoot.TINYINT, TypeRoot.SMALLINT, TypeRoot.INT, TypeRoot.DATE, TypeRoot.TIME):
        v32 = values.astype(np.int32)
        return [v32.view(np.uint32) ^ np.uint32(0x80000000)]
    if root in (TypeRoot.BIGINT, TypeRoot.TIMESTAMP, TypeRoot.TIMESTAMP_LTZ, TypeRoot.DECIMAL):
        hi, lo = split_int64_lanes(values)
        return [hi, lo]
    if root == TypeRoot.FLOAT:
        b = values.astype(np.float32).view(np.uint32)
        neg = (b & np.uint32(0x80000000)) != 0
        return [np.where(neg, ~b, b | np.uint32(0x80000000))]
    if root == TypeRoot.DOUBLE:
        b = values.astype(np.float64).view(np.uint64)
        neg = (b & np.uint64(1 << 63)) != 0
        u = np.where(neg, ~b, b | np.uint64(1 << 63))
        return [(u >> np.uint64(32)).astype(np.uint32), (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)]
    if root in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY):
        if pool is None:
            raise ValueError("string key column requires a pool (build_string_pool)")
        if len(pool) == 0:
            raise ValueError("string key value(s) missing from pool; pool must cover all merge inputs")
        if len(values) >= 65_536:
            ranks = _hash_ranks(values, pool)
            if ranks is not None:
                return [ranks]
        ranks = np.searchsorted(pool, values)
        # a value missing from the pool would silently collide with its
        # successor's rank — turn that data corruption into an error
        clipped = np.minimum(ranks, len(pool) - 1)
        if not bool(np.all(pool[clipped] == values)):
            raise ValueError("string key value(s) missing from pool; pool must cover all merge inputs")
        return [ranks.astype(np.uint32)]
    raise ValueError(f"type {root} not supported as key column")


def _hash_ranks(values: np.ndarray, pool: np.ndarray) -> np.ndarray | None:
    """Rank lookup through arrow's C hash table — replaces a |values| × log
    |pool| object-compare searchsorted for large merges. index_in against
    the sorted pool returns the rank directly; a null (value outside the
    pool) is the same data-corruption case the searchsorted path raises
    for. Returns None when the values cannot take the arrow path (mixed
    types) so the caller falls back."""
    try:
        import pyarrow as pa
        import pyarrow.compute as pc

        idx = pc.index_in(pa.array(values, from_pandas=True), value_set=pa.array(pool))
    except (TypeError, ValueError, OverflowError, pa.lib.ArrowInvalid):
        return None
    if idx.null_count:
        raise ValueError("string key value(s) missing from pool; pool must cover all merge inputs")
    return idx.to_numpy(zero_copy_only=False).astype(np.uint32)


def build_string_pool(column_values: Sequence[np.ndarray]) -> np.ndarray:
    """Sorted unique values across every input of one merge. Ranks against this
    pool are exact order-preserving surrogates for the strings themselves.

    Large inputs dedupe through arrow's C hash table first (object-compare
    sorting then touches only the distinct set — for dictionary-shaped key
    columns that is orders of magnitude smaller); the output contract is
    identical to np.unique: a sorted object ndarray."""
    non_empty = [v for v in column_values if len(v)]
    if not non_empty:
        return np.empty(0, dtype=object)
    total = sum(len(v) for v in non_empty)
    if total >= 65_536:
        try:
            import pyarrow as pa
            import pyarrow.compute as pc

            chunked = pa.chunked_array([pa.array(v, from_pandas=True) for v in non_empty])
            uniq = pc.drop_null(pc.unique(chunked)).to_numpy(zero_copy_only=False)
            if uniq.dtype != np.dtype(object):
                uniq = uniq.astype(object)
            uniq.sort()
            return uniq
        except (TypeError, ValueError, OverflowError, pa.lib.ArrowInvalid):
            pass  # mixed/unhashable values: the numpy sort path below
    return np.unique(np.concatenate(non_empty))


def exact_string_pool(cols: Sequence) -> np.ndarray:
    """Sorted distinct PRESENT values across the given Columns — identical
    to build_string_pool over their expanded values, but computed entirely
    in the code domain when every column carries a usable dict_cache: each
    (pool, codes) pair prunes to its referenced entries and the pruned
    pools unify (object work at |pool| scale). Falls back to the expanded
    build when any column lacks a cache."""
    from ..ops.dicts import cache_usable, prune_pool, unify_pools

    cols = list(cols)
    if cols and all(cache_usable(c) for c in cols):
        pruned = []
        for c in cols:
            pool, codes = c.dict_cache
            p, _ = prune_pool(pool, codes, c.validity)
            pruned.append(p)
        unified, _ = unify_pools(pruned)
        return unified
    return build_string_pool([c.values for c in cols])


def _ranks_from_cache(pool: np.ndarray, cache: tuple) -> np.ndarray:
    """Ranks of a cached (pool, codes) column against a caller-supplied
    sorted pool: the |pool_c|-sized searchsorted replaces the |rows|-sized
    one — the rows themselves only pay a uint32 gather (ops.dicts). A used
    code whose value is missing from the pool is the same data-corruption
    case the expanded path raises for."""
    from ..ops.dicts import remap_codes

    pool_c, codes = cache
    if pool_c is pool:
        return codes.astype(np.uint32, copy=False)
    if len(pool) == 0 or len(pool_c) == 0:
        if len(codes) == 0:
            return codes.astype(np.uint32, copy=False)
        raise ValueError("string key value(s) missing from pool; pool must cover all merge inputs")
    idx = np.searchsorted(pool, pool_c)
    clipped = np.minimum(idx, len(pool) - 1)
    entry_ok = pool[clipped] == pool_c
    ranks = remap_codes(clipped.astype(np.uint32), codes)
    if len(codes) and not bool(entry_ok.take(codes).all()):
        raise ValueError("string key value(s) missing from pool; pool must cover all merge inputs")
    return ranks


def encode_key_lanes(
    batch: ColumnBatch,
    key_names: Sequence[str],
    string_pools: Mapping[str, np.ndarray] | None = None,
) -> np.ndarray:
    """(N, L) uint32 lanes for the given key columns. Key columns must be
    non-null (primary keys are NOT NULL by schema validation).

    Side effect: string/bytes key columns get the (pool, ranks) pair cached
    on the Column (`dict_cache`) — the ranks double as exact dictionary
    codes, which the native parquet encoder consumes directly so flushed
    merge output never rematerializes key strings (any consistent pair is
    correct, so concurrent merges over a shared cached column are safe)."""
    lanes: list[np.ndarray] = []
    string_roots = (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY)
    for name in key_names:
        col = batch.column(name)
        if col.null_count:
            raise ValueError(f"key column {name!r} contains nulls")
        root = batch.schema.field(name).type.root
        pool = None if string_pools is None else string_pools.get(name)
        cache = col.dict_cache
        if (
            root in string_roots
            and pool is not None
            and cache is not None
            and len(cache[1]) == len(col)
        ):
            # compressed-domain short circuit: the column already carries
            # dictionary codes — ranks come from a pool-sized remap + one
            # uint32 gather, zero searchsorted over the rows and zero
            # string-object comparisons
            col_lanes = [_ranks_from_cache(pool, cache)]
        elif root not in string_roots and col.is_code_backed:
            # fixed-width code domain (ISSUE 12): encode the POOL once
            # (O(|pool|)) and gather each lane through the codes — element-
            # wise encoding commutes with the gather, so the lanes are
            # numerically identical to encoding the expanded values
            cpool, codes = col.dict_cache
            col_lanes = [pl.take(codes) for pl in _encode_column(cpool, root, None)]
        else:
            col_lanes = _encode_column(col.values, root, pool)
        if pool is not None and root in string_roots:
            col.dict_cache = (pool, col_lanes[0].astype(np.uint32, copy=False))
        lanes.extend(col_lanes)
    if not lanes:
        return np.zeros((batch.num_rows, 0), dtype=np.uint32)
    return np.stack(lanes, axis=1)


@dataclass
class NormalizedKeys:
    """Lanes plus the metadata needed to interpret them."""

    lanes: np.ndarray  # (N, L) uint32
    key_names: tuple[str, ...]

    def __len__(self) -> int:
        return self.lanes.shape[0]

    @property
    def num_lanes(self) -> int:
        return self.lanes.shape[1]


def lexsort_rows(lanes: np.ndarray, *tiebreakers: np.ndarray) -> np.ndarray:
    """Host-side (numpy) stable lexicographic argsort: lanes left-to-right are
    most-to-least significant, then tiebreaker arrays. Reference oracle for the
    device kernel in paimon_tpu.ops.merge."""
    keys = list(tiebreakers)[::-1] + [lanes[:, i] for i in range(lanes.shape[1] - 1, -1, -1)]
    if not keys:
        return np.arange(lanes.shape[0])
    return np.lexsort(keys)


def encode_key_lanes_with_pools(batch, key_names):
    """encode_key_lanes with string pools auto-built for string/bytes keys —
    the idiom every key-encoding call site needs. Pools prefer the code
    domain (exact_string_pool): a column the reader delivered as dictionary
    codes never expands to build its pool."""
    from ..types import TypeRoot

    pools = {
        name: exact_string_pool([batch.column(name)])
        for name in key_names
        if batch.schema.field(name).type.root
        in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY)
    }
    return encode_key_lanes(batch, key_names, pools)
