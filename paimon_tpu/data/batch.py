"""Columnar batch model.

Replaces the reference's row + columnar-batch duo (BinaryRow,
VectorizedColumnBatch — /root/reference/paimon-common/.../data/columnar/
VectorizedColumnBatch.java:37) with a single structure: a ColumnBatch is a
RowType plus one dense numpy vector (and optional validity bitmap) per field.

Design rules that keep this TPU-friendly:
  * fixed-width columns are contiguous numpy arrays of the type's dtype —
    they move to device memory with zero transformation;
  * validity is a separate bool vector (never sentinel values), so device
    kernels can consume it as a mask lane;
  * variable-width (string/bytes) columns are object arrays host-side and are
    never shipped to device — kernels see them only as dictionary ranks
    (see paimon_tpu.data.keys) and rematerialize by gather on host;
  * all structural ops (take/slice/concat) are O(columns) numpy calls, no
    Python-per-row loops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable, Mapping, Sequence

import numpy as np

from ..types import DataField, DataType, RowType, TypeRoot

__all__ = ["Column", "ColumnBatch", "concat_batches"]


class Column:
    """values + optional validity (True = present). validity None = all valid.

    String/bytes columns may additionally be backed by a pyarrow array
    (`arrow`): structural ops (take/slice/filter/concat) then run in arrow's
    C++ and the object ndarray materializes lazily only when `.values` is
    actually touched (predicates, key pools, python access).

    `dict_cache` is an optional (sorted pool, uint32 ranks) pair attached by
    the key-lane encoder (data/keys.py): the ranks ARE exact dictionary
    codes against the pool, so the native parquet encoder emits dictionary
    pages without ever touching a string object. Structural ops transform
    the ranks alongside the values.

    A column may also be CODE-BACKED (`from_codes`): no values, no arrow —
    only the (pool, codes) pair, produced by the code-domain reader mode
    (merge.dict-domain). Structural ops then touch only the uint32 codes;
    concat unifies the input pools in the code domain (ops.dicts); the
    object ndarray materializes lazily only when `.values` is actually
    needed (counted in dict{fallback_expanded}). Non-code-backed concat
    drops the cache (pools differ per input)."""

    __slots__ = ("_values", "validity", "arrow", "_len", "dict_cache")

    def __init__(self, values: np.ndarray | None = None, validity: np.ndarray | None = None, arrow=None):
        assert values is not None or arrow is not None
        self._values = values
        self.arrow = arrow
        self.dict_cache = None
        self._len = len(values) if values is not None else len(arrow)
        if validity is not None:
            assert validity.dtype == np.bool_
            assert len(validity) == self._len
            if bool(validity.all()):
                validity = None
        self.validity = validity

    @staticmethod
    def from_codes(pool: np.ndarray, codes: np.ndarray, validity: np.ndarray | None = None) -> "Column":
        """Code-backed column over a sorted dictionary pool. Codes are
        full-length uint32 ranks into the pool; values at invalid slots are
        meaningless by contract (conventionally 0)."""
        col = Column.__new__(Column)
        col._values = None
        col.arrow = None
        col.dict_cache = (pool, codes.astype(np.uint32, copy=False))
        col._len = len(codes)
        if validity is not None:
            assert validity.dtype == np.bool_ and len(validity) == col._len
            if bool(validity.all()):
                validity = None
        col.validity = validity
        return col

    @property
    def is_code_backed(self) -> bool:
        return self._values is None and self.arrow is None

    def _with_cache(self, out: "Column", transform) -> "Column":
        if self.dict_cache is not None:
            pool, codes = self.dict_cache
            out.dict_cache = (pool, transform(codes))
        return out

    @property
    def values(self) -> np.ndarray:
        if self._values is None:
            if self.arrow is None:
                # code-backed: expand pool[codes] on first python-level
                # access. Object pools fill nulls with None (matching the
                # expanded decode); fixed-width pools fill with the zero
                # sentinel, exactly like decode_chunk's null fill
                from ..metrics import dict_metrics

                pool, codes = self.dict_cache
                if len(pool):
                    v = pool.take(np.minimum(codes, len(pool) - 1))
                    if v is pool or not v.flags.writeable:
                        v = v.copy()
                else:
                    v = np.empty(self._len, dtype=pool.dtype)
                    if pool.dtype.kind in "biufM":
                        v[:] = 0
                if self.validity is not None:
                    v[~self.validity] = None if pool.dtype == np.dtype(object) else 0
                dict_metrics().counter("fallback_expanded").inc(self._len)
                self._values = v
                return v
            arr = self.arrow
            v = arr.to_numpy(zero_copy_only=False)
            if v.dtype != np.dtype(object):
                v = v.astype(object)
            self._values = v
        return self._values

    def value_at(self, i: int):
        """One python value without materializing the whole column (file
        min/max key extraction over code-backed/arrow columns)."""
        if self.validity is not None and not self.validity[i]:
            return None
        if self._values is None:
            if self.arrow is None:
                pool, codes = self.dict_cache
                return pool[int(codes[i])]
            return self.arrow[int(i)].as_py()
        return self._values[i]

    def byte_size(self) -> int:
        """Approximate heap footprint — the currency of write-buffer budgets
        (reference MemorySegmentPool accounts bytes, not rows)."""
        if self.arrow is not None:
            total = self.arrow.nbytes
        elif self._values is None:
            # code-backed: codes + a sampled estimate of the pool payload
            pool, codes = self.dict_cache
            sample = pool[:1024]
            payload = sum(len(x) if isinstance(x, (str, bytes)) else 16 for x in sample if x is not None)
            total = codes.nbytes + int(len(pool) * (8 + payload / max(len(sample), 1)))
        elif self._values.dtype == np.dtype(object):
            # object ndarray of str/bytes: pointer + measured payloads
            sample = self._values[:1024]
            payload = sum(len(x) if isinstance(x, (str, bytes)) else 16 for x in sample if x is not None)
            avg = payload / max(len(sample), 1)
            total = int(self._len * (8 + avg + 49))  # ptr + payload + PyObject overhead
        else:
            total = self._values.nbytes
        if self.validity is not None:
            total += self.validity.nbytes
        return total

    def __len__(self) -> int:
        return self._len

    @property
    def null_count(self) -> int:
        return 0 if self.validity is None else int((~self.validity).sum())

    def is_null(self) -> np.ndarray:
        if self.validity is None:
            return np.zeros(self._len, dtype=np.bool_)
        return ~self.validity

    def valid_mask(self) -> np.ndarray:
        if self.validity is None:
            return np.ones(self._len, dtype=np.bool_)
        return self.validity

    def take(self, indices: np.ndarray) -> "Column":
        m = None if self.validity is None else self.validity.take(indices)
        if self._values is None:
            if self.arrow is None:
                pool, codes = self.dict_cache
                return Column.from_codes(pool, codes.take(indices), m)
            import pyarrow.compute as pc

            out = Column(validity=m, arrow=pc.take(self.arrow, indices))
        else:
            out = Column(self.values.take(indices), m)
        return self._with_cache(out, lambda c: c.take(indices))

    def slice(self, start: int, stop: int) -> "Column":
        m = None if self.validity is None else self.validity[start:stop]
        if self._values is None:
            if self.arrow is None:
                pool, codes = self.dict_cache
                return Column.from_codes(pool, codes[start:stop], m)
            out = Column(validity=m, arrow=self.arrow.slice(start, stop - start))
        else:
            out = Column(self.values[start:stop], m)
        return self._with_cache(out, lambda c: c[start:stop])

    def filter(self, mask: np.ndarray) -> "Column":
        m = None if self.validity is None else self.validity[mask]
        if self._values is None:
            if self.arrow is None:
                pool, codes = self.dict_cache
                return Column.from_codes(pool, codes[mask], m)
            import pyarrow.compute as pc

            out = Column(validity=m, arrow=pc.filter(self.arrow, mask))
        else:
            out = Column(self.values[mask], m)
        return self._with_cache(out, lambda c: c[mask])

    def to_pylist(self) -> list:
        if self._values is None and self.arrow is not None and self.validity is None:
            return self.arrow.to_pylist()
        if self.validity is None:
            return self.values.tolist()
        return [v if ok else None for v, ok in zip(self.values.tolist(), self.validity.tolist())]

    @staticmethod
    def from_pylist(data: Sequence[Any], dtype: DataType) -> "Column":
        np_dtype = dtype.numpy_dtype()
        if isinstance(data, np.ndarray):
            # vectorized ingest fast paths: callers handing numpy arrays
            # (bench/engine surfaces) must not pay a per-element loop
            if np_dtype != np.dtype(object) and data.dtype.kind in "biuf":
                return Column(np.ascontiguousarray(data, dtype=np_dtype))
            if np_dtype == data.dtype == np.dtype(object):
                validity = np.asarray(data != None, dtype=np.bool_)  # noqa: E711 — elementwise
                return Column(data, None if validity.all() else validity)
        validity = np.array([x is not None for x in data], dtype=np.bool_)
        if np_dtype == np.dtype(object):
            values = np.empty(len(data), dtype=object)
            for i, x in enumerate(data):
                values[i] = x
        else:
            fill: Any = 0
            values = np.array([fill if x is None else x for x in data], dtype=np_dtype)
        return Column(values, None if validity.all() else validity)

    @staticmethod
    def concat(cols: Sequence["Column"]) -> "Column":
        validity = None
        if not all(c.validity is None for c in cols):
            validity = np.concatenate([c.valid_mask() for c in cols])
        if cols and all(c.is_code_backed for c in cols):
            # code-domain concat: unify the input pools and re-map codes —
            # no string object materializes (ops.dicts; None = domain past
            # the pool limit, fall through to the expanded paths)
            from ..ops.dicts import unify_columns

            out = unify_columns(cols, validity)
            if out is not None:
                return out
        if cols and all(c._values is None and c.arrow is not None for c in cols):
            import pyarrow as pa

            chunks = []
            for c in cols:
                a = c.arrow
                chunks.extend(a.chunks if isinstance(a, pa.ChunkedArray) else [a])
            types = {c.type for c in chunks if not pa.types.is_null(c.type)}
            if len(types) == 1:
                t = types.pop()
                chunks = [c.cast(t) if pa.types.is_null(c.type) else c for c in chunks]
                return Column(validity=validity, arrow=pa.concat_arrays(chunks))
            # all-null or mixed types: fall through to the numpy path
        values = np.concatenate([c.values for c in cols])
        return Column(values, validity)


class ColumnBatch:
    """A schema-carrying bundle of equal-length Columns."""

    def __init__(self, schema: RowType, columns: Mapping[str, Column] | Sequence[Column]):
        self.schema = schema
        if isinstance(columns, Mapping):
            cols = {name: columns[name] for name in schema.field_names}
        else:
            cols = {f.name: c for f, c in zip(schema.fields, columns)}
        assert len(cols) == len(schema.fields), (list(cols), schema.field_names)
        lengths = {len(c) for c in cols.values()}
        assert len(lengths) <= 1, f"ragged columns: { {n: len(c) for n, c in cols.items()} }"
        self.columns: dict[str, Column] = cols
        self._num_rows = lengths.pop() if lengths else 0

    # ---- construction --------------------------------------------------
    @staticmethod
    def from_pydict(schema: RowType, data: Mapping[str, Sequence[Any]]) -> "ColumnBatch":
        cols = {f.name: Column.from_pylist(data[f.name], f.type) for f in schema.fields}
        return ColumnBatch(schema, cols)

    @staticmethod
    def from_pylist(schema: RowType, rows: Sequence[Sequence[Any]]) -> "ColumnBatch":
        data = {f.name: [r[i] for r in rows] for i, f in enumerate(schema.fields)}
        return ColumnBatch.from_pydict(schema, data)

    @staticmethod
    def empty(schema: RowType) -> "ColumnBatch":
        cols = {
            f.name: Column(np.empty(0, dtype=f.type.numpy_dtype()))
            for f in schema.fields
        }
        return ColumnBatch(schema, cols)

    # ---- accessors -----------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._num_rows

    def byte_size(self) -> int:
        """Approximate heap bytes across all columns (budgeting currency)."""
        return sum(c.byte_size() for c in self.columns.values())

    def __len__(self) -> int:
        return self._num_rows

    def column(self, name: str) -> Column:
        return self.columns[name]

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    # ---- structural ops ------------------------------------------------
    def take(self, indices: np.ndarray) -> "ColumnBatch":
        return ColumnBatch(self.schema, {n: c.take(indices) for n, c in self.columns.items()})

    def slice(self, start: int, stop: int) -> "ColumnBatch":
        return ColumnBatch(self.schema, {n: c.slice(start, stop) for n, c in self.columns.items()})

    def filter(self, mask: np.ndarray) -> "ColumnBatch":
        return ColumnBatch(self.schema, {n: c.filter(mask) for n, c in self.columns.items()})

    def select(self, names: Iterable[str]) -> "ColumnBatch":
        names = list(names)
        return ColumnBatch(self.schema.project(names), {n: self.columns[n] for n in names})

    def with_column(self, field: DataField, col: Column) -> "ColumnBatch":
        fields = list(self.schema.fields) + [field]
        cols = dict(self.columns)
        cols[field.name] = col
        return ColumnBatch(RowType(fields), cols)

    def rename(self, schema: RowType) -> "ColumnBatch":
        """Reinterpret under a same-arity schema (positional)."""
        assert len(schema) == len(self.schema)
        cols = {
            nf.name: self.columns[of.name]
            for of, nf in zip(self.schema.fields, schema.fields)
        }
        return ColumnBatch(schema, cols)

    # ---- conversion ----------------------------------------------------
    def to_pydict(self) -> dict[str, list]:
        return {n: c.to_pylist() for n, c in self.columns.items()}

    def to_pylist(self) -> list[tuple]:
        cols = [self.columns[f.name].to_pylist() for f in self.schema.fields]
        return list(zip(*cols)) if cols else []

    def to_arrow(self):
        import pyarrow as pa

        from ..types import TypeRoot

        arrays = []
        for f in self.schema.fields:
            c = self.columns[f.name]
            if c._values is None and c.arrow is None:
                # code-backed: hand arrow the dictionary form directly —
                # one int32 cast, zero string materialization (parquet
                # writes it as a dictionary-encoded column)
                pool, codes = c.dict_cache
                if len(pool) == 0:  # all-null column: same null array the
                    arrays.append(pa.nulls(len(c)))  # expanded path infers
                    continue
                mask = None if c.validity is None else ~c.validity
                indices = pa.array(
                    np.minimum(codes, max(len(pool) - 1, 0)).astype(np.int32), mask=mask
                )
                arrays.append(pa.DictionaryArray.from_arrays(indices, pa.array(pool, from_pandas=True)))
                continue
            if c._values is None:
                arrays.append(c.arrow)  # zero-conversion passthrough
                continue
            mask = None if c.validity is None else ~c.validity
            if f.type.root in (TypeRoot.ARRAY, TypeRoot.MAP, TypeRoot.ROW):
                # nested columns need the declared type: inference cannot see
                # struct shapes through object ndarrays. The null-free fast
                # path hands the object vector over in one C pass; nulls take
                # one vectorized mask-assign on a copy — no per-row loop
                if mask is None:
                    vals = list(c.values)
                else:
                    masked = c.values.copy()
                    masked[mask] = None
                    vals = list(masked)
                arrays.append(pa.array(vals, type=_pa_nested_type(f.type)))
            else:
                arrays.append(pa.array(c.values, from_pandas=True, mask=mask))
        return pa.table(dict(zip(self.schema.field_names, arrays)))

    @staticmethod
    def row_type_from_arrow(arrow_schema) -> RowType:
        """Infer a RowType from a pyarrow schema (migration entry point)."""
        import pyarrow as pa

        from ..types import (
            BIGINT,
            BOOLEAN,
            BYTES,
            DATE,
            DOUBLE,
            FLOAT,
            INT,
            SMALLINT,
            STRING,
            TIMESTAMP,
            TINYINT,
            DataField,
        )

        def conv(t):
            if pa.types.is_boolean(t):
                return BOOLEAN()
            if pa.types.is_int8(t):
                return TINYINT()
            if pa.types.is_int16(t):
                return SMALLINT()
            if pa.types.is_int32(t):
                return INT()
            if pa.types.is_integer(t):
                return BIGINT()
            if pa.types.is_float32(t):
                return FLOAT()
            if pa.types.is_floating(t):
                return DOUBLE()
            if pa.types.is_date(t):
                return DATE()
            if pa.types.is_timestamp(t):
                return TIMESTAMP()
            if pa.types.is_binary(t) or pa.types.is_large_binary(t):
                return BYTES()
            if pa.types.is_decimal(t) and t.precision <= 18:
                from ..types import DECIMAL

                return DECIMAL(t.precision, t.scale)
            return STRING()

        return RowType(
            tuple(DataField(i, f.name, conv(f.type)) for i, f in enumerate(arrow_schema))
        )

    @staticmethod
    def from_arrow(table, schema: RowType) -> "ColumnBatch":
        cols: dict[str, Column] = {}
        for f in schema.fields:
            arr = table.column(f.name).combine_chunks()
            cols[f.name] = _arrow_to_column(arr, f.type)
        return ColumnBatch(schema, cols)

    def __repr__(self) -> str:  # pragma: no cover
        return f"ColumnBatch(rows={self.num_rows}, fields={self.schema.field_names})"


def _restore_nested(x, dtype: DataType):
    """Recursively restore dict shape for maps at ANY nesting depth (arrow
    reads maps back as [(k, v), ...] pair lists)."""
    if x is None:
        return None
    root = dtype.root
    if root == TypeRoot.MAP:
        return {k: _restore_nested(v, dtype.value) for k, v in x}
    if root == TypeRoot.ARRAY:
        return [_restore_nested(e, dtype.element) for e in x]
    if root == TypeRoot.ROW:
        return {f.name: _restore_nested(x.get(f.name), f.type) for f in dtype.fields}
    return x


def _pa_nested_type(dtype: DataType):
    """DataType -> pyarrow type for nested (array/map/row) columns."""
    import pyarrow as pa

    from ..types import TypeRoot

    root = dtype.root
    if root == TypeRoot.ARRAY:
        return pa.list_(_pa_nested_type(dtype.element))
    if root == TypeRoot.MAP:
        return pa.map_(_pa_nested_type(dtype.key), _pa_nested_type(dtype.value))
    if root == TypeRoot.ROW:
        return pa.struct([(f.name, _pa_nested_type(f.type)) for f in dtype.fields])
    np_dtype = dtype.numpy_dtype()
    if np_dtype == np.dtype(object):
        return pa.binary() if root in (TypeRoot.BINARY, TypeRoot.VARBINARY) else pa.string()
    return pa.from_numpy_dtype(np_dtype)


def _arrow_to_column(arr, dtype: DataType) -> Column:
    import pyarrow as pa
    import pyarrow.compute as pc

    validity = None
    if arr.null_count:
        validity = np.asarray(pc.is_valid(arr))
    np_dtype = dtype.numpy_dtype()
    if (
        np_dtype == np.dtype(object)
        and pa.types.is_dictionary(arr.type)
        and not pa.types.is_nested(arr.type.value_type)
        and arr.dictionary.null_count == 0
    ):
        # arrow decoded the chunk dictionary-encoded (read_dictionary under
        # merge.dict-domain): populate the code domain in one C pass —
        # indices + dictionary straight off the buffers, never a string
        # object per row (the arrow twin of decode/pages.chunk_codes)
        from ..metrics import dict_metrics
        from ..ops.dicts import remap_codes, resolve_pool_limit, sort_dictionary

        if len(arr.dictionary) <= resolve_pool_limit(None):
            indices = arr.indices
            if indices.null_count:
                indices = pc.fill_null(indices, 0)
            codes = indices.to_numpy(zero_copy_only=False).astype(np.uint32, copy=False)
            dictionary = arr.dictionary.to_numpy(zero_copy_only=False)
            if dictionary.dtype != np.dtype(object):
                dictionary = dictionary.astype(object)
            pool, remap = sort_dictionary(dictionary)
            dict_metrics().counter("rows_code_domain").inc(len(codes))
            return Column.from_codes(pool, remap_codes(remap, codes), validity)
        dict_metrics().counter("fallback_expanded").inc(len(arr))
    if (
        np_dtype != np.dtype(object)
        and np_dtype.kind in "iu"
        and pa.types.is_dictionary(arr.type)
        and not pa.types.is_nested(arr.type.value_type)
        and arr.dictionary.null_count == 0
    ):
        # fixed-width dictionary (int/date/timestamp — ISSUE 12): same one-
        # C-pass code-domain population as the string branch, with the pool
        # kept in the column's native numpy dtype
        from ..metrics import dict_metrics
        from ..ops.dicts import remap_codes, resolve_pool_limit, sort_dictionary

        if len(arr.dictionary) <= resolve_pool_limit(None):
            d = arr.dictionary
            if pa.types.is_timestamp(d.type):
                d = d.cast(pa.int64())
            elif pa.types.is_date32(d.type):
                d = d.cast(pa.int32())
            dnp = d.to_numpy(zero_copy_only=False)
            if dnp.dtype != np_dtype and dnp.dtype.kind in "iu":
                dnp = dnp.astype(np_dtype)
            if dnp.dtype == np_dtype:
                indices = arr.indices
                if indices.null_count:
                    indices = pc.fill_null(indices, 0)
                codes = indices.to_numpy(zero_copy_only=False).astype(np.uint32, copy=False)
                pool, remap = sort_dictionary(dnp)
                dict_metrics().counter("rows_code_domain").inc(len(codes))
                return Column.from_codes(pool, remap_codes(remap, codes), validity)
        dict_metrics().counter("fallback_expanded").inc(len(arr))
    if pa.types.is_dictionary(arr.type):
        # dictionary shape the code domain can't carry (nested values,
        # null dictionary entries, float/decimal dictionary): decode to the
        # plain type and take the ordinary paths below
        arr = arr.cast(arr.type.value_type)
    if np_dtype == np.dtype(object):
        if pa.types.is_nested(arr.type):
            # nested (list/map/struct) values must stay python lists/dicts —
            # to_numpy would hand back ndarrays whose equality semantics break
            values = np.empty(len(arr), dtype=object)
            for i, x in enumerate(arr.to_pylist()):
                values[i] = _restore_nested(x, dtype)
        else:
            # keep the arrow backing: structural ops stay in C++ and the
            # object ndarray materializes only if python-level access happens
            return Column(validity=validity, arrow=arr)
    else:
        if arr.null_count:
            arr = arr.fill_null(_zero_value(dtype))
        if pa.types.is_timestamp(arr.type):
            arr = arr.cast(pa.int64())
        elif pa.types.is_date32(arr.type):
            arr = arr.cast(pa.int32())
        elif pa.types.is_decimal(arr.type):
            # exact unscaled int64: stay in decimal space (no float detour)
            scale = arr.type.scale
            widened = arr.cast(pa.decimal256(38, scale))
            arr = pc.multiply(widened, pa.scalar(10**scale, pa.decimal256(20, 0))).cast(pa.int64())
        values = arr.to_numpy(zero_copy_only=False).astype(np_dtype, copy=False)
    return Column(values, validity)


def _zero_value(dtype: DataType):
    if dtype.root == TypeRoot.BOOLEAN:
        return False
    return 0


def concat_batches(batches: Sequence[ColumnBatch]) -> ColumnBatch:
    if not batches:
        raise ValueError("no batches")
    non_empty = [b for b in batches if b.num_rows]
    batches = non_empty or [batches[0]]
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    cols = {
        n: Column.concat([b.columns[n] for b in batches]) for n in schema.field_names
    }
    return ColumnBatch(schema, cols)
