"""paimon-tpu: a TPU-native LSM lake format.

A from-scratch framework with the capabilities of Apache Paimon (the reference
at /root/reference): snapshot-isolated tables on a lake filesystem with
primary-key upserts, schema evolution, time travel, and streaming changelog —
whose hot paths (k-way sorted merge-on-read, LSM compaction rewrites, predicate
filtering) execute as vectorized JAX/XLA kernels on TPU instead of per-row JVM
loops. See SURVEY.md at the repo root for the full structural map.

Layering (mirrors the reference's L0..L7):
  fs/       L0  filesystem abstraction + atomic-rename commit primitive
  data/     L1  column batches, normalized uint32 key lanes, predicates
  types.py  L1  SQL type system with field-id schema evolution
  format/   L2  parquet/orc encode-decode, stats, bloom file index
  ops/      --  the TPU kernels (sort-merge, segment-reduce merge engines)
  core/     L3  LSM merge-tree, compaction, manifests, snapshots, commit
  table/    L4  engine-neutral Table API (read/write builders, scans)
  parallel/ --  bucket/key-range sharding over jax device meshes
  catalog/  L4  catalog + warehouse layout
"""

def _enable_x64() -> None:
    """64-bit jax mode, package-wide. Without it jnp.asarray silently
    truncates int64 columns to int32 (corrupting BIGINT sums past 2^31) and
    float64 to float32 (~1e-7 relative error on DOUBLE sums). The sort/merge
    kernels are explicit-uint32 and unaffected; aggregation gains exact i64
    everywhere and exact f64 on CPU. TPUs have no native f64 — those
    reductions fall back to an exact host path (ops/aggregates.py).

    jax is NOT imported eagerly: metadata-only users (catalog browsing,
    options parsing) shouldn't pay backend init. The env var configures a
    later import; the config call covers an already-imported jax."""
    import os
    import sys

    if "jax" in sys.modules:
        sys.modules["jax"].config.update("jax_enable_x64", True)
    else:
        os.environ.setdefault("JAX_ENABLE_X64", "true")


_enable_x64()

from .types import (
    BIGINT,
    BOOLEAN,
    BYTES,
    DATE,
    DECIMAL,
    DOUBLE,
    FLOAT,
    INT,
    SMALLINT,
    STRING,
    TIMESTAMP,
    TINYINT,
    DataField,
    DataType,
    RowKind,
    RowType,
)
from .options import CoreOptions, MergeEngine, Options
from .data import ColumnBatch, PredicateBuilder

# CHAR/VARCHAR joined the type constructors in round 2
from .types import CHAR, VARCHAR  # noqa: E402


def __getattr__(name):
    """Lazy top-level access to the heavier surfaces, so `import paimon_tpu`
    stays metadata-cheap: FileSystemCatalog/JdbcCatalog, load_table,
    CdcStream, DedicatedCompactor, FullCacheLookupTable, SplitEnumerator,
    read/write_reference_table."""
    lazy = {
        "FileSystemCatalog": ("paimon_tpu.catalog", "FileSystemCatalog"),
        "JdbcCatalog": ("paimon_tpu.catalog.jdbc", "JdbcCatalog"),
        "load_table": ("paimon_tpu.table", "load_table"),
        "CdcStream": ("paimon_tpu.table.cdc_format", "CdcStream"),
        "DedicatedCompactor": ("paimon_tpu.table.compactor", "DedicatedCompactor"),
        "FullCacheLookupTable": ("paimon_tpu.lookup.tables", "FullCacheLookupTable"),
        "SplitEnumerator": ("paimon_tpu.table.enumerator", "SplitEnumerator"),
        "read_reference_table": ("paimon_tpu.interop", "read_reference_table"),
        "write_reference_table": ("paimon_tpu.interop", "write_reference_table"),
        "PaimonFlightServer": ("paimon_tpu.service.flight", "PaimonFlightServer"),
        "flight_scan": ("paimon_tpu.service.flight", "flight_scan"),
        "record_batch_reader": ("paimon_tpu.interop.arrow_surface", "record_batch_reader"),
        "call": ("paimon_tpu.sql", "call"),
        "query": ("paimon_tpu.sql", "query"),
        "execute_sql": ("paimon_tpu.sql", "execute"),
    }
    if name in lazy:
        import importlib

        module, attr = lazy[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'paimon_tpu' has no attribute {name!r}")


__version__ = "0.5.0"
