#!/usr/bin/env python
"""CDC subscription fan-out benchmark: decode-once at 1/8/32/128 subscribers.

The tentpole claim of the subscription service (service/subscription.py) is
that ONE tailer decodes each changelog snapshot exactly once and fans the
same decoded batches out to N subscribers — so decode work is flat in N and
aggregate delivered rows/s scales with N instead of dividing by it.

Two measured sides per subscriber count:

* **hub fan-out** — N subscribers on one SubscriptionHub follow a live
  writer streaming commits into a fresh table: the tailer decodes + merges
  each snapshot once and fans the shared batch to every queue. Reported:
  aggregate delivered rows/s (all subscribers, commit start -> last
  delivery), per-subscriber p99 delivery lag (commit -> batch handed to
  that subscriber), and the decode{pages_decoded} delta — asserted FLAT in
  N (the decode-once proof; the table reads through the native decoder so
  every decoded page counts).

* **independent scans** (baseline at N=32) — N independent StreamTableScan
  loops, each decoding for itself with the shared data-file cache disabled
  on its handle: the faithful model of N separate consumer processes, which
  cannot share decoded batches. Headline: hub aggregate rows/s >= 5x the
  independent aggregate at 32 subscribers.

Results land in benchmarks/results/subscribe_bench.json; bench.py runs
run_headline() for its spot-check row.
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_COMMITS = 16
ROWS_PER_COMMIT = 4_000
SUBSCRIBER_COUNTS = (1, 8, 32, 128)
BASELINE_N = 32
TARGET_SPEEDUP = 5.0


def _schema():
    import paimon_tpu as pt

    return pt.RowType.of(
        ("k", pt.BIGINT(False)),
        ("cat", pt.STRING()),  # low-cardinality: dictionary-encoded pages
        ("v", pt.DOUBLE()),
    )


def build_table(base: str, name: str):
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(base, commit_user="subbench")
    t = cat.create_table(
        f"db.{name}",
        _schema(),
        primary_keys=["k"],
        options={
            "bucket": "2",
            # every decoded page must count: the native decoder feeds
            # decode{pages_decoded}, which the flatness assertion reads
            "format.parquet.decoder": "native",
            "format.parquet.encoder": "native",
            "subscription.queue-depth": "64",
            "subscription.poll-backoff": "5 ms",
        },
    )
    return t


def stream_commits(table, commit_times: dict[int, float] | None = None, lock=None):
    """Write N_COMMITS commits of ROWS_PER_COMMIT rows, recording each landed
    append snapshot's commit time for lag measurement."""
    rng = np.random.default_rng(7)
    cats = np.array(["alpha", "beta", "gamma", "delta"], dtype=object)
    wb = table.new_batch_write_builder()
    for c in range(N_COMMITS):
        w = wb.new_write()
        keys = (np.arange(ROWS_PER_COMMIT, dtype=np.int64) + c * ROWS_PER_COMMIT).tolist()
        w.write(
            {
                "k": keys,
                "cat": cats[rng.integers(0, len(cats), ROWS_PER_COMMIT)].tolist(),
                "v": rng.random(ROWS_PER_COMMIT).tolist(),
            }
        )
        sids = wb.new_commit().commit(w.prepare_commit())
        if commit_times is not None:
            with lock:
                for sid in sids:
                    commit_times[sid] = time.perf_counter()


def _pages_decoded() -> int:
    from paimon_tpu.metrics import decode_metrics

    return decode_metrics().counter("pages_decoded").count


def _clear_data_file_cache() -> None:
    from paimon_tpu.utils.cache import data_file_cache

    data_file_cache().clear()


def _append_sids(table) -> set:
    from paimon_tpu.core.snapshot import CommitKind

    sm = table.store.snapshot_manager
    latest = sm.latest_snapshot_id() or 0
    return {
        i
        for i in range(1, latest + 1)
        if sm.snapshot_exists(i) and sm.snapshot(i).commit_kind == CommitKind.APPEND
    }


def run_hub(base: str, n_subs: int) -> dict:
    """N subscribers on a FRESH table with N_COMMITS of preloaded history:

    * throughput phase — every subscriber replays the history through the
      hub (decode + merge happen once; the replay cache and the live queue
      fan the shared batches out). Aggregate rows/s = total delivered rows /
      wall until every subscriber holds every APPEND snapshot.
    * lag phase (not counted in throughput) — a writer streams N_LIVE small
      commits; per-subscriber delivery lag (commit -> handed batch) is
      sampled across all subscribers.
    """
    from paimon_tpu.service.subscription import SubscriptionHub

    N_LIVE = 8
    table = build_table(base, f"hub{n_subs}")
    stream_commits(table)  # preloaded history (not timed)
    _clear_data_file_cache()
    pages0 = _pages_decoded()
    hub = SubscriptionHub(table.with_user("subbench-hub"))
    rows_delivered = [0] * n_subs
    received_sids: list[set] = [set() for _ in range(n_subs)]
    lags_ms: list[float] = []
    commit_times: dict[int, float] = {}
    commit_lock = threading.Lock()
    stop = threading.Event()
    lag_lock = threading.Lock()

    def consume(i: int, sub):
        while True:
            try:
                b = sub.poll(timeout=0.3)
            except Exception:
                break
            if b is None:
                if stop.is_set():
                    break
                continue
            rows_delivered[i] += b.num_rows
            received_sids[i].add(b.snapshot_id)
            with commit_lock:
                t0 = commit_times.get(b.snapshot_id)
            if t0 is not None:
                with lag_lock:
                    lags_ms.append((time.perf_counter() - t0) * 1000)

    history_sids = _append_sids(table)
    subs = [hub.subscribe(consumer_id=f"bench-{n_subs}-{i}", from_snapshot=1) for i in range(n_subs)]
    t_start = time.perf_counter()
    threads = [threading.Thread(target=consume, args=(i, s)) for i, s in enumerate(subs)]
    for th in threads:
        th.start()
    # throughput phase: wait until every subscriber replayed all history
    deadline = time.perf_counter() + 120.0
    while time.perf_counter() < deadline:
        if all(history_sids <= s for s in received_sids):
            break
        time.sleep(0.02)
    wall = time.perf_counter() - t_start
    agg_rows = sum(rows_delivered)
    # lag phase: a live writer streams small commits through the tailer
    wb = table.new_batch_write_builder()
    k = (N_COMMITS + 1) * ROWS_PER_COMMIT
    for _ in range(N_LIVE):
        w = wb.new_write()
        w.write({"k": list(range(k, k + 500)), "cat": ["alpha"] * 500, "v": [0.5] * 500})
        sids = wb.new_commit().commit(w.prepare_commit())
        with commit_lock:
            for sid in sids:
                commit_times[sid] = time.perf_counter()
        k += 500
        time.sleep(0.05)
    expected_sids = _append_sids(table)
    deadline = time.perf_counter() + 60.0
    while time.perf_counter() < deadline:
        if all(expected_sids <= s for s in received_sids):
            break
        time.sleep(0.05)
    stop.set()
    for th in threads:
        th.join(timeout=30.0)
    for s in subs:
        s.close()
    hub.close()
    for i, sids in enumerate(received_sids):
        assert expected_sids <= sids, (
            f"subscriber {i} of {n_subs} missed snapshots: "
            f"{sorted(expected_sids - sids)[:5]}"
        )
    pages = _pages_decoded() - pages0
    return {
        "subscribers": n_subs,
        "wall_s": round(wall, 3),
        "rows_delivered": agg_rows,
        "agg_rows_per_sec": round(agg_rows / wall, 1),
        "live_commits": N_LIVE,
        "snapshots": int(table.store.snapshot_manager.latest_snapshot_id()),
        "pages_decoded": pages,
        "_table": table,
        "lag_p50_ms": round(float(np.percentile(lags_ms, 50)), 2) if lags_ms else None,
        "lag_p99_ms": round(float(np.percentile(lags_ms, 99)), 2) if lags_ms else None,
    }


def run_independent(table, n_subs: int) -> dict:
    """Baseline: N independent StreamTableScan loops, data-file cache OFF on
    their handles (N separate consumer processes cannot share decoded
    batches). Each loop reads the same history for itself."""
    _clear_data_file_cache()
    pages0 = _pages_decoded()
    # cache opt-out on the reader handles only: 0-budget tables skip the
    # process-wide cache entirely (utils/cache.table_caches contract)
    reader_table = table.copy({"cache.data-file.max-memory-size": "0 b"})
    latest = table.store.snapshot_manager.latest_snapshot_id()
    rows_read = [0] * n_subs
    errors: list[str] = []

    def scan_loop(i: int):
        try:
            t = reader_table.with_user(f"indep-{i}")
            scan = t.new_read_builder().new_stream_scan()
            read = t.new_read_builder().new_read()
            scan.restore(1)
            while scan._next is not None and scan._next <= latest:
                splits = scan.plan()
                if splits is None:
                    break
                for s in splits:
                    data, _kinds = read.read_with_kinds(s)
                    rows_read[i] += data.num_rows
        except Exception as exc:  # pragma: no cover - surfaced in the report
            errors.append(f"loop {i}: {exc!r}")

    t_start = time.perf_counter()
    threads = [threading.Thread(target=scan_loop, args=(i,)) for i in range(n_subs)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t_start
    assert not errors, errors
    agg = sum(rows_read)
    return {
        "subscribers": n_subs,
        "wall_s": round(wall, 3),
        "rows_delivered": agg,
        "agg_rows_per_sec": round(agg / wall, 1),
        "pages_decoded": _pages_decoded() - pages0,
    }


def run_headline(iters: int = 1) -> list:
    """bench.py spot-check: hub at 32 vs independent at 32 + the flatness
    counters at 1 and 32 (the dedicated sweep runs via main())."""
    base = tempfile.mkdtemp(prefix="subscribe_bench_")
    try:
        hub1 = run_hub(base, 1)
        hub32 = run_hub(base, 32)
        indep = run_independent(hub32.pop("_table"), BASELINE_N)
        hub1.pop("_table", None)
        speedup = hub32["agg_rows_per_sec"] / max(indep["agg_rows_per_sec"], 1e-9)
        return [
            {
                "metric": "subscription fan-out (32 subscribers, decode-once hub vs independent scans)",
                "hub_rows_per_sec": hub32["agg_rows_per_sec"],
                "independent_rows_per_sec": indep["agg_rows_per_sec"],
                "speedup": round(speedup, 2),
                "pages_decoded_1_sub": hub1["pages_decoded"],
                "pages_decoded_32_subs": hub32["pages_decoded"],
                "lag_p99_ms_32_subs": hub32["lag_p99_ms"],
                "shed_subscribers": 0,
                "unit": "rows/s",
            }
        ]
    finally:
        shutil.rmtree(base, ignore_errors=True)


def main() -> int:
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "subscribe_bench.json")
    base = tempfile.mkdtemp(prefix="subscribe_bench_")
    results = {"config": {
        "commits": N_COMMITS,
        "rows_per_commit": ROWS_PER_COMMIT,
        "subscriber_counts": list(SUBSCRIBER_COUNTS),
        "baseline_subscribers": BASELINE_N,
    }}
    try:
        sweep = []
        baseline_table = None
        for n in SUBSCRIBER_COUNTS:
            row = run_hub(base, n)
            t = row.pop("_table")
            if n == BASELINE_N:
                baseline_table = t
            print(json.dumps(row))
            sweep.append(row)
        results["hub"] = sweep
        indep = run_independent(baseline_table, BASELINE_N)
        print(json.dumps(dict(indep, mode="independent")))
        results["independent"] = indep
        hub32 = next(r for r in sweep if r["subscribers"] == BASELINE_N)
        speedup = hub32["agg_rows_per_sec"] / max(indep["agg_rows_per_sec"], 1e-9)
        # decode-once proof: pages decoded must NOT scale with N. The live
        # phase writes a few extra snapshots per run, so allow small drift —
        # anything near-linear in N (128x) fails loudly.
        pages = {r["subscribers"]: r["pages_decoded"] for r in sweep}
        flat = max(pages.values()) <= 3 * max(min(pages.values()), 1)
        results["headline"] = {
            "speedup_at_32": round(speedup, 2),
            "target": TARGET_SPEEDUP,
            "pages_decoded_by_n": pages,
            "decode_once_flat": flat,
        }
        print(json.dumps(results["headline"]))
        assert flat, f"pages_decoded scales with subscriber count: {pages}"
        assert speedup >= TARGET_SPEEDUP, (
            f"hub fan-out speedup {speedup:.2f}x below the {TARGET_SPEEDUP}x target"
        )
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(results, f, indent=2)
        print(f"results -> {out_path}")
        return 0
    finally:
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
