#!/usr/bin/env python
"""Benchmark: production traffic soak — sustained concurrent commits and
snapshot-consistent reads under injected faults.

Runs the service.soak harness (N committer threads on shared buckets, M
verified readers, a dedicated full-compactor and a snapshot expirer, one
shared WriteBufferController) in two configurations:

  full        >= 60 s at a 5% injected transient-fault rate with admission
              control + the full resilience stack. The headline: sustained
              commits/s and p99 read latency with 0 failed commits, 0 lost
              or duplicated rows (oracle-log verified), and a post-soak
              orphan sweep leaving the on-disk file set exactly equal to
              the reachable closure (0 leaked files).
  seed        the contrast run WITHOUT backpressure and without IO/CAS
              retries (fs.retry.max-attempts=1, commit.max-retries=0): at
              the same fault rate commits abort, reads error, and aborted
              rounds strew orphans — recorded in the results JSON so the
              delta is auditable.

and (unless --no-process) the PROCESS-GRAIN crash soak (service.proc_soak):

  proc-full   >= 60 s with 2 writer + 1 reader OS processes sharing only
              the warehouse filesystem, scripted kill -9 deaths at every
              commit/flush crash point plus seeded random SIGKILLs, respawn
              with journal recovery and periodic orphan sweeps. Headline:
              accepted commits/s and kills survived with 0 lost/duplicated
              rows (journal-oracle fold == final scan), 0 read errors, and
              0 leaked files after the final sweep.
  proc-seed   the contrast WITHOUT CAS retries, recovery probes, or orphan
              sweeps: the same kill schedule loses commits outright
              (rounds_failed), strands landed-but-unaccounted commits
              (rounds_ack_lost with zero crash_recoveries), and leaks the
              kills' torn files (leaked_file_count > 0).

Prints one JSON line per configuration and writes
benchmarks/results/soak_bench.json.

    python benchmarks/soak_bench.py [--duration 60] [--fault-possibility 20]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_mode(mode: str, duration: float, possibility: int, seed: int) -> dict:
    from paimon_tpu.service.soak import SoakConfig, run_soak

    full = mode == "full"
    cfg = SoakConfig(
        duration_s=duration,
        writers=3,
        readers=2,
        fault_possibility=possibility,
        seed=seed,
        backpressure=full,
        resilient=full,
    )
    tmp = tempfile.mkdtemp(prefix=f"paimon_soak_bench_{mode}_")
    try:
        report = run_soak(tmp, cfg, domain=f"soakbench_{mode}_{seed}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    keep = [
        "wall_s",
        "consistent",
        "commits_ok",
        "commits_failed",
        "commits_conflict_survived",
        "commits_conflict_aborted",
        "commit_cas_retries",
        "commit_buckets_replanned",
        "accepted_commits",
        "accepted_rows",
        "commits_per_sec",
        "reads_ok",
        "read_errors",
        "reads_expired_race",
        "read_p50_ms",
        "read_p99_ms",
        "writes_throttled",
        "writes_rejected",
        "backpressure_ms_mean",
        "lost_rows",
        "duplicated_rows",
        "orphans_removed",
        "leaked_file_count",
    ]
    row = {
        "metric": "traffic soak (3 writers / 2 readers, shared buckets, churning compaction+expiry)",
        "mode": "full (backpressure + resilience)" if full else "seed (no backpressure, no retries)",
        "fault_rate": round(1.0 / possibility, 3) if possibility else 0.0,
        **{k: report.get(k) for k in keep},
    }
    if full:
        # the acceptance gate: a full-stack soak at 5% faults must be clean
        assert report["consistent"], report
        assert report["commits_failed"] == 0, report
        assert report["lost_rows"] == 0 and report["duplicated_rows"] == 0, report
        assert report["leaked_file_count"] == 0, report
        assert report["read_p99_ms"] is not None, report
    return row


def run_proc_mode(mode: str, duration: float, seed: int) -> dict:
    from paimon_tpu.service.proc_soak import DEFAULT_SCRIPTED_KILLS, ProcSoakConfig, run_proc_soak

    full = mode == "proc-full"
    cfg = ProcSoakConfig(
        duration_s=duration,
        writers=2,
        readers=1,
        seed=seed,
        scripted_kills=DEFAULT_SCRIPTED_KILLS,
        kill_period_s=8.0,
        sweep_period_s=12.0,
        resilient=full,
    )
    tmp = tempfile.mkdtemp(prefix=f"paimon_proc_soak_bench_{mode}_")
    try:
        report = run_proc_soak(tmp, cfg)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    keep = [
        "wall_s",
        "consistent",
        "accepted_commits",
        "commits_per_sec",
        "rounds_intended",
        "rounds_landed",
        "rounds_failed",
        "rounds_ack_lost",
        "crash_recoveries",
        "procs_spawned",
        "procs_killed",
        "procs_respawned",
        "sweeps_during_soak",
        "reads_ok",
        "read_errors",
        "lost_rows",
        "duplicated_rows",
        "expected_unique_keys",
        "total_record_count",
        "orphans_removed",
        "leaked_file_count",
    ]
    row = {
        "metric": "process-grain crash soak (2 writer + 1 reader OS processes, kill -9 at crash points + random)",
        "mode": (
            "full (journal recovery + CAS retries + orphan sweep)"
            if full
            else "seed (no retries, no recovery probe, no sweep)"
        ),
        **{k: report.get(k) for k in keep},
    }
    if full:
        # the acceptance gate: >= 5 process kills survived with nothing lost
        assert report["consistent"], report
        assert report["procs_killed"] >= 5, report
        assert report["lost_rows"] == 0 and report["duplicated_rows"] == 0, report
        assert report["read_errors"] == 0, report
        assert report["leaked_file_count"] == 0, report
        assert report["total_record_count"] == report["expected_unique_keys"], report
    else:
        # the contrast gate: the same kill schedule demonstrably loses
        # commits and/or leaks files without the recovery machinery
        assert report["leaked_file_count"] > 0 or report["rounds_failed"] > 0, report
        assert report["crash_recoveries"] == 0, report
    return row


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # host-side soak: never grab the chip
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--seed-duration", type=float, default=20.0, help="contrast run length")
    ap.add_argument("--fault-possibility", type=int, default=20, help="1/N ops fail (20 = 5%%)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-process", action="store_true", help="skip the process-grain rows")
    ap.add_argument("--no-thread", action="store_true", help="skip the thread-soak rows")
    args = ap.parse_args()
    rows = []
    modes = []
    if not args.no_thread:
        modes += [("full", args.duration), ("seed", args.seed_duration)]
    if not args.no_process:
        modes += [("proc-full", args.duration), ("proc-seed", args.seed_duration)]
    for mode, dur in modes:
        if mode.startswith("proc"):
            row = run_proc_mode(mode, dur, args.seed)
        else:
            row = run_mode(mode, dur, args.fault_possibility, args.seed)
        rows.append(row)
        print(json.dumps(row))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "soak_bench.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
