#!/usr/bin/env python
"""Benchmark: production traffic soak — sustained concurrent commits and
snapshot-consistent reads under injected faults.

Runs the service.soak harness (N committer threads on shared buckets, M
verified readers, a dedicated full-compactor and a snapshot expirer, one
shared WriteBufferController) in two configurations:

  full        >= 60 s at a 5% injected transient-fault rate with admission
              control + the full resilience stack. The headline: sustained
              commits/s and p99 read latency with 0 failed commits, 0 lost
              or duplicated rows (oracle-log verified), and a post-soak
              orphan sweep leaving the on-disk file set exactly equal to
              the reachable closure (0 leaked files).
  seed        the contrast run WITHOUT backpressure and without IO/CAS
              retries (fs.retry.max-attempts=1, commit.max-retries=0): at
              the same fault rate commits abort, reads error, and aborted
              rounds strew orphans — recorded in the results JSON so the
              delta is auditable.

Prints one JSON line per configuration and writes
benchmarks/results/soak_bench.json.

    python benchmarks/soak_bench.py [--duration 60] [--fault-possibility 20]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_mode(mode: str, duration: float, possibility: int, seed: int) -> dict:
    from paimon_tpu.service.soak import SoakConfig, run_soak

    full = mode == "full"
    cfg = SoakConfig(
        duration_s=duration,
        writers=3,
        readers=2,
        fault_possibility=possibility,
        seed=seed,
        backpressure=full,
        resilient=full,
    )
    tmp = tempfile.mkdtemp(prefix=f"paimon_soak_bench_{mode}_")
    try:
        report = run_soak(tmp, cfg, domain=f"soakbench_{mode}_{seed}")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    keep = [
        "wall_s",
        "consistent",
        "commits_ok",
        "commits_failed",
        "commits_conflict_survived",
        "commits_conflict_aborted",
        "commit_cas_retries",
        "commit_buckets_replanned",
        "accepted_commits",
        "accepted_rows",
        "commits_per_sec",
        "reads_ok",
        "read_errors",
        "reads_expired_race",
        "read_p50_ms",
        "read_p99_ms",
        "writes_throttled",
        "writes_rejected",
        "backpressure_ms_mean",
        "lost_rows",
        "duplicated_rows",
        "orphans_removed",
        "leaked_file_count",
    ]
    row = {
        "metric": "traffic soak (3 writers / 2 readers, shared buckets, churning compaction+expiry)",
        "mode": "full (backpressure + resilience)" if full else "seed (no backpressure, no retries)",
        "fault_rate": round(1.0 / possibility, 3) if possibility else 0.0,
        **{k: report.get(k) for k in keep},
    }
    if full:
        # the acceptance gate: a full-stack soak at 5% faults must be clean
        assert report["consistent"], report
        assert report["commits_failed"] == 0, report
        assert report["lost_rows"] == 0 and report["duplicated_rows"] == 0, report
        assert report["leaked_file_count"] == 0, report
        assert report["read_p99_ms"] is not None, report
    return row


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # host-side soak: never grab the chip
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--seed-duration", type=float, default=20.0, help="contrast run length")
    ap.add_argument("--fault-possibility", type=int, default=20, help="1/N ops fail (20 = 5%%)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    rows = []
    for mode, dur in (("full", args.duration), ("seed", args.seed_duration)):
        row = run_mode(mode, dur, args.fault_possibility, args.seed)
        rows.append(row)
        print(json.dumps(row))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "soak_bench.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
