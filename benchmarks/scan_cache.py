#!/usr/bin/env python
"""Benchmark: cold-vs-warm repeated scan (plan + read_all) through the
byte-budget caches (utils.cache).

Workload: a primary-key table written as several sorted runs then fully
compacted (the steady state of a serving table), re-scanned repeatedly —
the repeated-query shape the manifest object cache and decoded data-file
cache exist for. "Cold" clears both caches first (every plan re-fetches the
snapshot + manifests and re-decodes every parquet file); "warm" re-runs the
identical plan + read against populated caches.

Prints one JSON line per metric:
  repeated-scan cold  (ms)
  repeated-scan warm  (ms)
  repeated-scan speedup (warm cache)   <- acceptance: >= 5x
plus a final line with the cache counters from the metrics registry.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_ROWS = 400_000
N_RUNS = 4


def build_table(path: str):
    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(path, commit_user="bench")
    schema = pt.RowType.of(
        ("id", pt.BIGINT(False)),
        ("c1", pt.BIGINT()),
        ("d1", pt.DOUBLE()),
        ("s1", pt.STRING()),
        ("s2", pt.STRING()),
    )
    table = cat.create_table(
        "bench.scan_cache",
        schema,
        primary_keys=["id"],
        options={
            "bucket": "1",
            "file.format": "parquet",
            "cache.manifest.max-memory-size": "256 mb",
            "cache.data-file.max-memory-size": "1 gb",
        },
    )
    rng = np.random.default_rng(11)
    ids = rng.permutation(N_ROWS).astype(np.int64)
    per = N_ROWS // N_RUNS
    for r in range(N_RUNS):
        chunk = np.sort(ids[r * per : (r + 1) * per])
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write(
            {
                "id": chunk,
                "c1": chunk * 3,
                "d1": chunk.astype(np.float64) * 0.5,
                "s1": np.array([f"val-{int(x) % 1000:04d}" for x in chunk], dtype=object),
                "s2": np.array([f"tag-{int(x) % 10}" for x in chunk], dtype=object),
            }
        )
        if r == N_RUNS - 1:
            w.compact(full=True)  # settle into one sorted run (serving shape)
        wb.new_commit().commit(w.prepare_commit())
    return table


def scan_once(table) -> float:
    rb = table.new_read_builder()
    t0 = time.perf_counter()
    splits = rb.new_scan().plan()
    out = rb.new_read().read_all(splits)
    dt = (time.perf_counter() - t0) * 1000
    assert out.num_rows == N_ROWS, out.num_rows
    return dt


def main():
    from paimon_tpu.metrics import registry
    from paimon_tpu.utils import cache as cache_mod

    tmp = tempfile.mkdtemp(prefix="paimon_tpu_scan_cache_")
    try:
        table = build_table(tmp)
        # warm jit / pyarrow process globals WITHOUT the caches, so cold-vs-
        # warm isolates the caching effect rather than first-run compile cost
        plain = table.copy(
            {"cache.manifest.max-memory-size": "0 b", "cache.data-file.max-memory-size": "0 b"}
        )
        scan_once(plain)

        cold = min(self_time for self_time in (_cold_pass(table, cache_mod) for _ in range(3)))
        scan_once(table)  # populate
        warm = min(scan_once(table) for _ in range(5))
        speedup = cold / warm if warm > 0 else float("inf")
        print(json.dumps({"metric": "repeated-scan cold", "value": round(cold, 2), "unit": "ms"}))
        print(json.dumps({"metric": "repeated-scan warm", "value": round(warm, 2), "unit": "ms"}))
        print(
            json.dumps(
                {
                    "metric": "repeated-scan speedup (warm cache)",
                    "value": round(speedup, 2),
                    "unit": "x",
                    "target": ">= 5x",
                    "rows": N_ROWS,
                }
            )
        )
        counters = {
            name: stats
            for name, stats in registry.snapshot().items()
            if name.startswith("cache")
        }
        print(json.dumps({"metric": "cache counters", "value": counters}))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _cold_pass(table, cache_mod) -> float:
    cache_mod.clear_all()
    return scan_once(table)


if __name__ == "__main__":
    main()
