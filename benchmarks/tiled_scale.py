#!/usr/bin/env python
"""Scale proof for the key-range tiled merge (VERDICT r2 #10): a section far
larger than one device dispatch should stream through deduplicate_select_tiled
with correctness intact and throughput roughly flat across tile sizes (the
async per-tile dispatch overlaps host slicing with device sorts).

The reference handles over-memory sections by spilling (MergeSorter.java:
110-116); here the key space is cut on the most significant lane so every
duplicate lands in exactly one tile — no spill files, no re-merge pass.

Emits one JSON line per (rows, tile_rows) cell + a correctness line.
Usage: python benchmarks/tiled_scale.py [--rows 16777216] [--tiles 1048576,4194304,16777216]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paimon_tpu.utils import enable_compile_cache
from paimon_tpu.utils.tpuguard import ensure_live_backend

enable_compile_cache()
PLATFORM = ensure_live_backend()

BASE = 975_400.0


def emit(metric, value, unit="rows/s", **extra):
    print(
        json.dumps(
            {"metric": metric, "value": round(value, 1), "unit": unit,
             "vs_baseline": round(value / BASE, 3) if unit == "rows/s" else None,
             "platform": PLATFORM, **extra}
        ),
        flush=True,
    )


def make_runs(n: int, n_runs: int = 4, dup: int = 4, seed: int = 11):
    """n rows as n_runs key-sorted runs (ascending seq across runs), the
    shape deduplicate_select_tiled expects."""
    rng = np.random.default_rng(seed)
    n -= n % n_runs  # runs must tile the input exactly (no orphan rows)
    keys = rng.integers(0, max(n // dup, 1), size=n, dtype=np.uint32)
    per = n // n_runs
    lanes = np.empty((n, 1), dtype=np.uint32)
    offsets = [0]
    for r in range(n_runs):
        chunk = np.sort(keys[r * per : (r + 1) * per])
        lanes[r * per : (r + 1) * per, 0] = chunk
        offsets.append((r + 1) * per)
    return lanes, offsets


def oracle(lanes: np.ndarray, offsets) -> np.ndarray:
    """Numpy ground truth: per key, the LAST occurrence in run order (runs
    are seq-ascending, stability ties to input order)."""
    keys = lanes[:, 0]
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    keep_last = np.concatenate([sk[1:] != sk[:-1], [True]])
    return order[keep_last]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=16 * 1024 * 1024)
    ap.add_argument("--tiles", default="1048576,4194304,16777216")
    args = ap.parse_args()

    from paimon_tpu.ops.merge import deduplicate_select_tiled

    lanes, offsets = make_runs(args.rows)
    args.rows = offsets[-1]  # rounded to a run multiple
    expect = np.sort(oracle(lanes, offsets))

    for tile in (int(x) for x in args.tiles.split(",")):
        t0 = time.perf_counter()
        got = deduplicate_select_tiled(lanes, offsets, tile_rows=tile)
        dt = time.perf_counter() - t0
        ok = np.array_equal(np.sort(np.asarray(got)), expect)
        emit(
            f"tiled-dedup.tile{tile}", args.rows / dt, rows=args.rows,
            tile_rows=tile, selected=int(len(got)), correct=bool(ok),
        )
        if not ok:
            emit("tiled-dedup.MISMATCH", 0.0, unit="flag", tile_rows=tile)
            sys.exit(2)
    emit("tiled-dedup.correctness", 1.0, unit="flag", rows=args.rows)


if __name__ == "__main__":
    main()
