#!/usr/bin/env python
"""Benchmark: cluster coordinator/worker scaling (service.cluster).

Aggregate ingest + merge-read rows/s at 1/2/4 worker OS processes, each
worker a private jax runtime with 2 forced-host virtual devices running
merge.engine=mesh over its bucket shard. The coordinator runs in THIS
process and is the only committer; workers ship CommitMessages over the
cluster RPC.

Storage sits behind fs/testing.LatencyFileIO in the WORKERS only (the data
plane pays object-store RTT; the committer's metadata writes stay local —
the single-parallelism committer is deliberately cheap, exactly the
reference topology where task managers stream to S3 while the committer
touches only manifests). On this 1-core CI rig the per-file RTT is the
resource worker processes scale on: W workers sleep their read RTTs
concurrently, and within each worker the mesh feeder overlaps one prefetch
lane per device. Real chips add compute scaling on top.

Every run asserts correctness before any time counts:
  * each worker's timed merge-read digest is identical across passes, and
  * equals the digest of a SINGLE-PROCESS oracle table built from the same
    deterministic per-(bucket, round) rows — final cluster table state is
    bit-identical to the oracle, at every worker count.

Headline (asserted in main): aggregate rows/s at 4 workers >= 2.5x 1 worker.
Results land in benchmarks/results/cluster_bench.json.
"""

import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

N_BUCKETS = 8
ROUNDS = int(os.environ.get("PAIMON_TPU_CLUSTER_BENCH_ROUNDS", "4"))
ROWS_PER_BUCKET = int(os.environ.get("PAIMON_TPU_CLUSTER_BENCH_ROWS", "100"))
READ_ITERS = int(os.environ.get("PAIMON_TPU_CLUSTER_BENCH_READS", "8"))
RTT_READ_MS = float(os.environ.get("PAIMON_TPU_CLUSTER_BENCH_RTT_MS", "200"))
RTT_WRITE_MS = float(os.environ.get("PAIMON_TPU_CLUSTER_BENCH_WRITE_RTT_MS", "5"))
DEVICES_PER_WORKER = 2
WORKER_COUNTS = (1, 2, 4)
RESULTS = os.path.join(HERE, "results", "cluster_bench.json")

TABLE_OPTIONS = {
    "bucket": str(N_BUCKETS),
    "write-only": "true",
    "merge.engine": "mesh",
    "sort-engine": "xla-segmented",
    "write-buffer-rows": str(ROWS_PER_BUCKET * N_BUCKETS * 2),
    # data bytes cold on every timed pass; decoded manifests stay warm
    "cache.data-file.max-memory-size": "0 b",
}


def _create_table(root: str) -> None:
    from paimon_tpu.core.schema import SchemaManager
    from paimon_tpu.fs import get_file_io
    from paimon_tpu.service.soak import SCHEMA

    SchemaManager(get_file_io(root), root).create_table(
        SCHEMA, primary_keys=["k"], options=TABLE_OPTIONS
    )


def _child_env(devices: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split() if not f.startswith("--xla_force_host_platform_device_count")
    )
    env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={devices}").strip()
    env["PAIMON_TPU_CLUSTER_ROLE"] = "worker"
    # one IO lane per device PER WORKER HOST (the multichip_bench rule): a
    # worker models one host whose store concurrency is bounded by its own
    # device count — aggregate IO lanes then grow with worker processes,
    # which is exactly the axis this bench measures
    env["PAIMON_TPU_SHARED_POOL_WORKERS"] = str(devices)
    env["PYTHONPATH"] = os.path.dirname(HERE) + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    return env


def _oracle_digests(root: str, bucket_sets: list[list[int]]) -> tuple[dict, int]:
    """Build the single-process oracle (same deterministic rows: round r
    writes pools[b] with v = r*1000 + k%997 for EVERY bucket, rounds
    0..ROUNDS) and digest each worker's bucket set the way the worker does."""
    import numpy as np

    from paimon_tpu.core.manifest import ManifestCommittable
    from paimon_tpu.service.cluster import bucket_key_pools
    from paimon_tpu.service.soak import SCHEMA
    from paimon_tpu.table import load_table
    from paimon_tpu.table.write import TableWrite

    oroot = root + "_oracle"
    _create_table(oroot)
    t = load_table(oroot, commit_user="oracle")
    pools = bucket_key_pools(N_BUCKETS, 0, ROWS_PER_BUCKET)
    for r in range(ROUNDS + 1):  # the workers' warm round 0 + timed 1..ROUNDS
        ks = [k for b in range(N_BUCKETS) for k in pools[b].tolist()]
        vs = [float(r * 1000 + (k % 997)) for k in ks]
        tw = TableWrite(t)
        tw.write({"k": ks, "v": vs})
        msgs = tw.prepare_commit()
        tw.close()
        t.store.new_commit().commit(ManifestCommittable(r + 1, messages=msgs))
    digests = {}
    total_rows = 0
    for buckets in bucket_sets:
        rb = t.new_read_builder()
        splits = [s for s in rb.new_scan().plan() if s.bucket in set(buckets)]
        out = rb.new_read().read_all(splits)
        ks = np.asarray(out.column("k").values)
        vs = np.asarray(out.column("v").values)
        order = np.argsort(ks)
        digests[tuple(sorted(buckets))] = hashlib.sha256(
            ks[order].tobytes() + vs[order].tobytes()
        ).hexdigest()
        total_rows += out.num_rows
    return digests, total_rows


def run_point(workers: int, base: str) -> dict:
    from paimon_tpu.service.cluster import ClusterConfig, ClusterCoordinator

    root = os.path.join(base, f"cluster_w{workers}")
    _create_table(root)
    cfg = ClusterConfig(workers=workers, buckets=N_BUCKETS, compaction=False, serve=False)
    coord = ClusterCoordinator(root, cfg).start()
    procs = []
    logs = []
    try:
        for wid in range(workers):
            log = open(os.path.join(base, f"bench-w{workers}-{wid}.log"), "wb")
            logs.append(log)
            procs.append(
                subprocess.Popen(
                    [
                        sys.executable, "-m", "paimon_tpu.service.cluster", "worker",
                        "--table", f"latency://{root}",
                        "--wid", str(wid),
                        "--coordinator", f"{coord.host}:{coord.port}",
                        "--mode", "bench",
                        "--rounds", str(ROUNDS),
                        "--read-iters", str(READ_ITERS),
                        "--round-rows", str(ROWS_PER_BUCKET),
                        "--expected-workers", str(workers),
                        "--devices", str(DEVICES_PER_WORKER),
                        "--rtt-read-ms", str(RTT_READ_MS),
                        "--rtt-write-ms", str(RTT_WRITE_MS),
                        "--no-serve",
                    ],
                    env=_child_env(DEVICES_PER_WORKER),
                    stdout=log,
                    stderr=subprocess.STDOUT,
                )
            )
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            with coord._lock:
                if sum(1 for s in coord._slots.values() if s.alive) == workers:
                    break
            time.sleep(0.1)
        coord.go_event.set()
        while not coord.all_done():
            if time.monotonic() > deadline + 600:
                raise RuntimeError(f"bench point workers={workers} timed out")
            for p in procs:
                if p.poll() not in (None, 0):
                    tail = open(logs[procs.index(p)].name, "rb").read()[-2000:]
                    raise RuntimeError(f"bench worker died rc={p.returncode}:\n{tail.decode(errors='replace')}")
            time.sleep(0.1)
        status = coord.handle("status", {})
        stats = {int(w): s["done"] for w, s in status["workers"].items()}
    finally:
        coord.stop_event.set()
        for p in procs:
            try:
                p.wait(timeout=60)
            except subprocess.TimeoutExpired:
                p.kill()
        coord.close()
        for log in logs:
            log.close()
    bucket_sets = [st["buckets"] for st in stats.values()]
    digests, _ = _oracle_digests(root, bucket_sets)
    for wid, st in stats.items():
        want = digests[tuple(sorted(st["buckets"]))]
        assert st["digest"] == want, (
            f"worker {wid} final state diverged from the single-process oracle"
        )
    total_rows = sum(st["ingested"] + st["rows_read"] for st in stats.values())
    wall = max(st["wall_s"] for st in stats.values())
    return {
        "workers": workers,
        "devices_per_worker": DEVICES_PER_WORKER,
        "rows_ingested": sum(st["ingested"] for st in stats.values()),
        "rows_merge_read": sum(st["rows_read"] for st in stats.values()),
        "wall_s": round(wall, 3),
        "ingest_s_max": round(max(st.get("ingest_s", 0) for st in stats.values()), 3),
        "read_s_max": round(max(st.get("read_s", 0) for st in stats.values()), 3),
        "rows_per_sec": round(total_rows / wall, 1),
        "oracle_identical": True,
    }


def main() -> None:
    base = tempfile.mkdtemp(prefix="paimon_cluster_bench_")
    points = []
    try:
        for w in WORKER_COUNTS:
            pt = run_point(w, base)
            pt["cores"] = os.cpu_count()
            pt["rtt_read_ms"] = RTT_READ_MS
            print(json.dumps(pt))
            points.append(pt)
    finally:
        shutil.rmtree(base, ignore_errors=True)
    base_rate = points[0]["rows_per_sec"]
    top = points[-1]
    scaling = round(top["rows_per_sec"] / base_rate, 2)
    row = {
        "metric": "cluster aggregate ingest+merge-read scaling",
        "unit": "rows/s",
        **{f"rows_per_sec@{p['workers']}w": p["rows_per_sec"] for p in points},
        "scaling": scaling,
        "scaling_workers": f"{top['workers']} vs {points[0]['workers']}",
    }
    print(json.dumps(row))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump({"rtt_read_ms": RTT_READ_MS, "points": points, "row": row}, f, indent=1)
    assert scaling >= 2.5, f"cluster scaling {scaling} < 2.5x at {top['workers']} workers"


if __name__ == "__main__":
    main()
