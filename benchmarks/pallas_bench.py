#!/usr/bin/env python
"""Benchmark: sort-engine comparison (ISSUE 11) — the fused pallas merge
kernel vs the stock xla-segmented path, per schema, every timed pass
asserting bit-identical output.

Two schemas spanning the kernel's lane shapes:

  int_pk     — single BIGINT primary key (1-2 sort operands after
               truncation: the minimal fused compare network)
  composite  — 4-column composite STRING key (packed + OVC lanes: the wide
               compare network, PR 6 composition)

Per schema the bench measures merge-read rows/s with sort-engine =
xla-segmented vs pallas through table.copy over the SAME physical table
(identical files, pages, cache state), plus a kernel-level dedup micro row
(deduplicate_select xla vs pallas at 2^17 rows) and the pallas{} counter
breakdown.

On a CPU rig the pallas engine runs under interpret=True — the numbers
prove the engine is not a regression and the outputs are bit-identical; the
fused-kernel speed itself is a chip question (benchmarks/pallas_verdict.py
runs the kernels on real hardware). Results land in
benchmarks/results/pallas_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_ROWS = 400_000
N_RUNS = 4
ITERS = 3
RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "pallas_bench.json")


def _schemas():
    import paimon_tpu as pt

    return {
        "int_pk": dict(
            schema=pt.RowType.of(("id", pt.BIGINT(False)), ("v", pt.BIGINT()), ("w", pt.DOUBLE())),
            keys=["id"],
        ),
        "composite": dict(
            schema=pt.RowType.of(
                ("region", pt.STRING(False)),
                ("dept", pt.STRING(False)),
                ("user", pt.STRING(False)),
                ("item", pt.STRING(False)),
                ("v", pt.BIGINT()),
            ),
            keys=["region", "dept", "user", "item"],
        ),
    }


def _rows(kind, n, rng):
    if kind == "int_pk":
        ids = rng.integers(0, n * 2, n).astype(np.int64)
        return {"id": ids, "v": ids * 3, "w": ids.astype(np.float64) * 0.5}
    region = np.array([f"acct-region-{int(x):02d}" for x in rng.integers(0, 8, n)], dtype=object)
    dept = np.array([f"acct-dept-{int(x):03d}" for x in rng.integers(0, 64, n)], dtype=object)
    user = np.array([f"user-{int(x):05d}" for x in rng.integers(0, 2000, n)], dtype=object)
    item = np.array([f"item-{int(x):04d}" for x in rng.integers(0, 500, n)], dtype=object)
    return {
        "region": region,
        "dept": dept,
        "user": user,
        "item": item,
        "v": rng.integers(0, 1 << 40, n).astype(np.int64),
    }


def _pallas_counters():
    from paimon_tpu.metrics import pallas_metrics

    g = pallas_metrics()
    return {k: g.counter(k).count for k in ("kernels_launched", "tiles", "fallback_xla")}


def build_table(cat_path, kind, spec):
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(cat_path, commit_user="pallas-bench")
    base = cat.create_table(
        f"b.mr_{kind}",
        spec["schema"],
        primary_keys=spec["keys"],
        options={"bucket": "1", "file.format": "parquet", "write-only": "true"},
    )
    rng = np.random.default_rng(7)
    per = N_ROWS // N_RUNS
    for _ in range(N_RUNS):
        wb = base.new_batch_write_builder()
        w = wb.new_write()
        w.write(_rows(kind, per, rng))
        wb.new_commit().commit(w.prepare_commit())
    return base


def bench_merge_read(base, kind):
    return _compare_engines(base, {"schema": kind, "workload": "merge_read", "rows": N_ROWS}, {})


def bench_merge_read_tiled(base, kind):
    """Same table, key-range tiled at 2^17 rows per device merge step for
    BOTH engines: tiles pad to a VMEM-resident size, so the pallas side
    runs the FUSED sort+segment kernel instead of the sweep tier."""
    row = {"schema": kind, "workload": "merge_read_tiled_128k", "rows": N_ROWS}
    return _compare_engines(base, row, {"merge.read-batch-rows": str(1 << 17)})


def _compare_engines(base, row, extra):
    outs = {}
    for engine in ("xla-segmented", "pallas"):
        t = base.copy({"sort-engine": engine, **extra})
        rb = t.new_read_builder()
        best = float("inf")
        c0 = _pallas_counters()
        out = None
        for it in range(ITERS + 1):  # first pass warms jit caches
            t0 = time.perf_counter()
            out = rb.new_read().read_all(rb.new_scan().plan())
            dt = time.perf_counter() - t0
            if it > 0:
                best = min(best, dt)
        outs[engine] = out
        tag = engine.replace("-", "_")
        row[f"rows_per_sec_{tag}"] = round(out.num_rows / best, 1)
        if engine == "pallas":
            c1 = _pallas_counters()
            row["pallas_counters"] = {k: c1[k] - c0[k] for k in c0}
    assert outs["pallas"].to_pylist() == outs["xla-segmented"].to_pylist(), (
        f"{kind}: pallas read differs from xla-segmented"
    )
    row["identical_output"] = True
    row["speedup"] = round(row["rows_per_sec_pallas"] / row["rows_per_sec_xla_segmented"], 3)
    return row


def bench_kernel_micro():
    """Raw dedup kernel at 2^17 rows: dispatch+resolve wall, xla vs pallas
    (fused tier), identical selection asserted."""
    from paimon_tpu.ops import merge as M

    rng = np.random.default_rng(3)
    n = 1 << 17
    lanes = rng.integers(0, n, (n, 1)).astype(np.uint32)
    row = {"workload": "dedup_kernel_micro", "rows": n}
    sels = {}
    for backend in ("xla", "pallas"):
        best = float("inf")
        for it in range(ITERS + 1):
            t0 = time.perf_counter()
            sel = M.deduplicate_resolve(
                M.deduplicate_select_async(lanes, None, backend=backend, compress=False)
            )
            dt = time.perf_counter() - t0
            if it > 0:
                best = min(best, dt)
        sels[backend] = sel
        row[f"rows_per_sec_{backend}"] = round(n / best, 1)
    assert sels["pallas"].tolist() == sels["xla"].tolist()
    row["identical_output"] = True
    row["speedup"] = round(row["rows_per_sec_pallas"] / row["rows_per_sec_xla"], 3)
    return row


def run(write_json=True):
    import jax

    from paimon_tpu.utils import enable_compile_cache

    enable_compile_cache()
    platform = jax.default_backend()
    tmp = tempfile.mkdtemp(prefix="paimon_pallas_bench_")
    rows = []
    try:
        rows.append(bench_kernel_micro())
        for kind, spec in _schemas().items():
            base = build_table(os.path.join(tmp, kind), kind, spec)
            rows.append(bench_merge_read(base, kind))
            rows.append(bench_merge_read_tiled(base, kind))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    for r in rows:
        r["platform"] = platform + ("(interpret)" if platform == "cpu" else "")
        print(json.dumps(r))
    if write_json:
        os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
        with open(RESULTS, "w") as f:
            json.dump({"rows": rows, "platform": platform}, f, indent=2)
    return rows


if __name__ == "__main__":
    run()
