#!/usr/bin/env python
"""Background chip-grant watcher (VERDICT r4 #1).

Round 4 ended with ZERO chip evidence because every measurement attempt
blocked a work turn on a wedged tunnel. This watcher inverts that: it runs
detached for the whole round, keeps one sentinel probe in flight (via
tpuguard's detached-probe cache), and the moment the grant frees it runs the
full measurement suite unattended, appending each JSON result line to
benchmarks/results/ROUND5_CHIP.jsonl as it lands (partial progress counts).

Discipline rules it inherits from tpuguard (see paimon_tpu/utils/tpuguard.py):
  - the watcher process itself NEVER imports jax (policy code must not init
    a backend); it only reads the probe cache and spawns subprocesses
  - suite steps run serially (single CPU core; single device grant)
  - on a step timeout: SIGTERM (clean-exit handlers release the grant),
    bounded wait, NEVER SIGKILL (a killed client wedges the tunnel for hours)

Re-trigger protocol: the suite runs once per request token. After improving
kernel/decode code, write a new token to benchmarks/results/WATCHER_REQUEST
and the watcher re-runs the suite on the next grant. Status is mirrored to
benchmarks/results/WATCHER_STATUS.json every loop for humans.

Launch (detached):  nohup python benchmarks/chip_watcher.py >/dev/null 2>&1 &
No reference counterpart: the reference benchmarks on a local JVM; a remote
single-grant accelerator needs this scheduling layer.
"""

from __future__ import annotations

import fcntl
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paimon_tpu.utils.tpuguard import probe_devices  # noqa: E402  (no jax import)

RESULTS = os.path.join(REPO, "benchmarks", "results")
CHIP_LOG = os.path.join(RESULTS, "ROUND5_CHIP.jsonl")
REQUEST = os.path.join(RESULTS, "WATCHER_REQUEST")
DONE = os.path.join(RESULTS, "WATCHER_DONE")
STATUS = os.path.join(RESULTS, "WATCHER_STATUS.json")
WATCHER_LOCK = "/tmp/paimon_tpu_chip_watcher.lock"
LOG = os.path.join(RESULTS, "watcher.log")

# Priority-ordered suite: headline first (also refreshes LATEST_CHIP.json),
# then the below-1x BASELINE configs, then tiled cold+warm (VERDICT #7),
# then the broad micro suite. Matches round-3 scales for comparability.
SUITE = [
    ("bench", [sys.executable, "bench.py"], 2400),
    ("baseline_configs", [sys.executable, "benchmarks/baseline_configs.py",
                          "--scale", "4", "--configs", "2,3,4,5"], 3600),
    ("tiled_cold", [sys.executable, "benchmarks/tiled_scale.py",
                    "--rows", "8388608"], 2400),
    ("tiled_warm", [sys.executable, "benchmarks/tiled_scale.py",
                    "--rows", "8388608"], 2400),
    ("micro", [sys.executable, "benchmarks/micro_benchmarks.py"], 2400),
    ("kernel_resident", [sys.executable, "benchmarks/kernel_resident.py"], 2400),
]


def log(msg: str) -> None:
    line = f"[{time.strftime('%Y-%m-%dT%H:%M:%S')}] {msg}\n"
    with open(LOG, "a") as f:
        f.write(line)


def write_status(**kw) -> None:
    kw["updated"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    tmp = STATUS + ".tmp"
    with open(tmp, "w") as f:
        json.dump(kw, f, indent=1)
    os.replace(tmp, STATUS)


def read_token(path: str) -> str:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return ""


def append_results(step: str, stdout: bytes) -> int:
    """Append every JSON line from a step's stdout to the chip log."""
    n = 0
    with open(CHIP_LOG, "a") as out:
        for raw in stdout.decode(errors="replace").splitlines():
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                row = json.loads(raw)
            except ValueError:
                continue
            row["step"] = step
            row["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
            out.write(json.dumps(row) + "\n")
            out.flush()
            n += 1
    return n


def run_step(name: str, cmd: list[str], timeout_s: int) -> bool:
    """One suite step: PAIMON_TPU_REQUIRE=1 so a CPU fallback exits 3 and
    never pollutes the chip log. SIGTERM-then-wait on timeout; no SIGKILL."""
    env = dict(os.environ, PAIMON_TPU_REQUIRE="1", PAIMON_TPU_BENCH_RETRY_S="60")
    log(f"step {name}: {' '.join(cmd)}")
    proc = subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(f"step {name}: timeout after {timeout_s}s -> SIGTERM (never SIGKILL)")
        proc.send_signal(signal.SIGTERM)
        try:
            out, err = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            log(f"step {name}: still alive after SIGTERM+300s; abandoning suite "
                "run (process left to exit on its own — killing would wedge the grant)")
            return False
    n = append_results(name, out or b"")
    tail = (err or b"")[-2000:].decode(errors="replace")
    log(f"step {name}: rc={proc.returncode} rows_logged={n} stderr_tail={tail!r}")
    return proc.returncode == 0 and n > 0


def run_suite(token: str) -> None:
    ok_steps, failed = [], []
    with open(CHIP_LOG, "a") as f:
        f.write(json.dumps({"_suite_start": token,
                            "at": time.strftime("%Y-%m-%dT%H:%M:%S")}) + "\n")
    for name, cmd, timeout_s in SUITE:
        write_status(state="measuring", step=name, token=token,
                     ok=ok_steps, failed=failed)
        # re-check the grant between steps: if the tunnel wedged mid-suite,
        # stop cleanly and keep whatever already landed in the log
        n, _ = probe_devices(timeout_s=30.0, stale_negative_after_s=120.0)
        if n == 0:
            log(f"grant lost before step {name}; pausing suite")
            failed.append(name + ":grant-lost")
            break
        (ok_steps if run_step(name, cmd, timeout_s) else failed).append(name)
    with open(CHIP_LOG, "a") as f:
        f.write(json.dumps({"_suite_end": token, "ok": ok_steps, "failed": failed,
                            "at": time.strftime("%Y-%m-%dT%H:%M:%S")}) + "\n")
    if ok_steps and not failed:
        with open(DONE, "w") as f:
            f.write(token)
        log(f"suite complete for token {token!r}: {ok_steps}")
    else:
        log(f"suite partial for token {token!r}: ok={ok_steps} failed={failed} "
            "(will retry on next grant)")


def main() -> None:
    os.makedirs(RESULTS, exist_ok=True)
    # single watcher instance
    lock_fd = os.open(WATCHER_LOCK, os.O_CREAT | os.O_RDWR, 0o666)
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        sys.stderr.write("another chip watcher is running; exiting\n")
        return
    os.write(lock_fd, f"{os.getpid()}\n".encode())
    log(f"watcher up, pid={os.getpid()}")
    if not read_token(REQUEST):
        with open(REQUEST, "w") as f:
            f.write("r5-initial")

    while True:
        want, have = read_token(REQUEST), read_token(DONE)
        if want and want != have:
            # keep exactly one sentinel probe in flight; a negative verdict
            # goes stale immediately so the next loop respawns the sentinel
            n, backend = probe_devices(timeout_s=30.0, stale_negative_after_s=30.0)
            if n > 0:
                write_status(state="grant-acquired", backend=backend, token=want)
                log(f"grant free (backend={backend}); running suite for {want!r}")
                run_suite(want)
            else:
                write_status(state="waiting-for-grant", token=want, backend=backend)
        else:
            write_status(state="idle", done_token=have)
        time.sleep(60.0)


if __name__ == "__main__":
    main()
