#!/usr/bin/env python
"""Benchmark: key-lane compression (ISSUE 6) under merge read, compaction
rewrite, and sort-compact.

Three schemas spanning the planner's decision space:

  int_pk     — single BIGINT primary key (2 lanes; the constant hi word
               truncates away, the lo word min-shifts)
  composite  — 4-column composite STRING key with shared prefixes (4 dict-
               rank lanes; truncation + bit-packing fuse them into 1-2
               operands, wide batches carry the OVC lane)
  dict_pk    — dictionary-heavy STRING + INT key (low-cardinality ranks:
               tiny bit widths, maximal packing)

Per schema x workload the bench measures rows/s with merge.lane-compression
ON vs OFF (bit-identical outputs asserted on every pass) plus the planner's
lanes_in -> lanes_out width from the lanes{} metric group.

Acceptance (ISSUE 6): >= 1.25x merge-read rows/s on the composite schema and
lanes_out < lanes_in on every multi-lane schema. Results land in
benchmarks/results/lanes_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_ROWS = 400_000
N_RUNS = 4
ITERS = 5
RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "lanes_bench.json")


def _schemas():
    import paimon_tpu as pt

    return {
        "int_pk": dict(
            schema=pt.RowType.of(("id", pt.BIGINT(False)), ("v", pt.BIGINT()), ("w", pt.DOUBLE())),
            keys=["id"],
        ),
        "composite": dict(
            schema=pt.RowType.of(
                ("region", pt.STRING(False)),
                ("dept", pt.STRING(False)),
                ("user", pt.STRING(False)),
                ("item", pt.STRING(False)),
                ("v", pt.BIGINT()),
            ),
            keys=["region", "dept", "user", "item"],
        ),
        "dict_pk": dict(
            schema=pt.RowType.of(("cat", pt.STRING(False)), ("slot", pt.INT(False)), ("v", pt.BIGINT())),
            keys=["cat", "slot"],
        ),
    }


def _rows(kind, n, rng):
    if kind == "int_pk":
        ids = rng.integers(0, n * 2, n).astype(np.int64)
        return {"id": ids, "v": ids * 3, "w": ids.astype(np.float64) * 0.5}
    if kind == "composite":
        # shared prefixes everywhere: the OVC/prefix-truncation stress shape
        region = np.array([f"acct-region-{int(x):02d}" for x in rng.integers(0, 8, n)], dtype=object)
        dept = np.array([f"acct-dept-{int(x):03d}" for x in rng.integers(0, 64, n)], dtype=object)
        user = np.array([f"user-{int(x):05d}" for x in rng.integers(0, 2000, n)], dtype=object)
        item = np.array([f"item-{int(x):04d}" for x in rng.integers(0, 500, n)], dtype=object)
        return {"region": region, "dept": dept, "user": user, "item": item,
                "v": rng.integers(0, 1 << 40, n).astype(np.int64)}
    if kind == "dict_pk":
        cat = np.array([f"category-{int(x):03d}" for x in rng.integers(0, 100, n)], dtype=object)
        return {"cat": cat, "slot": rng.integers(0, 1000, n).astype(np.int32),
                "v": rng.integers(0, 1 << 40, n).astype(np.int64)}
    raise AssertionError(kind)


def _make_table(cat, name, kind, spec, compression, extra=None):
    opts = {
        "bucket": "1",
        "file.format": "parquet",
        "write-only": "true",
        "merge.lane-compression": "true" if compression else "false",
    }
    opts.update(extra or {})
    return cat.create_table(name, spec["schema"], primary_keys=spec["keys"], options=opts)


def _write_runs(table, kind, n, runs, seed=7):
    rng = np.random.default_rng(seed)
    per = n // runs
    for r in range(runs):
        data = _rows(kind, per, rng)
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write(data)
        wb.new_commit().commit(w.prepare_commit())


def _lane_counters():
    from paimon_tpu.metrics import lanes_metrics

    g = lanes_metrics()
    return {k: g.counter(k).count for k in ("plans", "lanes_in", "lanes_out", "ovc_merges", "bytes_saved")}


def _timed_read(table, iters):
    rb = table.new_read_builder()
    best = float("inf")
    out = None
    for it in range(iters + 1):  # first pass warms jit caches
        t0 = time.perf_counter()
        out = rb.new_read().read_all(rb.new_scan().plan())
        dt = time.perf_counter() - t0
        if it > 0:
            best = min(best, dt)
    return out, best


def bench_merge_read(cat_path, kind, spec, extra=None):
    """Both option values read the SAME physical table (table.copy swaps only
    merge.lane-compression), so file layout, page boundaries, and OS cache
    state are identical — the delta is the merge kernel's lane width."""
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(cat_path, commit_user="lanes-bench")
    row = {"schema": kind, "workload": "merge_read", "rows": N_ROWS}
    base = _make_table(cat, f"b.mr_{kind}", kind, spec, True, extra=extra)
    _write_runs(base, kind, N_ROWS, N_RUNS)
    outs = {}
    for comp in (False, True):
        t = base.copy({"merge.lane-compression": "true" if comp else "false"})
        c0 = _lane_counters()
        out, best = _timed_read(t, ITERS)
        c1 = _lane_counters()
        outs[comp] = out
        tag = "on" if comp else "off"
        row[f"rows_per_sec_{tag}"] = round(out.num_rows / best, 1)
        if comp:
            delta = {k: c1[k] - c0[k] for k in c0}
            row["lanes_in"] = delta["lanes_in"] // max(delta["plans"], 1)
            row["lanes_out"] = delta["lanes_out"] // max(delta["plans"], 1)
            row["ovc_merges"] = delta["ovc_merges"]
    assert outs[True].to_pylist() == outs[False].to_pylist(), f"{kind}: compressed read differs"
    row["speedup"] = round(row["rows_per_sec_on"] / row["rows_per_sec_off"], 3)
    return row


def bench_compaction(cat_path, kind, spec):
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(cat_path, commit_user="lanes-bench")
    n = N_ROWS // 2
    row = {"schema": kind, "workload": "compaction_rewrite", "rows": n}
    merged = {}
    # single-shot workload: best of 2 fresh-table runs per option damps
    # filesystem/allocator noise (outputs still asserted identical)
    for comp in (False, True):
        best = float("inf")
        for attempt in range(2):
            t = _make_table(
                cat, f"b.cp_{kind}_{int(comp)}_{attempt}", kind, spec, comp,
                extra={"write-only": "false"},
            )
            _write_runs(t, kind, n, N_RUNS)
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            t0 = time.perf_counter()
            w.compact(full=True)
            best = min(best, time.perf_counter() - t0)
            wb.new_commit().commit(w.prepare_commit())
            rb = t.new_read_builder()
            merged[comp] = rb.new_read().read_all(rb.new_scan().plan())
        row[f"rows_per_sec_{'on' if comp else 'off'}"] = round(n / best, 1)
    assert merged[True].to_pylist() == merged[False].to_pylist(), f"{kind}: compacted view differs"
    row["speedup"] = round(row["rows_per_sec_on"] / row["rows_per_sec_off"], 3)
    return row


def bench_sort_compact(cat_path, kind, spec):
    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.table.sort_compact import sort_compact

    cat = FileSystemCatalog(cat_path, commit_user="lanes-bench")
    n = N_ROWS // 2
    row = {"schema": kind, "workload": "sort_compact", "rows": n}
    views = {}
    for comp in (False, True):
        best = float("inf")
        for attempt in range(2):
            # append-only variant of the same schema (sort-compact precondition)
            t = cat.create_table(
                f"b.sc_{kind}_{int(comp)}_{attempt}",
                spec["schema"],
                options={
                    "bucket": "1",
                    "file.format": "parquet",
                    "merge.lane-compression": "true" if comp else "false",
                },
            )
            _write_runs(t, kind, n, 2)
            t0 = time.perf_counter()
            total = sort_compact(t, spec["keys"], order="order")
            best = min(best, time.perf_counter() - t0)
            rb = t.new_read_builder()
            views[comp] = rb.new_read().read_all(rb.new_scan().plan())
        row[f"rows_per_sec_{'on' if comp else 'off'}"] = round(total / best, 1)
    assert views[True].to_pylist() == views[False].to_pylist(), f"{kind}: clustered view differs"
    row["speedup"] = round(row["rows_per_sec_on"] / row["rows_per_sec_off"], 3)
    return row


def bench_ovc_wide(cat_path):
    """Extra headline: a key too wide to pack into one operand, driven
    through the DEVICE kernel (sort-engine pinned so the adaptive CPU
    fallback doesn't bypass it) — the batch genuinely carries the
    offset-value code lane through lax.sort (ovc_merges > 0)."""
    import paimon_tpu as pt

    spec = dict(
        schema=pt.RowType.of(
            ("hi", pt.BIGINT(False)), ("lo", pt.BIGINT(False)), ("tag", pt.STRING(False)),
            ("v", pt.BIGINT()),
        ),
        keys=["hi", "lo", "tag"],
    )

    def rows_fn(n, rng):
        # 20+20+4 varying bits: two fused operands (20 | 20+4) -> the
        # planner attaches the OVC lane (vbits 24 + 2 offset bits <= 32)
        return {
            "hi": rng.integers(0, 1 << 20, n).astype(np.int64),
            "lo": rng.integers(0, 1 << 20, n).astype(np.int64),
            "tag": np.array([f"t-{int(x):02d}" for x in rng.integers(0, 16, n)], dtype=object),
            "v": rng.integers(0, 1 << 40, n).astype(np.int64),
        }

    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(cat_path, commit_user="lanes-bench")
    n = N_ROWS // 2
    row = {"schema": "wide_ovc", "workload": "merge_read_device", "rows": n}
    base = cat.create_table(
        "b.ovc", spec["schema"], primary_keys=spec["keys"],
        options={"bucket": "1", "file.format": "parquet", "write-only": "true",
                 "sort-engine": "xla-segmented"},
    )
    rng = np.random.default_rng(5)
    per = n // N_RUNS
    for _ in range(N_RUNS):
        wb = base.new_batch_write_builder()
        w = wb.new_write()
        w.write(rows_fn(per, rng))
        wb.new_commit().commit(w.prepare_commit())
    outs = {}
    for comp in (False, True):
        t = base.copy({"merge.lane-compression": "true" if comp else "false"})
        c0 = _lane_counters()
        out, best = _timed_read(t, ITERS)
        c1 = _lane_counters()
        outs[comp] = out
        row[f"rows_per_sec_{'on' if comp else 'off'}"] = round(out.num_rows / best, 1)
        if comp:
            delta = {k: c1[k] - c0[k] for k in c0}
            row["lanes_in"] = delta["lanes_in"] // max(delta["plans"], 1)
            row["lanes_out"] = delta["lanes_out"] // max(delta["plans"], 1)
            row["ovc_merges"] = delta["ovc_merges"]
    assert outs[True].to_pylist() == outs[False].to_pylist(), "wide_ovc: compressed read differs"
    row["speedup"] = round(row["rows_per_sec_on"] / row["rows_per_sec_off"], 3)
    return row


def run():
    from paimon_tpu.utils import enable_compile_cache

    enable_compile_cache()
    rows = []
    specs = _schemas()
    for kind, spec in specs.items():
        for bench in (bench_merge_read, bench_compaction, bench_sort_compact):
            tmp = tempfile.mkdtemp(prefix="paimon_lanes_bench_")
            try:
                rows.append(bench(tmp, kind, spec))
                print(json.dumps(rows[-1]))
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
    tmp = tempfile.mkdtemp(prefix="paimon_lanes_bench_")
    try:
        rows.append(bench_ovc_wide(tmp))
        print(json.dumps(rows[-1]))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return rows


def main():
    rows = run()
    headline = next(r for r in rows if r["schema"] == "composite" and r["workload"] == "merge_read")
    summary = {
        "metric": "key-lane compression (merge read, composite string key)",
        "speedup": headline["speedup"],
        "lanes_in": headline["lanes_in"],
        "lanes_out": headline["lanes_out"],
        "acceptance_1_25x": headline["speedup"] >= 1.25,
    }
    print(json.dumps(summary))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump({"rows": rows, "summary": summary, "n_rows": N_ROWS}, f, indent=2)
    return rows


if __name__ == "__main__":
    main()
