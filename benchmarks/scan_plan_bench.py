#!/usr/bin/env python
"""Benchmark: scan-planning latency at high manifest scale (ISSUE 20
satellite; round-5 verdict Missing #6).

Builds a partitioned append-only table through the REAL commit path until
the live manifest set holds >= FILES data-file entries (default 10k:
COMMITS commits x PARTS partitions, one file each), then times
`new_read_builder().new_scan().plan()`:

  * full      — plan every entry (the coordinator's cost to open a scan
                over the whole table; this is what cluster_query pays
                before any fragment is dispatched)
  * pruned    — plan under a single-partition predicate (manifest entry
                stats must prune ~all files; measures the skipping path,
                not just the happy case). NOTE: pruning costs MORE than
                the unfiltered plan today — the partition predicate is
                evaluated per manifest entry on the host — so both rows
                gate against the same absolute budget, and the ratio is
                recorded for the day entry-level pruning is vectorized.

Both are best-of ITERS wall seconds against a stated budget. Planning is
pure metadata work — no data file is opened — so the budget holds on a
1-core CI container. Results land in benchmarks/results/scan_plan_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

PARTS = int(os.environ.get("PAIMON_TPU_SCANPLAN_PARTS", "500"))
COMMITS = int(os.environ.get("PAIMON_TPU_SCANPLAN_COMMITS", "20"))
FILES = PARTS * COMMITS
ITERS = int(os.environ.get("PAIMON_TPU_SCANPLAN_ITERS", "3"))
# metadata-only work: generous for a 1-core CI box, tight enough to catch
# an accidental O(files^2) or per-entry IO regression
PLAN_BUDGET_S = float(os.environ.get("PAIMON_TPU_SCANPLAN_BUDGET_S", "5.0"))
RESULTS = os.path.join(HERE, "results", "scan_plan_bench.json")


def _build(base: str):
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.types import BIGINT, DOUBLE, RowType

    cat = FileSystemCatalog(os.path.join(base, "wh"), commit_user="bench")
    t = cat.create_table(
        "db.plan",
        RowType.of(("p", BIGINT(False)), ("id", BIGINT()), ("v", DOUBLE())),
        partition_keys=("p",),
        options={"bucket": "1", "write-only": "true"},
    )
    ps = list(range(PARTS))
    for c in range(COMMITS):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write({"p": ps, "id": [c * PARTS + p for p in ps], "v": [float(c)] * PARTS})
        wb.new_commit().commit(w.prepare_commit())
    return t


def _best(fn) -> float:
    best = float("inf")
    for _ in range(ITERS):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run(iters: int = ITERS) -> dict:
    global ITERS
    ITERS = iters
    from paimon_tpu.data.predicate import equal

    base = tempfile.mkdtemp(prefix="paimon_scanplan_bench_")
    try:
        t0 = time.perf_counter()
        t = _build(base)
        build_s = time.perf_counter() - t0

        rb = t.new_read_builder()
        splits = rb.new_scan().plan()
        files = sum(len(s.files) for s in splits)
        assert files == FILES, f"expected {FILES} live files, planned {files}"
        full_s = _best(lambda: rb.new_scan().plan())

        rbp = t.new_read_builder().with_filter(equal("p", 7))
        pruned = rbp.new_scan().plan()
        pruned_files = sum(len(s.files) for s in pruned)
        assert pruned_files == COMMITS, (
            f"partition pruning kept {pruned_files} files, expected {COMMITS}"
        )
        pruned_s = _best(lambda: rbp.new_scan().plan())
    finally:
        shutil.rmtree(base, ignore_errors=True)

    row = {
        "metric": f"scan planning, {FILES} manifest entries ({COMMITS} commits x {PARTS} partitions)",
        "unit": "s/plan",
        "manifest_entries": FILES,
        "commits": COMMITS,
        "build_s": round(build_s, 2),
        "plan_full_s": round(full_s, 3),
        "plan_pruned_s": round(pruned_s, 3),
        "plan_budget_s": PLAN_BUDGET_S,
        "pruned_files": pruned_files,
        "pruned_over_full": round(pruned_s / full_s, 1) if full_s else None,
    }
    return {"row": row}


def run_headline(iters: int = 2) -> list:
    """bench.py hook: reduced iterations; gates live in main() only."""
    return [run(iters=iters)["row"]]


def main() -> None:
    res = run()
    row = res["row"]
    print(json.dumps(row))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump(res, f, indent=1)
    assert row["plan_full_s"] <= PLAN_BUDGET_S, (
        f"full scan plan over {row['manifest_entries']} manifest entries took "
        f"{row['plan_full_s']}s > {PLAN_BUDGET_S}s budget"
    )
    assert row["plan_pruned_s"] <= PLAN_BUDGET_S, (
        f"partition-pruned plan over {row['manifest_entries']} manifest entries "
        f"took {row['plan_pruned_s']}s > {PLAN_BUDGET_S}s budget"
    )


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
