#!/usr/bin/env python
"""Benchmark: adaptive background compaction vs inline compaction under a
sustained skewed-write soak (ISSUE 11, the LUDA scheduling headline).

Setup: an 8-bucket primary-key table, 2 writer threads on disjoint
keyspaces, 80% of each round's rows aimed at two HOT buckets (key pools are
pre-bucketed through the table's own hash function, so the skew is real
bucket skew, not just key skew). Two modes over the same workload + seed:

  inline    — write-only=false: every writer pays the universal-compaction
              pick inside its own flush/commit path (the pre-PR behavior)
  adaptive  — write-only=true writers + AdaptiveCompactorService draining
              compaction debt in the background by heat/read-amp priority

A sampler thread snapshots per-bucket sorted-run counts (= merge-read
amplification) every 250 ms in both modes. After the deadline the adaptive
service drains remaining debt, both modes full-compact, and the final scan
is verified row-for-row against the in-memory oracle (last write per key):
0 lost, 0 duplicated.

Acceptance (ISSUE 11): adaptive sustained ingest >= 1.2x inline rows/s at
equal-or-lower p99 read-amplification, with per-bucket read-amp bounded by
compaction.adaptive.read-amp-ceiling. Results land in
benchmarks/results/adaptive_compact_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

RESULTS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "results", "adaptive_compact_bench.json"
)

BUCKETS = 8
WRITERS = 4
HOT_BUCKETS = (0, 1)
HOT_FRACTION = 0.8
ROWS_PER_COMMIT = 400
KEY_STRIDE = 10_000_000
READ_AMP_CEILING = 7


def _make_table(base_dir, mode):
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.types import BIGINT, DOUBLE, RowType

    opts = {
        "bucket": str(BUCKETS),
        # one flush per commit (buffer >= commit size): per-bucket file
        # creation is exactly one per touched bucket per commit, so the
        # admission projection is exact
        "write-buffer-rows": "1024",
        "snapshot.num-retained.min": "12",
        "compaction.adaptive.read-amp-ceiling": str(READ_AMP_CEILING),
        "compaction.adaptive.trigger": "3",
        # deep rewrites only on a ceiling breach: the steady state is cheap
        # shallow universal picks of the L0 pileup
        "compaction.adaptive.deep-runs": "6",
        "compaction.adaptive.max-buckets-per-round": "2",
        "compaction.adaptive.interval": "50 ms",
        "write-only": "true" if mode == "adaptive" else "false",
    }
    cat = FileSystemCatalog(base_dir, commit_user=f"acb-{mode}")
    return cat.create_table(
        f"db.{mode}",
        RowType.of(("k", BIGINT(False)), ("v", DOUBLE())),
        primary_keys=["k"],
        options=opts,
    )


def _bucket_pools(table, wid, pool_size=24_000):
    """Pre-bucket a candidate keyspace through the table's own hash, so the
    workload can aim rows at specific buckets."""
    from paimon_tpu.data.batch import ColumnBatch
    from paimon_tpu.table.bucket import key_hashes

    keys = np.arange(wid * KEY_STRIDE, wid * KEY_STRIDE + pool_size, dtype=np.int64)
    batch = ColumnBatch.from_pydict(table.row_type, {"k": keys, "v": np.zeros(pool_size)})
    hashes = key_hashes(batch, table.store.key_names)
    buckets = hashes % BUCKETS
    return {b: keys[buckets == b] for b in range(BUCKETS)}


def _round_keys(rng, pools):
    """Skewed round: HOT_FRACTION of commits aim every row at the two hot
    buckets; the rest hit one rotating cold bucket. Per-bucket file-creation
    rate is therefore genuinely skewed (~40x hot vs cold) — the shape the
    adaptive policy exists for."""
    cold_buckets = [b for b in range(BUCKETS) if b not in HOT_BUCKETS]
    if rng.random() < HOT_FRACTION:
        target = list(HOT_BUCKETS)
        parts = [
            pools[b][rng.integers(0, len(pools[b]), ROWS_PER_COMMIT // len(HOT_BUCKETS))]
            for b in HOT_BUCKETS
        ]
    else:
        b = cold_buckets[int(rng.integers(0, len(cold_buckets)))]
        target = [b]
        parts = [pools[b][rng.integers(0, len(pools[b]), ROWS_PER_COMMIT)]]
    return np.unique(np.concatenate(parts)), target


def _observe_runs(table):
    plan = table.store.new_scan().plan()
    out = {}
    for partition, buckets in plan.grouped().items():
        for bucket, files in buckets.items():
            level0 = sum(1 for f in files if f.level == 0)
            upper = {f.level for f in files if f.level > 0}
            out[bucket] = level0 + len(upper)
    return out


def run_mode(mode, duration, seed=0, base_dir=None):
    from paimon_tpu.table.compactor import AdaptiveCompactorService

    own_tmp = base_dir is None
    base_dir = base_dir or tempfile.mkdtemp(prefix=f"paimon_acb_{mode}_")
    table = _make_table(base_dir, mode)
    pools = {w: _bucket_pools(table, w) for w in range(WRITERS)}
    expected: dict[int, float] = {}
    exp_lock = threading.Lock()
    accepted_rows = [0] * WRITERS
    commits = [0] * WRITERS
    errors: list[str] = []
    stop = threading.Event()
    samples: list[dict] = []

    def writer_loop(wid):
        import traceback

        from paimon_tpu.core.commit import CommitConflictError, CommitGiveUpError
        from paimon_tpu.core.manifest import ManifestCommittable
        from paimon_tpu.service.soak import find_landed_append
        from paimon_tpu.table.write import TableWrite

        rng = np.random.default_rng(seed * 1000 + wid)
        user = f"acb-{mode}-w{wid}"
        handle = table.with_user(user)
        store = handle.store
        ident = 0
        deadline = t_start + duration
        while not stop.is_set() and time.monotonic() < deadline:
            ks, target_buckets = _round_keys(rng, pools[wid])
            if svc is not None:
                # debt admission: block while any TARGET bucket's projected
                # sorted-run count sits at/over the read-amp ceiling (the
                # write-only stop-trigger analog; cold ingest keeps flowing
                # while hot debt drains) — THIS is what makes "sustained
                # ingest at bounded read-amplification" a real operating
                # point, not a race between writers and the scheduler
                svc.admit(target_buckets, timeout_s=10.0)
                if stop.is_set() or time.monotonic() >= deadline:
                    break
            ident += 1
            vs = ks.astype(np.float64) * 0.001 + ident
            landed = False
            try:
                try:
                    w = TableWrite(handle)
                    try:
                        w.write({"k": ks, "v": vs})
                        msgs = w.prepare_commit()
                    finally:
                        w.close()
                    landed = bool(
                        store.new_commit().commit(ManifestCommittable(ident, messages=msgs))
                    )
                except (CommitConflictError, CommitGiveUpError):
                    # a raised commit may still have landed its APPEND half
                    # (conflict on the COMPACT phase): the snapshot chain,
                    # not the exception, decides what the oracle counts
                    landed = find_landed_append(store, user, ident) is not None
                except Exception:
                    errors.append(traceback.format_exc())
                    return
            finally:
                if svc is not None:
                    svc.settle(target_buckets, landed=landed)
            if landed:
                with exp_lock:
                    for k, v in zip(ks.tolist(), vs.tolist()):
                        expected[k] = v
                accepted_rows[wid] += len(ks)
                commits[wid] += 1

    def sampler_loop():
        deadline = t_start + duration
        while not stop.is_set() and time.monotonic() < deadline:
            try:
                samples.append(_observe_runs(table))
            except Exception:
                pass  # planning races a commit: skip the sample
            time.sleep(0.25)

    svc = None
    if mode == "adaptive":
        svc = AdaptiveCompactorService(table)
        svc.start()
    t_start = time.monotonic()
    threads = [threading.Thread(target=writer_loop, args=(w,)) for w in range(WRITERS)]
    threads.append(threading.Thread(target=sampler_loop))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    stop.set()

    drain_s = 0.0
    if svc is not None:
        # drain remaining debt (not counted toward ingest wall time), then
        # stop the service
        t0 = time.monotonic()
        deadline = t0 + 10.0
        while time.monotonic() < deadline:
            runs = _observe_runs(table)
            # drained = back under the ceiling everywhere (cold buckets
            # below the trigger stay deferred BY DESIGN; the quiesced full
            # compact below squares the rest away before verification)
            if all(r < READ_AMP_CEILING for r in runs.values()):
                break
            time.sleep(0.2)
        drain_s = time.monotonic() - t0
        svc.close()

    # final verification: quiesced full compact + scan == oracle fold
    from paimon_tpu.table.compactor import DedicatedCompactor

    for _ in range(3):
        if not DedicatedCompactor(table).run_once(full=True):
            break
    rb = table.new_read_builder()
    out = rb.new_read().read_all(rb.new_scan().plan())
    ks = out.column("k").values.tolist()
    got = dict(zip(ks, out.column("v").values.tolist()))
    dup = len(ks) - len(got)
    lost = sum(1 for k in expected if k not in got)
    wrong = sum(1 for k in expected if k in got and got[k] != expected[k])
    extra = sum(1 for k in got if k not in expected)

    amps = [r for s in samples for r in s.values()]
    hot_amps = [s.get(b, 0) for s in samples for b in HOT_BUCKETS]
    row = {
        "mode": mode,
        "duration_s": round(wall, 2),
        "accepted_rows": int(sum(accepted_rows)),
        "commits": int(sum(commits)),
        "rows_per_sec": round(sum(accepted_rows) / wall, 1) if wall else 0.0,
        "read_amp_p99": float(np.percentile(amps, 99)) if amps else None,
        "read_amp_max": int(max(amps)) if amps else None,
        "read_amp_hot_p99": float(np.percentile(hot_amps, 99)) if hot_amps else None,
        "read_amp_samples": len(samples),
        "drain_s": round(drain_s, 2),
        "lost_rows": lost,
        "duplicated_rows": dup,
        "wrong_values": wrong,
        "extra_rows": extra,
        "unique_keys": len(expected),
        "final_rows": len(ks),
        "errors": errors[:3],
    }
    if mode == "adaptive":
        from paimon_tpu.metrics import registry

        snap = registry.snapshot().get("compaction", {})
        row["adaptive_runs"] = snap.get("adaptive_runs", 0)
        row["deferred_buckets"] = snap.get("deferred_buckets", 0)
        row["read_amp_ceiling"] = READ_AMP_CEILING
    if own_tmp:
        shutil.rmtree(base_dir, ignore_errors=True)
    return row


def run(duration=60.0, seed=0, write_json=True):
    from paimon_tpu.utils import enable_compile_cache

    enable_compile_cache()
    rows = [run_mode("inline", duration, seed), run_mode("adaptive", duration, seed)]
    inline, adaptive = rows
    summary = {
        "speedup": round(adaptive["rows_per_sec"] / max(inline["rows_per_sec"], 1e-9), 3),
        "target": 1.2,
        "read_amp_bounded": (
            adaptive["read_amp_p99"] is not None
            and adaptive["read_amp_p99"] <= READ_AMP_CEILING
        ),
        "read_amp_equal_or_lower": (
            adaptive["read_amp_p99"] is not None
            and inline["read_amp_p99"] is not None
            and adaptive["read_amp_p99"] <= inline["read_amp_p99"]
        ),
        "zero_lost_dup": all(
            r["lost_rows"] == 0 and r["duplicated_rows"] == 0 and r["wrong_values"] == 0
            and r["extra_rows"] == 0 and not r["errors"]
            for r in rows
        ),
    }
    for r in rows:
        print(json.dumps(r))
    print(json.dumps({"metric": "adaptive vs inline", **summary}))
    if write_json:
        os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
        with open(RESULTS, "w") as f:
            json.dump({"rows": rows, "summary": summary, "duration_s": duration}, f, indent=2)
    return rows, summary


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    _, summary = run(duration=args.duration, seed=args.seed)
    sys.exit(0 if summary["zero_lost_dup"] else 1)
