#!/usr/bin/env python
"""Benchmark: distributed shuffle aggregation for high-cardinality GROUP BY
(ISSUE 20, sql.cluster shuffle).

One table whose GROUP BY key is ~unique per row (>= 100k distinct string
groups), aggregated two ways over the SAME 4-worker-process topology:

  combine — PAIMON_TPU_SQL_SHUFFLE=0: every worker ships its whole partial
            to the coordinator, which unifies W large overlapping pools and
            runs the second-stage segment_reduce single-process.
  shuffle — PAIMON_TPU_SQL_SHUFFLE=1: workers hash-partition partials by
            group-key VALUE and exchange them peer-to-peer; each range
            owner reduces its (value-disjoint) range in parallel, and the
            coordinator only concatenates R already-reduced ranges.

The headline is the COORDINATOR SERIAL COMBINE STAGE (sql{combine_ms}:
partial decode + unify/segment-reduce, or reduced-range decode + concat
under shuffle, + batch assembly — RPC wait excluded). That stage is the
single-point bottleneck the shuffle plane exists to remove: it shrinks
from O(total partial rows, ~W x GROUPS here) to O(GROUPS) regardless of
worker count, and is what "combine cost scales out with workers" means.

End-to-end wall time is reported too, gated at >= 2x only on hosts with
at least WORKERS cpu cores: on fewer cores every "parallel" phase
time-slices the same core, so end-to-end wall equals total cpu and a
work REDISTRIBUTION cannot speed it up — there the bench instead bounds
the shuffle's end-to-end overhead. Every timed pass asserts the result
BIT-IDENTICAL to single-process `sql.query` (exactly-representable
doubles), and the shuffle passes assert sql{shuffle_rounds} grew.

A separate untimed pass SIGKILLs a range owner mid-shuffle (between the
scatter and the range fetch, via sql.cluster._SHUFFLE_TEST_HOOK): the
coordinator re-homes the range, survivors reship their buffered parts,
the dead worker's own parts re-execute — exact result, shuffle_retried
counted.

The local row is the satellite no-regression guard: single-process
`sql.query` on the same high-cardinality aggregate (the pure segment-
reduce path the shuffle must not disturb) against a stated budget.

Headlines (asserted in main, not in run_headline):
  * coordinator serial combine stage: shuffle >= 2x faster than combine
    at 4 workers
  * end-to-end: >= 2x when the host has >= WORKERS cores, else shuffle
    overhead bounded at <= 1.6x combine's wall
  * local single-process pass within LOCAL_BUDGET_S
Results land in benchmarks/results/sql_shuffle_bench.json.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

N_BUCKETS = 4
WORKERS = 4
GROUPS = int(os.environ.get("PAIMON_TPU_SQLSH_GROUPS", "100000"))
# ~8 rows per group, PK-hashed across every bucket: each group's partial row
# shows up on ALL W workers, so the coordinator-combine baseline decodes,
# unifies, and re-reduces ~W x GROUPS rows single-process — the regime the
# shuffle exists for (each range owner handles GROUPS/R of that, in parallel)
ROWS = int(os.environ.get("PAIMON_TPU_SQLSH_ROWS", str(8 * GROUPS)))
ITERS = int(os.environ.get("PAIMON_TPU_SQLSH_ITERS", "3"))
RESULTS = os.path.join(HERE, "results", "sql_shuffle_bench.json")

# local (single-process) high-card segment-reduce budget: measured ~3.2 s
# for 800k rows / 100k groups on the 1-core CI container; ~1.1x headroom
# per the no-regression satellite
LOCAL_BUDGET_S = float(os.environ.get("PAIMON_TPU_SQLSH_LOCAL_BUDGET_S", "3.6"))

QUERY = (
    "SELECT g, count(*), count(a), sum(a), min(b), max(b), avg(b), sum(c), min(c) "
    "FROM db.r GROUP BY g ORDER BY g LIMIT 32"
)

TABLE_OPTIONS = {
    "bucket": str(N_BUCKETS),
    "write-only": "true",
    # the bench measures EXECUTION: the fragment result cache would answer
    # repeat passes with no scatter at all, hiding both paths under test
    "sql.cluster.fragment-cache": "false",
}


def _build(base: str):
    import numpy as np

    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

    cat = FileSystemCatalog(os.path.join(base, "wh"), commit_user="bench")
    t = cat.create_table(
        "db.r",
        RowType.of(
            ("k", BIGINT(False)), ("a", BIGINT()), ("b", DOUBLE()),
            ("c", DOUBLE()), ("g", STRING()),
        ),
        primary_keys=["k"],
        options=TABLE_OPTIONS,
    )
    ks = np.arange(ROWS, dtype=np.int64)
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({
        "k": ks.tolist(),
        "a": [None if x % 17 == 0 else int(x % 100_003) for x in ks.tolist()],
        "b": (ks * 0.25).tolist(),  # exactly representable: order-free sums
        "c": (ks * 0.5 + 1.0).tolist(),
        "g": [f"u{int(x)}" for x in (ks % GROUPS).tolist()],
    })
    wb.new_commit().commit(w.prepare_commit())
    return cat, t


def _child_env(shuffle: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PAIMON_TPU_CLUSTER_ROLE"] = "worker"
    env["PAIMON_TPU_SQL_SHUFFLE"] = shuffle
    env["PYTHONPATH"] = os.path.dirname(HERE) + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    return env


class _Cluster:
    """4 serve-mode worker OS processes + coordinator + routed client."""

    def __init__(self, root: str, base: str, shuffle: str, heartbeat_timeout_s: float = 4.0):
        from paimon_tpu.service.cluster import ClusterClient, ClusterConfig, ClusterCoordinator
        from paimon_tpu.table import load_table

        self.coord = ClusterCoordinator(
            root,
            ClusterConfig(
                workers=WORKERS, buckets=N_BUCKETS, compaction=False,
                heartbeat_timeout_s=heartbeat_timeout_s,
            ),
        ).start()
        self.procs = {}
        self.cli = None
        try:
            for wid in range(WORKERS):
                log = open(os.path.join(base, f"shw{shuffle}-{wid}.log"), "wb")
                self.procs[wid] = subprocess.Popen(
                    [sys.executable, "-m", "paimon_tpu.service.cluster", "worker",
                     "--table", root, "--wid", str(wid),
                     "--coordinator", f"{self.coord.host}:{self.coord.port}",
                     "--mode", "serve", "--heartbeat-interval", "0.2"],
                    stdout=log, stderr=subprocess.STDOUT, env=_child_env(shuffle),
                )
                log.close()
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                for wid, p in self.procs.items():
                    if p.poll() is not None:
                        tail = open(os.path.join(base, f"shw{shuffle}-{wid}.log"), "rb").read()[-2000:]
                        raise RuntimeError(
                            f"worker {wid} died rc={p.returncode}:\n{tail.decode(errors='replace')}"
                        )
                try:
                    cli = ClusterClient(load_table(root, commit_user="cli"), self.coord.host, self.coord.port)
                    if len({cli.owner_of(b) for b in range(N_BUCKETS)}) == min(WORKERS, N_BUCKETS):
                        self.cli = cli
                        return
                    cli.close()
                except Exception:
                    pass
                time.sleep(0.2)
            raise RuntimeError(f"{WORKERS} workers never registered serve ports")
        except BaseException:
            self.close()
            raise

    def close(self):
        if self.cli is not None:
            self.cli.close()
        for p in self.procs.values():
            try:
                p.terminate()
                p.wait(timeout=30)
            except Exception:
                p.kill()
        self.coord.close()


def _time_cluster(cat, cli, want, shuffle_on: bool) -> tuple:
    """Best-of timed passes (iter 0 warms jax caches and worker conns).
    Returns (end-to-end wall s, coordinator serial combine-stage s) — the
    latter read from sql{combine_ms}.last, which both paths update with
    decode + combine/concat + assembly and never with RPC wait."""
    from paimon_tpu.metrics import sql_metrics
    from paimon_tpu.sql import cluster_query

    g = sql_metrics()
    best = float("inf")
    best_comb = float("inf")
    for it in range(ITERS):
        rounds0 = g.counter("shuffle_rounds").count
        comb0 = g.histogram("combine_ms").count
        t0 = time.perf_counter()
        rows = cluster_query(cat, QUERY, cli).to_pylist()
        dt = time.perf_counter() - t0
        assert rows == want, "diverged from single-process sql.query"
        assert (g.counter("shuffle_rounds").count > rounds0) == shuffle_on
        assert g.histogram("combine_ms").count == comb0 + 1
        if it > 0:
            best = min(best, dt)
            best_comb = min(best_comb, g.histogram("combine_ms").last / 1000.0)
    return best, best_comb


def _kill_owner_pass(cat, cluster, want) -> dict:
    """SIGKILL a range owner after its inbound parts landed, before the
    coordinator fetches its range — the recovery path must deliver the
    exact result with shuffle_retried > 0."""
    import paimon_tpu.sql.cluster as sqlc
    from paimon_tpu.metrics import sql_metrics
    from paimon_tpu.sql import cluster_query

    g = sql_metrics()
    killed = []

    def hook(stage, info):
        if stage == "post-scatter" and not killed:
            wid = info["ranges"][0][0]
            killed.append(wid)
            cluster.procs[wid].send_signal(signal.SIGKILL)
            cluster.procs[wid].wait(timeout=30)

    before = g.counter("shuffle_retried").count
    old = sqlc._SHUFFLE_TEST_HOOK
    sqlc._SHUFFLE_TEST_HOOK = hook
    try:
        rows = cluster_query(cat, QUERY, cluster.cli).to_pylist()
    finally:
        sqlc._SHUFFLE_TEST_HOOK = old
    assert killed, "shuffle path not taken — nothing was killed"
    assert rows == want, "post-SIGKILL result diverged from single-process"
    retried = g.counter("shuffle_retried").count - before
    assert retried > 0, "worker death did not surface in shuffle_retried"
    return {"killed_worker": killed[0], "shuffle_retried": retried, "identical": True}


def _time_local(cat, want) -> float:
    from paimon_tpu.sql import query

    best = float("inf")
    for it in range(ITERS):
        t0 = time.perf_counter()
        rows = query(cat, QUERY).to_pylist()
        dt = time.perf_counter() - t0
        assert rows == want, "single-process drift"
        if it > 0:
            best = min(best, dt)
    return best


def run(iters: int = ITERS) -> dict:
    global ITERS
    ITERS = iters
    from paimon_tpu.sql import query

    base = tempfile.mkdtemp(prefix="paimon_sqlshuffle_bench_")
    try:
        cat, t = _build(base)
        want = query(cat, QUERY).to_pylist()
        local_s = _time_local(cat, want)

        os.environ["PAIMON_TPU_SQL_SHUFFLE"] = "0"
        cl = _Cluster(t.path, base, "0")
        try:
            combine_s, combine_stage_s = _time_cluster(cat, cl.cli, want, shuffle_on=False)
        finally:
            cl.close()

        os.environ["PAIMON_TPU_SQL_SHUFFLE"] = "1"
        cl = _Cluster(t.path, base, "1", heartbeat_timeout_s=1.5)
        try:
            shuffle_s, shuffle_stage_s = _time_cluster(cat, cl.cli, want, shuffle_on=True)
            kill = _kill_owner_pass(cat, cl, want)
        finally:
            cl.close()
            os.environ.pop("PAIMON_TPU_SQL_SHUFFLE", None)
    finally:
        shutil.rmtree(base, ignore_errors=True)

    row = {
        "metric": f"shuffle aggregation, {GROUPS} distinct groups, {WORKERS} workers",
        "unit": "s/query",
        "groups": GROUPS,
        "rows": ROWS,
        "cpu_cores": len(os.sched_getaffinity(0)),
        "local_single_process_s": round(local_s, 3),
        "local_budget_s": LOCAL_BUDGET_S,
        # the headline: coordinator serial combine stage (sql{combine_ms})
        "coordinator_combine_s": round(combine_stage_s, 3),
        "coordinator_shuffle_s": round(shuffle_stage_s, 3),
        "coordinator_speedup_vs_combine": round(combine_stage_s / shuffle_stage_s, 2),
        # end-to-end wall on this host (total-cpu-bound when cores < WORKERS)
        "e2e_combine_s": round(combine_s, 3),
        "e2e_shuffle_s": round(shuffle_s, 3),
        "e2e_speedup_vs_combine": round(combine_s / shuffle_s, 2),
        "identical_output": True,
        "kill_recovery": kill,
    }
    return {"row": row}


def run_headline(iters: int = 2) -> list:
    """bench.py hook: reduced iterations; gates live in main() only."""
    return [run(iters=iters)["row"]]


def run_local_headline(iters: int = 3) -> list:
    """bench.py hook for the single-process no-regression satellite: time
    ONLY the local segment-reduce path at >=100k distinct groups (no
    cluster spin-up) and assert it within the stated ~1.1x-of-measured
    budget — the pure path the shuffle plane must not disturb."""
    global ITERS
    ITERS = iters
    from paimon_tpu.sql import query

    base = tempfile.mkdtemp(prefix="paimon_sqlsh_local_")
    try:
        cat, t = _build(base)
        want = query(cat, QUERY).to_pylist()
        local_s = _time_local(cat, want)
    finally:
        shutil.rmtree(base, ignore_errors=True)
    assert local_s <= LOCAL_BUDGET_S, (
        f"local high-cardinality GROUP BY regressed: {local_s:.3f}s > "
        f"{LOCAL_BUDGET_S}s budget"
    )
    return [{
        "metric": f"local high-cardinality GROUP BY, {GROUPS} distinct groups, {ROWS} rows",
        "unit": "s/query",
        "value": round(local_s, 3),
        "budget_s": LOCAL_BUDGET_S,
    }]


def main() -> None:
    res = run()
    row = res["row"]
    print(json.dumps(row))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump(res, f, indent=1)
    assert row["coordinator_speedup_vs_combine"] >= 2.0, (
        f"coordinator combine stage speedup {row['coordinator_speedup_vs_combine']} "
        f"< 2x over the single-point combine path"
    )
    if row["cpu_cores"] >= WORKERS:
        assert row["e2e_speedup_vs_combine"] >= 2.0, (
            f"end-to-end shuffle speedup {row['e2e_speedup_vs_combine']} < 2x "
            f"over coordinator-combine on a {row['cpu_cores']}-core host"
        )
    else:
        # workers time-slice one core: wall == total cpu, redistribution
        # cannot win — bound the exchange's overhead instead
        assert row["e2e_shuffle_s"] <= row["e2e_combine_s"] * 1.6, (
            f"shuffle end-to-end overhead too high on {row['cpu_cores']} core(s): "
            f"{row['e2e_shuffle_s']}s vs combine {row['e2e_combine_s']}s"
        )
    assert row["local_single_process_s"] <= LOCAL_BUDGET_S, (
        f"local high-cardinality GROUP BY regressed: "
        f"{row['local_single_process_s']}s > {LOCAL_BUDGET_S}s budget"
    )


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
