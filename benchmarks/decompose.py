#!/usr/bin/env python
"""Per-stage time decomposition of the merge-read hot path.

Answers "where does the time go" for the headline benchmark (bench.py
config): host columnar decode, key-lane encode, host->device transfer,
device sort+select kernel, winner gather. The kernel stage is isolated by
dispatching with pre-staged device arrays; the transfer stage is the delta
between dispatch-from-host and dispatch-from-device. Prints one JSON line
per stage plus the reconstructed total.

Usage: python benchmarks/decompose.py [--rows N] [--runs K]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paimon_tpu.utils import enable_compile_cache
from paimon_tpu.utils.tpuguard import ensure_live_backend

enable_compile_cache()

# wedge-proof device access (tpuguard): explicit-CPU honored, detached probe
# (never killed), single-flight lock, clean-exit signals, LOUD CPU fallback
# (PAIMON_TPU_REQUIRE=1 turns the fallback into exit 3)
PLATFORM = ensure_live_backend()


def best_of(fn, iters=3):
    best = float("inf")
    for i in range(iters + 1):
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        if i > 0:  # first run warms caches
            best = min(best, dt)
    return best, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--runs", type=int, default=4)
    args = ap.parse_args()

    import jax

    from benchmarks.micro_benchmarks import make_table  # noqa: F401  (path setup)

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__))))
    from micro_benchmarks import make_table

    import jax.numpy as jnp

    from paimon_tpu.data.keys import encode_key_lanes
    from paimon_tpu.ops.merge import (
        _dedup_select_fn,
        deduplicate_resolve,
        drop_constant_lanes,
        pad_size,
    )

    tmp = tempfile.mkdtemp(prefix="ptb_decomp_")
    results = {}
    try:
        t, _ = make_table(tmp, "parquet", args.rows, runs=args.runs, write_only=True)
        store = t.store
        plan = store.new_scan().plan()
        files = [e.file for e in plan.entries]
        rf = store.reader_factory((), 0)

        # --- stage 1: host columnar decode (all columns) -------------------
        def decode():
            return [rf.read(f) for f in files]

        results["decode_ms"], batches = best_of(decode)
        from paimon_tpu.core.kv import KVBatch

        kv = KVBatch.concat(batches)

        # --- stage 2: key-lane encode --------------------------------------
        def encode():
            return encode_key_lanes(kv.data, ["id"], {})

        results["lane_encode_ms"], lanes = best_of(encode)
        kl = drop_constant_lanes(lanes)
        if kl.shape[1] == 0:
            kl = lanes[:, :1]
        n, k = kl.shape
        m = pad_size(n)
        klp = np.full((k, m), 0xFFFFFFFF, dtype=np.uint32)
        klp[:, :n] = kl.T
        slp = np.zeros((0, m), dtype=np.uint32)
        pad = np.zeros(m, dtype=np.uint32)
        pad[n:] = 1
        fn = _dedup_select_fn(k, 0)

        # --- stage 3: kernel from host arrays (includes upload) ------------
        def kernel_from_host():
            packed, count = fn(klp, slp, pad)
            return deduplicate_resolve((packed, count))

        results["kernel_plus_transfer_ms"], take = best_of(kernel_from_host)

        # --- stage 4: kernel with pre-staged device arrays (no upload) -----
        dklp, dslp, dpad = jnp.asarray(klp), jnp.asarray(slp), jnp.asarray(pad)

        def kernel_device_only():
            packed, count = fn(dklp, dslp, dpad)
            return deduplicate_resolve((packed, count))

        results["kernel_ms"], _ = best_of(kernel_device_only)
        results["transfer_ms"] = max(results["kernel_plus_transfer_ms"] - results["kernel_ms"], 0.0)

        # --- stage 5: winner gather on host --------------------------------
        def gather():
            return kv.take(take)

        results["gather_ms"], merged = best_of(gather)

        total = (
            results["decode_ms"]
            + results["lane_encode_ms"]
            + results["kernel_plus_transfer_ms"]
            + results["gather_ms"]
        )
        meta = {
            "platform": PLATFORM,
            "rows": args.rows,
            "runs": args.runs,
            "merged_rows": merged.num_rows,
            "lane_bytes": int(klp.nbytes + pad.nbytes),
        }
        for stage in ("decode_ms", "lane_encode_ms", "transfer_ms", "kernel_ms", "gather_ms"):
            print(
                json.dumps(
                    {
                        "metric": f"merge-read.stage.{stage[:-3]}",
                        "value": round(results[stage] * 1000, 2),
                        "unit": "ms",
                        "share": round(results[stage] / total, 3),
                    }
                ),
                flush=True,
            )
        print(
            json.dumps(
                {
                    "metric": "merge-read.stage.total",
                    "value": round(total * 1000, 2),
                    "unit": "ms",
                    "rows_per_s": round(args.rows / total, 1),
                    **meta,
                }
            ),
            flush=True,
        )

        # --- the ADAPTIVE HOST pipeline (what table reads actually run on a
        # CPU-only backend, mergefn.effective_sort_engine): keys-only decode
        # without _SEQUENCE_NUMBER, host lexsort dedup, value-column decode,
        # winner gather ----------------------------------------------------
        host = {}
        key_cols = ["id"]
        rest = [n for n in t.row_type.field_names if n not in key_cols]

        def h_decode_keys():
            return [rf.read(f, fields=key_cols, system_columns="kind") for f in files]

        host["decode_keys_ms"], heads = best_of(h_decode_keys)
        kvk = KVBatch.concat(heads)

        def h_sort():
            from paimon_tpu.core.mergefn import _numpy_dedup_select

            lanes2 = encode_key_lanes(kvk.data, ["id"], {})
            return _numpy_dedup_select(lanes2, None)

        host["host_sort_ms"], take2 = best_of(h_sort)

        def h_decode_values():
            return [rf.read(f, fields=rest, system_columns=False) for f in files]

        host["decode_values_ms"], tails = best_of(h_decode_values)

        def h_gather():
            # the REAL pipeline gathers the full reassembled batch (keys +
            # concatenated value columns), not the keys-only head
            from paimon_tpu.data.batch import Column, ColumnBatch

            cols = {}
            for name in t.row_type.field_names:
                if name in key_cols:
                    cols[name] = kvk.data.column(name)
                else:
                    cols[name] = Column.concat([x.data.column(name) for x in tails])
            full = KVBatch(ColumnBatch(t.row_type, cols), kvk.seq, kvk.kind)
            return full.take(take2)

        host["gather_ms"], _ = best_of(h_gather)
        h_total = sum(host.values())
        for stage, v in host.items():
            print(
                json.dumps(
                    {"metric": f"merge-read.host.{stage[:-3]}",
                     "value": round(v * 1000, 2), "unit": "ms",
                     "share": round(v / h_total, 3)}
                ),
                flush=True,
            )
        print(
            json.dumps(
                {"metric": "merge-read.host.total", "value": round(h_total * 1000, 2),
                 "unit": "ms", "rows_per_s": round(args.rows / h_total, 1),
                 "platform": PLATFORM}
            ),
            flush=True,
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
