#!/usr/bin/env python
"""Benchmark: arrow vs native vs native+pushdown parquet decode, per encoding.

One file per (encoding, compression) config — plain / dictionary / delta
columns under uncompressed / snappy / zstd — read three ways through the
same `ParquetFormat.read` surface:

  arrow            pyarrow C++ decode (the default backend)
  native           paimon_tpu.decode page decode, full expansion
  native+pushdown  same, with a selective dictionary equality predicate:
                   the compressed-domain gate expands only surviving pages

Prints one JSON line per (config, backend) with rows/s, plus a pushdown
line quantifying pages decoded vs skipped (acceptance: the pushdown pass
expands strictly fewer pages than full decode). The result table is also
written to benchmarks/results/decode_bench.json next to the other round
artifacts.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_ROWS = 300_000
N_TAGS = 16  # dictionary cardinality; clustered so pages are homogeneous
RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "decode_bench.json")

CONFIGS = [
    # (name, dictionary, delta, compression)
    ("plain", "false", False, "none"),
    ("plain-snappy", "false", False, "snappy"),
    ("plain-zstd", "false", False, "zstd"),
    ("dict", "true", False, "none"),
    ("dict-snappy", "true", False, "snappy"),
    ("dict-zstd", "true", False, "zstd"),
    ("delta-zstd", None, True, "zstd"),
]


def build_batch():
    import paimon_tpu as pt
    from paimon_tpu.data.batch import ColumnBatch

    schema = pt.RowType.of(
        ("id", pt.BIGINT(False)),
        ("v", pt.DOUBLE()),
        ("tag", pt.STRING()),
        ("seq", pt.BIGINT()),
    )
    rng = np.random.default_rng(23)
    tag = np.sort(rng.integers(0, N_TAGS, N_ROWS))  # clustered dict column
    data = {
        "id": [int(x) for x in np.arange(N_ROWS)],
        "v": [float(x) for x in rng.random(N_ROWS)],
        "tag": [f"tag-{int(t):02d}" for t in tag],
        "seq": [int(x) for x in np.cumsum(rng.integers(0, 9, N_ROWS))],  # delta-friendly
    }
    return schema, ColumnBatch.from_pydict(schema, data)


def write_config(tmp, schema, batch, name, dictionary, delta, compression):
    from paimon_tpu.format.parquet import ParquetFormat
    from paimon_tpu.fs import LocalFileIO

    path = os.path.join(tmp, f"{name}.parquet")
    if delta:
        # pyarrow-only write path: per-column DELTA_BINARY_PACKED
        import pyarrow.parquet as pq

        pq.write_table(
            batch.to_arrow(),
            path,
            compression=compression if compression != "none" else "NONE",
            use_dictionary=False,
            column_encoding={"id": "DELTA_BINARY_PACKED", "seq": "DELTA_BINARY_PACKED",
                             "v": "PLAIN", "tag": "PLAIN"},
            data_page_size=64 << 10,
        )
    else:
        ParquetFormat().write(
            LocalFileIO(),
            path,
            batch,
            compression=compression,
            format_options={
                "parquet.enable.dictionary": dictionary,
                "parquet.page-size": str(64 << 10),
            },
        )
    return path


def read_once(path, schema, decoder, predicate=None) -> tuple[float, int]:
    from paimon_tpu.data.batch import concat_batches
    from paimon_tpu.format.parquet import ParquetFormat
    from paimon_tpu.fs import LocalFileIO

    t0 = time.perf_counter()
    parts = list(ParquetFormat(decoder=decoder).read(LocalFileIO(), path, schema, predicate=predicate))
    out = concat_batches(parts)
    # touch every lazy string column so arrow's deferred materialization is
    # included in the measured decode (the native path materializes eagerly)
    for name in out.schema.field_names:
        _ = out.column(name).values
    return time.perf_counter() - t0, out.num_rows


def bench(path, schema, decoder, predicate=None, iters=3) -> tuple[float, int]:
    best, rows = float("inf"), 0
    read_once(path, schema, decoder, predicate)  # warm (codecs, jit, page cache)
    for _ in range(iters):
        dt, rows = read_once(path, schema, decoder, predicate)
        best = min(best, dt)
    return best, rows


def main():
    from paimon_tpu.data import predicate as P
    from paimon_tpu.metrics import decode_metrics

    tmp = tempfile.mkdtemp(prefix="paimon_tpu_decode_bench_")
    rows_out = []
    try:
        schema, batch = build_batch()
        pred = P.equal("tag", f"tag-{N_TAGS // 2:02d}")  # ~1/N_TAGS of rows survive
        for name, dictionary, delta, compression in CONFIGS:
            path = write_config(tmp, schema, batch, name, dictionary, delta, compression)
            for decoder in ("arrow", "native"):
                dt, n = bench(path, schema, decoder)
                assert n == N_ROWS, (name, decoder, n)
                row = {
                    "metric": f"decode {name} [{decoder}]",
                    "value": round(N_ROWS / dt, 1),
                    "unit": "rows/s",
                }
                rows_out.append(row)
                print(json.dumps(row))
            if dictionary == "true":
                g = decode_metrics()
                d0, s0 = g.counter("pages_decoded").count, g.counter("pages_skipped").count
                dt, n = bench(path, schema, "native", predicate=pred, iters=1)
                decoded = g.counter("pages_decoded").count - d0
                skipped = g.counter("pages_skipped").count - s0
                assert skipped > 0 and decoded < decoded + skipped, (
                    "pushdown must expand strictly fewer pages than full decode"
                )
                row = {
                    "metric": f"decode {name} [native+pushdown, selective eq]",
                    "value": round(N_ROWS / dt, 1),
                    "unit": "rows/s (input rows over wall)",
                    "surviving_rows": n,
                    "pages_expanded": decoded,
                    "pages_skipped": skipped,
                }
                rows_out.append(row)
                print(json.dumps(row))
        os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
        with open(RESULTS, "w") as f:
            json.dump({"rows": N_ROWS, "results": rows_out}, f, indent=1)
        print(json.dumps({"metric": "decode_bench results file", "value": RESULTS}))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
