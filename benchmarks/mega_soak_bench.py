#!/usr/bin/env python
"""Benchmark: production mega-soak — every plane of the stack on one table
set, one composed chaos store, one oracle, one verdict.

Runs the service.mega_soak supervisor (cluster coordinator + worker OS
processes on the mesh engine, the multi-tenant gateway front door, journaled
writer / getter / subscriber / distributed-SQL OS processes, snapshot-expiry
+ consumer-expiry + orphan-sweep churn) over the full scenario matrix in two
configurations:

  full        the whole DEFAULT_MATRIX (flagship cluster+branch/tag cell,
              dict-dynamic consumer-expiry cell, wide-pallas cell,
              native-legacy engine-contrast cell), >= 10 min total at the
              default chaos shaping (1 op in 200 faulting, latency on every
              read/write), scripted kill -9 deaths at every registered
              crash point plus seeded random SIGKILLs. The headline: kills
              survived across >= 3 process kinds and >= 4 distinct crash
              points with ONE consistent:true verdict — 0 lost/duplicated/
              mismatched rows, 0 untyped sheds, 0 pinned-read errors,
              post-sweep disk set == reachable closure, and every metric
              group (io/soak/get/sub/cluster/sql/gateway/compaction/dict/
              pallas) nonzero somewhere in the run.
  seed        the contrast run WITHOUT the resilience stack (fs.retry.
              max-attempts=1, commit.max-retries=0) on one cell at a hotter
              fault rate: the same chaos store now surfaces raw IO faults
              to every plane and the verdict goes inconsistent — recorded
              in the results JSON so the delta is auditable.

Prints one JSON line per configuration and writes
benchmarks/results/mega_soak_bench.json.

    python benchmarks/mega_soak_bench.py [--duration 150] [--seed 0]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KEEP = [
    "wall_s",
    "consistent",
    "kills_total",
    "kills_by_kind",
    "kills_by_point",
    "process_kinds_killed",
    "crash_points_fired",
    "metric_groups",
    "procs_spawned",
    "procs_killed",
    "procs_respawned",
    "child_errors",
    "snapshot_expiries",
    "faults_injected",
]

CELL_KEEP = [
    "cell",
    "consistent",
    "accepted_commits",
    "final_rows",
    "total_record_count",
    "record_count_matches",
    "lost_rows",
    "duplicated_rows",
    "wrong_values",
    "gw_sheds_untyped",
    "pinned_read_errors",
    "getter_read_errors",
    "sql_client_errors",
    "sub_mismatches",
    "leaked_file_count",
]


def run_full(duration_per_cell: float, seed: int, workers: int) -> dict:
    from paimon_tpu.service.mega_soak import MegaConfig, run_mega_soak

    cfg = MegaConfig(duration_s=duration_per_cell, cluster_workers=workers, seed=seed)
    tmp = tempfile.mkdtemp(prefix="paimon_mega_bench_full_")
    try:
        report = run_mega_soak(tmp, cfg)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    row = {
        "metric": "production mega-soak (cluster + gateway + subscribers + SQL + churn, one chaos store)",
        "mode": "full (journal recovery + fs.retry + typed sheds + orphan sweeps)",
        **{k: report.get(k) for k in KEEP},
        "cells": [{k: c.get(k) for k in CELL_KEEP} for c in report["cells"]],
    }
    # the acceptance gate (ISSUE 18): >= 10 kills over >= 3 process kinds
    # and >= 4 distinct crash points, one clean verdict, every metric
    # group ticking somewhere in the matrix
    assert report["consistent"], report
    assert report["kills_total"] >= 10, report
    assert len(report["process_kinds_killed"]) >= 3, report
    assert len(report["crash_points_fired"]) >= 4, report
    for cell in report["cells"]:
        assert cell["lost_rows"] == 0 and cell["duplicated_rows"] == 0, cell
        assert cell["wrong_values"] == 0, cell
        assert cell["gw_sheds_untyped"] == 0, cell
        assert cell["pinned_read_errors"] == 0, cell
        assert cell["leaked_file_count"] == 0, cell
    dead = [g for g, n in report["metric_groups"].items() if n == 0]
    assert not dead, f"metric groups never ticked: {dead}"
    return row


def run_seed(duration: float, seed: int) -> dict:
    from paimon_tpu.service.mega_soak import DEFAULT_MATRIX, MegaConfig, run_mega_soak

    # one non-cluster cell, retries off, hotter faults: the point is the
    # contrast, not ten minutes of a known-broken configuration
    cell = tuple(s for s in DEFAULT_MATRIX if s.name == "dict-dynamic")
    cfg = MegaConfig(
        duration_s=duration,
        seed=seed,
        scenarios=cell,
        chaos_possibility=80,
        table_options={"fs.retry.max-attempts": "1", "commit.max-retries": "0"},
    )
    tmp = tempfile.mkdtemp(prefix="paimon_mega_bench_seed_")
    try:
        report = run_mega_soak(tmp, cfg)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    row = {
        "metric": "production mega-soak (single cell, same chaos store and kill schedule)",
        "mode": "seed (fs.retry.max-attempts=1, commit.max-retries=0)",
        **{k: report.get(k) for k in KEEP},
        "cells": [{k: c.get(k) for k in CELL_KEEP} for c in report["cells"]],
    }
    # the contrast gate: without retries the same chaos store demonstrably
    # breaks SOMETHING the full stack keeps clean — an untyped escape, a
    # failed plane, or a dirty verdict
    c = report["cells"][0]
    degraded = (
        not report["consistent"]
        or (c.get("gw_sheds_untyped") or 0) > 0
        or (c.get("pinned_read_errors") or 0) > 0
        or (c.get("getter_read_errors") or 0) > 0
        or (report.get("child_errors") or 0) > 0
    )
    assert degraded, report
    return row


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # host-side soak: never grab the chip
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--duration", type=float, default=150.0, help="seconds per matrix cell (4 cells)"
    )
    ap.add_argument("--seed-duration", type=float, default=30.0, help="contrast run length")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-seed", action="store_true", help="skip the contrast row")
    args = ap.parse_args()
    rows = [run_full(args.duration, args.seed, args.workers)]
    print(json.dumps(rows[0]))
    if not args.no_seed:
        rows.append(run_seed(args.seed_duration, args.seed))
        print(json.dumps(rows[1]))
    out = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results", "mega_soak_bench.json"
    )
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
