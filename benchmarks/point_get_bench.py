#!/usr/bin/env python
"""Batched point-get serving benchmark (ISSUE 13).

A 1M-row primary-key table (4 overlapping sorted runs, 1 bucket, bloom key
indexes on) served three ways:

  1. headline — 10k-key batches through `LocalTableQuery.get_batch`
     (one key-lane encode + one vectorized searchsorted per surviving file)
     vs the scalar `lookup()` loop (LookupLevels walk per key). EVERY timed
     pass asserts the batched results identical to the scalar oracle.
     Target: >= 10x.
  2. bloom pruning — a sparse (absent-key) batch against a cold data-file
     cache, bloom-prune on vs off: with the PTIX key index consulted the
     files prune with zero data IO (files_pruned > 0 asserted); without it
     every candidate file decodes.
  3. mixed soak — 4 writers + a batched get storm + the read-your-writes
     checker for 30 s (service/soak.py): sustained gets/s, per-key p99
     latency, zero mismatches vs the scalar oracle, typed-BUSY-only
     shedding.

Results land in benchmarks/results/point_get_bench.json; bench.py calls
run_headline() for its spot-check rows.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_ROWS = 1_000_000
N_RUNS = 4
BATCH_KEYS = 10_000


def build_table(path: str, n_rows: int = N_ROWS):
    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(path, commit_user="getbench")
    schema = pt.RowType.of(
        ("id", pt.BIGINT(False)),
        ("c1", pt.BIGINT()),
        ("s1", pt.STRING()),
        ("d1", pt.DOUBLE()),
    )
    table = cat.create_table(
        "bench.kv",
        schema,
        primary_keys=["id"],
        options={
            "bucket": "1",
            "write-only": "true",
            "file-index.bloom-filter.primary-key.enabled": "true",
        },
    )
    rng = np.random.default_rng(11)
    # EVEN ids only: odd keys inside [0, 2*n) are in-range absents — the
    # case where only the bloom key index (never min/max) can prune
    ids = rng.permutation(n_rows).astype(np.int64) * 2
    per = n_rows // N_RUNS
    for r in range(N_RUNS):
        chunk = np.sort(ids[r * per : (r + 1) * per])
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write(
            {
                "id": chunk,
                "c1": chunk * 3,
                "s1": np.array([f"val-{int(x) % 1000:04d}" for x in chunk], dtype=object),
                "d1": chunk.astype(np.float64) * 0.5,
            }
        )
        wb.new_commit().commit(w.prepare_commit())
    return table


def _scalar_loop(q, keys):
    out = []
    for k in keys:
        row = q.lookup((), int(k))
        out.append(None if row is None else row.to_pylist()[0])
    return out


def bench_batched_vs_scalar(table, iters: int = 2, n_keys: int = BATCH_KEYS) -> dict:
    from paimon_tpu.metrics import get_metrics
    from paimon_tpu.table.query import LocalTableQuery

    q = LocalTableQuery(table)
    rng = np.random.default_rng(7)
    keys = [int(k) for k in rng.integers(0, N_ROWS * 2, n_keys)]  # ~50% absent
    # warm both paths (file decode + lookup-file conversion are one-time)
    q.get_batch(keys[:64])
    _scalar_loop(q, keys[:64])
    g = get_metrics()
    best_batch = float("inf")
    best_scalar = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        batched = q.get_batch(keys).to_pylist()
        best_batch = min(best_batch, time.perf_counter() - t0)
        t0 = time.perf_counter()
        scalar = _scalar_loop(q, keys)
        best_scalar = min(best_scalar, time.perf_counter() - t0)
        assert batched == scalar, "batched gets diverged from the scalar oracle"
    found = sum(1 for r in batched if r is not None)
    p99_us = best_batch / n_keys * 1e6
    g.gauge("p99_us").set(p99_us)
    return {
        "metric": "point get: batched get_batch vs scalar lookup() loop (1M-row PK table)",
        "keys_per_batch": n_keys,
        "keys_found": found,
        "batched_ms": round(best_batch * 1000, 2),
        "scalar_ms": round(best_scalar * 1000, 2),
        "speedup": round(best_scalar / best_batch, 2),
        "batched_gets_per_sec": round(n_keys / best_batch, 1),
        "per_key_us": round(p99_us, 3),
        "identical_to_oracle": True,
        "unit": "x",
    }


def bench_bloom_pruning(table, iters: int = 2, n_keys: int = 64) -> dict:
    """Sparse absent-key batch, COLD data-file cache: bloom-on prunes every
    file with zero data IO; bloom-off pays the decode."""
    from paimon_tpu.metrics import get_metrics
    from paimon_tpu.table.query import LocalTableQuery
    from paimon_tpu.utils import cache as cache_mod

    rng = np.random.default_rng(13)
    # ODD keys inside the table's key range: every id is even, so these are
    # absent — and range pruning is powerless, only the bloom index prunes
    absent = [int(k) * 2 + 1 for k in rng.integers(0, N_ROWS - 1, n_keys)]
    g = get_metrics()
    out = {}
    for mode, opt in (("pruned", "true"), ("unpruned", "false")):
        t2 = table.copy({"lookup.get.bloom-prune.enabled": opt})
        best = float("inf")
        pruned = 0
        for _ in range(iters):
            cache_mod.clear_all()
            q = LocalTableQuery(t2)
            p0 = g.counter("files_pruned").count
            t0 = time.perf_counter()
            res = q.get_batch(absent)
            best = min(best, time.perf_counter() - t0)
            pruned = g.counter("files_pruned").count - p0
            assert res.to_pylist() == [None] * len(absent)
        out[mode] = (best, pruned)
    assert out["pruned"][1] > 0, "bloom key index pruned no files under a sparse key set"
    return {
        "metric": "point get: bloom key-index pruning (sparse absent keys, cold cache)",
        "keys": n_keys,
        "pruned_ms": round(out["pruned"][0] * 1000, 2),
        "unpruned_ms": round(out["unpruned"][0] * 1000, 2),
        "files_pruned": out["pruned"][1],
        "speedup": round(out["unpruned"][0] / max(out["pruned"][0], 1e-9), 2),
        "unit": "x",
    }


def bench_get_breakdown() -> dict:
    from paimon_tpu.metrics import get_metrics

    g = get_metrics()
    return {
        "metric": "point get breakdown",
        "gets": g.counter("gets").count,
        "keys_probed": g.counter("keys_probed").count,
        "files_pruned": g.counter("files_pruned").count,
        "index_hits": g.counter("index_hits").count,
        "memtable_hits": g.counter("memtable_hits").count,
        "probe_ms_mean": round(g.histogram("probe_ms").mean, 3),
        "p99_us": round(g.gauge("p99_us").value, 1),
        "unit": "counters",
    }


def bench_mixed_soak(duration: float = 30.0, seed: int = 0) -> dict:
    """4 writers + batched get storm + RYW checker + typed-BUSY overload
    bursts; oracle = the scalar lookup() loop per round."""
    from paimon_tpu.service.soak import SoakConfig, run_soak

    base = tempfile.mkdtemp(prefix="paimon_get_soak_")
    try:
        cfg = SoakConfig(
            duration_s=duration,
            writers=4,
            readers=1,
            getters=2,
            fault_possibility=0,
            seed=seed,
            get_batch_keys=2048,
            get_oracle_keys=16,
        )
        rep = run_soak(base, cfg)
        return {
            "metric": f"mixed ingest + point-get soak ({int(duration)} s, 4 writers + 2 getters)",
            "consistent": rep["consistent"],
            "gets_per_sec": rep["gets_per_sec"],
            "gets_served": rep["gets_served"],
            "get_p50_us": rep["get_p50_us"],
            "get_p99_us": rep["get_p99_us"],
            "get_mismatches": rep["get_mismatches"],
            "ryw_rounds": rep["ryw_rounds"],
            "ryw_misses": rep["ryw_misses"],
            "gets_shed_typed": rep["gets_shed_typed"],
            "gets_shed_untyped": rep["gets_shed_untyped"],
            "commits_ok": rep["commits_ok"],
            "lost_rows": rep["lost_rows"],
            "duplicated_rows": rep["duplicated_rows"],
            "wrong_values": rep["wrong_values"],
            "leaked_files": rep["leaked_file_count"],
            "unit": "counters",
        }
    finally:
        shutil.rmtree(base, ignore_errors=True)


def run_headline(iters: int = 2) -> list:
    """bench.py spot-check rows: batched-vs-scalar + pruning + breakdown."""
    tmp = tempfile.mkdtemp(prefix="paimon_get_bench_")
    try:
        table = build_table(tmp)
        rows = [
            bench_batched_vs_scalar(table, iters=iters),
            bench_bloom_pruning(table, iters=iters),
            bench_get_breakdown(),
        ]
        return rows
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description="batched point-get benchmark")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--soak-duration", type=float, default=30.0)
    ap.add_argument("--no-soak", action="store_true")
    args = ap.parse_args()

    rows = run_headline(iters=args.iters)
    if not args.no_soak:
        rows.append(bench_mixed_soak(duration=args.soak_duration))
    for row in rows:
        print(json.dumps(row))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "point_get_bench.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump({"rows": rows, "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S")}, f, indent=2)
    headline = rows[0]
    ok = headline["speedup"] >= 10.0 and (args.no_soak or (rows[-1]["consistent"] and rows[-1]["gets_per_sec"] >= 10_000))
    return 0 if ok else 1


if __name__ == "__main__":
    from paimon_tpu.utils import enable_compile_cache

    enable_compile_cache()
    raise SystemExit(main())
