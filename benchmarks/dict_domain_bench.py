#!/usr/bin/env python
"""Benchmark: compressed-domain merge & compaction (ISSUE 10,
merge.dict-domain) — dictionary codes as the merge currency end-to-end.

Three schemas spanning the dictionary decision space:

  dict_heavy — composite (BIGINT, STRING) key + four low-cardinality STRING
               payload columns: decode, key lanes, dedup winners, stats and
               the output dictionary pages all stay in the code domain
  mixed      — BIGINT key, two STRING + two numeric payload columns
  non_dict   — BIGINT key, numeric payload only: the code domain never
               engages; the row is the no-regression guard

Per schema x workload (merge-read, compaction rewrite, sort-compact) the
bench measures rows/s with merge.dict-domain ON vs OFF through the NATIVE
decoder+encoder (the current native path is the baseline the >=2x headline
is against). EVERY timed pass first asserts the code-domain output
byte-identical to the expanded-domain oracle, and the compaction passes
additionally re-read every output data file with plain pyarrow
(pq.read_table) — an independent reader must see identical rows.

Acceptance (ISSUE 10): compaction rewrite rows/s >= 2x on dict_heavy.
Results land in benchmarks/results/dict_domain_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_ROWS = 400_000
N_RUNS = 4
ITERS = 3
RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "dict_domain_bench.json")


def _schemas():
    import paimon_tpu as pt

    return {
        "dict_heavy": dict(
            schema=pt.RowType.of(
                ("k", pt.BIGINT(False)),
                ("cat", pt.STRING(False)),
                ("s1", pt.STRING()),
                ("s2", pt.STRING()),
                ("s3", pt.STRING()),
                ("s4", pt.STRING()),
            ),
            keys=["k", "cat"],
            sort_cols=["cat", "s1"],
        ),
        "mixed": dict(
            schema=pt.RowType.of(
                ("k", pt.BIGINT(False)),
                ("s1", pt.STRING()),
                ("s2", pt.STRING()),
                ("v1", pt.BIGINT()),
                ("v2", pt.DOUBLE()),
            ),
            keys=["k"],
            sort_cols=["s1", "v1"],
        ),
        "non_dict": dict(
            schema=pt.RowType.of(
                ("k", pt.BIGINT(False)), ("v1", pt.BIGINT()), ("v2", pt.DOUBLE())
            ),
            keys=["k"],
            sort_cols=["v1"],
        ),
    }


def _rows(kind, n, rng):
    k = rng.integers(0, n * 2, n).astype(np.int64)
    if kind == "dict_heavy":
        return {
            "k": k,
            "cat": np.array([f"category-{int(x):03d}" for x in rng.integers(0, 200, n)], dtype=object),
            "s1": np.array([f"city-{int(x):04d}" for x in rng.integers(0, 800, n)], dtype=object),
            "s2": np.array([f"status-{int(x):02d}" for x in rng.integers(0, 12, n)], dtype=object),
            "s3": np.array([f"device-{int(x):03d}" for x in rng.integers(0, 300, n)], dtype=object),
            "s4": np.array([f"plan-{int(x):02d}" for x in rng.integers(0, 40, n)], dtype=object),
        }
    if kind == "mixed":
        return {
            "k": k,
            "s1": np.array([f"region-{int(x):03d}" for x in rng.integers(0, 100, n)], dtype=object),
            "s2": np.array([f"tag-{int(x):02d}" for x in rng.integers(0, 30, n)], dtype=object),
            "v1": rng.integers(0, 1 << 40, n).astype(np.int64),
            "v2": rng.random(n),
        }
    if kind == "non_dict":
        return {"k": k, "v1": rng.integers(0, 1 << 40, n).astype(np.int64), "v2": rng.random(n)}
    raise AssertionError(kind)


def _base_opts(dd, extra=None):
    opts = {
        "bucket": "1",
        "file.format": "parquet",
        "format.parquet.decoder": "native",
        "format.parquet.encoder": "native",
        "cache.data-file.max-memory-size": "0 b",
        "merge.dict-domain": "true" if dd else "false",
    }
    opts.update(extra or {})
    return opts


def _write_runs(table, kind, n, runs, seed=7):
    rng = np.random.default_rng(seed)
    per = n // runs
    for _ in range(runs):
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write(_rows(kind, per, rng))
        wb.new_commit().commit(w.prepare_commit())


def _dict_counters():
    from paimon_tpu.metrics import dict_metrics

    g = dict_metrics()
    return {
        k: g.counter(k).count
        for k in ("pools_unified", "codes_remapped", "rows_code_domain", "fallback_expanded")
    }


def _pyarrow_state(table, warehouse, name):
    """Every data file of the table's current snapshot read back through
    plain pyarrow — the independent-reader guard."""
    import pyarrow.parquet as pq

    by_name = {}
    for root, _dirs, fnames in os.walk(warehouse):
        if f"/{name}" in root or root.endswith(name):
            by_name.update({f: os.path.join(root, f) for f in fnames if f.startswith("data-")})
    rows = []
    rb = table.new_read_builder()
    for s in rb.new_scan().plan():  # plan order, the order the reader sees
        for f in s.files:
            rows.extend(pq.read_table(by_name[f.file_name]).to_pylist())
    assert rows, f"pyarrow readback found no live data files for {name}"
    return rows


def bench_merge_read(cat_path, kind, spec):
    """Same physical table, table.copy flips only merge.dict-domain: the
    delta is decode + key ranks + winner gathers in the code domain. The
    timed region includes a to_arrow conversion — both modes must DELIVER
    the rows, the code domain as dictionary arrays."""
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(cat_path, commit_user="dict-bench")
    row = {"schema": kind, "workload": "merge_read", "rows": N_ROWS}
    base = cat.create_table(
        f"b.mr_{kind}", spec["schema"], primary_keys=spec["keys"],
        options=_base_opts(False, {"write-only": "true"}),
    )
    _write_runs(base, kind, N_ROWS, N_RUNS)
    outs = {}
    for dd in (False, True):
        t = base.copy({"merge.dict-domain": "true" if dd else "false"})
        rb = t.new_read_builder()
        best = float("inf")
        c0 = _dict_counters()
        out = None
        for it in range(ITERS + 1):  # first pass warms jit caches
            t0 = time.perf_counter()
            out = rb.new_read().read_all(rb.new_scan().plan())
            out.to_arrow()  # delivery included (code domain hands dictionaries)
            dt = time.perf_counter() - t0
            if it > 0:
                best = min(best, dt)
        outs[dd] = out
        tag = "on" if dd else "off"
        row[f"rows_per_sec_{tag}"] = round(out.num_rows / best, 1)
        if dd:
            row["counters"] = {k: v - c0[k] for k, v in _dict_counters().items()}
    assert outs[True].to_pylist() == outs[False].to_pylist(), f"{kind}: code-domain read differs"
    row["speedup"] = round(row["rows_per_sec_on"] / row["rows_per_sec_off"], 3)
    return row


def bench_compaction(cat_path, kind, spec):
    """The headline: full compaction rewrite (read -> merge -> encode) of
    N_RUNS overlapping sorted runs, fresh table per (option, attempt).
    Before timing counts, the ON table's compacted state is asserted equal
    to the OFF table's through the expanded reader AND through pyarrow."""
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(cat_path, commit_user="dict-bench")
    n = N_ROWS
    row = {"schema": kind, "workload": "compaction_rewrite", "rows": n}
    states = {}
    pa_states = {}
    for dd in (False, True):
        best = float("inf")
        for attempt in range(ITERS):
            name = f"cp_{kind}_{int(dd)}_{attempt}"
            t = cat.create_table(
                f"b.{name}", spec["schema"], primary_keys=spec["keys"],
                options=_base_opts(dd),  # compaction enabled (manual trigger)
            )
            _write_runs(t, kind, n, N_RUNS)
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            t0 = time.perf_counter()
            w.compact(full=True)
            best = min(best, time.perf_counter() - t0)
            wb.new_commit().commit(w.prepare_commit())
            if attempt == 0:
                # oracle check through the EXPANDED reader (option off) so
                # both states are compared by one decode path
                plain = t.copy({"merge.dict-domain": "false"})
                rb = plain.new_read_builder()
                states[dd] = rb.new_read().read_all(rb.new_scan().plan()).to_pylist()
                pa_states[dd] = _pyarrow_state(t, cat_path, name)
        row[f"rows_per_sec_{'on' if dd else 'off'}"] = round(n / best, 1)
    assert states[True] == states[False], f"{kind}: compacted state differs"
    assert pa_states[True] == pa_states[False], f"{kind}: pyarrow readback differs"
    row["speedup"] = round(row["rows_per_sec_on"] / row["rows_per_sec_off"], 3)
    return row


def bench_sort_compact(cat_path, kind, spec):
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.table.sort_compact import sort_compact

    cat = FileSystemCatalog(cat_path, commit_user="dict-bench")
    n = N_ROWS // 2
    row = {"schema": kind, "workload": "sort_compact", "rows": n}
    views = {}
    for dd in (False, True):
        best = float("inf")
        for attempt in range(2):
            t = cat.create_table(
                f"b.sc_{kind}_{int(dd)}_{attempt}", spec["schema"],
                options=_base_opts(dd),
            )
            _write_runs(t, kind, n, 2)
            t0 = time.perf_counter()
            total = sort_compact(t, spec["sort_cols"], order="order")
            best = min(best, time.perf_counter() - t0)
            rb = t.new_read_builder()
            views[dd] = rb.new_read().read_all(rb.new_scan().plan()).to_pylist()
        row[f"rows_per_sec_{'on' if dd else 'off'}"] = round(total / best, 1)
    assert views[True] == views[False], f"{kind}: clustered view differs"
    row["speedup"] = round(row["rows_per_sec_on"] / row["rows_per_sec_off"], 3)
    return row


def run(write_results=True):
    assert os.environ.get("PAIMON_TPU_DICT_DOMAIN") is None, (
        "unset PAIMON_TPU_DICT_DOMAIN: the bench flips the table option"
    )
    tmp = tempfile.mkdtemp(prefix="paimon_tpu_dict_bench_")
    rows = []
    try:
        for kind, spec in _schemas().items():
            rows.append(bench_merge_read(os.path.join(tmp, f"mr_{kind}"), kind, spec))
            rows.append(bench_compaction(os.path.join(tmp, f"cp_{kind}"), kind, spec))
            rows.append(bench_sort_compact(os.path.join(tmp, f"sc_{kind}"), kind, spec))
            for r in rows[-3:]:
                print(json.dumps(r))
        headline = next(
            r for r in rows if r["schema"] == "dict_heavy" and r["workload"] == "compaction_rewrite"
        )
        summary = {
            "metric": "compaction rewrite dict-domain on vs off (dict_heavy)",
            "speedup": headline["speedup"],
            "target": 2.0,
            "pass": headline["speedup"] >= 2.0,
        }
        print(json.dumps(summary))
        if write_results:
            os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
            with open(RESULTS, "w") as f:
                json.dump({"rows": rows, "summary": summary}, f, indent=1)
        return rows, summary
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    run()
