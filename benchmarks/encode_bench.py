#!/usr/bin/env python
"""Benchmark: arrow vs native parquet ENCODE on the write path.

Headline: ingest throughput (rows/s) for a 1M-row flat primary-key table —
dictionary string key + numeric values, the merge pool-reuse shape — driven
through the real table write surface (new_batch_write_builder → write →
prepare_commit → commit), so the measured wall covers memtable, merge and
file encode exactly as production flushes do. Two identical tables differ
only in `format.parquet.encoder`.

No-regression guard: after the timed passes, EVERY natively-written data
file is read back with pyarrow (pq.read_table) and compared bit-identically
against the arrow-encoded table's merged view — a native file pyarrow
cannot read exactly is a benchmark failure, not a footnote.

Acceptance (ISSUE 5): native flush encode >= 1.2x arrow rows/s on this
shape. Results also land in benchmarks/results/encode_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_ROWS = 1_000_000
RESULTS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "encode_bench.json")


N_REGIONS = 256  # dictionary cardinality of the string key column


def build_data(n_rows):
    """Flat PK schema with a dictionary string key: PK = (region, id) where
    region is a low-cardinality string (the merge pool-reuse shape — its
    ranks become dictionary codes directly) and id makes rows unique.
    Rows arrive PK-sorted, the merged flush shape."""
    rng = np.random.default_rng(11)
    region_ids = np.sort(rng.integers(0, N_REGIONS, n_rows))
    regions = np.array([f"region-{int(x):04d}" for x in range(N_REGIONS)], dtype=object)
    perm = rng.permutation(n_rows).astype(np.int64)
    return {
        "region": regions[region_ids],
        "id": np.arange(n_rows, dtype=np.int64),
        "c1": perm * 3,
        "d1": perm.astype(np.float64) * 0.5,
        "tag": np.array([f"tag-{int(x) % 16}" for x in perm], dtype=object),
    }


def make_table(cat, name, encoder):
    import paimon_tpu as pt

    schema = pt.RowType.of(
        ("region", pt.STRING(False)),
        ("id", pt.BIGINT(False)),
        ("c1", pt.BIGINT()),
        ("d1", pt.DOUBLE()),
        ("tag", pt.STRING()),
    )
    return cat.create_table(
        f"bench.{name}",
        schema,
        primary_keys=["region", "id"],
        options={
            "bucket": "1",
            "file.format": "parquet",
            "write-only": "true",
            "format.parquet.encoder": encoder,
        },
    )


def ingest_once(table, data) -> float:
    t0 = time.perf_counter()
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write(data)
    wb.new_commit().commit(w.prepare_commit())
    return time.perf_counter() - t0


def run_headline(n_rows=N_ROWS, iters=3):
    """[ingest row, breakdown row] — the two bench.py write-path lines."""
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.metrics import encode_metrics

    data = build_data(n_rows)
    tmp = tempfile.mkdtemp(prefix="paimon_tpu_encode_bench_")
    try:
        cat = FileSystemCatalog(tmp, commit_user="bench")
        walls = {}
        for encoder in ("arrow", "native"):
            best = float("inf")
            for it in range(iters):
                table = make_table(cat, f"{encoder}{it}", encoder)
                g = encode_metrics()
                n0, f0 = g.counter("files_native").count, g.counter("files_fallback").count
                dt = ingest_once(table, data)
                best = min(best, dt)
                if encoder == "native":
                    assert g.counter("files_native").count > n0, "native encoder did not run"
                    assert g.counter("files_fallback").count == f0, "unexpected arrow fallback"
            walls[encoder] = best
        # ---- no-regression guard: pyarrow reads every native file exactly
        import pyarrow.parquet as pq

        arrow_t = make_table(cat, "guard_a", "arrow")
        native_t = make_table(cat, "guard_n", "native")
        ingest_once(arrow_t, data)
        ingest_once(native_t, data)
        rb_a, rb_n = arrow_t.new_read_builder(), native_t.new_read_builder()
        ref = rb_a.new_read().read_all(rb_a.new_scan().plan())
        native_files = []
        for root, _dirs, files in os.walk(tmp):
            if "guard_n" in root:
                native_files += [os.path.join(root, f) for f in files if f.endswith(".parquet") and "data-" in f]
        assert native_files, "no native data files found for the guard"
        pa_rows = 0
        for f in native_files:
            pa_rows += pq.read_table(f).num_rows
        assert pa_rows == n_rows, f"pyarrow read {pa_rows} rows from native files, expected {n_rows}"
        got = rb_n.new_read().read_all(rb_n.new_scan().plan())
        assert got.to_pydict() == ref.to_pydict(), "native-encoded table diverges from arrow-encoded"

        g = encode_metrics()
        ingest_row = {
            "metric": f"ingest throughput ({n_rows // 1_000_000 or 1}M-row PK write+flush, dict string key)",
            "arrow_rows_per_sec": round(n_rows / walls["arrow"], 1),
            "native_rows_per_sec": round(n_rows / walls["native"], 1),
            "native_vs_arrow": round(walls["arrow"] / walls["native"], 3),
            "unit": "rows/s",
        }
        breakdown_row = {
            "metric": "native encode breakdown (write path)",
            "pages_written": g.counter("pages_written").count,
            "bytes_written": g.counter("bytes_written").count,
            "dict_pages": g.counter("dict_pages").count,
            "files_native": g.counter("files_native").count,
            "files_fallback": g.counter("files_fallback").count,
            "encode_ms_mean": round(g.histogram("encode_ms").mean, 2),
            "stats_ms_mean": round(g.histogram("stats_ms").mean, 3),
            "unit": "counters",
        }
        return [ingest_row, breakdown_row]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    rows = run_headline()
    for row in rows:
        print(json.dumps(row))
    ratio = rows[0]["native_vs_arrow"]
    verdict = {
        "metric": "native encode speedup target (>= 1.2x arrow)",
        "value": ratio,
        "pass": ratio >= 1.2,
        "unit": "x",
    }
    rows.append(verdict)
    print(json.dumps(verdict))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump({"rows": N_ROWS, "results": rows}, f, indent=1)
    print(json.dumps({"metric": "encode_bench results file", "value": RESULTS}))


if __name__ == "__main__":
    main()
