#!/usr/bin/env python
"""Pallas frontier verdict (VERDICT r2 #6): either measure the fused pallas
boundary kernel against the XLA path on chip, or capture exactly why it
cannot run and the roofline argument for the XLA path.

Attempts, in order:
  1. compile + run ops/pallas_kernels.keep_last_mask on the real chip
     (mosaic lowering through the environment's remote_compile service);
  2. if that fails, record the full error;
  3. always: measure the XLA sort kernel's achieved bytes/s on chip and
     compare against the v5e HBM roofline (~819 GB/s), counting the sort's
     actual pass traffic, so the "is XLA sort fast enough" question gets a
     number either way.

Prints JSON lines; the last line is the verdict summary.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # for kernel_resident

from paimon_tpu.utils import enable_compile_cache
from paimon_tpu.utils.tpuguard import ensure_live_backend

enable_compile_cache()
PLATFORM = ensure_live_backend()

HBM_PEAK_GBS = 819.0  # v5e HBM bandwidth


def emit(**kw):
    print(json.dumps({"platform": PLATFORM, **kw}), flush=True)


def try_pallas(n: int = 1 << 20) -> tuple[bool, str]:
    import jax
    import jax.numpy as jnp

    from paimon_tpu.ops.pallas_kernels import keep_last_mask

    rng = np.random.default_rng(5)
    keys = jnp.asarray(
        np.stack([np.zeros(n, np.uint32), np.sort(rng.integers(0, n // 4, n, dtype=np.uint32))])
    )
    try:
        t0 = time.perf_counter()
        out = keep_last_mask(keys, interpret=False)
        s = int(np.asarray(out).sum())  # value fetch = real sync
        compile_s = time.perf_counter() - t0
        # timed via chained value fetches (block_until_ready does not block
        # on the axon tunnel)
        t0 = time.perf_counter()
        for _ in range(4):
            s2 = int(np.asarray(keep_last_mask(keys, interpret=False)).sum())
        dt = (time.perf_counter() - t0) / 4
        emit(metric="pallas.keep_last_mask", ok=True, rows=n, selected=s,
             compile_s=round(compile_s, 1), per_call_ms=round(dt * 1e3, 2))
        return True, ""
    except Exception as e:  # noqa: BLE001
        err = repr(e)
        emit(metric="pallas.keep_last_mask", ok=False, rows=n, error=err[:2000])
        return False, err


def xla_roofline(n: int = 1 << 22) -> dict:
    """Achieved HBM traffic of the dedup sort+select kernel vs peak.

    Traffic model for lax.sort of L u32 lanes over m rows on TPU (variadic
    comparator sort, ~log2(m) merge passes, each pass streaming all lanes
    in + out) plus the segment/boundary epilogue (2 more passes over the
    key lanes): bytes ~= 2 * L * 4 * m * log2(m) + 2 * K * 4 * m."""
    import jax

    from paimon_tpu.ops.merge import _dedup_select_fn, prepare_lanes

    rng = np.random.default_rng(7)
    key_lanes = rng.integers(0, n // 4, size=(n, 1), dtype=np.uint32)
    klp, slp, pad, _, k, s, m = prepare_lanes(key_lanes, None)
    dev = jax.devices()[0]
    dklp = jax.block_until_ready(jax.device_put(klp, dev))
    dslp = jax.block_until_ready(jax.device_put(slp, dev))
    dpad = jax.block_until_ready(jax.device_put(pad, dev))
    fn = _dedup_select_fn(k, s)

    # chained-slope timing (kernel_resident.time_kernel): K data-dependent
    # kernel invocations inside ONE jit, one value-fetch sync — a per-call
    # value fetch would add the tunnel RTT (~80 ms) to every iteration and
    # understate the kernel ~10x
    from kernel_resident import time_kernel

    rows_per_s = time_kernel(fn, (dklp, dslp, dpad), n)
    per_call = n / rows_per_s
    # actual operand byte widths (lanes may be narrowed u16, pad is u8,
    # iota is i32) — hardcoding 4 B/lane would overstate achieved GB/s
    lane_bytes = pad.dtype.itemsize + sum(a.dtype.itemsize for a in klp) + sum(
        a.dtype.itemsize for a in slp
    ) + 4  # + iota
    key_bytes = pad.dtype.itemsize + sum(a.dtype.itemsize for a in klp)
    log2m = int(np.log2(m))
    traffic = 2 * lane_bytes * m * log2m + 2 * key_bytes * m
    achieved = traffic / per_call / 1e9
    out = {
        "metric": "xla-sort.roofline",
        "rows": n,
        "padded": m,
        "per_call_ms": round(per_call * 1e3, 2),
        "rows_per_s": round(n / per_call, 1),
        "modeled_traffic_mb": round(traffic / 1e6, 1),
        "achieved_gbs": round(achieved, 1),
        "hbm_peak_gbs": HBM_PEAK_GBS,
        "pct_of_peak": round(100 * achieved / HBM_PEAK_GBS, 1),
    }
    emit(**out)
    return out


def main():
    ok, err = try_pallas()
    roof = xla_roofline()
    emit(
        metric="pallas.verdict",
        pallas_compiles_on_chip=ok,
        xla_sort_pct_of_hbm_peak=roof["pct_of_peak"],
        conclusion=(
            "pallas path measured on chip"
            if ok
            else "mosaic compilation unavailable through this environment's "
                 "remote_compile service; XLA sort path quantified vs HBM roofline instead"
        ),
    )


if __name__ == "__main__":
    main()
