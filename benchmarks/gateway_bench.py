#!/usr/bin/env python
"""Benchmark: gateway hedged reads vs a latency-shamed straggler worker.

One primary-key table served by a 2-worker in-process cluster where
worker 0 is latency-shamed (serve_delay_ms=250): every get owning one of
its buckets pays the straggler unless the gateway hedges. The same
deterministic probe sequence runs through two Gateway configurations at
equal offered load (closed-loop, sequential):

  unhedged  gateway.hedge.max-fraction=0.0 — every straggler-owned group
            waits the full 250 ms
  hedged    gateway.hedge.deadline-ms=25, max-fraction=0.75 — a group
            that misses the deadline re-issues to the healthy non-owner;
            first non-BUSY reply wins, the loser is cancelled

Every probe's rows are asserted BIT-IDENTICAL across both modes and
against the formula oracle (exactly-representable doubles), the hedge
budget is asserted respected (hedges_issued <= max_fraction *
hedgeable + 1), and both gateways must drain (no orphaned attempt).

Headline (asserted in main): hedged p99 at least 2x better than the
unhedged p99. Results land in benchmarks/results/gateway_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

N_BUCKETS = 4
N_ROWS = int(os.environ.get("PAIMON_TPU_GWB_ROWS", "2000"))
N_PROBES = int(os.environ.get("PAIMON_TPU_GWB_PROBES", "40"))
KEYS_PER_PROBE = 8
STRAGGLER_MS = float(os.environ.get("PAIMON_TPU_GWB_STRAGGLER_MS", "250"))
HEDGE_DEADLINE_MS = float(os.environ.get("PAIMON_TPU_GWB_DEADLINE_MS", "25"))
MAX_FRACTION = 0.75
ITERS = int(os.environ.get("PAIMON_TPU_GWB_ITERS", "2"))
RESULTS = os.path.join(HERE, "results", "gateway_bench.json")


def _build(base: str):
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

    cat = FileSystemCatalog(os.path.join(base, "wh"), commit_user="gwbench")
    t = cat.create_table(
        "db.c",
        RowType.of(("k", BIGINT(False)), ("v", DOUBLE()), ("g", STRING())),
        primary_keys=["k"],
        options={"bucket": str(N_BUCKETS), "write-only": "true"},
    )
    ks = list(range(N_ROWS))
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({
        "k": ks,
        "v": [x * 0.25 for x in ks],  # exactly-representable doubles
        "g": [f"g{x % 5}" for x in ks],
    })
    wb.new_commit().commit(w.prepare_commit())
    return cat, t


def _probes() -> list:
    import numpy as np

    rng = np.random.default_rng(7)
    return [
        sorted(int(k) for k in rng.choice(N_ROWS, size=KEYS_PER_PROBE, replace=False))
        for _ in range(N_PROBES)
    ]


def _run_mode(cat, t, cli, options: dict, probes: list, iters: int):
    """One gateway configuration over the full probe sequence: per-probe
    latencies (ms), the probe results, and the gateway's hedge SLO slice.
    One untimed warm-up probe absorbs cold caches AND the hedge budget's
    cold start (the first hedgeable request can never hedge: issued+1 <=
    max_fraction * requests starts false)."""
    from paimon_tpu.service.gateway import Gateway

    with Gateway(t, catalog=cat, client=cli, options=options) as gw:
        gw.get_batch(probes[0])  # warm-up, untimed
        lats, outs = [], []
        for _ in range(iters):
            outs_it = []
            for ks in probes:
                t0 = time.perf_counter()
                got = gw.get_batch(ks)
                lats.append((time.perf_counter() - t0) * 1000.0)
                outs_it.append(got)
            if outs:
                assert outs_it == outs, "probe results drifted across iterations"
            outs = outs_it
        assert gw.wait_hedges_drained(30.0), "hedge attempts failed to drain"
        assert gw.hedge_inflight() == 0
        hedge = gw.slo()["hedge"]
    return outs, lats, hedge


def run(iters: int = ITERS) -> dict:
    import numpy as np

    from paimon_tpu.service.cluster import (
        ClusterClient,
        ClusterConfig,
        ClusterCoordinator,
        ClusterWorkerAgent,
    )
    from paimon_tpu.service.subscription import SubscriptionHub
    from paimon_tpu.table import load_table

    base = tempfile.mkdtemp(prefix="paimon_gateway_bench_")
    try:
        cat, t = _build(base)
        probes = _probes()
        oracle = [[(k, k * 0.25, f"g{k % 5}") for k in ks] for ks in probes]
        coord = ClusterCoordinator(
            t.path, ClusterConfig(workers=2, buckets=N_BUCKETS, compaction=False)
        ).start()
        agents, cli = [], None
        try:
            for wid in range(2):
                a = ClusterWorkerAgent(
                    wid, load_table(t.path, commit_user=f"gwb{wid}"),
                    coord.host, coord.port, serve=True, heartbeat_interval_s=0.5,
                    serve_delay_ms=(STRAGGLER_MS if wid == 0 else None),
                )
                a.register()
                a.start_heartbeats()
                agents.append(a)
            cli = ClusterClient(load_table(t.path, commit_user="gwbcli"), coord.host, coord.port)
            un_outs, un_lats, un_hedge = _run_mode(
                cat, t, cli,
                {"gateway.hedge.deadline-ms": str(int(HEDGE_DEADLINE_MS)),
                 "gateway.hedge.max-fraction": "0.0"},
                probes, iters,
            )
            h_outs, h_lats, h_hedge = _run_mode(
                cat, t, cli,
                {"gateway.hedge.deadline-ms": str(int(HEDGE_DEADLINE_MS)),
                 "gateway.hedge.max-fraction": str(MAX_FRACTION)},
                probes, iters,
            )
        finally:
            if cli is not None:
                cli.close()
            for a in agents:
                a.close()
            coord.close()
            SubscriptionHub.shutdown_all()
    finally:
        shutil.rmtree(base, ignore_errors=True)

    assert un_outs == oracle, "unhedged results diverged from the oracle"
    assert h_outs == oracle, "hedged results diverged from the oracle"
    assert un_hedge["hedges_issued"] == 0, "max-fraction 0.0 must never hedge"
    assert h_hedge["hedges_issued"] > 0, "the straggler never triggered a hedge"
    assert h_hedge["hedges_issued"] <= (
        MAX_FRACTION * max(h_hedge["hedgeable_requests"], 1) + 1
    ), "hedge budget exceeded"

    def pct(xs, q):
        return round(float(np.percentile(np.asarray(xs), q)), 2)

    points = [
        {"mode": "unhedged", "p50_ms": pct(un_lats, 50), "p99_ms": pct(un_lats, 99),
         "probes": len(un_lats), **{k: un_hedge[k] for k in ("hedges_issued", "hedgeable_requests")}},
        {"mode": "hedged", "p50_ms": pct(h_lats, 50), "p99_ms": pct(h_lats, 99),
         "probes": len(h_lats), **{k: h_hedge[k] for k in ("hedges_issued", "hedgeable_requests")}},
    ]
    speedup = round(points[0]["p99_ms"] / max(points[1]["p99_ms"], 1e-9), 2)
    row = {
        "metric": "gateway hedged get_batch p99 vs a straggler worker",
        "unit": "ms p99",
        "straggler_ms": STRAGGLER_MS,
        "hedge_deadline_ms": HEDGE_DEADLINE_MS,
        "hedge_max_fraction": MAX_FRACTION,
        "p99_unhedged_ms": points[0]["p99_ms"],
        "p99_hedged_ms": points[1]["p99_ms"],
        "p99_speedup": speedup,
        "hedges_issued": h_hedge["hedges_issued"],
        "hedgeable_requests": h_hedge["hedgeable_requests"],
        "identical_output": True,
    }
    return {"straggler_ms": STRAGGLER_MS, "points": points, "row": row}


def run_headline(iters: int = 2) -> list:
    """bench.py hook: the sweep at reduced iterations, returning the rows
    it prints. The p99 floor is asserted by main(), not here — the
    headline row reports whatever this rig produced."""
    res = run(iters=iters)
    return [res["row"]]


def main() -> None:
    res = run()
    for p in res["points"]:
        print(json.dumps(p))
    print(json.dumps(res["row"]))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump(res, f, indent=1)
    speedup = res["row"]["p99_speedup"]
    assert speedup >= 2.0, f"hedged p99 speedup {speedup} < 2x"


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
