#!/usr/bin/env python
"""Benchmark: distributed SQL scatter-gather scan scaling (sql.cluster).

One latency-shaped table (fs/testing.LatencyFileIO — every data/manifest
file open pays a simulated object-store RTT), aggregate GROUP BY queries
executed four ways: single-process `sql.query` reading THROUGH the
latency store, and `sql.cluster_query` against 1/2/4 serve-mode worker
OS processes. The worker data plane is where the RTT budget lives: each
worker scans only its owned buckets' splits and reduces them to ONE
partial aggregate on device (segment_reduce keyed on dictionary codes),
so W workers sleep their serial per-split RTTs concurrently and ship
back partial rows instead of scan rows. The coordinator combines
partials in the code domain (unify_pools + remap_codes + one more
segment_reduce) and runs the shared _finish tail. The coordinator's own
metadata plane (split planning) reads the plain local path — the
cluster_bench topology: data streams through the object store on the
workers while the coordinator keeps manifests cached locally.

Every timed pass asserts the distributed result BIT-IDENTICAL to the
single-process evaluator first (exactly-representable doubles make float
sums order-independent), and the cluster points additionally assert
sql{rows_reduced_device} grew — partials really reduced on workers.

Headline (asserted in main): aggregate-query speedup at 4 workers >= 3x
over 1 worker. Results land in benchmarks/results/sql_cluster_bench.json.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

N_BUCKETS = 4
COMMITS = int(os.environ.get("PAIMON_TPU_SQLCB_COMMITS", "6"))
ROWS_PER_COMMIT = int(os.environ.get("PAIMON_TPU_SQLCB_ROWS", "8000"))
RTT_READ_MS = float(os.environ.get("PAIMON_TPU_SQLCB_RTT_MS", "250"))
ITERS = int(os.environ.get("PAIMON_TPU_SQLCB_ITERS", "3"))
WORKER_COUNTS = (1, 2, 4)
RESULTS = os.path.join(HERE, "results", "sql_cluster_bench.json")

QUERY = (
    "SELECT g, count(*), count(a), sum(a), min(b), max(b) FROM db.r "
    "GROUP BY g ORDER BY g"
)
SCALAR_QUERY = "SELECT count(*), sum(b), min(b), max(b) FROM db.r"

TABLE_OPTIONS = {
    "bucket": str(N_BUCKETS),
    "write-only": "true",
    # data bytes cold on every timed pass (each open pays the RTT); decoded
    # manifests warm after the untimed first iteration, so plan cost does
    # not smear the scan-scaling signal
    "cache.data-file.max-memory-size": "0 b",
    "cache.manifest.max-memory-size": "256 mb",
}


def _build(base: str):
    import numpy as np

    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

    cat = FileSystemCatalog(os.path.join(base, "wh"), commit_user="bench")
    t = cat.create_table(
        "db.r",
        RowType.of(("k", BIGINT(False)), ("a", BIGINT()), ("b", DOUBLE()), ("g", STRING())),
        primary_keys=["k"],
        options=TABLE_OPTIONS,
    )
    rng = np.random.default_rng(11)
    for r in range(COMMITS):
        ks = rng.choice(2 * ROWS_PER_COMMIT * COMMITS, size=ROWS_PER_COMMIT, replace=False)
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write({
            "k": ks.tolist(),
            "a": [None if x % 13 == 0 else int(x % 997) for x in ks.tolist()],
            "b": (ks * 0.25 + r).tolist(),  # exactly representable: order-free sums
            "g": [f"g{int(x) % 7}" for x in ks.tolist()],
        })
        wb.new_commit().commit(w.prepare_commit())
    # the same physical files through the latency scheme: what the
    # single-process evaluator (whole engine behind the store) reads
    lat_cat = FileSystemCatalog("latency://" + os.path.join(base, "wh"), commit_user="bench")
    return cat, lat_cat, t


def _child_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PAIMON_TPU_CLUSTER_ROLE"] = "worker"
    env["PYTHONPATH"] = os.path.dirname(HERE) + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    return env


def _time_single(cat, want_rows: dict) -> float:
    from paimon_tpu.sql import query

    best = float("inf")
    for it in range(ITERS):
        t0 = time.perf_counter()
        outs = {q: query(cat, q).to_pylist() for q in want_rows}
        dt = time.perf_counter() - t0
        for q, rows in outs.items():
            assert rows == want_rows[q], f"single-process drift: {q}"
        if it > 0:
            best = min(best, dt)
    return best


def run_point(workers: int, cat, root: str, base: str, want_rows: dict) -> dict:
    """One cluster point: coordinator + client plan on the plain `root`;
    worker processes load `latency://root` so their scans pay the RTT."""
    from paimon_tpu.metrics import sql_metrics
    from paimon_tpu.service.cluster import ClusterClient, ClusterConfig, ClusterCoordinator
    from paimon_tpu.table import load_table

    coord = ClusterCoordinator(
        root, ClusterConfig(workers=workers, buckets=N_BUCKETS, compaction=False)
    ).start()
    procs, cli = [], None
    try:
        for wid in range(workers):
            log = open(os.path.join(base, f"sqlw{workers}-{wid}.log"), "wb")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "paimon_tpu.service.cluster", "worker",
                 "--table", "latency://" + root, "--wid", str(wid),
                 "--coordinator", f"{coord.host}:{coord.port}",
                 "--mode", "serve", "--heartbeat-interval", "0.2",
                 "--rtt-read-ms", str(RTT_READ_MS)],
                stdout=log, stderr=subprocess.STDOUT, env=_child_env(),
            ))
            log.close()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            for p in procs:
                if p.poll() not in (None,):
                    tail = open(os.path.join(base, f"sqlw{workers}-{procs.index(p)}.log"), "rb").read()[-2000:]
                    raise RuntimeError(f"worker died rc={p.returncode}:\n{tail.decode(errors='replace')}")
            try:
                cli = ClusterClient(load_table(root, commit_user="cli"), coord.host, coord.port)
                if len({cli.owner_of(b) for b in range(N_BUCKETS)}) == min(workers, N_BUCKETS):
                    break
                cli.close()
                cli = None
            except Exception:
                pass
            time.sleep(0.2)
        assert cli is not None, f"{workers} workers never registered serve ports"

        from paimon_tpu.sql import cluster_query

        g = sql_metrics()
        reduced0 = g.counter("rows_reduced_device").count
        best = float("inf")
        for it in range(ITERS):
            t0 = time.perf_counter()
            outs = {q: cluster_query(cat, q, cli).to_pylist() for q in want_rows}
            dt = time.perf_counter() - t0
            for q, rows in outs.items():
                assert rows == want_rows[q], f"{workers}w diverged from single-process: {q}"
            if it > 0:
                best = min(best, dt)
        reduced = g.counter("rows_reduced_device").count - reduced0
        assert reduced > 0, "no rows were reduced on workers"
        return {
            "workers": workers,
            "wall_s": round(best, 3),
            "queries_per_sec": round(len(want_rows) / best, 2),
            "rows_reduced_device": reduced,
            "identical_to_single_process": True,
        }
    finally:
        if cli is not None:
            cli.close()
        for p in procs:
            try:
                p.terminate()
                p.wait(timeout=30)
            except Exception:
                p.kill()
        coord.close()


def run(iters: int = ITERS) -> dict:
    """Full sweep: build, oracle, single-process timing, 1/2/4-worker
    cluster timings. Returns {points, single, row}."""
    global ITERS
    ITERS = iters
    from paimon_tpu.fs.testing import LatencyFileIO
    from paimon_tpu.sql import query

    base = tempfile.mkdtemp(prefix="paimon_sqlcluster_bench_")
    try:
        cat, lat_cat, t = _build(base)
        # the oracle rows: computed once on the plain path with NO latency,
        # asserted by every timed pass at every worker count
        want_rows = {q: query(cat, q).to_pylist() for q in (QUERY, SCALAR_QUERY)}
        LatencyFileIO.configure(read_ms=RTT_READ_MS, write_ms=0.0)
        try:
            single_s = _time_single(lat_cat, want_rows)
            points = [run_point(w, cat, t.path, base, want_rows) for w in WORKER_COUNTS]
        finally:
            LatencyFileIO.configure(read_ms=0.0, write_ms=0.0)
    finally:
        shutil.rmtree(base, ignore_errors=True)
    top = points[-1]
    speedup = round(points[0]["wall_s"] / top["wall_s"], 2)
    row = {
        "metric": "distributed SQL aggregate scan-fragment scaling (latency-shaped store)",
        "unit": "s/query-pair",
        "rtt_read_ms": RTT_READ_MS,
        "single_process_s": round(single_s, 3),
        **{f"wall_s@{p['workers']}w": p["wall_s"] for p in points},
        "speedup": speedup,
        "speedup_workers": f"{top['workers']}w vs {points[0]['workers']}w",
        "vs_single_process": round(single_s / top["wall_s"], 2),
        "identical_output": True,
    }
    return {"rtt_read_ms": RTT_READ_MS, "points": points, "single_process_s": round(single_s, 3), "row": row}


def run_headline(iters: int = 2) -> list:
    """bench.py hook: the sweep at reduced iterations, returning the rows
    it prints. The scaling floor is asserted by main(), not here — the
    headline row reports whatever this rig produced."""
    res = run(iters=iters)
    return [res["row"]]


def main() -> None:
    res = run()
    for p in res["points"]:
        print(json.dumps(p))
    print(json.dumps(res["row"]))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump(res, f, indent=1)
    speedup = res["row"]["speedup"]
    assert speedup >= 3.0, f"4-worker aggregate speedup {speedup} < 3x"


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
