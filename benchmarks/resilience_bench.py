#!/usr/bin/env python
"""Benchmark: commit throughput under injected transient faults.

Runs N small write->commit rounds against a fail:// store at 0% / 1% / 5%
injected transient-fault rates, in two configurations:

  resilient   fs.retry defaults (RetryingFileIO + bounded commit retry)
  seed        fs.retry.max-attempts=1 — the pre-resilience behavior where
              the FIRST fault aborts the commit

Demonstrates graceful degradation: with the resilience layer every commit
succeeds at every rate (bounded slowdown from backoff), while the seed
configuration aborts a commit on nearly every injected fault.

Prints one JSON line per (rate, mode) with commits/s, failed commits, and the
io{retries, giveups} counters. Also writes benchmarks/results/resilience_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paimon_tpu.core.manifest import ManifestCommittable
from paimon_tpu.core.schema import SchemaManager
from paimon_tpu.core.store import KeyValueFileStore
from paimon_tpu.data import ColumnBatch
from paimon_tpu.fs import get_file_io
from paimon_tpu.fs.testing import ArtificialException, FailingFileIO
from paimon_tpu.metrics import io_metrics, registry
from paimon_tpu.types import BIGINT, DOUBLE, RowType

SCHEMA = RowType.of(("k", BIGINT()), ("v", DOUBLE()))
N_COMMITS = 25
ROWS_PER_COMMIT = 200
RATES = [(0.0, 0), (0.01, 100), (0.05, 20)]  # (rate, 1/possibility)


def run_config(rate: float, possibility: int, resilient: bool, seed: int = 7) -> dict:
    domain = f"bench_{'res' if resilient else 'seed'}_{int(rate * 100)}"
    tmp = tempfile.mkdtemp(prefix="paimon_resilience_bench_")
    try:
        FailingFileIO.reset(domain, 0, 0)
        io = get_file_io(f"fail://{domain}/x")
        path = f"fail://{domain}{tmp}/table"
        opts = {"bucket": "1", "commit.retry-backoff": "2 ms"}
        if resilient:
            opts.update({"fs.retry.initial-backoff": "2 ms", "fs.retry.max-backoff": "50 ms"})
        else:
            opts["fs.retry.max-attempts"] = "1"
        ts = SchemaManager(io, path).create_table(SCHEMA, primary_keys=["k"], options=opts)
        store = KeyValueFileStore(io, path, ts, commit_user="bench")
        registry.reset()
        g = io_metrics()
        rng = np.random.default_rng(seed)
        FailingFileIO.reset(domain, max_fails=10**9, possibility=possibility, seed=seed)
        failed = 0
        committed = 0
        t0 = time.perf_counter()
        for i in range(1, N_COMMITS + 1):
            ks = rng.integers(0, 10_000, ROWS_PER_COMMIT).tolist()
            vs = [float(x) for x in rng.random(ROWS_PER_COMMIT)]
            try:
                w = store.new_writer((), 0)
                w.write(ColumnBatch.from_pydict(store.value_schema, {"k": ks, "v": vs}))
                msg = w.prepare_commit()
                store.new_commit().commit(ManifestCommittable(i, messages=[msg]))
                committed += 1
            except ArtificialException:
                failed += 1  # seed behavior: first fault aborts the commit
        dt = time.perf_counter() - t0
        FailingFileIO.reset(domain, 0, 0)
        return {
            "metric": "commit throughput under injected faults",
            "fault_rate": rate,
            "mode": "resilient" if resilient else "seed",
            "commits": committed,
            "failed_commits": failed,
            "commits_per_sec": round(committed / dt, 2) if dt > 0 else None,
            "io_retries": g.counter("retries").count,
            "io_giveups": g.counter("giveups").count,
            "wall_s": round(dt, 3),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # host-side IO bench: never grab the chip
    run_config(0.0, 0, True)  # warm jit/format caches so timings compare configs, not compilation
    rows = []
    for rate, possibility in RATES:
        for resilient in (True, False):
            row = run_config(rate, possibility, resilient)
            rows.append(row)
            print(json.dumps(row))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results", "resilience_bench.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
