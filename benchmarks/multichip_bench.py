#!/usr/bin/env python
"""Benchmark: mesh-sharded execution (merge.engine = mesh) scaling over
simulated device counts.

Three table-level workloads — merge-read, full compaction, sort-compact —
run at 1/2/4/8 devices, each device count in its OWN subprocess with
``XLA_FLAGS=--xla_force_host_platform_device_count=<d>`` (jax fixes the
device count at backend init, so scaling points can't share a process; the
same mechanism __graft_entry__'s dryrun uses). At every point the mesh
output is asserted BIT-IDENTICAL to the single-engine path before any time
is recorded; at 1 device the mesh engine exercises its cpu fallback, so the
"1 device" row doubles as the degradation guard.

Storage sits behind fs/testing.LatencyFileIO (fixed first-byte latency per
object read — the object-store shape). That is the resource the mesh layer
actually scales on this 1-core CI rig: the host-side feeder opens one
prefetch lane per device, so 8 devices pay the per-file RTT ~8 splits at a
time while the batched shard_map merges run; real chips add compute scaling
on top (each virtual CPU device here shares the single core, so device math
can only tie). Headline: merge-read wall at 8 devices >= 3x the 1-device
wall on the 8-bucket scan.

Rows land in benchmarks/results/multichip_bench.json; run_headline() is the
bench.py entry point (spawns only the 1- and 8-device children).
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

N_BUCKETS = 8
N_RUNS = int(os.environ.get("PAIMON_TPU_MULTICHIP_RUNS", "6"))
# x N_RUNS overlapping runs: a real k-way merge, IO-bound
ROWS_PER_RUN = int(os.environ.get("PAIMON_TPU_MULTICHIP_ROWS", "4000"))
STORE_RTT_MS = float(os.environ.get("PAIMON_TPU_MULTICHIP_RTT_MS", "90"))
SORT_ROWS = int(os.environ.get("PAIMON_TPU_MULTICHIP_SORT_ROWS", "24000"))
DEVICE_COUNTS = (1, 2, 4, 8)
RESULTS = os.path.join(HERE, "results", "multichip_bench.json")


# ---------------------------------------------------------------------------
# child: one device count, one process
# ---------------------------------------------------------------------------


def _build_pk_table(cat, name: str, engine: str):
    import numpy as np

    import paimon_tpu as pt

    schema = pt.RowType.of(
        ("id", pt.BIGINT(False)), ("c1", pt.BIGINT()), ("d1", pt.DOUBLE()), ("s1", pt.STRING())
    )
    table = cat.create_table(
        f"bench.{name}",
        schema,
        primary_keys=["id"],
        options={
            "bucket": str(N_BUCKETS),
            "write-only": "true",  # keep runs overlapping: real k-way merges
            "merge.engine": engine,
            "sort-engine": "xla-segmented",  # pin the device kernel on CPU
            # manifest cache ON (the PR 1 production default — planning RTT
            # is paid once, not per iteration), data-file cache OFF so every
            # timed scan re-fetches and re-decodes the data bytes cold
            "cache.data-file.max-memory-size": "0 b",
        },
    )
    rng = np.random.default_rng(23)
    total = ROWS_PER_RUN * N_RUNS
    ids = rng.permutation(total).astype(np.int64)
    for r in range(N_RUNS):
        chunk = np.sort(ids[r * ROWS_PER_RUN : (r + 1) * ROWS_PER_RUN])
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write(
            {
                "id": chunk,
                "c1": chunk * 3,
                "d1": chunk.astype(np.float64) * 0.5,
                "s1": np.array([f"v-{int(x) % 997:04d}" for x in chunk], dtype=object),
            }
        )
        wb.new_commit().commit(w.prepare_commit())
    return table


def _assert_identical(a, b):
    import numpy as np

    assert a.num_rows == b.num_rows, (a.num_rows, b.num_rows)
    for name in a.schema.field_names:
        assert np.array_equal(a.column(name).values, b.column(name).values), name
        assert np.array_equal(a.column(name).validity, b.column(name).validity), name


def _cold_read(table):
    # data bytes cold on every pass; the decoded-manifest cache stays warm
    # (see _build_pk_table) so the timed region is the scan, not planning
    from paimon_tpu.utils.cache import data_file_cache

    data_file_cache().clear()
    t0 = time.perf_counter()
    rb = table.new_read_builder()
    out = rb.new_read().read_all(rb.new_scan().plan())
    return time.perf_counter() - t0, out


def _bench_merge_read(slow_table, iters: int) -> dict:
    mesh = slow_table.copy({"merge.engine": "mesh"})
    single = slow_table.copy({"merge.engine": "single"})
    _cold_read(mesh)  # warm jit caches outside the timed region
    best_mesh = best_single = float("inf")
    for _ in range(iters):
        dt, out_m = _cold_read(mesh)
        best_mesh = min(best_mesh, dt)
        dt, out_s = _cold_read(single)
        best_single = min(best_single, dt)
        _assert_identical(out_m, out_s)  # every pass, before times count
    rows = out_m.num_rows
    return {
        "workload": "merge-read",
        "rows": rows,
        "mesh_ms": round(best_mesh * 1000, 1),
        "single_ms": round(best_single * 1000, 1),
        "rows_per_sec_mesh": round(rows / best_mesh, 1),
    }


def _bench_compaction(root: str, rtt_ms: float) -> dict:
    """Full compaction wall, mesh vs single, each on its OWN freshly built
    table (compaction mutates the LSM — the two engines can't share one)."""
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.fs.testing import LatencyFileIO
    from paimon_tpu.table import load_table

    out = {}
    readbacks = {}
    for engine in ("mesh", "single"):
        cat = FileSystemCatalog(os.path.join(root, f"compact_{engine}"), commit_user="bench")
        table = _build_pk_table(cat, f"compact_{engine}", engine)
        slow = load_table(f"latency://{table.path}", commit_user="bench")
        # the build table is write-only (keeps runs overlapping); the compact
        # job itself must run with compaction enabled
        slow = slow.copy({"merge.engine": engine, "write-only": "false"})
        t0 = time.perf_counter()
        wb = slow.new_batch_write_builder()
        w = wb.new_write()
        w.compact(full=True)
        wb.new_commit().commit(w.prepare_commit())
        out[engine] = time.perf_counter() - t0
        _, readbacks[engine] = _cold_read(slow)
    _assert_identical(readbacks["mesh"], readbacks["single"])
    return {
        "workload": "compaction",
        "mesh_ms": round(out["mesh"] * 1000, 1),
        "single_ms": round(out["single"] * 1000, 1),
    }


def _bench_sort_compact(root: str) -> dict:
    import numpy as np

    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.fs.testing import LatencyFileIO
    from paimon_tpu.table import load_table
    from paimon_tpu.table.sort_compact import sort_compact

    schema = pt.RowType.of(("x", pt.BIGINT(False)), ("y", pt.BIGINT()), ("s", pt.STRING()))
    out = {}
    readbacks = {}
    rng_seed = 31
    for engine in ("mesh", "single"):
        cat = FileSystemCatalog(os.path.join(root, f"sc_{engine}"), commit_user="bench")
        table = cat.create_table(
            f"bench.sc_{engine}",
            schema,
            options={
                "bucket": "4",
                "merge.engine": engine,
                "sort-engine": "xla-segmented",
                "parallel.key-axis.rows": "4096",
                "cache.manifest.max-memory-size": "0 b",
                "cache.data-file.max-memory-size": "0 b",
            },
        )
        rng = np.random.default_rng(rng_seed)
        per = SORT_ROWS // 3
        for r in range(3):  # 3 files per bucket: real multi-file input IO
            x = rng.integers(0, 1 << 40, per).astype(np.int64)
            wb = table.new_batch_write_builder()
            w = wb.new_write()
            w.write(
                {
                    "x": x,
                    "y": (x * 13) % 100_003,
                    "s": np.array([f"s{int(v) % 211}" for v in x], dtype=object),
                }
            )
            wb.new_commit().commit(w.prepare_commit())
        slow = load_table(f"latency://{table.path}", commit_user="bench").copy(
            {"merge.engine": engine}
        )
        # pass 1 warms the jit caches (key-axis kernel shapes are pow2-
        # padded, so the timed second pass reuses every compile)
        n = sort_compact(slow, ["y", "x"], order="zorder")
        assert n == 3 * per, n
        t0 = time.perf_counter()
        n = sort_compact(slow, ["y", "x"], order="zorder")
        out[engine] = time.perf_counter() - t0
        assert n == 3 * per, n
        _, readbacks[engine] = _cold_read(slow)
    _assert_identical(readbacks["mesh"], readbacks["single"])
    return {
        "workload": "sort-compact",
        "rows": SORT_ROWS,
        "mesh_ms": round(out["mesh"] * 1000, 1),
        "single_ms": round(out["single"] * 1000, 1),
    }


def child_main(n_devices: int, workloads: str, iters: int) -> None:
    import jax

    assert len(jax.devices()) == n_devices, (len(jax.devices()), n_devices)
    from paimon_tpu.fs.testing import LatencyFileIO
    from paimon_tpu.metrics import mesh_metrics
    from paimon_tpu.table import load_table

    tmp = tempfile.mkdtemp(prefix="paimon_tpu_multichip_")
    rows = []
    try:
        LatencyFileIO.configure(read_ms=STORE_RTT_MS)
        try:
            if "read" in workloads:
                from paimon_tpu.catalog import FileSystemCatalog

                cat = FileSystemCatalog(os.path.join(tmp, "read"), commit_user="bench")
                table = _build_pk_table(cat, "read", "mesh")
                slow = load_table(f"latency://{table.path}", commit_user="bench")
                rows.append(_bench_merge_read(slow, iters))
            if "compact" in workloads:
                rows.append(_bench_compaction(tmp, STORE_RTT_MS))
            if "sortcompact" in workloads:
                rows.append(_bench_sort_compact(tmp))
        finally:
            LatencyFileIO.configure()
        g = mesh_metrics()
        breakdown = {
            k: g.counter(k).count
            for k in ("buckets_sharded", "shards", "pad_rows", "exchange_rows")
        }
        print(
            json.dumps(
                {
                    "devices": n_devices,
                    "rtt_ms": STORE_RTT_MS,
                    "rows": rows,
                    "mesh_counters": breakdown,
                }
            )
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# parent: one subprocess per device count
# ---------------------------------------------------------------------------


def _spawn(n_devices: int, workloads: str = "read,compact,sortcompact", iters: int = 2) -> dict:
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split() if not f.startswith("--xla_force_host_platform_device_count")
    )
    env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    # pin the device merge kernels (the CPU-adaptive default would route the
    # whole bench through the host lexsort and measure nothing mesh-shaped),
    # and size the shared decode pool for one IO lane per device
    env["PAIMON_TPU_FORCE_DEVICE_ENGINE"] = "1"
    # one IO lane per device x files per split: the reads of every in-flight
    # split must be able to sleep their RTT concurrently (applies to both
    # engines equally — the single path simply has fewer lanes to fill)
    env.setdefault("PAIMON_TPU_SHARED_POOL_WORKERS", "64")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", str(n_devices), workloads, str(iters)],
        env=env,
        cwd=os.path.dirname(HERE),
        capture_output=True,
        text=True,
        timeout=1800,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"multichip child (devices={n_devices}) failed rc={proc.returncode}:\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _scaling_rows(points: list[dict]) -> list[dict]:
    """Fold per-device child outputs into one row per workload."""
    by_workload: dict[str, dict] = {}
    for pt_ in points:
        for row in pt_["rows"]:
            w = by_workload.setdefault(
                row["workload"], {"metric": f"multichip {row['workload']} scaling", "unit": "ms"}
            )
            w[f"mesh_ms@{pt_['devices']}dev"] = row["mesh_ms"]
            w.setdefault("rows", row.get("rows"))
    base_dev = min(p["devices"] for p in points)
    top_dev = max(p["devices"] for p in points)
    for w in by_workload.values():
        base = w.get(f"mesh_ms@{base_dev}dev")
        top = w.get(f"mesh_ms@{top_dev}dev")
        if base and top:
            w["scaling"] = round(base / top, 2)
            w["scaling_devices"] = f"{top_dev} vs {base_dev}"
    return list(by_workload.values())


def run_headline(iters: int = 2) -> list[dict]:
    """bench.py entry: the 8-vs-1-device merge-read scaling headline plus
    the mesh counter breakdown (spawns two children; every pass asserts
    mesh == single bit-identically before timing counts)."""
    points = [_spawn(d, workloads="read", iters=iters) for d in (1, 8)]
    rows = _scaling_rows(points)
    top = points[-1]
    rows.append(
        {
            "metric": "mesh execution breakdown (8 devices)",
            **top["mesh_counters"],
            "unit": "counters",
        }
    )
    return rows


def main():
    points = [_spawn(d) for d in DEVICE_COUNTS]
    rows = _scaling_rows(points)
    payload = {"rtt_ms": STORE_RTT_MS, "points": points, "rows": rows}
    for row in rows:
        row["cores"] = os.cpu_count()
        print(json.dumps(row))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump(payload, f, indent=1)
    read_row = next(r for r in rows if "merge-read" in r["metric"])
    assert read_row["scaling"] >= 3.0, (
        f"merge-read scaling {read_row['scaling']} < 3x at 8 devices"
    )


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        child_main(int(sys.argv[2]), sys.argv[3], int(sys.argv[4]))
    else:
        main()
