#!/usr/bin/env python
"""Benchmark: elastic cluster — live rescale, scale-out, replicated serving.

Three phases against in-process coordinator + worker agents (the TCP layer
is the same length-prefixed-JSON shim OS-process workers use; in-process
keeps the rig deterministic and the timings dominated by the injected
serve latency, not subprocess spawn noise):

1. rescale-under-load: 2 workers ingesting continuously over an 8-bucket
   table while serving probe threads measure routed-get latency; the
   coordinator drives a live 8 -> 16 mesh-repartition rescale mid-stream.
   Asserted: ZERO lost/duplicated rows (every journal-landed key present
   exactly once in the final scan) and serving p99 during the rescale
   window <= 2x the steady-state p99 — pinned readers keep serving the
   pre-rescale snapshot, so the window costs GIL overlap, not correctness.

2. scale-out 2 -> 4: two joiners register mid-stream (the join-steal range
   handoff), all four ingest to the end. Asserted: disjoint full bucket
   cover and ZERO lost/duplicated rows across the handoffs.

3. replicated serving for a hot shard: every get carries `delay-ms` of
   injected server latency and the client serializes calls per worker
   connection — the single-owner throughput ceiling is 1/delay. Once the
   heat EMA grants replicas (threshold crossed by the hammer itself), the
   round-robin owner ring multiplies that ceiling. Asserted: replicated
   get_batch throughput >= 2x the single-owner baseline, and every timed
   pass replica rows == primary rows == oracle (bit-identical serving is
   the precondition for counting the speedup at all).

Results land in benchmarks/results/elastic_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

# standalone runs get the forced-host virtual device mesh the cluster tests
# use; under bench.py jax is already configured and this is a no-op
if "jax" not in sys.modules:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

SERVE_DELAY_MS = float(os.environ.get("PAIMON_TPU_ELASTIC_BENCH_DELAY_MS", "10"))
REPLICA_DELAY_MS = float(os.environ.get("PAIMON_TPU_ELASTIC_BENCH_REP_DELAY_MS", "40"))
STEADY_S = float(os.environ.get("PAIMON_TPU_ELASTIC_BENCH_STEADY_S", "3"))
HAMMER_S = float(os.environ.get("PAIMON_TPU_ELASTIC_BENCH_HAMMER_S", "3"))
ROUND_ROWS = int(os.environ.get("PAIMON_TPU_ELASTIC_BENCH_ROWS", "64"))
RESULTS = os.path.join(HERE, "results", "elastic_bench.json")


def _mk_table(root: str, buckets: int, **extra) -> None:
    from paimon_tpu.core.schema import SchemaManager
    from paimon_tpu.fs import get_file_io
    from paimon_tpu.service.soak import SCHEMA

    opts = {
        "bucket": str(buckets),
        "write-only": "true",
        "merge.engine": "mesh",
        "write-buffer-rows": "128",
    }
    opts.update(extra)
    SchemaManager(get_file_io(root), root).create_table(SCHEMA, primary_keys=["k"], options=opts)


def _cluster(root: str, workers: int, buckets: int, serve_delay_ms: float, tmp: str):
    from paimon_tpu.service.cluster import ClusterClient, ClusterConfig, ClusterCoordinator, ClusterWorkerAgent
    from paimon_tpu.table import load_table

    coord = ClusterCoordinator(
        root, ClusterConfig(workers=workers, buckets=buckets, compaction=False)
    ).start()
    agents = []
    for wid in range(workers):
        a = ClusterWorkerAgent(
            wid, load_table(root, commit_user=f"cluster-w{wid}"),
            coord.host, coord.port,
            journal_path=os.path.join(tmp, f"journal-{os.path.basename(root)}-{wid}.jsonl"),
            round_rows=ROUND_ROWS, heartbeat_interval_s=0.1,
            serve=True, serve_delay_ms=serve_delay_ms,
        )
        a.register()
        a.start_heartbeats()
        agents.append(a)
    cli = ClusterClient(load_table(root, commit_user="bench-cli"), coord.host, coord.port)
    return coord, agents, cli


def _teardown(coord, agents, cli) -> None:
    cli.close()
    for a in agents:
        a.close()
    coord.close()


def _assert_no_lost_no_dup(root: str, agents) -> int:
    """Every journal-landed key appears EXACTLY once in the final scan (pk
    table: a duplicate would surface as an extra row, a loss as a missing
    key). Returns the row count."""
    from paimon_tpu.table import load_table

    rb = load_table(root, commit_user="verify").new_read_builder()
    out = rb.new_read().read_all(rb.new_scan().plan())
    got = out.column("k").values.tolist()
    landed = {k for a in agents for ks in a.landed_by_bucket.values() for k in ks}
    assert len(got) == len(set(got)), "duplicated primary keys in final scan"
    missing = landed - set(got)
    assert not missing, f"{len(missing)} landed rows lost (e.g. {sorted(missing)[:5]})"
    return len(got)


def _ingest_ok(a, deadline_s: float = 5.0) -> None:
    """Land one round, riding out the brief fencing window after a handoff
    or rescale (the poll-work resync reply carries the fresh assignment)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        a.poll_and_compact()
        if a.ingest_round():
            return
        time.sleep(0.05)
    raise RuntimeError(f"worker {a.wid} could not land a round")


def _probe_loop(cli, keys, stop, out_ms, lock):
    i = 0
    while not stop.is_set():
        k = keys[i % len(keys)]
        t0 = time.perf_counter()
        cli.get_batch([k])
        ms = (time.perf_counter() - t0) * 1000
        with lock:
            out_ms.append(ms)
        i += 1


def phase_rescale(base: str) -> dict:
    """8 -> 16 live rescale under continuous ingest + serving probes."""
    root = os.path.join(base, "rescale")
    _mk_table(root, 8)
    coord, agents, cli = _cluster(root, 2, 8, SERVE_DELAY_MS, base)
    try:
        for a in agents:
            assert a.ingest_round()
        keys = [k for a in agents for ks in a.landed_by_bucket.values() for k in ks]
        ingest_stop = threading.Event()

        def ingest_loop():
            while not ingest_stop.is_set():
                for a in agents:
                    a.poll_and_compact()
                    a.ingest_round()
                time.sleep(0.02)

        ingester = threading.Thread(target=ingest_loop, daemon=True)
        ingester.start()
        lat_lock = threading.Lock()
        steady_ms: list = []
        stop = threading.Event()
        probes = [
            threading.Thread(
                target=_probe_loop, args=(cli, keys[i::2], stop, steady_ms, lat_lock), daemon=True
            )
            for i in range(2)
        ]
        for p in probes:
            p.start()
        time.sleep(STEADY_S)
        with lat_lock:
            baseline = list(steady_ms)
            steady_ms.clear()
        # the live rescale: the ingest loop's poll_and_compact executes the
        # rewrite tasks; probes keep serving off the pinned snapshot
        r = coord.start_rescale(16)
        assert r.get("started"), f"rescale refused: {r}"
        t0 = time.monotonic()
        while coord.handle("rescale_status", {})["active"]:
            if time.monotonic() - t0 > 120:
                raise RuntimeError("rescale did not complete")
            time.sleep(0.05)
        rescale_s = time.monotonic() - t0
        time.sleep(0.3)  # settle: routes republished, probes on the new layout
        with lat_lock:
            window = list(steady_ms)
        stop.set()
        for p in probes:
            p.join(timeout=10)
        ingest_stop.set()
        ingester.join(timeout=30)
        for a in agents:  # land a post-rescale round through the new routing
            _ingest_ok(a)
        assert coord.num_buckets == 16
        rows = _assert_no_lost_no_dup(root, agents)
        p99_steady = float(np.percentile(baseline, 99))
        p99_window = float(np.percentile(window, 99))
        assert p99_window <= 2.0 * p99_steady, (
            f"serving p99 {p99_window:.1f} ms during rescale > 2x steady {p99_steady:.1f} ms"
        )
        return {
            "metric": "live rescale 8->16 under load",
            "unit": "ms",
            "serve_delay_ms": SERVE_DELAY_MS,
            "rescale_wall_s": round(rescale_s, 2),
            "p99_steady_ms": round(p99_steady, 2),
            "p99_rescale_ms": round(p99_window, 2),
            "p99_ratio": round(p99_window / p99_steady, 2),
            "rows_final": rows,
            "lost_rows": 0,
            "duplicated_rows": 0,
        }
    finally:
        _teardown(coord, agents, cli)


def phase_scaleout(base: str) -> dict:
    """2 -> 4 workers mid-stream: join-steal handoffs, zero lost/dup."""
    from paimon_tpu.metrics import cluster_metrics
    from paimon_tpu.service.cluster import ClusterWorkerAgent
    from paimon_tpu.table import load_table

    root = os.path.join(base, "scaleout")
    _mk_table(root, 8)
    coord, agents, cli = _cluster(root, 2, 8, 0.0, base)
    try:
        handoffs0 = cluster_metrics().counter("handoffs").count
        t0 = time.monotonic()
        for _ in range(3):
            for a in agents:
                assert a.ingest_round()
        for wid in (2, 3):  # the joiners: register -> steal from the loaded pair
            a = ClusterWorkerAgent(
                wid, load_table(root, commit_user=f"cluster-w{wid}"),
                coord.host, coord.port,
                journal_path=os.path.join(base, f"journal-scaleout-{wid}.jsonl"),
                round_rows=ROUND_ROWS, heartbeat_interval_s=0.1, serve=True,
            )
            a.register()
            a.start_heartbeats()
            agents.append(a)
        owned = [b for w in range(4) for b in coord.assignment_of(w)[1]]
        assert sorted(owned) == list(range(8)), f"broken bucket cover after scale-out: {owned}"
        for _ in range(3):
            for a in agents:
                _ingest_ok(a)
        wall = time.monotonic() - t0
        rows = _assert_no_lost_no_dup(root, agents)
        return {
            "metric": "scale-out 2->4 under load",
            "unit": "rows/s",
            "rows_final": rows,
            "rows_per_sec": round(rows / wall, 1),
            "handoffs": cluster_metrics().counter("handoffs").count - handoffs0,
            "lost_rows": 0,
            "duplicated_rows": 0,
        }
    finally:
        _teardown(coord, agents, cli)


def _hammer_throughput(cli, keys, seconds: float, threads: int = 6) -> float:
    stop = threading.Event()
    counts = [0] * threads
    errs: list = []

    def loop(ti):
        i = 0
        while not stop.is_set():
            try:
                cli.get_batch([keys[i % len(keys)]])
            except Exception as e:  # noqa: BLE001
                errs.append(e)
                return
            counts[ti] += 1
            i += 1

    ts = [threading.Thread(target=loop, args=(ti,), daemon=True) for ti in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in ts:
        t.join(timeout=10)
    if errs:
        raise errs[0]
    return sum(counts) / (time.perf_counter() - t0)


def phase_replica(base: str) -> dict:
    """Hot-shard serving throughput: single owner vs replicated ring. The
    injected per-get delay plus per-connection call serialization makes one
    owner a 1/delay ceiling; replicas multiply the ring."""
    hot = 0
    # baseline: replicas disabled
    root1 = os.path.join(base, "rep_single")
    _mk_table(root1, 4)
    coord1, agents1, cli1 = _cluster(root1, 3, 4, REPLICA_DELAY_MS, base)
    try:
        for a in agents1:
            assert a.ingest_round()
        keys = [k for a in agents1 for k in a.landed_by_bucket.get(hot, [])]
        assert keys
        single = _hammer_throughput(cli1, keys, HAMMER_S)
    finally:
        _teardown(coord1, agents1, cli1)

    # replicated: grant up to 2 replicas once the hammer's own heat crosses
    root2 = os.path.join(base, "rep_ring")
    _mk_table(
        root2, 4,
        **{
            "cluster.replica.heat-threshold": "1",
            "cluster.replica.interval": "100 ms",
            "cluster.replica.max-per-bucket": "2",
        },
    )
    coord2, agents2, cli2 = _cluster(root2, 3, 4, REPLICA_DELAY_MS, base)
    try:
        for a in agents2:
            assert a.ingest_round()
        keys = [k for a in agents2 for k in a.landed_by_bucket.get(hot, [])]
        assert keys
        from paimon_tpu.table import load_table
        from paimon_tpu.table.query import LocalTableQuery

        oracle = LocalTableQuery(load_table(root2, commit_user="oracle"))
        want = []
        for k in keys:
            d = oracle.lookup((), (k,))
            want.append(None if d is None else list(d.to_pylist()[0]))
        deadline = time.monotonic() + 60
        while len(cli2.replicas_of(hot)) < 2 and time.monotonic() < deadline:
            cli2.get_batch(keys)  # the hammer IS the heat source
            cli2.refresh_route()
        reps = cli2.replicas_of(hot)
        assert len(reps) >= 2, f"replicas never granted: {reps}"
        primary = cli2.owner_of(hot)
        # bit-identical serving across the whole ring, every timed pass
        wire_keys = [[k] for k in keys]
        for wid in (primary, *reps):
            rows = cli2._call(wid, "get_batch", keys=wire_keys, partition=[])["rows"]
            assert rows == want, f"owner {wid} diverged from the oracle"
        replicated = _hammer_throughput(cli2, keys, HAMMER_S)
        for wid in (primary, *reps):
            rows = cli2._call(wid, "get_batch", keys=wire_keys, partition=[])["rows"]
            assert rows == want, f"owner {wid} diverged after the timed pass"
    finally:
        _teardown(coord2, agents2, cli2)
    speedup = replicated / single
    assert speedup >= 2.0, f"replicated serving {speedup:.2f}x < 2x single-owner"
    return {
        "metric": "hot-bucket replicated serving throughput",
        "unit": "gets/s",
        "serve_delay_ms": REPLICA_DELAY_MS,
        "gets_per_sec_single": round(single, 1),
        "gets_per_sec_replicated": round(replicated, 1),
        "speedup": round(speedup, 2),
        "ring_size": 3,
        "replica_rows_bit_identical": True,
    }


def run_headline(iters: int = 1) -> list:
    """bench.py seam: one pass of every phase, returning the result rows."""
    rows = []
    base = tempfile.mkdtemp(prefix="paimon_elastic_bench_")
    try:
        rows.append(phase_rescale(base))
        rows.append(phase_scaleout(base))
        rows.append(phase_replica(base))
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return rows


def main() -> None:
    rows = run_headline()
    for row in rows:
        print(json.dumps(row))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump(
            {
                "serve_delay_ms": SERVE_DELAY_MS,
                "replica_delay_ms": REPLICA_DELAY_MS,
                "cores": os.cpu_count(),
                "rows": rows,
            },
            f,
            indent=1,
        )


if __name__ == "__main__":
    main()
