#!/usr/bin/env python
"""Device-resident merge-kernel throughput: the link-independent MFU proxy.

The end-to-end bench (bench.py) is bound by the host<->device link on this
rig (~28 MB/s tunnel). This harness removes the link from the measurement:
key/seq lanes are staged into device memory (HBM) first, then ONLY the
sort + segment + select kernel is timed (block_until_ready, best-of-N).
That number is the ceiling the transfer-slim work is chasing and the honest
answer to "how fast is the TPU merge itself vs the reference's heap loop"
(SortMergeReaderWithMinHeap.java:122-179, 975.4 Krows/s end-to-end parquet
scan baseline; the in-memory merge portion of the reference loop is what
this kernel replaces).

Grid: rows x lane-arity x engine(backend). Prints one JSON line per cell:
{"metric": "kernel.<engine>.k<K>s<S>", "value": rows/s, ...}.

Usage: python benchmarks/kernel_resident.py [--rows 1048576,4194304]
       [--engines dedup,dedup_pallas,partial_update]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paimon_tpu.utils import enable_compile_cache
from paimon_tpu.utils.tpuguard import ensure_live_backend

enable_compile_cache()
# guard the device claim behind __main__: pallas_verdict imports this module
# for time_kernel, and a second single-flight acquire from the SAME process
# (different fd, same lock file) would deadlock against our own lock
PLATFORM = ensure_live_backend() if __name__ == "__main__" else "(imported)"

BASE = 975_400.0


def emit(metric, value, **extra):
    print(
        json.dumps(
            {"metric": metric, "value": round(value, 1), "unit": "rows/s",
             "vs_baseline": round(value / BASE, 3), "platform": PLATFORM, **extra}
        ),
        flush=True,
    )


def make_lanes(n: int, k: int, s: int, dup_factor: int = 4, seed: int = 7):
    """Lanes shaped like a real merge: n rows over n/dup_factor distinct keys
    (4 overlapping runs), uint32, already in the kernel's (K, m) layout."""
    import jax

    from paimon_tpu.ops import merge as M

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n // dup_factor, size=n, dtype=np.uint32)
    key_lanes = np.empty((n, k), dtype=np.uint32)
    key_lanes[:, 0] = keys
    for i in range(1, k):
        key_lanes[:, i] = keys * (i + 1) + 13  # correlated secondary lanes
    seq = np.arange(n, dtype=np.uint32)
    seq_lanes = np.empty((n, s), dtype=np.uint32)
    for i in range(s):
        seq_lanes[:, i] = seq
    klp, slp, pad, _, kk, ss, m = M.prepare_lanes(key_lanes, seq_lanes if s else None)
    dev = jax.devices()[0]
    return (
        jax.block_until_ready(jax.device_put(klp, dev)),
        jax.block_until_ready(jax.device_put(slp, dev)),
        jax.block_until_ready(jax.device_put(pad, dev)),
        kk,
        ss,
        m,
    )


def _chained(inner, chain_iters: int):
    """K data-dependent kernel invocations inside ONE jit: each iteration's
    keys are perturbed by the previous iteration's (data-dependent) count, so
    the device MUST run them sequentially and cannot reuse a cached result.
    One dispatch + one sync amortizes the tunnel RTT over K real executions —
    naive per-call block_until_ready timing on this remote platform returned
    ~50 us/call, far below the link RTT, i.e. it measured dispatch, not
    execution."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(key_lanes, seq_lanes, pad_flag, *extra):
        def body(_, carry):
            salt, acc = carry
            # cheap data dependency; keeps dtype + distribution (lanes may be
            # a list of mixed-dtype arrays after range narrowing)
            kl = [x ^ salt.astype(x.dtype) for x in key_lanes]
            out = inner(kl, seq_lanes, pad_flag, *extra)
            count = out[-1]  # every kernel returns (..., count)
            c = count.astype(jnp.uint32)
            return c % jnp.uint32(2), acc + c

        salt, acc = jax.lax.fori_loop(0, chain_iters, body, (jnp.uint32(0), jnp.uint32(0)))
        return acc

    return f


def _timed_value(fn, args, reps: int) -> float:
    """Best seconds-to-scalar-VALUE over reps. On the axon tunnel
    block_until_ready returns ~0.1 ms for an 11 ms matmul (it does not
    block); only fetching a literal value synchronizes, so we time to
    float(result)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(fn(*args))  # value fetch = real sync on remote platforms
        best = min(best, time.perf_counter() - t0)
    return best


def time_kernel(inner, args, n_rows: int, k_lo: int = 4, k_hi: int = 32, reps: int = 3) -> float:
    """rows/s from the SLOPE between a short and a long kernel chain:
    t(K) ~= overhead + K * t_kernel, so t_kernel = (t(k_hi) - t(k_lo)) /
    (k_hi - k_lo). The intercept absorbs the tunnel RTT + dispatch, which
    dwarf a single kernel on this rig."""
    f_lo, f_hi = _chained(inner, k_lo), _chained(inner, k_hi)
    float(f_lo(*args)), float(f_hi(*args))  # compile + warm both
    t_lo = _timed_value(f_lo, args, reps)
    t_hi = _timed_value(f_hi, args, reps)
    t_kernel = max((t_hi - t_lo) / (k_hi - k_lo), 1e-9)
    return n_rows / t_kernel


def bench_dedup(n: int, k: int, s: int, backend: str):
    from paimon_tpu.ops import merge as M

    klp, slp, pad, kk, ss, m = make_lanes(n, k, s)
    fn = M._dedup_select_fn(kk, ss, backend)
    rps = time_kernel(fn, (klp, slp, pad), n)
    tag = "dedup" if backend == "xla" else f"dedup_{backend}"
    emit(f"kernel.{tag}.k{kk}s{ss}", rps, rows=n, padded=m)


def bench_partial_update(n: int, k: int, s: int, fields: int = 4):
    import jax

    from paimon_tpu.ops import merge as M

    klp, slp, pad, kk, ss, m = make_lanes(n, k, s)
    rng = np.random.default_rng(11)
    dev = jax.devices()[0]
    fv = jax.block_until_ready(
        jax.device_put(rng.random((fields, m)) < 0.7, dev)
    )
    is_add = jax.block_until_ready(jax.device_put(np.ones(m, dtype=np.bool_), dev))
    is_del = jax.block_until_ready(jax.device_put(np.zeros(m, dtype=np.bool_), dev))
    fn = M._fused_partial_update_fn(kk, ss, fields)
    rps = time_kernel(fn, (klp, slp, pad, fv, is_add, is_del), n)
    emit(f"kernel.partial_update.k{kk}s{ss}f{fields}", rps, rows=n, padded=m)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", default="1048576,4194304")
    ap.add_argument("--engines", default="dedup,dedup_pallas,partial_update")
    ap.add_argument("--arities", default="1:0,2:1")
    args = ap.parse_args()
    rows = [int(x) for x in args.rows.split(",")]
    engines = args.engines.split(",")
    arities = [tuple(int(v) for v in a.split(":")) for a in args.arities.split(",")]
    for n in rows:
        for k, s in arities:
            if "dedup" in engines:
                bench_dedup(n, k, s, "xla")
            if "dedup_pallas" in engines and not PLATFORM.startswith("cpu"):
                try:
                    bench_dedup(n, k, s, "pallas")
                except Exception as e:  # noqa: BLE001
                    emit(f"kernel.dedup_pallas.k{k}s{s}.FAILED", 0.0, rows=n, err=repr(e)[:200])
            if "partial_update" in engines:
                bench_partial_update(n, k, s)


if __name__ == "__main__":
    main()
