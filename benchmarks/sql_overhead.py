#!/usr/bin/env python
"""SQL layer overhead on the headline table: SELECT through sql.query vs the
direct Table API, plus a pushdown query and a GROUP BY. Emits one JSON line
per row. The SQL layer should cost noise (<5%) on a full scan — it routes to
the same read path — and the grouped aggregate should run at scan-like rates.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench as B  # repo-root headline-table builder; its import resolves the
                   # backend ONCE (ensure_live_backend_retrying) — resolving it
                   # here too would self-conflict on the single-flight lock

PLATFORM = B._PLATFORM
N = B.N_ROWS


def best_of(fn, iters=4):
    best = float("inf")
    out = None
    for i in range(iters + 1):
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        if i:
            best = min(best, dt)
    return best, out


def main():
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.sql import query

    tmp = tempfile.mkdtemp(prefix="sql_ovh_")
    try:
        B.build_table(tmp)
        cat = FileSystemCatalog(tmp, commit_user="bench")

        def direct():
            t = cat.get_table("bench.t")
            rb = t.new_read_builder()
            return rb.new_read().read_all(rb.new_scan().plan())

        def via_sql():
            return query(cat, "SELECT * FROM bench.t")

        def pushdown():
            return query(cat, "SELECT id, c1 FROM bench.t WHERE id < 100000")

        def grouped():
            return query(cat, "SELECT s2, count(*), sum(c1) FROM bench.t GROUP BY s2")

        t_direct, out = best_of(direct)
        assert out.num_rows == N
        t_sql, out = best_of(via_sql)
        assert out.num_rows == N
        t_push, out = best_of(pushdown)
        t_group, gout = best_of(grouped)
        assert gout.num_rows == 10  # s2 has 10 distinct values

        rows = [
            ("sql.select-star", N / t_sql, {"overhead_vs_direct": round(t_sql / t_direct - 1, 4)}),
            ("sql.direct-api", N / t_direct, {}),
            ("sql.pushdown-projection", N / t_push, {"selected": out.num_rows}),
            ("sql.group-by-agg", N / t_group, {"groups": gout.num_rows}),
        ]
        for metric, rps, extra in rows:
            print(json.dumps({"metric": metric, "value": round(rps, 1), "unit": "rows/s",
                              "platform": PLATFORM, **extra}), flush=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
