#!/usr/bin/env python
"""Micro-benchmarks mirroring the reference suite (SURVEY.md §6 /
paimon-micro-benchmarks): table write throughput per format, full scans,
projected scans, merge-read with sorted runs. Prints one JSON line per
config. bench.py (repo root) remains the driver's single headline metric.

Usage: python benchmarks/micro_benchmarks.py [--rows N] [--fast]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paimon_tpu.utils import enable_compile_cache
from paimon_tpu.utils.tpuguard import ensure_live_backend

enable_compile_cache()

# wedge-proof device access (tpuguard): explicit-CPU honored, detached probe
# (never killed), single-flight lock, clean-exit signals, LOUD CPU fallback
# (PAIMON_TPU_REQUIRE=1 turns the fallback into exit 3)
PLATFORM = ensure_live_backend()

BASELINES = {
    # reference numbers from BASELINE.md (rows/s)
    "write.parquet": 64_800.0,
    "write.orc": 94_300.0,
    "write.avro": 74_400.0,
    "scan.parquet": 975_400.0,
    "scan.orc": 2_867_300.0,
    "scan.avro": 721_800.0,
    "scan.projected.orc": 4_187_400.0,  # the reference's projected number is ORC
    "merge-read.parquet": 975_400.0,
}


def make_table(tmp, fmt, rows, runs=1, write_only=False, merge_engine=None, extra_options=None, overlap=False):
    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(tmp, commit_user="bench")
    schema = pt.RowType.of(
        ("id", pt.BIGINT(False)),
        *[(f"c{i}", pt.BIGINT()) for i in range(6)],
        *[(f"d{i}", pt.DOUBLE()) for i in range(4)],
        *[(f"s{i}", pt.STRING()) for i in range(4)],
    )
    opts = {"bucket": "1", "file.format": fmt}
    if write_only:
        opts["write-only"] = "true"
    if merge_engine:
        opts["merge-engine"] = merge_engine
    opts.update(extra_options or {})
    name = f"bench.t_{fmt}_{runs}_{merge_engine or 'dedup'}"
    t = cat.create_table(name, schema, primary_keys=["id"], options=opts)
    rng = np.random.default_rng(7)
    per = rows // runs
    if overlap:
        # every run re-draws from the SAME key space: the merge truly
        # combines versions across all runs
        key_space = np.arange(per, dtype=np.int64)
    else:
        ids = rng.permutation(rows).astype(np.int64)
    elapsed = 0.0
    for r in range(runs):
        chunk = key_space if overlap else np.sort(ids[r * per : (r + 1) * per])
        data = {"id": chunk}
        for i in range(6):
            data[f"c{i}"] = chunk * (i + 1)
        for i in range(4):
            data[f"d{i}"] = chunk.astype(np.float64) + i
        for i in range(4):
            data[f"s{i}"] = np.array([f"v{i}-{int(x) % 997:04d}" for x in chunk], dtype=object)
        t0 = time.perf_counter()
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write(data)
        wb.new_commit().commit(w.prepare_commit())
        elapsed += time.perf_counter() - t0
    return t, rows / elapsed


def bench_scan(t, rows, projection=None, iters=3, expect_rows=None):
    rb = t.new_read_builder()
    if projection:
        rb = rb.with_projection(projection)
    best = float("inf")
    for i in range(iters + 1):
        t0 = time.perf_counter()
        out = rb.new_read().read_all(rb.new_scan().plan())
        dt = time.perf_counter() - t0
        assert out.num_rows == (expect_rows if expect_rows is not None else rows)
        if i > 0:
            best = min(best, dt)
    return rows / best


def emit(metric, value, unit="rows/s"):
    base = BASELINES.get(metric)
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4 if value < 10 else 1),
                "unit": unit,
                "vs_baseline": round(value / base, 3) if base else None,
                "platform": PLATFORM,
            }
        ),
        flush=True,
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--fast", action="store_true", help="100k rows, parquet only")
    args = ap.parse_args()
    rows = 100_000 if args.fast else args.rows
    formats = ["parquet"] if args.fast else ["parquet", "orc", "avro"]
    for fmt in formats:
        tmp = tempfile.mkdtemp(prefix=f"ptb_{fmt}_")
        try:
            if fmt == "avro" and rows > 200_000:
                t, wtp = make_table(tmp, fmt, 200_000)  # row codec: keep runtime sane
                emit(f"write.{fmt}", wtp)
                emit(f"scan.{fmt}", bench_scan(t, 200_000, iters=1))
            else:
                t, wtp = make_table(tmp, fmt, rows)
                emit(f"write.{fmt}", wtp)
                emit(f"scan.{fmt}", bench_scan(t, rows))
                if fmt in ("parquet", "orc"):
                    emit(f"scan.projected.{fmt}", bench_scan(t, rows, projection=["id", "c0", "d0", "s0"]))
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    # merge-read with 4 overlapping runs (the headline config, see bench.py),
    # then BASELINE.json headline #2 on the same table: full-compaction
    # throughput (GB/s of input rewritten through the merge kernel)
    tmp = tempfile.mkdtemp(prefix="ptb_mr_")
    try:
        t, _ = make_table(tmp, "parquet", rows, runs=4, write_only=True)
        emit("merge-read.parquet", bench_scan(t, rows))
        input_bytes = sum(f.file_size for f in t.store.restore_files((), 0))
        t2 = t.copy({"write-only": "false"})
        wb = t2.new_batch_write_builder()
        w = wb.new_write()
        t0 = time.perf_counter()
        w.compact(full=True)
        wb.new_commit().commit(w.prepare_commit())
        dt = time.perf_counter() - t0
        emit("full-compaction.gbps", input_bytes / dt / (1 << 30), unit="GB/s")
        emit("full-compaction.rows", rows / dt)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    # BASELINE.json configs 2-3: partial-update and aggregation merge engines
    # over overlapping runs (no published reference numbers -> vs_baseline null)
    for engine, extra in (
        ("partial-update", {}),
        ("aggregation", {"fields.c0.aggregate-function": "sum", "fields.d0.aggregate-function": "max"}),
    ):
        tmp = tempfile.mkdtemp(prefix="ptb_eng_")
        try:
            # 4 fully-overlapping runs: every key has 4 versions to combine
            t, _ = make_table(
                tmp, "parquet", rows, runs=4, write_only=True,
                merge_engine=engine, extra_options=extra, overlap=True,
            )
            emit(f"merge-read.{engine}", bench_scan(t, rows, expect_rows=rows // 4))
        finally:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
