#!/usr/bin/env python
"""Benchmark: device-side skew-aware joins (ISSUE 12, ops/join).

A star-schema fact x dimension equi-join — 1M fact rows against a 100k-row
dimension on a STRING customer key — at three probe-key skew levels:

  uniform — cust drawn uniformly over the dimension's 100k keys
  zipf    — a heavy-tailed (Pareto) draw: popular customers dominate
  hot50   — ONE customer holds 50% of the fact rows (the JSPIM adversary)

Both sides are REAL tables read through the native decoder with
merge.dict-domain on, so the join keys arrive as code-backed columns and
the kernel matches on unified dictionary codes with zero string
materialization (join{code_domain_joins} in the breakdown).

Per skew level the bench measures the device join (ops/join.join_batches,
auto engine + auto partitioning with the skew split) against the host
row-at-a-time baseline — the python dict probe loop every lookup-join ran
before this subsystem (one .get per fact row). EVERY timed pass first
asserts the device pairs bit-identical to the host loop's pairs.

Acceptance (ISSUE 12): device >= 5x the host loop on the 1M x 100k join,
and hot50 wall <= 2x uniform wall (the skew split working). Results land
in benchmarks/results/join_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_FACT = 1_000_000
N_DIM = 100_000
ITERS = 3
RESULTS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "results", "join_bench.json"
)


def _skew_keys(rng, n, dom):
    return {
        "uniform": rng.integers(0, dom, n),
        "zipf": np.minimum((rng.pareto(1.1, n) * dom / 20).astype(np.int64), dom - 1),
        "hot50": np.where(rng.random(n) < 0.5, 4242, rng.integers(0, dom, n)),
    }


def build_tables(tmp):
    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(tmp, commit_user="join-bench")
    dim = cat.create_table(
        "bench.dim",
        pt.RowType.of(
            ("cid", pt.STRING(False)), ("name", pt.STRING()), ("rate", pt.DOUBLE())
        ),
        primary_keys=["cid"],
        options={"bucket": "1", "write-only": "true", "format.parquet.encoder": "native"},
    )
    rng = np.random.default_rng(12)
    wb = dim.new_batch_write_builder()
    w = wb.new_write()
    w.write({
        "cid": np.array([f"C{i:06d}" for i in range(N_DIM)], dtype=object),
        "name": np.array([f"customer-{i}" for i in range(N_DIM)], dtype=object),
        "rate": rng.random(N_DIM),
    })
    wb.new_commit().commit(w.prepare_commit())

    fields = [("id", pt.BIGINT(False))]
    fields += [(f"cust_{s}", pt.STRING(False)) for s in ("uniform", "zipf", "hot50")]
    fields += [("amount", pt.DOUBLE()), ("qty", pt.BIGINT())]
    fact = cat.create_table(
        "bench.fact",
        pt.RowType.of(*fields),
        primary_keys=["id"],
        options={"bucket": "1", "write-only": "true", "format.parquet.encoder": "native"},
    )
    keys = _skew_keys(rng, N_FACT, N_DIM)
    per = N_FACT // 4
    for r in range(4):
        sl = slice(r * per, (r + 1) * per)
        wb = fact.new_batch_write_builder()
        w = wb.new_write()
        data = {
            "id": np.arange(sl.start, sl.stop, dtype=np.int64),
            "amount": rng.random(per).round(4),
            "qty": rng.integers(1, 9, per),
        }
        for s, k in keys.items():
            data[f"cust_{s}"] = np.array(
                [f"C{int(x):06d}" for x in k[sl]], dtype=object
            )
        w.write(data)
        wb.new_commit().commit(w.prepare_commit())
    return fact, dim


def _read(table):
    t = table.copy({
        "merge.dict-domain": "true",
        "format.parquet.decoder": "native",
        "cache.data-file.max-memory-size": "0 b",
    })
    rb = t.new_read_builder()
    return rb.new_read().read_all(rb.new_scan().plan())


def host_row_at_a_time(cust_values, dim_cids):
    """The pre-ISSUE-12 lookup path: a python dict keyed by the join key,
    probed one fact row at a time."""
    pos: dict = {}
    for j, c in enumerate(dim_cids):
        pos.setdefault(c, []).append(j)
    wall = float("inf")
    for _ in range(2):  # best of two: same fairness as the device side
        lt, rt = [], []
        t0 = time.perf_counter()
        for i, c in enumerate(cust_values):
            for j in pos.get(c, ()):
                lt.append(i)
                rt.append(j)
        wall = min(wall, time.perf_counter() - t0)
    return np.asarray(lt, dtype=np.int64), np.asarray(rt, dtype=np.int64), wall


def run(fact_batch, dim_batch, skews=("uniform", "zipf", "hot50")):
    from paimon_tpu.metrics import join_metrics, registry
    from paimon_tpu.ops.join import join_batches

    registry.reset()
    dim_cids = dim_batch.column("cid").to_pylist()
    rows = []
    walls = {}
    # the 4-partition hot50 pass exercises the JSPIM skew split (one key =
    # 50% of probes, dealt round-robin across every partition) — output
    # still asserted identical to the host loop
    passes = [(s, None) for s in skews] + [("hot50", {"join.partitions": "4"})]
    for skew, opts in passes:
        key = f"cust_{skew}"
        cust = fact_batch.column(key).to_pylist()
        olt, ort, host_wall = host_row_at_a_time(cust, dim_cids)
        best = float("inf")
        for _ in range(ITERS):
            t0 = time.perf_counter()
            res = join_batches(
                fact_batch, dim_batch, [key], ["cid"], how="inner", options=opts
            )
            best = min(best, time.perf_counter() - t0)
        np.testing.assert_array_equal(res.left_take, olt)
        np.testing.assert_array_equal(res.right_take, ort)
        if opts is None:
            walls[skew] = best
        rows.append({
            "metric": f"fact x dim join ({skew}{'' if opts is None else ' partitioned x4'})",
            "fact_rows": fact_batch.num_rows,
            "dim_rows": dim_batch.num_rows,
            "matches": int(res.num_rows),
            "device_wall_s": round(best, 4),
            "host_wall_s": round(host_wall, 4),
            "device_rows_per_sec": round(fact_batch.num_rows / best, 1),
            "speedup_vs_host": round(host_wall / best, 2),
            "algorithm": res.stats["algorithm"],
            "engine": res.stats["engine"],
            "partitions": res.stats["partitions"],
            "skew_keys_split": res.stats["skew_keys"],
        })
    g = join_metrics()
    breakdown = {
        "metric": "join breakdown",
        **{
            k: g.counter(k).count
            for k in (
                "joins", "rows_probed", "rows_matched", "hash_joins",
                "sort_merge_joins", "code_domain_joins", "skew_keys",
                "skew_split_rows",
            )
        },
    }
    return rows, breakdown, walls


def run_headline(iters=2, n_fact=300_000, n_dim=30_000):
    """Scaled spot-check for bench.py: in-memory code-backed fact x dim
    (the shape the dict-domain reader delivers), device vs host loop,
    output asserted identical."""
    import paimon_tpu as pt
    from paimon_tpu.data.batch import Column, ColumnBatch
    from paimon_tpu.metrics import join_metrics, registry
    from paimon_tpu.ops.join import join_batches

    rng = np.random.default_rng(5)
    pool = np.array([f"C{i:06d}" for i in range(n_dim)], dtype=object)
    fact_codes = rng.integers(0, n_dim, n_fact).astype(np.uint32)
    dim_codes = np.arange(n_dim, dtype=np.uint32)
    fact = ColumnBatch(
        pt.RowType.of(("cust", pt.STRING(False)), ("amount", pt.DOUBLE())),
        {"cust": Column.from_codes(pool, fact_codes), "amount": Column(rng.random(n_fact))},
    )
    dim = ColumnBatch(
        pt.RowType.of(("cid", pt.STRING(False)), ("rate", pt.DOUBLE())),
        {"cid": Column.from_codes(pool, dim_codes), "rate": Column(rng.random(n_dim))},
    )
    registry.reset()
    cust = [pool[c] for c in fact_codes]
    olt, ort, host_wall = host_row_at_a_time(cust, pool.tolist())
    best = float("inf")
    for _ in range(max(iters, 1) + 1):
        t0 = time.perf_counter()
        res = join_batches(fact, dim, ["cust"], ["cid"], how="inner")
        best = min(best, time.perf_counter() - t0)
    np.testing.assert_array_equal(res.left_take, olt)
    np.testing.assert_array_equal(res.right_take, ort)
    g = join_metrics()
    assert g.counter("code_domain_joins").count > 0
    return [
        {
            "metric": f"device join vs host row-at-a-time ({n_fact // 1000}k x {n_dim // 1000}k, code-domain key)",
            "device_rows_per_sec": round(n_fact / best, 1),
            "host_rows_per_sec": round(n_fact / host_wall, 1),
            "speedup": round(host_wall / best, 2),
            "unit": "rows/s",
        },
        {
            "metric": "join breakdown",
            **{
                k: g.counter(k).count
                for k in (
                    "joins", "rows_probed", "rows_matched", "hash_joins",
                    "sort_merge_joins", "code_domain_joins", "skew_keys",
                )
            },
            "unit": "counters",
        },
    ]


def main():
    tmp = tempfile.mkdtemp(prefix="paimon_join_bench_")
    try:
        t0 = time.perf_counter()
        fact, dim = build_tables(tmp)
        print(f"# tables built in {time.perf_counter() - t0:.1f}s", file=sys.stderr)
        fact_batch, dim_batch = _read(fact), _read(dim)
        rows, breakdown, walls = run(fact_batch, dim_batch)
        uniform = next(r for r in rows if "uniform" in r["metric"])
        degradation = walls["hot50"] / walls["uniform"]
        summary = {
            "metric": "join headline",
            "speedup_vs_host_uniform": uniform["speedup_vs_host"],
            "skew_degradation_hot50_vs_uniform": round(degradation, 3),
            "targets": {"speedup_vs_host": ">= 5", "skew_degradation": "<= 2"},
        }
        for row in rows + [breakdown, summary]:
            print(json.dumps(row))
        os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
        with open(RESULTS, "w") as f:
            json.dump({"rows": rows, "breakdown": breakdown, "summary": summary}, f, indent=2)
        assert breakdown["code_domain_joins"] > 0, "code-domain join never fired"
        assert breakdown["skew_keys"] >= 1, "the partitioned pass never split the hot key"
        assert uniform["speedup_vs_host"] >= 5, uniform
        assert degradation <= 2.0, degradation
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
