#!/usr/bin/env python
"""Benchmark: pipelined vs sequential multi-bucket cold scan.

An 8-bucket primary-key table, 4 overlapping sorted runs per bucket, read
cold (object caches off) two ways through the same Table API:

  sequential   scan.prefetch-splits = 0 — splits fetch, decode and merge
               strictly one after another (the pre-pipeline behavior)
  pipelined    scan.prefetch-splits = 2 (default) — split i+1 fetches bytes
               through RetryingFileIO and decodes on pipeline workers while
               split i merges on device (parallel/pipeline.py)

Two storage profiles per run:

  local        data on the local filesystem. On a multi-core host the decode
               of split i+1 overlaps split i's merge; on a single-core host
               (this rig: os.cpu_count() == 1) CPU-bound stages serialize and
               the pipeline can only tie — the row is the no-regression guard.
  store rtt    the same table behind fs/testing.LatencyFileIO, which charges
               a fixed first-byte latency per object read — the shape of a
               real object-store cold scan. This is what the pipeline is FOR:
               overlapped prefetches pay the RTT concurrently, a serial scan
               pays it once per file. Headline: >= 1.5x on 8 buckets.

Also checked every pass: output of both modes is bit-identical, and the
pipeline's queue-depth high-water stays <= prefetch+1 (the memory high-water
regression guard — readahead must not silently materialize the whole scan).

Prints one JSON line per row; the table also lands in
benchmarks/results/pipeline_bench.json.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_BUCKETS = 8
N_RUNS = 4
ROWS_PER_RUN = 64_000  # x4 runs = 256k rows/bucket-set; decode-heavy but quick
STORE_RTT_MS = 8.0  # first-byte latency per object read (object-store shape)
PREFETCH = 2
RESULTS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "results", "pipeline_bench.json"
)


def build_table(root: str, buckets: int = N_BUCKETS, rows_per_run: int = ROWS_PER_RUN):
    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(root, commit_user="bench")
    schema = pt.RowType.of(
        ("id", pt.BIGINT(False)),
        ("c1", pt.BIGINT()),
        ("d1", pt.DOUBLE()),
        ("s1", pt.STRING()),
    )
    table = cat.create_table(
        "bench.pipe",
        schema,
        primary_keys=["id"],
        options={
            "bucket": str(buckets),
            "file.format": "parquet",
            "write-only": "true",  # keep the runs overlapping: real k-way merge
            # caches off so every timed scan is genuinely cold
            "cache.manifest.max-memory-size": "0 b",
            "cache.data-file.max-memory-size": "0 b",
        },
    )
    rng = np.random.default_rng(17)
    total = rows_per_run * N_RUNS
    ids = rng.permutation(total).astype(np.int64)
    for r in range(N_RUNS):
        chunk = np.sort(ids[r * rows_per_run : (r + 1) * rows_per_run])
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write(
            {
                "id": chunk,
                "c1": chunk * 3,
                "d1": chunk.astype(np.float64) * 0.5,
                "s1": np.array([f"val-{int(x) % 997:04d}" for x in chunk], dtype=object),
            }
        )
        wb.new_commit().commit(w.prepare_commit())
    return table


def cold_scan(table, expect_rows: int) -> tuple[float, object]:
    from paimon_tpu.utils import cache as cache_mod

    cache_mod.clear_all()
    t0 = time.perf_counter()
    rb = table.new_read_builder()
    out = rb.new_read().read_all(rb.new_scan().plan())
    dt = time.perf_counter() - t0
    assert out.num_rows == expect_rows, out.num_rows
    return dt, out


def assert_bit_identical(a, b) -> None:
    for name in a.schema.field_names:
        assert np.array_equal(a.column(name).values, b.column(name).values), name
        assert np.array_equal(a.column(name).validity, b.column(name).validity), name


def run_profile(table, label: str, expect_rows: int, iters: int = 3) -> dict:
    from paimon_tpu.metrics import pipeline_metrics, registry

    seq = table.copy({"scan.prefetch-splits": "0"})
    pipe = table.copy({"scan.prefetch-splits": str(PREFETCH)})
    # warm jit caches once outside the timed region
    cold_scan(seq, expect_rows)
    best_seq, best_pipe = float("inf"), float("inf")
    out_seq = out_pipe = None
    registry.reset()
    for _ in range(iters):
        dt, out_seq = cold_scan(seq, expect_rows)
        best_seq = min(best_seq, dt)
        dt, out_pipe = cold_scan(pipe, expect_rows)
        best_pipe = min(best_pipe, dt)
    assert_bit_identical(out_seq, out_pipe)
    g = pipeline_metrics()
    high_water = g.gauge("queue_depth_high_water").value
    # memory high-water regression guard: bounded readahead means at most
    # prefetch+1 splits' decoded batches in flight, never the whole scan
    assert high_water <= PREFETCH + 1, high_water
    return {
        "metric": f"pipelined 8-bucket cold scan ({label})",
        "sequential_ms": round(best_seq * 1000, 1),
        "pipelined_ms": round(best_pipe * 1000, 1),
        "speedup": round(best_seq / best_pipe, 2),
        "splits_prefetched": g.counter("splits_prefetched").count,
        "queue_depth_high_water": int(high_water),
        "unit": "x",
    }


def run(rows_per_run: int = ROWS_PER_RUN, rtt_ms: float = STORE_RTT_MS, iters: int = 3):
    from paimon_tpu.fs.testing import LatencyFileIO
    from paimon_tpu.table import load_table

    rows = []
    tmp = tempfile.mkdtemp(prefix="paimon_tpu_pipe_")
    try:
        table = build_table(tmp, rows_per_run=rows_per_run)
        expect = rows_per_run * N_RUNS
        rows.append(run_profile(table, "local fs", expect, iters=iters))
        # same physical table behind the latency-injecting store
        LatencyFileIO.configure(read_ms=rtt_ms)
        try:
            slow = load_table(f"latency://{table.path}", commit_user="bench")
            rows.append(
                dict(
                    run_profile(slow, f"store rtt {rtt_ms:g} ms", expect, iters=iters),
                    rtt_ms=rtt_ms,
                )
            )
        finally:
            LatencyFileIO.configure()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return rows


def main():
    rows = run()
    for row in rows:
        row["cores"] = os.cpu_count()
        print(json.dumps(row))
    os.makedirs(os.path.dirname(RESULTS), exist_ok=True)
    with open(RESULTS, "w") as f:
        json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
