#!/usr/bin/env python
"""BASELINE.json configs 2-5, runnable at scaled sizes.

  #2  partial-update merge-read, 4 sorted runs, predicate pushdown on 2 int
      columns (full scale 10M rows)
  #3  aggregation (sum/max) over 8 buckets data-parallel, ORC
      (full scale 50M rows)
  #4  streaming CDC upsert -> universal compaction (full scale 100M)
  #5  batch full-compaction of a many-bucket table + z-order clustering
      (full scale 1B / 64 buckets)

Default sizes fit CI; --scale N multiplies row counts (1.0 ~ a few million
total). Each config prints one JSON line; vs_baseline uses the reference's
975.4 Krows/s single-thread parquet scan where a denominator makes sense.
Run with JAX_PLATFORMS=cpu for the virtual mesh or on the real chip.

Usage: python benchmarks/baseline_configs.py [--scale N] [--configs 2,3,4,5]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paimon_tpu.utils import enable_compile_cache
from paimon_tpu.utils.tpuguard import ensure_live_backend

enable_compile_cache()

# wedge-proof device access (tpuguard): explicit-CPU honored, detached probe
# (never killed), single-flight lock, clean-exit signals, LOUD CPU fallback
# (PAIMON_TPU_REQUIRE=1 turns the fallback into exit 3)
PLATFORM = ensure_live_backend()

BASE = 975_400.0


def emit(metric, value, unit="rows/s", vs=None, **extra):
    print(
        json.dumps(
            {"metric": metric, "value": round(value, 1), "unit": unit,
             "vs_baseline": round(value / BASE, 3) if vs is None else vs,
             "platform": PLATFORM, **extra}
        ),
        flush=True,
    )


def _mk(tmp, name, schema, pk, options):
    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(tmp, commit_user="bench")
    return cat.create_table(name, schema, primary_keys=pk, options=options)


def config2(scale: float):
    """10M-row partial-update, 4 overlapping runs, 2-int-col predicate."""
    import paimon_tpu as pt
    from paimon_tpu.data.predicate import and_, greater_or_equal, less_than

    rows = int(2_000_000 * scale)
    tmp = tempfile.mkdtemp(prefix="bc2_")
    try:
        schema = pt.RowType.of(
            ("id", pt.BIGINT(False)), ("a", pt.BIGINT()), ("b", pt.BIGINT()),
            ("d0", pt.DOUBLE()), ("d1", pt.DOUBLE()), ("s0", pt.STRING()),
        )
        t = _mk(tmp, "db.c2", schema, ["id"], {"bucket": "1", "merge-engine": "partial-update", "write-only": "true"})
        per = rows // 4
        ids = np.arange(per, dtype=np.int64)
        for r in range(4):
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write({
                "id": ids,
                "a": ids % 1000 if r % 2 == 0 else [None] * per,
                "b": [None] * per if r % 2 == 0 else ids % 777,
                "d0": ids * 0.5 + r,
                "d1": [None] * per if r < 2 else ids * 1.5,
                "s0": np.array([f"v{int(x) % 97}" for x in ids], dtype=object),
            })
            wb.new_commit().commit(w.prepare_commit())
        pred = and_(greater_or_equal("a", 100), less_than("b", 500))
        rb = t.new_read_builder().with_filter(pred)
        best = float("inf")
        for it in range(3):
            t0 = time.perf_counter()
            out = rb.new_read().read_all(rb.new_scan().plan())
            dt = time.perf_counter() - t0
            if it:
                best = min(best, dt)
        emit("config2.partial-update.predicates", rows / best, rows=rows, matched=out.num_rows)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def config3(scale: float):
    """Aggregation (sum/max) over 8 buckets, ORC, mesh-parallel read."""
    import paimon_tpu as pt

    rows = int(4_000_000 * scale)
    tmp = tempfile.mkdtemp(prefix="bc3_")
    try:
        schema = pt.RowType.of(
            ("id", pt.BIGINT(False)), ("sum_col", pt.BIGINT()), ("max_col", pt.DOUBLE())
        )
        import jax

        mesh_ok = len(jax.devices()) >= 8
        t = _mk(tmp, "db.c3", schema, ["id"], {
            "bucket": "8", "file.format": "orc", "merge-engine": "aggregation",
            "fields.sum_col.aggregate-function": "sum",
            "fields.max_col.aggregate-function": "max",
            "write-only": "true",
            **({"parallel.mesh.enabled": "true"} if mesh_ok else {}),
        })
        per = rows // 4
        rng = np.random.default_rng(1)
        for r in range(4):
            ids = rng.integers(0, rows // 8, per)
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write({"id": ids, "sum_col": ids % 7, "max_col": ids * 0.25})
            wb.new_commit().commit(w.prepare_commit())
        rb = t.new_read_builder()
        best = float("inf")
        for it in range(3):
            t0 = time.perf_counter()
            out = rb.new_read().read_all(rb.new_scan().plan())
            dt = time.perf_counter() - t0
            if it:
                best = min(best, dt)
        emit("config3.aggregation.orc.8buckets", rows / best, rows=rows, keys=out.num_rows, mesh=mesh_ok)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def config4(scale: float):
    """Streaming CDC upsert with periodic universal compaction."""
    import paimon_tpu as pt

    rows = int(1_000_000 * scale)
    tmp = tempfile.mkdtemp(prefix="bc4_")
    try:
        schema = pt.RowType.of(("id", pt.BIGINT(False)), ("v", pt.DOUBLE()), ("tag", pt.STRING()))
        t = _mk(tmp, "db.c4", schema, ["id"], {"bucket": "1", "num-sorted-run.compaction-trigger": "4"})
        wb = t.new_stream_write_builder()
        w = wb.new_write()
        c = wb.new_commit()
        rng = np.random.default_rng(2)
        batches = 20
        per = rows // batches
        t0 = time.perf_counter()
        for b in range(batches):
            ids = rng.integers(0, rows // 2, per)
            w.write({"id": ids, "v": ids * 0.5 + b, "tag": np.array([f"t{b}"] * per, dtype=object)})
            c.commit_messages(b + 1, w.prepare_commit())
        dt = time.perf_counter() - t0
        # denominator: the reference's parquet WRITE baseline (64.8 Krows/s,
        # TableWriterBenchmark) — this is a write workload
        emit("config4.streaming-upsert.compacting", rows / dt, rows=rows, commits=batches,
             vs=round(rows / dt / 64_800.0, 3))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def config5(scale: float):
    """Full compaction of a many-bucket table, then z-order clustering."""
    import paimon_tpu as pt
    from paimon_tpu.table.compactor import DedicatedCompactor
    from paimon_tpu.table.sort_compact import sort_compact

    rows = int(2_000_000 * scale)
    buckets = 16
    tmp = tempfile.mkdtemp(prefix="bc5_")
    try:
        import jax

        mesh_ok = len(jax.devices()) >= 8
        schema = pt.RowType.of(("id", pt.BIGINT(False)), ("x", pt.BIGINT()), ("y", pt.BIGINT()), ("v", pt.DOUBLE()))
        t = _mk(tmp, "db.c5", schema, ["id"], {
            "bucket": str(buckets), "write-only": "true",
            **({"parallel.mesh.enabled": "true"} if mesh_ok else {}),
        })
        rng = np.random.default_rng(3)
        per = rows // 4
        for r in range(4):
            ids = rng.integers(0, rows, per)
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write({"id": ids, "x": ids % 4096, "y": (ids * 7) % 4096, "v": ids * 1.0})
            wb.new_commit().commit(w.prepare_commit())
        input_bytes = sum(e.file.file_size for e in t.store.new_scan().plan().entries)
        t0 = time.perf_counter()
        assert DedicatedCompactor(t).run_once(full=True)
        dt = time.perf_counter() - t0
        emit("config5.full-compaction.16buckets", rows / dt, rows=rows,
             gb_per_s=round(input_bytes / dt / (1 << 30), 3), mesh=mesh_ok, vs=None)
        # z-order clustering on an append clone of the data
        ta = _mk(tmp, "db.c5z", schema, [], {"bucket": "1"})
        wb = ta.new_batch_write_builder()
        w = wb.new_write()
        ids = rng.integers(0, rows, min(rows, 500_000))
        w.write({"id": ids, "x": ids % 4096, "y": (ids * 7) % 4096, "v": ids * 1.0})
        wb.new_commit().commit(w.prepare_commit())
        t0 = time.perf_counter()
        n = sort_compact(ta, ["x", "y"], order="zorder")
        dt = time.perf_counter() - t0
        emit("config5.zorder-cluster", n / dt, rows=n, vs=None)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.1)
    ap.add_argument("--configs", default="2,3,4,5")
    args = ap.parse_args()
    fns = {"2": config2, "3": config3, "4": config4, "5": config5}
    for c in args.configs.split(","):
        fns[c.strip()](args.scale)


if __name__ == "__main__":
    main()
