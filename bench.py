#!/usr/bin/env python
"""Benchmark: merge-on-read throughput (BASELINE.json config #1).

Mirrors the reference micro-benchmark (paimon-micro-benchmarks
TableReadBenchmark: 1M-row primary-key table, single bucket, full scan
through the Table API — write, then scan -> plan -> merge-read). The table is
written as 4 overlapping sorted runs (write-only mode, no compaction), so the
read path genuinely k-way-merges 1M keyed rows: columnar decode -> key-lane
encode -> device sort+segment kernel -> gather.

Baseline denominator: Parquet full scan 975.4 Krows/s on Apple M1 Pro JDK8
(reference TableReadBenchmark.java:62-68; see /root/repo/BASELINE.md).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paimon_tpu.utils import enable_compile_cache

enable_compile_cache()


# Wedge-proof device access: detached probe (never killed), single-flight
# lock around the grant, clean-exit signal handlers, loud CPU fallback.
# The retrying variant polls the probe cache for PAIMON_TPU_BENCH_RETRY_S
# (default 900s) before accepting the fallback, so the round-end artifact
# says "tpu" whenever the grant frees in time. PAIMON_TPU_REQUIRE=1 refuses
# the fallback (exit 3).
from paimon_tpu.utils.tpuguard import ensure_live_backend_retrying

_PLATFORM = ensure_live_backend_retrying()

# freshest successful chip measurement: written on every TPU run, embedded
# in the fallback row (with its timestamp) when the tunnel is down at
# snapshot time — the artifact then still carries the chip evidence
LATEST_CHIP = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "benchmarks", "results", "LATEST_CHIP.json")

BASELINE_ROWS_PER_SEC = 975_400.0
N_ROWS = 1_000_000
N_RUNS = 4


def build_table(path: str):
    import paimon_tpu as pt
    from paimon_tpu.catalog import FileSystemCatalog

    cat = FileSystemCatalog(path, commit_user="bench")
    schema = pt.RowType.of(
        ("id", pt.BIGINT(False)),
        ("c1", pt.BIGINT()),
        ("c2", pt.BIGINT()),
        ("c3", pt.BIGINT()),
        ("d1", pt.DOUBLE()),
        ("d2", pt.DOUBLE()),
        ("s1", pt.STRING()),
        ("s2", pt.STRING()),
    )
    table = cat.create_table(
        "bench.t",
        schema,
        primary_keys=["id"],
        options={"bucket": "1", "file.format": "parquet", "write-only": "true"},
    )
    rng = np.random.default_rng(7)
    ids = rng.permutation(N_ROWS).astype(np.int64)
    per = N_ROWS // N_RUNS
    for r in range(N_RUNS):
        chunk = np.sort(ids[r * per : (r + 1) * per])
        n = len(chunk)
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write(
            {
                "id": chunk,
                "c1": chunk * 3,
                "c2": chunk % 97,
                "c3": chunk // 7,
                "d1": chunk.astype(np.float64) * 0.5,
                "d2": chunk.astype(np.float64) + 0.25,
                "s1": np.array([f"val-{int(x) % 1000:04d}" for x in chunk], dtype=object),
                "s2": np.array([f"tag-{int(x) % 10}" for x in chunk], dtype=object),
            }
        )
        wb.new_commit().commit(w.prepare_commit())
    return table


def bench_read(table) -> float:
    rb = table.new_read_builder()
    best = float("inf")
    # first iteration warms jit caches; best-of-6 damps the tunnel's
    # bandwidth variance
    for it in range(7):
        t0 = time.perf_counter()
        splits = rb.new_scan().plan()
        out = rb.new_read().read_all(splits)
        dt = time.perf_counter() - t0
        assert out.num_rows == N_ROWS, out.num_rows
        if it > 0:
            best = min(best, dt)
    return N_ROWS / best


def bench_decode(table) -> dict:
    """One native-decoder pass over the standard merge-read table: the
    per-stage decode breakdown (pages decoded/skipped, bytes expanded, wall
    millis) from the decode{} metric group (benchmarks/decode_bench.py is
    the dedicated per-encoding comparison)."""
    from paimon_tpu.metrics import decode_metrics

    native = table.copy(
        {"format.parquet.decoder": "native", "cache.data-file.max-memory-size": "0 b"}
    )
    rb = native.new_read_builder()
    g = decode_metrics()
    c0 = {k: g.counter(k).count for k in ("pages_decoded", "pages_skipped", "bytes_expanded", "files_fallback")}
    t0 = time.perf_counter()
    out = rb.new_read().read_all(rb.new_scan().plan())
    dt = time.perf_counter() - t0
    assert out.num_rows == N_ROWS, out.num_rows
    return {
        "metric": "native decode breakdown (full scan)",
        "pages_decoded": g.counter("pages_decoded").count - c0["pages_decoded"],
        "pages_skipped": g.counter("pages_skipped").count - c0["pages_skipped"],
        "bytes_expanded": g.counter("bytes_expanded").count - c0["bytes_expanded"],
        "files_fallback": g.counter("files_fallback").count - c0["files_fallback"],
        "wall_ms": round(dt * 1000, 1),
        "unit": "counters",
    }


def bench_scan_cache(table) -> float:
    """Cold-vs-warm repeated scan (plan + read_all) through the byte-budget
    caches (benchmarks/scan_cache.py is the dedicated micro-benchmark; this
    line tracks the same effect on the standard merge-read table)."""
    from paimon_tpu.utils import cache as cache_mod

    cached = table.copy(
        {"cache.manifest.max-memory-size": "256 mb", "cache.data-file.max-memory-size": "1 gb"}
    )
    rb = cached.new_read_builder()

    def once() -> float:
        t0 = time.perf_counter()
        out = rb.new_read().read_all(rb.new_scan().plan())
        assert out.num_rows == N_ROWS, out.num_rows
        return time.perf_counter() - t0

    cache_mod.clear_all()
    cold = once()
    once()  # populate + warm
    warm = min(once() for _ in range(3))
    return cold / warm if warm > 0 else float("inf")


def bench_pipeline() -> list:
    """Pipelined split scheduler spot-check (benchmarks/pipeline_bench.py is
    the dedicated benchmark): 8-bucket cold scan, pipelined vs
    scan.prefetch-splits=0, on local fs (no-regression guard) and behind a
    simulated object-store read RTT (the latency the pipeline exists to
    hide). Each row asserts bit-identical output and the bounded queue-depth
    high-water."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "pipeline_bench.py")
    spec = importlib.util.spec_from_file_location("_pipeline_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # the dedicated bench's representative size: below ~2 MB/scan the fixed
    # thread-spawn cost dominates on a single-core host and the row would
    # measure overhead, not overlap
    return mod.run(iters=2)


def bench_encode() -> list:
    """Write-path headline (benchmarks/encode_bench.py is the dedicated
    benchmark): ingest throughput for a 1M-row PK write+flush, arrow vs
    native encoder, plus the native encode counter breakdown — the write
    mirror of the decode rows. The guard inside run_headline asserts
    pyarrow reads every natively-written file bit-identically."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "encode_bench.py")
    spec = importlib.util.spec_from_file_location("_encode_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=2)


def bench_lanes(table) -> list:
    """Key-lane compression breakdown (benchmarks/lanes_bench.py is the
    dedicated 3-schema x 3-workload sweep): the standard merge-read table
    read twice through table.copy — merge.lane-compression off vs on (same
    files, same cache state) — plus the planner counter deltas from the
    lanes{} metric group. Outputs are asserted identical row-for-row."""
    from paimon_tpu.metrics import lanes_metrics

    g = lanes_metrics()

    def counters():
        return {k: g.counter(k).count for k in ("plans", "lanes_in", "lanes_out", "ovc_merges", "bytes_saved")}

    results = {}
    deltas = None
    for comp in (False, True):
        t = table.copy({"merge.lane-compression": "true" if comp else "false"})
        rb = t.new_read_builder()
        best = float("inf")
        c0 = counters()
        out = None
        for it in range(4):
            t0 = time.perf_counter()
            out = rb.new_read().read_all(rb.new_scan().plan())
            dt = time.perf_counter() - t0
            assert out.num_rows == N_ROWS, out.num_rows
            if it > 0:
                best = min(best, dt)
        if comp:
            c1 = counters()
            deltas = {k: c1[k] - c0[k] for k in c0}
        results[comp] = (N_ROWS / best, out)
    assert results[True][1].to_pylist() == results[False][1].to_pylist()
    on, off = results[True][0], results[False][0]
    plans = max(deltas["plans"], 1)
    return [
        {
            "metric": "merge-read compressed vs uncompressed key lanes (same table)",
            "rows_per_sec_uncompressed": round(off, 1),
            "rows_per_sec_compressed": round(on, 1),
            "speedup": round(on / off, 3),
            "unit": "rows/s",
        },
        {
            "metric": "key-lane compression breakdown",
            "plans": deltas["plans"],
            "lanes_in_per_plan": round(deltas["lanes_in"] / plans, 2),
            "lanes_out_per_plan": round(deltas["lanes_out"] / plans, 2),
            "ovc_merges": deltas["ovc_merges"],
            "bytes_saved": deltas["bytes_saved"],
            "unit": "counters",
        },
    ]


def bench_dicts(table) -> list:
    """Compressed-domain merge spot-check (benchmarks/dict_domain_bench.py
    is the dedicated 3-schema x 3-workload sweep with the >=2x compaction
    headline): the standard merge-read table read through table.copy with
    merge.dict-domain off vs on — same files, same cache state — plus the
    dict{} counter breakdown. Outputs are asserted identical row-for-row."""
    from paimon_tpu.metrics import dict_metrics

    g = dict_metrics()

    def counters():
        return {
            k: g.counter(k).count
            for k in ("pools_unified", "codes_remapped", "rows_code_domain", "fallback_expanded")
        }

    results = {}
    deltas = None
    for dd in (False, True):
        t = table.copy(
            {
                "merge.dict-domain": "true" if dd else "false",
                "format.parquet.decoder": "native",
                "format.parquet.encoder": "native",
                "cache.data-file.max-memory-size": "0 b",
            }
        )
        rb = t.new_read_builder()
        best = float("inf")
        c0 = counters()
        out = None
        for it in range(4):
            t0 = time.perf_counter()
            out = rb.new_read().read_all(rb.new_scan().plan())
            out.to_arrow()  # delivery included: the code domain hands arrow dictionaries
            dt = time.perf_counter() - t0
            assert out.num_rows == N_ROWS, out.num_rows
            if it > 0:
                best = min(best, dt)
        if dd:
            deltas = {k: v - c0[k] for k, v in counters().items()}
        results[dd] = (N_ROWS / best, out)
    assert results[True][1].to_pylist() == results[False][1].to_pylist()
    on, off = results[True][0], results[False][0]
    return [
        {
            "metric": "merge-read dict-domain on vs off (same table, native decode)",
            "rows_per_sec_expanded": round(off, 1),
            "rows_per_sec_code_domain": round(on, 1),
            "speedup": round(on / off, 3),
            "unit": "rows/s",
        },
        {
            "metric": "compressed-domain merge breakdown",
            "pools_unified": deltas["pools_unified"],
            "codes_remapped": deltas["codes_remapped"],
            "rows_code_domain": deltas["rows_code_domain"],
            "fallback_expanded": deltas["fallback_expanded"],
            "unify_ms_mean": round(dict_metrics().histogram("unify_ms").mean, 3),
            "unit": "counters",
        },
    ]


def bench_pallas(table) -> list:
    """Fused pallas merge kernel spot-check (benchmarks/pallas_bench.py is
    the dedicated per-schema comparison): the standard merge-read table read
    through table.copy with sort-engine pallas vs xla-segmented, key-range
    tiled at 2^17 rows so the tiles pad to a VMEM-resident size and the
    pallas side runs the FUSED sort+segment kernel (on a CPU rig the kernel
    executes under interpret=True — the row is the parity + no-collapse
    guard; fused speed is a chip question). Outputs asserted identical
    row-for-row, plus the pallas{} counter breakdown."""
    from paimon_tpu.metrics import pallas_metrics

    g = pallas_metrics()

    def counters():
        return {k: g.counter(k).count for k in ("kernels_launched", "tiles", "fallback_xla")}

    results = {}
    deltas = None
    for engine in ("xla-segmented", "pallas"):
        t = table.copy({"sort-engine": engine, "merge.read-batch-rows": str(1 << 17)})
        rb = t.new_read_builder()
        best = float("inf")
        c0 = counters()
        out = None
        for it in range(3):
            t0 = time.perf_counter()
            out = rb.new_read().read_all(rb.new_scan().plan())
            dt = time.perf_counter() - t0
            assert out.num_rows == N_ROWS, out.num_rows
            if it > 0:
                best = min(best, dt)
        if engine == "pallas":
            deltas = {k: v - c0[k] for k, v in counters().items()}
        results[engine] = (N_ROWS / best, out)
    assert results["pallas"][1].to_pylist() == results["xla-segmented"][1].to_pylist()
    pal, xla = results["pallas"][0], results["xla-segmented"][0]
    return [
        {
            "metric": "merge-read sort-engine pallas vs xla-segmented (same table, 128k tiles)",
            "rows_per_sec_xla_segmented": round(xla, 1),
            "rows_per_sec_pallas": round(pal, 1),
            "speedup": round(pal / xla, 3),
            "identical_output": True,
            "unit": "rows/s",
        },
        {
            "metric": "pallas kernel breakdown",
            "kernels_launched": deltas["kernels_launched"],
            "tiles": deltas["tiles"],
            "fallback_xla": deltas["fallback_xla"],
            "kernel_ms_mean": round(pallas_metrics().histogram("kernel_ms").mean, 3),
            "unit": "counters",
        },
    ]


def bench_join() -> list:
    """Device-join spot-check (benchmarks/join_bench.py is the dedicated
    1M x 100k fact x dimension sweep with the >=5x headline and the skew
    degradation bound): a scaled code-domain-key join, device kernel vs the
    host row-at-a-time dict loop, output asserted identical, plus the
    join{} counter breakdown (code_domain_joins must be > 0)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "join_bench", os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "join_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=2)


def bench_point_get() -> list:
    """Batched point-get spot-check (benchmarks/point_get_bench.py is the
    dedicated benchmark with the 30 s mixed soak row): 10k-key get_batch vs
    the scalar lookup() loop on a 1M-row PK table (every pass asserting
    identical results), the bloom key-index pruning contrast on a sparse
    absent-key set, and the get{} counter breakdown."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "point_get_bench.py")
    spec = importlib.util.spec_from_file_location("_point_get_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=2)


def bench_subscribe() -> list:
    """CDC subscription fan-out spot-check (benchmarks/subscribe_bench.py is
    the dedicated 1/8/32/128-subscriber sweep): 32 subscribers on one
    decode-once hub vs 32 independent StreamTableScan loops (shared decode
    cache off — the N-separate-processes model), every subscriber asserting
    it received every snapshot, plus the decode{pages_decoded} flatness
    counters and per-subscriber p99 delivery lag."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "subscribe_bench.py")
    spec = importlib.util.spec_from_file_location("_subscribe_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=1)


def bench_adaptive() -> dict:
    """Adaptive-vs-inline compaction spot-check (benchmarks/
    adaptive_compact_bench.py is the dedicated 60 s skewed soak with the
    >=1.2x headline): a short two-mode run — inline compaction in the
    writers vs the LUDA-style background scheduler with debt admission —
    reporting sustained ingest, the read-amp bound, and the zero-lost/dup
    invariants."""
    import importlib.util

    p = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "adaptive_compact_bench.py"
    )
    spec = importlib.util.spec_from_file_location("_adaptive_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    inline = mod.run_mode("inline", duration=8.0, seed=0)
    adaptive = mod.run_mode("adaptive", duration=8.0, seed=0)
    clean = all(
        r["lost_rows"] == 0 and r["duplicated_rows"] == 0 and r["wrong_values"] == 0
        for r in (inline, adaptive)
    )
    return {
        "metric": "adaptive vs inline compaction (8 s skewed soak spot-check)",
        "rows_per_sec_inline": inline["rows_per_sec"],
        "rows_per_sec_adaptive": adaptive["rows_per_sec"],
        "speedup": round(adaptive["rows_per_sec"] / max(inline["rows_per_sec"], 1e-9), 3),
        "read_amp_p99_inline": inline["read_amp_p99"],
        "read_amp_p99_adaptive": adaptive["read_amp_p99"],
        "read_amp_ceiling": adaptive.get("read_amp_ceiling"),
        "adaptive_runs": adaptive.get("adaptive_runs"),
        "zero_lost_dup": clean,
        "unit": "counters",
    }


def bench_mesh() -> list:
    """Mesh-sharded execution headline (benchmarks/multichip_bench.py is the
    dedicated 1/2/4/8-device sweep): 8-bucket merge-read behind simulated
    store RTT at 8 simulated devices vs 1, each device count in its own
    subprocess with a forced host device count — every pass asserts the mesh
    output bit-identical to the single-device engine before timing counts —
    plus the mesh{} counter breakdown. Subprocess children pin
    JAX_PLATFORMS=cpu, so this row is rig-independent (a wedged tunnel
    cannot hang it)."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "multichip_bench.py")
    spec = importlib.util.spec_from_file_location("_multichip_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=2)


def bench_sql_cluster() -> list:
    """Distributed SQL spot-check (benchmarks/sql_cluster_bench.py is the
    dedicated 1/2/4-worker sweep with the >=3x headline): scatter-gather
    aggregate queries against serve-mode worker OS processes behind a
    latency-shaped store, every timed pass asserting the distributed result
    bit-identical to the single-process evaluator and that partial
    aggregates really reduced on workers (sql{rows_reduced_device})."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "sql_cluster_bench.py")
    spec = importlib.util.spec_from_file_location("_sql_cluster_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=2)


def bench_sql_shuffle() -> list:
    """Single-process high-cardinality GROUP BY no-regression guard
    (benchmarks/sql_shuffle_bench.py is the dedicated 4-worker shuffle rig
    with the >=2x coordinator-combine-stage headline): times the LOCAL
    segment-reduce path at >=100k distinct groups — the pure path the
    shuffle plane must not disturb — asserted within ~1.1x the measured
    baseline."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "sql_shuffle_bench.py")
    spec = importlib.util.spec_from_file_location("_sql_shuffle_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_local_headline(iters=2)


def bench_scan_plan() -> list:
    """Scan-planning scale spot-check (benchmarks/scan_plan_bench.py is the
    dedicated rig): plan latency over a 10k-entry live manifest set built
    through the real commit path, full and partition-pruned, against a
    stated metadata-only budget."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "scan_plan_bench.py")
    spec = importlib.util.spec_from_file_location("_scan_plan_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=2)


def bench_gateway() -> list:
    """Gateway hedged-read spot-check (benchmarks/gateway_bench.py is the
    dedicated rig): one latency-shamed worker in a 2-worker cluster, the
    same probe sequence through an unhedged and a hedged Gateway, results
    asserted bit-identical to the formula oracle and the hedge budget
    (gateway.hedge.max-fraction) asserted respected."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "gateway_bench.py")
    spec = importlib.util.spec_from_file_location("_gateway_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=2)


def bench_elastic() -> list:
    """Elastic-cluster spot-check (benchmarks/elastic_bench.py is the
    dedicated rig): a live 8->16 bucket rescale under continuous ingest
    (zero lost/dup rows, serving p99 <= 2x steady-state), a 2->4 worker
    scale-out through the join-steal handoff, and hot-bucket replicated
    serving asserted >= 2x single-owner throughput with every pass
    bit-identical to the primary and the oracle."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "elastic_bench.py")
    spec = importlib.util.spec_from_file_location("_elastic_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.run_headline(iters=1)


def bench_resilience() -> dict:
    """Commit resilience spot-check (benchmarks/resilience_bench.py is the
    dedicated rate-sweep): 25 small commits at a 5% injected transient-fault
    rate through the retry stack. failed_commits must stay 0; the retry/
    giveup counters make resilience regressions visible in BENCH_* exactly
    like perf regressions."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "resilience_bench.py")
    spec = importlib.util.spec_from_file_location("_resilience_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    row = mod.run_config(0.05, 20, True)
    return {
        "metric": "commit resilience (5% injected transient faults)",
        "commits": row["commits"],
        "failed_commits": row["failed_commits"],
        "io_retries": row["io_retries"],
        "io_giveups": row["io_giveups"],
        "commits_per_sec": row["commits_per_sec"],
        "unit": "counters",
    }


def bench_soak() -> dict:
    """Traffic-soak spot-check (benchmarks/soak_bench.py is the dedicated
    >=60 s run): a short multi-writer/multi-reader soak at 5% injected
    faults with admission control on. consistent must stay true and
    failed/lost/leaked must stay 0 — the composed-system invariants live in
    BENCH_* next to the perf rows."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks", "soak_bench.py")
    spec = importlib.util.spec_from_file_location("_soak_bench", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    row = mod.run_mode("full", duration=8.0, possibility=20, seed=0)
    return {
        "metric": "traffic soak spot-check (8 s, 3 writers / 2 readers, 5% faults)",
        "consistent": row["consistent"],
        "commits_ok": row["commits_ok"],
        "failed_commits": row["commits_failed"],
        "commits_per_sec": row["commits_per_sec"],
        "read_p99_ms": row["read_p99_ms"],
        "writes_throttled": row["writes_throttled"],
        "lost_rows": row["lost_rows"],
        "leaked_files": row["leaked_file_count"],
        "unit": "counters",
    }


def bench_mega() -> dict:
    """Mega-soak spot-check (benchmarks/mega_soak_bench.py is the dedicated
    full-matrix >=10 min run): one scenario cell — dynamic buckets, every
    plane live (gateway writers, getters, subscribers, SQL, churn) — on the
    composed chaos store with the scripted kill schedule armed. The one
    verdict must stay consistent:true with 0 untyped sheds."""
    from paimon_tpu.service.mega_soak import DEFAULT_MATRIX, MegaConfig, run_mega_soak

    cell = tuple(s for s in DEFAULT_MATRIX if s.name == "dict-dynamic")
    # expiry knobs scaled to the short cell: the decoy-consumer check needs
    # consumer_expire_ms + an expiry pass to fit inside the duration
    cfg = MegaConfig(
        duration_s=12.0,
        seed=0,
        scenarios=cell,
        kill_period_s=6.0,
        expire_period_s=3.0,
        consumer_expire_ms=4_000,
    )
    tmp = tempfile.mkdtemp(prefix="paimon_tpu_bench_mega_")
    try:
        report = run_mega_soak(tmp, cfg)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    c = report["cells"][0]
    return {
        "metric": "mega-soak spot-check (12 s, dict-dynamic cell, chaos store + kill schedule)",
        "consistent": report["consistent"],
        "kills": report["kills_total"],
        "accepted_commits": c.get("accepted_commits"),
        "final_rows": c.get("final_rows"),
        "lost_rows": c.get("lost_rows"),
        "duplicated_rows": c.get("duplicated_rows"),
        "gw_sheds_untyped": c.get("gw_sheds_untyped"),
        "leaked_files": c.get("leaked_file_count"),
        "unit": "counters",
    }


def main():
    tmp = tempfile.mkdtemp(prefix="paimon_tpu_bench_")
    try:
        table = build_table(tmp)
        rows_per_sec = bench_read(table)
        scan_cache_speedup = bench_scan_cache(table)
        decode_row = bench_decode(table)
        lanes_rows = bench_lanes(table)
        dict_rows = bench_dicts(table)
        join_rows = bench_join()
        point_get_rows = bench_point_get()
        subscribe_rows = bench_subscribe()
        pallas_rows = bench_pallas(table)
        adaptive_row = bench_adaptive()
        pipeline_rows = bench_pipeline()
        encode_rows = bench_encode()
        mesh_rows = bench_mesh()
        sql_cluster_rows = bench_sql_cluster()
        sql_shuffle_rows = bench_sql_shuffle()
        scan_plan_rows = bench_scan_plan()
        gateway_rows = bench_gateway()
        elastic_rows = bench_elastic()
        resilience_row = bench_resilience()
        soak_row = bench_soak()
        mega_row = bench_mega()
        row = {
            "metric": "merge-read throughput (1M-row PK table, 4 sorted runs, parquet, 1 bucket)",
            "value": round(rows_per_sec, 1),
            "unit": "rows/s",
            "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
            "platform": _PLATFORM,
        }
        if _PLATFORM.startswith("cpu"):
            try:
                with open(LATEST_CHIP) as f:
                    row["last_chip"] = json.load(f)
            except (OSError, ValueError):
                pass  # absent or torn file must never eat the result row
        else:
            chip = dict(row, measured_at=time.strftime("%Y-%m-%dT%H:%M:%S"))
            os.makedirs(os.path.dirname(LATEST_CHIP), exist_ok=True)
            tmp_path = LATEST_CHIP + ".tmp"
            with open(tmp_path, "w") as f:
                json.dump(chip, f)
            os.replace(tmp_path, LATEST_CHIP)
        print(json.dumps(row))
        print(
            json.dumps(
                {
                    "metric": "repeated-scan speedup (warm cache)",
                    "value": round(scan_cache_speedup, 2),
                    "unit": "x",
                    "platform": _PLATFORM,
                }
            )
        )
        print(json.dumps(dict(decode_row, platform=_PLATFORM)))
        for lrow in lanes_rows:
            print(json.dumps(dict(lrow, platform=_PLATFORM)))
        for drow in dict_rows:
            print(json.dumps(dict(drow, platform=_PLATFORM)))
        for jrow in join_rows:
            print(json.dumps(dict(jrow, platform=_PLATFORM)))
        for grow in point_get_rows:
            print(json.dumps(dict(grow, platform=_PLATFORM)))
        for srow in subscribe_rows:
            print(json.dumps(dict(srow, platform=_PLATFORM)))
        for prow in pallas_rows:
            print(json.dumps(dict(prow, platform=_PLATFORM)))
        print(json.dumps(dict(adaptive_row, platform=_PLATFORM)))
        for prow in pipeline_rows:
            print(json.dumps(dict(prow, platform=_PLATFORM)))
        for erow in encode_rows:
            print(json.dumps(dict(erow, platform=_PLATFORM)))
        for mrow in mesh_rows:
            print(json.dumps(dict(mrow, platform=_PLATFORM)))
        for qrow in sql_cluster_rows:
            print(json.dumps(dict(qrow, platform=_PLATFORM)))
        for shrow in sql_shuffle_rows:
            print(json.dumps(dict(shrow, platform=_PLATFORM)))
        for sprow in scan_plan_rows:
            print(json.dumps(dict(sprow, platform=_PLATFORM)))
        for grow in gateway_rows:
            print(json.dumps(dict(grow, platform=_PLATFORM)))
        for elrow in elastic_rows:
            print(json.dumps(dict(elrow, platform=_PLATFORM)))
        print(json.dumps(dict(resilience_row, platform=_PLATFORM)))
        print(json.dumps(dict(soak_row, platform=_PLATFORM)))
        print(json.dumps(dict(mega_row, platform=_PLATFORM)))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
