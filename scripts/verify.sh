#!/usr/bin/env bash
# Verification gates.
#
#   scripts/verify.sh          tier-1 gate — the EXACT command from ROADMAP.md,
#                              so builders and reviewers run the same check.
#   scripts/verify.sh faults   resilience fault-matrix stage: runs the
#                              scheduled-fault + crash-point suite under a
#                              FIXED seed set, so resilience regressions are
#                              reproducible across machines.
#   scripts/verify.sh pipeline pipelined-scheduler determinism stage: the
#                              randomized-oracle parity tests with
#                              scan.parallelism forced to 1 and then to 8 —
#                              pipelined output must be bit-identical to the
#                              sequential path at both extremes. Runs with
#                              the native parquet encoder forced, so the
#                              pipelined flush/compaction encode stages are
#                              exercised through paimon_tpu.encode
#                              (conftest asserts encode{files_native} > 0).
#   scripts/verify.sh lanes    key-lane compression parity stage: the
#                              tests/test_lanes.py + merge-kernel suites run
#                              TWICE — PAIMON_TPU_LANE_COMPRESSION forced on,
#                              then forced off — so compressed and legacy
#                              paths both prove bit-identical merge output.
#   scripts/verify.sh mesh     mesh-execution parity stage: the mesh-executor
#                              suite + mesh table ops + the randomized oracle
#                              run TWICE on the forced 8-device virtual CPU
#                              mesh — PAIMON_TPU_MERGE_ENGINE forced mesh,
#                              then forced single — so the mesh-sharded and
#                              single-device execution engines both prove
#                              bit-identical merge output.
#   scripts/verify.sh dicts    compressed-domain merge parity stage: the
#                              tests/test_dict_domain.py suite (which
#                              compares merge.dict-domain on vs off
#                              directly per table) plus the randomized
#                              whole-store oracle run TWICE —
#                              PAIMON_TPU_DICT_DOMAIN forced 1, then 0 —
#                              so dictionary-code and expanded-string
#                              merges both prove bit-identical output.
#   scripts/verify.sh soak     traffic-soak stage: the writer flow-control /
#                              conflict-storm suite plus a bounded (~60 s
#                              total) DETERMINISTIC mini-soak — fixed seed,
#                              3 writers / 2 readers / 5% injected faults —
#                              asserting snapshot-consistent reads (oracle
#                              log), zero failed commits, zero lost or
#                              duplicated rows, zero leaked worker threads
#                              (conftest), and a post-soak orphan sweep
#                              leaving the file set exactly equal to the
#                              reachable closure. Nightly-scale knobs live
#                              in benchmarks/soak_bench.py.
#   scripts/verify.sh proc-soak  process-grain crash-soak stage: the crash-
#                              point / recovery / load-shedding suite, then
#                              a bounded DETERMINISTIC multi-process soak —
#                              fixed seed, 2 writer + 1 reader OS processes
#                              sharing only the warehouse filesystem, four
#                              scripted kill -9 deaths at distinct commit/
#                              flush crash points plus seeded random
#                              SIGKILLs, respawn + journal recovery,
#                              periodic orphan sweeps — asserting >= 3 kills
#                              survived, final scan == journal-oracle fold,
#                              zero lost/duplicated rows, zero read errors,
#                              and a post-sweep file set exactly equal to
#                              the reachable closure. Nightly-scale knobs
#                              live in benchmarks/soak_bench.py --process.
#   scripts/verify.sh join     device-join parity stage: the
#                              tests/test_join.py suite (kernel oracle
#                              parity across skew x null rates x engines x
#                              partitions, the pinned 50%-skew regression,
#                              code-domain joins, SQL JOIN vs pandas,
#                              vectorized lookups) run TWICE —
#                              PAIMON_TPU_LANE_COMPRESSION forced on, then
#                              off — so compressed and legacy key lanes
#                              both prove bit-identical join output; the
#                              second pass also forces the dict-domain
#                              reader on.
#   scripts/verify.sh get      batched point-get parity stage: the
#                              tests/test_point_get.py suite (randomized
#                              get_batch == scalar lookup() == fold parity
#                              across schemas x engines, bloom key-index
#                              pruning, read-your-writes tiers, typed-BUSY
#                              serving, the compaction-chain cancel
#                              regression) run TWICE — PAIMON_TPU_KEY_BLOOM
#                              forced 1, then 0 — so gets prove identical
#                              with and without bloom key indexes on every
#                              written file.
#   scripts/verify.sh subscribe  CDC subscription stage: the subscription
#                              suite (decode-once fan-out, consumer-fix
#                              regression, expiry-pinning e2e, cdc wire
#                              roundtrips over Flight, typed shed + resume)
#                              plus a ~45 s deterministic subscriber soak —
#                              2 writers at 5% faults, 4 subscribers incl.
#                              one deliberately slow (typed shed +
#                              consumer-id resume), 1 subscriber OS process
#                              kill -9'd and respawned — asserting every
#                              subscriber's folded changelog stream ==
#                              pinned-snapshot scan at its checkpoint, 0
#                              lost/duplicated rows, 0 untyped sheds, and
#                              the conftest thread/process-leak checks.
#   scripts/verify.sh cluster  cluster-service stage: the coordinator/worker
#                              suite (epoch fencing, reassigned-exactly-once,
#                              debt-charge release on death, routed gets +
#                              subscriptions, distributed join partitions,
#                              subscription-driven query refresh), then a
#                              ~45 s DETERMINISTIC cluster soak — 2 worker
#                              OS processes x 2 virtual devices each running
#                              merge.engine=mesh over their bucket ranges,
#                              the coordinator as the only committer, the
#                              cluster compaction service draining debt,
#                              scripted kill -9 deaths (one mid-ingest-flush,
#                              one MID-COMPACTION, one between prepare_commit
#                              and the ship RPC) plus seeded random SIGKILLs
#                              — asserting >= 2 kills survived, fold == final
#                              scan, 0 lost/dup rows, 0 leaked files, and
#                              sampled read-amp p99 <= the adaptive ceiling.
#   scripts/verify.sh elastic  elastic-cluster stage: the tests/test_elastic.py
#                              suite (live bucket rescale parity + pinned
#                              readers + data-file cache reuse, join-steal
#                              scale-out, planned retire handoff, hot-bucket
#                              read replicas incl. randomized replica/oracle
#                              consistency and replica-death failover, push
#                              route invalidation), then a ~60 s DETERMINISTIC
#                              elastic soak — 2 workers under continuous
#                              ingest with one scripted live rescale 4->8 at
#                              30% (one worker armed to die with its rewrite
#                              files durable but unshipped), one worker admit
#                              at 50% (join-steal handoff), one planned
#                              retire at 70% — asserting >= 1 kill survived,
#                              0 lost/dup rows, 0 leaked files.
#   scripts/verify.sh encode   native-encoder roundtrip parity stage: the
#                              full test_encode suite (incl. the slow
#                              corpus sweep) with the encoder forced
#                              native — every natively-written file must
#                              read back bit-identically through BOTH the
#                              native decoder and pyarrow.
#   scripts/verify.sh pallas   fused-merge-kernel parity stage: the
#                              tests/test_pallas_merge.py randomized suite
#                              plus the merge-kernel + whole-store oracles
#                              run TWICE — PAIMON_TPU_SORT_ENGINE forced
#                              pallas (interpret mode on CPU), then
#                              xla-segmented — so the fused pallas kernels
#                              and the stock XLA path both prove
#                              bit-identical merge output end to end.
#   scripts/verify.sh gateway  multi-tenant gateway stage: the gateway
#                              suite (per-tenant admission, typed-shed
#                              canonicalization, hedged reads + loser
#                              cancellation, SLO surface) INCLUDING the
#                              slow-marked ~45 s DETERMINISTIC mixed-kind
#                              storm — 64 closed-loop clients across 4
#                              tenants (one deliberately greedy) against
#                              a 2-worker cluster with one latency-shamed
#                              worker, fixed seed — asserting the greedy
#                              tenant sheds TYPED (retry_after set, 0
#                              untyped sheds), the quiet tenant's latency
#                              stays bounded relative to its solo
#                              baseline, hedges stay within the
#                              max-fraction budget, and every hedge
#                              attempt drains (no orphaned RPC, no
#                              leaked "paimon-gw" thread via conftest).
#   scripts/verify.sh mega     production mega-soak stage: the kill-schedule /
#                              scenario-matrix / chaos-composition suite
#                              (tests/test_mega_soak.py), then a bounded
#                              (~90 s) DETERMINISTIC two-cell mega soak —
#                              flagship (cluster + gateway + branch/tag) and
#                              dict-dynamic (dynamic buckets + consumer
#                              expiry) on one composed chaos store, every
#                              plane (writers, getters, subscribers, SQL,
#                              expiry/sweep churn) live at once, scripted
#                              kill -9 deaths at registered crash points plus
#                              seeded random SIGKILLs — asserting >= 3 kills
#                              across >= 2 process kinds survived, one
#                              consistent:true verdict (0 lost/dup rows, 0
#                              untyped sheds, 0 pinned-read errors, post-
#                              sweep disk set == reachable closure).
#                              Nightly-scale knobs live in
#                              benchmarks/mega_soak_bench.py.
#   scripts/verify.sh sql-cluster  distributed-SQL parity stage: the
#                              tests/test_sql_cluster.py suite (scatter-
#                              gather fragments at 1/2/4 workers vs the
#                              single-process evaluator vs pandas, worker
#                              kill mid-query incl. the slow SIGKILL OS-
#                              process test, typed-BUSY admission) run
#                              TWICE — PAIMON_TPU_SQL_CODE_DOMAIN forced 1
#                              (partials combined as dictionary codes),
#                              then 0 (expanded values on the wire) — so
#                              both combine currencies prove bit-identical
#                              distributed results.
#   scripts/verify.sh sql-shuffle  distributed shuffle-aggregation parity
#                              stage: the tests/test_sql_shuffle.py suite
#                              (value-hash partitioner twins, shuffle
#                              parity at 2/4 workers, range-owner death
#                              mid-query, duplicate-dispatch idempotence,
#                              frag-cache layout-epoch keying incl. a live
#                              8->16 rescale) plus tests/test_sql_cluster.py
#                              run TWICE — PAIMON_TPU_SQL_SHUFFLE forced 1
#                              (every GROUP BY combines via worker↔worker
#                              exchange), then 0 (single-point coordinator
#                              combine) — so both aggregation topologies
#                              prove bit-identical to the single-process
#                              evaluator.
#
# Exits non-zero on test failure/timeout; tier-1 prints DOTS_PASSED=<n>
# (count of passing tests) for trend comparison.
set -o pipefail
cd "$(dirname "$0")/.."

if [ "${1:-}" = "pipeline" ]; then
  # lane compression forced ON: retry/prefetch interactions run through the
  # compressed merge kernels (ISSUE 6)
  for par in 1 8; do
    env JAX_PLATFORMS=cpu PAIMON_TPU_SCAN_PARALLELISM=$par PAIMON_TPU_PARQUET_ENCODER=native \
      PAIMON_TPU_LANE_COMPRESSION=1 \
      timeout -k 10 600 python -m pytest tests/test_pipeline.py tests/test_encode.py -q \
      -k 'parity or fault or flush or pipelined' \
      -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  done
  exit 0
fi

if [ "${1:-}" = "faults" ]; then
  # mesh engine + code-domain merge + pallas sort engine forced ON: the
  # fault matrix (transient retries, crash points, torn writes) must stay
  # green through the mesh-sharded executor, its feeder workers, the
  # dictionary-code merge currency, and the fused pallas kernels on every
  # single-device merge (ISSUE 7 / ISSUE 10 / ISSUE 11)
  exec env JAX_PLATFORMS=cpu PAIMON_TPU_FAULT_SEEDS="0 1 2 3 4" PAIMON_TPU_PARQUET_ENCODER=native \
    PAIMON_TPU_LANE_COMPRESSION=1 PAIMON_TPU_MERGE_ENGINE=mesh PAIMON_TPU_DICT_DOMAIN=1 \
    PAIMON_TPU_SORT_ENGINE=pallas \
    timeout -k 10 600 python -m pytest tests/test_resilience.py tests/test_commit_faults.py \
    tests/test_encode.py::test_native_encoder_under_transient_faults -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
fi

if [ "${1:-}" = "dicts" ]; then
  # parity suite (compares the table option on vs off directly), then the
  # randomized whole-store oracle with the code domain forced on and off
  for dd in 1 0; do
    env JAX_PLATFORMS=cpu PAIMON_TPU_DICT_DOMAIN=$dd \
      timeout -k 10 600 python -m pytest tests/test_dict_domain.py tests/test_randomized_oracle.py -q \
      -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  done
  exit 0
fi

if [ "${1:-}" = "mesh" ]; then
  # parity suites with the merge execution engine forced mesh, then single:
  # both sides of the merge.engine switch must produce bit-identical output
  # (the conftest forces the 8-device virtual CPU mesh)
  # the code domain rides along forced ON (ISSUE 10): mesh-batched merges
  # must stay bit-identical when their lanes are dictionary codes
  for eng in mesh single; do
    env JAX_PLATFORMS=cpu PAIMON_TPU_MERGE_ENGINE=$eng PAIMON_TPU_DICT_DOMAIN=1 \
      timeout -k 10 600 python -m pytest tests/test_mesh_exec.py tests/test_mesh_execution.py \
      tests/test_randomized_oracle.py -q \
      -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  done
  exit 0
fi

if [ "${1:-}" = "lanes" ]; then
  # parity suite with compression forced on, then forced off: both sides of
  # the merge.lane-compression switch must produce bit-identical output
  for comp in 1 0; do
    env JAX_PLATFORMS=cpu PAIMON_TPU_LANE_COMPRESSION=$comp \
      timeout -k 10 600 python -m pytest tests/test_lanes.py tests/test_merge_kernel.py \
      tests/test_randomized_oracle.py -q \
      -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  done
  exit 0
fi

if [ "${1:-}" = "soak" ]; then
  # no -m filter: this stage INCLUDES the slow-marked ~45 s stage soak.
  # PAIMON_TPU_SOAK_ADAPTIVE=1: the churn compactor is the LUDA-style
  # adaptive scheduler (ISSUE 11) instead of periodic full compaction
  exec env JAX_PLATFORMS=cpu PAIMON_TPU_SOAK_DURATION=45 PAIMON_TPU_SOAK_SEED=0 \
    PAIMON_TPU_SOAK_ADAPTIVE=1 \
    timeout -k 10 600 python -m pytest tests/test_soak.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
fi

if [ "${1:-}" = "proc-soak" ]; then
  env JAX_PLATFORMS=cpu \
    timeout -k 10 300 python -m pytest tests/test_proc_soak.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  exec env JAX_PLATFORMS=cpu timeout -k 10 240 python -m paimon_tpu.service.proc_soak \
    --duration 45 --writers 2 --readers 1 --seed 0 \
    --scripted-kills "commit:manifests-written:2:kill,commit:snapshot-committed:2:kill,flush:files-written:3:kill,commit:before-manifests:2:kill" \
    --kill-period 9 --sweep-period 12 --min-kills 3
fi

if [ "${1:-}" = "join" ]; then
  # parity suite with lane compression forced on, then off (the kernels'
  # global lane plan is the piece that differs); the compressed pass also
  # forces the code-domain reader so table-level joins run on codes
  env JAX_PLATFORMS=cpu PAIMON_TPU_LANE_COMPRESSION=1 PAIMON_TPU_DICT_DOMAIN=1 \
    timeout -k 10 600 python -m pytest tests/test_join.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  exec env JAX_PLATFORMS=cpu PAIMON_TPU_LANE_COMPRESSION=0 \
    timeout -k 10 600 python -m pytest tests/test_join.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
fi

if [ "${1:-}" = "get" ]; then
  # parity suite with bloom key indexes forced onto every written file,
  # then forced off: batched gets must serve identical rows either way
  # (pruning is an optimization, never a semantic)
  for kb in 1 0; do
    env JAX_PLATFORMS=cpu PAIMON_TPU_KEY_BLOOM=$kb \
      timeout -k 10 600 python -m pytest tests/test_point_get.py tests/test_lookup.py -q \
      -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  done
  exit 0
fi

if [ "${1:-}" = "subscribe" ]; then
  # no -m filter: this stage INCLUDES the slow-marked ~45 s subscriber soak
  # and the subscriber-process kill -9 test
  exec env JAX_PLATFORMS=cpu PAIMON_TPU_SOAK_DURATION=45 PAIMON_TPU_SOAK_SEED=0 \
    timeout -k 10 600 python -m pytest tests/test_subscription.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
fi

if [ "${1:-}" = "cluster" ]; then
  env JAX_PLATFORMS=cpu \
    timeout -k 10 400 python -m pytest tests/test_cluster.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  exec env JAX_PLATFORMS=cpu timeout -k 10 240 python -m paimon_tpu.service.cluster \
    --duration 45 --workers 2 --readers 1 --seed 0 \
    --scripted-kills "flush:files-written:2:kill,cluster:compact-executing:1:kill,cluster:before-ship:2:kill" \
    --kill-period 10 --sweep-period 15 --min-kills 2
fi

if [ "${1:-}" = "elastic" ]; then
  env JAX_PLATFORMS=cpu \
    timeout -k 10 600 python -m pytest tests/test_elastic.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  exec env JAX_PLATFORMS=cpu timeout -k 10 300 python -m paimon_tpu.service.cluster \
    --duration 60 --workers 2 --readers 1 --seed 0 --buckets 4 \
    --scripted-kills "rescale:files-written:1:kill" \
    --kill-period 0 --sweep-period 20 \
    --elastic-script "rescale:8@0.3,admit@0.5,retire@0.7" --min-kills 1
fi

if [ "${1:-}" = "gateway" ]; then
  # no -m filter: this stage INCLUDES the slow-marked ~45 s seeded
  # mixed-kind tenant-isolation storm
  exec env JAX_PLATFORMS=cpu PAIMON_TPU_SOAK_DURATION=45 PAIMON_TPU_SOAK_SEED=0 \
    timeout -k 10 600 python -m pytest tests/test_gateway.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
fi

if [ "${1:-}" = "mega" ]; then
  env JAX_PLATFORMS=cpu \
    timeout -k 10 300 python -m pytest tests/test_mega_soak.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  exec env JAX_PLATFORMS=cpu timeout -k 10 420 python -m paimon_tpu.service.mega_soak \
    --cells flagship,dict-dynamic --duration 25 --workers 2 --seed 0 \
    --kill-period 8 --min-kills 3 --min-kill-kinds 2
fi

if [ "${1:-}" = "sql-cluster" ]; then
  # no -m filter: includes the slow SIGKILL OS-process worker-kill test.
  # Code-domain combine forced on, then off: distributed aggregation must
  # be bit-identical to the single-process evaluator in both currencies
  for cd in 1 0; do
    env JAX_PLATFORMS=cpu PAIMON_TPU_SQL_CODE_DOMAIN=$cd \
      timeout -k 10 600 python -m pytest tests/test_sql_cluster.py tests/test_sql_select.py -q \
      -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  done
  exit 0
fi

if [ "${1:-}" = "sql-shuffle" ]; then
  # shuffle exchange forced on, then off: every grouped query must be
  # bit-identical to the single-process evaluator whether partials combine
  # peer-to-peer at range owners or single-point at the coordinator
  for sh in 1 0; do
    env JAX_PLATFORMS=cpu PAIMON_TPU_SQL_SHUFFLE=$sh \
      timeout -k 10 600 python -m pytest tests/test_sql_shuffle.py tests/test_sql_cluster.py -q \
      -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  done
  exit 0
fi

if [ "${1:-}" = "encode" ]; then
  exec env JAX_PLATFORMS=cpu PAIMON_TPU_PARQUET_ENCODER=native \
    timeout -k 10 600 python -m pytest tests/test_encode.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly
fi

if [ "${1:-}" = "pallas" ]; then
  # parity suites with the sort engine forced pallas (fused kernels, CPU
  # via interpret=True), then xla-segmented: both sides of the sort-engine
  # switch must produce bit-identical merge output (tables that explicitly
  # chose an engine keep it — the env only pins the undecided)
  for eng in pallas xla-segmented; do
    env JAX_PLATFORMS=cpu PAIMON_TPU_SORT_ENGINE=$eng \
      timeout -k 10 600 python -m pytest tests/test_pallas_merge.py tests/test_pallas.py \
      tests/test_merge_kernel.py tests/test_randomized_oracle.py -q \
      -p no:cacheprovider -p no:xdist -p no:randomly || exit $?
  done
  exit 0
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
