"""Tier-3 fault injection: the whole store running on FailingFileIO
(mirrors reference FileStoreCommitTest with FailingFileIO)."""

import numpy as np
import pytest

from paimon_tpu.core.manifest import ManifestCommittable
from paimon_tpu.core.schema import SchemaManager
from paimon_tpu.core.store import KeyValueFileStore
from paimon_tpu.data import ColumnBatch
from paimon_tpu.fs import get_file_io
from paimon_tpu.fs.testing import ArtificialException, FailingFileIO
from paimon_tpu.types import BIGINT, DOUBLE, RowType

SCHEMA = RowType.of(("k", BIGINT()), ("v", DOUBLE()))


import pytest


@pytest.mark.parametrize(
    "manifest_format,scheme",
    [("jsonl", "fail"), ("avro", "fail"), ("jsonl", "fail-s3"), ("jsonl", "fail-s3-legacy")],
)
def test_commit_crash_safety_under_random_failures(tmp_path, manifest_format, scheme):
    """Writers crash randomly mid write/commit; retries must never corrupt the
    table: every successful commit is fully visible, every failed one fully
    invisible. Runs for BOTH metadata planes (jsonl and reference avro) and
    for BOTH storage models: POSIX rename CAS ("fail") and object-store
    conditional-PUT-under-catalog-lock ("fail-s3"; "fail-s3-legacy" commits
    check-then-put under a jdbc lock — no store-level CAS at all)."""
    domain = f"commitfault_{manifest_format}_{scheme.replace('-', '')}"
    FailingFileIO.reset(domain, max_fails=0, possibility=0)
    io = get_file_io(f"{scheme}://{domain}/x")
    path = f"{scheme}://{domain}{tmp_path}/table"
    opts = {"bucket": "1", "manifest.format": manifest_format,
            "commit.catalog-lock.acquire-timeout": "10"}
    if scheme == "fail-s3-legacy":
        # no conditional PUT: the file lock itself would be check-then-put;
        # mutual exclusion must come from the external jdbc lock
        opts.update({"commit.catalog-lock.type": "jdbc",
                     "commit.catalog-lock.jdbc-path": str(tmp_path / "locks.db")})
    sm = SchemaManager(io, path)
    ts = sm.create_table(SCHEMA, primary_keys=["k"], options=opts)
    store = KeyValueFileStore(io, path, ts, commit_user="crashy")

    oracle = {}
    committed = 0
    rng = np.random.default_rng(0)
    for attempt in range(30):
        ident = committed + 1
        ks = rng.integers(0, 50, 20).tolist()
        vs = [float(x) for x in rng.random(20)]
        FailingFileIO.reset(domain, max_fails=3, possibility=4, seed=attempt)
        try:
            w = store.new_writer((), 0)
            w.write(ColumnBatch.from_pydict(store.value_schema, {"k": ks, "v": vs}))
            msg = w.prepare_commit()
            commit = store.new_commit()
            remaining = commit.filter_committed([ManifestCommittable(ident, messages=[msg])])
            if not remaining:
                continue
            commit.commit(remaining[0])
        except ArtificialException:
            # crashed somewhere: check whether the commit actually landed
            FailingFileIO.reset(domain, max_fails=0, possibility=0)
            latest = store.snapshot_manager.latest_snapshot()
            if latest is not None and latest.commit_user == "crashy" and latest.commit_identifier >= ident:
                pass  # landed despite the crash report
            else:
                continue  # fully invisible — retry next round with new data
        FailingFileIO.reset(domain, max_fails=0, possibility=0)
        committed = ident
        for k, v in zip(ks, vs):
            oracle[k] = v

    FailingFileIO.reset(domain, max_fails=0, possibility=0)
    assert committed > 0
    files = store.restore_files((), 0)
    out = store.read_bucket((), 0, files)
    got = {r[0]: r[1] for r in out.to_pylist()}
    assert got == oracle


def test_failed_commit_leaves_no_partial_snapshot(tmp_path):
    domain = "snapfault"
    FailingFileIO.reset(domain, max_fails=0, possibility=0)
    io = get_file_io(f"fail://{domain}/x")
    path = f"fail://{domain}{tmp_path}/table"
    sm = SchemaManager(io, path)
    ts = sm.create_table(SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    store = KeyValueFileStore(io, path, ts)
    w = store.new_writer((), 0)
    w.write(ColumnBatch.from_pydict(store.value_schema, {"k": [1], "v": [1.0]}))
    msg = w.prepare_commit()
    FailingFileIO.reset(domain, max_fails=100, possibility=1)  # fail everything
    with pytest.raises(ArtificialException):
        store.new_commit().commit(ManifestCommittable(1, messages=[msg]))
    FailingFileIO.reset(domain, max_fails=0, possibility=0)
    assert store.snapshot_manager.latest_snapshot() is None
