"""Avro codec, migration, privileges (reference paimon-format avro/,
migrate/Migrator, privilege/)."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.catalog.privilege import AccessDeniedError, PrivilegeManager, PrivilegedCatalog
from paimon_tpu.data import ColumnBatch
from paimon_tpu.format import get_format
from paimon_tpu.fs import LocalFileIO
from paimon_tpu.types import BIGINT, BOOLEAN, DOUBLE, INT, STRING, RowType

SCHEMA = RowType.of(("id", BIGINT(False)), ("name", STRING()), ("v", DOUBLE()), ("ok", BOOLEAN()))


def test_avro_roundtrip(tmp_path):
    io = LocalFileIO()
    fmt = get_format("avro")
    b = ColumnBatch.from_pydict(
        SCHEMA,
        {
            "id": [1, 2, 3],
            "name": ["a", None, "c"],
            "v": [1.5, 2.5, None],
            "ok": [True, False, None],
        },
    )
    p = str(tmp_path / "f.avro")
    fmt.write(io, p, b)
    out = list(fmt.read(io, p, SCHEMA))
    assert len(out) == 1
    assert out[0].to_pydict() == b.to_pydict()
    # projection
    proj = next(iter(fmt.read(io, p, SCHEMA, projection=["name", "id"])))
    assert proj.schema.field_names == ["name", "id"]
    assert proj.to_pylist() == [("a", 1), (None, 2), ("c", 3)]


def test_avro_table_end_to_end(tmp_path):
    cat = FileSystemCatalog(str(tmp_path), commit_user="av")
    t = cat.create_table(
        "db.av", RowType.of(("k", BIGINT()), ("s", STRING())), primary_keys=["k"],
        options={"bucket": "1", "file.format": "avro"},
    )
    wb = t.new_batch_write_builder(); w = wb.new_write()
    w.write({"k": [2, 1], "s": ["b", "a"]}); wb.new_commit().commit(w.prepare_commit())
    wb = t.new_batch_write_builder(); w = wb.new_write()
    w.write({"k": [2], "s": ["b2"]}); wb.new_commit().commit(w.prepare_commit())
    rb = t.new_read_builder()
    assert rb.new_read().read_all(rb.new_scan().plan()).to_pylist() == [(1, "a"), (2, "b2")]


def test_migrate_parquet_dir(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    src = tmp_path / "legacy"
    src.mkdir()
    pq.write_table(pa.table({"x": [1, 2], "y": ["a", "b"]}), str(src / "part-0.parquet"))
    pq.write_table(pa.table({"x": [3], "y": ["c"]}), str(src / "part-1.parquet"))
    cat = FileSystemCatalog(str(tmp_path / "wh"), commit_user="mig")
    from paimon_tpu.table.migrate import migrate_files

    rt = RowType.of(("x", BIGINT()), ("y", STRING()))
    t = migrate_files(cat, "db.legacy", str(src), rt)
    rb = t.new_read_builder()
    out = rb.new_read().read_all(rb.new_scan().plan())
    assert sorted(out.to_pylist()) == [(1, "a"), (2, "b"), (3, "c")]
    # files were moved, not copied
    assert not list(src.glob("*.parquet"))


def test_privileged_catalog(tmp_warehouse):
    pm = PrivilegeManager(tmp_warehouse)
    pm.init("rootpw")
    root = PrivilegedCatalog(tmp_warehouse, "root", "rootpw")
    t = root.create_table("db.secure", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    pm.create_user("bob", "pw")
    # bob: no SELECT yet
    bob = PrivilegedCatalog(tmp_warehouse, "bob", "pw")
    with pytest.raises(AccessDeniedError):
        bob.get_table("db.secure")
    pm.grant("bob", "db.secure", "SELECT")
    assert bob.get_table("db.secure").name == "secure"
    with pytest.raises(AccessDeniedError):
        bob.writable_table("db.secure")
    with pytest.raises(AccessDeniedError):
        bob.drop_table("db.secure")
    pm.grant("bob", "db", "ADMIN")  # db-level admin inherits down
    assert bob.writable_table("db.secure") is not None
    # wrong password
    with pytest.raises(AccessDeniedError):
        PrivilegedCatalog(tmp_warehouse, "bob", "wrong")
    pm.revoke("bob", "db", "ADMIN")
    with pytest.raises(AccessDeniedError):
        bob.writable_table("db.secure")


def test_more_system_tables(tmp_warehouse):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="st")
    t = cat.create_table(
        "db.agg",
        RowType.of(("k", BIGINT()), ("total", DOUBLE())),
        primary_keys=["k"],
        options={"bucket": "1", "merge-engine": "aggregation", "fields.total.aggregate-function": "sum"},
    )
    rows = cat.get_table("db.agg$aggregation_fields").to_pylist()
    assert ("total", "DOUBLE", "sum", None, None) in rows
    wb = t.new_batch_write_builder(); w = wb.new_write()
    w.write({"k": [1, 1], "total": [2.0, 3.0]}); wb.new_commit().commit(w.prepare_commit())
    from paimon_tpu.table.statistics import analyze_table

    analyze_table(t)
    srows = cat.get_table("db.agg$statistics").to_pylist()
    assert srows and srows[0][2] == 1  # one merged row (sum=5.0)


def test_avro_native_fallback_on_weird_values(tmp_path):
    """Values the arrow conversion rejects must fall back to the python
    encoder, not crash the write."""
    io = LocalFileIO()
    fmt = get_format("avro")
    schema = RowType.of(("k", BIGINT(False)), ("s", STRING()))
    import numpy as np

    vals = np.empty(2, dtype=object)
    vals[0] = "ok"
    vals[1] = 12345  # non-string in a VARCHAR column
    from paimon_tpu.data.batch import Column

    b = ColumnBatch(schema, {"k": Column(np.array([1, 2], dtype=np.int64)), "s": Column(vals)})
    p = str(tmp_path / "weird.avro")
    fmt.write(io, p, b)  # must not raise
    out = next(iter(fmt.read(io, p, schema)))
    assert out.to_pylist() == [(1, "ok"), (2, "12345")]


def test_avro_skewed_string_field_retry(tmp_path):
    """One string field owning nearly all payload bytes triggers the
    decoder's capacity retry path."""
    io = LocalFileIO()
    fmt = get_format("avro")
    schema = RowType.of(("a", STRING()), ("b", STRING()), ("c", STRING()))
    big = "x" * 50_000
    b = ColumnBatch.from_pydict(schema, {"a": [big, big], "b": ["t", "u"], "c": ["v", "w"]})
    p = str(tmp_path / "skew.avro")
    fmt.write(io, p, b, compression="null")
    out = next(iter(fmt.read(io, p, schema)))
    assert out.to_pydict() == b.to_pydict()


# ---------------------------------------------------------------------------
# round 2: ORC stripe-statistics pruning (orc_meta tail reader)
# ---------------------------------------------------------------------------


def test_orc_tail_stats_roundtrip(tmp_path):
    import io

    import numpy as np
    import pyarrow as pa
    import pyarrow.orc as po

    from paimon_tpu.format.orc_meta import read_tail

    n = 200_000
    rng = np.random.default_rng(5)
    ids = rng.permutation(n).astype(np.int64)
    t = pa.table(
        {
            "id": ids,
            "d": ids.astype(np.float64) * 0.5,
            "s": pa.array([f"k{int(x) % 1000:03d}" for x in ids]),
        }
    )
    buf = io.BytesIO()
    po.write_table(t, buf, compression="zstd", stripe_size=64 * 1024)
    data = buf.getvalue()
    tail = read_tail(data)
    of = po.ORCFile(io.BytesIO(data))
    assert tail.nstripes == of.nstripes > 1
    assert sum(tail.stripe_rows) == n
    assert tail.field_columns == {"id": 1, "d": 2, "s": 3}
    # stats agree with the actual stripe contents
    for i in range(tail.nstripes):
        st = tail.stripe_stats(i)
        chunk = of.read_stripe(i)
        got_ids = np.asarray(chunk["id"])
        assert st["id"].min == got_ids.min() and st["id"].max == got_ids.max()
        assert st["id"].null_count == 0
        assert st["d"].min == float(np.asarray(chunk["d"]).min())
        vals = [x.as_py() for x in chunk["s"]]
        assert st["s"].min == min(vals) and st["s"].max == max(vals)


def test_orc_stripe_pruning_skips_stripes(tmp_warehouse):
    import numpy as np

    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.data.predicate import PredicateBuilder
    from paimon_tpu.metrics import registry
    from paimon_tpu.types import BIGINT, DOUBLE, RowType

    cat = FileSystemCatalog(tmp_warehouse, commit_user="orcp")
    t = cat.create_table(
        "db.orcp",
        RowType.of(("id", BIGINT()), ("v", DOUBLE())),
        primary_keys=["id"],
        options={"bucket": "1", "file.format": "orc", "orc.stripe.size": "65536"},
    )
    n = 300_000
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    # sorted ids -> stripes have disjoint id ranges -> range predicates prune
    w.write({"id": np.arange(n, dtype=np.int64), "v": np.arange(n, dtype=np.float64)})
    wb.new_commit().commit(w.prepare_commit())

    registry.reset()
    from paimon_tpu.data.predicate import equal

    rb = t.new_read_builder().with_filter(equal("id", 5))
    out = rb.new_read().read_all(rb.new_scan().plan())
    assert out.to_pylist() == [(5, 5.0)]
    snap = registry.snapshot()
    assert snap.get("scan", {}).get("orc_stripes_skipped", 0) >= 1


def test_orc_boolean_stripe_stats_not_inverted(tmp_path):
    """Regression: min for a mixed True/False stripe must be False, else
    equal(flag, False) pruned stripes that contain matching rows."""
    import io

    import pyarrow as pa
    import pyarrow.orc as po

    from paimon_tpu.data.predicate import equal
    from paimon_tpu.format.orc_meta import read_tail

    t = pa.table({"flag": [True] * 100 + [False] * 100})
    buf = io.BytesIO()
    po.write_table(t, buf, compression="zstd")
    tail = read_tail(buf.getvalue())
    st = tail.stripe_stats(0)["flag"]
    assert st.min is False and st.max is True
    assert equal("flag", False).test_stats({"flag": st})
    assert equal("flag", True).test_stats({"flag": st})
    # all-True stripe prunes equal(flag, False)
    t2 = pa.table({"flag": [True] * 50})
    buf2 = io.BytesIO()
    po.write_table(t2, buf2, compression="zstd")
    st2 = read_tail(buf2.getvalue()).stripe_stats(0)["flag"]
    assert st2.min is True and st2.max is True
    assert not equal("flag", False).test_stats({"flag": st2})
