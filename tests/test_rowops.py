"""UPDATE / MERGE INTO command semantics (reference
UpdatePaimonTableCommand.scala, MergeIntoPaimonTable.scala +
MergeIntoTableTest)."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.data.predicate import equal, greater_than
from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

SCHEMA = RowType.of(("id", BIGINT()), ("name", STRING()), ("v", DOUBLE()))


@pytest.fixture
def catalog(tmp_warehouse):
    return FileSystemCatalog(tmp_warehouse, commit_user="rowops")


def _write(t, data, kinds=None):
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write(data, kinds)
    wb.new_commit().commit(w.prepare_commit())


def _read(t):
    rb = t.new_read_builder()
    return sorted(rb.new_read().read_all(rb.new_scan().plan()).to_pylist())


def test_update_where_pk(catalog):
    t = catalog.create_table("db.u", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    _write(t, {"id": [1, 2, 3], "name": ["a", "b", "c"], "v": [1.0, 2.0, 3.0]})
    n = t.update_where(greater_than("v", 1.5), {"name": "bumped", "v": lambda b: b.column("v").values + 100})
    assert n == 2
    assert _read(t) == [(1, "a", 1.0), (2, "bumped", 102.0), (3, "bumped", 103.0)]
    with pytest.raises(ValueError):
        t.update_where(equal("id", 1), {"id": 9})  # PK update forbidden


def test_update_where_append_rewrite(catalog):
    t = catalog.create_table("db.ua", SCHEMA, options={"bucket": "1"})
    _write(t, {"id": [1, 1, 2], "name": ["x", "x", "y"], "v": [1.0, 1.0, 2.0]})
    n = t.update_where(equal("id", 1), {"v": 0.0})
    assert n == 2  # BOTH duplicate rows updated (no PK)
    assert _read(t) == [(1, "x", 0.0), (1, "x", 0.0), (2, "y", 2.0)]


def test_merge_into_full_clause_set(catalog):
    t = catalog.create_table("db.m", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    _write(t, {"id": [1, 2, 3, 4], "name": ["a", "b", "c", "d"], "v": [1.0, 2.0, 3.0, 4.0]})
    source = {
        "id": [2, 3, 4, 5, 6],
        "name": ["B", "C", "D", "E", "F"],
        "v": [20.0, -1.0, 40.0, 5.0, -6.0],
    }
    res = (
        t.merge_into(source)
        .when_matched_delete(condition=lambda s, tg: np.asarray(s.column("v").values) < 0)
        .when_matched_update({"name": "src.name", "v": lambda s, tg: s.column("v").values + tg.column("v").values})
        .when_not_matched_insert(condition=lambda s: np.asarray(s.column("v").values) > 0)
        .execute()
    )
    assert (res.rows_updated, res.rows_deleted, res.rows_inserted) == (2, 1, 1)
    assert _read(t) == [
        (1, "a", 1.0),      # untouched
        (2, "B", 22.0),     # matched update: src.name, v = src+tgt
        (4, "D", 44.0),     # matched update
        (5, "E", 5.0),      # not matched insert (condition passed)
    ]  # id=3 deleted (v<0), id=6 not inserted (condition failed)


def test_merge_into_rejects_duplicate_source_keys(catalog):
    t = catalog.create_table("db.md", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    _write(t, {"id": [1], "name": ["a"], "v": [1.0]})
    with pytest.raises(ValueError, match="duplicate"):
        t.merge_into({"id": [1, 1], "name": ["x", "y"], "v": [0.0, 0.0]}).when_matched_update(
            {"v": 9.0}
        ).execute()


def test_merge_into_insert_only_and_projection_source(catalog):
    t = catalog.create_table("db.mi", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    _write(t, {"id": [1], "name": ["a"], "v": [1.0]})
    # source without the 'name' column: inserts fill missing fields with null
    res = t.merge_into({"id": [1, 7], "v": [99.0, 7.0]}).when_not_matched_insert().execute()
    assert (res.rows_updated, res.rows_deleted, res.rows_inserted) == (0, 0, 1)
    assert _read(t) == [(1, "a", 1.0), (7, None, 7.0)]  # matched row untouched


def test_merge_into_requires_pk_coverage(catalog):
    t = catalog.create_table("db.mr", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    with pytest.raises(ValueError, match="primary key"):
        t.merge_into({"name": ["x"], "v": [1.0]})
    ta = catalog.create_table("db.ma", SCHEMA, options={"bucket": "1"})
    with pytest.raises(ValueError, match="primary-key"):
        ta.merge_into({"id": [1]})


def test_update_respects_deletion_vectors(catalog):
    """Round-2 review regression: UPDATE on a DV-enabled append table must
    not resurrect DV-deleted rows."""
    t = catalog.create_table(
        "db.udv", SCHEMA, options={"bucket": "1", "deletion-vectors.enabled": "true"}
    )
    _write(t, {"id": [1, 2, 3], "name": ["a", "b", "c"], "v": [1.0, 2.0, 3.0]})
    assert t.delete_where(equal("id", 2)) == 1
    n = t.update_where(equal("id", 3), {"v": 30.0})
    assert n == 1
    assert _read(t) == [(1, "a", 1.0), (3, "c", 30.0)]  # id=2 stays dead


def test_rowops_reject_non_dedup_engines(catalog):
    t = catalog.create_table(
        "db.agg", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "merge-engine": "aggregation", "fields.v.aggregate-function": "sum"},
    )
    _write(t, {"id": [1], "name": ["a"], "v": [2.0]})
    with pytest.raises(ValueError, match="deduplicate"):
        t.update_where(equal("id", 1), {"v": 100.0})
    with pytest.raises(ValueError, match="deduplicate"):
        t.merge_into({"id": [1], "name": ["x"], "v": [0.0]})


def test_merge_into_clause_declaration_order(catalog):
    """SQL MERGE applies the FIRST matching clause per row."""
    t = catalog.create_table("db.ord", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    _write(t, {"id": [1, 2], "name": ["a", "b"], "v": [1.0, -2.0]})
    src = {"id": [1, 2], "name": ["A", "B"], "v": [10.0, -20.0]}
    # unconditional UPDATE declared first: the delete clause is unreachable
    res = (
        t.merge_into(src)
        .when_matched_update({"v": "src.v"})
        .when_matched_delete(condition=lambda s, g: np.asarray(s.column("v").values) < 0)
        .execute()
    )
    assert (res.rows_updated, res.rows_deleted) == (2, 0)
    # declared the other way, the conditional delete fires first
    res2 = (
        t.merge_into(src)
        .when_matched_delete(condition=lambda s, g: np.asarray(s.column("v").values) < 0)
        .when_matched_update({"name": "src.name"})
        .execute()
    )
    assert (res2.rows_updated, res2.rows_deleted) == (1, 1)
    assert _read(t) == [(1, "A", 10.0)]


def test_merge_into_validates_at_declaration(catalog):
    t = catalog.create_table("db.vd", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    with pytest.raises(ValueError, match="primary key"):
        t.merge_into({"id": [9], "name": ["x"], "v": [0.0]}).when_matched_update({"id": 1})
    with pytest.raises(ValueError, match="tgt"):
        t.merge_into({"id": [9], "name": ["x"], "v": [0.0]}).when_not_matched_insert(
            values={"name": "tgt.name"}
        ).execute()
