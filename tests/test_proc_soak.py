"""Process-grain crash soak: env-armed kill -9 crash points, cross-process
recovery, supervisor bookkeeping, and service-level load shedding.

Every crash here is a REAL process death (`os._exit` at an armed crash
point, or SIGKILL from the supervisor) — no exception unwinding, no cleanup,
torn `.tmp` files and orphaned manifests left exactly where a killed Flink
task JVM would leave them. Recovery is what the on-disk protocol provides:
the snapshot chain, the intent/ack journal, and the orphan sweep."""

import os
import subprocess
import sys
import time

import pytest

from paimon_tpu.core.schema import SchemaManager
from paimon_tpu.fs import get_file_io
from paimon_tpu.resilience.faults import (
    COMMIT_CRASH_POINTS,
    KILL_EXIT_CODE,
    WRITER_CRASH_POINTS,
    CrashError,
    arm_from_env,
    crash_point,
    disarm_crash_points,
)
from paimon_tpu.service.proc_soak import (
    ProcSoakConfig,
    WriterJournal,
    run_proc_soak,
)
from paimon_tpu.service.soak import SCHEMA, find_landed_append, sweep_and_audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_table(root: str, extra: dict | None = None) -> None:
    # ONE bucket: each writer round hits each crash point exactly once, so
    # an armed `nth` maps 1:1 onto round numbers (with N buckets the flush
    # points fire once per bucket writer per round)
    opts = {
        "bucket": "1",
        "write-buffer-rows": "64",
        "commit.max-retries": "30",
        "commit.retry-backoff": "2 ms",
    }
    opts.update(extra or {})
    SchemaManager(get_file_io(root), root).create_table(SCHEMA, primary_keys=["k"], options=opts)


def _run_writer(
    root: str,
    run_dir: str,
    wid: int = 0,
    rounds: int = 3,
    crash: str | None = None,
    incarnation: int = 0,
) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PAIMON_TPU_CRASH_POINT", None)
    if crash:
        env["PAIMON_TPU_CRASH_POINT"] = crash
    env["PYTHONPATH"] = REPO + (os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    cmd = [
        sys.executable, "-m", "paimon_tpu.service.proc_soak", "writer",
        "--table", root,
        "--wid", str(wid),
        "--journal", os.path.join(run_dir, f"journal-{wid}.jsonl"),
        "--stop-file", os.path.join(run_dir, "stop"),
        "--max-rounds", str(rounds),
        "--rows-per-commit", "40",
        "--chunk-rows", "20",
        "--compact-every", "0",
        # fresh keys only: physical record count == unique keys without a
        # compaction, so the no-double-apply assertions are exact
        "--update-fraction", "0",
        "--incarnation", str(incarnation),
    ]
    return subprocess.run(cmd, capture_output=True, text=True, timeout=120, env=env, cwd=REPO)


def _journal_oracle(store, run_dir: str, wid: int = 0) -> dict:
    """The fold a fresh process reconstructs from the journal + the landed-
    snapshot probe — the cross-process analog of the thread soak's OracleLog."""
    events = WriterJournal.read(os.path.join(run_dir, f"journal-{wid}.jsonl"))
    intents = {e["ident"]: e for e in events if e["t"] == "intent"}
    acked = {e["ident"]: e["sid"] for e in events if e["t"] in ("ack", "recovered")}
    landed = {}
    for ident in intents:
        sid = acked.get(ident)
        if sid is None:
            sid = find_landed_append(store, f"psoak-w{wid}", ident)
        if sid is not None:
            landed[sid] = {int(k): v for k, v in intents[ident]["rows"].items()}
    expected: dict = {}
    for sid in sorted(landed):
        expected.update(landed[sid])
    return expected


def _scan(table) -> dict:
    rb = table.new_read_builder()
    batch = rb.new_read().read_all(rb.new_scan().plan())
    ks = batch.column("k").values.tolist()
    got = dict(zip(ks, batch.column("v").values.tolist()))
    assert len(ks) == len(got), "duplicate keys in final scan"
    return got


# ---------------------------------------------------------------------------
# env arming (in-process, CrashError mode — never kill inside pytest!)
# ---------------------------------------------------------------------------
def test_arm_from_env_spec_parsing():
    try:
        armed = arm_from_env("commit:before-manifests:3,flush:files-written")
        assert armed == ["commit:before-manifests", "flush:files-written"]
        # nth=3: two hits pass, the third raises
        crash_point("commit:before-manifests")
        crash_point("commit:before-manifests")
        with pytest.raises(CrashError):
            crash_point("commit:before-manifests")
        # count=1: the spec is one-shot
        crash_point("commit:before-manifests")
        # default nth=1: first hit fires
        with pytest.raises(CrashError):
            crash_point("flush:files-written")
    finally:
        disarm_crash_points()


def test_arm_from_env_kill_mode_parsed_not_fired():
    """The :kill suffix must parse into the hard-death mode without firing
    at arm time (firing would take pytest down with it)."""
    from paimon_tpu.resilience.faults import _armed

    try:
        arm_from_env("commit:manifests-written:7:kill")
        st = _armed["commit:manifests-written"]
        assert st.kill and st.skip == 6 and st.count == 1 and st.fired == 0
    finally:
        disarm_crash_points()


# ---------------------------------------------------------------------------
# kill -9 at every crash point: torn state -> sweep -> journal-oracle re-read
# ---------------------------------------------------------------------------
# which points leave unreachable garbage on disk when the process dies there
# (the "fails without the sweep" half of the test)
_LEAKS = {
    # the round's level-0 files were already flushed when the commit died
    # pre-manifest: at process grain even this point strands data files
    "commit:before-manifests": True,
    "commit:manifests-written": True,  # orphan manifests + lists (+ data files)
    "commit:snapshot-committed": False,  # commit fully visible; only the ack died
    "flush:before-dispatch": False,  # memtable lost with the process, no bytes on disk
    "flush:files-written": True,  # orphan level-0 data files
}


@pytest.mark.parametrize("point", COMMIT_CRASH_POINTS + WRITER_CRASH_POINTS)
def test_kill_at_crash_point_then_sweep_matches_journal_oracle(tmp_path, point):
    from paimon_tpu.table import load_table

    root = str(tmp_path / "table")
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    _make_table(root)
    r = _run_writer(root, run_dir, rounds=3, crash=f"{point}:2:kill")
    assert r.returncode == KILL_EXIT_CODE, (r.returncode, r.stdout, r.stderr)

    table = load_table(root, commit_user="psoak-verify")
    expected = _journal_oracle(table.store, run_dir)
    assert expected, "the first round must have landed before the armed kill"
    if point == "commit:snapshot-committed":
        # died AFTER the CAS: round 2 is in the table although its ack is not
        assert len(expected) > 40
    # a build without the sweep keeps the kill's garbage forever — the
    # independent disk walk must call it out
    pre = sweep_and_audit(table, root, sweep=False)
    if _LEAKS[point]:
        assert pre["leaked_files"], f"kill at {point} must strand unreachable files"
    else:
        assert pre["leaked_files"] == []
    # fresh-process recovery: sweep at threshold 0 reclaims exactly the
    # garbage, and the surviving table still equals the journal oracle
    post = sweep_and_audit(table, root, older_than_millis=0, sweep=True)
    assert post["leaked_files"] == []
    assert post["orphans_removed"] >= len(pre["leaked_files"])
    assert _scan(table) == expected


def test_respawned_writer_recovers_landed_unacked_commit(tmp_path):
    """kill -9 after the snapshot CAS but before the journal ack: the
    respawned incarnation must resolve the round from the snapshot chain
    (journal `recovered` record), NOT replay it — no double-applied ADDs."""
    from paimon_tpu.core.snapshot import CommitKind
    from paimon_tpu.table import load_table

    root = str(tmp_path / "table")
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    _make_table(root)
    r = _run_writer(root, run_dir, rounds=3, crash="commit:snapshot-committed:2:kill")
    assert r.returncode == KILL_EXIT_CODE
    r2 = _run_writer(root, run_dir, rounds=1, incarnation=1)
    assert r2.returncode == 0, (r2.stdout, r2.stderr)
    events = WriterJournal.read(os.path.join(run_dir, "journal-0.jsonl"))
    kinds = [(e["t"], e["ident"]) for e in events if e["t"] != "intent"]
    assert ("recovered", 2) in kinds, kinds
    table = load_table(root, commit_user="psoak-verify")
    # identifier 2 landed exactly once: the recovery adopted, never replayed
    snaps = table.store.snapshot_manager.snapshots_of_user_with_identifier("psoak-w0", 2)
    assert len([s for s in snaps if s.commit_kind == CommitKind.APPEND]) == 1
    expected = _journal_oracle(table.store, run_dir)
    assert _scan(table) == expected
    # physical record count agrees with the key space: a hidden double-apply
    # could not survive this (rounds update disjoint fresh keys here)
    assert table.store.snapshot_manager.latest_snapshot().total_record_count == len(expected)


# ---------------------------------------------------------------------------
# supervised mini-soak: kills, respawns, periodic sweep, end-to-end verify
# ---------------------------------------------------------------------------
def test_mini_process_soak_with_kills_and_respawns(tmp_path):
    cfg = ProcSoakConfig(
        duration_s=8.0,
        writers=2,
        readers=1,
        seed=7,
        rows_per_commit=80,
        write_chunk_rows=40,
        compact_every=4,
        scripted_kills=(
            "commit:manifests-written:2:kill",
            "commit:snapshot-committed:2:kill",
        ),
        kill_period_s=3.0,
        sweep_period_s=4.0,
        sweep_older_than_ms=30_000,
        block_timeout_ms=5_000,
    )
    report = run_proc_soak(str(tmp_path), cfg)
    assert report["consistent"], report
    # supervisor bookkeeping: every death was counted and refilled
    assert report["procs_killed"] >= 2, report
    assert report["procs_respawned"] >= report["procs_killed"], report
    assert report["procs_spawned"] == cfg.writers + cfg.readers + report["procs_respawned"], report
    assert report["writer_errors"] == 0, report
    # the service did real work between the kills and lost none of it
    assert report["accepted_commits"] > 0
    assert report["lost_rows"] == 0 and report["duplicated_rows"] == 0
    assert report["read_errors"] == 0
    assert report["leaked_file_count"] == 0
    assert report["total_record_count"] == report["expected_unique_keys"]
    assert report["double_applied"] == []


# ---------------------------------------------------------------------------
# service-level load shedding
# ---------------------------------------------------------------------------
def test_kv_health_roundtrip(tmp_warehouse):
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.core.admission import WriteBufferController
    from paimon_tpu.service import KvQueryClient, KvQueryServer

    cat = FileSystemCatalog(tmp_warehouse, commit_user="svc")
    t = cat.create_table("db.h", SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    ctrl = WriteBufferController(1_000, stop_trigger=0.5, block_timeout_ms=50)
    server = KvQueryServer(t, health_provider=ctrl.health_dict)
    server.start()
    try:
        client = KvQueryClient.for_table(t)
        h = client.health()
        assert h["state"] == "ok" and h["buffered_bytes"] == 0
        # saturate: the remote surface must report the same stable schema
        ctrl.try_reserve(600)
        h = client.health()
        assert h["state"] == "throttling" and h["retry_after_ms"] > 0
        assert h["buffered_bytes"] == 600 and "pending_flushes" in h and "backpressure_ms" in h
        ctrl.release(600)
        assert client.health()["state"] == "ok"
        client.close()
    finally:
        server.shutdown()


def test_kv_health_without_provider_reports_ok(tmp_warehouse):
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.service import KvQueryClient, KvQueryServer

    cat = FileSystemCatalog(tmp_warehouse, commit_user="svc")
    t = cat.create_table("db.h2", SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    server = KvQueryServer(t)
    server.start()
    try:
        client = KvQueryClient.for_table(t)
        assert client.health() == {"state": "ok"}
        client.close()
    finally:
        server.shutdown()


def test_flight_health_and_typed_busy_shed(tmp_warehouse):
    pytest.importorskip("pyarrow.flight")
    import threading

    import pyarrow as pa

    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.core.admission import WriteBufferController
    from paimon_tpu.metrics import soak_metrics
    from paimon_tpu.service.flight import (
        FlightBusyError,
        PaimonFlightServer,
        flight_health,
        flight_put,
        flight_scan,
    )

    cat = FileSystemCatalog(tmp_warehouse, commit_user="svc")
    cat.create_table("db.ing", SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    ctrl = WriteBufferController(1_000, stop_trigger=0.5, block_timeout_ms=200)
    srv = PaimonFlightServer(tmp_warehouse, ingest_controller=ctrl)
    loc = srv.start()
    try:
        assert flight_health(loc, "db.ing")["state"] == "ok"
        data = pa.table({"k": list(range(100)), "v": [float(i) for i in range(100)]})
        r = flight_put(loc, "db.ing", data)
        assert r == {"attempts": 1, "sheds": 0, "rows": 100, "backoff_ms": 0.0}
        # saturate the writer budget: ingest must shed with a TYPED busy —
        # parseable state + retry-after, answered immediately (no timeout)
        ctrl.try_reserve(900)
        assert flight_health(loc, "db.ing")["state"] == "throttling"
        shed_before = soak_metrics().counter("shed_requests").count
        t0 = time.perf_counter()
        with pytest.raises(FlightBusyError) as ei:
            flight_put(loc, "db.ing", data, max_retries=2)
        elapsed = time.perf_counter() - t0
        assert ei.value.payload["state"] == "throttling"
        assert ei.value.retry_after_ms > 0
        # 2 retries x 100 ms hinted backoff, nowhere near a network timeout
        assert elapsed < 5.0
        assert soak_metrics().counter("shed_requests").count >= shed_before + 3
        # pressure releases mid-backoff: the client wrapper rides it out
        threading.Timer(0.3, lambda: ctrl.release(900)).start()
        data2 = pa.table({"k": list(range(100, 150)), "v": [2.0] * 50})
        r2 = flight_put(loc, "db.ing", data2, max_retries=20)
        assert r2["sheds"] >= 1 and r2["attempts"] == r2["sheds"] + 1
        got = flight_scan(loc, "db.ing")
        assert got.num_rows == 150
    finally:
        srv.shutdown()


def test_table_write_health_reports_admission_schema(tmp_warehouse):
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.core.admission import WriteBufferController
    from paimon_tpu.table.write import TableWrite

    cat = FileSystemCatalog(tmp_warehouse, commit_user="svc")
    t = cat.create_table("db.tw", SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    ctrl = WriteBufferController(10_000, stop_trigger=0.5, block_timeout_ms=50)
    tw = TableWrite(t, buffer_controller=ctrl)
    try:
        tw.write({"k": [1, 2], "v": [1.0, 2.0]})
        h = tw.health()
        for key in (
            "state",
            "buffered_bytes",
            "pending_flushes",
            "backpressure_ms",
            "retry_after_ms",
            "writes_throttled",
            "writes_rejected",
        ):
            assert key in h, key
        assert h["state"] == "ok"
    finally:
        tw.close()
