"""Fused pallas merge kernel parity: pallas(interpret) == xla-segmented ==
numpy oracle, bit for bit, across seeds x key shapes x null rates x
lane-compression on/off x dict-domain on/off — both pallas tiers (the fused
in-VMEM bitonic kernel and the lax.sort + boundary-sweep fallback above the
VMEM cap). The `scripts/verify.sh pallas` stage runs this file (plus the
merge-kernel and whole-store oracles) with PAIMON_TPU_SORT_ENGINE forced
pallas and then xla-segmented."""

import jax
import numpy as np
import pytest

import paimon_tpu.ops.pallas_kernels as pk
from paimon_tpu.core.mergefn import _numpy_dedup_select
from paimon_tpu.ops import merge as M
from paimon_tpu.ops.merge import merge_plan, sorted_segments


def _dedup_oracle(lanes: np.ndarray, seq_lanes: np.ndarray | None = None) -> np.ndarray:
    return _numpy_dedup_select(lanes, seq_lanes, compress=False)


def _rand_lanes(rng, n, shape):
    """Key-lane matrices covering the shapes the planner narrows/packs
    differently: single dense, two mixed-width, four wide, u16-range."""
    if shape == "one":
        return rng.integers(0, max(2, n // 2), (n, 1)).astype(np.uint32)
    if shape == "narrow":
        return rng.integers(0, 200, (n, 1)).astype(np.uint32)
    if shape == "two":
        a = rng.integers(0, 50, n).astype(np.uint32)
        b = rng.integers(0, 1 << 20, n).astype(np.uint32)
        return np.stack([a, b], axis=1)
    a = rng.integers(0, 9, n).astype(np.uint32)
    b = rng.integers(0, 3, n).astype(np.uint32)
    c = rng.integers(0, 1 << 30, n).astype(np.uint32)
    d = rng.integers(0, 100, n).astype(np.uint32)
    return np.stack([a, b, c, d], axis=1)


# ---------------------------------------------------------------------------
# kernel-level parity: dedup select
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("shape", ["one", "narrow", "two", "four"])
@pytest.mark.parametrize("compress", [False, True])
def test_dedup_parity_pallas_xla_numpy(seed, shape, compress):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(3, 2500))
    lanes = _rand_lanes(rng, n, shape)
    oracle = np.asarray(_dedup_oracle(lanes))
    xla = M.deduplicate_resolve(M.deduplicate_select_async(lanes, None, backend="xla", compress=compress))
    pallas = M.deduplicate_resolve(
        M.deduplicate_select_async(lanes, None, backend="pallas", compress=compress)
    )
    assert pallas.tolist() == xla.tolist() == oracle.tolist()


@pytest.mark.parametrize("seed", range(3))
def test_dedup_parity_with_seq_lanes(seed):
    rng = np.random.default_rng(100 + seed)
    n = int(rng.integers(10, 1500))
    lanes = _rand_lanes(rng, n, "two")
    seq = rng.permutation(n).astype(np.uint32).reshape(-1, 1)
    oracle = np.asarray(_dedup_oracle(lanes, seq))
    xla = M.deduplicate_resolve(M.deduplicate_select_async(lanes, seq, backend="xla", compress=False))
    pallas = M.deduplicate_resolve(
        M.deduplicate_select_async(lanes, seq, backend="pallas", compress=False)
    )
    assert pallas.tolist() == xla.tolist() == oracle.tolist()


@pytest.mark.parametrize("seed", range(3))
def test_sweep_tier_parity(monkeypatch, seed):
    """Above the fused kernel's VMEM cap the pallas engine keeps lax.sort
    and computes boundaries with the sweep kernel — same contract. The cap
    is forced down so the tier runs at test sizes (fresh local jits: the
    admission decision is baked per trace)."""
    monkeypatch.setattr(pk, "_FUSE_MAX_ROWS", 1)
    rng = np.random.default_rng(200 + seed)
    n = int(rng.integers(5, 2000))
    lanes = _rand_lanes(rng, n, "two")
    m = M.pad_size(n)
    kl = np.full((2, m), 0xFFFFFFFF, dtype=np.uint32)
    kl[:, :n] = lanes.T
    pad = np.zeros(m, dtype=np.uint32)
    pad[n:] = 1
    assert not pk.fusable(m, 3)

    def run(engine):
        @jax.jit
        def f(kl, pad):
            return sorted_segments(2, 0, kl, [], pad, engine=engine)

        return [np.asarray(x) for x in f(kl, pad)]

    for a, b in zip(run("xla"), run("pallas")):
        assert (a == b).all()


def test_fused_tier_actually_fuses():
    """Below the cap the pallas engine must route the fused kernel, not the
    sweep: fusable() is the single admission predicate both the trace and
    the metric hook use."""
    assert pk.fusable(4096, 3)
    assert not pk.fusable(4097, 3)  # not a power of two
    assert not pk.fusable(1 << 19, 3)  # above the row cap
    assert not pk.fusable(4096, 20)  # too many lanes


# ---------------------------------------------------------------------------
# merge_plan / partial-update / aggregate parity through the seam
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_merge_plan_parity(seed):
    rng = np.random.default_rng(300 + seed)
    n = int(rng.integers(3, 2000))
    lanes = _rand_lanes(rng, n, "two")
    seq = np.stack(
        [np.zeros(n, np.uint32), rng.permutation(n).astype(np.uint32)], axis=1
    )
    a = merge_plan(lanes, seq, compress=False, engine="xla")
    b = merge_plan(lanes, seq, compress=False, engine="pallas")
    assert (a.perm == b.perm).all()
    assert (a.seg_start == b.seg_start).all()
    assert (a.keep_last == b.keep_last).all()
    assert (a.seg_id == b.seg_id).all()
    assert a.n == b.n and a.m == b.m


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("null_rate", [0.0, 0.4])
def test_fused_partial_update_parity(seed, null_rate):
    from paimon_tpu.types import RowKind

    rng = np.random.default_rng(400 + seed)
    n = int(rng.integers(10, 1200))
    lanes = _rand_lanes(rng, n, "one")
    fv = rng.random((3, n)) >= null_rate
    kinds = rng.choice(
        [int(RowKind.INSERT), int(RowKind.UPDATE_AFTER), int(RowKind.DELETE)],
        size=n,
        p=[0.6, 0.3, 0.1],
    ).astype(np.uint8)
    outs = {}
    for engine in ("xla", "pallas"):
        outs[engine] = M.fused_partial_update(
            lanes, None, fv, kinds, remove_record_on_delete=True, compress=False, engine=engine
        )
    for a, b in zip(outs["xla"], outs["pallas"]):
        assert np.asarray(a).tolist() == np.asarray(b).tolist()


@pytest.mark.parametrize("seed", range(3))
def test_fused_aggregate_parity(seed):
    from paimon_tpu.data.batch import Column
    from paimon_tpu.ops import AggregateSpec
    from paimon_tpu.ops.aggregates import fused_aggregate
    from paimon_tpu.types import RowKind

    rng = np.random.default_rng(500 + seed)
    n = int(rng.integers(10, 1200))
    lanes = _rand_lanes(rng, n, "narrow")
    vals = rng.integers(-50, 50, n).astype(np.int64)
    valid = rng.random(n) >= 0.2
    cols = [Column(vals, valid), Column(np.abs(vals) + 1)]
    specs = [AggregateSpec("sum"), AggregateSpec("max")]
    kinds = np.full(n, int(RowKind.INSERT), dtype=np.uint8)
    outs = {}
    for engine in ("xla", "pallas"):
        agg, take = fused_aggregate(lanes, None, cols, specs, kinds, compress=False, engine=engine)
        outs[engine] = ([(c.values.tolist(), c.valid_mask().tolist()) for c in agg], take.tolist())
    assert outs["xla"] == outs["pallas"]


@pytest.mark.parametrize("seed", range(3))
def test_ovc_composes_with_pallas(seed):
    """PR 6 offset-value coding must ride through the pallas engine
    unchanged: run-sorted composite keys with compression on (the OVC
    qualifying shape) select identically under all three engines."""
    rng = np.random.default_rng(600 + seed)
    runs, per = 4, 400
    parts = []
    for _ in range(runs):
        r = np.stack(
            [
                np.sort(rng.integers(0, 1 << 24, per)).astype(np.uint32),
                rng.integers(0, 1 << 16, per).astype(np.uint32),
                rng.integers(0, 1 << 8, per).astype(np.uint32),
            ],
            axis=1,
        )
        r = r[np.lexsort([r[:, 2], r[:, 1], r[:, 0]])]
        parts.append(r)
    lanes = np.concatenate(parts)
    oracle = np.asarray(_numpy_dedup_select(lanes, None, compress=True))
    xla = M.deduplicate_resolve(M.deduplicate_select_async(lanes, None, backend="xla", compress=True))
    pallas = M.deduplicate_resolve(
        M.deduplicate_select_async(lanes, None, backend="pallas", compress=True)
    )
    assert pallas.tolist() == xla.tolist() == oracle.tolist()


# ---------------------------------------------------------------------------
# boundary-sweep shape contract (satellite: the m % 128 fix)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("m", [1, 3, 127, 129, 200, 2047, 2049, 5000])
def test_keep_last_mask_non_multiple_sizes(m):
    """The old wrapper silently required m % 128 == 0 (grid = m // block
    truncated the tail); any m must now produce the exact boundary mask."""
    rng = np.random.default_rng(m)
    keys = np.sort(rng.integers(0, max(2, m // 3), m)).astype(np.uint32)
    pad = np.zeros(m, dtype=np.uint32)
    stacked = np.stack([pad, keys])
    out = np.asarray(pk.keep_last_mask(stacked, interpret=True))
    if m == 1:
        expect = np.ones(1, np.uint32)
    else:
        expect = np.concatenate([keys[1:] != keys[:-1], [True]]).astype(np.uint32)
    assert (out == expect).all()


def test_keep_last_mask_pad_contract():
    """mask_pad=True zeroes pad rows (legacy dedup mask); mask_pad=False is
    the raw sorted_segments keep_last where the pad segment closes too."""
    keys = np.array([1, 1, 2, 0, 0], dtype=np.uint32)  # 2 valid keys + pads
    pad = np.array([0, 0, 0, 1, 1], dtype=np.uint32)
    stacked = np.stack([pad, keys])
    masked = np.asarray(pk.keep_last_mask(stacked, interpret=True, mask_pad=True))
    raw = np.asarray(pk.keep_last_mask(stacked, interpret=True, mask_pad=False))
    assert masked.tolist() == [0, 1, 1, 0, 0]
    assert raw.tolist() == [0, 1, 1, 0, 1]


def test_note_dispatch_metrics():
    from paimon_tpu.metrics import registry

    with registry._lock:
        registry.groups.pop(("pallas", ()), None)
    assert pk.note_dispatch(4096, 3) is True
    assert pk.note_dispatch(1 << 19, 3) is False
    snap = registry.snapshot()["pallas"]
    assert snap["kernels_launched"] == 2
    assert snap["fallback_xla"] == 1
    assert snap["tiles"] >= 1 + (1 << 19) // 2048


# ---------------------------------------------------------------------------
# table level: sort-engine x lane-compression x dict-domain matrix
# ---------------------------------------------------------------------------


def _build_matrix_table(tmp_warehouse, rng):
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.types import BIGINT, DOUBLE, RowType, STRING

    cat = FileSystemCatalog(tmp_warehouse, commit_user="pm")
    t = cat.create_table(
        "db.pm",
        RowType.of(
            ("k1", STRING(False)), ("k2", BIGINT(False)), ("v", DOUBLE()), ("tag", STRING())
        ),
        primary_keys=["k1", "k2"],
        options={"bucket": "1", "write-only": "true"},
    )
    for _ in range(3):
        n = 900
        k1 = np.array([f"user-{int(x):05d}" for x in rng.integers(0, 400, n)], dtype=object)
        k2 = rng.integers(0, 5, n).astype(np.int64)
        v = rng.random(n)
        tag = np.array(
            [None if rng.random() < 0.3 else f"t{int(x)}" for x in rng.integers(0, 8, n)],
            dtype=object,
        )
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write({"k1": k1, "k2": k2, "v": v, "tag": tag})
        wb.new_commit().commit(w.prepare_commit())
    return t


def test_table_matrix_sort_engines(tmp_warehouse, rng):
    t = _build_matrix_table(tmp_warehouse, rng)
    results = {}
    for engine in ("xla-segmented", "pallas", "numpy"):
        for compress in ("true", "false"):
            for dd in ("true", "false"):
                tt = t.copy(
                    {
                        "sort-engine": engine,
                        "merge.lane-compression": compress,
                        "merge.dict-domain": dd,
                        "cache.data-file.max-memory-size": "0 b",
                    }
                )
                rb = tt.new_read_builder()
                out = rb.new_read().read_all(rb.new_scan().plan())
                results[(engine, compress, dd)] = out.to_pylist()
    ref = results[("xla-segmented", "true", "true")]
    assert len(ref) > 0
    for key, rows in results.items():
        assert rows == ref, f"divergent output for {key}"


def test_table_pallas_compaction_parity(tmp_warehouse):
    """Compaction rewrite inherits the seam: full-compact twin tables under
    sort-engine=pallas and xla-segmented and assert identical content."""
    outs = {}
    for engine in ("xla-segmented", "pallas"):
        sub = f"{tmp_warehouse}/{engine}"
        tt = _build_matrix_table(sub, np.random.default_rng(7)).copy(
            {"sort-engine": engine, "write-only": "false"}
        )
        wb = tt.new_batch_write_builder()
        w = wb.new_write()
        w.compact(full=True)
        wb.new_commit().commit(w.prepare_commit())
        rb = tt.new_read_builder()
        outs[engine] = rb.new_read().read_all(rb.new_scan().plan()).to_pylist()
    assert outs["pallas"] == outs["xla-segmented"]
    assert len(outs["pallas"]) > 0


def test_table_pallas_sort_compact_parity(tmp_warehouse):
    """Sort-compact's clustering sort inherits the seam too (append-only
    tables): zorder-rewrite twins and compare plan-order readback."""
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.table.sort_compact import sort_compact
    from paimon_tpu.types import BIGINT, DOUBLE, RowType

    outs = {}
    for engine in ("xla-segmented", "pallas"):
        rng_e = np.random.default_rng(11)
        cat = FileSystemCatalog(f"{tmp_warehouse}/{engine}", commit_user="sc")
        t = cat.create_table(
            "db.sc",
            RowType.of(("a", BIGINT()), ("b", BIGINT()), ("v", DOUBLE())),
            options={"bucket": "1", "sort-engine": engine},
        )
        for _ in range(2):
            n = 1500
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write(
                {
                    "a": rng_e.integers(0, 1 << 16, n),
                    "b": rng_e.integers(0, 1 << 16, n),
                    "v": rng_e.random(n),
                }
            )
            wb.new_commit().commit(w.prepare_commit())
        sort_compact(t, ["a", "b"], order="zorder")
        rb = t.new_read_builder()
        outs[engine] = rb.new_read().read_all(rb.new_scan().plan()).to_pylist()
    assert outs["pallas"] == outs["xla-segmented"]
    assert len(outs["pallas"]) == 3000
