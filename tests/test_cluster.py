"""Cluster service: coordinator/worker mesh execution (ISSUE 15).

In-process tests drive ClusterCoordinator.handle() and ClusterWorkerAgent
directly (the TCP layer is a thin shim over both), so the failover edges —
reassigned-exactly-once, stale-epoch commit fencing, debt-charge release on
death — are deterministic. One bounded multi-process mini soak proves the
whole topology end to end: worker OS processes with their own jax runtimes,
kill -9 at a scripted crash point, journal recovery, and the proc-soak
consistency oracle (fold == final scan, zero lost/dup/leaked).
"""

import os
import time

import numpy as np
import pytest

from paimon_tpu.core.manifest import CommitMessage, ManifestCommittable
from paimon_tpu.core.schema import SchemaManager
from paimon_tpu.fs import get_file_io
from paimon_tpu.metrics import cluster_metrics
from paimon_tpu.service.cluster import (
    ClusterClient,
    ClusterConfig,
    ClusterCoordinator,
    ClusterWorkerAgent,
    bucket_key_pools,
    run_cluster_soak,
)
from paimon_tpu.service.soak import SCHEMA
from paimon_tpu.table import load_table


def _mk_table(root: str, buckets: int = 4, **extra) -> None:
    opts = {
        "bucket": str(buckets),
        "write-only": "true",
        "merge.engine": "mesh",
        "write-buffer-rows": "128",
        "compaction.adaptive.read-amp-ceiling": "10",
        "compaction.adaptive.interval": "200 ms",
    }
    opts.update(extra)
    SchemaManager(get_file_io(root), root).create_table(SCHEMA, primary_keys=["k"], options=opts)


@pytest.fixture
def cluster_table(tmp_path):
    root = str(tmp_path / "t")
    _mk_table(root)
    return root


def _coordinator(root, workers=2, compaction=True, **kw) -> ClusterCoordinator:
    cfg = ClusterConfig(workers=workers, buckets=4, compaction=compaction, **kw)
    return ClusterCoordinator(root, cfg).start()


def _agent(root, coord, wid, tmp_path=None, serve=False, **kw) -> ClusterWorkerAgent:
    t = load_table(root, commit_user=f"cluster-w{wid}")
    journal = str(tmp_path / f"journal-{wid}.jsonl") if tmp_path is not None else None
    a = ClusterWorkerAgent(
        wid, t, coord.host, coord.port, journal_path=journal, serve=serve,
        round_rows=48, heartbeat_interval_s=0.1, **kw,
    )
    a.register()
    return a


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------
def test_commit_message_wire_roundtrip(cluster_table):
    from paimon_tpu.table.write import TableWrite

    t = load_table(cluster_table, commit_user="w")
    tw = TableWrite(t)
    tw.write({"k": list(range(64)), "v": [float(i) for i in range(64)]})
    msgs = tw.prepare_commit()
    tw.close()
    assert msgs
    for m in msgs:
        rt = CommitMessage.from_dict(json_roundtrip(m.to_dict()))
        assert rt.partition == m.partition and rt.bucket == m.bucket
        assert [f.to_dict() for f in rt.new_files] == [f.to_dict() for f in m.new_files]
        assert rt.total_buckets == m.total_buckets
    # the wire form must actually commit
    t.store.new_commit().commit(
        ManifestCommittable(1, messages=[CommitMessage.from_dict(m.to_dict()) for m in msgs])
    )
    rb = t.new_read_builder()
    assert rb.new_read().read_all(rb.new_scan().plan()).num_rows == 64


def json_roundtrip(d):
    import json

    return json.loads(json.dumps(d))


# ---------------------------------------------------------------------------
# satellite 1: worker startup path through parallel/distributed.py
# ---------------------------------------------------------------------------
def test_init_worker_runtime_single_process_fallback():
    import jax

    from paimon_tpu.parallel import distributed

    mesh = distributed.init_worker_runtime()  # no topology: fallback
    assert mesh.devices.size == len(jax.devices())
    assert set(mesh.axis_names) == {"bucket", "key"}


def test_cluster_role_env_overrides_commit_coordinator(monkeypatch):
    from paimon_tpu.parallel import distributed

    monkeypatch.delenv(distributed.ROLE_ENV, raising=False)
    assert distributed.is_commit_coordinator()  # process_index 0 fallback
    monkeypatch.setenv(distributed.ROLE_ENV, "worker")
    assert not distributed.is_commit_coordinator()
    monkeypatch.setenv(distributed.ROLE_ENV, "coordinator")
    assert distributed.is_commit_coordinator()


# ---------------------------------------------------------------------------
# assignment + failover edges
# ---------------------------------------------------------------------------
def test_home_ranges_cover_and_registration_grants(cluster_table):
    coord = _coordinator(cluster_table, workers=2, compaction=False)
    try:
        r0 = coord.handle("register", {"worker": 0, "incarnation": 0})
        r1 = coord.handle("register", {"worker": 1, "incarnation": 0})
        assert sorted(r0["buckets"] + r1["buckets"]) == [0, 1, 2, 3]
        assert not set(r0["buckets"]) & set(r1["buckets"])
        assert r1["epoch"] > r0["epoch"]
    finally:
        coord.close()


def test_reassign_exactly_once_on_missed_heartbeat(cluster_table):
    g = cluster_metrics()
    before = g.counter("reassignments").count
    coord = _coordinator(
        cluster_table, workers=2, compaction=False, heartbeat_timeout_s=0.4
    )
    try:
        coord.handle("register", {"worker": 0, "incarnation": 0})
        coord.handle("register", {"worker": 1, "incarnation": 0})
        w0_buckets = set(coord.assignment_of(0)[1])
        assert w0_buckets
        # w1 keeps heartbeating, w0 goes silent -> the reaper reassigns
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            coord.handle("heartbeat", {"worker": 1, "epoch": 0})
            if set(coord.assignment_of(1)[1]) >= w0_buckets:
                break
            time.sleep(0.05)
        _, w1_buckets = coord.assignment_of(1)
        assert w0_buckets <= set(w1_buckets), (w0_buckets, w1_buckets)
        assert coord.assignment_of(0)[1] == []
        # each orphaned bucket moved EXACTLY once
        assert g.counter("reassignments").count - before == len(w0_buckets)
        # further reaper passes must not re-reassign (w1 keeps beating)
        until = time.monotonic() + 0.8
        while time.monotonic() < until:
            coord.handle("heartbeat", {"worker": 1, "epoch": 0})
            time.sleep(0.05)
        assert g.counter("reassignments").count - before == len(w0_buckets)
        # the declared-dead worker is told to re-register on its next beat
        assert coord.handle("heartbeat", {"worker": 0, "epoch": 0}).get("reregister")
    finally:
        coord.close()


def test_stale_commit_rejected_not_double_applied(cluster_table, tmp_path):
    """Failover edge: a worker killed (or merely slow), its bucket range
    reassigned, then heard from again — its late CommitMessage must be
    rejected by the epoch fence, never double-applied."""
    coord = _coordinator(cluster_table, workers=2, compaction=False)
    try:
        a0 = _agent(cluster_table, coord, 0, tmp_path)
        coord.handle("register", {"worker": 1, "incarnation": 0})
        epoch0, owned0 = a0.assignment()
        assert a0.ingest_round()  # a normal round lands
        store = load_table(cluster_table, commit_user="check").store
        sid_before = store.snapshot_manager.latest_snapshot_id()
        # build a full round's messages but DO NOT ship yet
        from paimon_tpu.data.batch import ColumnBatch
        from paimon_tpu.table.write import TableWrite

        fresh, _, _ = a0.keygen.take(set(owned0), 16)
        ks = [k for b in owned0 for k in fresh[b]]
        tw = TableWrite(a0.table)
        tw.write(ColumnBatch.from_pydict(SCHEMA, {"k": ks, "v": [1.0] * len(ks)}))
        msgs = [m.to_dict() for m in tw.prepare_commit()]
        tw.close()
        # reassign w0's range while the ship is "in flight"
        with coord._lock:
            coord._reassign_dead(coord._slots[0])
        r = coord.handle(
            "ship_commit",
            {"worker": 0, "epoch": epoch0, "ident": 99, "kind": "append", "messages": msgs},
        )
        assert r["stale"] and r["sid"] is None
        assert store.snapshot_manager.latest_snapshot_id() == sid_before
        a0.close()
    finally:
        coord.close()


def test_killed_worker_releases_debt_charges(cluster_table):
    """Failover edge: a worker killed mid-round (admitted, never shipped)
    must not leave its debt-gate charges blocking rivals at the ceiling."""
    g = cluster_metrics()
    coord = _coordinator(cluster_table, workers=2, compaction=True)
    try:
        coord.handle("register", {"worker": 0, "incarnation": 0})
        coord.handle("register", {"worker": 1, "incarnation": 0})
        assert coord.handle("admit", {"worker": 0, "ident": 1, "buckets": [0, 1]})["admitted"]
        svc = coord.compaction
        with svc._runs_cond:
            assert svc._inflight  # charges held
        before = g.counter("charges_released").count
        with coord._lock:
            coord._reassign_dead(coord._slots[0])
        with svc._runs_cond:
            assert not svc._inflight  # released with the death
        assert g.counter("charges_released").count - before == 2
        assert (0, 1) not in coord._admit_charges
    finally:
        coord.close()


def test_worker_killed_mid_compaction_releases_task_marks(cluster_table, tmp_path):
    """A compaction decision dispatched to a worker that dies must be
    re-dispatchable after the death (inflight mark dropped), and the dead
    worker's queued tasks vanish."""
    from paimon_tpu.table.compactor import CompactionDecision

    coord = _coordinator(cluster_table, workers=2, compaction=True)
    try:
        coord.handle("register", {"worker": 0, "incarnation": 0})
        coord.handle("register", {"worker": 1, "incarnation": 0})
        wid = coord._owner[0]
        d = CompactionDecision((), 0, False, "hot", 3)
        assert coord._dispatch_group([d], False) == 1
        assert coord._compact_inflight  # marked in flight
        assert coord._dispatch_group([d], False) == 0  # no double dispatch
        with coord._lock:
            coord._reassign_dead(coord._slots[wid])
        assert not coord._compact_inflight
        # the bucket has a live owner again: re-decidable
        assert coord._dispatch_group([d], False) == 1
    finally:
        coord.close()


# ---------------------------------------------------------------------------
# ingest + compaction + recovery (in-process agents)
# ---------------------------------------------------------------------------
def test_ingest_rounds_commit_through_coordinator(cluster_table, tmp_path):
    coord = _coordinator(cluster_table, workers=2)
    agents = []
    try:
        agents = [_agent(cluster_table, coord, w, tmp_path) for w in range(2)]
        for a in agents:
            a.start_heartbeats()
        for _ in range(3):
            for a in agents:
                assert a.ingest_round()
            for a in agents:
                a.poll_and_compact()
        store = load_table(cluster_table, commit_user="check").store
        latest = store.snapshot_manager.latest_snapshot()
        assert latest is not None
        # every APPEND snapshot was committed by the coordinator's
        # per-worker handle, none by a worker process itself
        rb = load_table(cluster_table, commit_user="check").new_read_builder()
        out = rb.new_read().read_all(rb.new_scan().plan())
        expect = {k for a in agents for ks in a.landed_by_bucket.values() for k in ks}
        assert set(out.column("k").values.tolist()) == expect
    finally:
        for a in agents:
            a.close()
        coord.close()


def test_journal_recovery_resolves_landed_unacked(cluster_table, tmp_path):
    """Kill between the coordinator's commit and the worker's ack: the next
    incarnation must adopt the landed round from the snapshot chain (a
    `recovered` journal record), never replay it."""
    from paimon_tpu.service.proc_soak import WriterJournal

    coord = _coordinator(cluster_table, workers=1, compaction=False)
    try:
        a0 = _agent(cluster_table, coord, 0, tmp_path)
        assert a0.ingest_round()
        epoch, owned = a0.assignment()
        # round 2: ship lands at the coordinator, but the "worker" dies
        # before journaling the ack — simulate by writing the intent and
        # shipping, then dropping the ack on the floor
        from paimon_tpu.data.batch import ColumnBatch
        from paimon_tpu.table.write import TableWrite

        ident = a0.next_ident
        fresh, start, span = a0.keygen.take(set(owned), 8)
        rows = {k: 7.0 for b in owned for k in fresh[b]}
        a0.journal.intent(ident, start, span, rows)
        tw = TableWrite(a0.table)
        tw.write(ColumnBatch.from_pydict(SCHEMA, {"k": list(rows), "v": list(rows.values())}))
        msgs = [m.to_dict() for m in tw.prepare_commit()]
        tw.close()
        r = coord.handle(
            "ship_commit",
            {"worker": 0, "epoch": epoch, "ident": ident, "kind": "append", "messages": msgs},
        )
        assert r["sid"] is not None
        a0.close()  # no ack written: the incarnation is gone
        # next incarnation recovers the landed round from the chain
        a1 = _agent(cluster_table, coord, 0, tmp_path, incarnation=1)
        assert a1.recovered == 1
        events = WriterJournal.read(str(tmp_path / "journal-0.jsonl"))
        assert any(e["t"] == "recovered" and e["ident"] == ident for e in events)
        # the adopted keys are update candidates, not re-minted
        assert set(rows) <= {k for ks in a1.landed_by_bucket.values() for k in ks}
        assert a1.next_ident == ident + 1
        a1.close()
    finally:
        coord.close()


def test_cluster_compaction_drains_read_amp(cluster_table, tmp_path):
    """Coordinator-scheduled, worker-executed drain: sustained write-only
    ingest piles L0 runs; the dispatched compactions must bring every
    bucket's sorted-run count back under the ceiling."""
    coord = _coordinator(cluster_table, workers=1, compaction=True)
    a0 = None
    try:
        a0 = _agent(cluster_table, coord, 0, tmp_path)
        a0.start_heartbeats()
        for _ in range(8):
            assert a0.ingest_round()
            a0.poll_and_compact()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            a0.poll_and_compact()
            shapes = coord.compaction.observe()
            if shapes and max(s.runs for s in shapes) <= 3:
                break
            time.sleep(0.2)
        assert shapes and max(s.runs for s in shapes) <= coord.compaction.policy.read_amp_ceiling
        assert cluster_metrics().counter("compact_commits").count > 0
    finally:
        if a0 is not None:
            a0.close()
        coord.close()


# ---------------------------------------------------------------------------
# serving plane: routed gets, routed subscriptions, distributed joins
# ---------------------------------------------------------------------------
def test_routed_get_batch_and_subscribe_parity(cluster_table, tmp_path):
    from paimon_tpu.table.query import LocalTableQuery

    coord = _coordinator(cluster_table, workers=2, compaction=False)
    agents, cli = [], None
    try:
        agents = [_agent(cluster_table, coord, w, tmp_path, serve=True) for w in range(2)]
        for a in agents:
            a.start_heartbeats()
        for _ in range(2):
            for a in agents:
                assert a.ingest_round()
        cli = ClusterClient(load_table(cluster_table, commit_user="cli"), coord.host, coord.port)
        keys = [k for a in agents for ks in a.landed_by_bucket.values() for k in ks[:4]]
        keys.append(10**9)  # absent
        oracle = LocalTableQuery(load_table(cluster_table, commit_user="oracle"))
        want = []
        for k in keys:
            d = oracle.lookup((), (k,))
            want.append(None if d is None else tuple(d.to_pylist()[0]))
        # serving is refresh-driven (the worker's followed query catches the
        # last commit through its subscription): poll until converged
        deadline = time.monotonic() + 20.0
        rows = cli.get_batch(keys)
        while rows != want and time.monotonic() < deadline:
            time.sleep(0.2)
            rows = cli.get_batch(keys)
        assert rows == want
        assert cluster_metrics().counter("serve_gets").count > 0
        # routed subscription: per-worker bucket-filtered folds union to the scan
        rb = load_table(cluster_table, commit_user="scan").new_read_builder()
        out = rb.new_read().read_all(rb.new_scan().plan())
        scan = dict(zip(out.column("k").values.tolist(), out.column("v").values.tolist()))
        subs = cli.subscribe(from_snapshot=1)
        assert len(subs) == 2  # one per owning worker
        fold = {}
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and set(fold) != set(scan):
            for _wid, h in subs:
                for kind, k, v in h.poll(timeout_ms=250).get("rows", []):
                    if kind in ("+I", "+U"):
                        fold[k] = v
                    elif kind == "-D":
                        fold.pop(k, None)
        assert fold == scan
        for _wid, h in subs:
            h.close()
    finally:
        if cli is not None:
            cli.close()
        for a in agents:
            a.close()
        coord.close()


def test_distributed_join_partitions_parity(cluster_table):
    """Satellite (PR 12 follow-up): the JSPIM skew split spans workers —
    partition kernels route through the worker/bucket assignment and the
    result is bit-identical to the single-process join."""
    from paimon_tpu.data.batch import ColumnBatch
    from paimon_tpu.ops.join import join_batches, partition_executor
    from paimon_tpu.types import BIGINT, RowType

    coord = _coordinator(cluster_table, workers=2, compaction=False)
    agents, cli = [], None
    try:
        agents = [_agent(cluster_table, coord, w, serve=True) for w in range(2)]
        rng = np.random.default_rng(11)
        n, m = 6000, 800
        lk = rng.integers(0, 900, n).astype(np.int64)
        lk[: n // 2] = 17  # heavy hitter
        left = ColumnBatch.from_pydict(
            RowType.of(("id", BIGINT()), ("x", BIGINT())),
            {"id": lk, "x": np.arange(n, dtype=np.int64)},
        )
        right = ColumnBatch.from_pydict(
            RowType.of(("id", BIGINT()), ("y", BIGINT())),
            {"id": np.arange(m, dtype=np.int64), "y": np.arange(m, dtype=np.int64) * 3},
        )
        opts = {"join.partitions": 4, "join.skew-factor": 0.3}
        local = join_batches(left, right, ["id"], ["id"], options=opts)
        cli = ClusterClient(load_table(cluster_table, commit_user="cli"), coord.host, coord.port)
        before = cluster_metrics().counter("join_parts_served").count
        with partition_executor(cli.partition_executor()):
            dist = join_batches(left, right, ["id"], ["id"], options=opts)
        assert np.array_equal(local.left_take, dist.left_take)
        assert np.array_equal(local.right_take, dist.right_take)
        assert dist.stats["skew_keys"] >= 1  # the split really spanned workers
        assert cluster_metrics().counter("join_parts_served").count - before == 4
    finally:
        if cli is not None:
            cli.close()
        for a in agents:
            a.close()
        coord.close()


# ---------------------------------------------------------------------------
# satellite 2: subscription-driven refresh of LocalTableQuery
# ---------------------------------------------------------------------------
def test_follow_refresh_matches_manual_refresh(cluster_table):
    from paimon_tpu.core.manifest import ManifestCommittable
    from paimon_tpu.table.query import LocalTableQuery
    from paimon_tpu.table.write import TableWrite

    t = load_table(cluster_table, commit_user="w")

    def commit(ident, ks):
        tw = TableWrite(t)
        tw.write({"k": ks, "v": [float(k) * 2 for k in ks]})
        msgs = tw.prepare_commit()
        tw.close()
        t.store.new_commit().commit(ManifestCommittable(ident, messages=msgs))

    commit(1, list(range(200)))
    from paimon_tpu.service.subscription import SubscriptionHub

    hub = SubscriptionHub.for_table(t)
    followed = LocalTableQuery(t).follow(hub=hub)
    try:
        commit(2, list(range(200, 260)))
        deadline = time.monotonic() + 15.0
        served = None
        while time.monotonic() < deadline:
            served = followed.get_batch([(205,)]).to_pylist()[0]
            if served is not None:
                break
            time.sleep(0.1)
        manual = LocalTableQuery(t)  # fresh build == manual refresh
        assert served == manual.get_batch([(205,)]).to_pylist()[0] == (205, 410.0)
    finally:
        followed.unfollow()
        hub.close()


def test_follow_refresh_rebuilds_only_touched_buckets(cluster_table):
    """The follower rides refresh()'s per-bucket diff: a commit touching one
    bucket must leave the other buckets' probe indexes untouched (object
    identity), while the touched bucket rebuilds."""
    from paimon_tpu.core.manifest import ManifestCommittable
    from paimon_tpu.service.subscription import SubscriptionHub
    from paimon_tpu.table.query import LocalTableQuery
    from paimon_tpu.table.write import TableWrite

    t = load_table(cluster_table, commit_user="w")
    tw = TableWrite(t)
    tw.write({"k": list(range(400)), "v": [0.0] * 400})
    t.store.new_commit().commit(ManifestCommittable(1, messages=tw.prepare_commit()))
    tw.close()
    hub = SubscriptionHub.for_table(t)
    q = LocalTableQuery(t).follow(hub=hub)
    try:
        ids_before = {pb: id(ix) for pb, ix in q._get_indexes.items()}
        assert len(ids_before) == 4
        # find keys of exactly one bucket and commit only them
        pools = bucket_key_pools(4, 1000, 32)
        target_keys = pools[2].tolist()
        tw = TableWrite(t)
        tw.write({"k": target_keys, "v": [9.0] * len(target_keys)})
        t.store.new_commit().commit(ManifestCommittable(2, messages=tw.prepare_commit()))
        tw.close()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if q.get_batch([(target_keys[0],)]).to_pylist()[0] is not None:
                break
            time.sleep(0.1)
        ids_after = {pb: id(ix) for pb, ix in q._get_indexes.items()}
        assert ids_after[((), 2)] != ids_before[((), 2)]  # touched: rebuilt
        for pb in ids_before:
            if pb != ((), 2):
                assert ids_after[pb] == ids_before[pb]  # untouched: kept warm
    finally:
        q.unfollow()
        hub.close()


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------
def test_bucket_key_pools_deterministic_and_routed(cluster_table):
    from paimon_tpu.data.batch import ColumnBatch
    from paimon_tpu.table.bucket import bucket_ids
    from paimon_tpu.types import BIGINT, RowType

    a = bucket_key_pools(4, 0, 50)
    b = bucket_key_pools(4, 0, 50)
    rt = RowType.of(("k", BIGINT()))
    for bucket in range(4):
        assert np.array_equal(a[bucket], b[bucket])
        assert len(a[bucket]) == 50
        routed = bucket_ids(
            ColumnBatch.from_pydict(rt, {"k": a[bucket]}), ["k"], 4
        )
        assert (routed == bucket).all()


# ---------------------------------------------------------------------------
# the multi-process mini soak (bounded; the 45 s stage soak lives in
# scripts/verify.sh cluster)
# ---------------------------------------------------------------------------
def test_cluster_mini_soak_multiprocess(tmp_path):
    cfg = ClusterConfig(
        workers=2,
        devices_per_worker=2,
        buckets=4,
        duration_s=10.0,
        readers=1,
        round_rows=48,
        scripted_kills=("flush:files-written:2:kill",),
        kill_period_s=0.0,  # scripted only: deterministic and bounded
        sweep_period_s=0.0,
        seed=3,
    )
    report = run_cluster_soak(str(tmp_path), cfg)
    assert report["consistent"], report
    assert report["procs_killed"] >= 1, report
    assert report["accepted_commits"] > 0
    assert report["lost_rows"] == 0 and report["duplicated_rows"] == 0
    assert report["leaked_file_count"] == 0
    assert report["read_errors"] == 0
