"""Native vectorized parquet page-encode subsystem (paimon_tpu.encode).

Covers the layers and the wiring, dual to test_decode.py:
  * kernels — pack/RLE/delta/byte-array encoders pinned to the DECODE
    kernels as oracles (what one side writes the other must read back),
    plus jax-vs-numpy pack parity;
  * roundtrip — randomized native-encode → (a) native decoder and
    (b) pyarrow pq.read_table, bit-identical across encodings ×
    compressions × null-rates × page versions (long corpus sweep is
    `slow`);
  * stats — natively-written row-group statistics must prune under BOTH
    the existing arrow predicate skip and the decode pushdown gate;
  * wiring — `format.parquet.encoder = native` through table writes,
    flush + compaction (incl. the pipelined paths), per-file arrow
    fallback on unsupported shapes, encoder coverage in the data-file
    cache-key identity test, and dictionary-page pool reuse that never
    materializes a key string.
"""

import io as _io
import os

import numpy as np
import pytest

import paimon_tpu as pt
from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.data import predicate as P
from paimon_tpu.data.batch import Column, ColumnBatch, concat_batches
from paimon_tpu.data.keys import build_string_pool, encode_key_lanes
from paimon_tpu.decode import UnsupportedParquetFeature, read_native
from paimon_tpu.decode import kernels as dk
from paimon_tpu.decode.container import (
    T_BOOLEAN,
    T_BYTE_ARRAY,
    T_INT32,
    T_INT64,
    parse_footer,
)
from paimon_tpu.encode import encode_parquet_bytes, write_native
from paimon_tpu.encode import kernels as ek
from paimon_tpu.format.parquet import ParquetFormat
from paimon_tpu.fs import LocalFileIO
from paimon_tpu.metrics import encode_metrics
from paimon_tpu.types import ArrayType

IO = LocalFileIO()

FULL_SCHEMA = pt.RowType.of(
    ("i8", pt.TINYINT()),
    ("i16", pt.SMALLINT()),
    ("i32", pt.INT()),
    ("i64", pt.BIGINT()),
    ("f32", pt.FLOAT()),
    ("f64", pt.DOUBLE()),
    ("b", pt.BOOLEAN()),
    ("s", pt.STRING()),
    ("y", pt.BYTES()),
    ("dt", pt.DATE()),
    ("ts", pt.TIMESTAMP()),
)


def _random_batch(rng, n, null_rate=0.15, schema=FULL_SCHEMA, distinct=50):
    def nullify(vals):
        if null_rate == 0:
            return list(vals)
        mask = rng.random(n) < null_rate
        return [None if m else v for v, m in zip(vals, mask)]

    gens = {
        "i8": lambda: nullify(int(x) for x in rng.integers(-128, 128, n)),
        "i16": lambda: nullify(int(x) for x in rng.integers(-1000, 1000, n)),
        "i32": lambda: nullify(int(x) for x in rng.integers(-(2**31), 2**31, n)),
        "i64": lambda: nullify(int(x) for x in rng.integers(-(2**62), 2**62, n)),
        "f32": lambda: nullify(float(x) for x in rng.integers(0, distinct, n)),
        "f64": lambda: nullify(float(x) * 0.5 for x in rng.integers(0, 10**6, n)),
        "b": lambda: nullify(bool(x) for x in rng.integers(0, 2, n)),
        "s": lambda: nullify(f"val-{int(x) % distinct:04d}" for x in rng.integers(0, 10**4, n)),
        "y": lambda: nullify(bytes([int(x) % 251]) * (int(x) % 7) for x in rng.integers(0, 255, n)),
        "dt": lambda: nullify(int(x) for x in rng.integers(0, 20000, n)),
        "ts": lambda: nullify(int(x) for x in rng.integers(0, 2**45, n)),
    }
    return ColumnBatch.from_pydict(schema, {f.name: gens[f.name]() for f in schema.fields})


def _roundtrip_both(tmp_path, batch, schema, compression="zstd", **opts):
    """Native-encode, then read back via (a) the native decoder and (b)
    pyarrow; assert both match the source bit-for-bit."""
    import pyarrow.parquet as pq

    raw = encode_parquet_bytes(batch, compression, opts)
    path = str(tmp_path / "rt.parquet")
    with open(path, "wb") as f:
        f.write(raw)
    via_arrow = ColumnBatch.from_arrow(pq.read_table(_io.BytesIO(raw)), schema)
    assert via_arrow.to_pydict() == batch.to_pydict(), "pyarrow read mismatch"
    parts = read_native(IO, path, schema)
    via_native = concat_batches(parts) if parts else ColumnBatch.empty(schema)
    assert via_native.to_pydict() == batch.to_pydict(), "native decode mismatch"
    return raw


# ---------------------------------------------------------------------------
# kernels (decode kernels are the oracles)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("width", [1, 2, 3, 5, 7, 8, 12, 17, 24, 31])
def test_pack_bits_roundtrip(width, rng):
    vals = rng.integers(0, 2**width, 117).astype(np.uint64)
    packed = np.frombuffer(ek.pack_bits(vals, width), dtype=np.uint8)
    out = dk.unpack_bits(packed, width, len(vals))
    assert out.tolist() == vals.tolist()


@pytest.mark.parametrize("width", [1, 3, 8, 13, 20, 32])
def test_pack_bits_jax_matches_numpy(width, rng):
    vals = rng.integers(0, 2**min(width, 31), 200).astype(np.uint64)
    a = ek.pack_bits(vals, width)
    b = bytes(np.asarray(ek.pack_bits_jax(vals, width)))
    assert a == b


@pytest.mark.parametrize(
    "maker",
    [
        lambda rng, n: rng.integers(0, 7, n),  # random: mostly bit-packed
        lambda rng, n: np.zeros(n, dtype=np.int64),  # constant: one RLE run
        lambda rng, n: np.repeat(rng.integers(0, 5, max(n // 9, 1)), 9)[:n],  # long runs
        lambda rng, n: np.concatenate(  # mixed short + long
            [np.repeat(rng.integers(0, 3, 1), 20), rng.integers(0, 3, n)]
        )[:n],
    ],
)
@pytest.mark.parametrize("n", [1, 7, 8, 23, 1000])
def test_rle_hybrid_roundtrip(maker, n, rng):
    vals = np.ascontiguousarray(maker(rng, n), dtype=np.int64)[:n]
    width = max(ek.bit_width_for(int(vals.max())), 1) if len(vals) else 1
    enc = ek.encode_rle_hybrid(vals, width)
    out = dk.decode_rle_hybrid(enc, 0, len(enc), width, len(vals))
    assert out.tolist() == vals.tolist()


def test_rle_hybrid_width_zero_single_entry_domain():
    vals = np.zeros(37, dtype=np.int64)
    enc = ek.encode_rle_hybrid(vals, 0)
    out = dk.decode_rle_hybrid(enc, 0, len(enc), 0, 37)
    assert out.tolist() == [0] * 37


@pytest.mark.parametrize("physical", [T_INT32, T_INT64])
@pytest.mark.parametrize("n", [1, 2, 63, 64, 1023, 1024, 1025, 5000])
def test_delta_binary_packed_roundtrip(physical, n, rng):
    lo, hi = (-(2**30), 2**30) if physical == T_INT32 else (-(2**61), 2**61)
    vals = np.sort(rng.integers(lo, hi, n))
    if physical == T_INT32:
        vals = vals.astype(np.int32).astype(np.int64)
    enc = ek.encode_delta_binary_packed(vals, physical)
    out = dk.decode_delta_binary_packed(enc, 0, n, physical)
    assert out.tolist() == vals.tolist()


def test_delta_binary_packed_unsorted_and_negative(rng):
    vals = rng.integers(-(2**40), 2**40, 3000)  # delta is valid for ANY ints
    enc = ek.encode_delta_binary_packed(vals, T_INT64)
    out = dk.decode_delta_binary_packed(enc, 0, len(vals), T_INT64)
    assert out.tolist() == vals.tolist()


def test_plain_byte_array_stream_matches_decoder(rng):
    values = [f"v-{i % 13}-{'x' * (i % 5)}" for i in range(200)]
    lens, payload = ek.byte_array_parts(np.array(values, dtype=object))
    stream = ek.encode_plain_byte_array(lens, payload)
    out = dk.decode_plain(stream, 0, T_BYTE_ARRAY, len(values), utf8=True)
    assert out.tolist() == values


def test_byte_array_parts_unicode_and_nul_fallback():
    uni = np.array(["π", "日本語", "a", ""], dtype=object)
    lens, payload = ek.byte_array_parts(uni)
    assert lens.tolist() == [2, 9, 1, 0]
    assert payload == "π日本語a".encode("utf-8")
    nul = np.array(["a\x00b", "c"], dtype=object)  # S-dtype would trim: loop path
    lens, payload = ek.byte_array_parts(nul)
    assert lens.tolist() == [3, 1] and payload == b"a\x00bc"
    raw = np.array([b"ab\x00", b"", b"q"], dtype=object)  # bytes keep trailing NUL
    lens, payload = ek.byte_array_parts(raw)
    assert lens.tolist() == [3, 0, 1] and payload == b"ab\x00q"


def test_plain_boolean_roundtrip(rng):
    vals = rng.integers(0, 2, 43).astype(np.bool_)
    enc = ek.encode_plain_boolean(vals)
    out = dk.decode_plain(enc, 0, T_BOOLEAN, len(vals))
    assert out.tolist() == vals.tolist()


# ---------------------------------------------------------------------------
# file roundtrips
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("compression", ["zstd", "snappy", None])
@pytest.mark.parametrize("page_version", ["1.0", "2.0"])
def test_roundtrip_compressions_and_page_versions(tmp_path, rng, compression, page_version):
    batch = _random_batch(rng, 1200)
    _roundtrip_both(
        tmp_path,
        batch,
        FULL_SCHEMA,
        compression,
        **{"parquet.page-size": "2048", "parquet.data-page-version": page_version},
    )


@pytest.mark.parametrize("null_rate", [0.0, 0.5, 1.0])
def test_roundtrip_null_rates(tmp_path, rng, null_rate):
    batch = _random_batch(rng, 800, null_rate=null_rate)
    _roundtrip_both(tmp_path, batch, FULL_SCHEMA, **{"parquet.page-size": "1024"})


def test_roundtrip_dictionary_disabled(tmp_path, rng):
    batch = _random_batch(rng, 600)
    raw = _roundtrip_both(
        tmp_path, batch, FULL_SCHEMA, **{"parquet.enable.dictionary": "false"}
    )
    footer = parse_footer(raw)
    assert not footer.row_groups[0].columns["s"].has_dictionary


def test_roundtrip_multi_row_group_and_zstd_level(tmp_path, rng):
    batch = _random_batch(rng, 3000, null_rate=0.05)
    raw = _roundtrip_both(
        tmp_path,
        batch,
        FULL_SCHEMA,
        **{"parquet.row-group.rows": "700", "file.compression.zstd-level": "5"},
    )
    assert len(parse_footer(raw).row_groups) == 5


def test_roundtrip_empty_and_single_row(tmp_path, rng):
    schema = pt.RowType.of(("a", pt.BIGINT()), ("s", pt.STRING()))
    _roundtrip_both(tmp_path, ColumnBatch.from_pydict(schema, {"a": [3], "s": ["x"]}), schema)
    empty = ColumnBatch.from_pydict(schema, {"a": [], "s": []})
    raw = encode_parquet_bytes(empty, "zstd", {})
    import pyarrow.parquet as pq

    t = pq.read_table(_io.BytesIO(raw))
    assert t.num_rows == 0 and t.column_names == ["a", "s"]


def test_sorted_int_columns_use_delta(tmp_path):
    schema = pt.RowType.of(("k", pt.BIGINT(False)), ("d", pt.INT()))
    batch = ColumnBatch.from_pydict(
        schema, {"k": list(range(5000)), "d": sorted(int(x) % 997 for x in range(5000))}
    )
    raw = _roundtrip_both(tmp_path, batch, schema)
    from paimon_tpu.decode.container import ENC_DELTA_BINARY_PACKED

    footer = parse_footer(raw)
    for name in ("k", "d"):
        assert ENC_DELTA_BINARY_PACKED in footer.row_groups[0].columns[name].encodings


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(24))
def test_roundtrip_corpus_sweep(tmp_path, seed):
    """Wide seeded sweep (dual of the PR 2 decode corpus): every seed picks
    its own size / null rate / distinct count / page size / compression /
    page version, and must round-trip bit-identically through BOTH readers."""
    rng = np.random.default_rng(9000 + seed)
    n = int(rng.integers(1, 4000))
    null_rate = float(rng.choice([0.0, 0.05, 0.3, 0.9]))
    distinct = int(rng.choice([1, 3, 50, 5000]))
    batch = _random_batch(rng, n, null_rate=null_rate, distinct=distinct)
    opts = {
        "parquet.page-size": str(int(rng.choice([512, 2048, 65536]))),
        "parquet.data-page-version": str(rng.choice(["1.0", "2.0"])),
        "parquet.enable.dictionary": str(rng.choice(["true", "false"])),
    }
    compression = rng.choice(["zstd", "snappy", None])
    _roundtrip_both(tmp_path, batch, FULL_SCHEMA, compression, **opts)


# ---------------------------------------------------------------------------
# dictionary pool reuse (the merge-path fast lane)
# ---------------------------------------------------------------------------


def test_dict_cache_pool_reuse_never_touches_key_strings(tmp_path, monkeypatch):
    schema = pt.RowType.of(("k", pt.STRING(False)), ("v", pt.BIGINT()))
    keys = [f"key-{i:05d}" for i in range(2000)]
    batch = ColumnBatch.from_pydict(schema, {"k": keys, "v": list(range(2000))})
    kcol = batch.column("k")
    pool = build_string_pool([kcol.values])
    encode_key_lanes(batch, ["k"], {"k": pool})
    assert kcol.dict_cache is not None

    touched = []
    orig = Column.values
    monkeypatch.setattr(
        Column, "values", property(lambda self: touched.append(self) or orig.fget(self))
    )
    g = encode_metrics()
    d0 = g.counter("dict_pages").count
    raw = encode_parquet_bytes(batch, "zstd", {}, metrics=g)
    assert kcol not in touched, "pool-reuse encode must not rematerialize key strings"
    assert g.counter("dict_pages").count == d0 + 1
    monkeypatch.undo()

    footer = parse_footer(raw)
    assert footer.row_groups[0].columns["k"].has_dictionary
    import pyarrow.parquet as pq

    assert pq.read_table(_io.BytesIO(raw)).column("k").to_pylist() == keys


def test_dict_cache_survives_structural_ops():
    schema = pt.RowType.of(("k", pt.STRING(False)),)
    batch = ColumnBatch.from_pydict(schema, {"k": [f"a{i % 7}" for i in range(50)]})
    col = batch.column("k")
    pool = build_string_pool([col.values])
    encode_key_lanes(batch, ["k"], {"k": pool})
    taken = col.take(np.array([4, 9, 11]))
    sliced = col.slice(5, 20)
    filtered = col.filter(np.arange(50) % 2 == 0)
    for derived in (taken, sliced, filtered):
        dpool, codes = derived.dict_cache
        assert dpool is pool
        assert (dpool[codes] == derived.values).all()
    assert Column.concat([taken, sliced]).dict_cache is None  # pools differ per merge


# ---------------------------------------------------------------------------
# statistics / pruning
# ---------------------------------------------------------------------------


def test_native_stats_prune_row_groups_under_arrow_predicate_skip(tmp_path):
    schema = pt.RowType.of(("k", pt.BIGINT()), ("v", pt.DOUBLE()))
    batch = ColumnBatch.from_pydict(
        schema, {"k": list(range(10000)), "v": [float(i) for i in range(10000)]}
    )
    path = str(tmp_path / "stats.parquet")
    write_native(IO, path, batch, "zstd", {"parquet.row-group.rows": "1000"})
    pred = P.PredicateBuilder(schema).between("k", 2500, 2600)
    # the EXISTING arrow read path (format/parquet.py::_row_group_stats)
    # must trust the native writer's statistics and open only one group
    parts = list(ParquetFormat().read(IO, path, schema, predicate=pred))
    assert sum(p.num_rows for p in parts) == 1000
    # and the decode subsystem's chunk-stats gate must prune identically
    native = concat_batches(read_native(IO, path, schema, predicate=pred))
    assert native.num_rows == 1000
    assert native.column("k").values.min() == 2000


def test_native_string_stats_prune(tmp_path):
    schema = pt.RowType.of(("s", pt.STRING()), ("v", pt.BIGINT()))
    batch = ColumnBatch.from_pydict(
        schema,
        {"s": [f"g{i // 1000}-{i:05d}" for i in range(4000)], "v": list(range(4000))},
    )
    path = str(tmp_path / "sstats.parquet")
    write_native(IO, path, batch, "zstd", {"parquet.row-group.rows": "1000"})
    pred = P.PredicateBuilder(schema).equal("s", "g2-02042")
    parts = list(ParquetFormat().read(IO, path, schema, predicate=pred))
    assert sum(p.num_rows for p in parts) == 1000
    rows = concat_batches(parts)
    assert rows.column("v").values.min() == 2000


def test_long_string_stats_are_omitted_not_wrong(tmp_path):
    schema = pt.RowType.of(("s", pt.STRING()),)
    batch = ColumnBatch.from_pydict(schema, {"s": ["z" * 100, "a" * 100]})
    path = str(tmp_path / "long.parquet")
    write_native(IO, path, batch, None, {})
    footer = parse_footer(IO.read_bytes(path))
    st = footer.row_groups[0].columns["s"].stats
    assert 5 not in st and 6 not in st  # >=64-byte min/max omitted (trust limit)
    pred = P.PredicateBuilder(schema).equal("s", "z" * 100)
    got = concat_batches(list(ParquetFormat().read(IO, path, schema, predicate=pred)))
    assert got.num_rows == 2  # nothing wrongly pruned


# ---------------------------------------------------------------------------
# wiring: format option, fallback, table writes, cache identity
# ---------------------------------------------------------------------------

TBL_SCHEMA = pt.RowType.of(("k", pt.BIGINT()), ("s", pt.STRING()), ("v", pt.DOUBLE()))


def _write_table(table, keys, step):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write(
        {
            "k": list(keys),
            "s": [f"s{int(k) % 5}" for k in keys],
            "v": [float(step) + float(k) / 1000 for k in keys],
        }
    )
    wb.new_commit().commit(w.prepare_commit())


def _read_rows(table, predicate=None):
    rb = table.new_read_builder()
    if predicate is not None:
        rb = rb.with_filter(predicate)
    return sorted(rb.new_read().read_all(rb.new_scan().plan()).to_pylist())


def test_format_write_routes_through_native_encoder(tmp_path, rng):
    batch = _random_batch(rng, 500)
    path = str(tmp_path / "fmt.parquet")
    g = encode_metrics()
    n0, f0 = g.counter("files_native").count, g.counter("files_fallback").count
    ParquetFormat().write(
        IO, path, batch, format_options={"format.parquet.encoder": "native"}
    )
    assert g.counter("files_native").count == n0 + 1
    assert g.counter("files_fallback").count == f0
    got = concat_batches(list(ParquetFormat().read(IO, path, FULL_SCHEMA)))
    assert got.to_pydict() == batch.to_pydict()


def test_unsupported_shapes_fall_back_per_file(tmp_path):
    schema = pt.RowType.of(("k", pt.BIGINT()), ("arr", ArrayType(pt.INT())))
    nested = ColumnBatch.from_pydict(schema, {"k": [1, 2], "arr": [[1], [2, 3]]})
    flat = ColumnBatch.from_pydict(
        pt.RowType.of(("k", pt.BIGINT())), {"k": [1, 2, 3]}
    )
    fmt = ParquetFormat()
    opts = {"format.parquet.encoder": "native"}
    g = encode_metrics()
    n0, f0 = g.counter("files_native").count, g.counter("files_fallback").count
    fmt.write(IO, str(tmp_path / "nested.parquet"), nested, format_options=opts)
    assert g.counter("files_fallback").count == f0 + 1, "nested must fall back"
    # fallback is per FILE: the next flat write on the same format instance
    # still encodes natively
    fmt.write(IO, str(tmp_path / "flat.parquet"), flat, format_options=opts)
    assert g.counter("files_native").count == n0 + 1
    got = concat_batches(list(ParquetFormat().read(IO, str(tmp_path / "nested.parquet"), schema)))
    assert got.to_pydict() == nested.to_pydict()


def test_native_encoder_through_table_write_and_compaction(tmp_warehouse):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="c")
    opts = {
        "bucket": "1",
        "num-sorted-run.compaction-trigger": "2",
        "cache.data-file.max-memory-size": "0 b",
    }
    arrow_t = cat.create_table("db.enc_a", TBL_SCHEMA, primary_keys=["k"], options=opts)
    native_t = cat.create_table(
        "db.enc_n",
        TBL_SCHEMA,
        primary_keys=["k"],
        options={**opts, "format.parquet.encoder": "native"},
    )
    g = encode_metrics()
    n0 = g.counter("files_native").count
    for step in range(4):  # trips compaction: rewrites encode natively too
        _write_table(arrow_t, range(step, 40 + step), step)
        _write_table(native_t, range(step, 40 + step), step)
    assert g.counter("files_native").count > n0
    assert _read_rows(native_t) == _read_rows(arrow_t)
    # natively-written files must ALSO decode natively (full dual stack)
    assert _read_rows(native_t.copy({"format.parquet.decoder": "native"})) == _read_rows(arrow_t)


@pytest.mark.parametrize("fmt_opts", [
    {"parquet.data-page-version": "2.0"},
    {"file.compression": "snappy"},
    {"parquet.enable.dictionary": "false", "parquet.page-size": "1024"},
])
def test_native_encoder_table_option_matrix(tmp_warehouse, fmt_opts):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="c")
    name = "db.m" + str(abs(hash(tuple(sorted(fmt_opts)))) % 10**6)
    t = cat.create_table(
        name,
        TBL_SCHEMA,
        primary_keys=["k"],
        options={
            "bucket": "1",
            "format.parquet.encoder": "native",
            "cache.data-file.max-memory-size": "0 b",
            **fmt_opts,
        },
    )
    for step in range(2):
        _write_table(t, range(30), step)
    rows = _read_rows(t)
    assert len(rows) == 30
    assert all(r[2] == pytest.approx(1.0 + r[0] / 1000) for r in rows)


def test_encoder_identity_in_data_file_cache_key(tmp_warehouse):
    """A natively-written file must not alias an arrow-written one in the
    decoded data-file cache: a table that toggles the encoder between
    commits keeps one cache entry per file and reads stay correct."""
    from paimon_tpu.utils.cache import data_file_cache

    cat = FileSystemCatalog(tmp_warehouse, commit_user="c")
    t = cat.create_table(
        "db.enc_ck",
        TBL_SCHEMA,
        primary_keys=["k"],
        options={"bucket": "1", "write-only": "true", "cache.data-file.max-memory-size": "64 mb"},
    )
    _write_table(t, range(30), 0)  # arrow-encoded file
    native_view = t.copy({"format.parquet.encoder": "native"})
    _write_table(native_view, range(20, 50), 1)  # native-encoded file
    expect = _read_rows(t.copy({"cache.data-file.max-memory-size": "0 b"}))
    before = len(data_file_cache())
    assert _read_rows(t) == expect
    after_first = len(data_file_cache())
    assert after_first > before, "both files must enter the cache"
    assert _read_rows(t) == expect  # warm hit: same entries, same rows
    assert len(data_file_cache()) == after_first, "re-read must not mint new entries"


# ---------------------------------------------------------------------------
# pipelined flush / compaction and faults (verify.sh stages run these)
# ---------------------------------------------------------------------------


def test_native_encoder_pipelined_flush_and_compaction(tmp_warehouse):
    """scripts/verify.sh pipeline: the PR 4 pipelined flush offload and the
    pipelined compaction rewrite must route their encodes through the native
    encoder when enabled — bit-identical to the arrow-encoded table."""
    cat = FileSystemCatalog(tmp_warehouse, commit_user="c")
    base = {
        "bucket": "1",
        "scan.prefetch-splits": "2",
        "num-sorted-run.compaction-trigger": "2",
        "write-buffer-rows": "64",  # force mid-commit auto-flushes (offloaded)
        "cache.data-file.max-memory-size": "0 b",
    }
    arrow_t = cat.create_table("db.pipe_a", TBL_SCHEMA, primary_keys=["k"], options=base)
    native_t = cat.create_table(
        "db.pipe_n",
        TBL_SCHEMA,
        primary_keys=["k"],
        options={**base, "format.parquet.encoder": "native"},
    )
    g = encode_metrics()
    n0 = g.counter("files_native").count
    for step in range(3):
        _write_table(arrow_t, range(step * 30, step * 30 + 150), step)
        _write_table(native_t, range(step * 30, step * 30 + 150), step)
    assert g.counter("files_native").count > n0, "pipelined flush must encode natively"
    assert _read_rows(native_t) == _read_rows(arrow_t)


def test_native_encoder_under_transient_faults(tmp_path):
    """scripts/verify.sh faults: native-encoded writes behind the retry
    stack — scripted write faults are absorbed, commits land, reads match."""
    from paimon_tpu.core.commit import ManifestCommittable
    from paimon_tpu.core.schema import SchemaManager
    from paimon_tpu.core.store import KeyValueFileStore
    from paimon_tpu.fs import get_file_io
    from paimon_tpu.fs.testing import FailingFileIO, FaultRule

    domain = "encfault"
    FailingFileIO.reset(domain, 0, 0)
    io = get_file_io(f"fail://{domain}/x")
    path = f"fail://{domain}{tmp_path}/table"
    schema = pt.RowType.of(("k", pt.BIGINT()), ("v", pt.DOUBLE()))
    ts = SchemaManager(io, path).create_table(
        schema,
        primary_keys=["k"],
        options={
            "bucket": "1",
            "format.parquet.encoder": "native",
            "fs.retry.initial-backoff": "1 ms",
            "cache.data-file.max-memory-size": "0 b",
        },
    )
    store = KeyValueFileStore(io, path, ts, commit_user="enc")
    g = encode_metrics()
    n0 = g.counter("files_native").count
    oracle = {}
    for round_ in range(1, 4):
        # fail the first data-file write of the round once: the retry layer
        # must re-drive the native encoder's write_bytes transparently
        FailingFileIO.schedule(domain, FaultRule(op="write", path="/bucket-0/data-"))
        ks = list(range(round_ * 3, round_ * 3 + 10))
        vs = [float(k) * 0.5 + round_ for k in ks]
        w = store.new_writer((), 0)
        w.write(ColumnBatch.from_pydict(store.value_schema, {"k": ks, "v": vs}))
        msg = w.prepare_commit()
        assert store.new_commit().commit(ManifestCommittable(round_, messages=[msg]))
        oracle.update(dict(zip(ks, vs)))
    FailingFileIO.reset(domain, 0, 0)
    assert g.counter("files_native").count > n0
    batch = store.read_bucket((), 0, store.restore_files((), 0))
    got = {r[0]: r[1] for r in batch.to_pylist()}
    assert got == oracle


# ---------------------------------------------------------------------------
# satellites: to_arrow nested fast path, metrics group
# ---------------------------------------------------------------------------


def test_to_arrow_nested_fast_path_parity():
    schema = pt.RowType.of(("k", pt.BIGINT()), ("arr", ArrayType(pt.INT())))
    no_nulls = ColumnBatch.from_pydict(schema, {"k": [1, 2, 3], "arr": [[1], [2, 3], []]})
    t = no_nulls.to_arrow()
    assert t.column("arr").to_pylist() == [[1], [2, 3], []]
    with_nulls = ColumnBatch.from_pydict(schema, {"k": [1, 2], "arr": [[7], None]})
    t2 = with_nulls.to_arrow()
    assert t2.column("arr").to_pylist() == [[7], None]
    # the masked path must not mutate the source column in place
    assert with_nulls.column("arr").values[1] is None or with_nulls.column("arr").values[0] == [7]


def test_encode_metric_group_members(tmp_path, rng):
    g = encode_metrics()
    before = {
        k: g.counter(k).count
        for k in ("pages_written", "bytes_written", "dict_pages", "files_native")
    }
    batch = _random_batch(rng, 400)
    write_native(IO, str(tmp_path / "m.parquet"), batch, "zstd", {"parquet.page-size": "1024"})
    assert g.counter("files_native").count == before["files_native"] + 1
    assert g.counter("pages_written").count > before["pages_written"]
    assert g.counter("bytes_written").count > before["bytes_written"]
    assert g.counter("dict_pages").count > before["dict_pages"]
    assert g.histogram("encode_ms").count > 0
    assert g.histogram("stats_ms").count > 0


def test_env_override_forces_native(tmp_path, rng, monkeypatch):
    monkeypatch.setenv("PAIMON_TPU_PARQUET_ENCODER", "native")
    g = encode_metrics()
    n0 = g.counter("files_native").count
    batch = _random_batch(rng, 100)
    ParquetFormat().write(IO, str(tmp_path / "env.parquet"), batch)  # no option set
    assert g.counter("files_native").count == n0 + 1


# ---------------------------------------------------------------------------
# numeric dictionary route (ISSUE 13, declared PR 12 follow-up)
# ---------------------------------------------------------------------------


def test_numeric_dictionary_route_roundtrip(tmp_path):
    """Low-cardinality int32/int64/date columns dictionary-encode natively:
    dict page + RLE_DICTIONARY codes, read back bit-identically by the
    native decoder, pyarrow AND the code-domain reader (so native-written
    files join fixed-width code-domain lookups/joins)."""
    import pyarrow.parquet as pq

    rng = np.random.default_rng(3)
    n = 2000
    schema = pt.RowType.of(
        ("k", pt.BIGINT()), ("c32", pt.INT()), ("c64", pt.BIGINT()), ("d", pt.DATE())
    )
    batch = ColumnBatch.from_pydict(
        schema,
        {
            "k": np.arange(n, dtype=np.int64),  # monotone: stays DELTA
            "c32": rng.integers(-50, 50, n).astype(np.int32),
            "c64": (rng.integers(0, 9, n) * 10_000).astype(np.int64),
            "d": rng.integers(18000, 18020, n).astype(np.int32),
        },
    )
    g = encode_metrics()
    d0 = g.counter("dict_pages").count
    path = str(tmp_path / "nd.parquet")
    write_native(IO, path, batch, "zstd", {})
    assert g.counter("dict_pages").count >= d0 + 3  # c32, c64, d
    # native decode parity
    got = concat_batches(read_native(IO, path, schema))
    for c in schema.field_names:
        assert np.array_equal(got.column(c).values, batch.column(c).values), c
    # pyarrow readback parity
    at = pq.read_table(path)
    for c in ("c32", "c64", "d"):
        assert at.column(c).to_pylist() == batch.column(c).values.tolist()
    # code-domain read: the fixed-width dict chunks come back code-backed
    coded = concat_batches(read_native(IO, path, schema, dict_domain=True))
    assert coded.column("c32").is_code_backed
    assert np.array_equal(coded.column("c32").values, batch.column("c32").values)
    pool, codes = coded.column("c64").dict_cache
    assert pool.dtype == np.int64 and np.array_equal(pool[codes], batch.column("c64").values)


def test_numeric_dictionary_route_skips_high_cardinality(tmp_path):
    """Unique-ish int columns must stay PLAIN/DELTA — a dictionary the size
    of the data would only add a page."""
    rng = np.random.default_rng(4)
    n = 1000
    schema = pt.RowType.of(("k", pt.BIGINT()), ("u", pt.BIGINT()))
    batch = ColumnBatch.from_pydict(
        schema,
        {"k": np.arange(n, dtype=np.int64), "u": rng.permutation(n).astype(np.int64) * 7 + 1},
    )
    g = encode_metrics()
    d0 = g.counter("dict_pages").count
    path = str(tmp_path / "hc.parquet")
    write_native(IO, path, batch, "none", {})
    assert g.counter("dict_pages").count == d0
    got = concat_batches(read_native(IO, path, schema))
    assert np.array_equal(got.column("u").values, batch.column("u").values)


def test_numeric_dictionary_route_with_nulls(tmp_path):
    rng = np.random.default_rng(5)
    n = 1500
    schema = pt.RowType.of(("k", pt.BIGINT()), ("c", pt.INT()))
    vals = rng.integers(0, 12, n).astype(np.int32)
    validity = rng.random(n) > 0.3
    col = Column(vals.copy(), validity.copy())
    batch = ColumnBatch(schema, {"k": Column(np.arange(n, dtype=np.int64)), "c": col})
    path = str(tmp_path / "nn.parquet")
    write_native(IO, path, batch, "zstd", {})
    got = concat_batches(read_native(IO, path, schema))
    gc = got.column("c")
    assert np.array_equal(gc.valid_mask(), validity)
    assert np.array_equal(gc.values[validity], vals[validity])
