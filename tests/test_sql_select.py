"""SELECT surface: SQL text -> merged rows through the Table API scan path,
with real pushdown (predicate file-skipping, projection decode-pruning,
LIMIT early-stop). Reference leaves SELECT to host engines; this is the
self-contained evaluator documented in sql/select.py."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.sql import execute, query
from paimon_tpu.sql.select import QueryError
from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType


@pytest.fixture
def cat(tmp_warehouse):
    c = FileSystemCatalog(tmp_warehouse, commit_user="sel")
    t = c.create_table(
        "db.t",
        RowType.of(("k", BIGINT(False)), ("v", BIGINT()), ("x", DOUBLE()), ("s", STRING())),
        primary_keys=["k"],
        options={"bucket": "1", "write-only": "true"},
    )
    # two overlapping runs: SELECT sees MERGED rows (upsert semantics), not
    # raw file contents
    for r in range(2):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        ids = np.arange(r * 50, 100 + r * 50, dtype=np.int64)
        w.write({"k": ids, "v": ids * (r + 1), "x": ids * 0.5, "s": [f"g{int(i) % 3}" for i in ids]})
        wb.new_commit().commit(w.prepare_commit())
    return c


def test_select_star_merges(cat):
    out = query(cat, "SELECT * FROM db.t")
    assert out.num_rows == 150
    rows = {r[0]: r[1] for r in out.to_pylist()}
    assert rows[75] == 150  # overlapped key: second commit won
    assert rows[25] == 25   # first-run-only key


def test_select_projection_where_order_limit(cat):
    out = query(cat, "SELECT k, v FROM db.t WHERE k >= 140 ORDER BY k DESC LIMIT 3")
    assert out.schema.field_names == ["k", "v"]
    assert [r[0] for r in out.to_pylist()] == [149, 148, 147]
    out = query(cat, "SELECT s, k FROM db.t WHERE s LIKE 'g1' AND k < 10 ORDER BY k")
    assert all(r[0] == "g1" for r in out.to_pylist())
    out = query(cat, "SELECT k FROM db.t LIMIT 7")
    assert out.num_rows == 7


def test_select_aggregates(cat):
    out = query(cat, "SELECT count(*), min(k), max(k), avg(v) FROM db.t WHERE k < 50")
    (row,) = out.to_pylist()
    assert row[0] == 50 and row[1] == 0 and row[2] == 49
    assert abs(row[3] - float(np.arange(50).mean())) < 1e-9
    out = query(cat, "SELECT sum(v) FROM db.t")
    total = sum(r[1] for r in query(cat, "SELECT k, v FROM db.t").to_pylist())
    assert out.to_pylist()[0][0] == total


def test_select_pushdown_skips_files(cat):
    # predicate pushdown reaches planning: k >= 140 lives only in run 2
    t = cat.get_table("db.t")
    rb = t.new_read_builder()
    n_all = sum(len(s.files) for s in rb.new_scan().plan())
    assert n_all == 2
    from paimon_tpu.sql.expr import parse_where

    rb2 = t.new_read_builder().with_filter(parse_where("k >= 140"))
    assert sum(len(s.files) for s in rb2.new_scan().plan()) == 1


def test_select_system_table(cat):
    out = query(cat, "SELECT * FROM db.t$snapshots")
    assert out.num_rows == 2  # two commits


def test_execute_dispatches_both_kinds(cat):
    assert execute(cat, "SELECT count(*) FROM db.t").to_pylist()[0][0] == 150
    got = execute(cat, "CALL sys.create_tag('db.t', 'sel-tag')")
    assert got["tag"] == "sel-tag"


def test_select_errors(cat):
    with pytest.raises(QueryError):
        query(cat, "SELECT nope FROM db.t")
    with pytest.raises(QueryError):
        query(cat, "SELECT k, count(*) FROM db.t")
    with pytest.raises(QueryError):
        query(cat, "DELETE FROM db.t")


def test_select_group_by(cat):
    out = query(cat, "SELECT s, count(*), sum(v), avg(x) FROM db.t GROUP BY s ORDER BY s")
    rows = out.to_pylist()
    assert [r[0] for r in rows] == ["g0", "g1", "g2"]
    # oracle over the merged table
    merged = query(cat, "SELECT s, v, x FROM db.t").to_pylist()
    import collections
    cnt = collections.Counter(r[0] for r in merged)
    sums = collections.defaultdict(int)
    xs = collections.defaultdict(list)
    for s, v, x in merged:
        sums[s] += v
        xs[s].append(x)
    for s, c, sv, ax in rows:
        assert c == cnt[s] and sv == sums[s]
        assert abs(ax - sum(xs[s]) / len(xs[s])) < 1e-9
    assert sum(r[1] for r in rows) == 150


def test_select_group_by_distinct_and_composite(cat):
    out = query(cat, "SELECT s FROM db.t GROUP BY s ORDER BY s")
    assert [r[0] for r in out.to_pylist()] == ["g0", "g1", "g2"]
    # composite grouping: (s, k % nothing) — use two real columns
    out = query(cat, "SELECT s, k, max(v) FROM db.t WHERE k < 6 GROUP BY s, k ORDER BY k")
    rows = out.to_pylist()
    assert len(rows) == 6  # k is unique, so (s, k) groups are singletons
    assert all(r[2] is not None for r in rows)
    with pytest.raises(QueryError, match="GROUP BY"):
        query(cat, "SELECT s, v FROM db.t GROUP BY s")
    with pytest.raises(QueryError, match="unknown"):
        query(cat, "SELECT count(*) FROM db.t GROUP BY nope")


def test_select_group_by_nulls_and_hidden_order(cat, tmp_warehouse):
    from paimon_tpu.types import BIGINT, STRING, RowType

    c2 = FileSystemCatalog(tmp_warehouse, commit_user="sel2")
    t = c2.create_table(
        "db.nulls",
        RowType.of(("k", np.int64 and BIGINT(False)), ("g", STRING()), ("v", BIGINT())),
        primary_keys=["k"], options={"bucket": "1"},
    )
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({"k": [1, 2, 3, 4, 5], "g": ["a", None, "a", None, "b"],
             "v": [10, 20, None, 40, None]})
    wb.new_commit().commit(w.prepare_commit())

    out = query(c2, "SELECT g, count(*), count(v), sum(v), min(v), avg(v) FROM db.nulls GROUP BY g")
    rows = {r[0]: r for r in out.to_pylist()}
    assert set(rows) == {"a", "b", None}
    assert rows["a"][1:] == (2, 1, 10, 10, 10.0)   # NULL v excluded everywhere
    assert rows[None][1:] == (2, 2, 60, 20, 30.0)  # NULL group key is its own group
    assert rows["b"][1:] == (1, 0, None, None, None)  # all-null group -> NULL aggs
    # ORDER BY a group column that is NOT in the select list
    out = query(c2, "SELECT count(*) FROM db.nulls WHERE g IS NOT NULL GROUP BY g ORDER BY g")
    assert [r[0] for r in out.to_pylist()] == [2, 1]
    assert out.schema.field_names == ["count(*)"]


def test_select_time_travel(cat):
    t = cat.get_table("db.t")
    t.create_tag("after-first", snapshot_id=1)
    # snapshot 1 = first commit only (100 rows, v = k)
    out = query(cat, "SELECT count(*), max(v) FROM db.t FOR VERSION AS OF 1;")
    assert out.to_pylist()[0] in ((100, 99), [100, 99])
    # VERSION AS OF resolves tags too (the reference's unified scan.version)
    out = query(cat, "SELECT count(*) FROM db.t FOR VERSION AS OF 'after-first'")
    assert out.to_pylist()[0][0] == 100
    out = query(cat, "SELECT count(*) FROM db.t FOR TAG AS OF 'after-first'")
    assert out.to_pylist()[0][0] == 100
    # latest view for contrast
    assert query(cat, "SELECT count(*) FROM db.t").to_pylist()[0][0] == 150
    with pytest.raises(QueryError, match="non-empty"):
        query(cat, "SELECT * FROM db.t FOR TAG AS OF ''")
    with pytest.raises(QueryError, match="TIMESTAMP AS OF"):
        query(cat, "SELECT * FROM db.t FOR TIMESTAMP AS OF 'not-a-date'")


def test_select_options_hints(cat):
    # time travel via the Flink dynamic-options hint
    out = query(cat, "SELECT count(*) FROM db.t /*+ OPTIONS('scan.snapshot-id' = '1') */")
    assert out.to_pylist()[0][0] == 100
    # any table option: force a tiny merge tile size (behavioral no-op, same rows)
    out = query(cat, "SELECT count(*) FROM db.t /*+ OPTIONS('merge-read-batch-rows' = '64') */")
    assert out.to_pylist()[0][0] == 150
    # hints compose with WHERE etc.
    out = query(cat, "SELECT k FROM db.t /*+ OPTIONS('scan.snapshot-id' = '1') */ WHERE k < 5 ORDER BY k")
    assert [r[0] for r in out.to_pylist()] == [0, 1, 2, 3, 4]
    with pytest.raises(QueryError):
        query(cat, "SELECT * FROM db.t /*+ OPTIONS(bad) */")


def test_select_distinct(cat):
    out = query(cat, "SELECT DISTINCT s FROM db.t ORDER BY s")
    assert [r[0] for r in out.to_pylist()] == ["g0", "g1", "g2"]
    out = query(cat, "SELECT DISTINCT s, k FROM db.t WHERE k < 3 ORDER BY k")
    assert len(out.to_pylist()) == 3  # (s, k) pairs, k unique
    with pytest.raises(QueryError, match="DISTINCT"):
        query(cat, "SELECT DISTINCT count(*) FROM db.t")
    with pytest.raises(QueryError, match="column list"):
        query(cat, "SELECT DISTINCT * FROM db.t")


def test_select_having_filters_groups(cat):
    # s cycles g0/g1/g2 over 150 merged keys: g0 gets 50, g1 gets 50, g2 gets 50
    out = query(cat, "SELECT s, count(*) FROM db.t GROUP BY s HAVING count(*) >= 50 ORDER BY s")
    assert [r[0] for r in out.to_pylist()] == ["g0", "g1", "g2"]
    # discriminating predicate: only groups whose min key is below the cut
    out = query(cat, "SELECT s, min(k) FROM db.t GROUP BY s HAVING min(k) < 2 ORDER BY s")
    assert [tuple(r) for r in out.to_pylist()] == [("g0", 0), ("g1", 1)]
    # HAVING over an aggregate NOT in the select list (hidden extra aggregate)
    out = query(cat, "SELECT s FROM db.t GROUP BY s HAVING max(k) = 149")
    assert [r[0] for r in out.to_pylist()] == ["g2"]
    assert out.schema.field_names == ["s"]
    # bare group-column refs combine with aggregate calls
    out = query(cat, "SELECT s, count(*) FROM db.t GROUP BY s HAVING s <> 'g1' AND count(*) > 0 ORDER BY s")
    assert [r[0] for r in out.to_pylist()] == ["g0", "g2"]
    # repeated call of a selected aggregate reuses the select item's column
    out = query(
        cat,
        "SELECT s, sum(v) FROM db.t GROUP BY s HAVING sum(v) > 0 ORDER BY sum(v) DESC LIMIT 1",
    )
    assert len(out.to_pylist()) == 1


def test_select_having_errors(cat):
    with pytest.raises(QueryError, match="HAVING requires GROUP BY"):
        query(cat, "SELECT count(*) FROM db.t HAVING count(*) > 1")
    with pytest.raises(QueryError):
        # non-grouped bare column ref in HAVING
        query(cat, "SELECT s, count(*) FROM db.t GROUP BY s HAVING v > 3")


def test_agg_projection_pruning():
    from paimon_tpu.sql.select import agg_projection, parse_select

    rt = RowType.of(("k", BIGINT(False)), ("v", BIGINT()), ("x", DOUBLE()), ("s", STRING()))
    # pure count(*): any single cheap column satisfies the scan
    assert agg_projection(parse_select("SELECT count(*) FROM db.t"), rt) == ["k"]
    # scalar aggregates read exactly their arguments, deduplicated
    assert agg_projection(
        parse_select("SELECT sum(v), min(v), max(x) FROM db.t"), rt
    ) == ["v", "x"]
    # GROUP BY adds keys first, then agg args, HAVING args, ORDER BY cols
    assert agg_projection(
        parse_select(
            "SELECT s, sum(v) FROM db.t GROUP BY s HAVING min(x) < 9 ORDER BY s"
        ),
        rt,
    ) == ["s", "v", "x"]
    # ORDER BY on an aggregate alias is not a table column: not projected
    assert agg_projection(
        parse_select("SELECT s, count(*) FROM db.t GROUP BY s ORDER BY count(*) DESC"), rt
    ) == ["s"]
    # non-aggregate plans opt out of pruning
    assert agg_projection(parse_select("SELECT k, v FROM db.t"), rt) is None
