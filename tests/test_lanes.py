"""Key-lane compression layer (ops/lanes.py): planner decisions, transform
invariants, OVC kernel numpy/JAX parity, and the compressed==uncompressed
bit-for-bit guarantee across every merge consumer.

The hard contract under test: with merge.lane-compression on, every sort
permutation, segmentation, and merge output is BIT-IDENTICAL to the
uncompressed path (which itself matches the pre-PR oracle: plain lexsort +
all-lane boundary compares)."""

import os

import numpy as np
import pytest

from paimon_tpu.data.keys import lexsort_rows
from paimon_tpu.ops import lanes as L
from paimon_tpu.ops import merge as M


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def _oracle_dedup(lanes, seq_lanes=None):
    """The pre-PR oracle: stable lexsort over ALL raw lanes + all-lane
    boundary compares; last row per key wins."""
    tiebreakers = [] if seq_lanes is None else [seq_lanes[:, i] for i in range(seq_lanes.shape[1])]
    order = lexsort_rows(lanes, *tiebreakers)
    s = lanes[order]
    if len(s) == 0:
        return np.empty(0, dtype=np.int64)
    neq = (s[1:] != s[:-1]).any(axis=1) if lanes.shape[1] else np.zeros(len(s) - 1, bool)
    keep_last = np.concatenate([neq, np.ones(1, dtype=np.bool_)])
    return order[keep_last]


# ---------------------------------------------------------------------------
# planner unit tests: packing decisions pinned per lane-stat input
# ---------------------------------------------------------------------------

def test_planner_drops_constant_lanes_and_packs():
    n = 1000
    rng = np.random.default_rng(0)
    lanes = np.stack(
        [
            np.full(n, 0xDEAD, np.uint32),  # constant: dropped
            rng.integers(100, 108, n).astype(np.uint32),  # 3 bits
            rng.integers(0, 2000, n).astype(np.uint32),  # 11 bits
            rng.integers(5, 37, n).astype(np.uint32),  # 5 bits
        ],
        axis=1,
    )
    plan = L.plan_lanes(lanes)
    assert plan.lanes_in == 4
    assert plan.keep == (1, 2, 3)
    assert plan.bits == (3, 11, 5)
    assert plan.groups == ((0, 1, 2),)  # 19 bits fuse into ONE operand
    assert plan.lanes_out == 1 and plan.lanes_out < plan.lanes_in
    assert not plan.use_ovc  # single-operand key IS its own complete code


def test_planner_group_split_at_32_bits():
    n = 500
    rng = np.random.default_rng(0)
    lanes = np.stack(
        [
            rng.integers(0, 1 << 20, n).astype(np.uint32),  # 20 bits
            rng.integers(0, 1 << 20, n).astype(np.uint32),  # 20 bits: won't fit with prev
            rng.integers(0, 50, n).astype(np.uint32),  # 6 bits: joins group 2
        ],
        axis=1,
    )
    plan = L.plan_lanes(lanes)
    assert plan.groups == ((0,), (1, 2))
    assert plan.use_ovc  # >= 2 fused operands: the OVC lane leads the sort
    assert plan.ovc_vbits == 26  # max group width (20 + 6)
    assert plan.sort_width == 3


def test_planner_min_shift_is_bit_exact():
    # two lanes spanning [1_000_000, +4) and [500, +8): 2 + 3 bits, packed
    # into one operand with both minimums subtracted first
    a = np.array([1_000_000, 1_000_001, 1_000_003], dtype=np.uint32)
    b = np.array([507, 500, 503], dtype=np.uint32)
    lanes = np.stack([a, b], axis=1)
    plan = L.plan_lanes(lanes)
    assert plan.bits == (2, 3)
    assert plan.los == (1_000_000, 500)
    assert plan.groups == ((0, 1),)
    packed = L.apply_plan(plan, lanes)
    assert packed[:, 0].tolist() == [(0 << 3) | 7, (1 << 3) | 0, (3 << 3) | 3]


def test_planner_singleton_groups_skip_the_shift():
    """When nothing fuses and no OVC value field needs bounding, the shift
    is a pure copy — the planner zeroes it and apply_plan returns a column
    selection (or the input itself) with no per-row arithmetic."""
    col = np.array([1_000_000, 1_000_001, 1_000_003], dtype=np.uint32)
    plan = L.plan_lanes(col.reshape(-1, 1))
    assert plan.bits == (2,) and plan.los == (0,)
    src = np.ascontiguousarray(col.reshape(-1, 1))
    out = L.apply_plan(plan, src)
    assert out is src  # zero-copy identity
    # constant lane + wide lane: selection without arithmetic
    lanes = np.stack([np.full(3, 9, np.uint32), col], axis=1)
    plan2 = L.plan_lanes(lanes)
    out2 = L.apply_plan(plan2, lanes)
    assert out2[:, 0].tolist() == col.tolist()


def test_planner_zero_width_for_trivial_inputs():
    assert L.plan_lanes(np.zeros((0, 3), np.uint32)).lanes_out == 0
    assert L.plan_lanes(np.full((1, 3), 9, np.uint32)).lanes_out == 0
    assert L.plan_lanes(np.full((64, 2), 7, np.uint32)).lanes_out == 0


def test_planner_base_is_lexicographic_minimum():
    rng = np.random.default_rng(3)
    n = 2000
    lanes = np.stack(
        [rng.integers(0, 1 << 20, n), rng.integers(0, 1 << 20, n)], axis=1
    ).astype(np.uint32)
    plan = L.plan_lanes(lanes)
    assert plan.use_ovc
    packed = L.apply_plan(plan, lanes)
    min_row = packed[lexsort_rows(packed)[0]]
    assert tuple(int(v) for v in min_row) == plan.base


# ---------------------------------------------------------------------------
# transform invariants: order, equality, stability
# ---------------------------------------------------------------------------

def _random_lanes(rng, n, shape_kind):
    if shape_kind == "single_small":
        return rng.integers(0, 300, (n, 1)).astype(np.uint32)
    if shape_kind == "single_wide":
        return rng.integers(0, 1 << 31, (n, 1)).astype(np.uint32)
    if shape_kind == "composite_dict":
        return np.stack(
            [
                rng.integers(0, 4, n),
                rng.integers(0, 100, n),
                rng.integers(0, 5000, n),
                rng.integers(0, 12, n),
            ],
            axis=1,
        ).astype(np.uint32)
    if shape_kind == "wide_multi":
        return np.stack(
            [rng.integers(0, 1 << 24, n), rng.integers(0, 1 << 24, n), rng.integers(0, 64, n)],
            axis=1,
        ).astype(np.uint32)
    if shape_kind == "const_prefix":
        return np.stack(
            [np.full(n, 42), np.full(n, 7), rng.integers(0, 900, n), rng.integers(0, 33, n)],
            axis=1,
        ).astype(np.uint32)
    raise AssertionError(shape_kind)


@pytest.mark.parametrize("seed", [0, 1, 2, 7])
@pytest.mark.parametrize(
    "shape_kind", ["single_small", "single_wide", "composite_dict", "wide_multi", "const_prefix"]
)
def test_transform_preserves_order_and_equality(seed, shape_kind):
    rng = np.random.default_rng(seed)
    n = 3000
    lanes = _random_lanes(rng, n, shape_kind)
    dup = rng.integers(0, n, n // 3)
    lanes = np.concatenate([lanes, lanes[dup]])  # guarantee duplicate keys
    plan = L.plan_lanes(lanes)
    packed = L.apply_plan(plan, lanes)
    o1, o2 = lexsort_rows(lanes), lexsort_rows(packed)
    assert np.array_equal(o1, o2)  # identical permutation incl. tie order
    s1, s2 = lanes[o1], packed[o1]
    b1 = (s1[1:] != s1[:-1]).any(axis=1)
    b2 = (s2[1:] != s2[:-1]).any(axis=1) if packed.shape[1] else np.zeros(len(s2) - 1, bool)
    assert np.array_equal(b1, b2)  # identical segmentation


# ---------------------------------------------------------------------------
# OVC kernel: numpy/JAX parity + the order-consistency property
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 5])
def test_ovc_numpy_jax_parity(seed):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    n = 4096
    lanes = np.stack(
        [rng.integers(0, 1 << 20, n), rng.integers(0, 1 << 18, n), rng.integers(0, 40, n)],
        axis=1,
    ).astype(np.uint32)
    plan = L.plan_lanes(lanes)
    assert plan.use_ovc
    packed = L.apply_plan(plan, lanes)
    base = np.asarray(plan.base, np.uint32)
    c_np = L.ovc_codes_np(packed, base, plan.ovc_vbits)
    c_jax = np.asarray(
        L.ovc_codes_jax(
            [jnp.asarray(packed[:, i]) for i in range(packed.shape[1])],
            jnp.asarray(base),
            plan.ovc_vbits,
        )
    )
    assert np.array_equal(c_np, c_jax)


def test_ovc_codes_are_order_consistent(rng):
    """The OVC contract: where codes differ, unsigned code order == full key
    order; equal keys always produce equal codes."""
    n = 5000
    lanes = np.stack([rng.integers(0, 1 << 20, n), rng.integers(0, 1 << 20, n)], axis=1).astype(
        np.uint32
    )
    lanes = np.concatenate([lanes, lanes[rng.integers(0, n, n // 2)]])
    plan = L.plan_lanes(lanes)
    packed = L.apply_plan(plan, lanes)
    codes = L.ovc_codes_np(packed, np.asarray(plan.base, np.uint32), plan.ovc_vbits)
    order = lexsort_rows(packed)
    sc = codes[order].astype(np.uint64)
    assert (sc[1:] >= sc[:-1]).all()  # codes non-decreasing in key order
    sp = packed[order]
    key_eq = (sp[1:] == sp[:-1]).all(axis=1)
    assert (sc[1:][key_eq] == sc[:-1][key_eq]).all()  # equal keys -> equal codes
    # a base row equal to the batch minimum codes 0
    assert codes[order[0]] == 0


def test_ovc_base_row_codes_zero():
    lanes = np.array([[5, 9], [5, 9], [6, 0]], dtype=np.uint32)
    codes = L.ovc_codes_np(lanes, np.array([5, 9], np.uint32), 8)
    assert codes[0] == 0 and codes[1] == 0 and codes[2] != 0


# ---------------------------------------------------------------------------
# compressed == uncompressed, bit-for-bit, across every consumer
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 3, 11])
@pytest.mark.parametrize(
    "shape_kind", ["single_small", "composite_dict", "wide_multi", "const_prefix"]
)
def test_dedup_parity_with_oracle(seed, shape_kind):
    rng = np.random.default_rng(seed)
    n = 2500
    lanes = _random_lanes(rng, n, shape_kind)
    lanes = np.concatenate([lanes, lanes[rng.integers(0, n, n // 4)]])
    seq = rng.permutation(len(lanes)).astype(np.uint32).reshape(-1, 1)
    on = M.deduplicate_select(lanes, seq, compress=True)
    off = M.deduplicate_select(lanes, seq, compress=False)
    oracle = _oracle_dedup(lanes, seq)
    assert np.array_equal(np.sort(on), np.sort(off))
    assert np.array_equal(np.sort(on), np.sort(oracle))


@pytest.mark.parametrize("seed", [0, 4])
def test_merge_plan_parity(seed):
    rng = np.random.default_rng(seed)
    n = 2000
    lanes = _random_lanes(rng, n, "wide_multi")
    lanes = np.concatenate([lanes, lanes[rng.integers(0, n, n // 2)]])
    seq = np.arange(len(lanes), dtype=np.uint32).reshape(-1, 1)
    p_on = M.merge_plan(lanes, seq, compress=True)
    p_off = M.merge_plan(lanes, seq, compress=False)
    assert np.array_equal(p_on.perm, p_off.perm)
    assert np.array_equal(p_on.seg_start, p_off.seg_start)
    assert np.array_equal(p_on.keep_last, p_off.keep_last)
    assert np.array_equal(p_on.seg_id, p_off.seg_id)


def _sorted_runs(rng, lanes, runs):
    per = len(lanes) // runs
    parts, offsets = [], [0]
    for r in range(runs):
        chunk = lanes[r * per : (r + 1) * per if r < runs - 1 else len(lanes)]
        parts.append(chunk[lexsort_rows(chunk)])
        offsets.append(offsets[-1] + len(chunk))
    return np.concatenate(parts), offsets


@pytest.mark.parametrize("tile_rows", [1024, 1 << 20])
def test_tiled_dedup_parity(rng, tile_rows):
    n = 12000
    lanes = _random_lanes(rng, n, "wide_multi")
    lanes = np.concatenate([lanes, lanes[rng.integers(0, n, n // 3)]])
    l2, offsets = _sorted_runs(rng, lanes, 4)
    on = M.deduplicate_select_tiled(l2, offsets, tile_rows=tile_rows, compress=True)
    off = M.deduplicate_select_tiled(l2, offsets, tile_rows=tile_rows, compress=False)
    assert np.array_equal(on, off)


def test_compact_download_parity_forced(rng, monkeypatch):
    monkeypatch.setenv("PAIMON_TPU_FORCE_COMPACT", "1")
    n = 8000
    lanes = _random_lanes(rng, n, "wide_multi")
    l2, offsets = _sorted_runs(rng, lanes, 3)
    a = M.deduplicate_resolve(M.deduplicate_select_compact_async(l2, offsets, compress=True))
    b = M.deduplicate_resolve(M.deduplicate_select_compact_async(l2, offsets, compress=False))
    assert np.array_equal(a, b)


# ---- collation edge cases --------------------------------------------------

def test_parity_0xffff_lane_boundary():
    """Lanes straddling the u16 narrowing boundary: ptp of exactly 0xFFFF-1,
    0xFFFF, 0xFFFF+1 — the planner's bit widths and the narrowing tiers must
    agree on segmentation either way."""
    for span in (0xFFFE, 0xFFFF, 0x10000, 0x10001):
        base = 1 << 20
        col = np.array([base, base + span, base, base + span // 2, base + span], dtype=np.uint32)
        lanes = np.stack([col, np.array([1, 2, 1, 2, 1], np.uint32)], axis=1)
        on = M.deduplicate_select(lanes, None, compress=True)
        off = M.deduplicate_select(lanes, None, compress=False)
        assert np.array_equal(on, off), span
        assert np.array_equal(np.sort(on), np.sort(_oracle_dedup(lanes))), span


def test_parity_prefix_equal_strings():
    """Dictionary ranks of prefix-equal strings ('a', 'aa', 'aaa', ...):
    adjacent ranks, heavy duplication — the classic OVC stress shape."""
    from paimon_tpu.data.keys import build_string_pool

    rng = np.random.default_rng(9)
    vocab = np.array(["a" * k for k in range(1, 40)] + ["a" * 20 + "b", "a" * 20 + "c"], dtype=object)
    vals = vocab[rng.integers(0, len(vocab), 4000)]
    pool = build_string_pool([vals])
    ranks = np.searchsorted(pool, vals).astype(np.uint32)
    salt = rng.integers(0, 3, len(vals)).astype(np.uint32)
    lanes = np.stack([ranks, salt], axis=1)
    on = M.deduplicate_select(lanes, None, compress=True)
    off = M.deduplicate_select(lanes, None, compress=False)
    assert np.array_equal(on, off)
    assert np.array_equal(np.sort(on), np.sort(_oracle_dedup(lanes)))


def test_parity_all_equal_keys_and_single_row_runs(rng):
    # all-equal: the zero-width scalar fast path must pick the same winner
    eq = np.full((257, 2), 12345, np.uint32)
    seq = rng.permutation(257).astype(np.uint32).reshape(-1, 1)
    on = M.deduplicate_select(eq, seq, compress=True)
    off = M.deduplicate_select(eq, seq, compress=False)
    assert np.array_equal(on, off) and len(on) == 1
    assert np.array_equal(on, _oracle_dedup(eq, seq))
    # single-row runs: n=1 per run — planner sees a 1-row batch per tile edge
    one = np.array([[7, 9]], dtype=np.uint32)
    assert M.deduplicate_select(one, None, compress=True).tolist() == [0]
    assert M.merge_plan(one, compress=True).num_segments == 1
    # empty input
    empty = np.zeros((0, 2), np.uint32)
    assert M.deduplicate_select(empty, None, compress=True).size == 0
    assert M.merge_plan(empty, compress=True).num_segments == 0


def test_scalar_fast_path_skips_key_sort(rng, monkeypatch):
    """All-equal keys: no device kernel runs at all — the handle is the
    host-computed scalar winner (the ISSUE 6 satellite replacing the old
    dummy-lane sort)."""
    eq = np.full((100, 3), 5, np.uint32)
    h = M.deduplicate_select_async(eq, None, compress=True)
    assert isinstance(h, tuple) and h[0] == "scalar"
    assert M.deduplicate_resolve(h).tolist() == [99]
    # with seq lanes the winner is ordered by the seq lanes alone
    seq = rng.permutation(100).astype(np.uint32).reshape(-1, 1)
    h2 = M.deduplicate_select_async(eq, seq, compress=True)
    assert h2[0] == "scalar"
    assert M.deduplicate_resolve(h2).tolist() == [int(np.argmax(seq[:, 0]))]
    # the fast path also applies with compression off (it replaces the old
    # ops/merge.py "shape sanity" dummy lane in both modes)
    h3 = M.deduplicate_select_async(eq, None, compress=False)
    assert h3[0] == "scalar"


# ---------------------------------------------------------------------------
# executor-level parity: full merges through MergeExecutor, option on vs off
# ---------------------------------------------------------------------------

def _mk_exec(schema, keys, engine, opts):
    from paimon_tpu.core.mergefn import MergeExecutor
    from paimon_tpu.options import CoreOptions, MergeEngine

    return MergeExecutor(schema, keys, MergeEngine(engine), CoreOptions(opts))


def _mk_kv(rng, n, null_rate=0.0, seed_vals=None):
    from paimon_tpu.core.kv import KVBatch
    from paimon_tpu.data.batch import Column, ColumnBatch
    from paimon_tpu.types import BIGINT, INT, STRING, RowKind, RowType

    schema = RowType.of(("k1", STRING(False)), ("k2", BIGINT(False)), ("v", INT()))
    k1 = np.array([f"acct-{int(x):03d}" for x in rng.integers(0, 50, n)], dtype=object)
    k2 = rng.integers(0, 200, n).astype(np.int64)
    v = rng.integers(-100, 100, n).astype(np.int32)
    valid = rng.random(n) >= null_rate
    cols = {"k1": Column(k1), "k2": Column(k2), "v": Column(v, valid)}
    data = ColumnBatch(schema, cols)
    seq = np.arange(n, dtype=np.int64)
    kind = np.full(n, int(RowKind.INSERT), np.uint8)
    return schema, KVBatch(data, seq, kind)


@pytest.mark.parametrize("engine", ["deduplicate", "partial-update", "aggregation"])
@pytest.mark.parametrize("null_rate", [0.0, 0.35])
def test_executor_merge_parity_on_vs_off(rng, engine, null_rate):
    n = 3000
    schema, kv = _mk_kv(rng, n, null_rate=null_rate)
    opts = {} if engine != "aggregation" else {"fields.v.aggregate-function": "sum"}
    ex_on = _mk_exec(schema, ["k1", "k2"], engine, dict(opts, **{"merge.lane-compression": "true"}))
    ex_off = _mk_exec(schema, ["k1", "k2"], engine, dict(opts, **{"merge.lane-compression": "false"}))
    out_on = ex_on.merge(kv, seq_ascending=True)
    out_off = ex_off.merge(kv, seq_ascending=True)
    assert out_on.num_rows == out_off.num_rows
    assert out_on.data.to_pylist() == out_off.data.to_pylist()
    assert np.array_equal(out_on.seq, out_off.seq)
    assert np.array_equal(out_on.kind, out_off.kind)


@pytest.mark.skipif(
    os.environ.get("PAIMON_TPU_LANE_COMPRESSION", "").strip().lower() in ("0", "off", "false"),
    reason="lane compression forced off by env (verify.sh lanes stage, off pass)",
)
def test_executor_records_lanes_metrics(rng):
    from paimon_tpu.metrics import lanes_metrics, registry

    registry.reset()
    n = 2000
    schema, kv = _mk_kv(rng, n)
    ex = _mk_exec(schema, ["k1", "k2"], "deduplicate", {"merge.lane-compression": "true"})
    ex.merge(kv, seq_ascending=True)
    g = lanes_metrics()
    assert g.counter("plans").count >= 1
    assert g.counter("lanes_in").count > g.counter("lanes_out").count


def test_env_var_forces_compression_both_ways(monkeypatch):
    monkeypatch.setenv("PAIMON_TPU_LANE_COMPRESSION", "0")
    assert L.resolve_compress(True) is False
    monkeypatch.setenv("PAIMON_TPU_LANE_COMPRESSION", "1")
    assert L.resolve_compress(False) is True
    monkeypatch.delenv("PAIMON_TPU_LANE_COMPRESSION")
    assert L.resolve_compress(None) is True
    assert L.resolve_compress(False) is False
