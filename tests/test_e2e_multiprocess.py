"""Tier-5 analog: real multi-process isolation (the reference's MiniCluster /
docker e2e stands in for this — here separate OS processes share only the
filesystem, proving snapshot isolation and the commit protocol across
process boundaries)."""

import subprocess
import sys
import textwrap

import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.types import BIGINT, DOUBLE, RowType

SCHEMA = RowType.of(("k", BIGINT()), ("v", DOUBLE()))


def run_py(code: str) -> str:
    r = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(code)],
        capture_output=True,
        text=True,
        timeout=120,
        cwd="/root/repo",
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "HOME": "/root"},
    )
    assert r.returncode == 0, r.stderr
    return r.stdout


def test_writer_process_reader_process(tmp_warehouse):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="parent")
    cat.create_table("db.xs", SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    # a separate OS process writes two commits
    run_py(f"""
        import jax; jax.config.update("jax_platforms", "cpu")
        from paimon_tpu.table import load_table
        t = load_table("{tmp_warehouse}/db.db/xs", commit_user="writerproc")
        for ident, (k, v) in enumerate([(1, 1.0), (1, 11.0)], start=1):
            wb = t.new_batch_write_builder(); w = wb.new_write()
            w.write({{"k": [k], "v": [v]}})
            wb.new_commit().commit(w.prepare_commit())
        print("wrote")
    """)
    # the parent process observes the committed state through the snapshots
    t = cat.get_table("db.xs")
    rb = t.new_read_builder()
    assert rb.new_read().read_all(rb.new_scan().plan()).to_pylist() == [(1, 11.0)]
    assert t.store.snapshot_manager.latest_snapshot().commit_user == "writerproc"


def test_concurrent_committers_across_processes(tmp_warehouse):
    """Two processes commit simultaneously; the CAS loop must keep both."""
    import threading

    cat = FileSystemCatalog(tmp_warehouse, commit_user="parent")
    cat.create_table("db.cc", SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    outs = {}

    def worker(name, key):
        outs[name] = run_py(f"""
            import jax; jax.config.update("jax_platforms", "cpu")
            from paimon_tpu.table import load_table
            t = load_table("{tmp_warehouse}/db.db/cc", commit_user="{name}")
            wb = t.new_batch_write_builder(); w = wb.new_write()
            w.write({{"k": [{key}], "v": [{key}.0]}})
            ids = wb.new_commit().commit(w.prepare_commit())
            print("committed", ids)
        """)

    t1 = threading.Thread(target=worker, args=("alice", 1))
    t2 = threading.Thread(target=worker, args=("bob", 2))
    t1.start(); t2.start(); t1.join(); t2.join()
    t = cat.get_table("db.cc")
    rb = t.new_read_builder()
    out = rb.new_read().read_all(rb.new_scan().plan())
    assert sorted(r[0] for r in out.to_pylist()) == [1, 2]
    assert t.store.snapshot_manager.latest_snapshot_id() == 2


def test_cas_race_shared_bucket_across_processes(tmp_warehouse):
    """Two processes fire ROUNDS commits each into the SAME bucket through
    the real snapshot-CAS retry path, released together by a go-file
    barrier so the rounds genuinely collide. Exactly one committer wins
    each CAS round; every loser's auto-retry must land its commit against
    the new latest — and land it exactly once (no double-applied ADDs)."""
    import os
    import threading

    cat = FileSystemCatalog(tmp_warehouse, commit_user="parent")
    cat.create_table(
        "db.race",
        SCHEMA,
        primary_keys=["k"],
        options={
            "bucket": "1",
            "commit.max-retries": "100",
            "commit.retry-backoff": "2 ms",
            # APPEND-only commits: auto compaction would add COMPACT
            # snapshots (and compact-vs-compact conflicts) — the thread/proc
            # soaks own that storm; this test isolates the snapshot-CAS race
            "write-only": "true",
        },
    )
    ROUNDS = 6
    go = f"{tmp_warehouse}/go"
    outs = {}

    def worker(name, base):
        outs[name] = run_py(f"""
            import jax; jax.config.update("jax_platforms", "cpu")
            import os, time
            from paimon_tpu.core.manifest import ManifestCommittable
            from paimon_tpu.table import load_table
            from paimon_tpu.table.write import TableWrite
            t = load_table("{tmp_warehouse}/db.db/race", commit_user="{name}")
            while not os.path.exists("{go}"):
                time.sleep(0.005)
            sids = []
            for ident in range(1, {ROUNDS} + 1):
                tw = TableWrite(t)
                try:
                    tw.write({{"k": [{base} + ident], "v": [float(ident)]}})
                    msgs = tw.prepare_commit()
                finally:
                    tw.close()
                sids += t.store.new_commit().commit(ManifestCommittable(ident, messages=msgs))
            print("SIDS", ",".join(map(str, sids)))
        """)

    t1 = threading.Thread(target=worker, args=("alice", 1000))
    t2 = threading.Thread(target=worker, args=("bob", 2000))
    t1.start(); t2.start()
    with open(go, "w") as f:
        f.write("go")
    t1.join(); t2.join()

    won = {}
    for name in ("alice", "bob"):
        line = next(ln for ln in outs[name].splitlines() if ln.startswith("SIDS"))
        won[name] = [int(s) for s in line.split(" ", 1)[1].split(",")]
        assert len(won[name]) == ROUNDS  # every round landed despite the races
    # exactly one winner per snapshot id: the two processes' landed ids are
    # disjoint and together cover the chain with no gap and no double
    assert set(won["alice"]).isdisjoint(won["bob"])
    assert sorted(won["alice"] + won["bob"]) == list(range(1, 2 * ROUNDS + 1))

    t = cat.get_table("db.race")
    sm = t.store.snapshot_manager
    assert sm.latest_snapshot_id() == 2 * ROUNDS
    # each (user, identifier) appears exactly once in the chain: a lost CAS
    # round was retried, never re-applied
    seen = set()
    for sid in range(1, 2 * ROUNDS + 1):
        snap = sm.snapshot(sid)
        key = (snap.commit_user, snap.commit_identifier)
        assert key not in seen, f"identifier committed twice: {key}"
        seen.add(key)
    # physical record count == unique keys: double-applied ADDs cannot hide
    assert sm.latest_snapshot().total_record_count == 2 * ROUNDS
    rb = t.new_read_builder()
    rows = dict(rb.new_read().read_all(rb.new_scan().plan()).to_pylist())
    assert rows == {
        **{1000 + i: float(i) for i in range(1, ROUNDS + 1)},
        **{2000 + i: float(i) for i in range(1, ROUNDS + 1)},
    }
