"""Tier-5 analog: real multi-process isolation (the reference's MiniCluster /
docker e2e stands in for this — here separate OS processes share only the
filesystem, proving snapshot isolation and the commit protocol across
process boundaries)."""

import subprocess
import sys
import textwrap

import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.types import BIGINT, DOUBLE, RowType

SCHEMA = RowType.of(("k", BIGINT()), ("v", DOUBLE()))


def run_py(code: str) -> str:
    r = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(code)],
        capture_output=True,
        text=True,
        timeout=120,
        cwd="/root/repo",
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu", "HOME": "/root"},
    )
    assert r.returncode == 0, r.stderr
    return r.stdout


def test_writer_process_reader_process(tmp_warehouse):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="parent")
    cat.create_table("db.xs", SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    # a separate OS process writes two commits
    run_py(f"""
        import jax; jax.config.update("jax_platforms", "cpu")
        from paimon_tpu.table import load_table
        t = load_table("{tmp_warehouse}/db.db/xs", commit_user="writerproc")
        for ident, (k, v) in enumerate([(1, 1.0), (1, 11.0)], start=1):
            wb = t.new_batch_write_builder(); w = wb.new_write()
            w.write({{"k": [k], "v": [v]}})
            wb.new_commit().commit(w.prepare_commit())
        print("wrote")
    """)
    # the parent process observes the committed state through the snapshots
    t = cat.get_table("db.xs")
    rb = t.new_read_builder()
    assert rb.new_read().read_all(rb.new_scan().plan()).to_pylist() == [(1, 11.0)]
    assert t.store.snapshot_manager.latest_snapshot().commit_user == "writerproc"


def test_concurrent_committers_across_processes(tmp_warehouse):
    """Two processes commit simultaneously; the CAS loop must keep both."""
    import threading

    cat = FileSystemCatalog(tmp_warehouse, commit_user="parent")
    cat.create_table("db.cc", SCHEMA, primary_keys=["k"], options={"bucket": "1"})
    outs = {}

    def worker(name, key):
        outs[name] = run_py(f"""
            import jax; jax.config.update("jax_platforms", "cpu")
            from paimon_tpu.table import load_table
            t = load_table("{tmp_warehouse}/db.db/cc", commit_user="{name}")
            wb = t.new_batch_write_builder(); w = wb.new_write()
            w.write({{"k": [{key}], "v": [{key}.0]}})
            ids = wb.new_commit().commit(w.prepare_commit())
            print("committed", ids)
        """)

    t1 = threading.Thread(target=worker, args=("alice", 1))
    t2 = threading.Thread(target=worker, args=("bob", 2))
    t1.start(); t2.start(); t1.join(); t2.join()
    t = cat.get_table("db.cc")
    rb = t.new_read_builder()
    out = rb.new_read().read_all(rb.new_scan().plan())
    assert sorted(r[0] for r in out.to_pylist()) == [1, 2]
    assert t.store.snapshot_manager.latest_snapshot_id() == 2
