"""Production mega-soak (ISSUE 18): the scenario matrix, the kill-schedule
coverage audit, and the journaled-put identity the gateway writers recover
through. The full supervisor run (processes + chaos store + oracle verdict)
lives in scripts/verify.sh's `mega` stage and benchmarks/mega_soak_bench.py;
these tests pin the pieces that must hold for that run to mean anything."""

import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.resilience import faults
from paimon_tpu.service.cluster import DEFAULT_CLUSTER_KILLS
from paimon_tpu.service.gateway import Gateway
from paimon_tpu.service.mega_soak import (
    DEFAULT_MATRIX,
    DEFAULT_MEGA_KILLS,
    GW_USER_PREFIX,
    MEGA_USER_PREFIXES,
    MegaConfig,
    MegaScenario,
    scenario_schema,
)
from paimon_tpu.service.oracle import find_landed_append
from paimon_tpu.service.proc_soak import DEFAULT_SCRIPTED_KILLS
from paimon_tpu.types import BIGINT, DOUBLE, RowType


# ---------------------------------------------------------------------------
# crash-point coverage audit: every registered point is armed by a soak
# ---------------------------------------------------------------------------
def test_mega_kill_schedule_covers_every_crash_point():
    """DEFAULT_MEGA_KILLS alone must arm every name in ALL_CRASH_POINTS —
    a crash point nobody schedules is a recovery path nobody soaks."""
    armed = {faults._parse_spec(spec)[0] for _, spec in DEFAULT_MEGA_KILLS}
    assert armed == set(faults.ALL_CRASH_POINTS), (
        f"unarmed crash points: {set(faults.ALL_CRASH_POINTS) - armed}; "
        f"unknown specs: {armed - set(faults.ALL_CRASH_POINTS)}"
    )


def test_mega_kill_schedule_spans_process_kinds():
    kinds = {kind for kind, _ in DEFAULT_MEGA_KILLS}
    assert len(kinds) >= 3, f"kill schedule must span >=3 process kinds, got {kinds}"
    # the service-plane points belong to service-plane processes
    by_point = {faults._parse_spec(s)[0]: k for k, s in DEFAULT_MEGA_KILLS}
    assert by_point["gateway:put-sent"] == "gateway-writer"
    assert by_point["subscriber:batch-journaled"] == "subscriber"
    assert by_point["cluster:before-ship"] == "worker"


def test_mega_kill_specs_are_hard_kills():
    """Every scheduled spec must parse as a hard kill (os._exit, no
    unwinding) — a CrashError a `finally` can observe is a softer death
    than the SIGKILL the soak claims to survive."""
    for _, spec in DEFAULT_MEGA_KILLS:
        name, nth, kill = faults._parse_spec(spec)
        assert kill, f"{spec!r} is not a :kill spec"
        assert nth >= 1
        assert name in faults.ALL_CRASH_POINTS


def test_union_of_soak_schedules_covers_every_crash_point():
    """The per-service soaks (proc_soak writers, cluster workers) plus the
    mega schedule together must also cover everything — the audit holds
    even for whoever runs the narrower soaks alone."""
    specs = list(DEFAULT_SCRIPTED_KILLS) + list(DEFAULT_CLUSTER_KILLS)
    specs += [spec for _, spec in DEFAULT_MEGA_KILLS]
    armed = {faults._parse_spec(s)[0] for s in specs}
    assert armed >= set(faults.ALL_CRASH_POINTS)


# ---------------------------------------------------------------------------
# scenario matrix shape
# ---------------------------------------------------------------------------
def test_matrix_covers_the_advertised_axes():
    names = [sc.name for sc in DEFAULT_MATRIX]
    assert len(names) == len(set(names))
    assert {sc.schema for sc in DEFAULT_MATRIX} == {"kv", "dict", "wide"}
    buckets = {sc.bucket for sc in DEFAULT_MATRIX}
    assert -1 in buckets and any(b > 0 for b in buckets), "fixed + dynamic bucket modes"
    assert len({sc.cdc_format for sc in DEFAULT_MATRIX}) >= 4
    assert any(sc.cluster for sc in DEFAULT_MATRIX)
    assert any(sc.branch_tag for sc in DEFAULT_MATRIX)
    assert any(sc.consumer_expiry for sc in DEFAULT_MATRIX)
    # engine toggles actually differ somewhere in the matrix
    toggled = {k for sc in DEFAULT_MATRIX for k, _ in sc.table_options}
    assert "sort-engine" in toggled


def test_table_ident_is_sql_safe():
    for sc in DEFAULT_MATRIX:
        assert "-" not in sc.table_ident, sc.table_ident
        assert sc.table_ident.startswith("mega.")
    assert MegaScenario(name="a-b-c").table_ident == "mega.a_b_c"


def test_scenario_schemas():
    for kind in ("kv", "dict", "wide"):
        rt = scenario_schema(kind)
        assert rt.field_names[0] == "k"
    assert len(scenario_schema("wide").field_names) == 4
    with pytest.raises(ValueError):
        scenario_schema("jagged")


def test_mega_config_from_table_options():
    from paimon_tpu.options import CoreOptions, Options

    co = CoreOptions(
        Options(
            {
                "soak.mega.duration": "30 s",
                "soak.mega.cluster-workers": "3",
                "soak.mega.kill-period": "4 s",
                "soak.mega.chaos.read-ms": "2.5",
                "soak.mega.chaos.possibility": "150",
            }
        )
    )
    cfg = MegaConfig.from_table_options(co)
    assert cfg.duration_s == 30.0
    assert cfg.cluster_workers == 3
    assert cfg.kill_period_s == 4.0
    assert cfg.chaos_read_ms == 2.5
    assert cfg.chaos_possibility == 150


def test_user_prefixes_partition_the_journal_planes():
    """The oracle folds all planes with ONE startswith(tuple) filter — the
    prefixes must be mutually non-overlapping or rounds double-fold."""
    assert GW_USER_PREFIX in MEGA_USER_PREFIXES
    for a in MEGA_USER_PREFIXES:
        for b in MEGA_USER_PREFIXES:
            if a != b:
                assert not a.startswith(b)


# ---------------------------------------------------------------------------
# the journaled-put identity: adopt-never-replay through the gateway
# ---------------------------------------------------------------------------
def test_gateway_put_identifier_resolves_from_the_chain(tmp_path):
    """A gateway put with (user, identifier) must be recoverable by a
    respawned client from the snapshot chain alone: find_landed_append
    returns the landed APPEND sid for the identifier it acked nothing
    about, and None for a round that never committed (adopt, never
    replay — the PR 9/15 protocol the mega gateway writers run)."""
    cat = FileSystemCatalog(str(tmp_path / "wh"), commit_user="test")
    rt = RowType.of(("k", BIGINT(nullable=False)), ("v", DOUBLE()))
    table = cat.create_table("db.t", rt, primary_keys=("k",), options={"bucket": "2"})
    gw = Gateway(table, catalog=cat)
    try:
        user = f"{GW_USER_PREFIX}-0"
        sid = gw.put(
            {"k": [1, 2, 3], "v": [0.5, 1.5, 2.5]}, tenant=None, user=user, identifier=7
        )
        assert sid is not None
        assert find_landed_append(table.store, user, 7) == sid
        # an identifier that never committed resolves to None -> replay it
        assert find_landed_append(table.store, user, 8) is None
        # another user's identifier space is disjoint
        assert find_landed_append(table.store, f"{GW_USER_PREFIX}-1", 7) is None
        # the landed rows are served back through the gateway read path
        rows = gw.get_batch([1, 2, 3])
        assert [r[1] for r in rows] == [0.5, 1.5, 2.5]
    finally:
        gw.close()
