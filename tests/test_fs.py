import threading

import pytest

from paimon_tpu.fs import LocalFileIO, get_file_io, split_scheme
from paimon_tpu.fs.testing import ArtificialException, FailingFileIO


def test_split_scheme():
    assert split_scheme("/a/b") == ("file", "/a/b")
    assert split_scheme("file:///a/b") == ("file", "/a/b")
    assert split_scheme("fail://dom/a/b") == ("fail", "dom/a/b")


def test_local_read_write_list(tmp_path):
    io = LocalFileIO()
    p = str(tmp_path / "d" / "x.txt")
    io.write_text(p, "hello")
    assert io.read_text(p) == "hello"
    assert io.exists(p)
    with pytest.raises(FileExistsError):
        io.write_text(p, "again")
    st = io.get_status(p)
    assert st.size == 5 and not st.is_dir
    files = io.list_files(str(tmp_path / "d"))
    assert [f.path for f in files] == [p]
    assert io.delete(p)
    assert not io.exists(p)


def test_atomic_write_cas(tmp_path):
    io = LocalFileIO()
    p = str(tmp_path / "snapshot-1")
    assert io.try_atomic_write(p, b"a")
    # second writer loses the race, file unchanged
    assert not io.try_atomic_write(p, b"b")
    assert io.read_bytes(p) == b"a"
    # no temp litter
    assert len(io.list_files(str(tmp_path))) == 1


def test_atomic_write_concurrent(tmp_path):
    io = LocalFileIO()
    p = str(tmp_path / "snapshot-7")
    results = []

    def attempt(i):
        results.append((i, io.try_atomic_write(p, f"writer-{i}".encode())))

    threads = [threading.Thread(target=attempt, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [i for i, ok in results if ok]
    assert len(winners) == 1
    assert io.read_bytes(p).decode() == f"writer-{winners[0]}"


def test_failing_file_io(tmp_path):
    FailingFileIO.reset("t1", max_fails=1000, possibility=1)  # always fail
    io = get_file_io("fail://t1/x")
    path = f"fail://t1{tmp_path}/f.txt"
    with pytest.raises(ArtificialException):
        io.write_text(path, "x")
    FailingFileIO.reset("t1", max_fails=0, possibility=0)  # heal
    io.write_text(path, "x")
    assert io.read_text(path) == "x"


def test_failing_file_io_eventually_succeeds(tmp_path):
    FailingFileIO.reset("t2", max_fails=3, possibility=2, seed=7)
    io = get_file_io("fail://t2/x")
    path = f"fail://t2{tmp_path}/g.txt"
    attempts = 0
    while True:
        attempts += 1
        try:
            io.write_text(path, "ok", overwrite=True)
            break
        except ArtificialException:
            continue
    FailingFileIO.reset("t2", max_fails=0, possibility=0)  # heal before verify
    assert io.read_text(path) == "ok"
    assert attempts <= 4
