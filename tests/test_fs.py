import threading

import pytest

from paimon_tpu.fs import LocalFileIO, get_file_io, split_scheme
from paimon_tpu.fs.testing import ArtificialException, FailingFileIO


def test_split_scheme():
    assert split_scheme("/a/b") == ("file", "/a/b")
    assert split_scheme("file:///a/b") == ("file", "/a/b")
    assert split_scheme("fail://dom/a/b") == ("fail", "dom/a/b")


def test_local_read_write_list(tmp_path):
    io = LocalFileIO()
    p = str(tmp_path / "d" / "x.txt")
    io.write_text(p, "hello")
    assert io.read_text(p) == "hello"
    assert io.exists(p)
    with pytest.raises(FileExistsError):
        io.write_text(p, "again")
    st = io.get_status(p)
    assert st.size == 5 and not st.is_dir
    files = io.list_files(str(tmp_path / "d"))
    assert [f.path for f in files] == [p]
    assert io.delete(p)
    assert not io.exists(p)


def test_atomic_write_cas(tmp_path):
    io = LocalFileIO()
    p = str(tmp_path / "snapshot-1")
    assert io.try_atomic_write(p, b"a")
    # second writer loses the race, file unchanged
    assert not io.try_atomic_write(p, b"b")
    assert io.read_bytes(p) == b"a"
    # no temp litter
    assert len(io.list_files(str(tmp_path))) == 1


def test_atomic_write_concurrent(tmp_path):
    io = LocalFileIO()
    p = str(tmp_path / "snapshot-7")
    results = []

    def attempt(i):
        results.append((i, io.try_atomic_write(p, f"writer-{i}".encode())))

    threads = [threading.Thread(target=attempt, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [i for i, ok in results if ok]
    assert len(winners) == 1
    assert io.read_bytes(p).decode() == f"writer-{winners[0]}"


def test_failing_file_io(tmp_path):
    FailingFileIO.reset("t1", max_fails=1000, possibility=1)  # always fail
    io = get_file_io("fail://t1/x")
    path = f"fail://t1{tmp_path}/f.txt"
    with pytest.raises(ArtificialException):
        io.write_text(path, "x")
    FailingFileIO.reset("t1", max_fails=0, possibility=0)  # heal
    io.write_text(path, "x")
    assert io.read_text(path) == "x"


def test_failing_file_io_eventually_succeeds(tmp_path):
    FailingFileIO.reset("t2", max_fails=3, possibility=2, seed=7)
    io = get_file_io("fail://t2/x")
    path = f"fail://t2{tmp_path}/g.txt"
    attempts = 0
    while True:
        attempts += 1
        try:
            io.write_text(path, "ok", overwrite=True)
            break
        except ArtificialException:
            continue
    FailingFileIO.reset("t2", max_fails=0, possibility=0)  # heal before verify
    assert io.read_text(path) == "ok"
    assert attempts <= 4


# ---------------------------------------------------------------------------
# Composed chaos stack: faults over latency over local, one FileIO.
# ---------------------------------------------------------------------------

import os
import time

from paimon_tpu.fs.testing import (
    CHAOS_ENV,
    FaultRule,
    LatencyFileIO,
    _posix_backed,
    apply_chaos_env,
    chaos_spec,
)


@pytest.fixture(autouse=True)
def _quiet_latency():
    yield
    LatencyFileIO.configure(0.0, 0.0)


def test_posix_backed_walks_wrapper_chain():
    from paimon_tpu.fs.object_store import ObjectStoreFileIO

    assert _posix_backed(LocalFileIO())
    assert _posix_backed(LatencyFileIO())
    assert _posix_backed(LatencyFileIO(inner=LocalFileIO()))
    assert not _posix_backed(ObjectStoreFileIO(conditional_put=True))
    assert not _posix_backed(LatencyFileIO(inner=ObjectStoreFileIO(conditional_put=True)))


def test_chaos_passthrough_no_faults_no_latency(tmp_path):
    FailingFileIO.reset("cpass", max_fails=0, possibility=0)
    io = get_file_io("chaos://cpass/x")
    path = f"chaos://cpass{tmp_path}/f.txt"
    io.write_text(path, "hello")
    assert io.read_text(path) == "hello"
    assert io.exists(path)
    assert io.get_status(path).size == 5
    assert io.delete(path)
    assert not io.exists(path)


def test_chaos_fault_fires_before_latency_nap(tmp_path):
    # a shed/failed op must not pay first-byte latency: the fault check sits
    # ABOVE the latency layer in the stack
    LatencyFileIO.configure(read_ms=200.0)
    FailingFileIO.schedule("cord", FaultRule(op="read", path="f.txt"))
    io = get_file_io("chaos://cord/x")
    path = f"chaos://cord{tmp_path}/f.txt"
    io.write_bytes(path, b"x", overwrite=True)  # rule only matches op='read'
    t0 = time.monotonic()
    with pytest.raises(ArtificialException):
        io.read_bytes(path)
    assert time.monotonic() - t0 < 0.1  # no 200 ms nap on the failed read
    # rule exhausted: next read succeeds AND pays the latency
    t0 = time.monotonic()
    assert io.read_bytes(path) == b"x"
    assert time.monotonic() - t0 >= 0.15


def test_chaos_write_latency_is_paid(tmp_path):
    LatencyFileIO.configure(write_ms=60.0)
    FailingFileIO.reset("cw", max_fails=0, possibility=0)
    io = get_file_io("chaos://cw/x")
    t0 = time.monotonic()
    io.write_bytes(f"chaos://cw{tmp_path}/a.bin", b"a")
    io.write_bytes(f"chaos://cw{tmp_path}/b.bin", b"b")
    assert time.monotonic() - t0 >= 0.1


def test_chaos_atomic_write_torn_on_rename_fault(tmp_path):
    # crash semantics must pass THROUGH the composed stack: a rename-phase
    # fault leaves the torn tmp sibling on disk, target absent
    FailingFileIO.schedule("ctorn", FaultRule(op="rename", path="snapshot-9"))
    io = get_file_io("chaos://ctorn/x")
    path = f"chaos://ctorn{tmp_path}/snapshot-9"
    with pytest.raises(ArtificialException):
        io.try_atomic_write(path, b"payload")
    local = LocalFileIO()
    names = [f.path.rsplit("/", 1)[-1] for f in local.list_files(str(tmp_path))]
    assert any("snapshot-9" in n and ".tmp" in n for n in names), names
    assert not local.exists(str(tmp_path / "snapshot-9"))
    # retry (rule exhausted) lands the commit
    assert io.try_atomic_write(path, b"payload")
    assert local.read_bytes(str(tmp_path / "snapshot-9")) == b"payload"


def test_chaos_atomic_write_nothing_on_write_fault(tmp_path):
    FailingFileIO.schedule("cwf", FaultRule(op="write", path="snapshot-3"))
    io = get_file_io("chaos://cwf/x")
    with pytest.raises(ArtificialException):
        io.try_atomic_write(f"chaos://cwf{tmp_path}/snapshot-3", b"z")
    assert LocalFileIO().list_files(str(tmp_path)) == []


def test_chaos_atomic_write_cas_loser_no_litter(tmp_path):
    FailingFileIO.reset("ccas", max_fails=0, possibility=0)
    io = get_file_io("chaos://ccas/x")
    path = f"chaos://ccas{tmp_path}/snapshot-1"
    assert io.try_atomic_write(path, b"a")
    assert not io.try_atomic_write(path, b"b")
    local = LocalFileIO()
    assert local.read_bytes(str(tmp_path / "snapshot-1")) == b"a"
    assert len(local.list_files(str(tmp_path))) == 1


def test_latency_io_keeps_single_wrapper_behavior(tmp_path):
    # existing latency:// scheme: no-arg construction, atomic write still CAS
    io = get_file_io("latency:///x")
    path = f"latency://{tmp_path}/snapshot-5"
    assert io.try_atomic_write(path, b"one")
    assert not io.try_atomic_write(path, b"two")
    assert io.read_bytes(path) == b"one"
    assert len(LocalFileIO().list_files(str(tmp_path))) == 1


def test_chaos_env_spec_configures_process(tmp_path, monkeypatch):
    spec = chaos_spec("cenv", read_ms=1.5, write_ms=2.5, possibility=100, seed=3)
    monkeypatch.setenv(CHAOS_ENV, spec)
    FailingFileIO._states.pop("cenv", None)
    apply_chaos_env()
    assert LatencyFileIO.read_ms == 1.5 and LatencyFileIO.write_ms == 2.5
    st = FailingFileIO._states["cenv"]
    assert st.possibility == 100
    # re-applying (factory re-entry) must NOT reset live fault counters
    st.fails = 7
    apply_chaos_env()
    assert FailingFileIO._states["cenv"].fails == 7
    # the scheme factory applies the env on construction
    io = get_file_io("chaos://cenv/x")
    p = f"chaos://cenv{tmp_path}/h.txt"
    FailingFileIO.retry_until_success("cenv", lambda: io.write_text(p, "hi"))
    FailingFileIO.reset("cenv", max_fails=0, possibility=0)
    assert io.read_text(p) == "hi"
