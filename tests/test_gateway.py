"""Unified multi-tenant gateway (ISSUE 17): per-tenant QoS admission
(weighted-fair byte budgets + inflight caps), the canonical typed-shed
protocol (ShedInfo; the legacy KvBusyError / FlightBusyError /
SubscriberShedError are serializations of it), read-path hedging with
cancellation accounting, the per-tenant SLO surface, and the seeded
mixed-kind storm that measures tenant isolation end to end."""

import contextlib
import os
import threading
import time

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.metrics import gateway_metrics, sql_metrics
from paimon_tpu.options import Options
from paimon_tpu.service import KvBusyError, KvQueryClient, KvQueryServer
from paimon_tpu.service.cluster import (
    ClusterClient,
    ClusterConfig,
    ClusterCoordinator,
    ClusterWorkerAgent,
)
from paimon_tpu.service.flight import FlightBusyError
from paimon_tpu.service.gateway import Gateway, GatewayShedError
from paimon_tpu.service.qos import (
    DEFAULT_TENANT,
    DecayedHistogram,
    QosController,
    SloTracker,
    TenantBudget,
    parse_tenant_configs,
)
from paimon_tpu.service.shed import ShedError, ShedInfo
from paimon_tpu.sql import query
from paimon_tpu.table import load_table
from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

BUCKETS = 4


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


@pytest.fixture(autouse=True)
def _hubs_down():
    from paimon_tpu.service.subscription import SubscriptionHub

    yield
    SubscriptionHub.shutdown_all()


# ---------------------------------------------------------------------------
# qos units: decayed histograms
# ---------------------------------------------------------------------------


def test_decayed_histogram_percentiles_and_empty_window():
    clk = FakeClock()
    h = DecayedHistogram(tau_s=30.0, clock=clk)
    assert h.percentile(50) == 0.0  # empty window reports 0, never NaN
    for _ in range(100):
        h.update(10.0)
    # samples report as their log-bucket's upper bound: conservative,
    # bounded error (<= 25%)
    assert 10.0 <= h.percentile(50) <= 12.6
    assert 10.0 <= h.percentile(99) <= 12.6
    assert h.total_samples == 100


def test_decayed_histogram_tracks_current_behavior():
    clk = FakeClock()
    h = DecayedHistogram(tau_s=30.0, clock=clk)
    for _ in range(100):
        h.update(10.0)
    clk.advance(90.0)  # 3 tau: the old samples fade to ~5 effective
    for _ in range(10):
        h.update(100.0)
    # 10 fresh 100ms samples now outweigh 100 decayed 10ms ones
    assert h.percentile(50) >= 100.0
    assert 13.0 <= h.decayed_count() <= 16.0
    assert h.total_samples == 110  # lifetime counter is undecayed


def test_decayed_histogram_fully_decayed_is_empty():
    clk = FakeClock()
    h = DecayedHistogram(tau_s=30.0, clock=clk)
    h.update(5.0)
    clk.advance(30.0 * 100)
    assert h.percentile(99) == 0.0
    assert h.decayed_count() < 1e-6


# ---------------------------------------------------------------------------
# qos units: tenant budget refill math
# ---------------------------------------------------------------------------


def test_tenant_budget_byte_refill_math_exact():
    clk = FakeClock()
    b = TenantBudget("t", max_inflight=10, retry_after_ms=25, clock=clk)
    b.set_rate(1000.0)  # 1000 B/s; bucket starts full at one second of burst
    assert b.try_admit(800, kind="put") is None  # 200 tokens left
    shed = b.try_admit(500, kind="put")
    assert shed is not None
    assert shed.state == "throttling-bytes" and shed.tenant == "t"
    # retry_after is the EXACT refill deadline: deficit 300 B at 1000 B/s
    assert shed.retry_after_ms == 300
    clk.advance(0.25)  # 450 tokens: still 50 short
    shed = b.try_admit(500, kind="put")
    assert shed is not None and shed.retry_after_ms == 50
    clk.advance(0.051)  # sleep the hint (plus FP slack): refilled
    assert b.try_admit(500, kind="put") is None
    # a shed consumed nothing: two admissions are in flight, not four
    assert b.snapshot()["inflight"] == 2
    b.release()
    b.release()
    assert b.snapshot()["inflight"] == 0
    assert b.snapshot()["admitted"] == 2 and b.snapshot()["shed"] == 2


def test_tenant_budget_inflight_cap_and_release():
    b = TenantBudget("t", max_inflight=2, retry_after_ms=7, clock=FakeClock())
    assert b.try_admit() is None and b.try_admit() is None
    shed = b.try_admit()
    assert shed is not None and shed.state == "busy-inflight"
    assert shed.retry_after_ms == 7
    assert shed.extras["inflight"] == 2 and shed.extras["max_inflight"] == 2
    b.release()
    assert b.try_admit() is None


def test_qos_weighted_fair_shares_and_reshare_on_new_tenant():
    o = (
        Options()
        .set("gateway.bytes-per-sec", "4000 b")
        .set("gateway.tenant.a.weight", "3")
        .set("gateway.tenant.b.weight", "1")
    )
    q = QosController(o, clock=FakeClock())
    assert q.tenants() == ["a", "b", DEFAULT_TENANT]
    snap = q.snapshot()
    # weights 3:1:1 over 4000 B/s
    assert snap["a"]["bytes_per_sec"] == 2400
    assert snap["b"]["bytes_per_sec"] == 800
    assert snap[DEFAULT_TENANT]["bytes_per_sec"] == 800
    # a new tenant appears: fairness re-divides over who actually exists
    q.budget("c")
    snap = q.snapshot()
    assert snap["a"]["bytes_per_sec"] == 2000
    assert snap["b"]["bytes_per_sec"] == snap["c"]["bytes_per_sec"] == 666


def test_qos_per_tenant_hard_cap_beats_fair_share():
    o = (
        Options()
        .set("gateway.bytes-per-sec", "10000 b")
        .set("gateway.tenant.capped.weight", "9")
        .set("gateway.tenant.capped.bytes-per-sec", "1000 b")
    )
    q = QosController(o, clock=FakeClock())
    snap = q.snapshot()
    # fair share would be 9000; the per-tenant cap wins
    assert snap["capped"]["bytes_per_sec"] == 1000


def test_qos_untagged_traffic_lands_in_default_tenant():
    q = QosController(clock=FakeClock())
    name, shed = q.admit(None, "get_batch")
    assert name == DEFAULT_TENANT and shed is None
    q.release(None)
    assert q.snapshot()[DEFAULT_TENANT]["admitted"] == 1


def test_parse_tenant_configs():
    o = (
        Options()
        .set("gateway.tenant.alpha.weight", "2.5")
        .set("gateway.tenant.alpha.max-inflight", "8")
        .set("gateway.tenant.alpha.bytes-per-sec", "2 kb")
        .set("gateway.tenant.team.b.weight", "4")  # dotted tenant id
        .set("gateway.bytes-per-sec", "1 mb")  # not a tenant key
    )
    cfg = parse_tenant_configs(o)
    assert cfg == {
        "alpha": {"weight": 2.5, "max_inflight": 8, "bytes_per_sec": 2048},
        "team.b": {"weight": 4.0},
    }


def test_slo_tracker_surface_shape():
    clk = FakeClock()
    s = SloTracker(tau_s=30.0, clock=clk)
    s.record("vip", "get_batch", 12.0)
    s.record("vip", "get_batch", 12.0, hedged=True)
    s.record_shed("vip", "get_batch")
    out = s.slo()
    e = out["vip"]["kinds"]["get_batch"]
    assert e["samples"] == 2 and e["admitted"] == 2
    assert e["shed"] == 1 and e["hedged"] == 1
    assert e["p50_ms"] >= 12.0 and e["p99_ms"] >= e["p50_ms"]


# ---------------------------------------------------------------------------
# the canonical shed protocol
# ---------------------------------------------------------------------------


def test_shed_info_payload_roundtrip():
    info = ShedInfo(
        kind="get_batch",
        state="busy-reads",
        tenant="vip",
        retry_after_ms=7,
        restart_offset=42,
        extras={"inflight": 3},
    )
    p = info.to_payload()
    assert p["busy"] is True and p["kind"] == "get_batch"
    assert p["next_snapshot"] == 42  # legacy wire alias of restart_offset
    assert p["inflight"] == 3
    back = ShedInfo.from_payload(p)
    assert (back.kind, back.state, back.tenant) == ("get_batch", "busy-reads", "vip")
    assert back.retry_after_ms == 7 and back.restart_offset == 42
    assert back.extras.get("inflight") == 3


def test_legacy_busy_errors_are_shed_serializations():
    kv = KvBusyError({"busy": True, "state": "busy-reads", "retry_after_ms": 9})
    assert isinstance(kv, ShedError)
    assert kv.shed_info.kind == "get_batch" and kv.retry_after_ms == 9

    fb = FlightBusyError({"busy": True, "state": "rejecting", "retry_after_ms": 11})
    assert isinstance(fb, ShedError)
    assert fb.shed_info.kind == "put" and fb.payload["retry_after_ms"] == 11

    from paimon_tpu.service.subscription import SubscriberShedError

    sub = SubscriberShedError(
        ShedInfo(
            kind="subscribe",
            state="busy-subscribers",
            retry_after_ms=13,
            restart_offset=5,
            extras={"consumer_id": "c1"},
        )
    )
    assert isinstance(sub, ShedError)
    assert sub.consumer_id == "c1" and sub.next_snapshot == 5
    # one record, three dialects: a GatewayShedError built from the legacy
    # payload preserves every field
    g = GatewayShedError(ShedInfo.from_payload(sub.payload, kind="subscribe"))
    assert g.shed_info.state == "busy-subscribers"
    assert g.shed_info.restart_offset == 5


# ---------------------------------------------------------------------------
# gateway: local (no cluster route)
# ---------------------------------------------------------------------------

GW_SCHEMA = RowType.of(("k", BIGINT(False)), ("v", DOUBLE()), ("s", STRING()))


@pytest.fixture
def gwcat(tmp_warehouse):
    return FileSystemCatalog(tmp_warehouse, commit_user="gw")


def _mk_table(cat, name="db.t", **extra):
    return cat.create_table(
        name,
        GW_SCHEMA,
        primary_keys=["k"],
        options={"bucket": "2", **extra},
    )


def test_gateway_local_put_get_sql_slo(gwcat):
    t = _mk_table(gwcat)
    with Gateway(t, catalog=gwcat) as gw:
        assert gw.put({"k": [1, 2, 3], "v": [1.0, 2.0, 3.0], "s": ["a", "b", "c"]}) == 3
        assert gw.get_batch([1, 2, 99]) == [(1, 1.0, "a"), (2, 2.0, "b"), None]
        out = gw.sql("SELECT k, v FROM db.t WHERE k <= 2 ORDER BY k")
        assert [tuple(r) for r in out.to_pylist()] == [(1, 1.0), (2, 2.0)]
        plan = gw.sql("EXPLAIN SELECT k, v FROM db.t WHERE k <= 2 ORDER BY k")
        lines = [r[0] for r in plan.to_pylist()]
        assert any(l.startswith("table: db.t") for l in lines)
        slo = gw.slo()
        kinds = slo["tenants"][DEFAULT_TENANT]["kinds"]
        for kind in ("put", "get_batch", "sql"):
            assert kinds[kind]["admitted"] >= 1
            assert kinds[kind]["p99_ms"] > 0.0
        assert "budget" in slo["tenants"][DEFAULT_TENANT]
        assert slo["hedge"]["inflight"] == 0


def test_gateway_inflight_cap_sheds_typed_and_isolated(gwcat):
    t = _mk_table(gwcat)
    g = gateway_metrics()
    typed0 = g.counter("sheds_typed").count
    with Gateway(t, catalog=gwcat, options={"gateway.tenant.greedy.max-inflight": "0"}) as gw:
        with pytest.raises(GatewayShedError) as ei:
            gw.put({"k": [1], "v": [1.0], "s": ["x"]}, tenant="greedy")
        info = ei.value.shed_info
        assert info.state == "busy-inflight" and info.tenant == "greedy"
        assert info.retry_after_ms > 0
        assert ei.value.payload["busy"] is True  # wire shape of the same record
        # the quiet tenant is untouched by greedy's refusals
        assert gw.put({"k": [1], "v": [1.0], "s": ["x"]}, tenant="quiet") == 1
        assert g.counter("sheds_typed").count == typed0 + 1
        slo = gw.slo()
        assert slo["tenants"]["greedy"]["kinds"]["put"]["shed"] == 1
        assert slo["tenants"]["quiet"]["kinds"]["put"]["admitted"] == 1


def test_gateway_byte_budget_sheds_typed(gwcat):
    t = _mk_table(gwcat)
    with Gateway(t, catalog=gwcat, options={"gateway.tenant.slow.bytes-per-sec": "1 b"}) as gw:
        with pytest.raises(GatewayShedError) as ei:
            gw.put({"k": [1, 2], "v": [1.0, 2.0], "s": ["a", "b"]}, tenant="slow")
        info = ei.value.shed_info
        assert info.state == "throttling-bytes" and info.kind == "put"
        assert info.retry_after_ms >= 1
        assert info.extras["bytes_per_sec"] == 1


def test_gateway_user_errors_are_not_untyped_sheds(gwcat):
    t = _mk_table(gwcat)
    g = gateway_metrics()
    with Gateway(t, catalog=gwcat) as gw:
        before = g.counter("sheds_untyped").count
        with pytest.raises(Exception):
            gw.sql("SELECT nope FROM db.missing")
        with pytest.raises(ValueError):
            gw.subscribe_poll("no-such-sub")
        assert g.counter("sheds_untyped").count == before


def test_gateway_subscribe_open_poll_close(gwcat):
    t = _mk_table(gwcat)
    with Gateway(t, catalog=gwcat) as gw:
        gw.put({"k": [1, 2], "v": [1.0, 2.0], "s": ["a", "b"]})
        sid = gw.subscribe_open(from_snapshot=1)
        got = []
        deadline = time.monotonic() + 10.0
        while len(got) < 2 and time.monotonic() < deadline:
            got += gw.subscribe_poll(sid, timeout_ms=500)["rows"]
        assert sorted(got) == [["+I", 1, 1.0, "a"], ["+I", 2, 2.0, "b"]]
        gw.put({"k": [3], "v": [3.0], "s": ["c"]})
        more = []
        deadline = time.monotonic() + 10.0
        while not more and time.monotonic() < deadline:
            more += gw.subscribe_poll(sid, timeout_ms=500)["rows"]
        assert more == [["+I", 3, 3.0, "c"]]
        gw.subscribe_close(sid)
        with pytest.raises(ValueError):
            gw.subscribe_poll(sid)


def test_gateway_subscribe_shed_is_typed(gwcat):
    t = _mk_table(gwcat, name="db.sub1", **{"subscription.max-subscribers": "1"})
    with Gateway(t, catalog=gwcat) as gw:
        gw.put({"k": [1], "v": [1.0], "s": ["a"]})
        sid = gw.subscribe_open()
        with pytest.raises(GatewayShedError) as ei:
            gw.subscribe_open(tenant="late")
        info = ei.value.shed_info
        assert info.kind == "subscribe" and info.state == "busy-subscribers"
        assert info.tenant == "late" and info.retry_after_ms > 0
        gw.subscribe_close(sid)


# ---------------------------------------------------------------------------
# bugfix regressions (ISSUE 17 shed-typing hunt)
# ---------------------------------------------------------------------------


def test_regression_hub_subscribe_after_close_sheds_typed(gwcat):
    """(c) A subscribe racing hub close must answer a typed shutting-down
    shed, never re-register on a dead hub or raise untyped."""
    from paimon_tpu.service.subscription import SubscriberShedError, SubscriptionHub

    t = _mk_table(gwcat, name="db.race")
    hub = SubscriptionHub.for_table(t)
    hub.close()
    with pytest.raises(SubscriberShedError) as ei:
        hub.subscribe(consumer_id="late")
    assert ei.value.payload["state"] == "shutting-down"
    assert ei.value.payload["retry_after_ms"] > 0


def test_regression_put_teardown_backpressure_keeps_typed_result(gwcat, monkeypatch):
    """(b) WriterBackpressureError raised from TableWrite.close during
    teardown must not replace the committed result (or an already-unwinding
    typed shed) with an untyped error."""
    from paimon_tpu.core.admission import WriterBackpressureError
    from paimon_tpu.table.write import TableWrite

    t = _mk_table(gwcat, name="db.bp")
    orig = TableWrite.close

    def bad_close(self, *a, **k):
        orig(self, *a, **k)
        raise WriterBackpressureError("buffer pinned at stop trigger")

    monkeypatch.setattr(TableWrite, "close", bad_close)
    g = gateway_metrics()
    before = g.counter("sheds_untyped").count
    with Gateway(t, catalog=gwcat) as gw:
        assert gw.put({"k": [1], "v": [1.0], "s": ["a"]}) == 1
        assert gw.get_batch([1]) == [(1, 1.0, "a")]
    assert g.counter("sheds_untyped").count == before


def test_regression_flight_poll_subscribe_shed_is_typed_busy(gwcat):
    """(a) hub.subscribe failing at poll time (max-subscribers) must reach
    the Flight client as the same typed BUSY as a mid-poll shed — not an
    untyped FlightServerError."""
    pytest.importorskip("pyarrow.flight")
    from paimon_tpu.service.flight import PaimonFlightServer, flight_subscribe_poll

    _mk_table(gwcat, name="db.fzero", **{"subscription.max-subscribers": "0"})
    srv = PaimonFlightServer(gwcat.warehouse)
    srv.start()
    try:
        with pytest.raises(FlightBusyError) as ei:
            flight_subscribe_poll(srv.location, "db.fzero", "c0", timeout_ms=500)
        assert ei.value.payload["kind"] == "subscribe"
        assert ei.value.payload["state"] == "busy-subscribers"
        assert ei.value.payload["retry_after_ms"] > 0
    finally:
        srv.shutdown()


def test_regression_flight_subscription_after_shutdown_sheds_typed(gwcat):
    """(c, Flight flavor) a poll racing server shutdown() must shed typed
    and must NOT re-create a hub (leaking its tailer threads)."""
    pytest.importorskip("pyarrow.flight")
    from paimon_tpu.service.flight import PaimonFlightServer
    from paimon_tpu.service.subscription import SubscriberShedError

    _mk_table(gwcat, name="db.fdown")
    srv = PaimonFlightServer(gwcat.warehouse)
    srv.start()
    srv.shutdown()
    with pytest.raises(SubscriberShedError) as ei:
        srv._subscription("db.fdown", "late", None)
    assert ei.value.payload["state"] == "shutting-down"
    assert srv._hubs == {}


def test_regression_worker_concurrent_subscribe_open_unique_ids(gwcat):
    """(d) concurrent subscribe_open on a worker server must mint unique
    sub ids (a shadowed Subscription leaks its consumer slot)."""
    from paimon_tpu.service.cluster import _WorkerServer

    t = _mk_table(gwcat, name="db.wopen")
    srv = _WorkerServer(t, owned=set(range(2)))
    try:
        ids, errs = [], []

        def opener(i):
            try:
                r = srv._dispatch("subscribe_open", {"consumer_id": f"c{i}"})
                ids.append(r["sub_id"])
            except Exception as e:  # surfaced below
                errs.append(e)

        ths = [threading.Thread(target=opener, args=(i,)) for i in range(8)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(10)
        assert not errs and len(set(ids)) == 8
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# KV server fronted by the gateway: shared budgets + the slo action
# ---------------------------------------------------------------------------


def test_kv_server_gateway_admission_and_slo_action(gwcat):
    t = _mk_table(gwcat, name="db.kv")
    with Gateway(t, catalog=gwcat, options={"gateway.tenant.greedy.max-inflight": "0"}) as gw:
        gw.put({"k": [1, 2], "v": [1.0, 2.0], "s": ["a", "b"]})
        srv = KvQueryServer(t, gateway=gw)
        host, port = srv.start()
        cli = KvQueryClient(host, port)
        try:
            assert cli.get_batch([1, 9], tenant="vip") == [(1, 1.0, "a"), None]
            with pytest.raises(KvBusyError) as ei:
                cli.get_batch([1], tenant="greedy")
            # the wire payload is the canonical ShedInfo serialization
            assert ei.value.payload["state"] == "busy-inflight"
            assert ei.value.payload["tenant"] == "greedy"
            assert ei.value.retry_after_ms > 0
            slo = cli.slo()
            assert slo["tenants"]["vip"]["kinds"]["get_batch"]["admitted"] >= 1
            assert slo["tenants"]["greedy"]["kinds"]["get_batch"]["shed"] >= 1
        finally:
            cli.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# cluster mode: routed gets, hedging, SQL + fragment cache + EXPLAIN
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _cluster(root, workers, delays=None, heartbeat_timeout_s=4.0):
    coord = ClusterCoordinator(
        root,
        ClusterConfig(
            workers=workers, buckets=BUCKETS, compaction=False,
            heartbeat_timeout_s=heartbeat_timeout_s,
        ),
    ).start()
    agents, cli = [], None
    try:
        for wid in range(workers):
            a = ClusterWorkerAgent(
                wid, load_table(root, commit_user=f"gww{wid}"), coord.host, coord.port,
                serve=True, heartbeat_interval_s=0.1,
                serve_delay_ms=(delays or {}).get(wid),
            )
            a.register()
            a.start_heartbeats()
            agents.append(a)
        cli = ClusterClient(load_table(root, commit_user="gwcli"), coord.host, coord.port)
        yield cli, agents, coord
    finally:
        if cli is not None:
            cli.close()
        for a in agents:
            a.close()
        coord.close()


def _mk_cluster_table(cat, name="db.c", n=600, options=None):
    opts = {"bucket": str(BUCKETS), "write-only": "true"}
    opts.update(options or {})
    t = cat.create_table(
        name,
        RowType.of(("k", BIGINT(False)), ("v", DOUBLE()), ("g", STRING())),
        primary_keys=["k"],
        options=opts,
    )
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    ks = list(range(n))
    w.write({
        "k": ks,
        "v": [x * 0.25 for x in ks],  # exactly-representable doubles
        "g": [f"g{x % 5}" for x in ks],
    })
    wb.new_commit().commit(w.prepare_commit())
    return t


def test_gateway_hedged_get_beats_straggler_and_drains(gwcat):
    """One worker latency-shamed far past the hedge deadline: gets owned by
    it are hedged to the healthy non-owner, win, stay bit-identical, and
    every losing attempt is cancelled and drained (no orphaned RPC)."""
    t = _mk_cluster_table(gwcat)
    g = gateway_metrics()
    with _cluster(t.path, 2, delays={0: 250}) as (cli, _agents, _coord):
        won0 = g.counter("hedges_won").count
        cancelled0 = g.counter("hedges_cancelled").count
        with Gateway(
            t, catalog=gwcat, client=cli,
            options={"gateway.hedge.deadline-ms": "25", "gateway.hedge.max-fraction": "1.0"},
        ) as gw:
            keys = list(range(0, 40)) + [999_999]
            t0 = time.perf_counter()
            got = gw.get_batch(keys)
            elapsed_ms = (time.perf_counter() - t0) * 1000.0
            want = [(k, k * 0.25, f"g{k % 5}") if k < 600 else None for k in keys]
            assert got == want
            # the straggler would cost >= 250 ms; the hedge must beat it
            assert elapsed_ms < 250.0
            assert g.counter("hedges_won").count > won0
            assert g.counter("hedges_cancelled").count > cancelled0
            assert gw.wait_hedges_drained(10.0)
            assert gw.hedge_inflight() == 0
            hedge = gw.slo()["hedge"]
            assert hedge["hedges_issued"] <= hedge["hedgeable_requests"]


def test_gateway_hedge_max_fraction_zero_never_hedges(gwcat):
    t = _mk_cluster_table(gwcat, name="db.c0")
    g = gateway_metrics()
    with _cluster(t.path, 2, delays={0: 150}) as (cli, _agents, _coord):
        issued0 = g.counter("hedges_issued").count
        with Gateway(
            t, catalog=gwcat, client=cli,
            options={"gateway.hedge.deadline-ms": "10", "gateway.hedge.max-fraction": "0.0"},
        ) as gw:
            got = gw.get_batch([0, 1, 2, 3])
            assert got == [(k, k * 0.25, f"g{k % 5}") for k in range(4)]
            assert g.counter("hedges_issued").count == issued0
            assert gw.wait_hedges_drained(10.0)


def test_gateway_hedged_sql_scan_fragments(gwcat):
    """Scan fragments route through the same hedged RPC seam: a shamed
    worker's fragment is re-issued and the aggregate stays bit-identical to
    the local evaluator."""
    t = _mk_cluster_table(gwcat, name="db.ch")
    g = gateway_metrics()
    q = "SELECT g, count(*), sum(v) FROM db.ch GROUP BY g ORDER BY g"
    want = query(gwcat, q).to_pylist()
    # 700 ms shame, 25 ms deadline: the hedge must win even when the
    # secondary pays first-scan JIT compile (a 250 ms shame lost the race
    # ~30% of the time — both attempts compile, the margin was noise)
    with _cluster(t.path, 2, delays={0: 700}) as (cli, _agents, _coord):
        won0 = g.counter("hedges_won").count
        with Gateway(
            t, catalog=gwcat, client=cli,
            options={"gateway.hedge.deadline-ms": "25", "gateway.hedge.max-fraction": "1.0"},
        ) as gw:
            assert gw.sql(q).to_pylist() == want
            assert g.counter("hedges_won").count > won0
            assert gw.wait_hedges_drained(10.0)


def test_gateway_cluster_sql_fragment_cache_and_explain(gwcat):
    t = _mk_cluster_table(gwcat, name="db.cc")
    q = "SELECT g, count(*), sum(v) FROM db.cc GROUP BY g ORDER BY g"
    with _cluster(t.path, 2) as (cli, _agents, _coord):
        with Gateway(t, catalog=gwcat, client=cli) as gw:
            want = query(gwcat, q).to_pylist()
            assert gw.sql(q).to_pylist() == want
            # identical statement at the same snapshot: answered from the
            # coordinator's fragment cache, zero worker RPCs
            hits0 = sql_metrics().counter("fragment_cache_hits").count
            frags0 = sql_metrics().counter("fragments").count
            assert gw.sql(q).to_pylist() == want
            assert sql_metrics().counter("fragment_cache_hits").count == hits0 + 1
            assert sql_metrics().counter("fragments").count == frags0
            # a commit advances the snapshot: stale entries purged, fresh scatter
            gw.put({"k": [10_000], "v": [2.5], "g": ["g9"]})
            want2 = query(gwcat, q).to_pylist()
            assert want2 != want
            assert gw.sql(q).to_pylist() == want2
            # EXPLAIN through the same front door shows the fragment plan
            lines = [r[0] for r in gw.sql("EXPLAIN " + q).to_pylist()]
            assert any(l.startswith("fragment -> worker") for l in lines)
            assert any(l.startswith("cluster: code-domain") for l in lines)
            assert any(l.startswith("shape: grouped aggregate") for l in lines)


# ---------------------------------------------------------------------------
# the storm: 64 clients, 4 tenants (one greedy), one shamed worker
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_gateway_mixed_kind_storm(tmp_path):
    """Tenant isolation measured end to end: a greedy tenant slams puts into
    tight byte/inflight budgets while a quiet tenant's point-gets must keep
    their solo latency profile; every refusal anywhere is the one typed
    shed protocol (gateway{sheds_untyped} stays 0) and every shed carries a
    positive retry_after hint."""
    duration = float(os.environ.get("PAIMON_TPU_SOAK_DURATION", "8"))
    seed = int(os.environ.get("PAIMON_TPU_SOAK_SEED", "0"))
    cat = FileSystemCatalog(str(tmp_path / "wh"), commit_user="storm")
    # compaction stays ON (a write-only table with no compactor grows one
    # file per bucket per commit, and read cost with it), but each round's
    # input is capped so its CPU burst stays small; one scan fragment at a
    # time per worker keeps SQL from convoying the point-get plane
    t = _mk_cluster_table(
        cat,
        name="db.s",
        n=1000,
        options={
            "write-only": "false",
            "sql.cluster.scan.max-inflight": "1",
            "compaction.max.file-num": "5",
        },
    )
    gw_opts = {
        "gateway.tenant.greedy.bytes-per-sec": "4 kb",
        "gateway.tenant.greedy.max-inflight": "4",
        "gateway.tenant.quiet.weight": "4.0",
        "gateway.hedge.deadline-ms": "50",
        "gateway.hedge.max-fraction": "0.8",
    }
    g = gateway_metrics()
    with _cluster(t.path, 2, delays={0: 15}) as (cli, _agents, _coord):
        with Gateway(t, catalog=cat, client=cli, options=gw_opts) as gw:
            # -- warm every kind once (imports, first-touch index builds,
            # kernel compile): the storm measures steady-state admission,
            # not the cost of the very first request of each shape
            gw.put({"k": [9_000_001], "v": [1.0], "g": ["g0"]}, tenant="warm")
            gw.get_batch([1, 2, 3], tenant="warm")
            gw.sql("SELECT g, count(*) FROM db.s GROUP BY g ORDER BY g", tenant="warm")
            ws = gw.subscribe_open(tenant="warm")
            gw.subscribe_poll(ws, timeout_ms=10, tenant="warm")
            gw.subscribe_close(ws)

            # -- solo baseline: the quiet tenant alone on the same cluster
            rng = np.random.default_rng(seed)
            solo = []
            end = time.monotonic() + min(3.0, duration / 3)
            while time.monotonic() < end:
                probe = rng.integers(0, 1000, size=8).tolist()
                t0 = time.perf_counter()
                gw.get_batch(probe, tenant="quiet")
                solo.append((time.perf_counter() - t0) * 1000.0)
                time.sleep(0.05)
            solo_p50 = float(np.percentile(solo, 50))
            solo_p99 = float(np.percentile(solo, 99))

            untyped0 = g.counter("sheds_untyped").count
            stop = threading.Event()
            lock = threading.Lock()
            greedy_sheds, errors, quiet_lat = [], [], []
            tenants = ["greedy", "quiet", "team-a", "team-b"]

            t_start = time.monotonic()

            def client(idx):
                trng = np.random.default_rng(seed * 1000 + idx)
                tenant = tenants[idx % 4]
                sub_id = None
                # paced clients: the storm measures admission fairness, not
                # how hard one python process can saturate its own GIL
                while not stop.is_set():
                    try:
                        if tenant == "greedy":
                            # bounded keyspace: PK upserts keep the table
                            # size stable while commits keep coming
                            base = 2000 + int(trng.integers(0, 2000))
                            kk = [base + i for i in range(256)]
                            gw.put(
                                {"k": kk, "v": [x * 0.25 for x in kk],
                                 "g": [f"g{x % 5}" for x in kk]},
                                tenant=tenant,
                            )
                            stop.wait(0.5)
                        elif tenant == "quiet":
                            probe = trng.integers(0, 1000, size=8).tolist()
                            t0 = time.perf_counter()
                            gw.get_batch(probe, tenant=tenant)
                            with lock:
                                quiet_lat.append(
                                    (time.monotonic() - t_start,
                                     (time.perf_counter() - t0) * 1000.0)
                                )
                            stop.wait(0.3)
                        else:
                            r = float(trng.random())
                            if r < 0.55:
                                gw.get_batch(
                                    trng.integers(0, 1000, size=4).tolist(), tenant=tenant
                                )
                            elif r < 0.57:
                                gw.sql(
                                    "SELECT g, count(*) FROM db.s GROUP BY g ORDER BY g",
                                    tenant=tenant,
                                )
                            elif r < 0.99:
                                if sub_id is None:
                                    sub_id = gw.subscribe_open(tenant=tenant)
                                gw.subscribe_poll(sub_id, timeout_ms=20, tenant=tenant)
                            else:
                                # puts stay rare on the team tenants: every
                                # commit costs a refresh + eventual
                                # compaction round on both workers, which is
                                # engine physics, not the admission fairness
                                # under test
                                kk = [60_000 + int(x) for x in trng.integers(0, 5000, size=8)]
                                gw.put(
                                    {"k": kk, "v": [x * 0.25 for x in kk],
                                     "g": [f"g{x % 5}" for x in kk]},
                                    tenant=tenant,
                                )
                                stop.wait(0.25)
                            stop.wait(0.75)
                    except GatewayShedError as e:
                        info = e.shed_info
                        with lock:
                            if info.tenant == "greedy":
                                greedy_sheds.append(info)
                            if not info.retry_after_ms or info.retry_after_ms <= 0:
                                errors.append(("shed-without-retry-hint", info.to_payload()))
                        if info.kind == "subscribe":
                            sub_id = None
                        stop.wait(min(info.retry_after_ms or 25, 200) / 1000.0)
                    except Exception as e:  # pragma: no cover - asserted below
                        with lock:
                            errors.append((tenant, repr(e)))
                        stop.wait(0.05)
                if sub_id is not None:
                    with contextlib.suppress(Exception):
                        gw.subscribe_close(sub_id)

            threads = [
                threading.Thread(target=client, args=(i,), name=f"storm-{i}")
                for i in range(64)
            ]
            for th in threads:
                th.start()
            time.sleep(duration)
            stop.set()
            for th in threads:
                th.join(timeout=120)
            assert not [th for th in threads if th.is_alive()], "storm clients hung"

            assert not errors, errors[:5]
            assert greedy_sheds, "greedy tenant was never shed"
            assert all(i.retry_after_ms > 0 for i in greedy_sheds)
            # ONE shed protocol: nothing escaped untyped, anywhere
            assert g.counter("sheds_untyped").count == untyped0
            arr = np.array(quiet_lat)
            # drop the ramp window (64 client threads starting + residual
            # first-touch work); keep everything if the run is too short to
            # have a steady state
            steady = arr[arr[:, 0] >= 2.0][:, 1]
            if len(steady) < 50:
                steady = arr[:, 1]
            quiet_p50 = float(np.percentile(steady, 50))
            quiet_p90 = float(np.percentile(steady, 90))
            quiet_p99 = float(np.percentile(steady, 99))
            # Isolation bounds, in three tiers. The whole cluster —
            # coordinator, 2 workers, gateway, 64 clients — shares ONE
            # interpreter here, so engine CPU bursts (a compaction round, a
            # scan fragment) hit every tenant at once in a way no admission
            # control can prevent; a real deployment spreads these across
            # processes. The gateway owns the queueing behavior, so the
            # typical quantiles are held tight against the solo baseline,
            # while the p99 gets an absolute ceiling that still catches
            # queueing collapse (without per-tenant admission the greedy
            # commit storm pushes p50 past 200ms and p99 past a second;
            # with the hedge-pool bug this PR fixes, p99 sat at ~800ms).
            assert quiet_p50 <= max(2.0 * solo_p50, solo_p50 + 25.0), (quiet_p50, solo_p50)
            assert quiet_p90 <= max(1.5 * solo_p99, solo_p99 + 75.0), (quiet_p90, solo_p99)
            assert quiet_p99 <= solo_p99 + 500.0, (quiet_p99, solo_p99)
            slo = gw.slo()
            assert slo["tenants"]["greedy"]["kinds"]["put"]["shed"] >= 1
            assert slo["tenants"]["quiet"]["kinds"]["get_batch"]["admitted"] > 0
            hedge = slo["hedge"]
            assert hedge["hedges_issued"] <= (
                hedge["max_fraction"] * max(hedge["hedgeable_requests"], 1) + 1
            )
            assert gw.wait_hedges_drained(30.0)
    assert gw.hedge_inflight() == 0


# ---------------------------------------------------------------------------
# gateway under faults (ISSUE 18): routed reads across a worker respawn
# ---------------------------------------------------------------------------


def test_regression_routed_get_fails_over_transient_worker_fault(gwcat):
    """The primary worker's serving socket dies mid-stream (the respawn
    window of the mega soak): gets owned by it must fail over to the
    surviving worker and return bit-identical rows, with route_failovers
    counted and ZERO untyped sheds — a dead socket is pressure, and
    pressure is typed."""
    t = _mk_cluster_table(gwcat, name="db.cf")
    g = gateway_metrics()
    with _cluster(t.path, 2) as (cli, agents, _coord):
        with Gateway(t, catalog=gwcat, client=cli) as gw:
            keys = list(range(0, 48)) + [999_999]
            want = [(k, k * 0.25, f"g{k % 5}") if k < 600 else None for k in keys]
            assert gw.get_batch(keys) == want  # healthy baseline
            untyped0 = g.counter("sheds_untyped").count
            failovers0 = g.counter("route_failovers").count
            # SIGKILL shape, not a polite drain: tear down worker 0's
            # listening socket without setting its _closed flag (which
            # would answer in-flight requests with a typed shutting-down
            # BUSY), and drop the cached conn so the next call reconnects
            # into a refused socket. Heartbeats keep it registered, so the
            # route still points at the dead address — the respawn window.
            srv = agents[0].server._server
            srv.shutdown()
            srv.server_close()
            cli.drop_conn(0)
            gw._pool.close()  # cached sockets still reach the dead server's threads
            got = gw.get_batch(keys)
            assert got == want  # bit-identical from the surviving worker
            assert g.counter("route_failovers").count > failovers0
            assert g.counter("sheds_untyped").count == untyped0


def test_regression_unowned_bucket_routes_to_live_worker(gwcat):
    """A bucket whose owner vanished from the route entirely (killed and
    not yet re-registered) must route to any live worker — shared
    filesystem, same answer — not raise a raw KeyError through get_batch
    (the flagship mega-soak failure shape)."""
    t = _mk_cluster_table(gwcat, name="db.cu")
    g = gateway_metrics()
    with _cluster(t.path, 2) as (cli, _agents, _coord):
        with Gateway(t, catalog=gwcat, client=cli) as gw:
            keys = list(range(0, 32))
            want = [(k, k * 0.25, f"g{k % 5}") for k in keys]
            assert gw.get_batch(keys) == want
            untyped0 = g.counter("sheds_untyped").count
            # simulate the respawn window: strip every bucket worker 0 owns
            # from the client's route, keeping worker 0's address live
            cli.refresh_route()
            full_route = dict(cli._route)
            orphaned = {b: w for b, w in full_route.items() if w != 0}
            assert len(orphaned) < len(full_route), "worker 0 owns no bucket"
            real_refresh = cli.refresh_route
            cli.refresh_route = lambda: None  # the coordinator still hasn't reassigned
            try:
                cli._route = dict(orphaned)
                assert gw.get_batch(keys) == want
                assert g.counter("sheds_untyped").count == untyped0
            finally:
                cli.refresh_route = real_refresh


def test_regression_dead_route_shed_has_sane_retry_after(gwcat):
    """EVERY worker dead (the whole pool mid-respawn): the escape must be
    the typed 'route-respawning' shed carrying a positive retry_after_ms —
    never None, never negative, never a raw ConnectionError/KeyError."""
    t = _mk_cluster_table(gwcat, name="db.cd")
    g = gateway_metrics()
    with _cluster(t.path, 2) as (cli, agents, _coord):
        with Gateway(t, catalog=gwcat, client=cli) as gw:
            assert gw.get_batch([1, 2, 3]) == [
                (k, k * 0.25, f"g{k % 5}") for k in (1, 2, 3)
            ]
            untyped0 = g.counter("sheds_untyped").count
            for wid, a in enumerate(agents):
                srv = a.server._server
                srv.shutdown()
                srv.server_close()
                cli.drop_conn(wid)
            gw._pool.close()
            with pytest.raises(GatewayShedError) as ei:
                gw.get_batch([1, 2, 3])
            info = ei.value.shed_info
            assert info.state == "route-respawning"
            assert isinstance(info.retry_after_ms, int)
            assert info.retry_after_ms >= 1
            assert g.counter("sheds_untyped").count == untyped0
