"""Wave-C option behaviors (the 25 keys closing the CoreOptions.java gap):
each test exercises the OPTION'S EFFECT, not just the key string."""

import time

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.options import CoreOptions, Options
from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

SCHEMA = RowType.of(("id", BIGINT()), ("v", DOUBLE()), ("s", STRING()))


@pytest.fixture
def cat(tmp_warehouse):
    return FileSystemCatalog(tmp_warehouse, commit_user="t")


def _write(t, ids, tag=None):
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    ids = np.asarray(ids, dtype=np.int64)
    w.write({"id": ids, "v": ids * 0.5, "s": np.array([f"s{i}" for i in ids], dtype=object)})
    wb.new_commit().commit(w.prepare_commit())


def _read_ids(t, predicate=None):
    rb = t.new_read_builder()
    if predicate is not None:
        rb = rb.with_filter(predicate)
    return sorted(r[0] for r in rb.new_read().read_all(rb.new_scan().plan()).to_pylist())


# ---- schema-from-options -------------------------------------------------

def test_primary_key_partition_via_options(cat):
    t = cat.create_table("db.o", SCHEMA, options={"primary-key": "id", "bucket": "1"})
    assert t.primary_keys == ["id"]
    _write(t, [1, 2, 1])
    assert _read_ids(t) == [1, 2]  # upserted => PK semantics active
    with pytest.raises(ValueError, match="both"):
        cat.create_table("db.o2", SCHEMA, primary_keys=["id"], options={"primary-key": "id"})


def test_auto_create_on_load(tmp_path):
    from paimon_tpu.table import load_table

    path = str(tmp_path / "auto_t")
    with pytest.raises(FileNotFoundError):
        load_table(path)
    t = load_table(path, dynamic_options={"auto-create": "true", "primary-key": "id", "bucket": "1"},
                   row_type=SCHEMA)
    assert t.primary_keys == ["id"]
    _write(t, [5])
    assert _read_ids(load_table(path)) == [5]  # storage persisted


# ---- file index ----------------------------------------------------------

def test_file_index_embeds_and_prunes(cat):
    from paimon_tpu.data import predicate as P

    t = cat.create_table(
        "db.fi", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "write-only": "true",
                 "file-index.bloom-filter.columns": "id",
                 "file-index.in-manifest-threshold": "1 mb"},
    )
    # overlapping key RANGES (evens vs odds) so min/max stats cannot prune —
    # only the bloom index can tell the files apart
    _write(t, range(0, 200, 2))
    _write(t, range(1, 200, 2))
    plan = t.store.new_scan().plan()
    files = [f for bs in plan.grouped().values() for fs in bs.values() for f in fs]
    assert all(f.embedded_index is not None for f in files)  # under threshold => embedded
    assert all(not f.extra_files for f in files)
    # bloom prunes the even file for an odd key at plan time
    rb = t.new_read_builder().with_filter(P.equal("id", 151))
    splits = rb.new_scan().plan()
    assert sum(len(s.files) for s in splits) == 1
    assert _read_ids(t, P.equal("id", 151)) == [151]
    # read gate off => no pruning (both files planned)
    t2 = t.copy({"file-index.read.enabled": "false"})
    rb2 = t2.new_read_builder().with_filter(P.equal("id", 151))
    assert sum(len(s.files) for s in rb2.new_scan().plan()) == 2


def test_file_index_sidecar_above_threshold(cat):
    t = cat.create_table(
        "db.fs", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "file-index.bloom-filter.columns": "id",
                 "file-index.in-manifest-threshold": "8 b"},
    )
    _write(t, range(200))
    plan = t.store.new_scan().plan()
    files = [f for bs in plan.grouped().values() for fs in bs.values() for f in fs]
    assert all(f.embedded_index is None for f in files)
    assert all(any(x.endswith(".index") for x in f.extra_files) for f in files)


# ---- manifest full compaction --------------------------------------------

def test_manifest_full_compaction_threshold(cat):
    t = cat.create_table(
        "db.mfc", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "write-only": "true",
                 "manifest.merge-min-count": "1000",  # count trigger off
                 "manifest.full-compaction-threshold-size": "1 b"},  # size trigger always on
    )
    for i in range(4):
        _write(t, range(i * 10, i * 10 + 10))
    snap = t.store.snapshot_manager.latest_snapshot()
    from paimon_tpu.core.manifest import ManifestList

    ml = ManifestList(t.file_io, f"{t.path}/manifest")
    # full compaction folded history into base; only the newest delta remains
    base = ml.read(snap.base_manifest_list)
    assert base, "full compaction should have produced base manifests"
    assert _read_ids(t) == list(range(40))


# ---- lookup knobs --------------------------------------------------------

def test_lookup_bloom_and_load_factor(cat):
    from paimon_tpu.table.query import LocalTableQuery

    t = cat.create_table(
        "db.lk", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "lookup.cache.bloom.filter.enabled": "true",
                 "lookup.hash-load-factor": "0.5",
                 "lookup.cache-max-memory-size": "1 mb"},
    )
    _write(t, range(100))
    q = LocalTableQuery(t)
    hit = q.lookup((), (42,))
    assert hit is not None and hit.column("v").values[0] == 21.0
    assert q.lookup((), (424242,)) is None  # bloom fast-negative path
    # the accelerators are actually armed
    lv = next(iter(q._levels.values()))
    lf = lv._lookup_file(lv.levels.all_files()[0])
    assert lf.bloom is not None and lf.slot_shift is not None


def test_lookup_disk_cache_sweep(cat, tmp_path):
    from paimon_tpu.table.query import LocalTableQuery

    t = cat.create_table(
        "db.ld", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "lookup.cache-max-disk-size": "1 b",
                 "lookup.cache-file-retention": "1 ms"},
    )
    _write(t, range(50))
    store_dir = str(tmp_path / "lkstore")
    q = LocalTableQuery(t, local_store_dir=store_dir)
    assert q.lookup((), (7,)) is not None
    _write(t, range(50, 60))
    q.refresh()
    time.sleep(0.01)
    assert q.lookup((), (55,)) is not None  # triggers sweep of expired files


# ---- dynamic bucket ------------------------------------------------------

def test_dynamic_bucket_initial_buckets_spread(cat):
    t = cat.create_table(
        "db.dyn", SCHEMA, primary_keys=["id"],
        options={"bucket": "-1", "dynamic-bucket.target-row-num": "1000000",
                 "dynamic-bucket.initial-buckets": "4"},
    )
    _write(t, range(1000))
    plan = t.store.new_scan().plan()
    buckets = {b for bs in plan.grouped().values() for b in bs}
    assert len(buckets) == 4  # rows spread across the initial window


def test_dynamic_bucket_assigner_striping():
    from paimon_tpu.core.bucket_index import SimpleHashBucketAssigner

    a = SimpleHashBucketAssigner(None, target_bucket_rows=10, num_assigners=3, assign_id=1)
    out = a.assign((), np.arange(100, dtype=np.uint64))
    assert set(np.unique(out) % 3) == {1}  # only this assigner's stripe


# ---- cross partition -----------------------------------------------------

def test_cross_partition_index_ttl(cat):
    schema = RowType.of(("pt", STRING(False)), ("id", BIGINT(False)), ("v", DOUBLE()))
    t = cat.create_table(
        "db.xp", schema, primary_keys=["id"], partition_keys=["pt"],
        options={"bucket": "-1", "cross-partition-upsert.index-ttl": "0 ms",
                 "cross-partition-upsert.bootstrap-parallelism": "2"},
    )
    from paimon_tpu.table.crosspartition import CrossPartitionUpsertWrite

    w = CrossPartitionUpsertWrite(t)
    assert w.assigner.index_ttl_millis == 0
    assert w.assigner.bootstrap_parallelism == 2
    w.assigner.index[("k",)] = ((), 0, 0)  # born at epoch => instantly expired
    assert w.assigner._get_live(("k",)) is None


# ---- deletion vectors ----------------------------------------------------

def test_dv_container_chain_roundtrip(tmp_path):
    from paimon_tpu.core.deletionvectors import DeletionVector, DeletionVectorsIndexFile
    from paimon_tpu.fs import LocalFileIO

    io = LocalFileIO()
    idx = DeletionVectorsIndexFile(io, str(tmp_path), target_size=64)  # tiny => chains
    dvs = {f"f{i}": DeletionVector(np.arange(i * 5, i * 5 + 40, dtype=np.int64)) for i in range(6)}
    name, total = idx.write(dvs)
    assert total == 6 * 40
    assert len(idx.chain_names(name)) > 1  # actually rolled
    back = idx.read_all(name)
    assert set(back) == set(dvs)
    for k in dvs:
        assert back[k].cardinality == dvs[k].cardinality


# ---- write buffer for append ---------------------------------------------

def test_write_buffer_for_append_spills(cat, tmp_path):
    t = cat.create_table(
        "db.app", SCHEMA,
        options={"bucket": "1", "write-buffer-for-append": "true",
                 "write-buffer-spill.rows": "10",
                 "write-buffer-spill.max-disk-size": "100 mb"},
    )
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    for lo in range(0, 100, 20):
        ids = np.arange(lo, lo + 20, dtype=np.int64)
        w.write({"id": ids, "v": ids * 0.5, "s": np.array(["x"] * 20, dtype=object)})
    wb.new_commit().commit(w.prepare_commit())
    assert _read_ids(t) == list(range(100))


def test_spill_max_disk_gate():
    from paimon_tpu.core.disk import IOManager, SpillableBuffer
    from paimon_tpu.data.batch import ColumnBatch

    schema = RowType.of(("x", BIGINT()))
    buf = SpillableBuffer(IOManager(), in_memory_rows=1, max_disk_bytes=1)
    buf.add(ColumnBatch.from_pydict(schema, {"x": list(range(10))}))
    buf.add(ColumnBatch.from_pydict(schema, {"x": list(range(10))}))  # disk now full
    assert buf.disk_full
    before = len(buf._spilled)
    buf.add(ColumnBatch.from_pydict(schema, {"x": list(range(10))}))
    assert len(buf._spilled) == before  # no further spilling
    assert buf.num_rows == 30
    buf.clear()
    assert not buf.disk_full


# ---- snapshot expire / watermark ----------------------------------------

def test_async_snapshot_expire(cat):
    t = cat.create_table(
        "db.exp", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "snapshot.num-retained.min": "1",
                 "snapshot.num-retained.max": "1", "snapshot.time-retained": "0 ms",
                 "snapshot.expire.execution-mode": "async"},
    )
    for i in range(4):
        _write(t, [i])
    assert t.expire_snapshots() == 0  # returns immediately
    t._expire_future.result(timeout=30)  # background run completes
    sm = t.store.snapshot_manager
    assert sm.earliest_snapshot_id() == sm.latest_snapshot_id()


def test_watermark_idle_timeout(cat):
    t = cat.create_table(
        "db.wm", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "snapshot.watermark-idle-timeout": "1 ms"},
    )
    _write(t, [1])
    rb = t.new_read_builder()
    scan = rb.new_stream_scan()
    scan.plan()
    time.sleep(0.01)
    wm = scan.current_watermark()
    assert wm is not None and wm > 0  # advanced to processing time while idle


# ---- lookup-wait ----------------------------------------------------------

def test_lookup_wait_false_defers_changelog_to_compaction(cat):
    t = cat.create_table(
        "db.lw", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "changelog-producer": "lookup",
                 "changelog-producer.lookup-wait": "false"},
    )
    _write(t, [1, 2])
    sm = t.store.snapshot_manager
    first = sm.snapshot(sm.latest_snapshot_id())
    # the write's APPEND snapshot carries no changelog (deferred)...
    appends = [s for s in sm.snapshots() if s.commit_kind.value == "APPEND"]
    assert all(not s.changelog_manifest_list for s in appends)
    # ...the compaction emits it
    from paimon_tpu.table.compactor import DedicatedCompactor

    DedicatedCompactor(t).run_once(full=True)
    compacts = [s for s in sm.snapshots() if s.commit_kind.value == "COMPACT"]
    assert any(s.changelog_manifest_list for s in compacts)


# ---- zorder / sort compaction knobs ---------------------------------------

def test_zorder_var_length_contribution(cat):
    t = cat.create_table(
        "db.z", SCHEMA,
        options={"bucket": "1", "zorder.var-length-contribution": "1",
                 "sort-compaction.range-strategy": "size"},
    )
    _write(t, range(500))
    from paimon_tpu.table.sort_compact import sort_compact

    n = sort_compact(t, ["s", "id"], order="zorder")
    assert n == 500
    assert _read_ids(t) == list(range(500))  # clustering is lossless


def test_range_shuffle_sample_magnification():
    import jax
    from jax.sharding import Mesh

    from paimon_tpu.parallel.merge import range_partition_lanes

    devs = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("key",))
    n = 1024
    rng = np.random.default_rng(3)
    kl = rng.integers(0, 1 << 30, size=(n, 1), dtype=np.uint32)
    sl = np.zeros((n, 0), dtype=np.uint32)
    pad = np.zeros(n, dtype=np.uint32)
    out_k, perm, keep, out_pad = range_partition_lanes(mesh, kl, sl, pad, sample_per_device=8)
    kept = np.asarray(out_k)[np.asarray(out_pad) == 0, 0]
    # all rows survive the exchange exactly once
    assert sorted(kept.tolist()) == sorted(kl[:, 0].tolist())


def test_deprecated_alias_keys_accepted():
    """Reference withDeprecatedKeys aliases resolve to their successors
    (CoreOptions.java: write-only<-write.compaction-skip, scan.mode<-log.scan,
    ignore-delete<-*.ignore-delete, compaction.max.file-num<-early-max,
    scan.timestamp-millis<-log.scan.timestamp-millis)."""
    from paimon_tpu.options import CoreOptions, Options, StartupMode

    o = Options({
        "write.compaction-skip": "true",
        "log.scan": "from-snapshot",
        "partial-update.ignore-delete": "true",
        "compaction.early-max.file-num": "7",
        "log.scan.timestamp-millis": "123",
    })
    assert o.get(CoreOptions.WRITE_ONLY) is True
    assert o.get(CoreOptions.SCAN_MODE) == StartupMode.FROM_SNAPSHOT
    assert o.get(CoreOptions.IGNORE_DELETE) is True
    assert o.get(CoreOptions.COMPACTION_MAX_FILE_NUM) == 7
    assert o.get(CoreOptions.SCAN_TIMESTAMP_MILLIS) == 123
    # the canonical key wins over an alias when both are present
    o2 = Options({"write-only": "false", "write.compaction-skip": "true"})
    assert o2.get(CoreOptions.WRITE_ONLY) is False


def test_deprecated_full_scan_mode_value():
    """log.scan=full (the primary legacy value) maps to latest-full, as the
    reference's deprecated StartupMode.FULL does."""
    from paimon_tpu.options import CoreOptions, Options, StartupMode

    o = Options({"log.scan": "full"})
    assert o.get(CoreOptions.SCAN_MODE) == StartupMode.LATEST_FULL
    assert StartupMode("full") is StartupMode.LATEST_FULL
