"""Randomized whole-store oracle test: a long random sequence of upserts,
deletes, compactions, expiry, and time travel must always agree with a plain
python dict replay (mirrors the reference's randomized table read-write
suites in paimon-core/src/test/.../table/)."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

SCHEMA = RowType.of(("k", BIGINT()), ("s", STRING()), ("v", DOUBLE()))


@pytest.mark.parametrize("seed", [7, 21])
def test_random_ops_match_dict_oracle(tmp_warehouse, seed):
    rng = np.random.default_rng(seed)
    cat = FileSystemCatalog(f"{tmp_warehouse}/{seed}", commit_user="oracle")
    t = cat.create_table(
        "db.r",
        SCHEMA,
        primary_keys=["k"],
        options={
            "bucket": "2",
            "num-sorted-run.compaction-trigger": "3",
            "target-file-size": "4 kb",
        },
    )
    oracle: dict[int, tuple] = {}
    history: list[dict] = []  # snapshot of oracle after each commit

    def do_commit(rows, deletes):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        if rows:
            ks = [r[0] for r in rows]
            w.write({"k": ks, "s": [r[1] for r in rows], "v": [r[2] for r in rows]})
            for r in rows:
                oracle[r[0]] = (r[0], r[1], r[2])
        if deletes:
            w.write(
                {"k": deletes, "s": [None] * len(deletes), "v": [None] * len(deletes)},
                kinds=["-D"] * len(deletes),
            )
            for k in deletes:
                oracle.pop(k, None)
        if rng.random() < 0.2:
            w.compact(full=rng.random() < 0.5)
        wb.new_commit().commit(w.prepare_commit())
        history.append(dict(oracle))

    for step in range(14):
        n = int(rng.integers(1, 60))
        keys = rng.integers(0, 120, n)
        rows = [(int(k), f"s{int(k)}-{step}", float(step) + float(k) / 1000) for k in keys]
        # dedupe within the batch: later occurrence wins (matches upsert order)
        uniq = {}
        for r in rows:
            uniq[r[0]] = r
        deletes = [int(k) for k in rng.choice(list(oracle), size=min(len(oracle), 5), replace=False)] if oracle and rng.random() < 0.5 else []
        uniq = {k: v for k, v in uniq.items() if k not in deletes}
        do_commit(list(uniq.values()), deletes)

        rb = t.new_read_builder()
        got = {r[0]: r for r in rb.new_read().read_all(rb.new_scan().plan()).to_pylist()}
        assert got == oracle, f"divergence at step {step}"

    # time travel back through every committed snapshot (APPEND ones advance
    # the logical state; COMPACT snapshots in between must not change it)
    sm = t.store.snapshot_manager
    logical = 0
    for snap in sm.snapshots():
        tt = t.copy({"scan.snapshot-id": str(snap.id)})
        rb = tt.new_read_builder()
        got = {r[0]: r for r in rb.new_read().read_all(rb.new_scan().plan()).to_pylist()}
        if snap.commit_kind.value == "APPEND":
            logical += 1
        assert got == history[logical - 1], f"time travel divergence at snapshot {snap.id}"


@pytest.mark.parametrize("seed", [3])
def test_random_ops_cache_parity(tmp_warehouse, seed):
    """Byte-budget caches must be invisible to semantics: the same randomized
    churn (upserts, deletes, compactions, snapshot expiry) read through a
    cache-enabled handle and a cache-disabled handle of ONE physical table
    must always agree with each other and with the dict oracle — including
    right after expire/compaction invalidation."""
    rng = np.random.default_rng(seed)
    cat = FileSystemCatalog(f"{tmp_warehouse}/cachepar{seed}", commit_user="oracle")
    t = cat.create_table(
        "db.cp",
        SCHEMA,
        primary_keys=["k"],
        options={
            "bucket": "2",
            "num-sorted-run.compaction-trigger": "3",
            "target-file-size": "4 kb",
            "manifest.merge-min-count": "2",
            "snapshot.num-retained.min": "2",
            "snapshot.num-retained.max": "4",
            "snapshot.time-retained": "0 ms",
            "cache.manifest.max-memory-size": "64 mb",
            "cache.data-file.max-memory-size": "64 mb",
        },
    )
    # cache-disabled view of the same physical table: ground truth from disk
    plain = t.copy(
        {"cache.manifest.max-memory-size": "0 b", "cache.data-file.max-memory-size": "0 b"}
    )
    oracle: dict[int, tuple] = {}
    for step in range(12):
        n = int(rng.integers(1, 50))
        keys = rng.integers(0, 100, n)
        rows = {}
        for k in keys:
            rows[int(k)] = (int(k), f"s{int(k)}-{step}", float(step) + float(k) / 1000)
        deletes = (
            [int(k) for k in rng.choice(list(oracle), size=min(len(oracle), 4), replace=False)]
            if oracle and rng.random() < 0.4
            else []
        )
        rows = {k: v for k, v in rows.items() if k not in deletes}
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        if rows:
            w.write(
                {
                    "k": [v[0] for v in rows.values()],
                    "s": [v[1] for v in rows.values()],
                    "v": [v[2] for v in rows.values()],
                }
            )
        if deletes:
            w.write(
                {"k": deletes, "s": [None] * len(deletes), "v": [None] * len(deletes)},
                kinds=["-D"] * len(deletes),
            )
        if rng.random() < 0.3:
            w.compact(full=rng.random() < 0.5)
        wb.new_commit().commit(w.prepare_commit())
        oracle.update(rows)
        for k in deletes:
            oracle.pop(k, None)

        def read_dict(table):
            rb = table.new_read_builder()
            return {r[0]: r for r in rb.new_read().read_all(rb.new_scan().plan()).to_pylist()}

        got_cached = read_dict(t)
        got_plain = read_dict(plain)
        assert got_cached == got_plain == oracle, f"cache parity divergence at step {step}"


def test_random_ops_partitioned_dynamic_bucket(tmp_warehouse):
    """Combined paths: partitions + dynamic buckets + deletes + compactions
    against the dict oracle."""
    rng = np.random.default_rng(5)
    cat = FileSystemCatalog(f"{tmp_warehouse}/pdyn", commit_user="oracle2")
    schema = RowType.of(("region", STRING()), ("k", BIGINT()), ("v", DOUBLE()))
    t = cat.create_table(
        "db.p",
        schema,
        partition_keys=["region"],
        primary_keys=["region", "k"],
        options={"bucket": "-1", "dynamic-bucket.target-row-num": "40", "num-sorted-run.compaction-trigger": "3"},
    )
    regions = ["eu", "us", "ap"]
    oracle: dict[tuple, tuple] = {}
    for step in range(10):
        n = int(rng.integers(1, 50))
        ks = rng.integers(0, 150, n)
        rs = [regions[i] for i in rng.integers(0, 3, n)]
        rows = {}
        for r, k in zip(rs, ks):
            rows[(r, int(k))] = (r, int(k), float(step))
        if oracle and rng.random() < 0.5:
            keys = list(oracle)
            idx = rng.choice(len(keys), size=min(len(keys), 4), replace=False)
            deletes = [keys[i] for i in idx]  # sample indices: no key coercion
        else:
            deletes = []
        rows = {key: v for key, v in rows.items() if key not in deletes}
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        if rows:
            w.write(
                {
                    "region": [v[0] for v in rows.values()],
                    "k": [v[1] for v in rows.values()],
                    "v": [v[2] for v in rows.values()],
                }
            )
        if deletes:
            w.write(
                {"region": [d[0] for d in deletes], "k": [d[1] for d in deletes], "v": [None] * len(deletes)},
                kinds=["-D"] * len(deletes),
            )
        if rng.random() < 0.3:
            w.compact(full=True)
        wb.new_commit().commit(w.prepare_commit())
        oracle.update(rows)
        for d in deletes:
            oracle.pop(d, None)
        rb = t.new_read_builder()
        got = {(r[0], r[1]): r for r in rb.new_read().read_all(rb.new_scan().plan()).to_pylist()}
        assert got == oracle, f"divergence at step {step}"


@pytest.mark.skipif(
    __import__("jax").device_count() < 8, reason="needs the 8-device virtual mesh"
)
@pytest.mark.parametrize("seed", [13])
def test_random_ops_mesh_mode_matches_oracle(tmp_warehouse, seed):
    """The same randomized churn with parallel.mesh.enabled + avro manifests:
    the mesh execution path and the interop metadata plane must be invisible
    to semantics."""
    rng = np.random.default_rng(seed)
    cat = FileSystemCatalog(f"{tmp_warehouse}/mesh{seed}", commit_user="oracle")
    t = cat.create_table(
        "db.rm",
        SCHEMA,
        primary_keys=["k"],
        options={
            "bucket": "4",
            "num-sorted-run.compaction-trigger": "3",
            "target-file-size": "4 kb",
            "parallel.mesh.enabled": "true",
            "manifest.format": "avro",
        },
    )
    oracle: dict[int, tuple] = {}
    for step in range(25):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        n = int(rng.integers(5, 60))
        ks = rng.integers(0, 150, n).tolist()
        rows = [(k, f"s{k % 13}", float(step * 1000 + k)) for k in ks]
        w.write({"k": [r[0] for r in rows], "s": [r[1] for r in rows], "v": [r[2] for r in rows]})
        for r in rows:
            oracle[r[0]] = r
        if rng.random() < 0.3 and oracle:
            idx = rng.integers(0, len(oracle), size=min(5, len(oracle)))
            dels = [sorted(oracle)[i] for i in np.unique(idx)]
            w.write({"k": dels, "s": [None] * len(dels), "v": [None] * len(dels)}, kinds=["-D"] * len(dels))
            for k in dels:
                oracle.pop(k, None)
        if rng.random() < 0.25:
            w.compact(full=rng.random() < 0.4)
        wb.new_commit().commit(w.prepare_commit())
        if step % 6 == 5:
            rb = t.new_read_builder()
            got = {r[0]: r for r in rb.new_read().read_all(rb.new_scan().plan()).to_pylist()}
            assert got == oracle, f"divergence at step {step}"
    rb = t.new_read_builder()
    got = {r[0]: r for r in rb.new_read().read_all(rb.new_scan().plan()).to_pylist()}
    assert got == oracle
