import numpy as np

from paimon_tpu.data import ColumnBatch
from paimon_tpu.data.predicate import (
    FieldStats,
    Predicate,
    PredicateBuilder,
    and_,
    between,
    contains,
    equal,
    greater_than,
    in_,
    is_null,
    less_than,
    not_in,
    or_,
    starts_with,
)
from paimon_tpu.types import DOUBLE, INT, STRING, RowType

SCHEMA = RowType.of(("a", INT()), ("b", DOUBLE()), ("s", STRING()))
BATCH = ColumnBatch.from_pydict(
    SCHEMA,
    {"a": [1, 2, 3, 4, None], "b": [1.0, None, 3.0, 4.0, 5.0], "s": ["apple", "banana", None, "apricot", "fig"]},
)


def ev(p):
    return p.eval(BATCH).tolist()


def test_leaf_eval():
    assert ev(equal("a", 2)) == [False, True, False, False, False]
    assert ev(less_than("a", 3)) == [True, True, False, False, False]
    assert ev(is_null("a")) == [False, False, False, False, True]
    assert ev(in_("a", [1, 4])) == [True, False, False, True, False]
    assert ev(not_in("a", [1, 4])) == [False, True, True, False, False]  # null -> False
    assert ev(between("b", 3.0, 4.5)) == [False, False, True, True, False]


def test_string_eval():
    assert ev(starts_with("s", "ap")) == [True, False, False, True, False]
    assert ev(contains("s", "an")) == [False, True, False, False, False]


def test_compound_eval_and_flatten():
    p = and_(greater_than("a", 1), less_than("a", 4))
    assert ev(p) == [False, True, True, False, False]
    q = or_(equal("a", 1), equal("a", 4), is_null("a"))
    assert ev(q) == [True, False, False, True, True]
    assert len(and_(p, equal("a", 2)).children) == 3  # flattened


def test_negate():
    p = and_(greater_than("a", 1), less_than("a", 4)).negate()
    assert ev(p) == [True, False, False, True, False]  # nulls stay False


def test_serde_roundtrip():
    p = or_(and_(equal("a", 1), less_than("b", 2.0)), starts_with("s", "x"))
    q = Predicate.from_dict(p.to_dict())
    assert ev(q) == ev(p)


def test_stats_pruning():
    stats = {"a": FieldStats(10, 20, 0, 100)}
    assert not equal("a", 5).test_stats(stats)
    assert equal("a", 15).test_stats(stats)
    assert not greater_than("a", 20).test_stats(stats)
    assert greater_than("a", 19).test_stats(stats)
    assert not between("a", 30, 40).test_stats(stats)
    assert in_("a", [1, 11]).test_stats(stats)
    assert not in_("a", [1, 2]).test_stats(stats)
    # all-null file
    stats2 = {"a": FieldStats(None, None, 100, 100)}
    assert not equal("a", 1).test_stats(stats2)
    assert is_null("a").test_stats(stats2)
    # unknown field -> conservative keep
    assert equal("zz", 1).test_stats(stats)


def test_stats_compound():
    stats = {"a": FieldStats(10, 20, 0, 100), "b": FieldStats(0.0, 1.0, 0, 100)}
    assert not and_(equal("a", 15), greater_than("b", 2.0)).test_stats(stats)
    assert or_(equal("a", 15), greater_than("b", 2.0)).test_stats(stats)


def test_builder_checks_fields():
    pb = PredicateBuilder(SCHEMA)
    pb.equal("a", 1)
    import pytest

    with pytest.raises(KeyError):
        pb.equal("nope", 1)
    parts = PredicateBuilder.split_and(and_(equal("a", 1), equal("b", 2.0)))
    assert len(parts) == 2
    assert PredicateBuilder.pick_by_fields(parts, {"a"}) == [parts[0]]
