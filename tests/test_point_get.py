"""Batched point-get serving (ISSUE 13): get_batch parity vs the scalar
lookup() walk and a pandas-style fold, bloom key-index pruning, the
read-your-writes delta tier, refresh() per-bucket diffing, serving
endpoints (KV server + Flight) with typed BUSY, and the (name, level)
compaction-chain cancel regression the RYW soak surfaced."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.metrics import get_metrics
from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

SCHEMA = RowType.of(("id", BIGINT()), ("name", STRING()), ("v", DOUBLE()))
STR_SCHEMA = RowType.of(("code", STRING()), ("grp", STRING()), ("v", DOUBLE()))


@pytest.fixture
def cat(tmp_warehouse):
    return FileSystemCatalog(tmp_warehouse, commit_user="pg")


def write(t, data, kinds=None):
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write(data, kinds)
    wb.new_commit().commit(w.prepare_commit())


def scalar_oracle(q, keys, partition=()):
    out = []
    for k in keys:
        row = q.lookup(partition, k)
        out.append(None if row is None else row.to_pylist()[0])
    return out


# ---------------------------------------------------------------------------
# randomized parity: get_batch == scalar lookup() loop == dict fold
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("bloom", [True, False])
@pytest.mark.parametrize("schema_kind", ["int", "dict"])
def test_get_batch_parity_randomized(cat, seed, bloom, schema_kind):
    from paimon_tpu.table.query import LocalTableQuery

    rng = np.random.default_rng(seed)
    opts = {
        "bucket": str(int(rng.integers(1, 4))),
        "file-index.bloom-filter.primary-key.enabled": str(bloom).lower(),
    }
    if schema_kind == "dict":
        opts.update({
            "format.parquet.decoder": "native",
            "format.parquet.encoder": "native",
            "merge.dict-domain": "true",
        })
        schema, key = STR_SCHEMA, "code"
        keyspace = [f"k{i:05d}" for i in range(400)]
    else:
        schema, key = SCHEMA, "id"
        keyspace = list(range(400))
    t = cat.create_table(f"db.p_{schema_kind}_{seed}_{int(bloom)}", schema,
                         primary_keys=[key], options=opts)
    fold = {}
    for commit in range(4):
        n = int(rng.integers(20, 80))
        ks = [keyspace[i] for i in rng.integers(0, len(keyspace), n)]
        ks = list(dict.fromkeys(ks))  # unique per commit
        deleted = rng.random(len(ks)) < 0.15
        vals = [float(commit * 100 + i) for i in range(len(ks))]
        if schema_kind == "dict":
            rows = {"code": ks, "grp": [f"g{hash(k) % 5}" for k in ks], "v": vals}
        else:
            rows = {"id": ks, "name": [f"n{k}" for k in ks], "v": vals}
        kinds = ["-D" if d else "+I" for d in deleted]
        write(t, rows, kinds)
        for k, d, i in zip(ks, deleted, range(len(ks))):
            if d:
                fold.pop(k, None)
            else:
                if schema_kind == "dict":
                    fold[k] = (k, f"g{hash(k) % 5}", vals[i])
                else:
                    fold[k] = (k, f"n{k}", vals[i])
    q = LocalTableQuery(t)
    probe = [keyspace[i] for i in rng.integers(0, len(keyspace), 120)]
    probe += ["zzz-absent", "absent2"] if schema_kind == "dict" else [99999, -5]
    got = q.get_batch(probe).to_pylist()
    assert got == scalar_oracle(q, probe)
    assert got == [fold.get(k) for k in probe]


def test_get_batch_parity_engines(cat):
    """sort-engine=pallas and merge.engine=mesh tables serve identical
    batched gets (the write/merge engines change file contents' layout,
    never the served rows)."""
    from paimon_tpu.table.query import LocalTableQuery

    for name, extra in (
        ("pal", {"sort-engine": "pallas"}),
        ("mesh", {"merge.engine": "mesh"}),
    ):
        t = cat.create_table(f"db.eng_{name}", SCHEMA, primary_keys=["id"],
                             options={"bucket": "2", **extra})
        write(t, {"id": list(range(60)), "name": [f"n{i}" for i in range(60)],
                  "v": [float(i) for i in range(60)]})
        write(t, {"id": [7], "name": ["seven"], "v": [77.0]})
        write(t, {"id": [9], "name": [None], "v": [None]}, kinds=["-D"])
        q = LocalTableQuery(t)
        probe = [7, 9, 0, 59, 1234]
        got = q.get_batch(probe).to_pylist()
        assert got == scalar_oracle(q, probe)
        assert got[0] == (7, "seven", 77.0) and got[1] is None and got[4] is None


def test_get_batch_dynamic_bucket(cat):
    from paimon_tpu.table.query import LocalTableQuery

    t = cat.create_table("db.dyn", SCHEMA, primary_keys=["id"],
                         options={"bucket": "-1", "dynamic-bucket.target-row-num": "10"})
    write(t, {"id": list(range(40)), "name": ["x"] * 40, "v": [float(i) for i in range(40)]})
    q = LocalTableQuery(t)
    probe = [0, 17, 39, 555]
    assert q.get_batch(probe).to_pylist() == scalar_oracle(q, probe)


def test_get_batch_input_shapes(cat):
    from paimon_tpu.data.batch import ColumnBatch
    from paimon_tpu.table.query import LocalTableQuery

    t = cat.create_table("db.shapes", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    write(t, {"id": [1, 2], "name": ["a", "b"], "v": [1.0, 2.0]})
    q = LocalTableQuery(t)
    expect = [(1, "a", 1.0), None]
    assert q.get_batch([1, 3]).to_pylist() == expect
    assert q.get_batch([(1,), (3,)]).to_pylist() == expect
    assert q.get_batch({"id": [1, 3]}).to_pylist() == expect
    key_schema = t.row_type.project(["id"])
    assert q.get_batch(ColumnBatch.from_pydict(key_schema, {"id": [1, 3]})).to_pylist() == expect
    res = q.get_batch([2, 9])
    assert res.row(0) == (2, "b", 2.0) and res.row(1) is None
    assert q.get_batch([]).to_pylist() == []


# ---------------------------------------------------------------------------
# bloom key-index pruning
# ---------------------------------------------------------------------------

def test_bloom_key_index_prunes_without_data_io(cat):
    """Two files with interleaved key ranges (min/max cannot tell them
    apart): probing a key only ONE file holds must bloom-prune the other —
    with zero data IO. Out-of-range probes are range-pruned even without
    blooms; with bloom-prune disabled the index is never consulted."""
    from paimon_tpu.format.fileindex import resolve_key_bloom

    if not resolve_key_bloom("true"):
        pytest.skip("PAIMON_TPU_KEY_BLOOM forced off: no key indexes to consult")
    t = cat.create_table("db.bloom", SCHEMA, primary_keys=["id"], options={
        "bucket": "1", "write-only": "true",
        "file-index.bloom-filter.primary-key.enabled": "true",
    })
    write(t, {"id": list(range(0, 400, 2)), "name": ["e"] * 200, "v": [0.0] * 200})
    write(t, {"id": list(range(1, 400, 2)), "name": ["o"] * 200, "v": [1.0] * 200})
    from paimon_tpu.table.query import LocalTableQuery

    q = LocalTableQuery(t)
    g = get_metrics()
    # odd-only probes: the even file's key range covers them, only its
    # bloom can rule them out. 20 single-key probes: P(no prune at
    # fpp=0.001) is negligible
    pruned0 = g.counter("files_pruned").count
    for k in range(1, 41, 2):
        assert q.get_batch([k]).to_pylist() == [(k, "o", 1.0)]
    assert g.counter("files_pruned").count > pruned0
    assert g.counter("index_hits").count > 0
    # out-of-range probes: range pruning alone skips BOTH files
    pruned1 = g.counter("files_pruned").count
    assert q.get_batch([-5, 5000]).to_pylist() == [None, None]
    assert g.counter("files_pruned").count >= pruned1 + 2
    # bloom-prune off: the key index is never consulted
    t2 = t.copy({"lookup.get.bloom-prune.enabled": "false"})
    q2 = LocalTableQuery(t2)
    hits0 = g.counter("index_hits").count
    assert q2.get_batch([398, 399]).to_pylist() == [(398, "e", 0.0), (399, "o", 1.0)]
    assert g.counter("index_hits").count == hits0


def test_key_bloom_payload_roundtrip():
    from paimon_tpu.data.batch import ColumnBatch
    from paimon_tpu.format.fileindex import FileIndexPredicate, build_index_payload
    from paimon_tpu.table.bucket import key_hashes

    schema = RowType.of(("a", BIGINT()), ("b", STRING()))
    batch = ColumnBatch.from_pydict(schema, {"a": [1, 2, 3], "b": ["x", "y", "z"]})
    hashes = key_hashes(batch, ["a", "b"])
    payload = build_index_payload(batch, [], key_hashes=hashes)
    pred = FileIndexPredicate.from_bytes(payload)
    assert pred.key_bloom() is not None
    mask = pred.test_key_hashes(hashes)
    assert mask.all()  # every written key might be present
    other = ColumnBatch.from_pydict(schema, {"a": [100 + i for i in range(64)], "b": ["q"] * 64})
    miss = pred.test_key_hashes(key_hashes(other, ["a", "b"]))
    assert not miss.all()  # fpp 0.001: essentially all absents excluded


def test_key_hashes_code_domain_parity():
    """The pool-gather fast path must hash bit-identically to expanded
    values — routing and bloom probes depend on it."""
    from paimon_tpu.data.batch import Column, ColumnBatch
    from paimon_tpu.table.bucket import key_hashes

    schema = RowType.of(("s", STRING()),)
    pool = np.array(["aa", "bb", "cc"], dtype=object)
    codes = np.array([2, 0, 1, 1, 2], dtype=np.uint32)
    coded = Column.from_codes(pool, codes)
    expanded = Column(pool.take(codes))
    b1 = ColumnBatch(schema, {"s": coded})
    b2 = ColumnBatch(schema, {"s": expanded})
    assert np.array_equal(key_hashes(b1, ["s"]), key_hashes(b2, ["s"]))


# ---------------------------------------------------------------------------
# read-your-writes
# ---------------------------------------------------------------------------

def test_read_your_writes_tiers(cat):
    from paimon_tpu.table.query import LocalTableQuery
    from paimon_tpu.table.write import TableWrite

    t = cat.create_table("db.ryw", SCHEMA, primary_keys=["id"], options={"bucket": "2"})
    write(t, {"id": [1, 2], "name": ["a", "b"], "v": [1.0, 2.0]})
    q = LocalTableQuery(t)
    tw = TableWrite(t)
    q.attach_write(tw)
    tw.write({"id": [2, 5], "name": ["b2", "e"], "v": [20.0, 50.0]})
    g = get_metrics()
    m0 = g.counter("memtable_hits").count
    assert q.get_batch([1, 2, 5, 9]).to_pylist() == [
        (1, "a", 1.0), (2, "b2", 20.0), (5, "e", 50.0), None]
    assert g.counter("memtable_hits").count > m0
    # buffered delete masks a committed row
    tw.write({"id": [1], "name": [None], "v": [None]}, kinds=["-D"])
    assert q.get_batch([1]).to_pylist() == [None]
    # flushed-but-uncommitted level-0 files stay visible
    for w in tw._writers.values():
        w.flush()
    assert q.get_batch([1, 2, 5]).to_pylist() == [None, (2, "b2", 20.0), (5, "e", 50.0)]
    # after commit + refresh the same state serves from the snapshot
    t.new_batch_write_builder().new_commit().commit(tw.prepare_commit())
    tw.close()
    q.attach_write(None)
    q.refresh()
    assert q.get_batch([1, 2, 5]).to_pylist() == [None, (2, "b2", 20.0), (5, "e", 50.0)]


# ---------------------------------------------------------------------------
# refresh() per-bucket diff
# ---------------------------------------------------------------------------

def test_refresh_diff_keeps_unchanged_buckets(cat):
    from paimon_tpu.table.query import LocalTableQuery

    t = cat.create_table("db.diff", SCHEMA, primary_keys=["id"], options={"bucket": "4"})
    write(t, {"id": list(range(40)), "name": ["x"] * 40, "v": [float(i) for i in range(40)]})
    q = LocalTableQuery(t)
    before_levels = dict(q._levels)
    before_idx = dict(q._get_indexes)
    write(t, {"id": [0], "name": ["y"], "v": [100.0]})  # lands in ONE bucket
    q.refresh()
    changed = [pb for pb in before_levels if q._levels[pb] is not before_levels[pb]]
    unchanged = [pb for pb in before_levels if q._levels[pb] is before_levels[pb]]
    assert len(changed) == 1 and len(unchanged) == 3
    assert all(q._get_indexes[pb] is before_idx[pb] for pb in unchanged)
    assert q.get_batch([0]).to_pylist() == [(0, "y", 100.0)]
    # same snapshot: refresh is a no-op
    ids = {pb: id(v) for pb, v in q._levels.items()}
    q.refresh()
    assert {pb: id(v) for pb, v in q._levels.items()} == ids


# ---------------------------------------------------------------------------
# serving endpoints
# ---------------------------------------------------------------------------

def test_kv_server_get_batch_and_typed_busy(cat):
    from paimon_tpu.service import KvBusyError, KvQueryClient, KvQueryServer

    t = cat.create_table("db.srv", SCHEMA, primary_keys=["id"], options={"bucket": "2"})
    write(t, {"id": [1, 2, 3], "name": ["a", "b", "c"], "v": [1.0, 2.0, 3.0]})
    srv = KvQueryServer(t, max_inflight_gets=1)
    host, port = srv.start()
    try:
        c = KvQueryClient(host, port)
        assert c.get_batch([1, 2, 99]) == [(1, "a", 1.0), (2, "b", 2.0), None]
        # saturate the admission gate: the next get must shed TYPED
        assert srv._get_gate.acquire(blocking=False)
        try:
            with pytest.raises(KvBusyError) as ei:
                c.get_batch([1])
            assert ei.value.payload["state"] == "busy-reads"
            assert ei.value.retry_after_ms > 0
        finally:
            srv._get_gate.release()
        assert c.get_batch([1]) == [(1, "a", 1.0)]
        c.close()
    finally:
        srv.shutdown()


def test_kv_server_read_your_writes(cat):
    from paimon_tpu.service import KvQueryClient, KvQueryServer
    from paimon_tpu.table.write import TableWrite

    t = cat.create_table("db.srv2", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    write(t, {"id": [1], "name": ["a"], "v": [1.0]})
    tw = TableWrite(t)
    srv = KvQueryServer(t, table_write=tw)
    host, port = srv.start()
    try:
        tw.write({"id": [9], "name": ["buf"], "v": [9.0]})
        c = KvQueryClient(host, port)
        assert c.get_batch([1, 9]) == [(1, "a", 1.0), (9, "buf", 9.0)]
        c.close()
    finally:
        srv.shutdown()
        tw.close()


def test_flight_get_batch(tmp_warehouse):
    pytest.importorskip("pyarrow.flight")
    from paimon_tpu.service.flight import PaimonFlightServer, flight_get_batch

    cat = FileSystemCatalog(tmp_warehouse, commit_user="fl")
    t = cat.create_table("db.fg", SCHEMA, primary_keys=["id"], options={"bucket": "2"})
    write(t, {"id": [1, 2, 3], "name": ["a", "b", "c"], "v": [1.0, 2.0, 3.0]})
    srv = PaimonFlightServer(tmp_warehouse)
    loc = srv.start()
    try:
        assert flight_get_batch(loc, "db.fg", [2, 44]) == [(2, "b", 2.0), None]
        # refresh-on-action: new commits are visible to subsequent actions
        write(t, {"id": [44], "name": ["d"], "v": [44.0]})
        assert flight_get_batch(loc, "db.fg", [44]) == [(44, "d", 44.0)]
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# regression: compaction-chain cancel must key on (name, level)
# ---------------------------------------------------------------------------

def test_compaction_chain_upgrade_keeps_rows(cat):
    """One commit chaining rewrite([L0 runs]) -> F@mid then upgrade F@mid ->
    F@max lost F entirely under the old name-keyed cancel: the upgrade's
    DELETE(F@mid)/ADD(F@max) share F's name with round 1's ADD(F@mid), so
    the whole chain cancelled — the message deleted the L0 inputs but never
    added F (rows silently dropped, the file left to the orphan sweep). The
    (name, LEVEL) key cancels only the true create-then-consume pair.

    Setup: runs at L5 (big) and L4 (mid, so the size-ratio pick's first
    EXCLUDED run is non-zero-level and round 1 outputs BELOW max), all key
    ranges disjoint so the full pass sees singleton sections and upgrades."""
    from paimon_tpu.core.kv import KVBatch
    from paimon_tpu.core.manifest import CommitMessage, ManifestCommittable
    from paimon_tpu.data.batch import ColumnBatch

    t = cat.create_table("db.chain", SCHEMA, primary_keys=["id"], options={
        "bucket": "1", "write-buffer-rows": "8",
    })
    store = t.store
    wf = store.writer_factory((), 0)

    def mk(ids, seq0, level):
        batch = ColumnBatch.from_pydict(
            SCHEMA, {"id": ids, "name": [f"n{k}" for k in ids], "v": [float(k) for k in ids]}
        )
        return wf.write(KVBatch.from_rows(batch, seq0), level=level)

    metas = mk(list(range(0, 10000)), 0, 5) + mk(list(range(20000, 23000)), 10000, 4)
    store.new_commit().commit(ManifestCommittable(1, messages=[
        CommitMessage(partition=(), bucket=0, total_buckets=1, new_files=metas)
    ]))
    # ONE commit: 6 small flushes (auto-compaction rewrites the L0 runs to a
    # mid level), then a full compaction that UPGRADES that output to max
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    for i in range(6):
        ids = [50000 + i * 10 + j for j in range(8)]
        w.write({"id": ids, "name": [f"n{k}" for k in ids], "v": [float(k) for k in ids]})
    w.compact(full=True)
    wb.new_commit().commit(w.prepare_commit())
    rb = t.new_read_builder()
    batch = rb.new_read().read_all(rb.new_scan().plan())
    got = set(batch.column("id").values.tolist())
    expect = (
        set(range(10000)) | set(range(20000, 23000))
        | {50000 + i * 10 + j for i in range(6) for j in range(8)}
    )
    missing = sorted(expect - got)
    assert not missing, f"rows lost by the compaction-chain cancel: {missing[:10]}"
    assert batch.num_rows == len(expect)  # and nothing double-counted


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def test_get_metric_group(cat):
    from paimon_tpu.table.query import LocalTableQuery

    t = cat.create_table("db.met", SCHEMA, primary_keys=["id"], options={
        "bucket": "1", "file-index.bloom-filter.primary-key.enabled": "true"})
    write(t, {"id": [1, 2], "name": ["a", "b"], "v": [1.0, 2.0]})
    q = LocalTableQuery(t)
    g = get_metrics()
    gets0 = g.counter("gets").count
    probed0 = g.counter("keys_probed").count
    q.get_batch([1, 2, 3])
    assert g.counter("gets").count == gets0 + 3
    assert g.counter("keys_probed").count > probed0
    assert g.histogram("probe_ms").count > 0
