"""Dynamic bucket mode (reference index/HashBucketAssigner + DynamicBucketSink)."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.data.predicate import equal
from paimon_tpu.types import BIGINT, DOUBLE, RowType

SCHEMA = RowType.of(("id", BIGINT()), ("v", DOUBLE()))


@pytest.fixture
def catalog(tmp_warehouse):
    return FileSystemCatalog(tmp_warehouse, commit_user="dyn")


def write(t, data, kinds=None):
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write(data, kinds)
    wb.new_commit().commit(w.prepare_commit())


def read(t, predicate=None):
    rb = t.new_read_builder()
    if predicate is not None:
        rb = rb.with_filter(predicate)
    return rb.new_read().read_all(rb.new_scan().plan())


def test_dynamic_bucket_spills_to_new_buckets(catalog):
    t = catalog.create_table(
        "db.dyn",
        SCHEMA,
        primary_keys=["id"],
        options={"bucket": "-1", "dynamic-bucket.target-row-num": "100"},
    )
    assert t.bucket_mode == "dynamic"
    n = 350
    write(t, {"id": list(range(n)), "v": [float(i) for i in range(n)]})
    plan = t.store.new_scan().plan()
    buckets = {e.bucket for e in plan.entries}
    assert len(buckets) == 4  # 350 keys / 100 per bucket
    # hash index files registered
    hash_entries = [e for e in plan.index_entries if e.kind == "HASH_INDEX"]
    assert len(hash_entries) == 4
    assert sum(e.row_count for e in hash_entries) == n
    out = read(t)
    assert out.num_rows == n


def test_dynamic_bucket_upsert_sticks_to_bucket(catalog):
    t = catalog.create_table(
        "db.dyn2",
        SCHEMA,
        primary_keys=["id"],
        options={"bucket": "-1", "dynamic-bucket.target-row-num": "10"},
    )
    write(t, {"id": list(range(25)), "v": [0.0] * 25})
    # second writer session: must route updates to the original buckets
    write(t, {"id": list(range(25)), "v": [1.0] * 25})
    out = read(t)
    assert out.num_rows == 25  # upserts, not duplicates
    assert all(r[1] == 1.0 for r in out.to_pylist())
    # updating existing keys must not create new buckets
    plan = t.store.new_scan().plan()
    assert len({e.bucket for e in plan.entries}) == 3  # ceil(25/10)


def test_dynamic_bucket_delete(catalog):
    t = catalog.create_table(
        "db.dyn3", SCHEMA, primary_keys=["id"], options={"bucket": "-1", "dynamic-bucket.target-row-num": "5"}
    )
    write(t, {"id": list(range(12)), "v": [float(i) for i in range(12)]})
    write(t, {"id": [3], "v": [None]}, kinds=["-D"])
    out = read(t)
    assert sorted(r[0] for r in out.to_pylist()) == [i for i in range(12) if i != 3]
