"""Global system tables, lineage store, catalog lock, full-cache lookup
tables (reference SystemTableLoader.loadGlobal, CatalogLock,
FullCacheLookupTable)."""

import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.lookup.tables import FullCacheLookupTable
from paimon_tpu.types import BIGINT, STRING, RowType

SCHEMA = RowType.of(("id", BIGINT()), ("city", STRING()), ("name", STRING()))


@pytest.fixture
def catalog(tmp_warehouse):
    return FileSystemCatalog(tmp_warehouse, commit_user="glob")


def _write(t, data, kinds=None):
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write(data, kinds)
    wb.new_commit().commit(w.prepare_commit())


def test_global_system_tables(catalog):
    catalog.create_table("db.a", SCHEMA, primary_keys=["id"], options={"bucket": "2"})
    catalog.create_table("db2.b", SCHEMA, options={"write-only": "true"})
    rows = catalog.get_table("sys.all_table_options").to_pylist()
    assert ("db", "a", "bucket", "2") in rows
    assert ("db2", "b", "write-only", "true") in rows
    co = catalog.get_table("sys.catalog_options").to_pylist()
    assert co and co[0][0] == "warehouse"


def test_lineage_tables(catalog):
    lm = catalog.lineage_meta()
    lm.save_source_table_lineage("job1", "db.a")
    lm.save_sink_table_lineage("job1", "db.b")
    lm.save_source_data_lineage("job1", "db.a", barrier_id=7, snapshot_id=3)
    lm.save_sink_data_lineage("job1", "db.b", barrier_id=7, snapshot_id=9)
    src = catalog.get_table("sys.source_table_lineage").to_pylist()
    assert src[0][:3] == ("db", "a", "job1")
    snk = catalog.get_table("sys.sink_data_lineage").to_pylist()
    assert snk[0][:5] == ("db", "b", "job1", 7, 9)
    assert catalog.get_table("sys.source_data_lineage").to_pylist()[0][3] == 7


def test_file_monitor_system_table(catalog):
    t = catalog.create_table("db.fm", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    _write(t, {"id": [1], "city": ["x"], "name": ["n"]})
    _write(t, {"id": [2], "city": ["y"], "name": ["m"]})
    rows = catalog.get_table("db.fm$file_monitor").to_pylist()
    assert len(rows) >= 2
    assert rows[0][0] == 1 and rows[0][2] == 0  # snapshot 1, bucket 0
    import json

    assert len(json.loads(rows[0][4])) == 1  # one added data file


def test_catalog_lock_serializes_commits(catalog, tmp_path):
    """commit.catalog-lock.enabled: concurrent committers on a LINK-LESS
    filesystem (no CAS rename) still cannot lose a commit."""
    import threading

    t = catalog.create_table(
        "db.lk", SCHEMA, primary_keys=["id"], options={"bucket": "1", "commit.catalog-lock.enabled": "true"}
    )
    errs = []

    def worker(i):
        try:
            _write(t, {"id": [i], "city": [f"c{i}"], "name": [f"n{i}"]})
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs
    rb = t.new_read_builder()
    out = rb.new_read().read_all(rb.new_scan().plan())
    assert out.num_rows == 6  # every commit landed
    assert t.store.snapshot_manager.latest_snapshot_id() == 6


def test_full_cache_lookup_primary_and_refresh(catalog):
    t = catalog.create_table("db.lkp", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    _write(t, {"id": [1, 2], "city": ["ber", "muc"], "name": ["a", "b"]})
    lt = FullCacheLookupTable(t)
    assert lt.mode == "primary"
    assert lt.get((1,)) == [(1, "ber", "a")]
    assert lt.get((9,)) == []
    # changes become visible after refresh()
    _write(t, {"id": [1, 3], "city": ["ber", "ham"], "name": ["a2", "c"]})
    _write(t, {"id": [2], "city": ["muc"], "name": ["b"]}, kinds=["-D"])
    assert lt.get((1,)) == [(1, "ber", "a")]  # stale until refresh
    applied = lt.refresh()
    assert applied >= 3
    assert lt.get((1,)) == [(1, "ber", "a2")]
    assert lt.get((2,)) == []
    assert lt.get((3,)) == [(3, "ham", "c")]


def test_full_cache_lookup_secondary_index(catalog):
    t = catalog.create_table("db.sec", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    _write(t, {"id": [1, 2, 3], "city": ["ber", "ber", "muc"], "name": ["a", "b", "c"]})
    lt = FullCacheLookupTable(t, join_keys=["city"])
    assert lt.mode == "secondary"
    assert lt.get(("ber",)) == [(1, "ber", "a"), (2, "ber", "b")]
    # moving id=2 to muc updates the index on refresh
    _write(t, {"id": [2], "city": ["muc"], "name": ["b"]})
    lt.refresh()
    assert lt.get(("ber",)) == [(1, "ber", "a")]
    assert lt.get(("muc",)) == [(2, "muc", "b"), (3, "muc", "c")]


def test_full_cache_lookup_no_pk_multimap(catalog):
    t = catalog.create_table("db.nopk", SCHEMA, options={"bucket": "1"})
    _write(t, {"id": [1, 1], "city": ["x", "x"], "name": ["dup", "dup"]})
    lt = FullCacheLookupTable(t, join_keys=["id"])
    assert lt.mode == "no-pk"
    assert len(lt.get((1,))) == 2  # duplicates preserved


def test_sys_database_reserved(catalog):
    with pytest.raises(ValueError, match="reserved"):
        catalog.create_database("sys", ignore_if_exists=False)
    with pytest.raises(ValueError, match="reserved"):
        catalog.create_table("sys.t", SCHEMA)


def test_non_atomic_fileio_auto_locks(tmp_warehouse):
    """A FileIO that declares atomic_write_supported=False gets the catalog
    lock automatically (reference: CatalogLock engages on object stores)."""
    from paimon_tpu.fs import LocalFileIO

    class ObjectStoreishIO(LocalFileIO):
        atomic_write_supported = False

    from paimon_tpu.core.schema import SchemaManager
    from paimon_tpu.table import FileStoreTable

    io = ObjectStoreishIO()
    path = f"{tmp_warehouse}/db.db/oss"
    schema = SchemaManager(io, path).create_table(SCHEMA, (), ["id"], {"bucket": "1"})
    t = FileStoreTable(io, path, schema, "oss-user")
    commit = t.store.new_commit()
    assert commit._lock is not None  # auto-engaged
    _write(t, {"id": [1], "city": ["c"], "name": ["n"]})
    rb = t.new_read_builder()
    assert rb.new_read().read_all(rb.new_scan().plan()).num_rows == 1
