"""tpuguard: wedge-proof device access discipline (probe cache, single-flight
lock, loud fallback). The real-probe path needs the tunnel; here we pin the
cache/lock logic so a benchmark run can never wedge or silently lie."""

import json
import os
import subprocess
import sys
import time

import pytest

from paimon_tpu.utils import tpuguard


@pytest.fixture
def guard_paths(tmp_path, monkeypatch):
    monkeypatch.setattr(tpuguard, "PROBE_CACHE", str(tmp_path / "probe.json"))
    monkeypatch.setattr(tpuguard, "PROBE_PIDFILE", str(tmp_path / "probe.pid"))
    monkeypatch.setattr(tpuguard, "TPU_LOCK", str(tmp_path / "device.lock"))
    # cache verdicts are env-scoped: pin a known env for these tests
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    return tmp_path


def test_probe_uses_fresh_cache_without_spawning(guard_paths):
    with open(tpuguard.PROBE_CACHE, "w") as f:
        json.dump({"done": True, "started": time.time(), "completed": time.time(), "platforms_env": "", "n": 1, "backend": "axon"}, f)
    assert tpuguard.probe_devices(timeout_s=0.1) == (1, "axon")


def test_probe_ignores_stale_cache(guard_paths, monkeypatch):
    # stale verdict + a "live prober" pidfile pointing at this test process:
    # probe must wait (not trust stale data, not kill pid, not spawn a second
    # prober) and report unreachable. Marker aligned so our own cmdline
    # passes the pid-recycling guard.
    monkeypatch.setattr(tpuguard, "_PROBE_MARKER", "pytest")
    with open(tpuguard.PROBE_CACHE, "w") as f:
        json.dump({"done": True, "started": time.time() - 10_000, "completed": time.time() - 10_000, "platforms_env": "", "n": 1, "backend": "axon"}, f)
    with open(tpuguard.PROBE_PIDFILE, "w") as f:
        f.write(str(os.getpid()))
    n, backend = tpuguard.probe_devices(timeout_s=0.1)
    assert n == 0 and "unreachable" in backend
    # and the "prober" (us) was not killed: reaching here proves it


def test_single_flight_excludes_second_process(guard_paths):
    sf = tpuguard.SingleFlight(tpuguard.TPU_LOCK)
    assert sf.acquire()
    # a second PROCESS (flock is per-process) must fail fast
    code = subprocess.run(
        [sys.executable, "-c", f"""
import sys
sys.path.insert(0, {str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})
from paimon_tpu.utils.tpuguard import SingleFlight
sys.exit(0 if not SingleFlight({tpuguard.TPU_LOCK!r}).acquire() else 1)
"""],
        timeout=30,
    ).returncode
    assert code == 0
    sf.release()
    code2 = subprocess.run(
        [sys.executable, "-c", f"""
import sys
sys.path.insert(0, {str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})
from paimon_tpu.utils.tpuguard import SingleFlight
sys.exit(0 if SingleFlight({tpuguard.TPU_LOCK!r}).acquire() else 1)
"""],
        timeout=30,
    ).returncode
    assert code2 == 0


def test_ensure_live_backend_refuses_fallback_when_required(guard_paths, capsys):
    with open(tpuguard.PROBE_CACHE, "w") as f:
        json.dump({"done": True, "started": time.time(), "completed": time.time(), "platforms_env": "", "n": 0, "backend": "unreachable"}, f)
    with pytest.raises(SystemExit) as e:
        tpuguard.ensure_live_backend(require_tpu=True, probe_timeout_s=0.1)
    assert e.value.code == 3


def test_ensure_live_backend_loud_cpu_fallback(guard_paths, capsys):
    with open(tpuguard.PROBE_CACHE, "w") as f:
        json.dump({"done": True, "started": time.time(), "completed": time.time(), "platforms_env": "", "n": 0, "backend": "unreachable"}, f)
    tag = tpuguard.ensure_live_backend(require_tpu=False, probe_timeout_s=0.1)
    assert tag == "cpu (accelerator unreachable)"
    assert "ACCELERATOR UNREACHABLE" in capsys.readouterr().err
