"""Table API end-to-end (mirrors reference table read-write suites)."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.data.predicate import equal, greater_than
from paimon_tpu.table import load_table
from paimon_tpu.types import BIGINT, DOUBLE, INT, STRING, RowType

SCHEMA = RowType.of(("id", BIGINT()), ("region", STRING()), ("amount", DOUBLE()))


@pytest.fixture
def catalog(tmp_warehouse):
    return FileSystemCatalog(tmp_warehouse, commit_user="tester")


def create(catalog, name="db.orders", options=None, partition_keys=(), pk=("id",), schema=SCHEMA):
    opts = {"bucket": "2"}
    opts.update(options or {})
    return catalog.create_table(name, schema, partition_keys=partition_keys, primary_keys=pk, options=opts)


def write_batch(table, data, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write(data, kinds)
    wb.new_commit().commit(w.prepare_commit())


def read_batch(table, predicate=None, projection=None):
    rb = table.new_read_builder()
    if predicate is not None:
        rb = rb.with_filter(predicate)
    if projection is not None:
        rb = rb.with_projection(projection)
    splits = rb.new_scan().plan()
    return rb.new_read().read_all(splits)


def test_catalog_create_get_list(catalog):
    t = create(catalog)
    assert catalog.list_databases() == ["db"]
    assert catalog.list_tables("db") == ["orders"]
    t2 = catalog.get_table("db.orders")
    assert t2.row_type == t.row_type
    with pytest.raises(ValueError):
        create(catalog)
    catalog.rename_table("db.orders", "db.orders2")
    assert catalog.list_tables("db") == ["orders2"]
    catalog.drop_table("db.orders2")
    assert catalog.list_tables("db") == []


def test_batch_write_read_multi_bucket(catalog):
    t = create(catalog)
    n = 500
    write_batch(t, {"id": list(range(n)), "region": [f"r{i % 3}" for i in range(n)], "amount": [float(i) for i in range(n)]})
    out = read_batch(t)
    assert out.num_rows == n
    assert sorted(r[0] for r in out.to_pylist()) == list(range(n))
    # upsert hits the right buckets
    write_batch(t, {"id": [7, 8], "region": ["rx", "ry"], "amount": [77.0, 88.0]})
    out2 = read_batch(t, predicate=equal("id", 7))
    assert out2.to_pylist() == [(7, "rx", 77.0)]
    assert read_batch(t).num_rows == n


def test_partitioned_table_pruning(catalog):
    t = create(
        catalog,
        "db.part",
        partition_keys=("region",),
        pk=("region", "id"),
    )
    write_batch(t, {"id": [1, 2, 3, 4], "region": ["eu", "eu", "us", "us"], "amount": [1.0, 2.0, 3.0, 4.0]})
    rb = t.new_read_builder().with_filter(equal("region", "eu"))
    splits = rb.new_scan().plan()
    assert all(s.partition == ("eu",) for s in splits)
    out = rb.new_read().read_all(splits)
    assert sorted(r[0] for r in out.to_pylist()) == [1, 2]


def test_delete_via_rowkind(catalog):
    t = create(catalog, "db.del")
    write_batch(t, {"id": [1, 2, 3], "region": ["a", "b", "c"], "amount": [1.0, 2.0, 3.0]})
    write_batch(t, {"id": [2], "region": [None], "amount": [None]}, kinds=["-D"])
    out = read_batch(t)
    assert sorted(r[0] for r in out.to_pylist()) == [1, 3]


def test_overwrite_partition(catalog):
    t = create(catalog, "db.ow", partition_keys=("region",), pk=("region", "id"))
    write_batch(t, {"id": [1, 2], "region": ["eu", "us"], "amount": [1.0, 2.0]})
    wb = t.new_batch_write_builder().with_overwrite(lambda p: p == ("eu",))
    w = wb.new_write()
    w.write({"id": [9], "region": ["eu"], "amount": [9.0]})
    wb.new_commit().commit(w.prepare_commit())
    out = read_batch(t)
    assert sorted((r[0], r[1]) for r in out.to_pylist()) == [(2, "us"), (9, "eu")]


def test_time_travel_snapshot_and_tag(catalog):
    t = create(catalog, "db.tt", options={"bucket": "1"})
    write_batch(t, {"id": [1], "region": ["a"], "amount": [1.0]})
    t.create_tag("v1")
    write_batch(t, {"id": [1], "region": ["a2"], "amount": [2.0]})
    # latest
    assert read_batch(t).to_pylist()[0][1] == "a2"
    # by snapshot id
    t_old = t.copy({"scan.snapshot-id": "1"})
    assert read_batch(t_old).to_pylist()[0][1] == "a"
    # by tag
    t_tag = t.copy({"scan.tag-name": "v1"})
    assert read_batch(t_tag).to_pylist()[0][1] == "a"
    assert t.tags() == {"v1": 1}


def test_rollback(catalog):
    t = create(catalog, "db.rb", options={"bucket": "1"})
    write_batch(t, {"id": [1], "region": ["a"], "amount": [1.0]})
    write_batch(t, {"id": [2], "region": ["b"], "amount": [2.0]})
    write_batch(t, {"id": [3], "region": ["c"], "amount": [3.0]})
    t.rollback_to(1)
    out = read_batch(t)
    assert [r[0] for r in out.to_pylist()] == [1]
    assert t.store.snapshot_manager.latest_snapshot_id() == 1
    # table still writable after rollback
    write_batch(t, {"id": [4], "region": ["d"], "amount": [4.0]})
    assert sorted(r[0] for r in read_batch(t).to_pylist()) == [1, 4]


def test_stream_scan_follow_up(catalog):
    t = create(catalog, "db.stream", options={"bucket": "1"})
    write_batch(t, {"id": [1], "region": ["a"], "amount": [1.0]})
    scan = t.new_read_builder().new_stream_scan()
    read = t.new_read_builder().new_read()
    # starting plan: full
    splits = scan.plan()
    assert splits and read.read_all(splits).num_rows == 1
    assert scan.plan() is None  # nothing new
    write_batch(t, {"id": [2], "region": ["b"], "amount": [2.0]})
    splits2 = scan.plan()
    got = read.read_all(splits2)
    assert [r[0] for r in got.to_pylist()] == [2]  # delta only
    assert scan.plan() is None
    # checkpoint/restore
    cp = scan.checkpoint()
    write_batch(t, {"id": [3], "region": ["c"], "amount": [3.0]})
    scan2 = t.new_read_builder().new_stream_scan()
    scan2.restore(cp)
    splits3 = scan2.plan()
    assert [r[0] for r in read.read_all(splits3).to_pylist()] == [3]


def test_stream_scan_consumer_id(catalog):
    t = create(catalog, "db.consume", options={"bucket": "1", "consumer-id": "c1"})
    write_batch(t, {"id": [1], "region": ["a"], "amount": [1.0]})
    scan = t.new_read_builder().new_stream_scan()
    scan.plan()
    scan.checkpoint()  # the framework checkpoints, then acks
    scan.notify_checkpoint_complete()
    from paimon_tpu.table.consumer import ConsumerManager

    cm = ConsumerManager(t.file_io, t.path)
    assert cm.consumer("c1") == 2
    # new scan resumes from consumer progress, not from latest-full
    write_batch(t, {"id": [2], "region": ["b"], "amount": [2.0]})
    scan2 = t.new_read_builder().new_stream_scan()
    splits = scan2.plan()
    read = t.new_read_builder().new_read()
    assert [r[0] for r in read.read_all(splits).to_pylist()] == [2]


def test_system_tables(catalog):
    t = create(catalog, "db.sys", options={"bucket": "1"})
    write_batch(t, {"id": [1, 2], "region": ["a", "b"], "amount": [1.0, 2.0]})
    write_batch(t, {"id": [1], "region": ["a2"], "amount": [1.5]})
    t.create_tag("rel")
    snaps = catalog.get_table("db.sys$snapshots").to_pylist()
    assert len(snaps) == 2 and snaps[0][0] == 1
    files = catalog.get_table("db.sys$files").to_pylist()
    assert len(files) == 2
    opts = dict((k, v) for k, v, *_ in catalog.get_table("db.sys$options").to_pylist())
    assert opts["bucket"] == "1"
    tags = catalog.get_table("db.sys$tags").to_pylist()
    assert tags == [("rel", 2)]
    schemas = catalog.get_table("db.sys$schemas").to_pylist()
    assert len(schemas) == 1
    audit = catalog.get_table("db.sys$audit_log").to_pylist()
    kinds = sorted(r[0] for r in audit)
    assert kinds == ["+I", "+I"]  # batch audit view = merged rows with kinds
    assert sorted(r[1] for r in audit) == [1, 2]
    parts = catalog.get_table("db.sys$partitions").to_pylist()
    assert parts[0][1] == 3  # total record count across files
    with pytest.raises(ValueError, match="unknown system table"):
        catalog.get_table("db.sys$nope")


def test_read_optimized_and_audit_after_compact(catalog):
    t = create(catalog, "db.ro", options={"bucket": "1"})
    write_batch(t, {"id": [1], "region": ["a"], "amount": [1.0]})
    write_batch(t, {"id": [1], "region": ["b"], "amount": [2.0]})
    ro = catalog.get_table("db.ro$read_optimized")
    assert ro.to_pylist() == []  # nothing compacted to top level yet
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({"id": [2], "region": ["c"], "amount": [3.0]})
    w.compact(full=True)
    wb.new_commit().commit(w.prepare_commit())
    ro2 = catalog.get_table("db.ro$read_optimized").to_pylist()
    assert sorted(r[0] for r in ro2) == [1, 2]


def test_load_table_and_limit(catalog, tmp_warehouse):
    t = create(catalog, "db.load", options={"bucket": "1"})
    write_batch(t, {"id": list(range(10)), "region": ["x"] * 10, "amount": [float(i) for i in range(10)]})
    t2 = load_table(f"{tmp_warehouse}/db.db/load")
    rb = t2.new_read_builder().with_limit(3)
    out = rb.new_read().read_all(rb.new_scan().plan())
    assert out.num_rows == 3


def test_expire_respects_tags_and_consumers(catalog):
    t = create(
        catalog,
        "db.exp",
        options={
            "bucket": "1",
            "snapshot.num-retained.min": "1",
            "snapshot.num-retained.max": "1",
            "snapshot.time-retained.ms": "0",
        },
    )
    # disable auto-expire to control timing: write 4 snapshots
    wb = t.new_stream_write_builder()
    w = wb.new_write()
    from paimon_tpu.core.manifest import ManifestCommittable

    for i in range(4):
        w.write({"id": [i], "region": ["x"], "amount": [float(i)]})
        msgs = w.prepare_commit()
        t.store.new_commit().commit(ManifestCommittable(i + 1, messages=msgs))
    t.create_tag("keep", 2)
    expired = t.expire_snapshots()
    sm = t.store.snapshot_manager
    remaining = [s.id for s in sm.snapshots()]
    assert 2 in remaining or 2 in t.tags().values()
    assert sm.latest_snapshot_id() == 4
    # tagged snapshot data still readable via tag time travel
    t_tag = t.copy({"scan.tag-name": "keep"})
    out = read_batch(t_tag)
    assert sorted(r[0] for r in out.to_pylist()) == [0, 1]


def test_stream_plan_aligned(catalog):
    import threading
    import time

    t = create(catalog, "db.aligned", options={"bucket": "1"})
    write_batch(t, {"id": [1], "region": ["a"], "amount": [1.0]})
    scan = t.new_read_builder().new_stream_scan()
    scan.plan()  # consume the starting plan
    # nothing new: aligned plan times out cleanly
    assert scan.plan_aligned(timeout_seconds=0.3, poll_seconds=0.1) is None
    # a commit arriving mid-wait unblocks the aligned plan
    def later_write():
        time.sleep(0.3)
        write_batch(t, {"id": [2], "region": ["b"], "amount": [2.0]})

    th = threading.Thread(target=later_write)
    th.start()
    splits = scan.plan_aligned(timeout_seconds=10.0, poll_seconds=0.1)
    th.join()
    assert splits is not None
    read = t.new_read_builder().new_read()
    assert [r[0] for r in read.read_all(splits).to_pylist()] == [2]


def test_stream_plan_aligned_concurrent_writer_exact_boundary(catalog):
    """plan_aligned vs a concurrently committing writer (ISSUE 20
    satellite, round-5 verdict Weak #7): the poll loop races commits
    landing at arbitrary points between plan() calls, yet every aligned
    plan must sit EXACTLY on one snapshot boundary — each delta holds one
    commit's batch, whole, never rows of a half-landed or merged-in-later
    commit — and replaying the plans in order reconstructs the commit
    sequence with nothing lost, duplicated, or reordered."""
    import threading

    # write-only: inline compaction would interleave COMPACT snapshots whose
    # delta plans are empty — the test pins COMMIT boundaries, not compaction
    t = create(catalog, "db.aligned_race", options={"bucket": "1", "write-only": "true"})
    write_batch(t, {"id": [0], "region": ["seed"], "amount": [0.0]})
    scan = t.new_read_builder().new_stream_scan()
    first = scan.plan()  # starting plan: the seed commit
    read = t.new_read_builder().new_read()
    assert [r[0] for r in read.read_all(first).to_pylist()] == [0]

    commits = 12
    batches = {i: list(range(i * 100, i * 100 + 7)) for i in range(1, commits + 1)}
    done = threading.Event()

    def writer():
        # no artificial pacing: commits land as fast as they can, so plans
        # race the writer at every interleaving the loop can produce
        for i in range(1, commits + 1):
            ids = batches[i]
            write_batch(
                t,
                {
                    "id": ids,
                    "region": [f"c{i}"] * len(ids),
                    "amount": [float(x) for x in ids],
                },
            )
        done.set()

    th = threading.Thread(target=writer)
    th.start()
    seen: list[list[int]] = []
    try:
        while len(seen) < commits:
            splits = scan.plan_aligned(timeout_seconds=30.0, poll_seconds=0.01)
            assert splits is not None, f"aligned plan timed out after {len(seen)} deltas"
            rows = read.read_all(splits).to_pylist()
            # one aligned plan == one commit, exactly: every row carries the
            # same commit tag and the id set is that commit's whole batch
            tags = {r[1] for r in rows}
            assert len(tags) == 1, f"aligned plan mixed commits: {sorted(tags)}"
            i = int(next(iter(tags))[1:])
            assert sorted(r[0] for r in rows) == batches[i]
            seen.append(sorted(r[0] for r in rows))
    finally:
        th.join()
    assert done.is_set()
    # in-order, gapless, duplicate-free reconstruction of the commit stream
    assert seen == [batches[i] for i in range(1, commits + 1)]
    # quiescent tail: nothing further to align on
    assert scan.plan_aligned(timeout_seconds=0.2, poll_seconds=0.05) is None


def test_batch_split_packing(catalog):
    """Section-aware weighted bin-packing (reference MergeTreeSplitGenerator
    splitForBatch): key-disjoint sections spread over multiple splits under a
    small target size; overlapping runs stay together; results byte-match."""
    t = catalog.create_table(
        "db.packing",
        SCHEMA,
        primary_keys=["id"],
        options={"bucket": "1", "write-only": "true"},
    )
    # 6 commits of DISJOINT key ranges -> 6 non-overlapping sections
    for r in range(6):
        write_batch(t, {"id": list(range(r * 100, r * 100 + 100)),
                        "region": ["x"] * 100, "amount": [float(r)] * 100})
    before = sorted(read_batch(t).to_pylist())
    small = t.copy({"source.split.target-size": "1 kb", "source.split.open-file-cost": "1 b"})
    rb = small.new_read_builder()
    splits = rb.new_scan().plan()
    assert len(splits) == 6  # one split per section under the tiny target
    assert all(s.bucket == 0 for s in splits)
    assert sorted(rb.new_read().read_all(splits).to_pylist()) == before
    # overlapping runs (same key space) must stay in ONE split
    t2 = catalog.create_table(
        "db.packing2", SCHEMA, primary_keys=["id"], options={"bucket": "1", "write-only": "true"}
    )
    for r in range(4):
        write_batch(t2, {"id": list(range(100)), "region": ["x"] * 100, "amount": [float(r)] * 100})
    small2 = t2.copy({"source.split.target-size": "1 kb", "source.split.open-file-cost": "1 b"})
    splits2 = small2.new_read_builder().new_scan().plan()
    assert len(splits2) == 1 and len(splits2[0].files) == 4


def test_append_table_split_packing(catalog):
    """Append tables have no key ranges: files pack individually (reference
    AppendOnlySplitGenerator), so split-level parallelism works there too."""
    t = catalog.create_table("db.packapp", SCHEMA, options={"bucket": "1", "write-only": "true"})
    for r in range(5):
        write_batch(t, {"id": list(range(100)), "region": ["x"] * 100, "amount": [float(r)] * 100})
    small = t.copy({"source.split.target-size": "1 kb", "source.split.open-file-cost": "1 b"})
    splits = small.new_read_builder().new_scan().plan()
    assert len(splits) == 5  # one split per file under the tiny target
    rb = small.new_read_builder()
    assert rb.new_read().read_all(splits).num_rows == 500


def test_split_enumerator_distributed_assignment(catalog):
    """Streaming splits distribute across N readers with per-bucket affinity
    and checkpoint/restore (reference ContinuousFileSplitEnumerator)."""
    from paimon_tpu.table.enumerator import SplitEnumerator

    t = catalog.create_table(
        "db.enum", SCHEMA, primary_keys=["id"], options={"bucket": "4", "write-only": "true"}
    )
    enum = SplitEnumerator(t, num_readers=3)
    for r in range(3):
        write_batch(t, {"id": list(range(200)), "region": ["x"] * 200, "amount": [float(r)] * 200})
        enum.discover()
    assert enum.pending_count > 0
    # bucket affinity: every bucket's splits live on exactly one reader
    owner_of = {}
    drained = {r: enum.next_splits(r) for r in range(3)}
    for rid, splits in drained.items():
        for s in splits:
            key = (s.partition, s.bucket)
            assert owner_of.setdefault(key, rid) == rid
    total = sum(len(v) for v in drained.values())
    assert total > 0 and enum.pending_count == 0
    # the drained splits reconstruct the table state exactly once
    rb = t.new_read_builder()
    read = rb.new_read()
    seen = {}
    for splits in drained.values():
        for s in splits:
            for row in read.read(s).to_pylist():
                seen[row[0]] = row
    # follow-ups re-deliver per-snapshot deltas; last writer wins per key
    assert sorted(seen) == list(range(200))

    # checkpoint with undrained work, restore into a NEW enumerator
    write_batch(t, {"id": [999], "region": ["x"], "amount": [9.0]})
    enum.discover()
    state = enum.checkpoint()
    assert enum.pending_count > 0
    enum2 = SplitEnumerator(t, num_readers=2)  # different parallelism
    enum2.restore(state)
    assert enum2.pending_count == enum.pending_count  # nothing lost
    got = [s for r in range(2) for s in enum2.next_splits(r)]
    assert any(999 in [row[0] for row in read.read(s).to_pylist()] for s in got)
    # restored scan continues AFTER the checkpointed snapshot (no re-delivery)
    assert enum2.discover() == 0


def test_incremental_between(catalog):
    """incremental-between reads the change stream of (a, b] — kinds
    preserved, compaction snapshots skipped (reference
    IncrementalStartingScanner, delta mode)."""
    t = catalog.create_table("db.inc", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    write_batch(t, {"id": [1, 2], "region": ["x", "x"], "amount": [1.0, 2.0]})     # snap 1
    write_batch(t, {"id": [2, 3], "region": ["x", "x"], "amount": [20.0, 3.0]})    # snap 2
    t.create_tag("a", snapshot_id=1)
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({"id": [1], "region": ["x"], "amount": [None]}, kinds=["-D"])
    w.compact(full=True)  # adds a COMPACT snapshot in the range
    wb.new_commit().commit(w.prepare_commit())                                      # snaps 3+4
    t.create_tag("b")

    inc = t.copy({"incremental-between": "1,9"})
    rb = inc.new_read_builder()
    read = rb.new_read()
    events = []
    for s in rb.new_scan().plan():
        data, kinds = read.read_with_kinds(s)
        for row, k in zip(data.to_pylist(), kinds.tolist()):
            events.append((k, row[0]))
    # snap2: +I for 2 (upsert), +I for 3; snap3: -D for 1; compaction skipped
    assert sorted(events) == [(0, 2), (0, 3), (3, 1)]
    # tag names work too
    inc2 = t.copy({"incremental-between": "a,b"})
    rb2 = inc2.new_read_builder()
    assert len(rb2.new_scan().plan()) == len(rb.new_scan().plan())


def test_scan_bounded_watermark(catalog):
    """Streaming ends once a snapshot's watermark passes the bound."""
    from paimon_tpu.core.manifest import ManifestCommittable
    from paimon_tpu.table.write import BatchWriteBuilder, TableCommit

    t = catalog.create_table("db.bw", SCHEMA, primary_keys=["id"], options={"bucket": "1"})

    def write_wm(ident, rows, watermark):
        wb = t.new_stream_write_builder()
        w = wb.new_write()
        w.write(rows)
        wb.new_commit().commit_messages(ident, w.prepare_commit(), watermark=watermark)

    bounded = t.copy({"scan.bounded.watermark": "1000"})
    scan = bounded.new_read_builder().new_stream_scan()
    write_wm(1, {"id": [1], "region": ["x"], "amount": [1.0]}, watermark=500)
    assert scan.plan()  # starting plan
    write_wm(2, {"id": [2], "region": ["x"], "amount": [2.0]}, watermark=900)
    assert scan.plan()  # within bound
    write_wm(3, {"id": [3], "region": ["x"], "amount": [3.0]}, watermark=1500)
    assert scan.plan() is None and scan.ended  # bound crossed: stream ENDS
    write_wm(4, {"id": [4], "region": ["x"], "amount": [4.0]}, watermark=1600)
    assert scan.plan() is None  # stays ended


def test_incremental_between_validation_and_pruning(catalog):
    import pytest as _pytest

    t = catalog.create_table(
        "db.incv", SCHEMA, primary_keys=["region", "id"], partition_keys=["region"],
        options={"bucket": "1"},
    )
    write_batch(t, {"id": [1, 2], "region": ["a", "b"], "amount": [1.0, 2.0]})
    write_batch(t, {"id": [3, 4], "region": ["a", "b"], "amount": [3.0, 4.0]})
    with _pytest.raises(ValueError, match="unknown tag"):
        t.copy({"incremental-between": "nope,alsono"}).new_read_builder().new_scan().plan()
    with _pytest.raises(ValueError, match="precede"):
        t.copy({"incremental-between": "2,1"}).new_read_builder().new_scan().plan()
    # partition predicate prunes incremental splits
    from paimon_tpu.data.predicate import equal

    inc = t.copy({"incremental-between": "1,2"})
    rb = inc.new_read_builder().with_filter(equal("region", "a"))
    splits = rb.new_scan().plan()
    assert splits and all(s.partition == ("a",) for s in splits)


def test_bounded_watermark_applies_to_first_plan(catalog):
    from paimon_tpu.table.write import BatchWriteBuilder

    t = catalog.create_table("db.bw2", SCHEMA, primary_keys=["id"], options={"bucket": "1"})
    wb = t.new_stream_write_builder()
    w = wb.new_write()
    w.write({"id": [1], "region": ["x"], "amount": [1.0]})
    wb.new_commit().commit_messages(1, w.prepare_commit(), watermark=5000)
    bounded = t.copy({"scan.bounded.watermark": "1000"})
    scan = bounded.new_read_builder().new_stream_scan()
    assert scan.plan() is None and scan.ended  # past bound before any data
    scan.restore(1)
    assert not scan.ended  # rollback clears the ended latch


def test_incremental_between_changelog_mode(catalog):
    """incremental-between-scan-mode=changelog replays the recorded change
    events (input producer) of the range."""
    t = catalog.create_table(
        "db.incc", SCHEMA, primary_keys=["id"],
        options={"bucket": "1", "changelog-producer": "input"},
    )
    write_batch(t, {"id": [1], "region": ["x"], "amount": [1.0]})
    write_batch(t, {"id": [1, 2], "region": ["x", "x"], "amount": [10.0, 2.0]})
    write_batch(t, {"id": [2], "region": ["x"], "amount": [None]}, kinds=["-D"])
    inc = t.copy({"incremental-between": "1,3", "incremental-between-scan-mode": "changelog"})
    rb = inc.new_read_builder()
    read = rb.new_read()
    events = []
    for s in rb.new_scan().plan():
        assert s.is_changelog
        data, kinds = read.read_with_kinds(s)
        events += [(int(k), r[0], r[2]) for r, k in zip(data.to_pylist(), kinds.tolist())]
    assert sorted(events) == [(0, 1, 10.0), (0, 2, 2.0), (3, 2, None)]


def test_local_merge_buffer(catalog):
    """local-merge-buffer-size collapses high-churn keys BEFORE routing
    (reference LocalMergeOperator): fewer rows land in L0, state identical."""
    import pytest as _pytest

    # tiny memtable: the plain table flushes per batch, so churn reaches L0;
    # the local-merge table collapses it in the PRE-routing buffer instead
    opts = {"bucket": "2", "write-only": "true", "write-buffer-rows": "30"}
    plain = catalog.create_table("db.lm_plain", SCHEMA, primary_keys=["id"], options=opts)
    local = catalog.create_table(
        "db.lm_local", SCHEMA, primary_keys=["id"],
        options={**opts, "local-merge-buffer-size": "64 mb"},
    )
    churn = []
    for r in range(5):
        churn.append({
            "id": list(range(20)),
            "region": ["x"] * 20,
            "amount": [float(r * 100 + i) for i in range(20)],
        })
    for t in (plain, local):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        for batch in churn:
            w.write(batch)
        w.write({"id": [0], "region": ["x"], "amount": [None]}, kinds=["-D"])
        wb.new_commit().commit(w.prepare_commit())
    assert sorted(read_batch(plain).to_pylist()) == sorted(read_batch(local).to_pylist())
    rows_plain = sum(f.file.row_count for f in plain.store.new_scan().plan().entries)
    rows_local = sum(f.file.row_count for f in local.store.new_scan().plan().entries)
    assert rows_local < rows_plain  # churn collapsed before the memtable
    assert rows_local <= 20  # one surviving record per key at most (+ -D)
    # guarded: only dedup PK tables
    with _pytest.raises(ValueError, match="deduplicate"):
        t = catalog.create_table(
            "db.lm_bad", SCHEMA, primary_keys=["id"],
            options={"bucket": "1", "merge-engine": "first-row", "local-merge-buffer-size": "1 mb"},
        )
        t.new_batch_write_builder().new_write()


def test_local_merge_partitioned_keeps_cross_partition_rows(catalog):
    """Round-2 review regression: local merge must dedup on the FULL primary
    key — same trimmed id in different partitions must BOTH survive."""
    schema = RowType.of(("region", STRING()), ("id", BIGINT()), ("amount", DOUBLE()))
    t = catalog.create_table(
        "db.lm_part", schema, primary_keys=["region", "id"], partition_keys=["region"],
        options={"bucket": "1", "local-merge-buffer-size": "64 mb"},
    )
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({"region": ["a"], "id": [1], "amount": [10.0]})
    w.write({"region": ["b"], "id": [1], "amount": [20.0]})
    wb.new_commit().commit(w.prepare_commit())
    assert sorted(read_batch(t).to_pylist()) == [("a", 1, 10.0), ("b", 1, 20.0)]
    # invalid combos rejected up front
    import pytest as _pytest

    with _pytest.raises(ValueError, match="sequence.field"):
        catalog.create_table(
            "db.lm_seq", schema, primary_keys=["region", "id"],
            options={"bucket": "1", "local-merge-buffer-size": "1 mb", "sequence.field": "amount"},
        ).new_batch_write_builder().new_write()
    with _pytest.raises(ValueError, match="ignore-delete"):
        catalog.create_table(
            "db.lm_ign", schema, primary_keys=["region", "id"],
            options={"bucket": "1", "local-merge-buffer-size": "1 mb", "ignore-delete": "true"},
        ).new_batch_write_builder().new_write()
