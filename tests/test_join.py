"""Device-side skew-aware join subsystem (ISSUE 12, ops/join + SQL JOIN +
vectorized lookups).

The contract: every join result is BIT-IDENTICAL to an independent host
oracle (a dict-based nested probe, cross-checked against pandas.merge at
the SQL level) — across seeds, key skew, null rates, dict/non-dict key
columns, lane-compression on/off, engines (numpy / xla / pallas) and
partitioned skew-split execution — while dict-backed keys actually match
in the code domain (join{code_domain_joins} > 0, zero string
materialization on the matched path) and one hot key never serializes a
partition (the pinned 50%-skew regression)."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.data.batch import Column, ColumnBatch
from paimon_tpu.metrics import join_metrics, registry
from paimon_tpu.ops.join import JoinError, JoinIndex, join_batches, materialize_join
from paimon_tpu.types import BIGINT, DATE, DOUBLE, INT, STRING, RowType


def oracle_pairs(left_keys, right_keys, how="inner"):
    """Independent nested-probe oracle: probe-major pairs, build rows
    ascending within each probe row; NULL (None) keys never match."""
    pos: dict = {}
    for j, k in enumerate(right_keys):
        if k is not None and (not isinstance(k, tuple) or None not in k):
            pos.setdefault(k, []).append(j)
    lt, rt = [], []
    for i, k in enumerate(left_keys):
        matches = (
            pos.get(k, [])
            if k is not None and (not isinstance(k, tuple) or None not in k)
            else []
        )
        if matches:
            for j in matches:
                lt.append(i)
                rt.append(j)
        elif how == "left":
            lt.append(i)
            rt.append(-1)
    return np.asarray(lt, dtype=np.int64), np.asarray(rt, dtype=np.int64)


def keys_of(batch, names):
    cols = [batch.column(n).to_pylist() for n in names]
    if len(cols) == 1:
        return cols[0]
    return [None if any(v is None for v in row) else tuple(row) for row in zip(*cols)]


def assert_join_matches_oracle(left, right, lkeys, rkeys, how, **kw):
    res = join_batches(left, right, lkeys, rkeys, how=how, **kw)
    olt, ort = oracle_pairs(keys_of(left, lkeys), keys_of(right, rkeys), how)
    np.testing.assert_array_equal(res.left_take, olt)
    np.testing.assert_array_equal(res.right_take, ort)
    return res


# ---------------------------------------------------------------------------
# kernel parity
# ---------------------------------------------------------------------------

SKEWS = {
    "uniform": lambda rng, n, dom: rng.integers(0, dom, n),
    "zipfish": lambda rng, n, dom: np.minimum(
        (rng.pareto(1.2, n) * dom / 8).astype(np.int64), dom - 1
    ),
    "hot50": lambda rng, n, dom: np.where(
        rng.random(n) < 0.5, 7, rng.integers(0, dom, n)
    ),
}


@pytest.mark.parametrize("engine", ["numpy", "xla", "pallas"])
@pytest.mark.parametrize("how", ["inner", "left"])
@pytest.mark.parametrize("skew", sorted(SKEWS))
def test_single_int_key_parity(engine, how, skew):
    rng = np.random.default_rng(hash((engine, how, skew)) % (1 << 16))
    n_l, n_r, dom = 3000, 500, 700
    lk = SKEWS[skew](rng, n_l, dom).astype(np.int64)
    rk = rng.choice(dom, n_r, replace=False).astype(np.int64)
    left = ColumnBatch.from_pydict(
        RowType.of(("k", BIGINT()), ("v", DOUBLE())), {"k": lk.tolist(), "v": (lk * 0.5).tolist()}
    )
    right = ColumnBatch.from_pydict(
        RowType.of(("id", BIGINT()), ("name", STRING())),
        {"id": rk.tolist(), "name": [f"n{int(x)}" for x in rk]},
    )
    assert_join_matches_oracle(left, right, ["k"], ["id"], how, engine=engine)


@pytest.mark.parametrize("engine", ["numpy", "xla", "pallas"])
@pytest.mark.parametrize("null_rate", [0.0, 0.25])
def test_composite_string_int_key_parity(engine, null_rate):
    rng = np.random.default_rng(hash((engine, null_rate)) % (1 << 16))
    n_l, n_r = 1500, 400
    schema_l = RowType.of(("s", STRING()), ("k", INT()), ("v", DOUBLE()))
    schema_r = RowType.of(("s", STRING()), ("id", INT()), ("w", BIGINT()))

    def col(n, dom):
        return [
            None if rng.random() < null_rate else f"g{int(x)}"
            for x in rng.integers(0, dom, n)
        ]

    left = ColumnBatch.from_pydict(
        schema_l,
        {"s": col(n_l, 6), "k": rng.integers(0, 40, n_l).tolist(), "v": [0.5] * n_l},
    )
    right = ColumnBatch.from_pydict(
        schema_r,
        {"s": col(n_r, 9), "id": rng.integers(0, 40, n_r).tolist(), "w": [1] * n_r},
    )
    for how in ("inner", "left"):
        res = assert_join_matches_oracle(left, right, ["s", "k"], ["s", "id"], how, engine=engine)
    assert res.stats["algorithm"] in ("hash", "sort-merge")


@pytest.mark.parametrize("compress", ["1", "0"])
def test_lane_compression_on_off_identical(monkeypatch, compress):
    monkeypatch.setenv("PAIMON_TPU_LANE_COMPRESSION", compress)
    rng = np.random.default_rng(11)
    n_l, n_r = 2000, 300
    left = ColumnBatch.from_pydict(
        RowType.of(("a", BIGINT()), ("b", INT())),
        {"a": rng.integers(0, 50, n_l).tolist(), "b": rng.integers(0, 9, n_l).tolist()},
    )
    right = ColumnBatch.from_pydict(
        RowType.of(("a", BIGINT()), ("b", INT())),
        {"a": rng.integers(0, 50, n_r).tolist(), "b": rng.integers(0, 9, n_r).tolist()},
    )
    for engine in ("numpy", "xla"):
        assert_join_matches_oracle(left, right, ["a", "b"], ["a", "b"], "inner", engine=engine)


def test_skew_split_pinned_regression():
    """One key holds 50% of the probe rows: the partitioner must SPLIT it
    (join{skew_keys, skew_split_rows}) across every partition, and the
    output must stay bit-identical to the unpartitioned oracle."""
    rng = np.random.default_rng(5)
    n_l, n_r = 8000, 600
    lk = rng.integers(0, 800, n_l)
    lk[: n_l // 2] = 13
    rng.shuffle(lk)
    rk = rng.choice(800, n_r, replace=False)
    left = ColumnBatch.from_pydict(RowType.of(("k", BIGINT()),), {"k": lk.tolist()})
    right = ColumnBatch.from_pydict(RowType.of(("id", BIGINT()),), {"id": rk.tolist()})
    registry.reset()
    res = assert_join_matches_oracle(
        left, right, ["k"], ["id"], "inner", options={"join.partitions": "4"}
    )
    assert res.stats["partitions"] == 4
    assert res.stats["skew_keys"] >= 1
    assert res.stats["skew_split_rows"] >= n_l // 2
    g = join_metrics()
    assert g.counter("skew_keys").count >= 1
    assert g.counter("skew_split_rows").count >= n_l // 2
    # and the split spread the hot key: each partition saw some of its rows
    # (round-robin deal), which the bit-identical output already proves


def test_partitioned_left_join_parity():
    rng = np.random.default_rng(17)
    n_l, n_r = 5000, 600
    lk = SKEWS["hot50"](rng, n_l, 900).astype(np.int64)
    rk = rng.choice(900, n_r, replace=False).astype(np.int64)
    left = ColumnBatch.from_pydict(RowType.of(("k", BIGINT()),), {"k": lk.tolist()})
    right = ColumnBatch.from_pydict(RowType.of(("id", BIGINT()),), {"id": rk.tolist()})
    for engine in ("numpy", "xla"):
        assert_join_matches_oracle(
            left, right, ["k"], ["id"], "left",
            options={"join.partitions": "3"}, engine=engine,
        )


def test_all_equal_keys_cross_product():
    left = ColumnBatch.from_pydict(RowType.of(("k", BIGINT()),), {"k": [7, 7, 7]})
    right = ColumnBatch.from_pydict(RowType.of(("id", BIGINT()),), {"id": [7, 7]})
    res = assert_join_matches_oracle(left, right, ["k"], ["id"], "inner")
    assert res.num_rows == 6


def test_empty_sides():
    left = ColumnBatch.from_pydict(RowType.of(("k", BIGINT()),), {"k": [1, 2]})
    empty = ColumnBatch.from_pydict(RowType.of(("id", BIGINT()),), {"id": []})
    assert join_batches(left, empty, ["k"], ["id"], how="inner").num_rows == 0
    res = join_batches(left, empty, ["k"], ["id"], how="left")
    np.testing.assert_array_equal(res.right_take, [-1, -1])
    assert join_batches(empty.rename(RowType.of(("k", BIGINT()),)), left.rename(RowType.of(("id", BIGINT()),)), ["k"], ["id"]).num_rows == 0


def test_null_keys_never_match():
    left = ColumnBatch.from_pydict(RowType.of(("s", STRING()),), {"s": ["a", None, "b", None]})
    right = ColumnBatch.from_pydict(RowType.of(("s", STRING()),), {"s": [None, "a", "a"]})
    res = assert_join_matches_oracle(left, right, ["s"], ["s"], "inner")
    assert res.num_rows == 2  # "a" matches twice; None rows never
    res = assert_join_matches_oracle(left, right, ["s"], ["s"], "left")
    assert (res.right_take < 0).sum() == 3  # both None rows + "b" unmatched


def test_key_type_mismatch_raises():
    left = ColumnBatch.from_pydict(RowType.of(("k", BIGINT()),), {"k": [1]})
    right = ColumnBatch.from_pydict(RowType.of(("k", STRING()),), {"k": ["x"]})
    with pytest.raises(JoinError):
        join_batches(left, right, ["k"], ["k"])


# ---------------------------------------------------------------------------
# code-domain joins
# ---------------------------------------------------------------------------


def _coded_column(rng, n, dom, prefix):
    vals = np.array([f"{prefix}{int(x):04d}" for x in rng.integers(0, dom, n)], dtype=object)
    pool = np.unique(vals)
    codes = np.searchsorted(pool, vals).astype(np.uint32)
    return Column.from_codes(pool, codes), vals


def test_code_domain_join_zero_string_materialization():
    rng = np.random.default_rng(23)
    n_l, n_r = 4000, 700
    lc, lvals = _coded_column(rng, n_l, 300, "d")
    rc, rvals = _coded_column(rng, n_r, 450, "d")
    left = ColumnBatch(RowType.of(("s", STRING()), ("v", DOUBLE())), {"s": lc, "v": Column(np.ones(n_l))})
    right = ColumnBatch(RowType.of(("s", STRING()), ("w", DOUBLE())), {"s": rc, "w": Column(np.ones(n_r))})
    registry.reset()
    res = join_batches(left, right, ["s"], ["s"], how="inner")
    olt, ort = oracle_pairs(lvals.tolist(), rvals.tolist(), "inner")
    np.testing.assert_array_equal(res.left_take, olt)
    np.testing.assert_array_equal(res.right_take, ort)
    assert res.stats["code_domain_cols"] == 1
    assert join_metrics().counter("code_domain_joins").count == 1
    out = materialize_join(left, right, res, [("s", "s"), ("v", "v")], [("w", "w")])
    # the matched path never expanded a single string: the output key column
    # is still code-backed and dict{fallback_expanded} stayed at zero
    assert out.column("s").is_code_backed
    from paimon_tpu.metrics import dict_metrics

    assert dict_metrics().counter("fallback_expanded").count == 0


def test_code_domain_pool_limit_falls_back(monkeypatch):
    monkeypatch.setenv("PAIMON_TPU_DICT_POOL_LIMIT", "8")
    rng = np.random.default_rng(29)
    lc, lvals = _coded_column(rng, 500, 40, "d")
    rc, rvals = _coded_column(rng, 200, 40, "d")
    left = ColumnBatch(RowType.of(("s", STRING()),), {"s": lc})
    right = ColumnBatch(RowType.of(("s", STRING()),), {"s": rc})
    res = join_batches(left, right, ["s"], ["s"])
    assert res.stats["code_domain_cols"] == 0  # expanded fallback, still exact
    olt, ort = oracle_pairs(lvals.tolist(), rvals.tolist(), "inner")
    np.testing.assert_array_equal(res.left_take, olt)
    np.testing.assert_array_equal(res.right_take, ort)


def test_fixed_width_code_domain_table_join(tmp_warehouse):
    """ISSUE 12 satellite: int/date dictionary columns read code-backed
    (native decoder) join in the code domain — bit-identical to the
    expanded read."""
    cat = FileSystemCatalog(tmp_warehouse, commit_user="fw")
    rt = RowType.of(("k", BIGINT(False)), ("cat", INT()), ("d", DATE()), ("v", DOUBLE()))
    t = cat.create_table(
        "db.fw", rt, primary_keys=["k"],
        options={"bucket": "1", "format.parquet.decoder": "native"},
    )
    rng = np.random.default_rng(31)
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    ids = np.arange(1200, dtype=np.int64)
    w.write({
        "k": ids, "cat": (ids % 11).astype(np.int32),
        "d": (ids % 25).astype(np.int32), "v": ids * 0.25,
    })
    wb.new_commit().commit(w.prepare_commit())

    def read(dd):
        t2 = t.copy({"merge.dict-domain": dd})
        rb = t2.new_read_builder()
        return rb.new_read().read_all(rb.new_scan().plan())

    on, off = read("true"), read("false")
    assert on.column("cat").is_code_backed  # the reader delivered codes
    assert on.column("cat").dict_cache[0].dtype == np.dtype(np.int32)
    # parity AFTER the code-backed checks: to_pylist expands lazily in place
    assert on.to_pylist() == off.to_pylist()
    on = read("true")  # fresh code-backed batch for the join below
    dim = ColumnBatch.from_pydict(
        RowType.of(("cid", INT()), ("label", STRING())),
        {"cid": list(range(11)), "label": [f"c{i}" for i in range(11)]},
    )
    res_on = join_batches(on, dim, ["cat"], ["cid"])
    res_off = join_batches(off, dim, ["cat"], ["cid"])
    np.testing.assert_array_equal(res_on.left_take, res_off.left_take)
    np.testing.assert_array_equal(res_on.right_take, res_off.right_take)


# ---------------------------------------------------------------------------
# JoinIndex (cached build side / lookup tier)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("keys", [["id"], ["s"], ["s", "id"]])
def test_join_index_probe_parity(keys):
    rng = np.random.default_rng(37)
    n_b, n_p = 600, 2500
    build = ColumnBatch.from_pydict(
        RowType.of(("id", BIGINT()), ("s", STRING()), ("v", DOUBLE())),
        {
            "id": rng.integers(0, 200, n_b).tolist(),
            "s": [f"g{int(x)}" for x in rng.integers(0, 30, n_b)],
            "v": [1.0] * n_b,
        },
    )
    probe = ColumnBatch.from_pydict(
        RowType.of(("id", BIGINT()), ("s", STRING())),
        {
            # half the probe values fall OUTSIDE the build domain: the
            # present-mask must kill them exactly (no false matches)
            "id": rng.integers(0, 400, n_p).tolist(),
            "s": [f"g{int(x)}" for x in rng.integers(0, 60, n_p)],
        },
    )
    idx = JoinIndex(build, keys)
    for how in ("inner", "left"):
        res = idx.probe(probe, keys, how=how)
        olt, ort = oracle_pairs(keys_of(probe, keys), keys_of(build, keys), how)
        np.testing.assert_array_equal(res.left_take, olt)
        np.testing.assert_array_equal(res.right_take, ort)


def test_join_index_wide_key_falls_back():
    rng = np.random.default_rng(41)
    n = 300
    schema = RowType.of(("a", BIGINT()), ("b", BIGINT()), ("c", BIGINT()), ("s", STRING()))
    data = {
        "a": rng.integers(0, 1 << 40, n).tolist(),
        "b": rng.integers(0, 1 << 40, n).tolist(),
        "c": rng.integers(0, 1 << 40, n).tolist(),
        "s": [f"x{int(v)}" for v in rng.integers(0, 50, n)],
    }
    build = ColumnBatch.from_pydict(schema, data)
    idx = JoinIndex(build, ["a", "b", "c", "s"])
    assert idx.wide
    probe = build.slice(0, 50)
    res = idx.probe(probe, ["a", "b", "c", "s"], how="inner")
    olt, ort = oracle_pairs(
        keys_of(probe, ["a", "b", "c", "s"]), keys_of(build, ["a", "b", "c", "s"]), "inner"
    )
    np.testing.assert_array_equal(res.left_take, olt)
    np.testing.assert_array_equal(res.right_take, ort)


def test_join_index_null_and_empty_build():
    build = ColumnBatch.from_pydict(RowType.of(("s", STRING()),), {"s": [None, None]})
    idx = JoinIndex(build, ["s"])
    probe = ColumnBatch.from_pydict(RowType.of(("s", STRING()),), {"s": ["a", None]})
    res = idx.probe(probe, ["s"], how="left")
    np.testing.assert_array_equal(res.right_take, [-1, -1])
    empty = ColumnBatch.from_pydict(RowType.of(("s", STRING()),), {"s": []})
    idx2 = JoinIndex(empty, ["s"])
    assert idx2.probe(probe, ["s"], how="inner").num_rows == 0


# ---------------------------------------------------------------------------
# vectorized lookup tables
# ---------------------------------------------------------------------------


def _dim_table(tmp_warehouse, name="db.dim", n=300):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="lkp")
    t = cat.create_table(
        name,
        RowType.of(("id", BIGINT(False)), ("name", STRING()), ("grp", STRING())),
        primary_keys=["id"],
        options={"bucket": "1"},
    )
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({
        "id": np.arange(n, dtype=np.int64),
        "name": [f"n{i}" for i in range(n)],
        "grp": [f"g{i % 7}" for i in range(n)],
    })
    wb.new_commit().commit(w.prepare_commit())
    return t


def test_scalar_get_is_thin_wrapper_with_parity(tmp_warehouse):
    from paimon_tpu.lookup.tables import FullCacheLookupTable

    t = _dim_table(tmp_warehouse)
    primary = FullCacheLookupTable(t)
    secondary = FullCacheLookupTable(t, join_keys=["grp"])
    for k in [(0,), (123,), (299,), (9999,)]:
        assert primary.get(k) == primary._legacy_get(k)
    for k in [("g0",), ("g6",), ("nope",)]:
        assert secondary.get(k) == secondary._legacy_get(k)


def test_get_batch_vectorized_and_refresh_invalidation(tmp_warehouse):
    from paimon_tpu.lookup.tables import FullCacheLookupTable

    t = _dim_table(tmp_warehouse)
    lt = FullCacheLookupTable(t)
    batch, lidx = lt.get_batch([(5,), (700,), (9,)])
    assert batch.to_pylist() == [(5, "n5", "g5"), (9, "n9", "g2")]
    np.testing.assert_array_equal(lidx, [0, 2])
    # upsert a row, refresh: the index must rebuild
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({"id": [5], "name": ["CHANGED"], "grp": ["g5"]})
    wb.new_commit().commit(w.prepare_commit())
    assert lt.refresh() > 0
    batch, _ = lt.get_batch([(5,)])
    assert batch.to_pylist() == [(5, "CHANGED", "g5")]


def test_lookup_join_enrichment_matches_pandas(tmp_warehouse):
    import pandas as pd

    from paimon_tpu.lookup.tables import FullCacheLookupTable, lookup_join

    t = _dim_table(tmp_warehouse)
    lt = FullCacheLookupTable(t)
    rng = np.random.default_rng(43)
    probe = ColumnBatch.from_pydict(
        RowType.of(("id", BIGINT()), ("x", DOUBLE())),
        {"id": rng.integers(0, 450, 1000).tolist(), "x": rng.random(1000).tolist()},
    )
    out = lookup_join(lt, probe)
    assert out.schema.field_names == ["id", "x", "id_lookup", "name", "grp"]
    pdf = pd.DataFrame(probe.to_pydict())
    ddf = pd.DataFrame(lt.state_batch().to_pydict())
    exp = pdf.merge(ddf, left_on="id", right_on="id", how="left", suffixes=("", "_r"))
    got = out.to_pydict()
    assert got["id"] == exp["id"].tolist()
    assert [v if v is not None else None for v in got["name"]] == [
        None if isinstance(v, float) and np.isnan(v) else v for v in exp["name"].tolist()
    ]


def test_no_pk_multimap_get_batch(tmp_warehouse):
    from paimon_tpu.lookup.tables import FullCacheLookupTable

    cat = FileSystemCatalog(tmp_warehouse, commit_user="lkp")
    t = cat.create_table(
        "db.app", RowType.of(("k", BIGINT()), ("v", STRING())), options={"bucket": "1"}
    )
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write({"k": [1, 2, 1, 3, 1], "v": ["a", "b", "c", "d", "e"]})
    wb.new_commit().commit(w.prepare_commit())
    lt = FullCacheLookupTable(t, join_keys=["k"])
    assert lt.get((1,)) == lt._legacy_get((1,)) == [(1, "a"), (1, "c"), (1, "e")]
    batch, lidx = lt.get_batch([(3,), (1,)])
    assert batch.to_pylist() == [(3, "d"), (1, "a"), (1, "c"), (1, "e")]
    np.testing.assert_array_equal(lidx, [0, 1, 1, 1])


# ---------------------------------------------------------------------------
# SQL JOIN end to end
# ---------------------------------------------------------------------------


@pytest.fixture
def star(tmp_warehouse):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="sql")
    fact = cat.create_table(
        "db.fact",
        RowType.of(("id", BIGINT(False)), ("cust", BIGINT()), ("amount", DOUBLE()), ("qty", BIGINT())),
        primary_keys=["id"],
        options={"bucket": "1"},
    )
    dim = cat.create_table(
        "db.dim",
        RowType.of(("cid", BIGINT(False)), ("name", STRING()), ("region", STRING())),
        primary_keys=["cid"],
        options={"bucket": "1"},
    )
    rng = np.random.default_rng(7)
    n = 3000
    wb = fact.new_batch_write_builder()
    w = wb.new_write()
    w.write({
        "id": np.arange(n, dtype=np.int64),
        "cust": rng.integers(0, 140, n),  # 100..139 have no dim row
        "amount": rng.random(n).round(4),
        "qty": rng.integers(1, 5, n),
    })
    wb.new_commit().commit(w.prepare_commit())
    wb = dim.new_batch_write_builder()
    w = wb.new_write()
    w.write({
        "cid": np.arange(100, dtype=np.int64),
        "name": [f"c{i:03d}" for i in range(100)],
        "region": [["EU", "US", "APAC"][i % 3] for i in range(100)],
    })
    wb.new_commit().commit(w.prepare_commit())
    return cat, fact, dim


def _frames(fact, dim):
    import pandas as pd

    rb = fact.new_read_builder()
    fdf = pd.DataFrame(rb.new_read().read_all(rb.new_scan().plan()).to_pydict())
    rb = dim.new_read_builder()
    ddf = pd.DataFrame(rb.new_read().read_all(rb.new_scan().plan()).to_pydict())
    return fdf, ddf


def test_sql_inner_join_matches_pandas(star):
    from paimon_tpu.sql import query

    cat, fact, dim = star
    out = query(
        cat,
        "SELECT f.id, d.name, f.amount FROM db.fact f JOIN db.dim d ON f.cust = d.cid ORDER BY f.id",
    )
    fdf, ddf = _frames(fact, dim)
    exp = fdf.merge(ddf, left_on="cust", right_on="cid", how="inner").sort_values("id")
    assert out.num_rows == len(exp)
    assert out.to_pydict()["id"] == exp["id"].tolist()
    assert out.to_pydict()["name"] == exp["name"].tolist()


def test_sql_left_join_and_residual_where(star):
    from paimon_tpu.sql import query

    cat, fact, dim = star
    out = query(
        cat,
        "SELECT count(*) FROM db.fact f LEFT JOIN db.dim d ON f.cust = d.cid WHERE d.name IS NULL",
    )
    fdf, ddf = _frames(fact, dim)
    exp = fdf.merge(ddf, left_on="cust", right_on="cid", how="left")
    assert out.to_pylist()[0][0] == int(exp["name"].isna().sum())


def test_sql_join_group_by_and_pushdown(star):
    from paimon_tpu.sql import query

    cat, fact, dim = star
    out = query(
        cat,
        "SELECT region, count(*), sum(amount) FROM db.fact f JOIN db.dim d ON f.cust = d.cid "
        "WHERE region = 'EU' AND f.qty >= 2 GROUP BY region",
    )
    fdf, ddf = _frames(fact, dim)
    exp = fdf[fdf.qty >= 2].merge(ddf[ddf.region == "EU"], left_on="cust", right_on="cid")
    (row,) = out.to_pylist()
    assert row[0] == "EU" and row[1] == len(exp)
    assert abs(row[2] - exp["amount"].sum()) < 1e-9


def test_sql_join_star_and_ambiguity(star):
    from paimon_tpu.sql import query
    from paimon_tpu.sql.select import QueryError

    cat, _, _ = star
    out = query(cat, "SELECT * FROM db.fact f JOIN db.dim d ON f.cust = d.cid LIMIT 3")
    assert out.schema.field_names == ["id", "cust", "amount", "qty", "cid", "name", "region"]
    # a column present in both sides must be qualified
    with pytest.raises(QueryError):
        query(cat, "SELECT name FROM db.fact f JOIN db.fact g ON f.id = g.id")
    with pytest.raises(QueryError):
        query(cat, "SELECT f.id FROM db.fact f JOIN db.dim d ON f.cust < d.cid")


def test_sql_join_small_side_prunes_big_scan(star):
    """The dimension filter shrinks the fact-side scan: the planner pushes
    the small side's key set onto the big side as an IN predicate, so the
    fact read returns only prunable-matching rows (validated by result
    parity; the pushdown itself is observable through the join metrics'
    probe row count)."""
    from paimon_tpu.sql import query

    cat, fact, dim = star
    registry.reset()
    out = query(
        cat,
        "SELECT f.id FROM db.fact f JOIN db.dim d ON f.cust = d.cid WHERE d.region = 'APAC' ORDER BY f.id",
    )
    fdf, ddf = _frames(fact, dim)
    exp = fdf.merge(ddf[ddf.region == "APAC"], left_on="cust", right_on="cid")
    assert out.to_pydict()["id"] == sorted(exp["id"].tolist())
    probed = join_metrics().counter("rows_probed").count
    # the IN pushdown pre-filtered the fact rows to (close to) the matched
    # set: far fewer than the full 3000-row fact table reached the kernel
    assert probed <= len(exp)


def test_sql_join_under_mesh_and_dict_domain(star, monkeypatch):
    from paimon_tpu.sql import query

    cat, fact, dim = star
    base = query(
        cat,
        "SELECT f.id, d.region FROM db.fact f JOIN db.dim d ON f.cust = d.cid ORDER BY f.id LIMIT 50",
    ).to_pylist()
    monkeypatch.setenv("PAIMON_TPU_MERGE_ENGINE", "mesh")
    monkeypatch.setenv("PAIMON_TPU_DICT_DOMAIN", "1")
    got = query(
        cat,
        "SELECT f.id, d.region FROM db.fact f JOIN db.dim d ON f.cust = d.cid ORDER BY f.id LIMIT 50",
    ).to_pylist()
    assert got == base


def test_sql_join_multi_key_and_aliases_defaulted(tmp_warehouse):
    from paimon_tpu.sql import query

    cat = FileSystemCatalog(tmp_warehouse, commit_user="sql")
    a = cat.create_table(
        "db.a", RowType.of(("g", STRING(False)), ("n", BIGINT(False)), ("v", DOUBLE())),
        primary_keys=["g", "n"], options={"bucket": "1"},
    )
    b = cat.create_table(
        "db.b", RowType.of(("g", STRING(False)), ("n", BIGINT(False)), ("w", DOUBLE())),
        primary_keys=["g", "n"], options={"bucket": "1"},
    )
    rng = np.random.default_rng(3)
    for t, col in ((a, "v"), (b, "w")):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        n = 400
        w.write({
            "g": [f"g{int(x)}" for x in rng.integers(0, 5, n)],
            "n": rng.integers(0, 50, n),
            col: rng.random(n),
        })
        wb.new_commit().commit(w.prepare_commit())
    out = query(
        cat, "SELECT a.g, a.n, v, w FROM db.a JOIN db.b ON a.g = b.g AND a.n = b.n ORDER BY a.g, a.n"
    )
    import pandas as pd

    rb = a.new_read_builder()
    adf = pd.DataFrame(rb.new_read().read_all(rb.new_scan().plan()).to_pydict())
    rb = b.new_read_builder()
    bdf = pd.DataFrame(rb.new_read().read_all(rb.new_scan().plan()).to_pydict())
    exp = adf.merge(bdf, on=["g", "n"], how="inner").sort_values(["g", "n"])
    # g/n exist in both tables: the output labels them alias-qualified
    assert out.schema.field_names == ["a.g", "a.n", "v", "w"]
    assert out.to_pydict()["a.g"] == exp["g"].tolist()
    assert out.to_pydict()["v"] == exp["v"].tolist()


# ---------------------------------------------------------------------------
# randomized cross-dimension oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(4))
def test_randomized_join_oracle(seed, monkeypatch):
    """seeds x skew x null-rate x dict/non-dict x engine x how x partitions:
    every combination bit-identical to the nested-probe oracle."""
    rng = np.random.default_rng(seed)
    monkeypatch.setenv(
        "PAIMON_TPU_LANE_COMPRESSION", "1" if seed % 2 == 0 else "0"
    )
    n_l = int(rng.integers(50, 4000))
    n_r = int(rng.integers(10, 800))
    dom = int(rng.integers(5, 500))
    null_rate = float(rng.choice([0.0, 0.1, 0.4]))
    skew = rng.choice(sorted(SKEWS))
    dict_backed = bool(rng.integers(0, 2))
    lk = SKEWS[skew](rng, n_l, dom)
    rk = rng.integers(0, dom, n_r)

    def scol(keys, n):
        vals = np.array(
            [None if rng.random() < null_rate else f"s{int(x):04d}" for x in keys],
            dtype=object,
        )
        if not dict_backed:
            return Column.from_pylist(vals, STRING()), vals
        present = np.array([v for v in vals if v is not None], dtype=object)
        pool = np.unique(present) if len(present) else np.empty(0, dtype=object)
        validity = np.array([v is not None for v in vals], dtype=bool)
        codes = np.zeros(n, dtype=np.uint32)
        if len(pool):
            codes[validity] = np.searchsorted(pool, present).astype(np.uint32)
        return Column.from_codes(pool, codes, None if validity.all() else validity), vals

    lc, lvals = scol(lk, n_l)
    rc, rvals = scol(rk, n_r)
    left = ColumnBatch(RowType.of(("s", STRING()),), {"s": lc})
    right = ColumnBatch(RowType.of(("s", STRING()),), {"s": rc})
    how = "left" if seed % 2 else "inner"
    engine = ["numpy", "xla", "pallas"][seed % 3]
    parts = str(int(rng.integers(1, 5)))
    res = join_batches(
        left, right, ["s"], ["s"], how=how,
        options={"join.partitions": parts}, engine=engine,
    )
    olt, ort = oracle_pairs(lvals.tolist(), rvals.tolist(), how)
    np.testing.assert_array_equal(res.left_take, olt)
    np.testing.assert_array_equal(res.right_take, ort)
