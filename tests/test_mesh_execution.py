"""Table operations through the device mesh (parallel.mesh.enabled):
write flush, compaction rewrite, and merge-read batch their per-bucket merge
jobs into shard_map calls over the 8-device virtual CPU mesh, and results
byte-match the single-device path. The TPU analog of the reference's
engine-distributed execution (FlinkSinkBuilder.java:223 topology,
MergeTreeSplitGenerator.java:38 splits)."""

import numpy as np
import pytest

import jax

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.types import BIGINT, DOUBLE, STRING, RowType

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (virtual CPU mesh or a pod slice)"
)

SCHEMA = RowType.of(("pt", STRING()), ("id", BIGINT()), ("v", DOUBLE()), ("name", STRING()))


@pytest.fixture
def two_tables(tmp_warehouse):
    """The same logical table twice: mesh-parallel and single-device."""
    cat = FileSystemCatalog(tmp_warehouse, commit_user="mesh")
    common = {"bucket": "4", "write-buffer.rows": "100000"}
    par = cat.create_table(
        "db.par", SCHEMA, primary_keys=["pt", "id"], partition_keys=["pt"],
        options={**common, "parallel.mesh.enabled": "true"},
    )
    ser = cat.create_table(
        "db.ser", SCHEMA, primary_keys=["pt", "id"], partition_keys=["pt"], options=common
    )
    return par, ser


def _dataset(rng, rounds=3, n=600):
    out = []
    for r in range(rounds):
        ids = rng.integers(0, 400, n)
        out.append(
            {
                "pt": [f"p{i % 2}" for i in ids],
                "id": ids.tolist(),
                "v": (ids * 1.0 + r * 1000).tolist(),
                "name": [f"r{r}-{i}" for i in ids],
            }
        )
    return out


def _write(t, data):
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write(data)
    wb.new_commit().commit(w.prepare_commit())


def _read(t, **kw):
    rb = t.new_read_builder()
    for k, v in kw.items():
        getattr(rb, f"with_{k}")(v)
    return rb.new_read().read_all(rb.new_scan().plan())


def _canon(batch):
    rows = batch.to_pylist()
    return sorted(rows)


def test_mesh_write_read_matches_single_device(two_tables, rng):
    par, ser = two_tables
    for data in _dataset(rng):
        _write(par, data)
        _write(ser, data)
    got, want = _canon(_read(par)), _canon(_read(ser))
    assert got == want
    assert len(got) == len({(r[0], r[1]) for r in got})  # unique PKs


def test_mesh_compaction_matches_single_device(two_tables, rng):
    par, ser = two_tables
    for data in _dataset(rng, rounds=4, n=300):
        _write(par, data)
        _write(ser, data)
    for t in (par, ser):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.compact(full=True)
        wb.new_commit().commit(w.prepare_commit())
    # full compaction leaves one top-level run per bucket and identical rows
    assert _canon(_read(par)) == _canon(_read(ser))
    plan = par.store.new_scan().plan()
    for e in plan.entries:
        assert e.file.level == par.store.options.num_levels - 1


def test_mesh_read_batches_merges_into_one_call(two_tables, rng):
    """All buckets' merge-read jobs run in ONE batched shard_map call."""
    from paimon_tpu.parallel.executor import mesh_batch

    par, _ = two_tables
    for data in _dataset(rng, rounds=2, n=400):
        _write(par, data)
    rb = par.new_read_builder()
    splits = rb.new_scan().plan()
    assert len(splits) >= 4  # 2 partitions x >=2 live buckets
    read = rb.new_read()
    with mesh_batch() as ctx:
        pending = [(s, read._dispatch(s)) for s in splits]
        out = [c() for _, c in pending]
        # one dedup batch served every bucket's merge (no per-bucket calls)
        assert ctx.executed_batches == 1
    rows = sorted(r for b in out for r in b.to_pylist())
    assert rows == _canon(_read(par))


def test_mesh_partial_update_and_aggregation(tmp_warehouse, rng):
    """Non-dedup engines route through the batched plan kernel."""
    cat = FileSystemCatalog(tmp_warehouse, commit_user="mesh2")
    schema = RowType.of(("id", BIGINT()), ("a", DOUBLE()), ("b", DOUBLE()))
    for engine, extra in (
        ("partial-update", {}),
        ("aggregation", {"fields.a.aggregate-function": "sum", "fields.b.aggregate-function": "max"}),
    ):
        par = cat.create_table(
            f"db.pu_par_{engine[:4]}", schema, primary_keys=["id"],
            options={"bucket": "2", "merge-engine": engine, "parallel.mesh.enabled": "true", **extra},
        )
        ser = cat.create_table(
            f"db.pu_ser_{engine[:4]}", schema, primary_keys=["id"],
            options={"bucket": "2", "merge-engine": engine, **extra},
        )
        for r in range(3):
            ids = rng.integers(0, 50, 120)
            data = {
                "id": ids.tolist(),
                "a": [float(i + r) for i in ids],
                "b": [None if (i + r) % 3 == 0 else float(i * r) for i in ids],
            }
            _write(par, data)
            _write(ser, data)
        assert _canon(_read(par)) == _canon(_read(ser)), engine


def test_distributed_dedup_select_oracle(rng):
    """Key-axis path: range-shuffled dedup over all 8 devices matches the
    host oracle, including input-order tie-breaks."""
    from paimon_tpu.parallel.executor import distributed_dedup_select, _meshes

    _, key_mesh = _meshes()
    n = 4096
    keys = rng.integers(0, 300, n).astype(np.uint32)
    lanes = keys.reshape(-1, 1)
    sel = distributed_dedup_select(key_mesh, lanes)
    oracle = {}
    for i, k in enumerate(keys.tolist()):
        oracle[k] = i  # stability: last occurrence wins
    assert sel.tolist() == [oracle[k] for k in sorted(oracle)]
    # with explicit seq lanes reversing arrival order
    seq = (n - 1 - np.arange(n)).astype(np.uint32).reshape(-1, 1)
    sel2 = distributed_dedup_select(key_mesh, lanes, seq)
    oracle2 = {}
    for i, k in enumerate(keys.tolist()):
        if k not in oracle2:
            oracle2[k] = i  # highest seq = first occurrence
    assert sel2.tolist() == [oracle2[k] for k in sorted(oracle2)]


def test_mesh_oversized_bucket_routes_to_key_axis(two_tables, rng):
    """Jobs above parallel.key-axis.rows range-partition over the key axis."""
    from paimon_tpu.parallel.executor import mesh_batch

    par, _ = two_tables
    from paimon_tpu.core.mergefn import MergeExecutor
    from paimon_tpu.core.kv import KVBatch
    from paimon_tpu.data.batch import ColumnBatch

    ex = par.store.merge_executor()
    n = 2048
    ids = rng.integers(0, 500, n)
    data = ColumnBatch.from_pydict(
        SCHEMA,
        {
            "pt": ["p0"] * n,
            "id": ids.tolist(),
            "v": [float(i) for i in range(n)],
            "name": ["x"] * n,
        },
    )
    kv = KVBatch.from_rows(data, 0)
    with mesh_batch(key_axis_rows=1024) as ctx:  # force the key-axis path
        h = ex.merge_async(kv, seq_ascending=True)
        merged = ex.merge_resolve(h)
    want = ex.merge(kv, seq_ascending=True)
    assert merged.data.to_pylist() == want.data.to_pylist()
    assert (merged.seq == want.seq).all()


def test_mesh_partial_update_sequence_groups(tmp_warehouse, rng):
    """Sequence groups under mesh execution (batched plan jobs + per-group
    device picks) must match the single-device result."""
    cat = FileSystemCatalog(tmp_warehouse, commit_user="meshsg")
    schema = RowType.of(("id", BIGINT()), ("g1_seq", BIGINT()), ("a", DOUBLE()), ("b", DOUBLE()))
    opts = {
        "bucket": "2",
        "merge-engine": "partial-update",
        "fields.g1_seq.sequence-group": "a,b",
    }
    par = cat.create_table("db.sg_par", schema, primary_keys=["id"],
                           options={**opts, "parallel.mesh.enabled": "true"})
    ser = cat.create_table("db.sg_ser", schema, primary_keys=["id"], options=opts)
    for r in range(3):
        ids = rng.integers(0, 40, 80)
        data = {
            "id": ids.tolist(),
            # group sequence occasionally goes BACKWARD: stale updates must lose
            "g1_seq": [int(v) for v in rng.integers(0, 100, 80)],
            "a": [None if i % 4 == 0 else float(r * 100 + i) for i in ids],
            "b": [float(r) if i % 3 else None for i in ids],
        }
        _write(par, data)
        _write(ser, data)
    assert _canon(_read(par)) == _canon(_read(ser))
