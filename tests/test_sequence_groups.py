"""Partial-update sequence groups (reference PartialUpdateMergeFunction
sequence-group behavior :185-230)."""

import numpy as np
import pytest

from paimon_tpu.catalog import FileSystemCatalog
from paimon_tpu.types import BIGINT, DOUBLE, INT, STRING, RowType

SCHEMA = RowType.of(
    ("k", BIGINT()),
    ("a", INT()), ("seq_a", BIGINT()),
    ("b", INT()), ("seq_b", BIGINT()),
)


@pytest.fixture
def table(tmp_warehouse):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="sg")
    return cat.create_table(
        "db.sg", SCHEMA, primary_keys=["k"],
        options={
            "bucket": "1",
            "merge-engine": "partial-update",
            "fields.seq_a.sequence-group": "a",
            "fields.seq_b.sequence-group": "b",
        },
    )


def write(t, data):
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write(data)
    wb.new_commit().commit(w.prepare_commit())


def read(t):
    rb = t.new_read_builder()
    return rb.new_read().read_all(rb.new_scan().plan())


def test_sequence_groups_independent_ordering(table):
    t = table
    # group a arrives out of order: seq_a=2 first, then a stale seq_a=1
    write(t, {"k": [1], "a": [20], "seq_a": [2], "b": [None], "seq_b": [None]})
    write(t, {"k": [1], "a": [10], "seq_a": [1], "b": [100], "seq_b": [5]})
    out = read(t)
    # a keeps the seq_a=2 value despite the later arrival of seq_a=1;
    # b takes its own group's latest (only) value
    assert out.to_pylist() == [(1, 20, 2, 100, 5)]


def test_sequence_groups_update_on_higher_seq(table):
    t = table
    write(t, {"k": [1], "a": [10], "seq_a": [1], "b": [100], "seq_b": [1]})
    write(t, {"k": [1], "a": [30], "seq_a": [3], "b": [None], "seq_b": [None]})
    out = read(t)
    assert out.to_pylist() == [(1, 30, 3, 100, 1)]  # b untouched by a's update


def test_sequence_group_ties_resolved_by_system_seq(table):
    t = table
    write(t, {"k": [1, 1], "a": [10, 11], "seq_a": [7, 7], "b": [None, None], "seq_b": [None, None]})
    out = read(t)
    assert out.to_pylist()[0][1] == 11  # same group seq: later arrival wins


def test_aggregation_within_sequence_group(tmp_warehouse):
    """fields.<f>.aggregate-function inside a sequence group aggregates over
    the group's rows instead of taking the winner's snapshot."""
    cat = FileSystemCatalog(tmp_warehouse, commit_user="sga")
    schema = RowType.of(("k", BIGINT()), ("total", INT()), ("g", BIGINT()))
    t = cat.create_table(
        "db.sga", schema, primary_keys=["k"],
        options={
            "bucket": "1",
            "merge-engine": "partial-update",
            "fields.g.sequence-group": "total",
            "fields.total.aggregate-function": "sum",
        },
    )
    write(t, {"k": [1, 1], "total": [10, 5], "g": [1, 2]})
    write(t, {"k": [1], "total": [7], "g": [3]})
    out = read(t)
    assert out.to_pylist() == [(1, 22, 3)]  # sum over the group, latest g


def test_group_aggregation_skips_null_group_rows(tmp_warehouse):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="sgn")
    schema = RowType.of(("k", BIGINT()), ("total", INT()), ("g", BIGINT()))
    t = cat.create_table(
        "db.sgn", schema, primary_keys=["k"],
        options={
            "bucket": "1",
            "merge-engine": "partial-update",
            "fields.g.sequence-group": "total",
            "fields.total.aggregate-function": "sum",
        },
    )
    write(t, {"k": [1, 1], "total": [10, 5], "g": [1, None]})
    out = read(t)
    assert out.to_pylist() == [(1, 10, 1)]  # null-g row excluded from the group


def test_group_aggregation_default_function(tmp_warehouse):
    cat = FileSystemCatalog(tmp_warehouse, commit_user="sgd")
    schema = RowType.of(("k", BIGINT()), ("total", INT()), ("g", BIGINT()))
    t = cat.create_table(
        "db.sgd", schema, primary_keys=["k"],
        options={
            "bucket": "1",
            "merge-engine": "partial-update",
            "fields.g.sequence-group": "total",
            "fields.default-aggregate-function": "sum",
        },
    )
    write(t, {"k": [1, 1], "total": [10, 5], "g": [1, 2]})
    out = read(t)
    assert out.to_pylist() == [(1, 15, 2)]  # default agg applies inside groups
